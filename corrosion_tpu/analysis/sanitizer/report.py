"""corrosan findings + the machine-readable report.

A sanitizer finding is deliberately NOT a corrolint
:class:`~corrosion_tpu.analysis.base.Finding`: corrolint findings are
(path, line) facts about source text; sanitizer findings are facts
about one *execution* (threads, witnessed orders, surviving handles)
and carry that context instead. The two meet in the report artifact
(``artifacts/san_r08.json``), written next to the lint artifact by
``scripts/check.sh``.

Report layout (one file, independently-written sections so the fixture
replay CLI and the sanitized pytest run can both land in it)::

    {
      "version": 1,
      "tool": "corrosan",
      "sections": {
        "fixtures": {...},   # corrosion-tpu san: per-fixture verdicts
        "pytest":   {...}    # sanitized run: edges, races, leaks
      }
    }
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional

#: finding kind -> one-line description — the catalog of record
#: (docs/corrosan.md must list every id; a tier-1 meta-test enforces it)
KINDS: Dict[str, str] = {
    "attr-race": (
        "two threads accessed the same shared attribute (>=1 write) "
        "with no happens-before ordering between them"
    ),
    "lock-edge-unknown": (
        "a witnessed lock-acquisition edge falls outside corrolint's "
        "static lock-order graph (and is not allow-listed)"
    ),
    "lock-cycle": (
        "witnessed acquisitions complete a cycle (alone or with the "
        "static edges) — a deadlock two threads can reach"
    ),
    "fs-resurrect": (
        "a watched file survives teardown via a write that another "
        "thread's delete should have killed (manifest-resurrection "
        "shape, the PR-5 pubsub race)"
    ),
    "thread-leak": (
        "a thread spawned during the sanitized window is still alive "
        "at the gate"
    ),
    "executor-leak": (
        "a ThreadPoolExecutor created during the window was never "
        "shut down"
    ),
    "fd-leak": (
        "a file opened under a watched root is still open at the gate"
    ),
}


@dataclasses.dataclass(frozen=True, order=True)
class SanFinding:
    kind: str
    subject: str  # "Class.attr", "nodeA -> nodeB", thread/file name
    message: str
    site: str = ""  # "path:line" of the flagged access, when known
    thread: str = ""

    def render(self) -> str:
        where = f" at {self.site}" if self.site else ""
        who = f" [{self.thread}]" if self.thread else ""
        return f"{self.kind}: {self.subject}: {self.message}{where}{who}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def write_section(path: str, section: str, payload: dict) -> None:
    """Read-modify-write one section of the report file (creating it
    and its directory on first write). Corrupt/legacy content is
    replaced rather than crashing the gate that is trying to report."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    doc: dict = {"version": 1, "tool": "corrosan", "sections": {}}
    if os.path.exists(path):
        try:
            with open(path, "r", encoding="utf-8") as f:
                loaded = json.load(f)
            if isinstance(loaded, dict) and isinstance(
                    loaded.get("sections"), dict):
                doc = loaded
        except (OSError, ValueError):
            pass
    doc["sections"][section] = payload
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


def load_section(path: str, section: str) -> Optional[dict]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)["sections"].get(section)
    except (OSError, ValueError, KeyError):
        return None


def findings_payload(findings: List[SanFinding]) -> dict:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.kind] = counts.get(f.kind, 0) + 1
    return {
        "findings": [f.to_json() for f in sorted(findings)],
        "kind_counts": counts,
        "clean": not findings,
    }
