"""Seeded concurrency fixtures: the sanitizer's true-positive guard.

A detector nobody has watched CATCH a bug is a no-op with overhead, so
every detector ships with fixtures that provoke its bug class under
barrier-forced interleavings and assert the finding appears — plus
clean twins asserting the FIXED shape passes (false-positive guard).
``corrosion-tpu san`` replays them all into the JSON report;
``tests/test_corrosan.py`` runs the same battery in tier-1.

The crown fixture pair re-provokes the PR-5 pubsub bug against the
REAL ``SubsManager``: ``pubsub-resurrect-reverted`` swaps in the
pre-fix ``_persist_worker`` (no post-write liveness re-check) and must
be flagged; ``pubsub-resurrect-fixed`` runs the shipped worker through
the same forced interleaving and must pass.
"""

from __future__ import annotations

import dataclasses
import os
import tempfile
import threading
from typing import Callable, Dict, List, Optional, Tuple

from corrosion_tpu.analysis.sanitizer.runtime import Sanitizer, sanitized


@dataclasses.dataclass
class FixtureResult:
    name: str
    expect: Tuple[str, ...]  # finding kinds that MUST appear (() = clean)
    found: Tuple[str, ...]
    ok: bool
    details: List[str]

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def _spawn(fn, name: str) -> threading.Thread:
    t = threading.Thread(target=fn, name=name, daemon=True)
    t.start()
    return t


# --- race detector ---------------------------------------------------------

def _fx_race_unlocked(san: Sanitizer, tmp: str) -> Optional[Callable]:
    """Two threads increment a shared counter with no lock: a textbook
    write/write + read/write race the happens-before detector must
    flag. The barrier orders both threads after setup but leaves the
    increments themselves concurrent."""

    class Shared:
        def __init__(self):
            self.val = 0

    san.track(Shared)
    obj = Shared()
    barrier = threading.Barrier(2)

    def worker():
        barrier.wait(timeout=10)
        for _ in range(50):
            obj.val += 1

    threads = [_spawn(worker, f"corrosan-racer-{i}") for i in range(2)]
    for t in threads:
        t.join(timeout=10)
    return None


def _fx_race_locked(san: Sanitizer, tmp: str) -> Optional[Callable]:
    """The fixed twin: same increments under one lock — every access
    pair is ordered through the lock's clock, so the detector must stay
    silent (false-positive guard)."""

    class Shared:
        def __init__(self):
            self.val = 0

    san.track(Shared)
    obj = Shared()
    mu = threading.Lock()
    barrier = threading.Barrier(2)

    def worker():
        barrier.wait(timeout=10)
        for _ in range(50):
            with mu:
                obj.val += 1

    threads = [_spawn(worker, f"corrosan-locked-{i}") for i in range(2)]
    for t in threads:
        t.join(timeout=10)
    return None


# --- lock-order witness ----------------------------------------------------

def _fx_lock_inversion(san: Sanitizer, tmp: str) -> Optional[Callable]:
    """ABBA without the deadlock: thread 1 nests a->b and FINISHES
    before thread 2 nests b->a, so the run completes — exactly the
    interleaving-dependent bug class only a witness catches. The gate
    must report the 2-cycle."""
    a = threading.Lock()
    b = threading.Lock()

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    first = _spawn(t1, "corrosan-ab")
    first.join(timeout=10)
    second = _spawn(t2, "corrosan-ba")
    second.join(timeout=10)
    return None


def _fx_lock_nested_clean(san: Sanitizer, tmp: str) -> Optional[Callable]:
    """Consistent a->b nesting from two threads: edges are witnessed
    but no cycle forms and no named pair leaves the static graph."""
    a = threading.Lock()
    b = threading.Lock()

    def worker():
        with a:
            with b:
                pass

    threads = [_spawn(worker, f"corrosan-nest-{i}") for i in range(2)]
    for t in threads:
        t.join(timeout=10)
    return None


# --- leak gate -------------------------------------------------------------

def _fx_thread_leak(san: Sanitizer, tmp: str) -> Optional[Callable]:
    stop = threading.Event()
    t = _spawn(lambda: stop.wait(timeout=60), "corrosan-leaky")

    def cleanup():
        stop.set()
        t.join(timeout=10)

    return cleanup


def _fx_fd_leak(san: Sanitizer, tmp: str) -> Optional[Callable]:
    root = os.path.join(tmp, "files")
    os.makedirs(root, exist_ok=True)
    san.watch_dir(root)
    leaked = open(os.path.join(root, "leak.txt"), "w")
    leaked.write("never closed\n")
    return leaked.close


def _fx_executor_leak(san: Sanitizer, tmp: str) -> Optional[Callable]:
    import concurrent.futures

    ex = concurrent.futures.ThreadPoolExecutor(max_workers=1)
    ex.submit(lambda: None).result(timeout=10)
    return lambda: ex.shutdown(wait=True)


# --- the PR-5 pubsub regression pair ---------------------------------------

def _small_config():
    from corrosion_tpu.config import Config

    cfg = Config()
    cfg.sim.n_nodes = 8
    cfg.sim.m_slots = 8
    cfg.sim.n_origins = 2
    cfg.sim.n_rows = 4
    cfg.sim.n_cols = 2
    cfg.gossip.drop_prob = 0.0
    return cfg


def _pubsub_resurrect(san: Sanitizer, tmp: str, fixed: bool
                      ) -> Optional[Callable]:
    """Re-provoke the PR-5 unsubscribe-vs-persist race with a forced
    interleaving: the persist worker is gated so its manifest write
    lands strictly after unsubscribe's unlink. The pre-fix worker
    (``fixed=False``) resurrects the manifest of a dead subscription —
    the fs witness must flag it; the shipped worker re-checks liveness
    after the write and unlinks, and must pass."""
    from corrosion_tpu.agent import Agent
    from corrosion_tpu.db import Database
    from corrosion_tpu.pubsub import SubsManager

    if fixed:
        mgr_cls = SubsManager
    else:
        class RevertedSubsManager(SubsManager):
            """PR-5-era worker: persists with no post-write liveness
            re-check (the exact code the review hardening replaced)."""

            def _persist_worker(self):
                while True:
                    mid = self._persist_q.get()
                    if mid is None:
                        return
                    m = self._matchers.get(mid)
                    if m is not None:
                        self._persist(m)

        mgr_cls = RevertedSubsManager

    # an un-started Agent: the fixture drives _on_round by hand, so no
    # round loop (and no jax dispatch beyond state creation) is needed
    agent = Agent(_small_config())
    db = Database(agent)
    db.apply_schema_sql(
        "CREATE TABLE items (pk INTEGER PRIMARY KEY, v INTEGER);"
    )
    persist_dir = os.path.join(tmp, "subs")
    san.watch_dir(persist_dir)
    mgr = mgr_cls(db, persist_dir=persist_dir)
    matcher, _ = mgr.subscribe(0, "SELECT pk, v FROM items")

    persist_started = threading.Event()
    persist_gate = threading.Event()
    real_persist = mgr._persist

    def gated_persist(m):
        persist_started.set()
        persist_gate.wait(timeout=10)
        real_persist(m)

    mgr._persist = gated_persist
    with mgr._mu:
        mgr._dirty.add(matcher.id)
    # a persist-cadence round hands the dirty matcher to the worker
    mgr._on_round(mgr.PERSIST_EVERY)
    if not persist_started.wait(timeout=10):
        raise RuntimeError("persist worker never picked up the manifest")
    # worker is parked pre-write; unsubscribe unlinks the manifest...
    mgr.unsubscribe(matcher.id)
    # ...and only now may the worker's write land
    persist_gate.set()
    mgr._persist = real_persist
    # close() drains the queue and joins the worker BEFORE the gate
    # runs, so the resurrecting write (or the fixed worker's re-check
    # unlink) is on disk when the fs witness looks
    mgr.close()
    return None


#: name -> (callable(san, tmpdir) -> cleanup|None, expected kinds, doc)
FIXTURES: Dict[str, Tuple[Callable, Tuple[str, ...], str]] = {
    "race-unlocked": (
        _fx_race_unlocked, ("attr-race",),
        "two unlocked incrementing threads -> attr-race",
    ),
    "race-locked": (
        _fx_race_locked, (),
        "same increments under a lock -> clean",
    ),
    "lock-inversion": (
        _fx_lock_inversion, ("lock-cycle",),
        "sequential ABBA nesting -> witnessed 2-cycle",
    ),
    "lock-nested-clean": (
        _fx_lock_nested_clean, (),
        "consistent a->b nesting -> clean",
    ),
    "thread-leak": (
        _fx_thread_leak, ("thread-leak",),
        "spawned thread outlives the window -> thread-leak",
    ),
    "fd-leak": (
        _fx_fd_leak, ("fd-leak",),
        "unclosed file under a watch root -> fd-leak",
    ),
    "executor-leak": (
        _fx_executor_leak, ("executor-leak",),
        "ThreadPoolExecutor never shut down -> executor-leak",
    ),
    "pubsub-resurrect-reverted": (
        lambda san, tmp: _pubsub_resurrect(san, tmp, fixed=False),
        ("fs-resurrect",),
        "PR-5-era persist worker resurrects a dead manifest -> flagged",
    ),
    "pubsub-resurrect-fixed": (
        lambda san, tmp: _pubsub_resurrect(san, tmp, fixed=True),
        (),
        "shipped persist worker under the same interleaving -> clean",
    ),
}


def run_fixture(name: str) -> FixtureResult:
    fn, expect, _doc = FIXTURES[name]
    cleanup = None
    with tempfile.TemporaryDirectory(prefix="corrosan-") as tmp:
        with sanitized() as san:
            cleanup = fn(san, tmp)
        try:
            findings = san.gate()
        finally:
            if cleanup is not None:
                cleanup()
    found = tuple(sorted({f.kind for f in findings}))
    if expect:
        ok = set(expect).issubset(found)
    else:
        ok = not findings
    return FixtureResult(
        name=name, expect=tuple(expect), found=found, ok=ok,
        details=[f.render() for f in findings],
    )


def run_all_fixtures(names=None) -> List[FixtureResult]:
    picked = list(names) if names else list(FIXTURES)
    unknown = set(picked) - set(FIXTURES)
    if unknown:
        raise ValueError(
            f"unknown fixtures: {sorted(unknown)} "
            f"(available: {sorted(FIXTURES)})"
        )
    return [run_fixture(name) for name in picked]
