"""Sanctioned runtime findings, each with a reason.

The corrolint contract, carried to runtime: a suppression that does not
say WHY is itself a bug. Every entry here is a deliberate design
decision the sanitizer would otherwise flag — the dynamic analog of the
``# corrolint: disable=... -- reason`` sites in the tree. An entry with
an empty reason raises at import (meta-tested), so the list can never
silently grow unexplained holes.

Keep entries MINIMAL and specific: the detector's value is exactly the
set of accesses NOT listed here.
"""

from __future__ import annotations

from typing import Dict, Tuple

#: (class name, attribute) -> why the unsynchronized access is safe.
#: These mirror the lock-discipline suppressions corrolint already
#: carries for single-writer / GIL-atomic sites.
ALLOWED_ATTR_RACES: Dict[Tuple[str, str], str] = {
    ("Agent", "round_no"): (
        "GIL-atomic monotonic int; API readers tolerate a one-round "
        "stale value by design (the serving contract is eventual)"
    ),
    ("Agent", "_state"): (
        "single-reference pytree swap by the round thread; snapshot() "
        "re-checks round_no under _snap_lock and tolerates staleness"
    ),
    ("Agent", "_net"): (
        "single-reference NetModel swap between rounds; admin readers "
        "(members) only render it"
    ),
    ("Agent", "_key"): (
        "round-thread-owned PRNG key; soak() swaps it only with the "
        "loop stopped (guarded by a RuntimeError)"
    ),
    ("Agent", "_supervisor"): (
        "start() binds it before the loop spawns (ordered by the spawn "
        "edge); tests that inject a supervisor into a LIVE agent "
        "tolerate one stale round of the single-reference swap"
    ),
    ("Agent", "generation"): (
        "GIL-atomic int fence; the commit-side compare runs under "
        "_input_lock, observers only render it"
    ),
    ("Agent", "_recovering"): (
        "GIL-atomic bool flag set around a restore; health() reads it "
        "under _input_lock and a stale False only delays the 503"
    ),
    ("Agent", "_thread"): (
        "written before the loop exists or with it provably stopped; "
        "liveness checks tolerate staleness"
    ),
    ("Supervisor", "retries"): (
        "GIL-atomic telemetry counter; /v1/health renders it, nothing "
        "branches on it"
    ),
    ("Supervisor", "aborts"): (
        "GIL-atomic telemetry counter; /v1/health renders it, nothing "
        "branches on it"
    ),
    ("Matcher", "_subs"): (
        "mutation is under _mu; the n_subscribers property does a "
        "GIL-atomic len() on the list reference"
    ),
    ("Matcher", "n_queries"): (
        "GIL-atomic test/metrics counter incremented by the round "
        "thread; test readers assert on quiesced values"
    ),
    ("Matcher", "last_change_id"): (
        "mutated only under _mu; unlocked reads (manifest fast path, "
        "tests) render a monotonic int and tolerate staleness"
    ),
    ("Database", "schema"): (
        "immutable Schema object swapped under _mu; readers hold a "
        "consistent snapshot via one attribute read"
    ),
    ("Database", "heap"): (
        "immutable-identity swap on restore only (load_state_dict); "
        "concurrent readers during a restore are fenced by the agent "
        "generation bump"
    ),
    ("Database", "rows"): (
        "same restore-only swap contract as Database.heap"
    ),
    ("AsyncCheckpointWriter", "last_path"): (
        "worker-thread-owned; submitters read it only after close() "
        "joins the worker (join edge orders it)"
    ),
    ("AsyncCheckpointWriter", "io_seconds"): (
        "worker-thread-owned stat, read after close() join"
    ),
    ("AsyncCheckpointWriter", "written"): (
        "worker-thread-owned stat, read after close() join"
    ),
    ("AsyncCheckpointWriter", "overlapped"): (
        "worker-thread-owned stat, read after close() join"
    ),
}

#: (lock node, lock node) witnessed-edge pairs sanctioned BEYOND the
#: static graph. The meta-test asserts witnessed ⊆ static ∪ this dict:
#: a dynamically-created edge static call resolution provably cannot
#: see (these all flow through the ``Matcher(...)`` constructor, which
#: ``callgraph.resolve_call`` deliberately abstains on) must be argued
#: in with the argument, never silently absorbed. Deadlock-safety
#: argument shared by all three: the right-hand locks are LEAF locks —
#: they protect pure data, never call out, so no path can ever acquire
#: a pubsub lock under them and close a cycle.
ALLOWED_LOCK_EDGES: Dict[Tuple[str, str], str] = {
    ("corrosion_tpu.pubsub.SubsManager._mu",
     "corrosion_tpu.db.schema.RowMap._mu"): (
        "subscribe() validates the query under its lock; the row-map "
        "lookup lock is a leaf (guards dict reads, no outcalls)"
    ),
    ("corrosion_tpu.pubsub.SubsManager._mu",
     "corrosion_tpu.utils.locks.TrackedLock._lock"): (
        "query validation reads the agent snapshot under subscribe()'s "
        "lock; agent-plane locks never acquire host-plane pubsub locks "
        "(one-way layering)"
    ),
    ("corrosion_tpu.pubsub.SubsManager._mu",
     "corrosion_tpu.utils.locks.LockRegistry._mu"): (
        "every TrackedLock acquisition notes itself in the registry; "
        "the registry lock is a leaf (event-dict updates only)"
    ),
    ("corrosion_tpu.db.database.Database._mu",
     "corrosion_tpu.db.schema.RowMap._mu"): (
        "schema/restore surgery touches row-map lookups under the db "
        "lock; RowMap._mu is a leaf (guards dict reads, no outcalls)"
    ),
    ("corrosion_tpu.pubsub.DeltaTracker._mu",
     "corrosion_tpu.db.schema.RowMap._mu"): (
        "changed() maps delta cells to (table, pk) through the row-map "
        "reverse lookup while holding its baseline lock; RowMap._mu is "
        "a leaf (guards dict reads, no outcalls)"
    ),
    ("corrosion_tpu.pubsub.UpdatesManager._mu",
     "corrosion_tpu.db.schema.RowMap._mu"): (
        "attach()'s first-feed snapshot queries under the feeds lock; "
        "RowMap._mu is a leaf"
    ),
    ("corrosion_tpu.pubsub.UpdatesManager._mu",
     "corrosion_tpu.utils.locks.TrackedLock._lock"): (
        "attach()'s first-feed snapshot reads the agent snapshot under "
        "the feeds lock; agent-plane locks never acquire host-plane "
        "pubsub locks (one-way layering)"
    ),
    ("corrosion_tpu.pubsub.UpdatesManager._mu",
     "corrosion_tpu.utils.locks.LockRegistry._mu"): (
        "same snapshot path as TrackedLock._lock above; the registry "
        "lock is a leaf"
    ),
    ("corrosion_tpu.api.admission.AdmissionController._mu",
     "corrosion_tpu.utils.metrics.Registry._lock"): (
        "admit()/release() publish the corro.admission.* counters and "
        "level gauges while the admission mutex is held so the levels "
        "are snapshot-consistent with the decision; Registry._lock is "
        "a leaf (pure dict updates, no outcalls), so no path can "
        "acquire an admission lock under it and close a cycle"
    ),
}

#: thread-name prefixes the leak gate exempts, with reasons.
ALLOWED_LEAK_PREFIXES: Dict[str, str] = {
    "corro-supervised-": (
        "a dispatch that missed its deadline cannot be cancelled, only "
        "orphaned (Supervisor._with_deadline) — daemonic by design"
    ),
}


def _validate() -> None:
    for table in (ALLOWED_ATTR_RACES, ALLOWED_LOCK_EDGES,
                  ALLOWED_LEAK_PREFIXES):
        for key, reason in table.items():
            if not str(reason).strip():
                raise ValueError(
                    f"corrosan allowlist entry {key!r} has no reason — "
                    "a suppression that does not say why is a bug"
                )


_validate()
