"""Filesystem witness: happens-before over watched paths.

The PR-5 pubsub bug class: a background writer's ``open(path, "w")``
racing another thread's ``os.unlink(path)`` resurrects a manifest the
unsubscribe just killed. Attribute-level shadow state cannot see it (the
shared resource is a *path*, not an attribute), so corrosan records
write/delete operations on paths under registered watch roots, each
stamped with the acting thread's full vector clock.

Gate rule (``fs-resurrect``): a path that still EXISTS at the gate,
whose final recorded operation is a write, where some delete by a
*different* thread is ordered before or concurrent with that write. The
fixed persist worker ends every such interleaving with its own
re-check-and-unlink — final op a delete, path gone, clean — while the
pre-fix worker ends on the resurrecting write and is flagged. Same-path
delete-then-rewrite by ONE thread (checkpoint side rotation) is the
normal case and never flags.

File handles opened under a watch root are also tracked (weakly) for
the ``fd-leak`` gate.
"""

from __future__ import annotations

import _thread
import dataclasses
import os
import weakref
from typing import Dict, List, Optional, Tuple

from corrosion_tpu.analysis.sanitizer import vc as _vc
from corrosion_tpu.analysis.sanitizer.frames import call_site, realpath_cached
from corrosion_tpu.analysis.sanitizer.report import SanFinding


@dataclasses.dataclass
class _Op:
    kind: str  # "write" | "delete"
    tid: int
    clock: Dict[int, int]
    thread: str
    site: str


class FsWitness:
    def __init__(self, san):
        self._san = san
        self._ilock = _thread.allocate_lock()
        self._roots: List[str] = []
        self._log: Dict[str, List[_Op]] = {}
        self._files: List[Tuple[weakref.ref, str]] = []

    def watch(self, root: str) -> None:
        """Track write/delete/open ops on every path under ``root``."""
        real = realpath_cached(str(root))
        with self._ilock:
            if real not in self._roots:
                self._roots.append(real)

    def _watched(self, path) -> Optional[str]:
        if not self._roots or not isinstance(path, (str, bytes, os.PathLike)):
            return None
        real = realpath_cached(os.fspath(path))
        if not isinstance(real, str):
            return None
        for root in self._roots:
            if real == root or real.startswith(root + os.sep):
                return real
        return None

    def _record(self, path, kind: str) -> None:
        real = self._watched(path)
        if real is None:
            return
        st = self._san.thread_state()
        if st.busy:
            return
        name = self._san.thread_display_name(st)
        st.busy = True
        try:
            op = _Op(kind=kind, tid=st.tid, clock=dict(st.vc),
                     thread=name, site=call_site())
            with self._ilock:
                self._log.setdefault(real, []).append(op)
        finally:
            st.busy = False

    # --- hook surface (runtime.py patches route here) --------------------
    def on_open(self, path, mode: str, fobj) -> None:
        if self._watched(path) is None:
            return
        if any(c in mode for c in "wax+"):
            self._record(path, "write")
        try:
            ref = weakref.ref(fobj)
        except TypeError:
            return
        with self._ilock:
            self._files.append((ref, os.fspath(path)))

    def on_delete(self, path) -> None:
        self._record(path, "delete")

    def on_replace(self, src, dst) -> None:
        self.on_delete(src)
        self._record(dst, "write")

    # --- gate -------------------------------------------------------------
    def ops_payload(self) -> dict:
        with self._ilock:
            return {
                path: [(o.kind, o.thread, o.site) for o in ops]
                for path, ops in sorted(self._log.items())
            }

    def check(self) -> List[SanFinding]:
        findings: List[SanFinding] = []
        with self._ilock:
            log = {p: list(ops) for p, ops in self._log.items()}
            files = list(self._files)
        for path, ops in sorted(log.items()):
            last = ops[-1]
            if last.kind != "write" or not os.path.exists(path):
                continue
            for op in ops[:-1]:
                if op.kind != "delete" or op.tid == last.tid:
                    continue
                if _vc.clock_before(last.clock, op.clock):
                    continue  # the delete is strictly after this write
                findings.append(SanFinding(
                    kind="fs-resurrect", subject=path,
                    message=(
                        f"file survives the gate through a write by "
                        f"{last.thread} that {op.thread}'s delete "
                        "(ordered before or concurrent) should have "
                        "killed — unsubscribe-vs-persist resurrection "
                        "shape"
                    ),
                    site=last.site, thread=last.thread,
                ))
                break
        for ref, path in files:
            f = ref()
            if f is not None and not f.closed:
                findings.append(SanFinding(
                    kind="fd-leak", subject=path,
                    message="file opened under a watch root is still "
                            "open at the gate",
                ))
        return findings
