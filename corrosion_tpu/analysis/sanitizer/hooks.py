"""Tiny runtime seams production code may call unconditionally.

Production call sites must NOT import this module directly — importing
any sanitizer submodule executes the package ``__init__`` and drags in
the whole instrumentation stack. The contract instead (see
``pubsub.SubsManager``): resolve via
``sys.modules.get("corrosion_tpu.analysis.sanitizer.hooks")`` and call
only when present — a live sanitizer session has necessarily imported
this module already, and a production process without one pays zero
import cost.
"""

from __future__ import annotations


def watch_dir(path) -> None:
    """Register ``path`` with the active corrosan session's filesystem
    witness; no-op when no session is active."""
    from corrosion_tpu.analysis.sanitizer import runtime

    san = runtime._ACTIVE
    if san is not None and san.active:
        san.fs.watch(path)
