"""Cheap call-stack introspection shared by the corrosan components.

``sys._getframe`` walking instead of ``traceback``/``inspect``: the
race detector runs on hot attribute paths and must not allocate a
traceback per access. Frames inside the sanitizer itself, threading,
and queue are "plumbing" — user-facing sites skip them.
"""

from __future__ import annotations

import os
import queue
import sys
import threading
from typing import Iterator, Tuple

_SELF_DIR = os.path.dirname(os.path.abspath(__file__))
_PLUMBING_FILES = {
    os.path.abspath(getattr(threading, "__file__", "") or ""),
    os.path.abspath(getattr(queue, "__file__", "") or ""),
}

_REALPATHS: dict = {}


def realpath_cached(path: str) -> str:
    got = _REALPATHS.get(path)
    if got is None:
        got = os.path.realpath(path)
        _REALPATHS[path] = got
    return got


def _is_plumbing(filename: str) -> bool:
    ab = os.path.abspath(filename)
    return ab.startswith(_SELF_DIR) or ab in _PLUMBING_FILES


def iter_call_frames(skip: int = 2, limit: int = 20
                     ) -> Iterator[Tuple[str, int]]:
    """(filename, lineno) pairs walking outward from the caller's
    caller, plumbing frames included (the lock-naming walk matches them
    against the static creation-site map, which simply never contains
    stdlib paths)."""
    try:
        f = sys._getframe(skip)
    except ValueError:  # shallower stack than skip
        return
    n = 0
    while f is not None and n < limit:
        yield f.f_code.co_filename, f.f_lineno
        f = f.f_back
        n += 1


def call_site(skip: int = 2) -> str:
    """``path:line`` of the nearest non-plumbing frame ('' when the
    whole visible stack is plumbing)."""
    for filename, lineno in iter_call_frames(skip=skip):
        if not _is_plumbing(filename):
            return f"{filename}:{lineno}"
    return ""
