"""Trace-stability harness: "we never retrace" as an enforced contract.

AST analysis (``trace.py``) catches the lexical retrace hazards; this
harness catches the semantic ones — weak-type drift, a config object
that stopped being hashable, an input builder that changed a dtype, a
refactor that threads a Python scalar where an array used to flow.
Each registered hot entry point is jit-wrapped with a **compile
counter** (the wrapped Python body runs once per trace, so the counter
IS the trace count) and invoked several representative ways:

- fresh PRNG keys (same aval, different value);
- inputs rebuilt from scratch (same shapes/dtypes);
- the carry round-tripped through host numpy and re-uploaded — the
  exact shape of a checkpoint resume, where weak-type or dtype drift
  would silently retrace;
- for the donated probes, the returned carry chained back in (the soak
  segment pattern).

``assert_trace_stable`` raises if any entry point compiled more than
once — turning the PERF.md claim into a tier-1 test
(``tests/test_analysis.py``). Entry points registered here are the ones
whose throughput the bench records: the full-sim round step, the scale
round step, the segment dispatch (``scale_run_rounds_carry``), and the
node-sharded flagship run.
"""

from __future__ import annotations

from typing import Callable, Dict, Iterable, Optional

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np


def counting_jit(fn: Callable, **jit_kwargs):
    """``jax.jit(fn)`` plus a trace counter: the wrapped Python body
    executes exactly once per trace, never on cache hits."""
    counter = {"traces": 0}

    def traced(*args, **kwargs):
        counter["traces"] += 1
        return fn(*args, **kwargs)

    return jax.jit(traced, **jit_kwargs), (lambda: counter["traces"])


def _host_roundtrip(tree):
    """Checkpoint-resume shape: drain to owned numpy, re-upload."""
    host = jax.tree.map(lambda a: np.array(a), tree)
    return jax.tree.map(jnp.asarray, host)


def _host_roundtrip_owned(tree):
    """Resume shape for DONATION-bound probes: the re-upload must be an
    OWNED device copy (``jnp.array``, never ``asarray``) — the next
    dispatch donates these buffers, and donating a zero-copy-adopted
    numpy buffer corrupts the heap on CPU (PR 8)."""
    host = jax.tree.map(lambda a: np.array(a), tree)
    return jax.tree.map(lambda a: jnp.array(a), host)


# tiny CPU-sized configs, matching shapes tier-1 already compiles
# (tests/test_resilience.py) so the persistent cache is shared
def _full_cfg():
    from corrosion_tpu.sim.config import SimConfig

    return SimConfig(n_nodes=12, n_origins=4, n_rows=4, n_cols=2,
                     tx_max_cells=2)


def _scale_cfg():
    from corrosion_tpu.sim.scale_step import scale_sim_config

    return scale_sim_config(
        24, m_slots=8, n_origins=4, n_rows=4, n_cols=2, sync_interval=4
    )


def _probe_full_step(repeats: int) -> int:
    from corrosion_tpu.sim.step import RoundInput, SimState, sim_step
    from corrosion_tpu.sim.transport import NetModel

    cfg = _full_cfg()
    net = NetModel.create(cfg.n_nodes)
    fn, traces = counting_jit(sim_step, static_argnums=(0,))
    st = SimState.create(cfg)
    for i in range(repeats):
        inp = RoundInput.quiet(cfg)  # rebuilt fresh: same avals
        st, _info = fn(cfg, st, net, jr.key(i), inp)
        if i == 0:
            st = _host_roundtrip(st)  # the resume path must not retrace
    jax.block_until_ready(st)
    return traces()


def _probe_scale_step(repeats: int) -> int:
    from corrosion_tpu.sim.scale_step import (
        ScaleRoundInput,
        ScaleSimState,
        scale_sim_step,
    )
    from corrosion_tpu.sim.transport import NetModel

    cfg = _scale_cfg()
    net = NetModel.create(cfg.n_nodes)
    fn, traces = counting_jit(scale_sim_step, static_argnums=(0,))
    st = ScaleSimState.create(cfg)
    for i in range(repeats):
        inp = ScaleRoundInput.quiet(cfg)
        st, _info = fn(cfg, st, net, jr.key(i), inp)
        if i == 0:
            st = _host_roundtrip(st)
    jax.block_until_ready(st)
    return traces()


def _probe_segment_dispatch(repeats: int, rounds_per_segment: int = 2) -> int:
    """The soak runner's dispatch: ``scale_run_rounds_carry`` with the
    FULL carry chained across segments (one jitted program per segment
    length — re-dispatching the same length must not recompile)."""
    from corrosion_tpu.resilience.segments import make_soak_inputs
    from corrosion_tpu.sim.scale_step import (
        ScaleSimState,
        scale_run_rounds_carry,
    )
    from corrosion_tpu.sim.transport import NetModel

    cfg = _scale_cfg()
    net = NetModel.create(cfg.n_nodes)
    fn, traces = counting_jit(
        lambda s, k, i: scale_run_rounds_carry(cfg, s, net, k, i)
    )
    st, key = ScaleSimState.create(cfg), jr.key(0)
    for i in range(repeats):
        seg = make_soak_inputs(cfg, jr.key(i), rounds_per_segment,
                               write_frac=0.25)
        (st, key), _infos = fn(st, key, seg)
        if i == 0:
            st = _host_roundtrip(st)  # supervised-retry re-upload shape
    jax.block_until_ready(st)
    return traces()


def _probe_sharded_scale_run(repeats: int, rounds: int = 2) -> int:
    """The flagship path: the REAL ``parallel/mesh.sharded_scale_run``
    (module-level donated jit) with node-sharded state and the carry
    chained back in — exactly how ``bench.py`` steps it.

    The entry point's jit already exists, so a fresh compile counter
    cannot wrap it; instead the probe reads the jit's own cache size.
    Warmup is TWO calls — the first on freshly-placed state, the second
    chaining the jit's own output (on current jax the output arrays key
    one extra cache entry the first time they re-enter, with identical
    avals/shardings/weak types; bench.py's warmup absorbs the same
    entry). The enforced contract is the steady state the bench's timed
    loop runs in: every chained re-invocation after that adds ZERO
    compilations. Reported as ``1 + extra`` so stable == 1."""
    from corrosion_tpu.parallel import mesh as pmesh
    from corrosion_tpu.resilience.segments import make_soak_inputs
    from corrosion_tpu.sim.scale_step import ScaleSimState
    from corrosion_tpu.sim.transport import NetModel

    cfg = _scale_cfg()
    mesh = pmesh.make_mesh()
    net = pmesh.shard_state(mesh, cfg.n_nodes, NetModel.create(cfg.n_nodes))
    st = pmesh.shard_state(mesh, cfg.n_nodes, ScaleSimState.create(cfg))
    for i in range(2):  # fresh-placed, then first output-chained call
        inputs = pmesh.shard_state(mesh, cfg.n_nodes, make_soak_inputs(
            cfg, jr.key(i), rounds, write_frac=0.25))
        st, _ = pmesh.sharded_scale_run(cfg, mesh, st, net,
                                        jr.key(i), inputs)
    jax.block_until_ready(st)
    base = pmesh._scale_run._cache_size()
    for i in range(2, 2 + repeats):
        inputs = pmesh.shard_state(mesh, cfg.n_nodes, make_soak_inputs(
            cfg, jr.key(i), rounds, write_frac=0.25))
        st, _infos = pmesh.sharded_scale_run(cfg, mesh, st, net,
                                             jr.key(i), inputs)
    jax.block_until_ready(st)
    return 1 + (pmesh._scale_run._cache_size() - base)


def _probe_segmented_soak(repeats: int, rounds_per_segment: int = 8) -> int:
    """The REAL segmented soak (``run_segmented``) with the async
    checkpoint writer active: dispatches must compile exactly TWO
    programs — the un-donated first segment and the donated steady
    state — and every later segment boundary (carry chained through a
    donated dispatch while the writer drains host copies in the
    background) must add ZERO compilations.

    Shapes match ``tests/test_resilience.py``'s ``scale16`` fixture
    (``_scale_cfg`` config, 8-round segments, ``write_frac=0.25``) so
    the persistent compile cache is shared — keep them in sync.
    Reported as ``observed - 1`` so the expected two programs read as
    the stable ``1``: a per-segment retrace (or donation silently
    disabled, which would collapse the two programs into one) fails
    the gate either way."""
    import tempfile

    from corrosion_tpu.resilience import segments
    from corrosion_tpu.sim.scale_step import ScaleSimState
    from corrosion_tpu.sim.transport import NetModel

    cfg = _scale_cfg()
    net = NetModel.create(cfg.n_nodes)
    st = ScaleSimState.create(cfg)
    # un-donated, donated, then steady state — capped at ONE steady
    # segment: it re-runs the donated program with the chained carry
    # while the writer drains, which is the whole claim; more segments
    # only re-prove it at ~3 s of tier-1 budget each
    n_segments = 2 + min(repeats, 1)
    inputs = segments.make_soak_inputs(
        cfg, jr.key(5), rounds_per_segment * n_segments, write_frac=0.25
    )
    counter = {"traces": 0}
    real_jit = segments._jit

    def counting(fn, **kwargs):
        def wrapped(*a, **k):
            counter["traces"] += 1
            return fn(*a, **k)

        return real_jit(wrapped, **kwargs)

    segments._jit = counting
    try:
        with tempfile.TemporaryDirectory() as root:
            res = segments.run_segmented(
                cfg, st, net, jr.key(0), inputs, rounds_per_segment,
                checkpoint_root=root, donate=True, async_checkpoint=True,
            )
    finally:
        segments._jit = real_jit
    if res.aborted or res.stats["ckpt_written"] != n_segments:
        raise RuntimeError(
            f"segmented-soak probe did not run as configured: "
            f"aborted={res.aborted} "
            f"ckpt_written={res.stats['ckpt_written']}/{n_segments} "
            "(the writer must be active for the probed steady state)"
        )
    if res.stats["donated_segments"] != n_segments - 1:
        raise RuntimeError(
            f"segmented-soak probe expected {n_segments - 1} donated "
            f"segments, got {res.stats['donated_segments']} — the "
            "steady state being enforced is the donated one"
        )
    return counter["traces"] - 1


def _probe_fused_scale_run(repeats: int, rounds_per_segment: int = 2) -> int:
    """The fused megakernel path (ISSUE 10): ``scale_run_rounds_carry``
    under ``fused="interpret"`` with the FULL carry DONATED and chained
    back in — the exact shape of a fused segmented-soak dispatch. The
    eager probes are hoisted (``prime_fused``) before the first trace,
    so a retrace here means the fused gates or the pallas lowering
    destabilized the steady state, with donation active."""
    import dataclasses

    from corrosion_tpu.ops import megakernel
    from corrosion_tpu.resilience.segments import make_soak_inputs
    from corrosion_tpu.sim.scale_step import (
        ScaleSimState,
        scale_run_rounds_carry,
    )
    from corrosion_tpu.sim.transport import NetModel

    cfg = dataclasses.replace(_scale_cfg(), fused="interpret").validate()
    megakernel.prime_fused(cfg)  # probes run HERE, never inside a trace
    net = NetModel.create(cfg.n_nodes)
    fn, traces = counting_jit(
        lambda s, k, i: scale_run_rounds_carry(cfg, s, net, k, i),
        donate_argnums=(0, 1),
    )
    st, key = ScaleSimState.create(cfg), jr.key(0)
    for i in range(repeats):
        seg = make_soak_inputs(cfg, jr.key(i), rounds_per_segment,
                               write_frac=0.25)
        (st, key), _infos = fn(st, key, seg)
        if i == 0:
            st = _host_roundtrip_owned(st)  # resume shape, donate-safe
    jax.block_until_ready(st)
    return traces()


def _probe_quiet_scale_run(repeats: int, rounds_per_segment: int = 2) -> int:
    """The quiescence-gated path (ISSUE 19): ``scale_run_rounds_carry``
    under ``quiet="on"`` with the carry donated and chained back in —
    the shape a quiet-auto segment dispatch takes. The quiet step body
    carries an extra ``lax.cond`` over the whole active round; a
    retrace here means the quiet predicate or the fixpoint branch
    destabilized the steady state."""
    import dataclasses

    from corrosion_tpu.resilience.segments import make_soak_inputs
    from corrosion_tpu.sim.scale_step import (
        ScaleSimState,
        scale_run_rounds_carry,
    )
    from corrosion_tpu.sim.transport import NetModel

    cfg = dataclasses.replace(_scale_cfg(), quiet="on").validate()
    net = NetModel.create(cfg.n_nodes)
    fn, traces = counting_jit(
        lambda s, k, i: scale_run_rounds_carry(cfg, s, net, k, i),
        donate_argnums=(0, 1),
    )
    st, key = ScaleSimState.create(cfg), jr.key(0)
    for i in range(repeats):
        seg = make_soak_inputs(cfg, jr.key(i), rounds_per_segment,
                               write_frac=0.25)
        (st, key), _infos = fn(st, key, seg)
        if i == 0:
            st = _host_roundtrip_owned(st)  # resume shape, donate-safe
    jax.block_until_ready(st)
    return traces()


#: name -> probe(repeats) -> observed trace count
#: every name here must ALSO be priced by corrocost
#: (cost.PRICED_ENTRY_POINTS — the tests/test_cost.py coverage gate):
#: trace-stable AND costed, or not a hot entry point
HOT_ENTRY_POINTS: Dict[str, Callable[[int], int]] = {
    "full_sim_step": _probe_full_step,
    "scale_sim_step": _probe_scale_step,
    "segment_dispatch": _probe_segment_dispatch,
    "sharded_scale_run": _probe_sharded_scale_run,
    "segmented_soak": _probe_segmented_soak,
    "fused_scale_run": _probe_fused_scale_run,
    "quiet_scale_run": _probe_quiet_scale_run,
}


def trace_counts(names: Optional[Iterable[str]] = None,
                 repeats: int = 3) -> Dict[str, int]:
    """Observed compile counts per entry point over ``repeats``
    representative invocations each."""
    selected = list(names) if names is not None else list(HOT_ENTRY_POINTS)
    unknown = [n for n in selected if n not in HOT_ENTRY_POINTS]
    if unknown:
        raise ValueError(
            f"unknown entry points {unknown} "
            f"(registered: {sorted(HOT_ENTRY_POINTS)})"
        )
    return {name: HOT_ENTRY_POINTS[name](repeats) for name in selected}


def assert_trace_stable(names: Optional[Iterable[str]] = None,
                        repeats: int = 3) -> Dict[str, int]:
    """Raise unless every entry point compiled exactly once."""
    counts = trace_counts(names, repeats)
    unstable = {n: c for n, c in counts.items() if c != 1}
    if unstable:
        raise RuntimeError(
            f"hot entry points retraced: {unstable} (expected exactly "
            f"one compilation over {repeats} representative invocations "
            f"each — a refactor introduced a per-call retrace)"
        )
    return counts
