"""corrocost cost model (v4, ISSUE 20): price every hot entry point's
jaxpr in flops and HBM-model bytes, fit the counts to polynomials in
the config extents, and project the declared 1M point as a static
roofline — before any hardware sees the program.

corrobudget (``shapes.py``) prices what the state *is*; this tier
prices what one round *does*. The two must agree on growth: a table the
inventory prices at degree N must not be touched by compute of a higher
degree, and compute must never outgrow the inventory (an N×N pairwise
intermediate shows up here as a fitted N²-term long before it OOMs).

Methodology — deliberately simple, so the counts stay *exactly*
polynomial in the extents and the fits are interpolations, not
regressions:

- every primitive's flop cost is ``weight × element count`` with a
  small per-primitive weight table (``dot_general`` gets the real
  ``2·m·n·k``); weights are constants, never ``log`` terms, so a fit
  that reproduces held-out points proves the cost *function* is the
  fitted polynomial, not approximately near it;
- HBM-model bytes are the unfused upper bound: every equation reads
  its inputs and writes its outputs once. XLA fuses most of that away —
  the ``lowered.compile().cost_analysis()`` cross-check (see
  ``xla_agreement``) bounds the constant-factor slack;
- control flow: ``scan`` multiplies its body by the static trip count,
  ``cond`` takes the most expensive branch (the roofline branch),
  ``pallas_call`` multiplies the kernel body by the grid,
  ``while`` bodies count once (trip count is dynamic — recorded).

The module imports jax ONLY inside tracing helpers: the lint engine
(``runner.py``) registers :func:`check_project` (the ``cost-drift``
rule), which is pure AST + symbolic arithmetic and must work with no
backend, no devices, and no jax import — exactly like ``mem-budget``.

Tier-1 gates live in ``tests/test_cost.py``; the CI face is
``scripts/cost_probe.py`` -> ``artifacts/cost_r20.json``
(docs/corrolint.md, "corrocost").
"""

from __future__ import annotations

import dataclasses
import math
from fractions import Fraction
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from corrosion_tpu.analysis import shapes
from corrosion_tpu.analysis.base import Finding
from corrosion_tpu.analysis.callgraph import Project
from corrosion_tpu.analysis.shapes import index_classes

RULE = "cost-drift"

#: state root -> the extent degrees corrocost's fitted polynomials have
#: (and therefore the degrees the SYMBOLIC inventory must have: compute
#: scales with the tables it touches, nothing superlinear hides). A PR
#: that changes a constructor's growth must re-price the fits
#: (``scripts/cost_probe.py``) and update this registry in the same
#: change — the ``cost-drift`` lint rule holds the two together.
COST_DEGREES: Dict[str, Dict[str, int]] = {
    # scale state: every plane is [N], [N, M] or smaller — one round is
    # bilinear in (N, M)
    "ScaleSimState": {"N": 1, "M": 1},
    # full-view state: the [N, N] membership plane is the design
    # (sim/swim.py) — one round is quadratic in N. Slot planes keep the
    # inventory degree-1 in M; the full tier's fit sweeps N only and
    # holds M at the template (the scale tier owns the M axis).
    "SimState": {"N": 2, "M": 1},
}

#: the declared flagship projection point (shared with corrobudget —
#: kept equal to ``shapes.HBM_BUDGET["point"]`` by tests/test_cost.py)
ROOFLINE_POINT: Dict[str, int] = {"N": 1_000_000, "M": 64}


# --------------------------------------------------------------------------
# the per-primitive cost counter
# --------------------------------------------------------------------------

#: pure data-movement primitives: 0 flops (bytes still counted)
_ZERO_FLOP = frozenset({
    "broadcast_in_dim", "reshape", "squeeze", "transpose", "rev",
    "slice", "dynamic_slice", "concatenate", "pad", "iota", "copy",
    "gather", "bitcast_convert_type", "stop_gradient",
    "optimization_barrier", "expand_dims", "device_put",
})

#: flop weight per OUTPUT element for primitives that are not 1/element.
#: Constants by design (no log terms): see the module docstring.
_FLOP_WEIGHTS = {
    "sort": 8,          # stand-in for the comparator network depth
    "top_k": 4,
    "random_bits": 16,  # threefry rounds per emitted word
    "random_fold_in": 16,
    "random_split": 16,
    "random_wrap": 0,
    "random_unwrap": 0,
    "population_count": 1,
    "clz": 1,
}

#: reductions price at the INPUT size (one combine per input element)
_INPUT_SIZED = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_and", "reduce_or",
    "reduce_prod", "argmax", "argmin", "cumsum", "cummax", "cummin",
    "cumprod", "reduce_precision",
})


def _size(aval) -> int:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 1
    return math.prod(shape) if shape else 1


def _nbytes(aval) -> int:
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return 0
    return _size(aval) * dtype.itemsize


@dataclasses.dataclass(frozen=True)
class CostCount:
    """One jaxpr's priced totals (the unit every fit interpolates)."""

    flops: int
    hbm_bytes: int
    eqns: int
    while_loops: int = 0  # bodies counted once — dynamic trip counts

    def minus(self, other: "CostCount") -> "CostCount":
        return CostCount(self.flops - other.flops,
                         self.hbm_bytes - other.hbm_bytes,
                         self.eqns - other.eqns,
                         max(self.while_loops, other.while_loops))


def _dot_flops(eqn) -> int:
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    batch = math.prod(lhs[d] for d in lb) if lb else 1
    k = math.prod(lhs[d] for d in lc) if lc else 1
    out = _size(eqn.outvars[0].aval)
    return 2 * out * k * (1 if batch else 1)


def _branch_cost(closed, mult: int) -> CostCount:
    acc = {"flops": 0, "hbm_bytes": 0, "eqns": 0, "while_loops": 0}
    _walk(closed.jaxpr if hasattr(closed, "jaxpr") else closed, acc, mult)
    return CostCount(**acc)


def _walk(jaxpr, acc: Dict[str, int], mult: int) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "scan":
            _walk(eqn.params["jaxpr"].jaxpr, acc,
                  mult * int(eqn.params["length"]))
            continue
        if name == "while":
            acc["while_loops"] += 1
            _walk(eqn.params["cond_jaxpr"].jaxpr, acc, mult)
            _walk(eqn.params["body_jaxpr"].jaxpr, acc, mult)
            continue
        if name == "cond":
            # the roofline branch: whichever arm prices highest
            costs = [_branch_cost(br, mult)
                     for br in eqn.params["branches"]]
            worst = max(costs, key=lambda c: (c.flops, c.hbm_bytes))
            acc["flops"] += worst.flops
            acc["hbm_bytes"] += worst.hbm_bytes
            acc["eqns"] += worst.eqns
            acc["while_loops"] += worst.while_loops
            continue
        if name == "pallas_call":
            grid = eqn.params["grid_mapping"].grid
            cells = math.prod(int(g) for g in grid) if grid else 1
            inner = eqn.params["jaxpr"]
            _walk(inner.jaxpr if hasattr(inner, "jaxpr") else inner,
                  acc, mult * cells)
            continue
        recursed = False
        for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
            inner = eqn.params.get(key)
            if inner is not None:
                _walk(inner.jaxpr if hasattr(inner, "jaxpr") else inner,
                      acc, mult)
                recursed = True
                break
        if recursed:
            continue
        out_elems = sum(_size(v.aval) for v in eqn.outvars)
        if name == "dot_general":
            flops = _dot_flops(eqn)
        elif name in _ZERO_FLOP:
            flops = 0
        elif name in _INPUT_SIZED:
            flops = sum(_size(v.aval) for v in eqn.invars
                        if hasattr(v, "aval"))
        elif name.startswith("scatter"):
            # work scales with the UPDATES, not the operand being
            # scattered into (operand, indices, updates)
            flops = (_size(eqn.invars[2].aval)
                     if len(eqn.invars) >= 3 else out_elems)
        else:
            flops = _FLOP_WEIGHTS.get(name, 1) * out_elems
        io = sum(_nbytes(v.aval) for v in eqn.invars
                 if hasattr(v, "aval"))
        io += sum(_nbytes(v.aval) for v in eqn.outvars)
        acc["flops"] += mult * flops
        acc["hbm_bytes"] += mult * io
        acc["eqns"] += 1


def count_jaxpr(closed) -> CostCount:
    """Price a closed jaxpr with the corrocost model."""
    acc = {"flops": 0, "hbm_bytes": 0, "eqns": 0, "while_loops": 0}
    _walk(closed.jaxpr, acc, 1)
    return CostCount(**acc)


# --------------------------------------------------------------------------
# the priced entry-point registry
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PricedEntry:
    """One hot entry point's pricing recipe.

    ``build(cfg, rounds)`` -> closed jaxpr (abstract trace — no arrays,
    no devices: a 1M-node trace costs ~2s and a few MB of constants).
    ``scan`` entries take a per-dispatch round count; step entries
    ignore it. ``template`` builds the config family the fit sweeps —
    replace only the extents, keep every knob."""

    name: str
    root: str                       # COST_DEGREES key it is gated against
    extents: Tuple[str, ...]        # fit symbols
    scanned: bool                   # True: per-round = marginal round
    template: Callable[[], object]
    build: Callable[[object, int], object]
    #: False for entries whose cost is only PIECEWISE polynomial (the
    #: fused path: pallas grids are ceil-divisions of N, so tail
    #: masking wobbles the count ~1e-4 between grid-aligned points).
    #: Their roofline uses a DIRECT 1M abstract trace as truth and
    #: reports the fit's relative error instead of demanding bit-equal
    #: extrapolation.
    exact_fit: bool = True


def _flagship_cfg():
    from corrosion_tpu.sim.scale_step import scale_sim_config

    return scale_sim_config(100_000)


def _full_cfg():
    from corrosion_tpu.sim.config import SimConfig

    # the tracecount harness's full-view shape (tracecount._full_cfg)
    return SimConfig(n_nodes=12, n_origins=4, n_rows=4, n_cols=2,
                     tx_max_cells=2)


def config_at(template, env: Dict[str, int]):
    """The template config with its extents rebound (validated)."""
    kw = {}
    if "N" in env:
        kw["n_nodes"] = int(env["N"])
    if "M" in env and hasattr(template, "m_slots"):
        kw["m_slots"] = int(env["M"])
    return dataclasses.replace(template, **kw).validate()


def _scale_specs(cfg, rounds: int):
    import jax
    import jax.random as jr

    from corrosion_tpu.sim.scale_step import (
        ScaleSimState,
        make_write_inputs,
    )
    from corrosion_tpu.sim.transport import NetModel

    st = jax.eval_shape(lambda: ScaleSimState.create(cfg))
    net = jax.eval_shape(
        lambda: NetModel.create(cfg.n_nodes, drop_prob=0.05))
    key = jax.eval_shape(lambda: jr.key(0))
    mask = jax.ShapeDtypeStruct((rounds, cfg.n_nodes), bool)
    inputs = jax.eval_shape(
        lambda m: make_write_inputs(cfg, jr.key(8), rounds, m), mask)
    return st, net, key, inputs


def _trace_scale_step(cfg, rounds: int):
    import functools

    import jax

    from corrosion_tpu.sim.scale_step import ScaleRoundInput, scale_sim_step

    st, net, key, _ = _scale_specs(cfg, 1)
    inp = jax.eval_shape(lambda: ScaleRoundInput.quiet(cfg))
    return jax.make_jaxpr(functools.partial(scale_sim_step, cfg))(
        st, net, key, inp)


def _trace_scale_run(cfg, rounds: int):
    import functools

    import jax

    from corrosion_tpu.sim.scale_step import scale_run_rounds

    if cfg.fused in ("on", "interpret"):
        from corrosion_tpu.ops import megakernel

        megakernel.prime_fused(cfg)  # eager probes BEFORE the trace
    st, net, key, inputs = _scale_specs(cfg, rounds)
    return jax.make_jaxpr(functools.partial(scale_run_rounds, cfg))(
        st, net, key, inputs)


def _trace_scale_run_carry(cfg, rounds: int):
    import functools

    import jax

    from corrosion_tpu.sim.scale_step import scale_run_rounds_carry

    if cfg.fused in ("on", "interpret"):
        from corrosion_tpu.ops import megakernel

        megakernel.prime_fused(cfg)
    st, net, key, inputs = _scale_specs(cfg, rounds)
    return jax.make_jaxpr(functools.partial(scale_run_rounds_carry, cfg))(
        st, net, key, inputs)


def _with(factory, **knobs):
    def template():
        return dataclasses.replace(factory(), **knobs).validate()

    return template


def _trace_full_step(cfg, rounds: int):
    import functools

    import jax
    import jax.random as jr

    from corrosion_tpu.sim.step import RoundInput, SimState, sim_step
    from corrosion_tpu.sim.transport import NetModel

    st = jax.eval_shape(lambda: SimState.create(cfg))
    net = jax.eval_shape(lambda: NetModel.create(cfg.n_nodes))
    key = jax.eval_shape(lambda: jr.key(0))
    inp = jax.eval_shape(lambda: RoundInput.quiet(cfg))
    return jax.make_jaxpr(functools.partial(sim_step, cfg))(
        st, net, key, inp)


#: every entry point the bench/tracecount registries care about, priced.
#: ``tracecount.HOT_ENTRY_POINTS`` must stay a SUBSET of this dict
#: (tests/test_cost.py coverage gate): registering a new hot entry
#: without pricing it fails tier-1. ``sharded_scale_run``'s jaxpr is
#: placement-independent (sharding changes collectives, not the traced
#: program) — its cross-shard traffic is priced by
#: ``analysis/collectives.py`` on the real lowered modules.
PRICED_ENTRY_POINTS: Dict[str, PricedEntry] = {
    "full_sim_step": PricedEntry(
        "full_sim_step", "SimState", ("N",), False,
        _full_cfg, _trace_full_step),
    "scale_sim_step": PricedEntry(
        "scale_sim_step", "ScaleSimState", ("N", "M"), False,
        _flagship_cfg, _trace_scale_step),
    "segment_dispatch": PricedEntry(
        "segment_dispatch", "ScaleSimState", ("N", "M"), True,
        _flagship_cfg, _trace_scale_run_carry),
    "segmented_soak": PricedEntry(
        # the soak runner dispatches the SAME donated carry program as
        # segment_dispatch — priced under its registered name so the
        # coverage gate stays a set relation, not a special case
        "segmented_soak", "ScaleSimState", ("N", "M"), True,
        _flagship_cfg, _trace_scale_run_carry),
    "sharded_scale_run": PricedEntry(
        "sharded_scale_run", "ScaleSimState", ("N", "M"), True,
        _flagship_cfg, _trace_scale_run),
    "fused_scale_run": PricedEntry(
        "fused_scale_run", "ScaleSimState", ("N", "M"), True,
        _with(_flagship_cfg, fused="interpret"), _trace_scale_run,
        exact_fit=False),
    "quiet_scale_run": PricedEntry(
        "quiet_scale_run", "ScaleSimState", ("N", "M"), True,
        _with(_flagship_cfg, quiet="on"), _trace_scale_run_carry),
}

#: per-dispatch round count the scan fits trace at (marginal = r2 - r1)
_FIT_ROUNDS = 2


def price_entry(name: str, env: Dict[str, int],
                rounds: Optional[int] = None,
                template=None) -> CostCount:
    """Price one entry at concrete extents (one abstract trace)."""
    entry = PRICED_ENTRY_POINTS[name]
    cfg = config_at(template if template is not None else entry.template(),
                    env)
    return count_jaxpr(entry.build(cfg, rounds or _FIT_ROUNDS))


def price_per_round(name: str, env: Dict[str, int],
                    template=None) -> CostCount:
    """The marginal cost of ONE round: scan entries price at 2 rounds
    and 1 round and difference (exactly the scan body's contribution);
    step entries price the step itself."""
    entry = PRICED_ENTRY_POINTS[name]
    if not entry.scanned:
        return price_entry(name, env, template=template)
    two = price_entry(name, env, rounds=2, template=template)
    one = price_entry(name, env, rounds=1, template=template)
    return two.minus(one)


# --------------------------------------------------------------------------
# exact polynomial fits over the extents
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CostFit:
    """An exact interpolating polynomial for one entry × metric.

    ``basis`` holds monomial exponent tuples aligned with ``extents``
    (e.g. ``((0, 0), (1, 0), (0, 1), (1, 1))`` = 1, N, M, NM).
    ``exact`` is True when every HELD-OUT point reproduced bit-for-bit —
    the proof that the cost function IS this polynomial on the swept
    family, which is what licenses the 1M extrapolation."""

    entry: str
    metric: str
    extents: Tuple[str, ...]
    basis: Tuple[Tuple[int, ...], ...]
    coeffs: Tuple[Fraction, ...]
    points: Tuple[Tuple[int, ...], ...]
    holdouts: Tuple[Tuple[int, ...], ...]
    exact: bool

    def at(self, env: Dict[str, int]) -> int:
        total = Fraction(0)
        for expts, c in zip(self.basis, self.coeffs):
            term = c
            for sym, e in zip(self.extents, expts):
                term *= Fraction(env[sym]) ** e
            total += term
        if total.denominator != 1:
            raise ValueError(f"non-integer cost at {env}: {total}")
        return int(total)

    def degree(self, sym: str) -> int:
        if sym not in self.extents:
            return 0
        i = self.extents.index(sym)
        return max((e[i] for e, c in zip(self.basis, self.coeffs)
                    if c != 0), default=0)

    def render(self) -> str:
        parts = []
        for expts, c in zip(self.basis, self.coeffs):
            if c == 0:
                continue
            mono = "*".join(
                sym if e == 1 else f"{sym}^{e}"
                for sym, e in zip(self.extents, expts) if e)
            parts.append(f"{c}{'*' + mono if mono else ''}")
        return " + ".join(parts) or "0"


def _solve(rows: List[List[Fraction]],
           ys: List[Fraction]) -> List[Fraction]:
    """Exact Gaussian elimination (the systems are 3x3 / 4x4)."""
    n = len(rows)
    aug = [list(r) + [y] for r, y in zip(rows, ys)]
    for i in range(n):
        piv = next((r for r in range(i, n) if aug[r][i] != 0), None)
        if piv is None:
            raise ValueError("singular fit system — degenerate points")
        aug[i], aug[piv] = aug[piv], aug[i]
        inv = aug[i][i]
        aug[i] = [x / inv for x in aug[i]]
        for r in range(n):
            if r != i and aug[r][i] != 0:
                f = aug[r][i]
                aug[r] = [a - f * b for a, b in zip(aug[r], aug[i])]
    return [aug[r][n] for r in range(n)]


def _fit_points(entry: PricedEntry, template) -> Tuple[
        Tuple[Tuple[int, ...], ...], Tuple[Tuple[int, ...], ...],
        Tuple[Tuple[int, ...], ...]]:
    """(basis, fit points, holdout points) for the entry's extents,
    scaled so every point validates against the template config."""
    if entry.extents == ("N",):
        # full view: quadratic in N. Points respect n_origins <= N.
        n0 = max(8, getattr(template, "n_origins", 4) * 2)
        basis = ((0,), (1,), (2,))
        pts = ((n0,), (2 * n0,), (4 * n0,))
        hold = ((3 * n0,),)
        return basis, pts, hold
    n0 = 64
    n_origins = getattr(template, "n_origins", 16)
    while n0 < max(n_origins, 2 * getattr(template, "sync_peers", 0)):
        n0 *= 2
    m0 = getattr(template, "m_slots", 64)
    basis = ((0, 0), (1, 0), (0, 1), (1, 1))
    pts = ((n0, m0), (2 * n0, m0), (n0, 2 * m0), (2 * n0, 2 * m0))
    hold = ((3 * n0, m0), (n0, 3 * m0))
    return basis, pts, hold


def fit_entry(name: str, template=None) -> Dict[str, CostFit]:
    """Exact per-round fits for one entry: ``{"flops": CostFit,
    "hbm_bytes": CostFit}``. Every fit interpolates the fit points and
    verifies the holdouts; ``exact`` records whether the holdouts
    reproduced (the probe and tier-1 gate on it)."""
    entry = PRICED_ENTRY_POINTS[name]
    template = template if template is not None else entry.template()
    basis, pts, hold = _fit_points(entry, template)
    counts = {p: price_per_round(name, dict(zip(entry.extents, p)),
                                 template=template) for p in pts + hold}
    fits: Dict[str, CostFit] = {}
    for metric in ("flops", "hbm_bytes"):
        rows = [[Fraction(math.prod(int(x) ** e
                                    for x, e in zip(p, expts)))
                 for expts in basis] for p in pts]
        ys = [Fraction(getattr(counts[p], metric)) for p in pts]
        coeffs = _solve(rows, ys)
        fit = CostFit(name, metric, entry.extents, basis, tuple(coeffs),
                      pts, hold, exact=True)
        exact = all(
            fit.at(dict(zip(entry.extents, h)))
            == getattr(counts[h], metric) for h in hold)
        fits[metric] = dataclasses.replace(fit, exact=exact)
    return fits


_FIT_CACHE: Dict[object, Dict[str, CostFit]] = {}


def fit_for_config(cfg, entry: str = "sharded_scale_run") -> Dict[
        str, CostFit]:
    """Fits for a LIVE config family (the bench provenance hook): the
    swept points keep every knob of ``cfg`` and rebind only the
    extents, so the projection prices the run that was measured."""
    key = (entry, cfg)
    if key not in _FIT_CACHE:
        _FIT_CACHE[key] = fit_entry(entry, template=cfg)
    return _FIT_CACHE[key]


def projected_flops(cfg, n_nodes: int,
                    entry: str = "sharded_scale_run") -> int:
    """Per-round flops of ``cfg``'s family at N=n_nodes (the
    ``flops_projected_1m`` bench field when n_nodes=1M)."""
    fit = fit_for_config(cfg, entry)["flops"]
    return fit.at({"N": n_nodes, "M": cfg.m_slots})


def xla_agreement(name: str = "scale_sim_step",
                  env: Optional[Dict[str, int]] = None) -> dict:
    """Compile one entry (single device) and compare the model against
    ``compiled.cost_analysis()`` where the backend reports it. The
    model is unfused and constant-weighted, XLA is fused and DCE'd —
    agreement means the RATIO stays inside a declared band, recorded
    either way. Returns ``{"reported": bool, ...}``."""
    import functools

    import jax

    entry = PRICED_ENTRY_POINTS[name]
    env = env or {"N": 64, "M": 64}
    cfg = config_at(entry.template(), env)
    closed = entry.build(cfg, 1)
    model = count_jaxpr(closed)

    from corrosion_tpu.sim.scale_step import ScaleRoundInput, scale_sim_step

    st, net, key, _ = _scale_specs(cfg, 1)
    inp = jax.eval_shape(lambda: ScaleRoundInput.quiet(cfg))
    comp = jax.jit(functools.partial(scale_sim_step, cfg)).lower(
        st, net, key, inp).compile()
    ca = comp.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    rec = {"entry": name, "env": dict(env),
           "model_flops": model.flops,
           "model_hbm_bytes": model.hbm_bytes,
           "band": XLA_AGREEMENT_BAND, "reported": False}
    if not ca or "flops" not in ca:
        return rec
    xla_flops = float(ca["flops"])
    xla_bytes = float(ca.get("bytes accessed", 0.0))
    rec.update({
        "reported": True,
        "xla_flops": xla_flops,
        "xla_bytes_accessed": xla_bytes,
        "flops_ratio": model.flops / max(1.0, xla_flops),
        "bytes_ratio": model.hbm_bytes / max(1.0, xla_bytes),
    })
    lo, hi = XLA_AGREEMENT_BAND
    rec["agrees"] = (lo <= rec["flops_ratio"] <= hi
                     and (xla_bytes == 0.0
                          or lo <= rec["bytes_ratio"] <= hi))
    return rec


#: model/XLA ratio band: the model is deliberately unfused (bytes read
#: high) and constant-weighted (flops read low vs XLA's per-op counts);
#: measured ratios sit near 0.4x (flops) and 2.8x (bytes). A drift past
#: 8x either way means the model lost a subsystem, not a constant.
XLA_AGREEMENT_BAND: Tuple[float, float] = (1 / 8, 8.0)


def roofline(entries: Sequence[str] = ("sharded_scale_run",
                                       "fused_scale_run",
                                       "quiet_scale_run")) -> dict:
    """The static 1M roofline (PERF.md "Static roofline"): per-round
    flops and HBM-model bytes projected to :data:`ROOFLINE_POINT`,
    cross-checked against a DIRECT abstract trace at N=1M — the
    extrapolation must reproduce the real jaxpr count bit-for-bit."""
    out = {"point": dict(ROOFLINE_POINT), "entries": {}}
    for name in entries:
        entry = PRICED_ENTRY_POINTS[name]
        fits = fit_entry(name)
        direct = price_per_round(name, dict(ROOFLINE_POINT))
        rec = {"exact_fit_expected": entry.exact_fit}
        for metric, fit in fits.items():
            proj = fit.at(ROOFLINE_POINT)
            truth = getattr(direct, metric)
            rec[metric + "_per_round"] = truth if not entry.exact_fit \
                else proj
            rec[metric + "_poly"] = fit.render()
            rec[metric + "_fit_exact"] = fit.exact
            if entry.exact_fit:
                rec[metric + "_direct_1m_matches"] = proj == truth
            else:
                rec[metric + "_fit_rel_err"] = (
                    abs(proj - truth) / max(1, truth))
        out["entries"][name] = rec
    return out


# --------------------------------------------------------------------------
# the static lint rule (no jax — runs in the no-backend lint engine)
# --------------------------------------------------------------------------


def inventory_degrees(inv) -> Dict[str, int]:
    """Max per-symbol shape degree over an inventory's resolved leaves
    (a leaf's degree = the sum over its dims — an [N, N] plane is
    degree 2). Unresolved leaves are mem-budget's finding, not ours."""
    degs: Dict[str, int] = {"N": 0, "M": 0}
    for leaf in inv.leaves.values():
        if leaf.dims is None:
            continue
        for sym in degs:
            d = sum(dim.degree(sym) if hasattr(dim, "degree") else 0
                    for dim in leaf.dims)
            degs[sym] = max(degs[sym], d)
    return degs


def check_project(project: Project) -> List[Finding]:
    """``cost-drift``: the walked tree's own state constructors must
    grow at exactly the degrees the committed cost fits were priced at.
    A new [N, N] plane (or a vanished [N, M] one) flips the symbolic
    inventory's degree and fails lint until the fits are re-run and
    :data:`COST_DEGREES` is updated in the same PR."""
    findings: List[Finding] = []
    classes = index_classes(project)
    for root, declared in COST_DEGREES.items():
        info = classes.get(root)
        if info is None:
            continue  # walked subset does not define this state
        inv = shapes.build_inventory(project, root,
                                     shapes.ConfigVal.default())
        if inv is None:
            continue
        got = inventory_degrees(inv)
        for sym, want in declared.items():
            have = got.get(sym, 0)
            if have == want:
                continue
            findings.append(Finding(
                path=info.module.path, line=info.node.lineno, rule=RULE,
                message=(
                    f"{root}'s symbolic inventory is degree {have} in "
                    f"{sym} but corrocost's committed fits price degree "
                    f"{want} — the static roofline and the 1M flop "
                    "projection are stale"),
                hint=("re-run scripts/cost_probe.py and update "
                      "analysis/cost.py COST_DEGREES with the PR that "
                      "changes the state's growth"),
            ))
    return findings
