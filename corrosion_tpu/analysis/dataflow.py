"""Forward dataflow over function bodies: the v2 checker substrate.

A checker subclasses :class:`ForwardAnalysis`, defines what its
abstract values are (a taint bit, an abstract dtype, anything
joinable), and gets for free the structural plumbing every pass was
otherwise going to reimplement:

- environments (variable -> abstract value) threaded through
  assignments in program order;
- tuple packing/unpacking (``(st, key), infos = f(...)`` distributes a
  :class:`TupleVal` across the target pattern — the pytree-ish shape
  all the sim carries use);
- branch joins: ``if``/``else`` evaluate from the same pre-state and
  merge by :meth:`join`, so a fact true on either path survives;
- loops: the body runs twice so loop-carried values reach their own
  uses (the carries here are small tuples — two passes reach the
  fixed point the checkers care about);
- ``with``/``try`` bodies in sequence, headers first.

Subclasses override the ``eval_*`` hooks to give calls/attributes/
operators meaning and the ``on_*`` hooks to flag sinks. Everything
unknown evaluates to ``None`` (bottom), which every hook must treat as
"no information" — the precision-over-recall contract: the engine never
guesses, so a checker built on it never flags what it cannot prove.

Nested ``def``/``lambda`` bodies are NOT walked (they run at call
time); :meth:`on_nested_def` lets a checker record them (the donation
pass uses it for the closure blind spot).
"""

from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional

from corrosion_tpu.analysis.base import Finding
from corrosion_tpu.analysis.callgraph import FunctionInfo


class TupleVal:
    """Abstract tuple: element values positionally, joinable."""

    __slots__ = ("elements",)

    def __init__(self, elements):
        self.elements = tuple(elements)

    def __eq__(self, other):
        return (isinstance(other, TupleVal)
                and self.elements == other.elements)

    def __hash__(self):
        return hash(self.elements)

    def __repr__(self):
        return f"TupleVal{self.elements}"


Env = Dict[str, Any]


class ForwardAnalysis:
    """One function body, walked forward with an abstract environment."""

    def __init__(self, fn: Optional[FunctionInfo], path: str,
                 findings: Optional[List[Finding]] = None):
        self.fn = fn
        self.path = path
        self.findings = findings if findings is not None else []
        #: join of every `return` expression's abstract value
        self.return_value: Any = None

    # -- overridable hooks -------------------------------------------------

    def join(self, a: Any, b: Any) -> Any:
        """Merge two abstract values (control-flow join). Default: keep
        the common value, drop to bottom on disagreement; tuples join
        element-wise."""
        if a == b:
            return a
        if isinstance(a, TupleVal) and isinstance(b, TupleVal) and (
                len(a.elements) == len(b.elements)):
            return TupleVal(
                self.join(x, y) for x, y in zip(a.elements, b.elements)
            )
        return None

    def initial_env(self) -> Env:
        """Starting environment (parameter values). Default: bottom."""
        return {}

    def eval_call(self, node: ast.Call, env: Env, args: List[Any],
                  keywords: Dict[str, Any]) -> Any:
        """Abstract value of a call, given the already-evaluated
        positional/keyword argument values (sink checks live here)."""
        return None

    def eval_attr(self, node: ast.Attribute, base: Any, env: Env) -> Any:
        """Abstract value of ``base.attr`` given base's value."""
        return None

    def eval_binop(self, node: ast.AST, left: Any, right: Any,
                   env: Env) -> Any:
        return None

    def eval_subscript(self, node: ast.Subscript, base: Any,
                       env: Env) -> Any:
        """Default: indexing an abstract tuple by a constant selects the
        element; anything else is bottom."""
        if isinstance(base, TupleVal) and isinstance(node.slice,
                                                     ast.Constant):
            idx = node.slice.value
            if isinstance(idx, int) and -len(base.elements) <= idx < len(
                    base.elements):
                return base.elements[idx]
        return None

    def eval_constant(self, node: ast.Constant, env: Env) -> Any:
        return None

    def on_store(self, name: str, value: Any, node: ast.AST,
                 env: Env) -> None:
        """A variable was (re)bound. Sink hook for store-side checks."""

    def on_nested_def(self, node: ast.AST, env: Env) -> None:
        """A nested def/lambda was encountered (its body is NOT walked)."""

    # -- expression evaluation ---------------------------------------------

    def eval_expr(self, node: Optional[ast.AST], env: Env) -> Any:
        if node is None:
            return None
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, (ast.Tuple, ast.List)):
            return TupleVal(self.eval_expr(e, env) for e in node.elts)
        if isinstance(node, ast.Constant):
            return self.eval_constant(node, env)
        if isinstance(node, ast.Call):
            args = [self.eval_expr(arg, env) for arg in node.args]
            keywords = {
                kw.arg: self.eval_expr(kw.value, env)
                for kw in node.keywords if kw.arg is not None
            }
            for kw in node.keywords:
                if kw.arg is None:  # **kwargs
                    self.eval_expr(kw.value, env)
            return self.eval_call(node, env, args, keywords)
        if isinstance(node, ast.Attribute):
            return self.eval_attr(node, self.eval_expr(node.value, env),
                                  env)
        if isinstance(node, ast.Subscript):
            base = self.eval_expr(node.value, env)
            self.eval_expr(node.slice, env)
            return self.eval_subscript(node, base, env)
        if isinstance(node, ast.BinOp):
            return self.eval_binop(
                node, self.eval_expr(node.left, env),
                self.eval_expr(node.right, env), env)
        if isinstance(node, ast.UnaryOp):
            return self.eval_expr(node.operand, env)
        if isinstance(node, ast.BoolOp):
            vals = [self.eval_expr(v, env) for v in node.values]
            out = vals[0]
            for v in vals[1:]:
                out = self.join(out, v)
            return out
        if isinstance(node, ast.IfExp):
            self.eval_expr(node.test, env)
            return self.join(self.eval_expr(node.body, env),
                             self.eval_expr(node.orelse, env))
        if isinstance(node, ast.Compare):
            self.eval_expr(node.left, env)
            for comp in node.comparators:
                self.eval_expr(comp, env)
            return None
        if isinstance(node, ast.Starred):
            return self.eval_expr(node.value, env)
        if isinstance(node, (ast.Lambda,)):
            self.on_nested_def(node, env)
            return None
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            # comprehension bodies see their own scope; evaluate the
            # iterables (data flows in through them) and stop there
            for gen in node.generators:
                self.eval_expr(gen.iter, env)
            return None
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                self.eval_expr(v, env)
            return None
        if isinstance(node, ast.FormattedValue):
            return self.eval_expr(node.value, env)
        if isinstance(node, ast.Dict):
            for k, v in zip(node.keys, node.values):
                self.eval_expr(k, env)
                self.eval_expr(v, env)
            return None
        if isinstance(node, (ast.Slice,)):
            for part in (node.lower, node.upper, node.step):
                self.eval_expr(part, env)
            return None
        return None

    # -- statement walk ----------------------------------------------------

    def _bind(self, target: ast.AST, value: Any, env: Env,
              node: ast.AST) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = value
            self.on_store(target.id, value, node, env)
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            if (isinstance(value, TupleVal)
                    and len(value.elements) == len(elts)
                    and not any(isinstance(e, ast.Starred) for e in elts)):
                for elt, v in zip(elts, value.elements):
                    self._bind(elt, v, env, node)
            else:
                # unknown/starred unpack: each element inherits the
                # JOIN of the whole value's facts (taint still flows
                # through `st, *rest = ...` — conservatively smeared)
                if isinstance(value, TupleVal):
                    spread = None
                    for el in value.elements:
                        spread = self.join(spread, el) if (
                            spread is not None) else el
                else:
                    spread = value
                for elt in elts:
                    self._bind(
                        elt.value if isinstance(elt, ast.Starred) else elt,
                        spread, env, node)
            return
        if isinstance(target, (ast.Attribute, ast.Subscript)):
            # a store through an attribute/subscript: evaluate the
            # receiver (sinks may fire) but bind nothing
            self.eval_expr(target.value, env)
            self.on_store_into(target, value, node, env)

    def on_store_into(self, target: ast.AST, value: Any, node: ast.AST,
                      env: Env) -> None:
        """``x.attr = v`` / ``x[i] = v`` — sink hook for ref stores."""

    def _join_envs(self, a: Env, b: Env) -> Env:
        out: Env = {}
        for k in set(a) | set(b):
            out[k] = self.join(a.get(k), b.get(k))
        return out

    def run(self, body: List[ast.stmt], env: Optional[Env] = None) -> Env:
        if env is None:
            env = self.initial_env()
        for stmt in body:
            env = self._stmt(stmt, env)
        return env

    def _stmt(self, stmt: ast.stmt, env: Env) -> Env:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.on_nested_def(stmt, env)
            env[stmt.name] = None
            return env
        if isinstance(stmt, ast.ClassDef):
            return env
        if isinstance(stmt, ast.Assign):
            value = self.eval_expr(stmt.value, env)
            for target in stmt.targets:
                self._bind(target, value, env, stmt)
            return env
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._bind(stmt.target, self.eval_expr(stmt.value, env),
                           env, stmt)
            return env
        if isinstance(stmt, ast.AugAssign):
            cur = self.eval_expr(stmt.target, env) if isinstance(
                stmt.target, ast.Name) else None
            value = self.eval_binop(
                stmt, cur, self.eval_expr(stmt.value, env), env)
            self._bind(stmt.target, value, env, stmt)
            return env
        if isinstance(stmt, ast.Return):
            val = self.eval_expr(stmt.value, env)
            self.return_value = (val if self.return_value is None
                                 else self.join(self.return_value, val))
            return env
        if isinstance(stmt, (ast.Expr, ast.Assert)):
            self.eval_expr(getattr(stmt, "value", None)
                           or getattr(stmt, "test", None), env)
            return env
        if isinstance(stmt, ast.If):
            self.eval_expr(stmt.test, env)
            then_env = self.run(stmt.body, dict(env))
            else_env = self.run(stmt.orelse, dict(env))
            return self._join_envs(then_env, else_env)
        if isinstance(stmt, (ast.While,)):
            self.eval_expr(stmt.test, env)
            once = self.run(stmt.body, dict(env))
            joined = self._join_envs(env, once)
            twice = self.run(stmt.body, dict(joined))
            return self._join_envs(joined, twice)
        if isinstance(stmt, ast.For):
            self.eval_expr(stmt.iter, env)
            loop_env = dict(env)
            self._bind(stmt.target, None, loop_env, stmt)
            once = self.run(stmt.body, loop_env)
            joined = self._join_envs(env, once)
            self._bind(stmt.target, None, joined, stmt)
            twice = self.run(stmt.body, dict(joined))
            out = self._join_envs(joined, twice)
            return self.run(stmt.orelse, out)
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                ctx = self.eval_expr(item.context_expr, env)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, ctx, env, stmt)
            return self.run(stmt.body, env)
        if isinstance(stmt, ast.Try):
            env = self.run(stmt.body, env)
            for handler in stmt.handlers:
                env = self._join_envs(env, self.run(handler.body,
                                                    dict(env)))
            env = self.run(stmt.orelse, env)
            return self.run(stmt.finalbody, env)
        if isinstance(stmt, (ast.Raise,)):
            self.eval_expr(stmt.exc, env)
            return env
        if isinstance(stmt, ast.Delete):
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    env.pop(target.id, None)
            return env
        return env

    # -- entry point -------------------------------------------------------

    def analyze(self) -> Any:
        """Walk self.fn's body; returns the joined return value (for
        summary passes)."""
        if self.fn is None:
            raise ValueError("analyze() needs a FunctionInfo")
        self.run(list(self.fn.node.body))
        return self.return_value
