"""dtype-flow: the narrow-dtype byte budget, enforced at the boundary.

PERF.md's fused-path budget (~10k rounds/s) is a BYTE budget: the
small-range HBM planes (``mem_timer``, ``mem_tx``, ``q_cell``,
``q_seq``, ``q_nseq``, ``q_tx``, ``last_sync``) live as int16
(``ScaleSimConfig.narrow_dtypes``) and one silent int16->int32 upcast
on a carry leaf doubles that plane's traffic — AND changes the carry
aval, so every downstream jit retraces. jnp makes the upcast easy to
write: mix a narrow plane with any concrete int32 operand and the
promotion rules widen silently.

**dtype-widen** simulates those promotion rules through the hot
``sim``/``ops`` modules (on the :mod:`dataflow` engine): narrow-leaf
reads seed int16 abstract dtypes, Python scalars stay weak (they do
NOT widen — jax's weak-type rule), concrete wider operands promote,
and an explicit ``.astype(...)`` resets to whatever it names. The rule
fires only at the declared-narrow BOUNDARIES — a narrow keyword
(``_replace(mem_timer=...)``, constructor kwargs) or a narrow kernel
out-ref store (``o_timer[:] = ...``) receiving a provably-wider
concrete integer. Mid-kernel promotion stays free (megakernel
deliberately computes wide and casts back at the store); a dynamic
``.astype(ref.dtype)`` evaluates to unknown and never flags.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, List, NamedTuple, Optional

from corrosion_tpu.analysis.base import Finding, dotted_name
from corrosion_tpu.analysis.callgraph import FunctionInfo, Project
from corrosion_tpu.analysis.dataflow import Env, ForwardAnalysis

RULE = "dtype-widen"

#: declared-narrow state leaves -> bit width (the ``narrow_dtypes``
#: registry, seeded from ``sim/scale_step.py`` + ``ops/megakernel.py``
#: boundaries; keep in sync with ``ScaleSimConfig.timer_dtype``).
#: ``mem_tx`` is 8 since ISSUE 12: under ``narrow_int8`` (the
#: corrobudget-identified shrink, docs/memory-budget.md) the budget
#: plane lives as int8, so its boundaries must never receive a
#: concretely-wider store — dynamic ``.astype(<plane>.dtype)`` casts
#: stay the contract at every boundary, which is also why the int16
#: default config needs no code change. ``q_tx``/``q_seq``/``q_nseq``
#: are 8 since ISSUE 19 (``narrow_q_int8``, the analogous queue-counter
#: tier) for the same reason. Since ISSUE 20 this registry is also
#: cross-checked against the REAL traced entry outputs:
#: ``tests/test_cost.py`` abstract-traces the scan entry under the
#: narrow knobs and asserts every name here exists in the carry at
#: exactly its declared width — the static rule and the runtime dtype
#: flow cannot drift apart silently.
NARROW_LEAVES: Dict[str, int] = {
    "mem_timer": 16,
    "mem_tx": 8,
    "q_cell": 16,
    "q_seq": 8,
    "q_nseq": 8,
    "q_tx": 8,
    "last_sync": 16,
}

#: kernel out-ref spellings of the same planes (``ops/megakernel.py``):
#: the swim kernel's timer/budget stores (``o_timer``/``o_tx``) and the
#: fused ingest kernel's narrowed queue-plane stores
#: (``o_q_cell``/``o_q_tx`` — the seq/nseq planes stay at their
#: constant 0/1 on the single-cell fused path and never re-store).
#: Every one of these must cast back at the store
#: (``.astype(ref.dtype)``): a widened store changes the donated
#: carry's aval and retraces every consumer (ISSUE 10).
NARROW_REFS: Dict[str, int] = {
    "o_timer": 16, "o_tx": 8, "m_timer": 16, "m_tx": 8,
    "o_q_cell": 16, "o_q_tx": 8,
}
NARROW_REFS.update(NARROW_LEAVES)

_DTYPE_NAMES = {
    "int8": ("int", 8), "int16": ("int", 16), "int32": ("int", 32),
    "int64": ("int", 64), "uint8": ("uint", 8), "uint16": ("uint", 16),
    "uint32": ("uint", 32), "uint64": ("uint", 64),
    "bool_": ("bool", 1), "float16": ("float", 16),
    "bfloat16": ("float", 16), "float32": ("float", 32),
    "float64": ("float", 64),
}


class Dtype(NamedTuple):
    kind: str  # "int" | "uint" | "float" | "bool" | "weak"
    bits: int
    origin: Optional[str] = None  # narrow leaf this value derives from


def _literal_dtype(node: Optional[ast.AST]) -> Optional[Dtype]:
    """``jnp.int16`` / ``np.int32`` / ``"int16"`` -> Dtype; dynamic
    expressions (``ref.dtype``) -> None (unknown, never flags)."""
    if node is None:
        return None
    name = ""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        name = node.value
    else:
        name = dotted_name(node).rsplit(".", 1)[-1]
    if name == "bool":
        name = "bool_"
    if name in _DTYPE_NAMES:
        kind, bits = _DTYPE_NAMES[name]
        return Dtype(kind, bits)
    return None


def promote(a: Optional[Dtype], b: Optional[Dtype]) -> Optional[Dtype]:
    """jnp-style promotion, narrowed to what the rule needs: weak
    scalars adopt the other side; mixed concrete ints widen to the max
    width; anything involving unknown is unknown."""
    if a is None or b is None:
        return None
    if a.kind == "weak":
        return b
    if b.kind == "weak":
        return a
    origin = a.origin or b.origin
    if a.kind in ("int", "uint") and b.kind in ("int", "uint"):
        bits = max(a.bits, b.bits)
        if a.kind != b.kind and a.bits == b.bits:
            bits = min(64, bits * 2)  # int16 x uint16 -> int32, etc.
        kind = "int" if "int" in (a.kind, b.kind) else "uint"
        return Dtype(kind, bits, origin)
    if "float" in (a.kind, b.kind):
        bits = max(x.bits for x in (a, b) if x.kind == "float")
        return Dtype("float", bits, origin)
    return Dtype(a.kind, max(a.bits, b.bits), origin)


#: jnp calls whose result keeps the first array argument's dtype
#: (verified against real jnp: cumsum/max/min reductions keep int16;
#: sum does NOT — it accumulates at int32 and lives below)
_PASS_FIRST = {
    "abs", "negative", "cumsum", "max", "min", "roll",
    "reshape", "broadcast_to", "squeeze", "transpose", "sort", "flip",
}
#: jnp calls that promote across their array arguments (clip/mod/
#: bitwise widen when any operand is wider — same rules as binops)
_PROMOTING = {"minimum", "maximum", "add", "multiply", "remainder",
              "power", "clip", "mod", "floor_divide", "bitwise_and",
              "bitwise_or", "bitwise_xor"}
#: reductions that accumulate at (at least) 32 bits regardless of the
#: input width — jnp.sum(int16) is int32
_WIDENING_REDUCTIONS = {"sum", "prod", "dot", "matmul", "tensordot"}


class _Analysis(ForwardAnalysis):
    def __init__(self, fn: FunctionInfo, findings: List[Finding]):
        super().__init__(fn, fn.path, findings)

    def initial_env(self) -> Env:
        # kernel refs arrive as parameters named after their plane
        return {
            name: Dtype("int", NARROW_REFS[name], origin=name)
            for name in self.fn.param_names() if name in NARROW_REFS
        }

    def join(self, a, b):
        if isinstance(a, Dtype) and isinstance(b, Dtype):
            return a if a == b else None
        return super().join(a, b)

    def eval_constant(self, node, env):
        if isinstance(node.value, bool):
            return Dtype("bool", 1)
        if isinstance(node.value, int):
            return Dtype("weak", 0)
        if isinstance(node.value, float):
            return Dtype("weak", 0)
        return None

    def eval_attr(self, node, base, env):
        if node.attr in NARROW_LEAVES:
            return Dtype("int", NARROW_LEAVES[node.attr],
                         origin=node.attr)
        if isinstance(base, Dtype) and node.attr in ("T", "real"):
            return base
        return None

    def eval_subscript(self, node, base, env):
        # indexing/slicing an array keeps its dtype
        if isinstance(base, Dtype):
            return base
        return super().eval_subscript(node, base, env)

    def eval_binop(self, node, left, right, env):
        # arithmetic and bit ops follow the same promotion rules
        return promote(self._as_dtype(left), self._as_dtype(right))

    @staticmethod
    def _as_dtype(v) -> Optional[Dtype]:
        return v if isinstance(v, Dtype) else None

    def _check_boundary(self, node: ast.AST, target: str,
                        value: Any) -> None:
        narrow_bits = NARROW_REFS.get(target)
        if narrow_bits is None or not isinstance(value, Dtype):
            return
        if value.kind in ("int", "uint") and value.bits > narrow_bits:
            came_from = (f" (derives from narrow `{value.origin}`)"
                         if value.origin else "")
            self.findings.append(Finding(
                path=self.path, line=node.lineno, rule=RULE,
                message=f"declared-narrow `{target}` (int{narrow_bits}) "
                        f"receives a silently widened int{value.bits} "
                        f"value{came_from} — doubles the plane's HBM "
                        "traffic and retraces every consumer",
                hint=f"cast back at the boundary: "
                     f".astype(jnp.int{narrow_bits}) or "
                     ".astype(<ref>.dtype)",
            ))

    def eval_call(self, node, env, args, keywords):
        name = dotted_name(node.func)
        last = name.rsplit(".", 1)[-1]
        # narrow keyword boundary: _replace(mem_timer=...), ctor kwargs
        for kw in node.keywords:
            if kw.arg in NARROW_LEAVES:
                self._check_boundary(kw.value, kw.arg,
                                     keywords.get(kw.arg))
        if isinstance(node.func, ast.Attribute) and (
                node.func.attr == "astype"):
            if node.args:
                target = _literal_dtype(node.args[0])
            else:
                target = _literal_dtype(
                    node.keywords[0].value if node.keywords else None)
            base = self.eval_expr(node.func.value, env)
            if target is not None:
                origin = base.origin if isinstance(base, Dtype) else None
                return Dtype(target.kind, target.bits, origin)
            return None
        if "dtype" in keywords or (last in ("zeros", "ones", "full",
                                            "arange", "empty", "randint",
                                            "asarray")):
            for kw in node.keywords:
                if kw.arg == "dtype":
                    return _literal_dtype(kw.value)
            # positional dtype in arange/zeros is rare here; unknown
            return None
        if last in _PASS_FIRST and args:
            return self._as_dtype(args[0])
        if last in _WIDENING_REDUCTIONS and args:
            first = self._as_dtype(args[0])
            if first is not None and first.kind in ("int", "uint"):
                return Dtype(first.kind, max(first.bits, 32),
                             first.origin)
            return first
        if last == "where" and len(args) == 3:
            return promote(self._as_dtype(args[1]),
                           self._as_dtype(args[2]))
        if last in _PROMOTING and args:
            out = self._as_dtype(args[0])
            for v in args[1:]:
                out = promote(out, self._as_dtype(v))
            return out
        return None

    def on_store_into(self, target, value, node, env):
        # kernel out-ref boundary: o_timer[:] = <wider int>
        if isinstance(target, ast.Subscript) and isinstance(
                target.value, ast.Name):
            self._check_boundary(node, target.value.id, value)


def in_scope(path: str) -> bool:
    """Scope on the ABSOLUTE path, so the CLI (relative paths) and the
    tier-1 gate (absolute paths) can never disagree about which files
    the rule covers. Paths that don't exist on disk are synthetic
    fixture sources — always in scope."""
    import os

    p = os.path.abspath(path)
    if not os.path.exists(p):
        return True  # fixture / bare source blob
    norm = p.replace("\\", "/")
    return "/sim/" in norm or "/ops/" in norm


def check_project(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for fn in project.iter_functions():
        if not in_scope(fn.path):
            continue
        _Analysis(fn, findings).analyze()
    return findings
