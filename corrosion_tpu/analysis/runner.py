"""File walking + checker orchestration for ``corrolint``.

``run_paths`` is the whole engine: walk the given files/directories,
parse each Python file once, run every (selected) checker over the
tree, apply inline suppressions, and return sorted findings. The CLI
(``__main__``) and the tier-1 gate
(``tests/test_analysis.py::test_repo_is_clean``) both call it, so the
lint that blocks CI is byte-identical to the one run by hand.
"""

from __future__ import annotations

import ast
import os
from typing import Callable, Dict, Iterable, Iterator, List, Optional

from corrosion_tpu.analysis import asserts, donation, locks, trace
from corrosion_tpu.analysis.base import (
    Finding,
    apply_suppressions,
    parse_suppressions,
)

#: checker name -> callable(tree, source, path) -> [Finding]
ALL_CHECKERS: Dict[str, Callable] = {
    "donation-safety": donation.check,
    "lock-discipline": locks.check,
    "strippable-assert": asserts.check,
    "trace-hygiene": trace.check,
}

_SKIP_DIRS = {"__pycache__", ".git", ".jax_cache", "node_modules"}


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    """Python files under ``paths``. A path that does not exist raises:
    for a lint GATE, "walked zero files" must never read as "clean" —
    a typo'd path or wrong cwd would otherwise exit 0."""
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        if not os.path.isdir(path):
            raise FileNotFoundError(
                f"lint path {path!r} does not exist (cwd: {os.getcwd()})"
            )
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def check_source(
    source: str,
    path: str = "<string>",
    checkers: Optional[Dict[str, Callable]] = None,
) -> List[Finding]:
    """Run checkers over one source blob (the test-fixture entry
    point). Suppressions are honored; a suppression with no reason is
    itself a finding."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(
            path=path, line=e.lineno or 0, rule="syntax-error",
            message=f"not parseable: {e.msg}",
        )]
    by_line, bad_suppressions = parse_suppressions(source, path)
    findings: List[Finding] = list(bad_suppressions)
    for _, checker in sorted((checkers or ALL_CHECKERS).items()):
        findings.extend(checker(tree, source, path))
    return sorted(apply_suppressions(findings, by_line))


def run_paths(
    paths: Iterable[str],
    checkers: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """All findings over ``paths``, suppressions applied, sorted by
    (path, line)."""
    selected = ALL_CHECKERS
    if checkers is not None:
        unknown = set(checkers) - set(ALL_CHECKERS)
        if unknown:
            raise ValueError(
                f"unknown checkers: {sorted(unknown)} "
                f"(available: {sorted(ALL_CHECKERS)})"
            )
        selected = {k: ALL_CHECKERS[k] for k in checkers}
    findings: List[Finding] = []
    n_files = 0
    for file_path in iter_python_files(paths):
        n_files += 1
        with open(file_path, "r", encoding="utf-8") as f:
            source = f.read()
        findings.extend(check_source(source, file_path, selected))
    if n_files == 0:
        raise FileNotFoundError(
            f"no Python files under {list(paths)!r} — refusing to "
            f"report a clean result for an empty walk"
        )
    return sorted(findings)
