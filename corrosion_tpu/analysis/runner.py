"""File walking + checker orchestration for ``corrolint``.

``run_paths`` is the whole engine: walk the given files/directories,
parse each Python file once, run every (selected) per-file checker
over its tree, build the project call graph, run the (selected)
interprocedural project checkers over it, apply inline suppressions,
de-duplicate, and return sorted findings. The CLI (``__main__``) and
the tier-1 gate (``tests/test_analysis.py::test_repo_is_clean``) both
call it, so the lint that blocks CI is byte-identical to the one run
by hand.

Two checker shapes since v2:

- **per-file** (:data:`ALL_CHECKERS`) — ``(tree, source, path) ->
  [Finding]``, pure AST passes over one file;
- **project** (:data:`PROJECT_CHECKERS`) — ``(Project) -> [Finding]``,
  interprocedural passes over the whole walked set (call graph +
  dataflow). On a partial walk (``--changed``) they still run, over
  just the walked files — facts are derived from the SUBSET's view, so
  cross-file facts whose other half was not walked go missing, and a
  bare name that is only unique within the subset can resolve where
  the full walk would abstain. The full walk is the gate of record;
  ``--changed`` is the fast pre-commit approximation.

The lexical donation pass and the interprocedural ``donation-flow``
pass overlap by construction (the project table is a superset); both
emit identical Finding records for the shared cases and the global
de-dup collapses them.
"""

from __future__ import annotations

import ast
import os
from typing import Callable, Dict, Iterable, Iterator, List, Optional, Tuple

from corrosion_tpu.analysis import (
    asserts,
    collectives,
    cost,
    donation,
    dtypes,
    locks,
    lockorder,
    shapes,
    sharding,
    trace,
)
from corrosion_tpu.analysis.base import Finding, parse_suppressions
from corrosion_tpu.analysis.callgraph import (
    ModuleInfo,
    Project,
    module_name_for,
)

#: per-file checker name -> callable(tree, source, path) -> [Finding]
ALL_CHECKERS: Dict[str, Callable] = {
    "donation-safety": donation.check,
    "lock-discipline": locks.check,
    "strippable-assert": asserts.check,
    "trace-hygiene": trace.check,
}

#: project checker name -> callable(Project) -> [Finding]
PROJECT_CHECKERS: Dict[str, Callable] = {
    "donation-flow": donation.check_project,
    "sharding-contract": sharding.check_project,
    "dtype-flow": dtypes.check_project,
    "lock-order": lockorder.check_project,
    # corrobudget (v3, ISSUE 12): symbolic shape/memory interpreter
    "mem-budget": shapes.check_budget,
    "densify": shapes.check_densify,
    # corrocost (v4, ISSUE 20): cost & collective auditor — the static
    # halves only (AST + symbolic degrees; the trace/compile gates live
    # in tests/test_cost.py and scripts/cost_probe.py, keeping `--lint`
    # jax-free)
    "collective-budget": collectives.check_project,
    "cost-drift": cost.check_project,
}

_SKIP_DIRS = {"__pycache__", ".git", ".jax_cache", "node_modules"}


def iter_python_files(paths: Iterable[str]) -> Iterator[str]:
    """Python files under ``paths``. A path that does not exist raises:
    for a lint GATE, "walked zero files" must never read as "clean" —
    a typo'd path or wrong cwd would otherwise exit 0."""
    for path in paths:
        if os.path.isfile(path):
            if path.endswith(".py"):
                yield path
            continue
        if not os.path.isdir(path):
            raise FileNotFoundError(
                f"lint path {path!r} does not exist (cwd: {os.getcwd()})"
            )
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
            for name in sorted(files):
                if name.endswith(".py"):
                    yield os.path.join(root, name)


def _select(checkers: Optional[Iterable[str]]) -> Tuple[Dict, Dict]:
    """(per-file, project) checker subsets for a ``--checkers`` spec."""
    if checkers is None:
        return ALL_CHECKERS, PROJECT_CHECKERS
    names = list(checkers)
    unknown = set(names) - set(ALL_CHECKERS) - set(PROJECT_CHECKERS)
    if unknown:
        raise ValueError(
            f"unknown checkers: {sorted(unknown)} (available: "
            f"{sorted(ALL_CHECKERS) + sorted(PROJECT_CHECKERS)})"
        )
    return (
        {k: ALL_CHECKERS[k] for k in names if k in ALL_CHECKERS},
        {k: PROJECT_CHECKERS[k] for k in names if k in PROJECT_CHECKERS},
    )


def _lint_sources(
    sources: List[Tuple[str, str]],
    per_file: Dict[str, Callable],
    project_checkers: Dict[str, Callable],
) -> List[Finding]:
    """The shared engine body over parsed (path, source) pairs."""
    findings: List[Finding] = []
    suppressions: Dict[str, Dict[int, set]] = {}
    modules = []
    for path, source in sources:
        by_line, bad = parse_suppressions(source, path)
        suppressions[path] = by_line
        findings.extend(bad)
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            findings.append(Finding(
                path=path, line=e.lineno or 0, rule="syntax-error",
                message=f"not parseable: {e.msg}",
            ))
            continue
        for _, checker in sorted(per_file.items()):
            findings.extend(checker(tree, source, path))
        modules.append(ModuleInfo(
            path=path, name=module_name_for(path), tree=tree,
            source=source, suppressions=by_line, bad_suppressions=bad,
        ))
    if project_checkers and modules:
        project = Project(modules)
        for _, checker in sorted(project_checkers.items()):
            findings.extend(checker(project))
    kept = [
        f for f in findings
        if f.rule not in suppressions.get(f.path, {}).get(f.line, ())
    ]
    return sorted(set(kept))


def check_source(
    source: str,
    path: str = "<string>",
    checkers: Optional[Dict[str, Callable]] = None,
) -> List[Finding]:
    """Run checkers over one source blob (the test-fixture entry
    point). Suppressions are honored; a suppression with no reason is
    itself a finding. ``checkers`` maps names to callables — names in
    :data:`PROJECT_CHECKERS` run as project passes over the one-file
    project."""
    if checkers is None:
        per_file, project_checkers = ALL_CHECKERS, PROJECT_CHECKERS
    else:
        per_file = {k: v for k, v in checkers.items()
                    if k not in PROJECT_CHECKERS}
        project_checkers = {k: v for k, v in checkers.items()
                            if k in PROJECT_CHECKERS}
    return _lint_sources([(path, source)], per_file, project_checkers)


def lint_report(
    paths: Iterable[str],
    checkers: Optional[Iterable[str]] = None,
) -> Tuple[List[Finding], int]:
    """(findings, files walked) over ``paths`` — the machine-readable
    artifact's data source."""
    per_file, project_checkers = _select(checkers)
    sources: List[Tuple[str, str]] = []
    for file_path in iter_python_files(paths):
        with open(file_path, "r", encoding="utf-8") as f:
            sources.append((file_path, f.read()))
    if not sources:
        raise FileNotFoundError(
            f"no Python files under {list(paths)!r} — refusing to "
            f"report a clean result for an empty walk"
        )
    return _lint_sources(sources, per_file, project_checkers), len(sources)


def run_paths(
    paths: Iterable[str],
    checkers: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """All findings over ``paths``, suppressions applied, sorted by
    (path, line)."""
    return lint_report(paths, checkers)[0]
