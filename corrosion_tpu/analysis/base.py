"""Shared checker plumbing: findings, rule catalog, suppressions.

A checker is a callable ``(tree, source, path) -> list[Finding]``. The
runner owns file walking and suppression filtering so every checker
stays a pure AST pass.

Suppression syntax (reason REQUIRED — a suppression that does not say
why is itself a finding, the same contract as the registry's named
assertions)::

    risky_line()  # corrolint: disable=bare-assert -- validated at boot

The comment suppresses matching findings on its own line; on a line of
its own it suppresses the NEXT line (for statements too long to share a
line with a justification).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from typing import Dict, List, Tuple

#: rule id -> one-line description (the CLI's ``--list-rules`` catalog)
RULES: Dict[str, str] = {
    "donation-reuse": (
        "variable read after being passed in donated position to a jit "
        "without re-binding (use-after-donate DeletedBuffer hazard)"
    ),
    "unlocked-mutation": (
        "method of a lock-owning class mutates private shared state "
        "outside `with self.<lock>:`"
    ),
    "blocking-under-lock": (
        "file IO / .result() / device sync / sleep while holding the "
        "instance lock"
    ),
    "bare-assert": (
        "bare `assert` in library code — stripped under `python -O`, the "
        "invariant silently stops being checked"
    ),
    "tracer-branch": (
        "Python `if`/`while` on a traced argument inside a jitted "
        "function (TracerBoolConversionError or a retrace per value)"
    ),
    "import-time-jnp": (
        "jnp/jax.random work at module import time (device work before "
        "backends are configured; leaked tracers when first imported "
        "inside a trace)"
    ),
    "unhashable-static-default": (
        "static jit argument with an unhashable (list/dict/set) default"
    ),
    "suppression-missing-reason": (
        "`# corrolint: disable=...` without a `-- reason` justification"
    ),
    # --- v2 interprocedural rules (call-graph + dataflow engine) ---
    "shard-gather": (
        "node-sharded state host-materialized (device_get/np.asarray/"
        "whole-pytree drain) outside the sharding drain registry — "
        "funnels the HBM working set through one host"
    ),
    "shard-spec-drift": (
        "freshly-built state passed into a sharded entry point without "
        "`shard_state` placement — silently drops the P(\"node\") layout"
    ),
    "dtype-widen": (
        "declared-narrow (int16) state leaf receives a silently "
        "promotion-widened value at a carry/kernel boundary — doubles "
        "HBM traffic and retraces every consumer"
    ),
    "lock-cycle": (
        "non-reentrant lock re-acquired while held, or a >2-lock "
        "acquisition cycle across the call graph (deadlock)"
    ),
    "lock-inversion": (
        "two locks acquired in opposite orders on two code paths "
        "(ABBA deadlock across threads)"
    ),
    # --- v3 corrobudget rules (symbolic shape/memory interpreter) ---
    "mem-budget": (
        "statically-projected state footprint at the declared N=1M "
        "point exceeds its per-complexity-class HBM budget (or a state "
        "leaf's shape is no longer statically priceable)"
    ),
    "densify": (
        "trace-time intermediate whose N-degree exceeds every input's "
        "(an N x N pairwise broadcast: fits at 100k, OOMs at 1M)"
    ),
    # --- v4 corrocost rules (jaxpr/HLO cost & collective auditor) ---
    "collective-budget": (
        "explicit cross-shard collective (lax.psum/all_gather/"
        "with_sharding_constraint/...) in the runtime surface with no "
        "reasoned DECLARED_COLLECTIVE_SITES entry — cross-shard bytes "
        "must be argued into the budget, never smuggled"
    ),
    "cost-drift": (
        "state constructor's symbolic shape degree no longer matches "
        "the degree corrocost's committed cost fits were priced at — "
        "the static roofline and 1M flop projection are stale"
    ),
}


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str
    hint: str = ""

    def render(self) -> str:
        tail = f" (fix: {self.hint})" if self.hint else ""
        return f"{self.path}:{self.line}: {self.rule}: {self.message}{tail}"

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


_SUPPRESS_RE = re.compile(
    r"#\s*corrolint:\s*disable=([a-z0-9_,\- ]+?)\s*(?:--\s*(\S.*))?$"
)


def _comment_tokens(source: str):
    """(line, col, text) for every real COMMENT token. Tokenizing keeps
    directives inside string literals inert — they neither suppress a
    finding nor misfire as a reasonless suppression."""
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.start[1], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return  # unparseable tail: the syntax-error finding covers it


def parse_suppressions(
    source: str, path: str
) -> Tuple[Dict[int, set], List[Finding]]:
    """Map line -> suppressed rule ids, plus findings for suppressions
    that carry no reason. A suppression on a line with no code applies
    to the following line."""
    by_line: Dict[int, set] = {}
    bad: List[Finding] = []
    lines = source.splitlines()
    for lineno, col, text in _comment_tokens(source):
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        if not m.group(2):
            bad.append(Finding(
                path=path, line=lineno, rule="suppression-missing-reason",
                message=f"suppression for {', '.join(sorted(rules))} has "
                        "no reason",
                hint="append `-- <why this is deliberate>`",
            ))
            continue
        target = lineno
        if lines[lineno - 1][:col].strip() == "":
            target = lineno + 1  # standalone comment guards the next line
        by_line.setdefault(target, set()).update(rules)
    return by_line, bad


#: names that resolve to ``jax.jit`` / ``functools.partial`` in this
#: codebase's import conventions — ONE copy, shared by the donation and
#: trace checkers so they can never disagree on what counts as a jit
JIT_NAMES = {"jax.jit", "jit"}
PARTIAL_NAMES = {"functools.partial", "partial"}


def jit_call(node):
    """The ``jax.jit(...)`` Call inside ``jax.jit(...)`` or
    ``partial(jax.jit, ...)``; a bare ``jax.jit`` reference returns a
    synthetic keywordless Call; anything else returns None."""
    if dotted_name(node) in JIT_NAMES:
        return ast.Call(func=node, args=[], keywords=[])
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        if name in JIT_NAMES:
            return node
        if name in PARTIAL_NAMES and node.args and (
                dotted_name(node.args[0]) in JIT_NAMES):
            return node
    return None


def walk_shallow(node):
    """``ast.walk`` that does not descend into nested function/lambda
    bodies — their statements run at call time, not here."""
    stack = [node]
    while stack:
        cur = stack.pop()
        yield cur
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def dotted_name(node) -> str:
    """``a.b.c`` for Name/Attribute chains, '' for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return ""
    parts.append(node.id)
    return ".".join(reversed(parts))
