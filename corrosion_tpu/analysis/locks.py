"""lock-discipline: one lock, one discipline, checked mechanically.

The threaded hosts in this repo (checkpoint writers, supervisors, the
assertion registry, pubsub matchers) follow one convention: a class
creates a single ``threading.Lock``/``RLock`` attribute in ``__init__``
and every mutation of its private (``self._*``) shared state happens
inside ``with self.<lock>:``. This checker enforces the convention for
exactly that shape:

- **unlocked-mutation** — a method assigns / aug-assigns / subscript-
  stores / calls a known mutator (``append``, ``pop``, ``update``, ...)
  on a private instance attribute outside the lock.
- **blocking-under-lock** — file IO (``open``), future ``.result()``,
  ``time.sleep``, ``jax.device_get`` / ``block_until_ready``,
  subprocess or ``os.replace``-style filesystem calls made while the
  lock is held: the reference's LockRegistry watchdog catches these at
  runtime as 10s-held locks; here they are caught at review time.

Scope rules (precision over recall):

- classes owning **more than one** lock are skipped — which lock guards
  which attribute is a design fact AST cannot recover;
- ``__init__`` is exempt (the object is not shared yet);
- a method named ``*_locked`` is treated as called with the lock held
  (the ``_flush_locked`` convention);
- a nested ``def`` resets the held-lock context: a closure defined
  under ``with`` runs later, when the lock is long released;
- ``self._cv = threading.Condition(self.<lock>)`` makes ``self._cv``
  an ALIAS of the lock (a Condition shares the mutex it wraps), so
  ``with self._cv:`` counts as holding it — the admission-controller
  idiom. A Condition wrapping anything else stays out of scope.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from corrosion_tpu.analysis.base import Finding, dotted_name

RULE_MUTATION = "unlocked-mutation"
RULE_BLOCKING = "blocking-under-lock"

_LOCK_CTORS = {"threading.Lock", "threading.RLock", "Lock", "RLock"}
_CONDITION_CTORS = {"threading.Condition", "Condition"}

#: container methods that mutate their receiver
_MUTATORS = {
    "append", "extend", "add", "insert", "remove", "discard", "clear",
    "pop", "popleft", "popitem", "update", "setdefault", "appendleft",
}

#: calls that block (or do IO) and must not run under the instance lock
_BLOCKING_NAMES = {"open", "sleep", "device_get", "block_until_ready"}
_BLOCKING_DOTTED = {
    "time.sleep", "jax.device_get", "jax.block_until_ready",
    "subprocess.run", "subprocess.check_call", "subprocess.check_output",
    "subprocess.Popen", "os.replace", "os.rename", "os.remove",
    "os.unlink", "os.makedirs", "shutil.rmtree", "shutil.copy",
    "shutil.copytree",
}
_BLOCKING_METHODS = {"result"}  # fut.result() — waits on another thread


def _self_attr(node) -> Optional[str]:
    """'x' for ``self.x``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _walk_own_class(cls: ast.ClassDef):
    """Walk a class's own body without descending into nested classes —
    an inner class's lock belongs to ITS instances, and counting it
    here would wrongly flip the outer class to 'multi-lock, skipped'."""
    stack: list = list(cls.body)
    while stack:
        node = stack.pop()
        if isinstance(node, ast.ClassDef):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    attrs: Set[str] = set()
    for node in _walk_own_class(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if dotted_name(node.value.func) in _LOCK_CTORS:
                for tgt in node.targets:
                    name = _self_attr(tgt)
                    if name:
                        attrs.add(name)
    return attrs


def _cv_aliases(cls: ast.ClassDef, lock_attr: str) -> Set[str]:
    """Attrs bound to ``threading.Condition(self.<lock>)``: the
    Condition shares the class's own mutex, so entering it IS entering
    the lock."""
    aliases: Set[str] = set()
    for node in _walk_own_class(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            call = node.value
            if (dotted_name(call.func) in _CONDITION_CTORS
                    and len(call.args) == 1
                    and _self_attr(call.args[0]) == lock_attr):
                for tgt in node.targets:
                    name = _self_attr(tgt)
                    if name:
                        aliases.add(name)
    return aliases


def _is_lock_with(item: ast.withitem, lock_names: Set[str]) -> bool:
    return _self_attr(item.context_expr) in lock_names


class _MethodScan:
    def __init__(self, cls_name: str, method: ast.FunctionDef,
                 lock_attr: str, lock_names: Set[str], path: str,
                 findings: List[Finding]):
        self.cls_name = cls_name
        self.method = method
        self.lock_attr = lock_attr
        self.lock_names = lock_names  # the lock + its Condition aliases
        self.path = path
        self.findings = findings

    def run(self) -> None:
        held = self.method.name.endswith("_locked")
        for stmt in self.method.body:
            self._scan(stmt, held)

    # --- helpers ---------------------------------------------------------
    def _mutated_attrs(self, target) -> List[str]:
        """Private self attrs mutated by an assignment target."""
        if isinstance(target, (ast.Tuple, ast.List)):
            return [a for t in target.elts for a in self._mutated_attrs(t)]
        name = _self_attr(target)
        if name is None and isinstance(target, ast.Subscript):
            name = _self_attr(target.value)
        if name and name.startswith("_") and name not in self.lock_names:
            return [name]
        return []

    def _flag_mutation(self, node, attr: str) -> None:
        self.findings.append(Finding(
            path=self.path, line=node.lineno, rule=RULE_MUTATION,
            message=f"{self.cls_name}.{self.method.name} mutates "
                    f"self.{attr} outside `with self.{self.lock_attr}:`",
            hint=f"move the mutation under the lock, or rename the "
                 f"method `*_locked` if callers hold self.{self.lock_attr}",
        ))

    def _flag_blocking(self, node, what: str) -> None:
        self.findings.append(Finding(
            path=self.path, line=node.lineno, rule=RULE_BLOCKING,
            message=f"{self.cls_name}.{self.method.name} calls {what} "
                    f"while holding self.{self.lock_attr}",
            hint="stage the data under the lock, do the blocking call "
                 "after releasing it",
        ))

    def _check_call(self, node: ast.Call, held: bool) -> None:
        if not held:
            return
        name = dotted_name(node.func)
        if name in _BLOCKING_DOTTED or name in _BLOCKING_NAMES:
            self._flag_blocking(node, f"{name}()")
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr in _BLOCKING_METHODS):
            self._flag_blocking(node, f".{node.func.attr}()")

    def _mutator_call_attr(self, node: ast.Call) -> Optional[str]:
        """'x' for ``self._x.append(...)``-style mutator calls."""
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATORS):
            name = _self_attr(node.func.value)
            if (name and name.startswith("_")
                    and name not in self.lock_names):
                return name
        return None

    # --- the walk --------------------------------------------------------
    def _scan_expr(self, node, held: bool) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._check_call(sub, held)
                if not held:
                    attr = self._mutator_call_attr(sub)
                    if attr:
                        self._flag_mutation(sub, attr)

    def _scan(self, stmt, held: bool) -> None:
        if isinstance(stmt, ast.ClassDef):
            return  # a nested class's `self` is not this instance
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a closure runs later; the lock is not held then
            for inner in stmt.body:
                self._scan(inner, False)
            return
        if isinstance(stmt, ast.With):
            inner_held = held or any(
                _is_lock_with(it, self.lock_names) for it in stmt.items
            )
            for it in stmt.items:
                self._scan_expr(it.context_expr, held)
            for inner in stmt.body:
                self._scan(inner, inner_held)
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            if not held:
                for tgt in targets:
                    for attr in self._mutated_attrs(tgt):
                        self._flag_mutation(stmt, attr)
            if stmt.value is not None:
                self._scan_expr(stmt.value, held)
            return
        if isinstance(stmt, ast.Delete) and not held:
            for tgt in stmt.targets:
                for attr in self._mutated_attrs(tgt):
                    self._flag_mutation(stmt, attr)
            return
        # compound statements: recurse into bodies, scan embedded exprs
        for field in ("body", "orelse", "finalbody"):
            for inner in getattr(stmt, field, []):
                self._scan(inner, held)
        for handler in getattr(stmt, "handlers", []):
            for inner in handler.body:
                self._scan(inner, held)
        for attr_name in ("test", "iter", "value", "exc"):
            sub = getattr(stmt, attr_name, None)
            if sub is not None and isinstance(sub, ast.AST):
                self._scan_expr(sub, held)


def check(tree: ast.AST, source: str, path: str) -> List[Finding]:
    findings: List[Finding] = []
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _lock_attrs(cls)
        if len(locks) != 1:
            continue  # no lock, or multi-lock: ownership is not inferable
        lock_attr = locks.pop()
        lock_names = {lock_attr} | _cv_aliases(cls, lock_attr)
        for method in cls.body:
            if not isinstance(method, ast.FunctionDef):
                continue
            if method.name == "__init__":
                continue  # the object is not shared during construction
            _MethodScan(cls.name, method, lock_attr, lock_names, path,
                        findings).run()
    return findings
