"""Mesh parallelism for the cluster simulator.

The reference scales by adding gossiping processes connected over
QUIC/NCCL-less sockets (SURVEY §2.3 "Distributed comm backend"); the
TPU-native analog shards the *simulated nodes* axis across a
``jax.sharding.Mesh`` and lets XLA insert the collectives (all_gather /
reduce_scatter / ppermute over ICI) implied by cross-node message
traffic. See ``mesh.py``.
"""

from corrosion_tpu.parallel.mesh import (  # noqa: F401
    buffers_donated,
    make_mesh,
    make_multihost_mesh,
    node_sharding,
    shard_state,
    sharded_step,
    sharded_run,
    sharded_scale_run,
    sharded_scale_run_carry,
)
