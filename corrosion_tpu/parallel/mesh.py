"""Shard the simulator over a device mesh along the node axis.

Design (SURVEY §5 "long-context"): the simulator's "long axis" is N
simulated nodes. Every piece of ``SimState`` is a struct-of-arrays with
leading dimension N, so the whole state shards with one
``NamedSharding(mesh, P("node"))`` annotation and the fused round step
runs under ``jit`` unchanged — XLA turns the cross-node traffic
(piggyback scatters, fanout gathers, peer store reads) into ICI
collectives. This is the pjit recipe: pick a mesh, annotate shardings,
let XLA insert collectives, profile, iterate.

The reference reaches the same scale with one OS process per node and
QUIC between them (``Transport``, ``crates/corro-agent/src/transport.rs``);
here a "process" is a row of the state arrays and the transport is the
mesh interconnect.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from corrosion_tpu.sim.config import SimConfig
from corrosion_tpu.sim.step import RoundInput, SimState, sim_step
from corrosion_tpu.sim.transport import NetModel

NODE_AXIS = "node"


DCN_AXIS = "dcn"


def make_mesh(devices=None) -> Mesh:
    """A 1-D mesh over the node axis; all devices simulate node shards."""
    if devices is None:
        devices = jax.devices()
    import numpy as np

    return Mesh(np.asarray(devices), (NODE_AXIS,))


def make_multihost_mesh(n_hosts: int, devices=None) -> Mesh:
    """A 2-D (dcn, node) mesh for multi-host runs: the outer axis spans
    hosts (traffic crosses the data-center network), the inner axis
    spans each host's chips (traffic rides ICI). The node dimension
    shards over BOTH axes jointly — ``P((DCN_AXIS, NODE_AXIS))`` — so
    contiguous node blocks stay host-local and XLA's collectives
    hierarchy keeps the dense intra-block exchange on ICI, touching DCN
    only for the cross-block slices. This is the replacement for the
    reference's NCCL/MPI-style story: its gossip topology spans hosts
    over QUIC; ours spans them over the mesh's outer axis.

    On a real pod slice pass ``jax.devices()`` (ordered host-major by
    JAX); under ``xla_force_host_platform_device_count`` any factor of
    the device count works as a virtual host count.
    """
    if devices is None:
        devices = jax.devices()
    import numpy as np

    devices = np.asarray(devices)
    # a real error, not a bare assert: ``python -O`` strips asserts and a
    # silently mis-shaped mesh would crash far away in device_put
    if n_hosts <= 0 or len(devices) % n_hosts != 0:
        raise ValueError(
            f"{len(devices)} devices do not split over {n_hosts} hosts"
        )
    return Mesh(
        devices.reshape(n_hosts, -1), (DCN_AXIS, NODE_AXIS)
    )


def node_sharding(mesh: Mesh, n_nodes: int):
    """Pytree-of-shardings: shard leading axis when it is the node axis.

    Per-node arrays ([N], [N, ...]) shard over ``node``; scalars and
    small broadcast tables replicate. Works for ``SimState``,
    ``NetModel``, ``RoundInput`` and stacked round inputs ([rounds, N,
    ...], where axis 1 is the node axis).
    """

    # on a multi-host (dcn, node) mesh the node dimension shards over
    # both axes jointly: host-local blocks ride ICI, cross-host DCN
    axis = (
        (DCN_AXIS, NODE_AXIS) if DCN_AXIS in mesh.axis_names else NODE_AXIS
    )

    def spec(x) -> NamedSharding:
        shape = jnp.shape(x)
        if len(shape) >= 1 and shape[0] == n_nodes:
            return NamedSharding(mesh, P(axis, *([None] * (len(shape) - 1))))
        if len(shape) >= 2 and shape[1] == n_nodes:  # stacked rounds
            return NamedSharding(mesh, P(None, axis, *([None] * (len(shape) - 2))))
        return NamedSharding(mesh, P())

    return spec


def shard_state(mesh: Mesh, n_nodes: int, tree: Any) -> Any:
    """Device-put a state pytree with node-axis sharding."""
    spec = node_sharding(mesh, n_nodes)
    return jax.tree.map(lambda x: jax.device_put(x, spec(x)), tree)


def buffers_donated(tree: Any) -> bool:
    """True when any leaf buffer of ``tree`` was consumed by a donated
    dispatch (jit reused it for an output). The one shared probe for
    "did donation actually engage": the bench records it per
    measurement, the soak runner uses it to detect a consumed carry
    before a retry, and the multichip dryrun asserts it."""
    return any(
        getattr(leaf, "is_deleted", lambda: False)()
        for leaf in jax.tree.leaves(tree)
    )


@functools.partial(jax.jit, static_argnums=(0,))
def _step(cfg: SimConfig, st: SimState, net: NetModel, key, inp: RoundInput):
    return sim_step(cfg, st, net, key, inp)


def sharded_step(cfg: SimConfig, mesh: Mesh, st, net, key, inp):
    """One fused round with node-sharded state.

    The state/net/inputs must already be placed via ``shard_state``;
    jit infers shardings from the arguments (no mesh context needed) and
    XLA propagates them through the scatters/gathers, inserting
    collectives where messages cross shard boundaries.
    """
    del mesh  # sharding travels on the arguments
    return _step(cfg, st, net, key, inp)


@functools.partial(jax.jit, static_argnums=(0,))
def _run(cfg: SimConfig, st: SimState, net: NetModel, key, inputs: RoundInput):
    def body(carry, inp):
        st, key = carry
        key, sub = jax.random.split(key)
        st, info = sim_step(cfg, st, net, sub, inp)
        return (st, key), info

    (st, _), infos = jax.lax.scan(body, (st, key), inputs)
    return st, infos


def sharded_run(cfg: SimConfig, mesh: Mesh, st, net, key, inputs):
    """``lax.scan`` over stacked rounds with node-sharded state — the
    whole simulation compiles to one XLA program spanning the mesh."""
    del mesh  # sharding travels on the arguments
    return _run(cfg, st, net, key, inputs)


# --- flagship (scale) path -------------------------------------------------
#
# ``ScaleSimState`` / ``ScaleRoundInput`` / ``NetModel`` are all
# struct-of-arrays with a leading node axis, so the same ``shard_state``
# placement covers them; these are the scan entry points for the
# 100k-capable simulator with the carry DONATED — at 100k nodes the scan
# carry is the HBM working set, and an un-donated dispatch would hold
# two copies of it across every call boundary (bench rep, soak segment).
#
# Changing donate_argnums here REQUIRES updating
# ``analysis/donation.py::KNOWN_DONATING`` — enforced by
# ``tests/test_analysis_v2.py::test_known_donating_matches_runtime``,
# which traces these jits and compares the donated leaf set against the
# registry. These wrappers are also the sharding-contract checker's
# taint sources (``analysis/sharding.py``): their state args must come
# placed through ``shard_state`` and their outputs must never be
# host-materialized outside the drain registry.


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
def _scale_run(cfg, st, net, key, inputs):
    from corrosion_tpu.sim.scale_step import scale_run_rounds

    return scale_run_rounds(cfg, st, net, key, inputs)


def sharded_scale_run(cfg, mesh, st, net, key, inputs):
    """Flagship scan (``scale_run_rounds``) with node-sharded, DONATED
    state: the carry-out reuses the carry-in's buffers, so stepping the
    returned state in a loop never holds two device copies. The caller's
    ``st`` is consumed — keep a host copy if it must survive."""
    del mesh  # sharding travels on the arguments
    return _scale_run(cfg, st, net, key, inputs)


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(1, 2))
def _scale_run_carry(cfg, st, key, net, inputs):
    from corrosion_tpu.sim.scale_step import scale_run_rounds_carry

    return scale_run_rounds_carry(cfg, st, net, key, inputs)


def sharded_scale_run_carry(cfg, mesh, st, net, key, inputs):
    """Segment entry point (``scale_run_rounds_carry``) with the FULL
    scan carry (state + PRNG key) donated — chaining the returned
    ``(state, key)`` back in reproduces the straight scan bit for bit
    with zero duplicate carry allocations at segment boundaries."""
    del mesh  # sharding travels on the arguments
    return _scale_run_carry(cfg, st, key, net, inputs)
