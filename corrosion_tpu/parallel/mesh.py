"""Shard the simulator over a device mesh along the node axis.

Design (SURVEY §5 "long-context"): the simulator's "long axis" is N
simulated nodes. Every piece of ``SimState`` is a struct-of-arrays with
leading dimension N, so the whole state shards with one
``NamedSharding(mesh, P("node"))`` annotation and the fused round step
runs under ``jit`` unchanged — XLA turns the cross-node traffic
(piggyback scatters, fanout gathers, peer store reads) into ICI
collectives. This is the pjit recipe: pick a mesh, annotate shardings,
let XLA insert collectives, profile, iterate.

The reference reaches the same scale with one OS process per node and
QUIC between them (``Transport``, ``crates/corro-agent/src/transport.rs``);
here a "process" is a row of the state arrays and the transport is the
mesh interconnect.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from corrosion_tpu.sim.config import SimConfig
from corrosion_tpu.sim.step import RoundInput, SimState, sim_step
from corrosion_tpu.sim.transport import NetModel

NODE_AXIS = "node"


DCN_AXIS = "dcn"


def make_mesh(devices=None) -> Mesh:
    """A 1-D mesh over the node axis; all devices simulate node shards."""
    if devices is None:
        devices = jax.devices()
    import numpy as np

    return Mesh(np.asarray(devices), (NODE_AXIS,))


def make_multihost_mesh(n_hosts: int, devices=None) -> Mesh:
    """A 2-D (dcn, node) mesh for multi-host runs: the outer axis spans
    hosts (traffic crosses the data-center network), the inner axis
    spans each host's chips (traffic rides ICI). The node dimension
    shards over BOTH axes jointly — ``P((DCN_AXIS, NODE_AXIS))`` — so
    contiguous node blocks stay host-local and XLA's collectives
    hierarchy keeps the dense intra-block exchange on ICI, touching DCN
    only for the cross-block slices. This is the replacement for the
    reference's NCCL/MPI-style story: its gossip topology spans hosts
    over QUIC; ours spans them over the mesh's outer axis.

    On a real pod slice pass ``jax.devices()`` (ordered host-major by
    JAX); under ``xla_force_host_platform_device_count`` any factor of
    the device count works as a virtual host count.
    """
    if devices is None:
        devices = jax.devices()
    import numpy as np

    devices = np.asarray(devices)
    # a real error, not a bare assert: ``python -O`` strips asserts and a
    # silently mis-shaped mesh would crash far away in device_put
    if n_hosts <= 0 or len(devices) % n_hosts != 0:
        raise ValueError(
            f"{len(devices)} devices do not split over {n_hosts} hosts"
        )
    return Mesh(
        devices.reshape(n_hosts, -1), (DCN_AXIS, NODE_AXIS)
    )


def node_sharding(mesh: Mesh, n_nodes: int):
    """Pytree-of-shardings: shard leading axis when it is the node axis.

    Per-node arrays ([N], [N, ...]) shard over ``node``; scalars and
    small broadcast tables replicate. Works for ``SimState``,
    ``NetModel``, ``RoundInput`` and stacked round inputs ([rounds, N,
    ...], where axis 1 is the node axis).
    """

    # on a multi-host (dcn, node) mesh the node dimension shards over
    # both axes jointly: host-local blocks ride ICI, cross-host DCN
    axis = (
        (DCN_AXIS, NODE_AXIS) if DCN_AXIS in mesh.axis_names else NODE_AXIS
    )

    def spec(x) -> NamedSharding:
        shape = jnp.shape(x)
        if len(shape) >= 1 and shape[0] == n_nodes:
            return NamedSharding(mesh, P(axis, *([None] * (len(shape) - 1))))
        if len(shape) >= 2 and shape[1] == n_nodes:  # stacked rounds
            return NamedSharding(mesh, P(None, axis, *([None] * (len(shape) - 2))))
        return NamedSharding(mesh, P())

    return spec


def shard_state(mesh: Mesh, n_nodes: int, tree: Any) -> Any:
    """Device-put a state pytree with node-axis sharding."""
    spec = node_sharding(mesh, n_nodes)
    return jax.tree.map(lambda x: jax.device_put(x, spec(x)), tree)


def buffers_donated(tree: Any) -> bool:
    """True when any leaf buffer of ``tree`` was consumed by a donated
    dispatch (jit reused it for an output). The one shared probe for
    "did donation actually engage": the bench records it per
    measurement, the soak runner uses it to detect a consumed carry
    before a retry, and the multichip dryrun asserts it."""
    return any(
        getattr(leaf, "is_deleted", lambda: False)()
        for leaf in jax.tree.leaves(tree)
    )


@functools.partial(jax.jit, static_argnums=(0,))
def _step(cfg: SimConfig, st: SimState, net: NetModel, key, inp: RoundInput):
    return sim_step(cfg, st, net, key, inp)


def sharded_step(cfg: SimConfig, mesh: Mesh, st, net, key, inp):
    """One fused round with node-sharded state.

    The state/net/inputs must already be placed via ``shard_state``;
    jit infers shardings from the arguments (no mesh context needed) and
    XLA propagates them through the scatters/gathers, inserting
    collectives where messages cross shard boundaries.
    """
    del mesh  # sharding travels on the arguments
    return _step(cfg, st, net, key, inp)


@functools.partial(jax.jit, static_argnums=(0,))
def _run(cfg: SimConfig, st: SimState, net: NetModel, key, inputs: RoundInput):
    def body(carry, inp):
        st, key = carry
        key, sub = jax.random.split(key)
        st, info = sim_step(cfg, st, net, sub, inp)
        return (st, key), info

    (st, _), infos = jax.lax.scan(body, (st, key), inputs)
    return st, infos


def sharded_run(cfg: SimConfig, mesh: Mesh, st, net, key, inputs):
    """``lax.scan`` over stacked rounds with node-sharded state — the
    whole simulation compiles to one XLA program spanning the mesh."""
    del mesh  # sharding travels on the arguments
    return _run(cfg, st, net, key, inputs)


# --- flagship (scale) path -------------------------------------------------
#
# ``ScaleSimState`` / ``ScaleRoundInput`` / ``NetModel`` are all
# struct-of-arrays with a leading node axis, so the same ``shard_state``
# placement covers them; these are the scan entry points for the
# 100k-capable simulator with the carry DONATED — at 100k nodes the scan
# carry is the HBM working set, and an un-donated dispatch would hold
# two copies of it across every call boundary (bench rep, soak segment).
#
# Changing donate_argnums here REQUIRES updating
# ``analysis/donation.py::KNOWN_DONATING`` — enforced by
# ``tests/test_analysis_v2.py::test_known_donating_matches_runtime``,
# which traces these jits and compares the donated leaf set against the
# registry. These wrappers are also the sharding-contract checker's
# taint sources (``analysis/sharding.py``): their state args must come
# placed through ``shard_state`` and their outputs must never be
# host-materialized outside the drain registry. Under ``cfg.fused``
# the scanned step dispatches the pallas megakernels INSIDE these
# donated programs — the kernels' donated-carry/narrow-dtype contract
# lives at ``ops/megakernel.ingest_changes_fused`` (every in-ref
# consumed within the dispatch, int16 planes re-narrowed at the
# out-ref store), and the wrappers hoist the eager fused probes
# (``megakernel.prime_fused``) so path selection never runs a probe
# thread from inside a traced/sharded dispatch.
#
# The quiet round variant (ISSUE 19) needs no wiring here: the step is
# chosen inside ``scale_run_rounds_carry`` from ``cfg.quiet`` (a static
# argnum), and its gating ``lax.cond`` predicate is a scalar reduction
# over the node-sharded planes — under SPMD the reduction all-gathers
# to a REPLICATED scalar, so every device takes the same branch and the
# cheap round skips work on all shards at once. The same donation
# contract holds on the pass-through branch: the cond returns the
# donated carry buffers unchanged.


def _prime_fused(cfg) -> None:
    from corrosion_tpu.ops import megakernel

    megakernel.prime_fused(cfg)


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(1,))
def _scale_run(cfg, st, net, key, inputs):
    from corrosion_tpu.sim.scale_step import scale_run_rounds

    return scale_run_rounds(cfg, st, net, key, inputs)


def sharded_scale_run(cfg, mesh, st, net, key, inputs):
    """Flagship scan (``scale_run_rounds``) with node-sharded, DONATED
    state: the carry-out reuses the carry-in's buffers, so stepping the
    returned state in a loop never holds two device copies. The caller's
    ``st`` is consumed — keep a host copy if it must survive."""
    del mesh  # sharding travels on the arguments
    _prime_fused(cfg)  # eager probes BEFORE the trace, never inside it
    return _scale_run(cfg, st, net, key, inputs)


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(1, 2))
def _scale_run_carry(cfg, st, key, net, inputs):
    from corrosion_tpu.sim.scale_step import scale_run_rounds_carry

    return scale_run_rounds_carry(cfg, st, net, key, inputs)


def sharded_scale_run_carry(cfg, mesh, st, net, key, inputs):
    """Segment entry point (``scale_run_rounds_carry``) with the FULL
    scan carry (state + PRNG key) donated — chaining the returned
    ``(state, key)`` back in reproduces the straight scan bit for bit
    with zero duplicate carry allocations at segment boundaries."""
    del mesh  # sharding travels on the arguments
    _prime_fused(cfg)  # eager probes BEFORE the trace, never inside it
    return _scale_run_carry(cfg, st, key, net, inputs)


#: corrocost's audit surface (ISSUE 20): public sharded entry name ->
#: the underlying donated jit it dispatches. ``analysis/collectives.py``
#: lowers EXACTLY these objects (static config, donation intact) to
#: extract the GSPMD collective manifests it pins — auditing a copy of
#: the function would let the real dispatch drift unpriced. Adding a
#: sharded entry point means registering it here; the coverage gate in
#: ``tests/test_cost.py`` pins this dict against the audited set.
SHARDED_ENTRY_POINTS = {
    "sharded_scale_run": _scale_run,
    "sharded_scale_run_carry": _scale_run_carry,
}


# --- per-shard host drain + elastic re-placement ---------------------------
#
# The checkpoint pipeline's device<->host boundary (docs/checkpoints.md).
# A mesh-sharded carry must NEVER funnel through a replicated host view:
# each device's addressable shard drains its own slice
# (``host_shard_copy``), the manifest records where every slice lives
# (``HostLeafShards``), and restore re-places the recorded slices
# against whatever mesh the resuming process has (``elastic_sharding``)
# — 8 chips, 4 chips, a 2-D (dcn, node) fold, or a single device.


def _joint_node_axis(mesh: Mesh):
    """The axis (or axis tuple) ``node_sharding`` shards the node
    dimension over on this mesh."""
    return (
        (DCN_AXIS, NODE_AXIS) if DCN_AXIS in mesh.axis_names else NODE_AXIS
    )


@dataclasses.dataclass(frozen=True)
class HostLeafShards:
    """One leaf of a carry pytree, drained per device shard.

    ``parts`` holds OWNED numpy slices ``(start, array)`` ordered by
    their start index along ``dim`` (``dim is None`` = the leaf was
    unsharded/replicated and ``parts`` is one full copy). ``axes`` is
    the JSON-able record of the mesh axes the sharded dim rode (for the
    checkpoint manifest); ``sharding`` keeps the LIVE sharding object so
    a same-process re-upload (donated-retry, abort handback) can put the
    slices back exactly where they came from. A plain class, not a
    NamedTuple, so ``jax.tree`` treats it as a LEAF — a tree.map over a
    drained carry must not recurse into the slice bookkeeping."""

    shape: Tuple[int, ...]
    dtype: Any
    dim: Optional[int]
    parts: Tuple[Tuple[int, Any], ...]
    axes: Optional[Tuple[str, ...]] = None
    sharding: Any = None

    def nbytes(self) -> int:
        return sum(int(a.nbytes) for _start, a in self.parts)


def _leaf_shard_layout(leaf):
    """-> (dim, shards): the single dimension ``leaf``'s addressable
    shards slice it along, or ``(None, None)`` when the leaf is
    unsharded / fully replicated / not decomposable along one axis
    (those drain as one whole copy)."""
    shards = getattr(leaf, "addressable_shards", None)
    if not shards or len(shards) == 1:
        return None, None
    sliced_dims = set()
    for s in shards:
        for d, (sl, n) in enumerate(zip(s.index, leaf.shape)):
            start, stop = sl.start or 0, n if sl.stop is None else sl.stop
            if (start, stop) != (0, n):
                sliced_dims.add(d)
    if len(sliced_dims) != 1:
        return None, None
    return sliced_dims.pop(), shards


def _spec_axes(leaf, dim: Optional[int]) -> Optional[Tuple[str, ...]]:
    """JSON-able mesh-axis names the sharded dim rides (manifest record)."""
    sharding = getattr(leaf, "sharding", None)
    spec = getattr(sharding, "spec", None)
    if dim is None or spec is None or dim >= len(spec):
        return None
    entry = spec[dim]
    if entry is None:
        return None
    return tuple(entry) if isinstance(entry, tuple) else (entry,)


def host_shard_copy(tree: Any) -> Any:
    """Per-shard host drain of a (possibly mesh-sharded) pytree.

    Every addressable shard's D2H transfer is enqueued asynchronously
    first (on TPU the per-device DMAs run in parallel), then each slice
    materializes as an OWNED numpy copy — ``np.array``, never a view:
    the next segment's dispatch donates the device buffers, and a view
    of a donated buffer would read freed memory. No shard is ever
    gathered into a replicated whole-tree intermediate, so the host
    cost is per-shard state, not total state."""
    leaves, treedef = jax.tree.flatten(tree)
    layouts = [_leaf_shard_layout(leaf) for leaf in leaves]
    for leaf, (dim, shards) in zip(leaves, layouts):
        if dim is None:
            copy_async = getattr(leaf, "copy_to_host_async", None)
            if copy_async is not None:
                copy_async()
        else:
            for s in shards:
                s.data.copy_to_host_async()
    out = []
    for leaf, (dim, shards) in zip(leaves, layouts):
        if dim is None:
            parts = ((0, np.array(leaf)),)
        else:
            by_start = {}
            for s in shards:  # replicas of a window drain once
                start = s.index[dim].start or 0
                if start not in by_start:
                    by_start[start] = np.array(s.data)
            parts = tuple(sorted(by_start.items()))
        out.append(HostLeafShards(
            shape=tuple(np.shape(leaf)),
            dtype=parts[0][1].dtype,
            dim=dim,
            parts=parts,
            axes=_spec_axes(leaf, dim),
            sharding=getattr(leaf, "sharding", None),
        ))
    return jax.tree.unflatten(treedef, out)


def assemble_shards(hs: HostLeafShards):
    """One leaf's slices -> a full host array (restore / re-upload)."""
    if hs.dim is None:
        return hs.parts[0][1]
    return np.concatenate([a for _start, a in hs.parts], axis=hs.dim)


def device_put_shards(tree: Any) -> Any:
    """Re-upload a ``host_shard_copy`` tree to its ORIGINAL placement —
    the donated-retry / abort-handback path: a consumed carry comes back
    bitwise-identical, on the same devices with the same specs.

    The upload MUST be an owned device copy (``jnp.array``, copy
    semantics — never ``asarray``/bare ``device_put``): the CPU backend
    zero-copy-adopts 64-byte-aligned numpy buffers, and the re-uploaded
    carry goes straight back into a DONATED dispatch, which would then
    free numpy-owned memory (observed as glibc heap corruption)."""

    def put(hs: HostLeafShards):
        owned = jnp.array(assemble_shards(hs))
        if hs.sharding is not None:
            return jax.device_put(owned, hs.sharding)
        return owned

    return jax.tree.map(put, tree)


def drained_mesh_meta(tree: Any) -> Optional[dict]:
    """The saving mesh, JSON-ably, from a drained carry (or None when
    nothing was mesh-placed): recorded in the v3 manifest so restore can
    report what it reshards FROM."""
    for hs in jax.tree.leaves(tree):
        mesh = getattr(getattr(hs, "sharding", None), "mesh", None)
        if mesh is not None and getattr(mesh, "axis_names", None):
            return {
                "axis_names": list(mesh.axis_names),
                "shape": [int(s) for s in mesh.devices.shape],
            }
    return None


def elastic_sharding(mesh: Mesh, n_nodes: int, arr,
                     dim: Optional[int] = None) -> NamedSharding:
    """Target sharding for one RESTORED leaf on the CURRENT mesh.

    A leaf whose manifest records a sharded dim re-maps that dim onto
    this mesh's joint node axis — the recorded axis names need not
    exist here, which is exactly what makes restore mesh-shape-agnostic
    (8→4 chips, 1-D↔2-D ``(dcn, node)``). Leaves with no recorded spec
    (v2 checkpoints, single-device saves) fall back to the
    ``node_sharding`` placement rule."""
    if dim is None:
        return node_sharding(mesh, n_nodes)(arr)
    spec = [None] * np.ndim(arr)
    spec[dim] = _joint_node_axis(mesh)
    return NamedSharding(mesh, P(*spec))
