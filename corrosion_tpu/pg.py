"""PostgreSQL wire-protocol (v3) server.

Mirrors ``crates/corro-pg`` (``corro-pg/src/lib.rs``, ~4k LoC): optional
``api.pg`` listeners speak the PostgreSQL frontend/backend protocol —
startup (incl. SSLRequest refusal), simple query, and the extended
protocol (Parse/Bind/Describe/Execute/Sync/Close with prepared
statements + portals) — translating PG SQL onto the local store, so any
PG client can read and write the cluster. Writes ride the same statement
path as the HTTP API (the reference routes them through
``insert_local_changes``/``broadcast_changes``); reads observe one
node's replica.

Values travel in text format by default; portals bound with binary
result-format codes get PG binary encodings for the supported OIDs
(int8/float8/bytea/text — the declared column oid drives the wire
bytes). ``BEGIN``/``COMMIT``/``ROLLBACK`` are REAL buffered
transactions since round 5: statements between BEGIN and COMMIT plan
eagerly against a shared overlay (exact row counts, read-your-writes
for later statements in the block) and stage into ONE round-loop
transaction at COMMIT; an error aborts the block (SQLSTATE 25P02 until
COMMIT/ROLLBACK, COMMIT of an aborted block reports ROLLBACK), and
ReadyForQuery carries the true I/T/E status. Reads inside an open block
observe the pre-transaction replica (the eventually-consistent read
model). ``pg_catalog`` / ``information_schema`` introspection is
answered from the live schema for the common shapes
(``pg_class``/``pg_attribute``/``pg_type``/``pg_namespace``/
``pg_database``, ``information_schema.{tables,columns}`` — the
reference fakes these with vtabs, ``src/vtab/pg_*.rs``); unrecognized
catalog queries degrade to empty result sets.
"""

from __future__ import annotations

import re
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from corrosion_tpu.db.database import SqlError
from corrosion_tpu.db.schema import SchemaError
from corrosion_tpu.utils.lifecycle import DrainingConnMixin
from corrosion_tpu.utils.tracing import logger

PROTO_V3 = 196608
SSL_REQUEST = 80877103
CANCEL_REQUEST = 80877102

# minimal OID map (values always travel in text format)
OID_INT2 = 21
OID_INT4 = 23
OID_INT8 = 20
OID_INTS = (OID_INT2, OID_INT4, OID_INT8)
OID_FLOAT8 = 701
OID_TEXT = 25
OID_BYTEA = 17

# SQLSTATE codes (corro-pg ships a full table, sql_state.rs; this maps
# the error classes this engine actually raises)
SQLSTATE_SYNTAX = "42601"
SQLSTATE_UNDEFINED_TABLE = "42P01"
SQLSTATE_UNDEFINED_COLUMN = "42703"
SQLSTATE_AMBIGUOUS_COLUMN = "42702"
SQLSTATE_NOT_NULL = "23502"
SQLSTATE_INVALID_TEXT = "22P02"
SQLSTATE_FEATURE_UNSUPPORTED = "0A000"
SQLSTATE_PROGRAM_LIMIT = "54000"
SQLSTATE_INTERNAL = "XX000"
SQLSTATE_IN_FAILED_TX = "25P02"
SQLSTATE_TOO_MANY_CONNECTIONS = "53300"  # corroguard admission shed


def _sqlstate_for(exc: Exception) -> str:
    """Map an engine error to the PG SQLSTATE a real server would send
    (``corro-pg/src/sql_state.rs`` ships the full table; this covers
    the classes this engine raises)."""
    msg = str(exc).lower()
    if "no such table" in msg:
        return SQLSTATE_UNDEFINED_TABLE
    if "no such column" in msg or "unknown column" in msg:
        return SQLSTATE_UNDEFINED_COLUMN
    if "ambiguous column" in msg:
        return SQLSTATE_AMBIGUOUS_COLUMN
    if "not null violation" in msg or "cannot be null" in msg:
        return SQLSTATE_NOT_NULL
    if "unsupported literal" in msg:
        return SQLSTATE_INVALID_TEXT
    if "not supported" in msg or "do not support" in msg:
        return SQLSTATE_FEATURE_UNSUPPORTED
    if ("capacity exhausted" in msg or "exceeded int32 id space" in msg
            or ("recursive cte" in msg and "exceeded" in msg)):
        return SQLSTATE_PROGRAM_LIMIT
    return SQLSTATE_SYNTAX


def _col_oid(sql_type: str) -> int:
    return {
        "INTEGER": OID_INT8,
        "REAL": OID_FLOAT8,
        "BLOB": OID_BYTEA,
    }.get(sql_type, OID_TEXT)


# --- pg_catalog virtual tables (vtab analogs, src/vtab/pg_*.rs) ---------
# stable synthetic OIDs: namespaces ship PG's well-known values; relation
# oids are 16384 + table index in schema declaration order
_NS_CATALOG, _NS_PUBLIC = 11, 2200
_FIRST_REL_OID = 16384
_PG_TYPES = [
    # (oid, typname, typlen)
    (16, "bool", 1), (17, "bytea", -1), (20, "int8", 8), (21, "int2", 2),
    (23, "int4", 4), (25, "text", -1), (701, "float8", 8),
    (1043, "varchar", -1),
]


def _catalog_rows(db, table: str) -> List[Dict[str, Any]]:
    """Rows of one catalog vtab, generated from the live schema."""
    tables = list(db.schema.tables.values())
    rel_oid = {t.name: _FIRST_REL_OID + i for i, t in enumerate(tables)}
    if table == "pg_namespace":
        return [
            {"oid": _NS_CATALOG, "nspname": "pg_catalog"},
            {"oid": _NS_PUBLIC, "nspname": "public"},
        ]
    if table == "pg_database":
        return [{"oid": 1, "datname": "corrosion"}]
    if table == "pg_type":
        return [
            {"oid": o, "typname": n, "typlen": ln,
             "typnamespace": _NS_CATALOG, "typtype": "b"}
            for o, n, ln in _PG_TYPES
        ]
    if table == "pg_class":
        return [
            {"oid": rel_oid[t.name], "relname": t.name,
             "relnamespace": _NS_PUBLIC, "relkind": "r",
             "relowner": 10, "reltuples": -1}
            for t in tables
        ]
    if table == "pg_attribute":
        rows = []
        for t in tables:
            for i, c in enumerate(t.columns):
                rows.append({
                    "attrelid": rel_oid[t.name], "attname": c.name,
                    "atttypid": _col_oid(c.sql_type), "attnum": i + 1,
                    "attnotnull": c.not_null or c.primary_key,
                    "attisdropped": False,
                })
        return rows
    if table == "pg_range":
        return []
    if table == "tables":  # information_schema.tables
        return [
            {"table_catalog": "corrosion", "table_schema": "public",
             "table_name": t.name, "table_type": "BASE TABLE"}
            for t in tables
        ]
    if table == "columns":  # information_schema.columns
        rows = []
        for t in tables:
            for i, c in enumerate(t.columns):
                rows.append({
                    "table_schema": "public", "table_name": t.name,
                    "column_name": c.name, "ordinal_position": i + 1,
                    "data_type": c.sql_type.lower(),
                    "is_nullable": "NO" if (c.not_null or c.primary_key)
                    else "YES",
                })
        return rows
    return []


_CATALOG_TABLES = (
    "pg_class", "pg_attribute", "pg_type", "pg_namespace", "pg_database",
    "pg_range", "tables", "columns",
)
# a query is a catalog query only when its FROM target is a catalog
# table — a user query merely *mentioning* pg_class in a literal must
# still run against the real store
_CATALOG_FROM_RE = re.compile(
    r"\bFROM\s+(?:PG_CATALOG\.\w+|INFORMATION_SCHEMA\.\w+|"
    r"PG_(?:CLASS|ATTRIBUTE|TYPE|NAMESPACE|DATABASE|RANGE|TABLES)\b)",
    re.IGNORECASE,
)
_CATALOG_RE = re.compile(
    r"^SELECT\s+(?P<cols>.*?)\s+FROM\s+"
    r"(?:pg_catalog\.|information_schema\.)?(?P<table>\w+)"
    r"(?:\s+(?:AS\s+)?(?P<alias>(?!WHERE|ORDER|LIMIT)\w+))?"
    r"(?:\s+WHERE\s+(?P<where>.*?))?"
    r"(?:\s+ORDER\s+BY\s+(?P<order>.*?))?"
    r"(?:\s+LIMIT\s+(?P<limit>\d+))?\s*;?\s*$",
    re.IGNORECASE | re.DOTALL,
)


def _catalog_literal(tok: str, db, params, pos: List[int]) -> Any:
    """A catalog WHERE literal: int, 'str', ``$N``/``?`` param (``pos``
    is the running positional-``?`` counter), or ``'name'::regclass``
    (resolved to the relation oid)."""
    tok = tok.strip()
    m = re.match(r"^'([^']*)'\s*::\s*regclass$", tok,
                               re.IGNORECASE)
    if m:
        name = m.group(1).split(".")[-1]
        for row in _catalog_rows(db, "pg_class"):
            if row["relname"] == name:
                return row["oid"]
        return -1
    plist = list(params or [])
    nm = re.match(r"^\$(\d+)$", tok)
    if nm:
        i = int(nm.group(1)) - 1
        return plist[i] if 0 <= i < len(plist) else None
    if tok == "?":
        i = pos[0]
        pos[0] += 1
        return plist[i] if i < len(plist) else None
    if tok.startswith("'") and tok.endswith("'"):
        return tok[1:-1].replace("''", "'")
    try:
        return int(tok)
    except ValueError:
        return tok


def _answer_catalog(db, sql: str, params) -> Optional[Tuple[List[str], List[List[Any]]]]:
    """Try to answer a catalog introspection query from the live schema.
    Returns (cols, rows) or None when the shape is unrecognized (caller
    degrades to an empty result set)."""
    m = _CATALOG_RE.match(sql.strip())
    if m is None or m.group("table").lower() not in _CATALOG_TABLES:
        return None
    table = m.group("table").lower()
    alias = (m.group("alias") or table).lower()
    rows = _catalog_rows(db, table)
    known = set(rows[0]) if rows else set()

    def strip_alias(ident):
        ident = ident.strip().strip('"')
        if "." in ident:
            q, _, c = ident.partition(".")
            if q.lower() not in (alias, table):
                return None
            ident = c.strip('"')
        return ident.lower()

    # WHERE: conjunction of col = literal / col IN (lit, ...)
    if m.group("where"):
        pos = [0]  # running positional-? parameter counter
        for clause in re.split(r"\s+AND\s+", m.group("where"),
                                flags=re.IGNORECASE):
            cm = re.match(r"^([\w\".]+)\s*=\s*(.+)$", clause.strip(),
                           re.DOTALL)
            im = re.match(r"^([\w\".]+)\s+IN\s*\((.+)\)$", clause.strip(),
                           re.IGNORECASE | re.DOTALL)
            if im:
                col = strip_alias(im.group(1))
                if col is None or (rows and col not in known):
                    return None
                vals = {_catalog_literal(t, db, params, pos)
                        for t in im.group(2).split(",")}
                rows = [r for r in rows if r.get(col) in vals]
            elif cm:
                col = strip_alias(cm.group(1))
                if col is None or (rows and col not in known):
                    return None
                val = _catalog_literal(cm.group(2), db, params, pos)
                rows = [r for r in rows
                        if r.get(col) == val or str(r.get(col)) == str(val)]
            else:
                return None

    # projection
    raw = m.group("cols").strip()
    if raw == "*":
        names = sorted(known) if rows else []
    else:
        names = []
        for part in raw.split(","):
            am = re.match(r"^(.*?)\s+AS\s+[\"']?([\w ]+)[\"']?\s*$",
                           part.strip(), re.IGNORECASE | re.DOTALL)
            ident = am.group(1) if am else part
            col = strip_alias(ident)
            if col is None or col == "count(*)":
                return None
            names.append(col)
        for n in names:
            if rows and n not in known:
                return None

    # ORDER BY col [DESC] (output columns only)
    if m.group("order"):
        for part in reversed(m.group("order").split(",")):
            toks = part.split()
            desc = len(toks) > 1 and toks[-1].upper() == "DESC"
            col = strip_alias(toks[0])
            if col is None or (rows and col not in known):
                return None
            # ints compare numerically, strings lexically (type-tagged so
            # attnum 10 sorts after 2, not between 1 and 2)
            rows = sorted(
                rows,
                key=lambda r: (r.get(col) is not None,
                               isinstance(r.get(col), str), r.get(col)),
                reverse=desc,
            )
    if m.group("limit"):
        rows = rows[: int(m.group("limit"))]
    return names, [[r.get(n) for n in names] for r in rows]


def _text_value(v: Any) -> Optional[bytes]:
    if v is None:
        return None
    if isinstance(v, bool):
        return b"t" if v else b"f"
    if isinstance(v, bytes):
        return b"\\x" + v.hex().encode()
    return str(v).encode()


def _binary_value(v: Any, oid: int) -> Optional[bytes]:
    """PG binary result encoding for the supported OIDs
    (``corro-pg`` answers binary-format portals the same way). The
    declared column oid drives the coercion so the wire bytes always
    match the RowDescription the client planned against."""
    if v is None:
        return None
    if oid == OID_FLOAT8:
        try:
            return struct.pack("!d", float(v))
        except (TypeError, ValueError):
            # flexible typing: a non-numeric value in a REAL column —
            # fall back to its utf8 text (length-prefixed, so a strict
            # client sees len != 8 rather than garbage)
            return str(v).encode()
    if oid in OID_INTS:
        try:
            return struct.pack("!q", int(v))
        except (TypeError, ValueError):
            return str(v).encode()
    if oid == OID_BYTEA:
        return v if isinstance(v, bytes) else str(v).encode()
    if isinstance(v, bool):  # bool as text-ish byte for OID_TEXT
        return b"\x01" if v else b"\x00"
    # text binary format is the utf8 bytes themselves
    if isinstance(v, bytes):
        return v
    return str(v).encode()


def _fmt_for(i: int, fmts: Optional[List[int]]) -> int:
    """Bind result-format codes: [] = all text, [f] = all f, else per
    column (PG protocol)."""
    if not fmts:
        return 0
    if len(fmts) == 1:
        return fmts[0]
    return fmts[i] if i < len(fmts) else 0


def _iter_sql_segments(sql: str):
    """Yield ``(is_literal, segment)`` pairs, where literal segments are
    single-quoted strings (``''`` escapes stay inside one literal). The
    single quote-scanner every literal-aware transform builds on."""
    i, n = 0, len(sql)
    while i < n:
        if sql[i] == "'":
            j = i + 1
            while j < n:
                if sql[j] == "'":
                    if j + 1 < n and sql[j + 1] == "'":
                        j += 2
                        continue
                    break
                j += 1
            yield True, sql[i:min(j + 1, n)]
            i = j + 1
        else:
            j = sql.find("'", i)
            if j == -1:
                j = n
            yield False, sql[i:j]
            i = j


def _split_sql_outside_quotes(sql: str, sep: str) -> List[str]:
    """Split on ``sep`` only outside single-quoted literals."""
    parts, cur = [], []
    for is_lit, seg in _iter_sql_segments(sql):
        if is_lit:
            cur.append(seg)
            continue
        while True:
            k = seg.find(sep)
            if k == -1:
                cur.append(seg)
                break
            cur.append(seg[:k])
            parts.append("".join(cur))
            cur = []
            seg = seg[k + 1:]
    parts.append("".join(cur))
    return parts


def _translate_sql(sql: str) -> str:
    """Light PG -> local dialect cleanup: strip ``::type`` casts outside
    string literals (the reference runs a full sqlparser -> SQLite
    translation)."""
    import re

    return "".join(
        seg if is_lit else re.sub(r"::\w+", "", seg)
        for is_lit, seg in _iter_sql_segments(sql)
    ).strip()


def _sql_kind(sql: str) -> str:
    """Bounded-cardinality statement class for the per-kind query
    latency histogram (ISSUE 16): ``select`` / ``write`` / ``tx`` /
    ``catalog`` / ``meta`` / ``empty``. Classification mirrors the
    ``_run_sql`` dispatch order so every wire statement lands in exactly
    the class whose code path served it."""
    s = _translate_sql(sql)
    upper = s.upper().rstrip(";").strip()
    if not upper:
        return "empty"
    verb = upper.split()[0]
    if (verb in ("BEGIN", "COMMIT", "END", "ROLLBACK", "SAVEPOINT",
                 "RELEASE")
            or upper.startswith("START TRANSACTION")):
        return "tx"
    if verb in ("SET", "RESET", "DISCARD", "SHOW"):
        return "meta"
    if _CATALOG_FROM_RE.search(upper):
        return "catalog"
    if verb == "SELECT":
        return "select"
    return "write"


def _substitute_placeholders(sql: str) -> "Tuple[str, List[int]]":
    """Rewrite ``$N`` -> ``?`` *outside single-quoted literals* (a dollar
    sign inside a string like ``'costs $5'`` is data, not a parameter).
    Returns ``(text, param_map)`` where occurrence i of ``?`` consumes
    client-param index ``param_map[i]``."""
    import re

    param_map: List[int] = []

    def repl(m):
        param_map.append(int(m.group(1)) - 1)
        return "?"

    text = "".join(
        seg if is_lit else re.sub(r"\$(\d+)", repl, seg)
        for is_lit, seg in _iter_sql_segments(sql)
    )
    return text, param_map


class _Msg:
    """Backend message writer."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._buf = bytearray()

    def add(self, kind: bytes, payload: bytes = b"") -> "_Msg":
        self._buf += kind + struct.pack("!I", len(payload) + 4) + payload
        return self

    def flush(self) -> None:
        if self._buf:
            self.sock.sendall(bytes(self._buf))
            self._buf.clear()


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


class _PreparedStatement:
    def __init__(self, sql: str, param_oids: List[int],
                 param_map: Optional[List[int]] = None):
        self.sql = sql
        self.param_oids = param_oids
        # textual order of $N placeholders: occurrence i consumes
        # client-param index param_map[i] (handles $2 ... $1 and reuse)
        self.param_map = param_map or []

    def reorder(self, params: List[Any]) -> List[Any]:
        if not self.param_map:
            return params
        return [params[i] if i < len(params) else None
                for i in self.param_map]


class _Portal:
    def __init__(self, stmt: _PreparedStatement, params: List[Any],
                 result_fmts: Optional[List[int]] = None):
        self.stmt = stmt
        self.params = params
        # Bind's result-format codes: [] all-text, [1] all-binary, or
        # per-column
        self.result_fmts = result_fmts or []
        # True once Describe(portal) emitted a RowDescription; Execute
        # then must NOT send a second one (protocol), but when Describe
        # answered NoData (synthetic results: SHOW, constant SELECT,
        # pg_catalog) Execute still owes the client a description
        self.described = False


class PgServer:
    """PG v3 listener bound to one Database."""

    def __init__(self, db, addr: str = "127.0.0.1", port: int = 0,
                 default_node: int = 0, admission=None):
        from corrosion_tpu.api.admission import AdmissionController

        self.db = db
        self.default_node = default_node
        # corroguard (docs/overload.md): pass the ApiServer's controller
        # to shed PG connections against the same per-class budgets as
        # the HTTP plane; the default standalone controller is disabled
        # (ServeConfig.max_inflight == 0)
        self.admission = admission or AdmissionController(
            None, registry=db.agent.metrics)
        handler = _make_handler(self)

        class _DrainingTCPServer(DrainingConnMixin,
                                 socketserver.ThreadingTCPServer):
            _conn_name = "corro-pg-conn"

        self.server = _DrainingTCPServer(
            (addr, port), handler, bind_and_activate=False
        )
        self.server.allow_reuse_address = True
        self.server.server_bind()
        self.server.server_activate()
        self.addr, self.port = self.server.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "PgServer":
        from corrosion_tpu.utils.lifecycle import spawn_counted

        self._thread = spawn_counted(
            self.server.serve_forever, name="corro-pg-wire"
        )
        return self

    def stop(self) -> None:
        self.server.shutdown()
        # grace=0: a PG handler parked in recv only exits when its
        # socket dies — a well-behaved client already sent Terminate
        self.server.drain_connections(grace=0.0)
        self.server.server_close()
        if self._thread:
            self._thread.join(timeout=10)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def _make_handler(server: PgServer):
    class Handler(socketserver.BaseRequestHandler):
        def setup(self):
            self.sock: socket.socket = self.request
            self.out = _Msg(self.sock)
            self.stmts: Dict[str, _PreparedStatement] = {}
            self.portals: Dict[str, _Portal] = {}
            self.node = server.default_node
            self.tx = None  # open StagedTx between BEGIN and COMMIT
            self.tx_failed = False  # aborted: reject until COMMIT/ROLLBACK

        # --- low-level reads ---------------------------------------------
        def _read_exact(self, n: int) -> bytes:
            data = b""
            while len(data) < n:
                chunk = self.sock.recv(n - len(data))
                if not chunk:
                    raise ConnectionResetError
                data += chunk
            return data

        def _read_startup(self) -> Optional[dict]:
            (length,) = struct.unpack("!I", self._read_exact(4))
            payload = self._read_exact(length - 4)
            (code,) = struct.unpack("!I", payload[:4])
            if code == SSL_REQUEST:
                self.sock.sendall(b"N")  # no TLS on the simulator listener
                return self._read_startup()
            if code == CANCEL_REQUEST:
                return None
            if code != PROTO_V3:
                raise ValueError(f"unsupported protocol {code}")
            params = {}
            parts = payload[4:].split(b"\x00")
            for k, v in zip(parts[::2], parts[1::2]):
                if k:
                    params[k.decode()] = v.decode()
            return params

        def _read_message(self) -> Tuple[bytes, bytes]:
            kind = self._read_exact(1)
            (length,) = struct.unpack("!I", self._read_exact(4))
            return kind, self._read_exact(length - 4)

        # --- backend responses -------------------------------------------
        def _send_ready(self):
            # ReadyForQuery carries the real transaction status: I idle,
            # T in transaction, E failed transaction (pg protocol)
            status = (b"E" if self.tx_failed
                      else b"T" if self.tx is not None else b"I")
            self.out.add(b"Z", status).flush()

        def _send_error(self, message: str, code: str = SQLSTATE_INTERNAL):
            fields = (b"S" + _cstr("ERROR") + b"C" + _cstr(code)
                      + b"M" + _cstr(message) + b"\x00")
            self.out.add(b"E", fields)

        def _col_oids(self, cols: List[str],
                      table_name: Optional[str] = None) -> List[int]:
            """Deterministic per-column OIDs (schema-driven, else TEXT) —
            shared by RowDescription and the binary row encoder so the
            wire bytes always match the declared description."""
            table = None
            if table_name is not None:
                try:
                    table = server.db.schema.table(table_name)
                except SchemaError:
                    table = None
            oids = []
            for name in cols:
                oid = OID_TEXT
                if table is not None:
                    try:
                        oid = _col_oid(table.column(name).sql_type)
                    except SchemaError:
                        pass
                oids.append(oid)
            return oids

        def _row_description(self, cols: List[str],
                             table_name: Optional[str] = None,
                             fmts: Optional[List[int]] = None):
            payload = struct.pack("!H", len(cols))
            oids = self._col_oids(cols, table_name)
            for i, name in enumerate(cols):
                payload += _cstr(name)
                payload += struct.pack("!IhIhih", 0, 0, oids[i], -1, -1,
                                       _fmt_for(i, fmts))
            self.out.add(b"T", payload)

        def _data_row(self, row: List[Any],
                      fmts: Optional[List[int]] = None,
                      oids: Optional[List[int]] = None):
            payload = struct.pack("!H", len(row))
            for i, v in enumerate(row):
                if _fmt_for(i, fmts) == 1:
                    tv = _binary_value(
                        v, oids[i] if oids and i < len(oids) else OID_TEXT
                    )
                else:
                    tv = _text_value(v)
                if tv is None:
                    payload += struct.pack("!i", -1)
                else:
                    payload += struct.pack("!I", len(tv)) + tv
            self.out.add(b"D", payload)

        def _command_complete(self, tag: str):
            self.out.add(b"C", _cstr(tag))

        # --- statement execution -----------------------------------------
        def _table_of(self, sql: str) -> Optional[str]:
            import re

            m = re.search(r"\b(?:FROM|INTO|UPDATE)\s+([\w\"]+)", sql,
                          re.IGNORECASE)
            return m.group(1).strip('"') if m else None

        def _run_sql(self, sql: str, params: Any = None,
                     send_desc: bool = True,
                     fmts: Optional[List[int]] = None) -> None:
            """Timed envelope around :meth:`_dispatch_sql` — both wire
            entry points (simple query and extended Execute) land here,
            so the per-kind latency histogram counts every issued
            statement exactly once, errors included."""
            t0 = time.perf_counter()
            try:
                self._dispatch_sql(sql, params, send_desc, fmts)
            finally:
                server.db.agent.metrics.histogram(
                    "corro.pg.query.seconds",
                    time.perf_counter() - t0, {"kind": _sql_kind(sql)})

        def _dispatch_sql(self, sql: str, params: Any = None,
                          send_desc: bool = True,
                          fmts: Optional[List[int]] = None) -> None:
            """``send_desc``: simple query includes RowDescription;
            extended Execute must NOT (the client learned the shape from
            Describe — a second 'T' is a protocol violation). ``fmts``:
            the portal's Bind result-format codes (binary results)."""
            orig_sql = sql  # pre-translation (keeps ::regclass casts)
            sql = _translate_sql(sql)
            if not sql or sql.rstrip(";") == "":
                self.out.add(b"I", b"")  # EmptyQueryResponse
                return
            upper = sql.upper().rstrip(";")
            verb = upper.split()[0] if upper.split() else ""
            # transaction control (real BEGIN/COMMIT since round 5: the
            # reference's PG server runs genuine txs, corro-pg/src/lib.rs)
            if verb == "BEGIN" or upper.startswith("START TRANSACTION"):
                if self.tx is None:
                    self.tx = server.db.begin(self.node)
                self._command_complete("BEGIN")
                return
            if verb in ("COMMIT", "END"):
                tx, failed = self.tx, self.tx_failed
                self.tx, self.tx_failed = None, False
                if failed or tx is None:
                    if tx is not None:
                        tx.rollback()
                    # committing an aborted tx rolls back (pg semantics)
                    self._command_complete(
                        "ROLLBACK" if failed else "COMMIT")
                    return
                tx.commit()
                self._command_complete("COMMIT")
                return
            if verb in ("SAVEPOINT", "RELEASE") or (
                verb == "ROLLBACK"
                and re.match(r"ROLLBACK\s+TO\b", upper)
            ):
                # savepoints are not supported; erroring (0A000) keeps
                # the block's state honest — a silent full ROLLBACK for
                # 'ROLLBACK TO SAVEPOINT' would drop buffered statements
                # while the client believes the tx is still open
                self._send_error("savepoints are not supported",
                                 SQLSTATE_FEATURE_UNSUPPORTED)
                if self.tx is not None:
                    self.tx_failed = True
                return
            if verb == "ROLLBACK":
                if self.tx is not None:
                    self.tx.rollback()
                self.tx, self.tx_failed = None, False
                self._command_complete("ROLLBACK")
                return
            if self.tx_failed:
                self._send_error(
                    "current transaction is aborted, commands ignored "
                    "until end of transaction block", SQLSTATE_IN_FAILED_TX)
                return
            if upper.startswith(("SET ", "RESET ", "DISCARD ")):
                self._command_complete("SET")
                return
            if upper.startswith("SHOW "):
                name = sql.split(None, 1)[1].rstrip(";")
                if send_desc:
                    self._row_description([name.lower()], fmts=fmts)
                self._data_row([""], fmts)
                self._command_complete("SHOW")
                return
            if _CATALOG_FROM_RE.search(upper):
                # introspection served from the live schema (vtab analog);
                # unrecognized shapes degrade to an empty result set
                answer = _answer_catalog(server.db, orig_sql, params)
                if answer is None:
                    if send_desc:
                        self._row_description(["?column?"], fmts=fmts)
                    self._command_complete("SELECT 0")
                    return
                cols, rows = answer
                if send_desc:
                    self._row_description(cols, fmts=fmts)
                for row in rows:
                    self._data_row(row, fmts)
                self._command_complete(f"SELECT {len(rows)}")
                return
            if upper.startswith("SELECT"):
                self._run_select(sql, params, send_desc, fmts)
                return
            n = self._run_write(sql, params)
            tag = f"INSERT 0 {n}" if verb == "INSERT" else f"{verb} {n}"
            self._command_complete(tag)

        def _run_select(self, sql: str, params: Any,
                        send_desc: bool = True,
                        fmts: Optional[List[int]] = None) -> None:
            import re

            # constant selects like SELECT 1 / SELECT version()
            m = re.match(r"SELECT\s+([^\s,]+)\s*;?$", sql, re.IGNORECASE)
            if m and "FROM" not in sql.upper():
                expr = m.group(1).rstrip(";")
                if expr.lower() in ("version()", "current_schema()"):
                    val = ("corrosion-tpu (PostgreSQL 14.0 compatible)"
                           if "version" in expr.lower() else "public")
                else:
                    try:
                        val = int(expr)
                    except ValueError:
                        val = expr.strip("'")
                if send_desc:
                    self._row_description(["?column?"], fmts=fmts)
                self._data_row([val], fmts)
                self._command_complete("SELECT 1")
                return
            cols, rows = server.db.query(self.node, sql, params)
            table = self._table_of(sql)
            if send_desc:
                self._row_description(cols, table, fmts)
            oids = self._col_oids(cols, table) if fmts else None
            n = 0
            for row in rows:
                self._data_row(row, fmts, oids)
                n += 1
            self._command_complete(f"SELECT {n}")

        def _run_write(self, sql: str, params: Any) -> int:
            if self.tx is not None:
                # buffered inside the open BEGIN block; visible to the
                # cluster only at COMMIT
                return self.tx.execute(sql, params)["rows_affected"]
            results = server.db.execute(self.node, [(sql, params)])
            return results[0]["rows_affected"]

        # --- protocol phases ---------------------------------------------
        def handle(self):
            admitted = False
            try:
                params = self._read_startup()
                if params is None:
                    return
                if "node" in params.get("database", ""):
                    # database name "node<K>" selects the observer replica
                    try:
                        self.node = int(
                            params["database"].replace("node", ""))
                    except ValueError:
                        pass
                # corroguard admission on the accept path (docs/
                # overload.md): a connection slot is a "pg"-class ticket
                # held for the whole wire session; a shed connection gets
                # the canonical 53300 before the auth handshake
                if not server.admission.admit("pg"):
                    ra = server.admission.retry_after("pg")
                    self._send_error(
                        f"server overloaded; retry after {ra}s",
                        SQLSTATE_TOO_MANY_CONNECTIONS)
                    self.out.flush()
                    return
                admitted = True
                self.out.add(b"R", struct.pack("!I", 0))  # AuthenticationOk
                for k, v in (("server_version", "14.0"),
                             ("server_encoding", "UTF8"),
                             ("client_encoding", "UTF8"),
                             ("DateStyle", "ISO, MDY")):
                    self.out.add(b"S", _cstr(k) + _cstr(v))
                self.out.add(b"K", struct.pack("!II", 0, 0))
                self._send_ready()
                self._loop()
            except (ConnectionResetError, BrokenPipeError):
                pass
            except Exception:  # noqa: BLE001
                logger.exception("pg connection failed")
            finally:
                if admitted:
                    server.admission.release("pg")

        def _loop(self):
            while True:
                kind, payload = self._read_message()
                if kind == b"X":  # Terminate
                    return
                if kind == b"Q":
                    self._on_simple_query(payload)
                elif kind == b"P":
                    self._on_parse(payload)
                elif kind == b"B":
                    self._on_bind(payload)
                elif kind == b"D":
                    self._on_describe(payload)
                elif kind == b"E":
                    self._on_execute(payload)
                elif kind == b"C":
                    self._on_close(payload)
                elif kind == b"S":  # Sync
                    self._send_ready()
                elif kind == b"H":  # Flush
                    self.out.flush()
                else:
                    self._send_error(f"unsupported message {kind!r}")
                    self._send_ready()

        def _on_simple_query(self, payload: bytes):
            sql = payload.rstrip(b"\x00").decode()
            try:
                parts = [s for s in _split_sql_outside_quotes(sql, ";")
                         if s.strip()]
                for part in parts or [""]:
                    self._run_sql(part)
            except (SqlError, SchemaError) as e:
                if self.tx is not None:
                    self.tx_failed = True  # abort the open BEGIN block
                self._send_error(str(e), _sqlstate_for(e))
            except Exception as e:  # noqa: BLE001
                if self.tx is not None:
                    self.tx_failed = True
                logger.exception("pg simple query failed")
                self._send_error(str(e))
            self._send_ready()

        def _on_parse(self, payload: bytes):
            name, rest = payload.split(b"\x00", 1)
            sql, rest = rest.split(b"\x00", 1)
            (n_oids,) = struct.unpack("!H", rest[:2])
            oids = list(struct.unpack(f"!{n_oids}I", rest[2:2 + 4 * n_oids]))
            # $N placeholders -> positional ?, keeping the N order so
            # $2 ... $1 and repeated placeholders bind correctly; quoted
            # literals are skipped so 'costs $5' stays data
            text, param_map = _substitute_placeholders(sql.decode())
            self.stmts[name.decode()] = _PreparedStatement(
                text, oids, param_map)
            self.out.add(b"1", b"")  # ParseComplete

        def _on_bind(self, payload: bytes):
            portal, rest = payload.split(b"\x00", 1)
            stmt_name, rest = rest.split(b"\x00", 1)
            off = 0
            (n_fmt,) = struct.unpack("!H", rest[off:off + 2])
            off += 2
            fmts = list(struct.unpack(f"!{n_fmt}H", rest[off:off + 2 * n_fmt]))
            off += 2 * n_fmt
            (n_params,) = struct.unpack("!H", rest[off:off + 2])
            off += 2
            params: List[Any] = []
            stmt = self.stmts.get(stmt_name.decode())
            for i in range(n_params):
                (plen,) = struct.unpack("!i", rest[off:off + 4])
                off += 4
                if plen == -1:
                    params.append(None)
                    continue
                raw = rest[off:off + plen]
                off += plen
                fmt = fmts[i] if i < len(fmts) else (fmts[0] if fmts else 0)
                params.append(self._decode_param(raw, fmt, stmt, i))
            if stmt is None:
                self._send_error(f"no such prepared statement "
                                 f"{stmt_name.decode()!r}", SQLSTATE_SYNTAX)
                return
            # result-format codes (binary results, corro-pg parity)
            result_fmts: List[int] = []
            if off + 2 <= len(rest):
                (n_rfmt,) = struct.unpack("!H", rest[off:off + 2])
                off += 2
                if off + 2 * n_rfmt <= len(rest):
                    result_fmts = list(
                        struct.unpack(f"!{n_rfmt}H",
                                      rest[off:off + 2 * n_rfmt])
                    )
            self.portals[portal.decode()] = _Portal(
                stmt, stmt.reorder(params), result_fmts)
            self.out.add(b"2", b"")  # BindComplete

        def _decode_param(self, raw: bytes, fmt: int,
                          stmt: Optional[_PreparedStatement], i: int) -> Any:
            oid = (stmt.param_oids[i]
                   if stmt and i < len(stmt.param_oids) else 0)
            if fmt == 1:  # binary
                if oid == OID_FLOAT8:
                    return struct.unpack("!d", raw)[0]
                if oid in OID_INTS or (oid == 0 and len(raw) in (2, 4, 8)):
                    return int.from_bytes(raw, "big", signed=True)
                return raw
            text = raw.decode()
            if oid in OID_INTS:
                return int(text)
            if oid == OID_FLOAT8:
                return float(text)
            if oid in (0, OID_TEXT):
                # untyped text: try numeric, else string (SQLite affinity)
                try:
                    return int(text)
                except ValueError:
                    try:
                        return float(text)
                    except ValueError:
                        return text
            return text

        def _on_describe(self, payload: bytes):
            kind, name = payload[:1], payload[1:].rstrip(b"\x00").decode()
            if kind == b"S":
                stmt = self.stmts.get(name)
                if stmt is None:
                    self._send_error(f"no such statement {name!r}")
                    return
                self.out.add(b"t", struct.pack("!H", len(stmt.param_oids))
                             + b"".join(struct.pack("!I", o or OID_TEXT)
                                        for o in stmt.param_oids))
                sql = stmt.sql
            else:
                portal = self.portals.get(name)
                if portal is None:
                    self._send_error(f"no such portal {name!r}")
                    return
                sql = portal.stmt.sql
            described = False
            pfmts = (self.portals[name].result_fmts
                     if kind == b"P" and name in self.portals else None)
            if sql.upper().lstrip().startswith("SELECT"):
                try:
                    # schema-only plan: no table scan on the Describe phase
                    cols = server.db.query_columns(_translate_sql(sql))
                    self._row_description(cols, self._table_of(sql), pfmts)
                    described = True
                except Exception:  # noqa: BLE001 — constant SELECTs etc.
                    self.out.add(b"n", b"")  # NoData
            else:
                self.out.add(b"n", b"")
            if kind == b"P":
                portal.described = described

        def _on_execute(self, payload: bytes):
            name = payload.split(b"\x00", 1)[0].decode()
            portal = self.portals.get(name)
            if portal is None:
                self._send_error(f"no such portal {name!r}")
                return
            try:
                # Describe already told the client the row shape iff it
                # produced a RowDescription; synthetic results (NoData
                # from Describe) still need theirs here
                self._run_sql(portal.stmt.sql, portal.params or None,
                              send_desc=not portal.described,
                              fmts=portal.result_fmts)
            except (SqlError, SchemaError) as e:
                if self.tx is not None:
                    self.tx_failed = True  # abort the open BEGIN block
                self._send_error(str(e), _sqlstate_for(e))
            except Exception as e:  # noqa: BLE001
                if self.tx is not None:
                    self.tx_failed = True
                logger.exception("pg execute failed")
                self._send_error(str(e))

        def _on_close(self, payload: bytes):
            kind, name = payload[:1], payload[1:].rstrip(b"\x00").decode()
            if kind == b"S":
                self.stmts.pop(name, None)
            else:
                self.portals.pop(name, None)
            self.out.add(b"3", b"")  # CloseComplete

    return Handler
