import sys

from corrosion_tpu.cli import main

sys.exit(main())
