"""Template engine: programs that query the cluster and render files,
re-rendering when the underlying data changes.

Mirrors ``crates/corro-tpl`` + ``corrosion template`` (``corro-tpl/src/
lib.rs:33-80``, ``command/tpl.rs``): the reference runs Rhai programs
exposing ``sql()`` (streaming rows), ``hostname()``, and JSON/CSV
rendering, and re-renders a template whenever the subscription behind one
of its queries fires. Here the template language is Python: the template
file is executed with the same primitives in scope and its ``write()``
output lands atomically in the destination file.

Template API (in scope during execution):
- ``sql(query, params=None)`` -> list of row dicts
- ``sql_json(query, params=None)`` / ``sql_csv(query, params=None)``
- ``hostname()``
- ``write(text)`` — append to the output
- ``env`` — os.environ copy
"""

from __future__ import annotations

import csv
import io
import json
import os
import socket
import threading
import time
from typing import Any, Callable, List, Optional, Tuple

from corrosion_tpu.utils.tracing import logger


class TemplateState:
    """One template's execution context; records the queries it ran so
    the runner knows what to watch."""

    def __init__(self, query_fn: Callable[[str, Any], Tuple[List[str], list]],
                 node: int = 0):
        self._query_fn = query_fn
        self.node = node
        self.queries: List[Tuple[str, Any]] = []
        self._out = io.StringIO()

    # --- template API ----------------------------------------------------
    def sql(self, query: str, params: Any = None) -> List[dict]:
        self.queries.append((query, params))
        cols, rows = self._query_fn(query, params)
        return [dict(zip(cols, row)) for row in rows]

    def sql_json(self, query: str, params: Any = None) -> str:
        return json.dumps(self.sql(query, params))

    def sql_csv(self, query: str, params: Any = None) -> str:
        self.queries.append((query, params))
        cols, rows = self._query_fn(query, params)
        buf = io.StringIO()
        w = csv.writer(buf)
        w.writerow(cols)
        w.writerows(rows)
        return buf.getvalue()

    def write(self, text: str) -> None:
        self._out.write(str(text))

    @staticmethod
    def hostname() -> str:
        return socket.gethostname()

    def output(self) -> str:
        return self._out.getvalue()


def render_template(src: str, query_fn, node: int = 0) -> Tuple[str, list]:
    """Execute template source -> (rendered output, queries used)."""
    state = TemplateState(query_fn, node)
    scope = {
        "sql": state.sql,
        "sql_json": state.sql_json,
        "sql_csv": state.sql_csv,
        "write": state.write,
        "hostname": state.hostname,
        "env": dict(os.environ),
        "json": json,
    }
    exec(compile(src, "<template>", "exec"), scope)  # noqa: S102 — operator-supplied program, like Rhai in the reference
    return state.output(), state.queries


def _atomic_write(path: str, data: str) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(data)
    os.replace(tmp, path)


class TemplateRunner:
    """Render ``template.py:dest`` specs; without ``once``, keep watching
    the queried data and re-render on change (the reference re-renders
    when the sub fires)."""

    def __init__(self, client, specs: List[str], node: int = 0,
                 poll_seconds: float = 0.5):
        self.client = client
        self.node = node
        self.poll_seconds = poll_seconds
        self.specs: List[Tuple[str, str]] = []
        for spec in specs:
            src, _, dst = spec.rpartition(":")
            if not src:
                raise ValueError(f"bad template spec {spec!r} "
                                 f"(want template.py:output)")
            self.specs.append((src, dst))
        self._stop = threading.Event()

    def _query(self, sql: str, params: Any):
        return self.client.query(sql, params, node=self.node)

    def render_all(self) -> List[str]:
        outputs = []
        for src_path, dst_path in self.specs:
            with open(src_path) as f:
                src = f.read()
            out, _queries = render_template(src, self._query, self.node)
            _atomic_write(dst_path, out)
            outputs.append(dst_path)
        return outputs

    def watch(self) -> None:
        """Re-render whenever any queried data changes. Uses the
        subscription stream when available, falling back to polling the
        rendered output."""
        last: dict = {}
        while not self._stop.is_set():
            changed = False
            for src_path, dst_path in self.specs:
                with open(src_path) as f:
                    src = f.read()
                out, _ = render_template(src, self._query, self.node)
                if last.get(dst_path) != out:
                    _atomic_write(dst_path, out)
                    last[dst_path] = out
                    changed = True
            if changed:
                logger.info("templates re-rendered")
            self._stop.wait(self.poll_seconds)

    def stop(self) -> None:
        self._stop.set()


def render_template_cli(args) -> int:
    from corrosion_tpu.client import CorrosionApiClient

    client = CorrosionApiClient(args.api_addr, args.api_port)
    runner = TemplateRunner(client, args.spec, node=args.node)
    outputs = runner.render_all()
    for o in outputs:
        print(f"rendered {o}")
    if not args.once:
        try:
            runner.watch()
        except KeyboardInterrupt:
            runner.stop()
    return 0
