"""The host agent: the operator-facing node runtime around the simulator.

The reference's ``corro-agent`` boots one OS process per node with loops
for gossip, changes, and sync plus an HTTP API (SURVEY §3.1). Here one
host agent carries the *whole simulated cluster* (the TPU holds every
node's state); the API surface is per-node through an explicit ``node``
parameter — write through node A, read at node B, and convergence is
observable exactly like the reference's ``insert_rows_and_gossip`` tests.
"""

from corrosion_tpu.agent.core import Agent

__all__ = ["Agent"]
