"""Agent core: the round loop + write/read surface over the simulator.

Maps the reference's node runtime (SURVEY §3.1 ``start_with_config`` ->
``run``) onto the TPU model:

- the **round loop** thread is every corro-agent loop fused: each tick
  advances the whole cluster one protocol round (SWIM + broadcast + sync)
  through one jitted step — ``runtime_loop``/``handle_changes``/
  ``sync_loop`` in one dispatch;
- the **write path** mirrors ``POST /v1/transactions``
  (``api_v1_transactions``, ``crates/corro-agent/src/api/public/mod.rs:177``):
  statements execute against a node's pending-write slot and are
  disseminated by the next round's broadcast step;
- the **read path** mirrors ``/v1/queries``: reads observe one node's
  local replica only (eventually consistent by construction);
- **churn/partition controls** are the admin/fault-injection surface
  (Antithesis drivers, SURVEY §4).

Thread-safety: API threads only touch the pending-input buffers and the
latest host snapshot, both under tracked locks; the round thread owns the
device state exclusively.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from corrosion_tpu.config import Config
from corrosion_tpu.utils.assertions import assert_always, assert_sometimes
from corrosion_tpu.utils.hlc import HLClock
from corrosion_tpu.utils.lifecycle import Tripwire, spawn_counted
from corrosion_tpu.utils.locks import LockRegistry
from corrosion_tpu.utils.metrics import Registry, RoundTimer, record_round_info
from corrosion_tpu.utils.tracing import logger


class _CarryConsumed(Exception):
    """A donated round dispatch failed AFTER consuming the carry
    buffers: there is nothing on-device left to retry with. Deliberately
    a plain ``Exception`` (NOT RuntimeError) so the supervisor's retry
    set never re-runs it — it propagates to the round loop, whose
    checkpoint rollback is the generation-fenced re-upload story."""


class Agent:
    """The node runtime. ``Agent(config).start()`` -> round loop running.

    Use :meth:`execute` / :meth:`query` / :meth:`snapshot` from any
    thread; :meth:`shutdown` is the tripwire.
    """

    def __init__(self, config: Optional[Config] = None):
        self.config = config or Config()
        sim = self.config.sim
        self.mode = sim.mode
        self.cfg = self.config.sim_config()
        self.n_nodes = self.cfg.n_nodes
        self.n_origins = self.cfg.n_origins
        self.n_cells = self.cfg.n_cells

        if self.mode == "scale":
            from corrosion_tpu.sim.scale_step import (
                ScaleRoundInput,
                ScaleSimState,
                scale_sim_step,
            )

            self._state = ScaleSimState.create(self.cfg)
            self._quiet = ScaleRoundInput.quiet(self.cfg)
            self._step_fn = (
                lambda st, net, key, inp:
                scale_sim_step(self.cfg, st, net, key, inp)
            )
        else:
            from corrosion_tpu.sim.step import RoundInput, SimState, sim_step

            self._state = SimState.create(self.cfg)
            self._quiet = RoundInput.quiet(self.cfg)
            self._step_fn = (
                lambda st, net, key, inp:
                sim_step(self.cfg, st, net, key, inp)
            )
        self._step = jax.jit(self._step_fn)

        from corrosion_tpu.sim.transport import NetModel

        self._net = NetModel.create(
            self.n_nodes,
            drop_prob=self.config.gossip.drop_prob,
            n_regions=self.config.gossip.n_regions,
        )
        self._key = jr.key(sim.seed)
        self._bootstrap_from_members_file()

        self.metrics = Registry()
        self.locks = LockRegistry(logger=logger)
        self.tripwire = Tripwire()
        self._input_lock = self.locks.lock("agent.pending_inputs")
        self._snap_lock = self.locks.lock("agent.snapshot")

        # pending per-node inputs for the next round (host-side staging).
        # Writes queue in per-node FIFOs — one *transaction* (up to
        # tx_max_cells cells, committed atomically under one db_version)
        # enters the round per node per tick, the array analog of the
        # reference's broadcast batching queue (``broadcast/mod.rs:395-408``)
        # + chunked-changeset commit (``public/mod.rs:177-256``).
        n = self.n_nodes
        self._tx_k = max(1, getattr(self.cfg, "tx_max_cells", 1))
        # node -> list of ([(cell, val, clp)...], event|None, final).
        # Chunks of one write_many transaction SHARE the event (the
        # waiter handle) but only the final chunk wakes it on commit;
        # the shared handle lets a failed round drop the WHOLE
        # transaction — flagging the waiter and purging queued
        # trailing chunks — instead of committing it partially.
        self._write_queues: dict = {}
        # API-boundary hybrid logical clocks, one per writer node: every
        # transaction is stamped on entry (crsql_set_ts analog,
        # public/mod.rs:88-100); the in-round clock lives device-side as
        # CrdtState.hlc and folds through ingest + sync handshakes
        self._hlc = {node: HLClock(node) for node in range(self.n_origins)}
        self._pend_kill = np.zeros(n, bool)
        self._pend_revive = np.zeros(n, bool)
        self._pend_partition: Optional[np.ndarray] = None
        self._pend_restore = None  # (state, applied-Event) | None

        self.round_no = 0
        self._round_cv = threading.Condition()
        self._snapshot_host = None  # (round_no, store planes, heads, alive)
        self._thread = None
        self._listeners = []  # subscription manager hooks

        # --- round-carry donation (ISSUE 9 satellite) -------------------
        # with donation the round dispatch CONSUMES self._state's
        # buffers (the scan carry is the HBM working set at flagship
        # scale — an un-donated dispatch holds two copies). Readers and
        # the donated dispatch are therefore mutually exclusive: a
        # reader holds the state lease while copying, the round thread
        # waits for zero leases before a donated dispatch and marks the
        # state busy until the new carry is committed.
        self._donate_rounds = bool(
            getattr(self.config.perf, "donate_rounds", True))
        self._donate_effective = False  # decided at start()
        self._state_cv = threading.Condition()
        self._state_readers = 0
        self._state_busy = False

        # --- recovery / supervision (resilience subsystem) --------------
        # generation fences stale state: every applied restore bumps it,
        # and a round result computed against an older generation is
        # discarded at commit instead of clobbering the restored state
        self.generation = 0
        self._supervisor = None  # optional watchdog around dispatch
        # the attached Database registers itself here so checkpoint
        # recovery restores the HOST state (schema, heap, rows) together
        # with the device state — a rewound cluster must not keep
        # serving rows it no longer holds
        self.recovery_db = None
        self._auto_recover = False
        self._recovering = False  # True while a checkpoint restore runs
        self._consec_failures = 0
        self._max_recoveries = 3  # consecutive failed rounds before giving up

    def _bootstrap_from_members_file(self) -> None:
        """Replay a persisted member list into the fresh SWIM state — the
        ``__corro_members`` bootstrap (``initialise_foca``'s ApplyMany
        from the DB, ``util.rs:69-130``): a restarted cluster starts from
        yesterday's membership instead of only the static seed set. The
        maintenance loop keeps the file fresh (``broadcast/mod.rs:814-949``
        persists foca state diffs every 60 s)."""
        import json
        import os

        path = getattr(self.config.db, "members_path", "")
        if not path or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                dump = json.load(f)
            members = [
                (int(m[0]), int(m[1]))
                for m in dump.get("members", [])
                if 0 <= int(m[0]) < self.n_nodes
            ]
        except (OSError, ValueError, KeyError, TypeError, IndexError):
            logger.exception("members bootstrap file unreadable; skipping")
            return
        if not members:
            return
        ids = [m[0] for m in members]
        incs = [m[1] for m in members]
        if self.mode == "scale":
            from corrosion_tpu.sim.scale import bootstrap_members
        else:
            from corrosion_tpu.sim.swim import bootstrap_members
        self._state = self._state._replace(
            swim=bootstrap_members(self._state.swim, ids, incs)
        )
        logger.info("bootstrapped %d members from %s", len(members), path)

    def persist_members(self, path: str) -> None:
        """Dump the alive member list (id, incarnation) for restart
        bootstrap — the ``__corro_members`` upsert. Reads only the two
        [N] liveness vectors (not the full store snapshot — at 100k that
        transfer is hundreds of MB the maintenance tick must not pay)."""
        import json
        import os

        with self._state_lease():
            st = self._state
            alive = np.asarray(st.swim.alive)
            inc = np.asarray(
                getattr(st.swim, "inc",
                        getattr(st.swim, "incarnation", None))
            )
            # materialized to python ints INSIDE the lease: under round
            # donation the views above die with the next dispatch
            members = [
                [int(i), int(inc[i])] for i in np.nonzero(alive)[0]
            ]
        tmp = f"{path}.tmp"
        with open(tmp, "w") as f:
            json.dump({"round": self.round_no, "members": members}, f)
        os.replace(tmp, path)

    # --- lifecycle ------------------------------------------------------
    def start(self, pace_seconds: float = 0.0, auto_recover: bool = False,
              supervisor=None):
        """Boot the round loop.

        ``auto_recover`` restores the newest valid checkpoint under
        ``config.db.path`` before the first round (missing/corrupt
        checkpoints are skipped — a fresh cluster boots clean), and
        re-arms after a mid-run round failure: the loop rolls back to
        the last good checkpoint instead of dying, up to
        ``_max_recoveries`` consecutive failures.

        ``supervisor`` (a ``resilience.Supervisor``) wraps every device
        dispatch with its deadline + jittered-retry policy."""
        if self._thread is not None:
            raise RuntimeError("agent already started")
        if supervisor is not None:
            self._supervisor = supervisor.bind_abort(
                lambda: self.tripwire.tripped, sleep=self.tripwire.wait
            )
        self._auto_recover = auto_recover
        # donate the round carry (config.perf.donate_rounds) when a
        # failed donated dispatch has a re-upload story: either no
        # supervisor retries it (failures already kill or roll back the
        # loop) or auto_recover's checkpoint rollback restores the carry
        # — the same rule the segmented runner applies (a supervised run
        # without a snapshot keeps donation off)
        self._donate_effective = (
            self._donate_rounds
            and (self._supervisor is None or auto_recover)
        )
        if self._donate_effective:
            self._step = jax.jit(self._step_fn, donate_argnums=(0,))
        # hoist the fused-path probes out of the first round's trace
        # (docs/fused.md): path selection must never spawn an eager
        # probe from inside the (possibly donated) round dispatch
        from corrosion_tpu.ops import megakernel

        megakernel.prime_fused(self.cfg)
        # HBM-footprint gauges (ISSUE 11): the per-table audit is array
        # metadata only — no device transfer — and gives /metrics the
        # corro.mem.* series from boot
        from corrosion_tpu.obs.memory import (
            memory_report,
            publish_memory_gauges,
        )

        publish_memory_gauges(
            memory_report(self._state, self.n_nodes), self.metrics
        )
        if auto_recover:
            self.recover_latest()
        self._thread = spawn_counted(
            self._run_loop, pace_seconds, name="corro-agent-round-loop"
        )
        return self

    def recover_latest(self, root: Optional[str] = None,
                       db=None) -> Optional[dict]:
        """Restore from the newest checkpoint under ``root`` (default
        ``config.db.path``) that passes integrity verification AND is
        config-compatible AND actually restores — candidates failing any
        of those gates are logged and skipped for the next-newest, so a
        bad newest side never masks an older good recovery point. Stale
        in-flight state is fenced by the generation bump the restore
        applies. Returns the restored manifest, or None when nothing
        restorable exists. This is the ONE recovery path: boot-time
        resume (``MaintenanceLoop.resume_latest``) and mid-run crash
        rollback both land here."""
        import json
        import os

        from corrosion_tpu.checkpoint import (
            config_identity,
            restore_checkpoint,
        )
        from corrosion_tpu.resilience.retention import (
            iter_valid_checkpoints,
        )

        root = root or self.config.db.path
        db = db if db is not None else self.recovery_db
        self._recovering = True
        try:
            for path in iter_valid_checkpoints(root):
                # manifest-only read for the config gate: verification
                # already deserialized the full state once and the
                # restore will again — don't pay a third decode here.
                # Identity excludes execution-only keys (``fused``): a
                # checkpoint written under another execution mode is
                # bitwise-compatible state
                with open(os.path.join(path, "manifest.json")) as f:
                    manifest = json.load(f)
                if (config_identity(manifest["sim_config"])
                        != config_identity(self.cfg)):
                    logger.error(
                        "checkpoint %s has a different sim config than "
                        "this agent; trying the next-newest", path,
                    )
                    continue
                try:
                    # the iterator already ran the full hash pass on this
                    # path — don't hash/decompress the state a second time
                    man = restore_checkpoint(self, path, db=db,
                                             verify=False)
                except Exception:  # noqa: BLE001 — try the next-newest
                    logger.exception(
                        "checkpoint %s is unrestorable; trying the "
                        "next-newest", path,
                    )
                    continue
                man["path"] = path
                if self._thread is None:
                    # boot-time recover: resume the round counter at the
                    # saved round (a live loop keeps its own monotonic
                    # counter for waiters)
                    self.round_no = int(man.get("round", self.round_no))
                logger.info(
                    "recovered from %s (round %d, generation %d)",
                    path, man["round"], self.generation,
                )
                return man
            return None
        finally:
            self._recovering = False

    def shutdown(self):
        self.tripwire.trip()
        if self._thread is not None:
            self._thread.join(timeout=30)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.shutdown()
        return False

    # --- the round loop -------------------------------------------------
    def _run_loop(self, pace_seconds: float):
        try:
            while not self.tripwire.tripped:
                t0 = time.perf_counter()
                try:
                    self._one_round()
                    self._consec_failures = 0
                except Exception:  # noqa: BLE001 — recovery decides below
                    if not self._auto_recover:
                        raise
                    self._consec_failures += 1
                    if self._consec_failures > self._max_recoveries:
                        logger.error(
                            "round failed %d times in a row; giving up",
                            self._consec_failures,
                        )
                        raise
                    logger.exception(
                        "round failed; rolling back to the last good "
                        "checkpoint (recovery %d/%d)",
                        self._consec_failures, self._max_recoveries,
                    )
                    if self.recover_latest() is None:
                        logger.error(
                            "no restorable checkpoint under %r; shutting "
                            "down", self.config.db.path,
                        )
                        raise
                    # the rollback re-uploaded a valid state — reopen
                    # the reader window a consumed-carry failure left
                    # closed (the generation fence discards the round)
                    self._set_state_busy(False)
                    continue
                if pace_seconds > 0:
                    left = pace_seconds - (time.perf_counter() - t0)
                    if left > 0 and self.tripwire.wait(left):
                        break
        except Exception:  # noqa: BLE001 — a dead loop must not look alive
            logger.exception("round loop crashed; tripping shutdown")
        finally:
            self.tripwire.trip()
            # never leave readers parked on a dead loop's busy window; a
            # reader that then copies a consumed carry gets a loud
            # deleted-buffer error from a dying agent, not a deadlock
            self._set_state_busy(False)
            # wake everything parked on us: queued writers, round waiters,
            # and any restore staged after the last round started
            with self._input_lock:
                self._apply_pend_restore()
                for q in self._write_queues.values():
                    for _cells, ev in q:
                        if ev is not None:
                            # never entered a round — the wake must read
                            # as a drop, not a commit
                            ev.dropped = True
                            ev.set()
                self._write_queues.clear()
            with self._round_cv:
                self._round_cv.notify_all()

    def _apply_pend_restore(self):
        """Apply a staged restore. Callers must hold ``_input_lock``; only
        the round thread (or a caller when no round thread runs) may call
        this, so the swap never races an in-flight step."""
        if self._pend_restore is None:
            return
        state, ev, box = self._pend_restore
        self._pend_restore = None
        # jnp.array, NOT asarray: the upload must be an owned device
        # copy. asarray zero-copy-adopts 64-byte-aligned numpy buffers
        # (npz-loaded checkpoint leaves routinely are), and the next
        # DONATED round dispatch would then free numpy-owned memory —
        # observed as glibc heap corruption, not a clean error
        self._state = jax.tree.map(jnp.array, state)
        # fence: any round result computed against the pre-restore state
        # is now stale and must not commit over this one
        self.generation += 1
        box["applied"] = True
        ev.set()

    def _set_state_busy(self, value: bool) -> None:
        with self._state_cv:
            self._state_busy = value
            if not value:
                self._state_cv.notify_all()

    def _carry_consumed(self) -> bool:
        """True when a donated dispatch consumed ``self._state``'s
        buffers (the tree then holds deleted arrays until a restore or
        commit replaces it)."""
        from corrosion_tpu.parallel.mesh import buffers_donated

        return buffers_donated(self._state)

    @contextlib.contextmanager
    def _state_lease(self):
        """Reader lease on the live device state.

        With round-carry donation the dispatch CONSUMES ``self._state``'s
        buffers mid-round; a reader that copied concurrently would read
        freed device memory. The lease excludes readers from the donated
        dispatch window (and vice versa) — readers must take OWNED
        copies before releasing it. Un-donated agents skip the gate
        entirely (immutable old buffers stay valid, today's behavior)."""
        if not self._donate_effective:
            yield
            return
        with self._state_cv:
            self._state_cv.wait_for(lambda: not self._state_busy)
            self._state_readers += 1
        try:
            yield
        finally:
            with self._state_cv:
                self._state_readers -= 1
                self._state_cv.notify_all()

    def _run_step(self, st, net, sub, inp):
        new_state, info = self._step(st, net, sub, inp)
        # completion inside the (possibly supervised) call: a wedged
        # device surfaces as a deadline miss, not a hang at next use
        jax.block_until_ready(new_state)
        return new_state, info

    def _dispatch(self, st, net, sub, inp):
        if self._supervisor is None:
            return self._run_step(st, net, sub, inp)
        if not self._donate_effective:
            return self._supervisor.call(
                self._run_step, st, net, sub, inp, label="round-dispatch"
            )

        def attempt():
            from corrosion_tpu.parallel.mesh import buffers_donated

            if buffers_donated(st):
                # the failed donated attempt consumed the carry — there
                # is nothing on-device to retry with. Propagate (non-
                # retryable) to the round loop, whose checkpoint
                # rollback + generation fence is the re-upload story
                # (start() only arms donation when that story exists).
                raise _CarryConsumed(
                    "donated round carry consumed by a failed dispatch"
                )
            return self._run_step(st, net, sub, inp)

        return self._supervisor.call(attempt, label="round-dispatch")

    def _one_round(self):
        with self._input_lock:
            self._apply_pend_restore()
            gen = self.generation
            n, k = self.n_nodes, self._tx_k
            write_mask = np.zeros(n, bool)
            write_cell = np.zeros(n, np.int32)
            write_val = np.zeros(n, np.int32)
            write_clp = np.zeros(n, np.int32)
            tx_mask = np.zeros(n, bool)
            tx_len = np.ones(n, np.int32)
            tx_cell = np.zeros((n, k), np.int32)
            tx_val = np.zeros((n, k), np.int32)
            tx_clp = np.zeros((n, k), np.int32)
            waiters = []
            drained = []
            for node, q in self._write_queues.items():
                cells, ev = q.pop(0)
                if len(cells) == 1:
                    cell, val, clp = cells[0]
                    write_mask[node] = True
                    write_cell[node] = cell
                    write_val[node] = val
                    write_clp[node] = clp
                else:  # multi-cell: one db_version, atomic remote apply
                    tx_mask[node] = True
                    tx_len[node] = len(cells)
                    for i, (cell, val, clp) in enumerate(cells):
                        tx_cell[node, i] = cell
                        tx_val[node, i] = val
                        tx_clp[node, i] = clp
                if ev is not None:
                    waiters.append(ev)
                if not q:
                    drained.append(node)
            for node in drained:
                del self._write_queues[node]
            # np.array copies: jnp.asarray may alias the staging buffers
            # (zero-copy on the CPU backend) which we zero right below
            inp = self._quiet._replace(
                write_mask=jnp.asarray(write_mask),
                write_cell=jnp.asarray(write_cell),
                write_val=jnp.asarray(write_val),
                write_clp=jnp.asarray(write_clp),
                kill=jnp.asarray(np.array(self._pend_kill)),
                revive=jnp.asarray(np.array(self._pend_revive)),
            )
            if k > 1:
                inp = inp._replace(
                    tx_mask=jnp.asarray(tx_mask),
                    tx_len=jnp.asarray(tx_len),
                    tx_cell=jnp.asarray(tx_cell),
                    tx_val=jnp.asarray(tx_val),
                    tx_clp=jnp.asarray(tx_clp),
                )
            net = self._net
            if self._pend_partition is not None:
                net = net._replace(partition=jnp.asarray(self._pend_partition))
                self._net = net
                self._pend_partition = None
            self._pend_kill[:] = False
            self._pend_revive[:] = False

        with RoundTimer("round", warn_seconds=1.0, registry=self.metrics,
                        logger=logger):
            self._key, sub = jr.split(self._key)
            if self._donate_effective:
                # the dispatch is about to consume self._state's buffers
                # — wait out in-flight readers, then close the reader
                # window until the new carry is committed (the window
                # stays closed on a consumed-carry failure; _run_loop
                # reopens it once recovery put a valid state back)
                with self._state_cv:
                    self._state_cv.wait_for(
                        lambda: self._state_readers == 0)
                    self._state_busy = True
            try:
                new_state, info = self._dispatch(
                    self._state, net, sub, inp)
            except BaseException:
                # the drained writes die with the failed round (recovery
                # rolls back past them like any post-checkpoint write) —
                # wake their waiters now; they were popped off
                # _write_queues, so the shutdown sweep can't reach them
                # and they'd otherwise block out their full timeout. The
                # flag turns the wake into a clear error at the caller
                # instead of a false success.
                for ev in waiters:
                    ev.dropped = True
                    ev.set()
                if self._donate_effective and not self._carry_consumed():
                    self._set_state_busy(False)
                raise

        with self._input_lock:
            listeners = list(self._listeners)
            if self.generation != gen:
                # a restore applied while this round was in flight (e.g.
                # crash recovery rolling back): its result was computed
                # against pre-restore state — fence it out. Writes that
                # entered this round roll back with it, exactly like any
                # write committed after the checkpoint being restored;
                # their waiters are woken (flagged, so the caller gets a
                # clear error rather than a false success) instead of
                # hanging
                logger.warning(
                    "round result fenced: generation %d -> %d",
                    gen, self.generation,
                )
                for ev in waiters:
                    ev.dropped = True
                    ev.set()
                # self._state is the restored (valid) tree — reopen the
                # reader window the donated dispatch closed
                self._set_state_busy(False)
                return
            self._state = new_state
        # the new carry is committed: readers may copy again
        self._set_state_busy(False)

        vals = {k: float(v) for k, v in info.items()}
        record_round_info(vals, registry=self.metrics)
        # inline always/sometimes probes (the Antithesis instrumentation
        # seam, SURVEY §4): invariants log+count, liveness is aggregated
        assert_always(
            all(v >= 0 for v in vals.values()),
            "round counters non-negative",
            str({k: v for k, v in vals.items() if v < 0}),
        )
        assert_sometimes(vals.get("syncs", 0) > 0,
                         "nodes sync with other nodes")
        assert_sometimes(vals.get("delivered", 0) > 0,
                         "broadcasts deliver changes")
        assert_sometimes(vals.get("acked", 0) > 0,
                         "SWIM probes are acked")
        # invalidate the cached snapshot BEFORE waking round waiters, so a
        # woken wait_rounds() caller never reads pre-round state
        with self._snap_lock:
            self._snapshot_host = None
        with self._round_cv:
            self.round_no += 1
            self._round_cv.notify_all()
        for ev in waiters:
            ev.set()
        for hook in listeners:
            try:
                hook(self.round_no)
            except Exception:  # noqa: BLE001 — a bad subscriber must not kill the loop
                logger.exception("round listener failed")

    def wait_rounds(self, k: int = 1, timeout: float = 30.0) -> bool:
        """Block until ``k`` more rounds completed (False on timeout or
        shutdown)."""
        with self._round_cv:
            target = self.round_no + k
            return self._round_cv.wait_for(
                lambda: self.round_no >= target or self.tripwire.tripped,
                timeout,
            ) and self.round_no >= target

    def add_round_listener(self, hook):
        # under _input_lock: registration is how the pubsub managers
        # PUBLISH themselves (and everything they built) to the round
        # thread — an unlocked append would hand the hook over with no
        # happens-before edge to its owner's construction (corrosan)
        with self._input_lock:
            self._listeners.append(hook)

    def remove_round_listener(self, hook) -> None:
        with self._input_lock:
            if hook in self._listeners:
                self._listeners.remove(hook)

    # --- write path (transactions) --------------------------------------
    def write(self, node: int, cell: int, value: int, wait: bool = True,
              timeout: float = 30.0) -> dict:
        """One-cell write transaction at ``node`` (must be an origin).

        Returns ``{rows_affected, round}`` after the write entered a round
        (the reference returns once committed locally; dissemination is
        async, ``public/mod.rs:177-256``)."""
        return self.write_many(node, [(cell, value)], wait=wait, timeout=timeout)

    def write_many(self, node: int, cells, wait: bool = True,
                   timeout: float = 30.0) -> dict:
        """Multi-cell transaction at ``node``: a list of ``(cell, value)``
        or ``(cell, value, clp)`` where ``clp`` is the causal-length row
        lifetime of the write (the DB layer stamps it; raw writes default
        to 0).

        Up to ``tx_max_cells`` cells commit atomically under one
        db_version and are disseminated as a chunked changeset — remote
        nodes buffer the chunks and never observe the transaction torn
        (``public/mod.rs:177-256`` + ``util.rs:546-696``). Repeated
        cells collapse to the last write (the transaction overlay
        already resolved dependent statements); transactions larger than
        ``tx_max_cells`` split into several versions, each atomic —
        whole-transaction atomicity then requires the DB layer's
        chunking (a size cap the reference does not have; its chunks
        share one version). With ``wait`` the call returns once the last
        chunk entered a round."""
        if not (0 <= node < self.n_origins):
            raise ValueError(
                f"node {node} is not a writer (origins are 0..{self.n_origins - 1})"
            )
        cells = [(c[0], c[1], c[2] if len(c) > 2 else 0) for c in cells]
        if not cells:
            return {"rows_affected": 0, "round": self.round_no}
        for cell, _, _ in cells:
            if not (0 <= cell < self.n_cells):
                raise ValueError(f"cell {cell} out of range (n_cells={self.n_cells})")
        if self.tripwire.tripped:
            raise RuntimeError("agent is shut down")
        # a version's cells must be distinct (one clock row per cell) —
        # later statements already observed earlier ones via the tx
        # overlay, so last-write-wins within the transaction
        dedup: dict = {}
        for cell, value, clp in cells:
            dedup[int(cell)] = (int(cell), int(value), int(clp))
        flat = list(dedup.values())
        chunks = [flat[i:i + self._tx_k] for i in range(0, len(flat), self._tx_k)]
        ts = self._hlc[node].new_timestamp()  # stamp on entry (crsql_set_ts)
        ev = threading.Event()
        with self._input_lock:
            q = self._write_queues.setdefault(node, [])
            for chunk in chunks[:-1]:
                q.append((chunk, None))
            q.append((chunks[-1], ev))
        if wait:
            if not ev.wait(timeout):
                raise TimeoutError("write did not enter a round in time")
            if getattr(ev, "dropped", False):
                # the round that drained this write failed, was fenced
                # out by a recovery rollback, or the agent shut down —
                # the write did NOT commit; the caller must retry
                raise RuntimeError(
                    "write was dropped before it committed (round "
                    "failure, recovery rollback, or shutdown) — retry"
                )
        return {"rows_affected": len(cells), "round": self.round_no,
                "ts": str(ts)}

    # --- fault injection (admin surface) --------------------------------
    def kill_node(self, node: int):
        with self._input_lock:
            self._pend_kill[node] = True

    def revive_node(self, node: int):
        with self._input_lock:
            self._pend_revive[node] = True

    def set_partition(self, groups: np.ndarray):
        """Assign partition group per node (same group = connected)."""
        groups = np.asarray(groups, np.int32)
        if groups.shape != (self.n_nodes,):
            raise ValueError(
                f"partition groups shape {groups.shape} != "
                f"({self.n_nodes},)"
            )
        with self._input_lock:
            self._pend_partition = groups

    def heal_partition(self):
        self.set_partition(np.zeros(self.n_nodes, np.int32))

    def set_cluster_id(self, cluster_id: int, nodes=None):
        """Stamp ``nodes`` (default: all) with a ClusterId. Mismatched
        payloads stop delivering — the uni-drop / sync-rejection gate
        (``uni.rs:75-77``, ``peer/mod.rs:1425-1436``); settable live via
        admin (``corro-admin/src/lib.rs:135-140``)."""
        with self._input_lock:
            ids = np.asarray(self._net.cluster_id)
            if nodes is None:
                ids = np.full(self.n_nodes, int(cluster_id), np.int32)
            else:
                ids = ids.copy()
                for node in nodes:
                    node = int(node)
                    if not (0 <= node < self.n_nodes):
                        raise ValueError(
                            f"node {node} out of range (n_nodes={self.n_nodes})"
                        )
                    ids[node] = int(cluster_id)
            self._net = self._net._replace(cluster_id=jnp.asarray(ids))

    def set_regions(self, regions: np.ndarray):
        """Assign geographic region per node (drives the RTT rings).
        Applied between rounds, like partitions."""
        regions = np.asarray(regions, np.int32)
        if regions.shape != (self.n_nodes,):
            raise ValueError(
                f"regions shape {regions.shape} != ({self.n_nodes},)"
            )
        with self._input_lock:
            self._net = self._net._replace(region=jnp.asarray(regions))

    # --- checkpoint / restore -------------------------------------------
    def device_state(self):
        """The current device-state pytree (read-only for checkpointing;
        the round thread owns the live copy).

        While the donated round loop is live there is only ONE device
        copy of the state and the next dispatch consumes it — a raw
        reference would read freed buffers mid-serialization — so this
        returns an OWNED host copy taken under the state lease. With
        the loop stopped (or donation off) the immutable device tree is
        returned directly, as before."""
        if not (self._donate_effective and self._thread is not None
                and self._thread.is_alive()):
            return self._state
        with self._state_lease():
            return jax.tree.map(lambda a: np.array(a), self._state)

    def restore_state(self, state, timeout: float = 60.0) -> bool:
        """Swap in a new device-state pytree under a live round loop —
        the ``sqlite3-restore`` analog (byte-lock swap of the DB under a
        running agent). The swap is staged and applied at the next round
        boundary by the round thread itself (never racing an in-flight
        step); with no round thread it applies inline. Returns True once
        applied; False if it timed out or was superseded by a newer
        restore — in both failure cases the staged state is withdrawn."""
        ev = threading.Event()
        box = {"applied": False}
        with self._input_lock:
            if self._pend_restore is not None:
                # supersede: wake the earlier caller un-applied
                _, old_ev, _old_box = self._pend_restore
                self._pend_restore = None
                old_ev.set()
            self._pend_restore = (state, ev, box)
            loop_running = self._thread is not None and self._thread.is_alive()
            if not loop_running or threading.current_thread() is self._thread:
                # no round thread — or we ARE it (crash recovery between
                # rounds): apply inline; waiting on the next round
                # boundary would deadlock
                self._apply_pend_restore()
        ok = ev.wait(timeout) and box["applied"]
        if ok:
            with self._snap_lock:
                self._snapshot_host = None
        else:
            with self._input_lock:
                if (self._pend_restore is not None
                        and self._pend_restore[1] is ev):
                    self._pend_restore = None
        return ok

    def soak(self, rounds: int, segment_rounds: int = 128,
             checkpoint_root: Optional[str] = None, keep_last: int = 3,
             write_frac: float = 0.0, resume: bool = False,
             donate: bool = True, async_checkpoint: bool = True,
             supervisor=None, inputs=None, mesh=None, obs=None):
        """Throughput soak dispatch: run ``rounds`` rounds from the
        agent's current state through the segmented runner
        (:func:`corrosion_tpu.resilience.segments.run_segmented`) — the
        scan carry is buffer-donated across segment boundaries and
        checkpoints drain on the overlapped background writer — then
        adopt the final carry as the agent's state (round counter
        advances by the completed rounds; the generation fence bumps so
        any stale in-flight result cannot commit over it).

        The round loop must be stopped: a live round's in-flight carry
        would race the donated buffers. The agent's own state buffers
        are never donated (the runner's first segment runs un-donated),
        so an aborted soak leaves the agent usable at the runner's last
        good carry. ``resume=True`` continues from the newest valid
        checkpoint under ``checkpoint_root`` instead of the live state.

        ``mesh`` shards the soak over a device mesh: state, net and
        inputs are placed with ``P("node")`` specs, checkpoints drain
        per shard, and a resume re-places the recorded slices against
        THIS mesh whatever topology the interrupted run had (elastic
        restore, docs/checkpoints.md).

        ``obs`` is a :class:`corrosion_tpu.obs.flight.SoakObserver`
        (caller-owned). With ``obs=None`` one is built from
        ``config.obs`` ([obs] flight_path / prometheus_port /
        jax_profile) — or, with that section idle, a bridge-only
        observer onto the agent's OWN metrics registry, so a soak
        always advances ``corro.soak.rounds_total`` on this agent's
        ``/metrics`` route; an agent-built observer is closed before
        returning.
        """
        # real errors, not asserts (python -O strips asserts, and a live
        # round's in-flight carry racing the donated segment buffers
        # corrupts state instead of failing loudly)
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("stop the round loop before a soak dispatch")
        if resume and not checkpoint_root:
            raise ValueError("resume needs a checkpoint root")
        from corrosion_tpu.resilience.segments import (
            make_soak_inputs,
            resume_segmented,
            run_segmented,
        )

        if inputs is None:
            inputs = make_soak_inputs(
                self.cfg, jr.key(self.config.sim.seed + 1), rounds,
                write_frac=write_frac, mode=self.mode,
            )
        net = self._net
        st = self._state
        if mesh is not None:
            from corrosion_tpu.parallel.mesh import shard_state

            # placement copies: the agent's own buffers stay valid (and
            # un-donated) whatever happens to the sharded run
            inputs = shard_state(mesh, self.n_nodes, inputs)
            net = shard_state(mesh, self.n_nodes, net)
            if not resume:
                st = shard_state(mesh, self.n_nodes, st)
        owned_obs = None
        if obs is None:
            from corrosion_tpu.obs.flight import SoakObserver, make_observer

            # serve_registry = the agent's own metrics: the admission
            # controller and subscription shed counters publish there,
            # so a production soak's flight record carries its shed
            # story (docs/observability.md)
            owned_obs = (make_observer(self.config.obs,
                                       registry=self.metrics,
                                       serve_registry=self.metrics)
                         or SoakObserver(registry=self.metrics,
                                         serve_registry=self.metrics))
            obs = owned_obs
        common = dict(
            mode=self.mode, checkpoint_root=checkpoint_root,
            keep_last=keep_last, db=self.recovery_db,
            supervisor=supervisor or self._supervisor,
            donate=donate, async_checkpoint=async_checkpoint, obs=obs,
        )
        try:
            if resume:
                result = resume_segmented(
                    self.cfg, net, inputs, segment_rounds, mesh=mesh,
                    **common
                )
            else:
                result = run_segmented(
                    self.cfg, st, net, self._key, inputs,
                    segment_rounds, **common,
                )
        finally:
            if owned_obs is not None:
                owned_obs.close()
        adopted = result.state
        if any(isinstance(leaf, np.ndarray)
               for leaf in jax.tree.leaves(adopted)):
            # host-resident leaves (a resume that had nothing left to
            # run returns the loaded checkpoint as-is): upload as OWNED
            # device copies — a restarted donated round loop must never
            # donate an adopted numpy buffer (see _apply_pend_restore)
            adopted = jax.tree.map(jnp.array, adopted)
        with self._input_lock:
            self._state = adopted
            self._key = result.key
            if resume:
                # completed_rounds is ABSOLUTE within the input stack
                # (start_round included) and the adopted state replaces
                # this agent's, it doesn't extend it — adding would
                # double-count the pre-crash rounds
                self.round_no = result.completed_rounds
            else:
                self.round_no += result.completed_rounds
            self.generation += 1
        with self._snap_lock:
            self._snapshot_host = None
        return result

    def memory_report(self) -> dict:
        """Per-table nbytes audit of the live device state
        (``obs/memory.py``) — metadata only, no device transfer. Taken
        under the state lease so a donated round dispatch never
        invalidates the leaves mid-walk. Served at ``/v1/obs/memory``;
        the same audit feeds the boot-time ``corro.mem.*`` gauges."""
        from corrosion_tpu.obs.memory import memory_report

        with self._state_lease():
            return memory_report(self._state, self.n_nodes)

    # --- health / readiness (feeds /v1/health + /v1/ready) ---------------
    def health(self) -> dict:
        """Liveness + readiness summary.

        ``status``: ``ok`` (serving), ``restoring`` (a checkpoint
        restore is staged or being applied), ``backoff`` (the watchdog
        supervisor is between dispatch retries), ``down`` (tripped).
        ``retry_after`` (seconds, present when not ok) feeds the HTTP
        ``Retry-After`` header."""
        with self._input_lock:
            restoring = self._pend_restore is not None or self._recovering
        sup = self._supervisor
        sup_state = sup.state if sup is not None else "idle"
        if self.tripwire.tripped:
            status = "down"
        elif restoring:
            status = "restoring"
        elif sup_state == "backoff":
            status = "backoff"
        else:
            status = "ok"
        out = {
            "status": status,
            "ready": status == "ok",
            "round": self.round_no,
            "generation": self.generation,
            "mode": self.mode,
            "n_nodes": self.n_nodes,
        }
        if sup is not None:
            out["supervisor"] = {
                "state": sup_state,
                "retries": sup.retries,
                "aborts": sup.aborts,
            }
        if status == "backoff":
            out["retry_after"] = max(1, int(round(sup.retry_after_seconds())))
        elif status != "ok":
            out["retry_after"] = 1
        return out

    # --- read path ------------------------------------------------------
    def snapshot(self) -> dict:
        """Host copy of cluster state: store planes, heads, liveness.

        Device->host transfer happens at most once per round (lazy)."""
        with self._snap_lock:
            if self._snapshot_host is not None:
                return self._snapshot_host
            round_no = self.round_no
        # device->host transfer happens OUTSIDE the snapshot lock so the
        # round thread's invalidation never stalls behind a large copy.
        # Under round-carry donation the copies ride the state lease and
        # must be OWNED (np.array): the cached snapshot outlives the
        # lease, and a CPU-backend asarray view would read freed memory
        # once the next dispatch consumes the buffers.
        copy = np.array if self._donate_effective else np.asarray
        with self._state_lease():
            st = self._state
            store = tuple(copy(p) for p in st.crdt.store)
            snap = {
                "round": round_no,
                "store": store,  # (ver, val, site, dbv) planes [N, n_cells]
                "head": copy(st.crdt.book.head),
                "known_max": copy(st.crdt.book.known_max),
                "hlc": copy(st.crdt.hlc),
                "alive": copy(st.swim.alive),
                "incarnation": copy(
                    getattr(st.swim, "inc",
                            getattr(st.swim, "incarnation", None))
                ),
            }
        with self._snap_lock:
            if self._snapshot_host is None and self.round_no == round_no:
                self._snapshot_host = snap
            return snap

    def read_cell(self, node: int, cell: int) -> dict:
        snap = self.snapshot()
        return {
            "value": int(snap["store"][1][node, cell]),
            "col_version": int(snap["store"][0][node, cell]),
            "site": int(snap["store"][2][node, cell]),
            "db_version": int(snap["store"][3][node, cell]),
            "cl_lifetime": int(snap["store"][4][node, cell]),
        }

    def node_rows(self, node: int) -> np.ndarray:
        """One node's replica as [n_rows, n_cols] values."""
        snap = self.snapshot()
        return snap["store"][1][node].reshape(self.cfg.n_rows, self.cfg.n_cols)

    # --- cluster introspection (admin sync state dump) -------------------
    def sync_state(self, node: int) -> dict:
        """``corrosion sync generate`` analog: heads + needs per origin.

        Need = known_max - head, an upper bound: versions already sitting
        in the node's out-of-order buffer still count as needed until
        applied (the precise count is ``ops.versions.needs_count``, which
        requires the buffer planes; the snapshot deliberately omits them)."""
        snap = self.snapshot()
        needs = np.maximum(
            snap["known_max"][node] - snap["head"][node], 0
        )
        from corrosion_tpu.sim.broadcast import HLC_ROUND_BITS

        hlc = int(snap["hlc"][node])
        return {
            "actor_id": node,
            "heads": {str(o): int(h) for o, h in enumerate(snap["head"][node])},
            "need": {
                str(o): int(v) for o, v in enumerate(needs) if v > 0
            },
            # the node's HLC as round.logical (the sync handshake's clock
            # message, peer/mod.rs:1439-1458)
            "ts": f"{hlc >> HLC_ROUND_BITS}.{hlc & ((1 << HLC_ROUND_BITS) - 1)}",
        }

    def members(self) -> list:
        """Member dump incl. region + RTT ring relative to node 0 (the
        reference's members dump shows per-peer ring membership)."""
        from corrosion_tpu.sim.transport import RING_RTT_MS, ring_of

        snap = self.snapshot()
        ids = np.arange(self.n_nodes, dtype=np.int32)
        rings = np.asarray(
            ring_of(self._net, jnp.zeros(self.n_nodes, jnp.int32),
                    jnp.asarray(ids))
        )
        regions = np.asarray(self._net.region)
        return [
            {"id": i, "state": "Alive" if bool(a) else "Down",
             "incarnation": int(inc), "region": int(regions[i]),
             "ring": int(rings[i]),
             "rtt_ms": float(RING_RTT_MS[int(rings[i])])}
            for i, (a, inc) in enumerate(
                zip(snap["alive"], snap["incarnation"])
            )
        ]

    def converged(self) -> bool:
        """The check_bookkeeping predicate on the current snapshot."""
        snap = self.snapshot()
        alive = snap["alive"]
        if not alive.any():
            return True
        ref = int(np.argmax(alive))
        same = np.all(
            [np.all(p[alive] == p[ref], axis=1) for p in snap["store"]]
        )
        heads_eq = np.all(snap["head"][alive] == snap["head"][ref])
        no_needs = np.all(
            (snap["known_max"][alive] - snap["head"][alive]) <= 0
        )
        return bool(same and heads_eq and no_needs)
