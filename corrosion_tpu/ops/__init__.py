"""Jittable kernels shared by the simulator: LWW merge, version
bookkeeping, lexicographic segment reductions."""

from corrosion_tpu.ops.lww import (  # noqa: F401
    INT32_MIN,
    STATE_ALIVE,
    STATE_DOWN,
    STATE_SUSPECT,
    apply_changes_to_store,
    lex_max,
    lex_segment_argmax,
    lex_wins,
    merge_store,
    pack_inc_state,
    unpack_inc_state,
)
from corrosion_tpu.ops.versions import (  # noqa: F401
    Book,
    advance_heads,
    needs_count,
    raise_heads,
    record_versions,
)
