"""Fixed-capacity slot machinery — bounded queues/mailboxes under jit.

The reference is full of bounded queues with drop policies (change
processing queue cap 20k, broadcast queue with drop-oldest,
``crates/corro-types/src/config.rs:15-60``,
``crates/corro-agent/src/broadcast/mod.rs:410-812``). Under XLA every
shape is static, so those become fixed-width slot arrays plus two
primitives:

- ``alloc_slots``: place a batch of candidate items into free slots of
  per-row pools (overflow -> dropped, the drop policy);
- ``mailbox_pack``: regroup a flat, arbitrarily-addressed message batch
  into dense per-receiver rows (the "one channel per node" illusion),
  bounded per-receiver capacity, overflow dropped.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from corrosion_tpu.ops.dense import lookup_cols, scatter_cols_set


def alloc_slots(free, want):
    """Assign free slots of each row to wanting items of the same row.

    ``free``: bool [N, K] — free slots per row. ``want``: bool [N, M] —
    items wanting a slot. Returns ``(slot, placed)``: int32 [N, M] slot
    index per item (clipped garbage when not placed) and bool [N, M].
    Items beyond the free-slot supply are not placed (drop policy).
    """
    n, k = free.shape
    slot_order = jnp.argsort(~free, axis=1, stable=True).astype(jnp.int32)
    n_free = jnp.sum(free, axis=1).astype(jnp.int32)
    rank = (jnp.cumsum(want, axis=1) - 1).astype(jnp.int32)
    placed = want & (rank < n_free[:, None])
    slot = lookup_cols(slot_order, jnp.clip(rank, 0, k - 1))
    return slot, placed


def alloc_slots_evict(free, evict_key, want):
    """Like :func:`alloc_slots`, but the queue always admits new items:
    when free slots run out, occupied slots are sacrificed in ascending
    ``evict_key`` order — with ``evict_key = remaining transmission
    budget`` this is exactly the reference's broadcast-queue overflow
    policy, "drop the oldest most-sent changeset to make room"
    (``crates/corro-agent/src/broadcast/mod.rs:410-812``).

    Items beyond the total slot count K are still dropped.
    """
    n, k = free.shape
    key = jnp.where(free, jnp.int32(-2147483648), evict_key)
    slot_order = jnp.argsort(key, axis=1, stable=True).astype(jnp.int32)
    rank = (jnp.cumsum(want, axis=1) - 1).astype(jnp.int32)
    placed = want & (rank < k)
    slot = lookup_cols(slot_order, jnp.clip(rank, 0, k - 1))
    return slot, placed


def budget_mask(live, priority, allowed):
    """Keep only the ``allowed`` highest-``priority`` live slots per row —
    the per-round send-budget shaping (10 MiB/s governor analog,
    ``broadcast/mod.rs:460-463``): when a node has more queued changesets
    than budget, the least-sent (highest remaining budget) go first and
    the rest wait for a later round.

    ``allowed`` is a static int (same budget every row) or an int32 [N]
    array (per-row budgets, e.g. scaled by how many packets each sender
    delivers this round).
    """
    n, k = live.shape
    if isinstance(allowed, int):
        if allowed >= k:
            return live
        allowed = jnp.full((n,), allowed, jnp.int32)
    order = jnp.argsort(
        jnp.where(live, -priority, jnp.int32(2147483647)), axis=1, stable=True
    ).astype(jnp.int32)
    rank = scatter_rows(
        jnp.zeros((n, k), jnp.int32), order, jnp.ones((n, k), bool),
        jnp.broadcast_to(jnp.arange(k, dtype=jnp.int32)[None, :], (n, k)),
    )
    return live & (rank < allowed[:, None])


def scatter_rows(dest, slot, placed, values):
    """``dest[i, slot[i,j]] = values[i,j]`` where ``placed`` — one writer
    per (row, slot). Loop-scatter over the static slot axis (see
    ``ops/dense.py`` for why flat element scatters are avoided)."""
    return scatter_cols_set(dest, slot, values, placed)


def mailbox_pack(recv, valid, n_rows: int, capacity: int, fields):
    """Regroup flat messages into dense per-receiver mailboxes.

    ``recv`` int32 [M], ``valid`` bool [M], ``fields``: tuple of int32 [M]
    payload arrays. Returns ``(live, packed_fields)`` with shapes
    [n_rows, capacity]; messages past a receiver's capacity are dropped
    (bounded-queue semantics). Implemented as one sort by receiver plus a
    segmented rank — no per-receiver loops.
    """
    m = recv.shape[0]
    sort_key = jnp.where(valid, recv, jnp.int32(n_rows))
    order = jnp.argsort(sort_key, stable=True).astype(jnp.int32)
    r_s = sort_key[order]
    idx = jnp.arange(m, dtype=jnp.int32)
    is_start = jnp.concatenate([jnp.ones(1, bool), r_s[1:] != r_s[:-1]])
    run_start = jax.lax.cummax(jnp.where(is_start, idx, 0))
    rank = idx - run_start
    ok = (r_s < n_rows) & (rank < capacity)
    flat = jnp.where(ok, r_s * capacity + rank, n_rows * capacity)

    live = (
        jnp.zeros(n_rows * capacity, bool)
        .at[flat]
        .set(True, mode="drop")
        .reshape(n_rows, capacity)
    )
    packed = tuple(
        jnp.zeros(n_rows * capacity, f.dtype)
        .at[flat]
        .set(f[order], mode="drop")
        .reshape(n_rows, capacity)
        for f in fields
    )
    return live, packed
