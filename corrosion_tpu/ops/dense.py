"""Backend-adaptive column lookups/scatters over small static-width tables.

On the target TPU backend, per-ELEMENT index ops — ``take_along_axis``,
``x.reshape(-1)[flat]``, ``.at[flat].set/add/max`` — execute at ~9 ns per
element (measured: a [100k, 64] ``take_along_axis`` into a [100k, 16]
table costs ~59 ms, ~100x the bandwidth cost), while slices and
elementwise kernels run at full HBM speed. The protocol state is full of
tiny per-row tables (per-origin heads [N, 16], queue slots [N, 32],
member slots [N, 64]) indexed by data — so on TPU every such
lookup/scatter is re-expressed as a **static unrolled loop over the
table's columns** with elementwise compare+select, which XLA fuses into
a handful of full-bandwidth kernels.

On CPU the loop form is W× more arithmetic for a scalar core (and W×
the HLO to compile), so the element-indexed form is kept there. Both
forms are semantically identical — callers guarantee one writer per
(row, column) for set-scatters — and ``FORCE_DENSE`` pins a form for
differential unit tests.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from corrosion_tpu.ops.lww import (
    apply_changes_cols,
    apply_changes_to_store,
)

# None = decide by backend (dense loops everywhere except CPU);
# True/False pin the dense/element form (tests)
FORCE_DENSE: Optional[bool] = None


def _dense() -> bool:
    if FORCE_DENSE is not None:
        return FORCE_DENSE
    return jax.default_backend() != "cpu"


def _flat(idx, valid, n, w):
    # out-of-range indices are invalid on BOTH forms (the dense loop
    # ignores them structurally; mask here so the element form cannot
    # wrap into a neighboring row)
    valid = valid & (idx >= 0) & (idx < w)
    rows = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None], idx.shape)
    return jnp.where(valid, rows * w + idx, n * w)


def lookup_cols(table, idx, fill=0):
    """``out[n, m] = table[n, idx[n, m]]`` for a small static table width
    (``fill`` where idx is out of range) — replaces
    ``take_along_axis(table, idx, axis=1)``."""
    w = table.shape[1]
    in_range = (idx >= 0) & (idx < w)
    if not _dense():
        got = jnp.take_along_axis(table, jnp.clip(idx, 0, w - 1), axis=1)
        return jnp.where(in_range, got, jnp.asarray(fill, table.dtype))
    out = jnp.full(idx.shape, fill, table.dtype)
    for c in range(w):
        out = jnp.where(idx == c, table[:, c:c + 1], out)
    return out


def scatter_cols_max(dest, idx, vals, valid):
    """``dest[n, idx[n, m]] = max(dest, vals[n, m])`` where valid."""
    vals = vals.astype(dest.dtype)  # dtype-preserving (narrowed planes)
    n, w = dest.shape
    if not _dense():
        flat = _flat(idx, valid, n, w)
        return (
            dest.reshape(-1)
            .at[flat.reshape(-1)]
            .max(vals.reshape(-1), mode="drop")
            .reshape(n, w)
        )
    cols = []
    for c in range(w):
        m = valid & (idx == c)
        upd = jnp.max(jnp.where(m, vals, jnp.iinfo(vals.dtype).min), axis=1)
        cols.append(jnp.maximum(dest[:, c], upd))
    return jnp.stack(cols, axis=1)


def scatter_cols_add(dest, idx, vals, valid):
    """``dest[n, idx[n, m]] += vals[n, m]`` where valid."""
    vals = vals.astype(dest.dtype)  # dtype-preserving (narrowed planes)
    n, w = dest.shape
    if not _dense():
        flat = _flat(idx, valid, n, w)
        return (
            dest.reshape(-1)
            .at[flat.reshape(-1)]
            .add(vals.reshape(-1), mode="drop")
            .reshape(n, w)
        )
    cols = []
    for c in range(w):
        m = valid & (idx == c)
        cols.append(dest[:, c] + jnp.sum(jnp.where(m, vals, 0), axis=1))
    return jnp.stack(cols, axis=1)


def scatter_cols_set(dest, idx, vals, valid):
    """``dest[n, idx[n, m]] = vals[n, m]`` where valid; at most one valid
    writer per (row, column) — the unique-slot scatter (queue placement,
    slot tables). With duplicate writers the max value wins on the dense
    path (deterministic) while the element path keeps the last."""
    vals = vals.astype(dest.dtype)  # dtype-preserving (narrowed planes)
    n, w = dest.shape
    if not _dense():
        flat = _flat(idx, valid, n, w)
        return (
            dest.reshape(-1)
            .at[flat.reshape(-1)]
            .set(vals.reshape(-1), mode="drop")
            .reshape(n, w)
        )
    cols = []
    for c in range(w):
        m = valid & (idx == c)
        has = jnp.any(m, axis=1)
        v = jnp.max(jnp.where(m, vals, jnp.iinfo(vals.dtype).min), axis=1)
        cols.append(jnp.where(has, v, dest[:, c]))
    return jnp.stack(cols, axis=1)


def scatter_cols_or(dest, idx, vals, valid):
    """``dest[n, idx[n, m]] |= vals[n, m]`` where valid (unsigned int
    bitmasks). Precondition: within one call, no two valid writers carry
    the same set bit for the same (row, column) — the element form
    implements OR as add (there is no ``.at[].or``), which matches OR
    exactly under that no-carry condition; callers guarantee it by
    deduping their batches first. (Bits already set in ``dest`` are fine
    on both forms: the element form masks them out of the addends.)"""
    n, w = dest.shape
    if not _dense():
        flat = _flat(idx, valid, n, w)
        already = lookup_cols(dest, idx, fill=0)
        vals = jnp.where(valid, vals & ~already, 0).astype(dest.dtype)
        return (
            dest.reshape(-1)
            .at[flat.reshape(-1)]
            .add(vals.reshape(-1), mode="drop")
            .reshape(n, w)
        )
    cols = []
    zero = jnp.zeros((), dest.dtype)
    for c in range(w):
        m = valid & (idx == c)
        upd = jax.lax.reduce(
            jnp.where(m, vals, zero).astype(dest.dtype),
            zero, jax.lax.bitwise_or, (1,),
        )
        cols.append(dest[:, c] | upd)
    return jnp.stack(cols, axis=1)


def select_cols(rows, idx):
    """``out[n, m] = rows[n, idx[n, m]]`` — alias of :func:`lookup_cols`
    for [N, W] payload rows picked by per-row slot indices."""
    return lookup_cols(rows, idx)


def apply_changes(store, cell, ver, val, site, dbv, clp, valid):
    """Backend-adaptive LWW apply of per-node message batches.

    ``store``: ``(ver, val, site, dbv, clp)`` planes [N, C]; message
    fields [N, M] addressed by ``cell`` (column per message). On TPU this
    is the column-loop form (``lww.apply_changes_cols``); on CPU the
    flatten + segment-reduce form (``lww.apply_changes_to_store``) —
    identical semantics, differentially tested like the other dense ops.
    """
    if _dense():
        return apply_changes_cols(store, cell, ver, val, site, dbv, clp, valid)
    n, c_cnt = store[0].shape
    # out-of-range cells are invalid on BOTH forms (the column loop skips
    # them structurally; _flat routes them to the scratch segment)
    valid = valid & (cell >= 0) & (cell < c_cnt)
    flat_idx = _flat(cell, valid, n, c_cnt)
    out = apply_changes_to_store(
        tuple(p.reshape(-1) for p in store),
        flat_idx.reshape(-1),
        ver.reshape(-1),
        val.reshape(-1),
        site.reshape(-1),
        dbv.reshape(-1),
        clp.reshape(-1),
        valid.reshape(-1),
    )
    return tuple(p.reshape(n, c_cnt) for p in out)
