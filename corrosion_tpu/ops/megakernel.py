"""Fused receiver-side ingest as one pallas TPU kernel per node block.

The XLA form of change ingest (``sim/broadcast.ingest_changes``) lowers
to thousands of small [N]-wide kernels (the dense column loops over the
store's 64 cells, the queue's 32 slots, the book's 16 origins), each
paying a launch and an HBM round-trip at ~400 KB operand sizes. Every
step is *row-local* — node i's messages touch only node i's tables — so
the whole phase maps onto a pallas grid over node blocks: each program
instance pulls one block's planes into VMEM, runs dedupe + bookkeeping +
LWW apply + re-broadcast enqueue in-register, and writes each plane back
once. State traffic collapses to one read + one write per plane per
round — the bandwidth bound PERF.md derives.

Protocol semantics are IDENTICAL to the unfused path (the reference
behaviors mirrored are the same ones cited in ``sim/broadcast.py`` /
``ops/versions.py``: seen-cache dedupe ``handlers.rs:548-786``, HLC fold
``handlers.rs:689-701``, drop-oldest-most-sent queue overflow
``broadcast/mod.rs:410-812``); a differential test pins fused ==
unfused exactly. Only the single-cell fast path is fused (``nseq == 1``,
``process_complete_version``, reference ``util.rs:1197``); configs with
multi-cell transactions keep the XLA partial-buffer path.

Path selection is the ``fused`` config knob (``config.perf.fused`` ->
``cfg.fused`` on the sim configs, docs/fused.md): ``auto`` takes the
fused path on non-CPU backends when the eager differential/width probes
pass; ``on``/``off`` pin the fused/XLA path; ``interpret`` runs the
fused kernels in pallas interpret mode on ANY backend — which is how
tier-1 exercises fused==unfused parity on CPU, through the sharded mesh
and the segmented soak included. Production dispatchers hoist the eager
probes with :func:`prime_fused` so they run once per (backend, shape)
BEFORE trace time instead of inside a sharded dispatch.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# the legal knob values live with the configs that validate them
# (sim/config.py is import-light; this re-export keeps the gates' home
# module the natural place to look them up)
from corrosion_tpu.sim.config import FUSED_MODES

_pallas_ok_cache: dict = {}  # backend -> tiny differential probes passed
_width_ok_cache: dict = {}  # (backend, kernel, shape key) -> lowers + runs
# jax._src.core.trace_state_clean, resolved once on first use; False
# once the private API is found missing (thread path used from then on)
_trace_state_clean = None


def _backend() -> str:
    """The backend name the gates/probes key on — a seam so tests can
    exercise TPU-shaped gating without a TPU (monkeypatch this, never
    ``jax.default_backend`` itself: the jit machinery uses that too)."""
    return jax.default_backend()


def fused_mode(cfg) -> str:
    """The ``fused`` knob of ``cfg`` (``auto`` for configs that predate
    the field — e.g. checkpoint manifests written before it existed)."""
    mode = getattr(cfg, "fused", "auto") or "auto"
    if mode not in FUSED_MODES:
        raise ValueError(
            f"fused mode {mode!r} not one of {FUSED_MODES} (docs/fused.md)"
        )
    return mode


def fused_interpret(cfg) -> Optional[bool]:
    """``interpret=`` argument for a fused kernel call under ``cfg``:
    True pins pallas interpret mode, None defers to the backend default
    (interpret on CPU, compiled elsewhere)."""
    return True if fused_mode(cfg) == "interpret" else None


def _eager(fn):
    """Run ``fn`` outside any ambient jax trace.

    The probe functions below execute real pallas calls and ``int()``
    their results; callers invoke them from INSIDE jit traces (the scale
    step chooses fused-vs-XLA while being traced), where the probe ops
    would become tracers and the int() would raise
    ConcretizationTypeError — permanently caching "pallas broken".
    ``jax.ensure_compile_time_eval`` is not usable here: it leaks into
    the pallas kernel's own tracing and turns every kernel-internal
    array creation into a captured constant. Trace state is
    thread-local, so a fresh thread gives a genuinely clean context."""
    global _trace_state_clean
    if _trace_state_clean is None:
        # resolve the private helper ONCE (ADVICE r4): if JAX removes or
        # renames it, we record the miss and every probe call takes the
        # (correct, slightly slower) thread path without re-importing
        try:
            from jax._src import core as _core

            _trace_state_clean = _core.trace_state_clean
        except Exception:  # noqa: BLE001 — private API gone
            _trace_state_clean = False
    try:
        clean = bool(_trace_state_clean and _trace_state_clean())
    except Exception:  # noqa: BLE001 — behave as if dirty
        clean = False
    if clean:
        return fn()
    from corrosion_tpu.utils.lifecycle import spawn_counted

    box: dict = {}

    def run() -> None:
        try:
            box["v"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised below
            box["e"] = e

    # a counted corro-* spawn (not a raw Thread) so corrosan's leak
    # gate and the conftest corro-prefix liveness check attribute it
    # like every other thread this repo starts; it joins before this
    # returns, so it can never survive a sanitizer window
    t = spawn_counted(run, name="corro-pallas-probe")
    t.join()
    if "e" in box:
        raise box["e"]
    return box["v"]


def _warn_degrade(stage: str, detail: str = "") -> None:
    import sys

    print(
        f"WARNING: pallas megakernel {stage} probe failed on backend "
        f"{_backend()!r}; callers degrade to the (much "
        f"slower) XLA form. {detail}",
        file=sys.stderr, flush=True,
    )


def _swim_probe_args(n: int, m: int, key, pig_k: int = 0,
                     narrow: bool = False, tx8: bool = False):
    """Operand tuple for a ``swim_tables_*`` probe call (21 positional
    args after ``consts``) — shared by the tiny differential probes and
    the block-width probes so they cannot drift from the signature.
    ``pig_k > 0`` shapes the channel planes as packed entry lists
    ([n, pig_k]) like the bounded-piggyback mode; ``narrow`` carries the
    timer/budget planes as int16 like ``narrow_dtypes`` configs; ``tx8``
    carries ``mem_tx`` as int8 like ``narrow_int8`` configs (ISSUE 12 —
    the probed dtype set must match the caller's or a DIFFERENT,
    unprobed kernel would lower at dispatch)."""
    import jax.random as jr

    tdt = jnp.int16 if narrow else jnp.int32
    txdt = jnp.int8 if tx8 else tdt
    iarr = jnp.arange(n, dtype=jnp.int32)
    mem_id = jr.randint(key, (n, m), -1, n, dtype=jnp.int32)
    mem_view = jr.randint(jr.fold_in(key, 1), (n, m), -1, 64,
                          dtype=jnp.int32)
    if pig_k > 0:
        ch_id = jr.randint(jr.fold_in(key, 2), (n, pig_k), -1, n,
                           dtype=jnp.int32)
        ch_view = jr.randint(jr.fold_in(key, 3), (n, pig_k), 0, 64,
                             dtype=jnp.int32)
        ch_send = jnp.ones((n, pig_k), bool)
    else:
        ch_id, ch_view = mem_id, mem_view
        ch_send = jnp.ones((n, m), bool)
    return (
        mem_id, mem_view, mem_id, mem_view,
        jnp.zeros((n, m), tdt), jnp.ones((n, m), txdt),
        jnp.ones(n, bool), jnp.zeros(n, jnp.int32), iarr, iarr % m,
        jnp.full(n, -1, jnp.int32), jnp.ones(n, jnp.int32),
        iarr % m, jnp.ones(n, jnp.int32), jnp.zeros(n, bool),
        [ch_id] * 4, [ch_view] * 4, [ch_send] * 4,
        [jnp.ones(n, bool)] * 4, [(iarr + 1) % n] * 4,
        [jnp.zeros(n, jnp.int32)] * 4,
    )


def _pallas_works() -> bool:
    """Compile + run BOTH real kernels once per backend on tiny shapes,
    differentially against the shared XLA forms, cached — if the
    backend's pallas lowering can't handle them (experimental tunnel
    plugins), every caller degrades to the XLA path instead of failing
    the bench."""
    backend = _backend()
    if backend not in _pallas_ok_cache:
        def _run_probe() -> bool:
            import jax.random as jr
            import numpy as np

            from corrosion_tpu.sim.broadcast import CrdtState
            from corrosion_tpu.sim.config import SimConfig

            cfg = SimConfig(n_nodes=32, n_origins=2).validate()
            cst = CrdtState.create(cfg)
            z = jnp.zeros((32, 2), jnp.int32)
            live = jnp.zeros((32, 2), bool).at[0, 0].set(True)
            cst2, info = ingest_changes_fused(
                cfg, cst, live, z, z + 1, z, z + 1, z + 7, z, z, z,
                interpret=False,
            )
            ok = (
                int(info["fresh"]) == 1
                and int(np.asarray(cst2.store[1])[0, 0]) == 7
                and int(np.asarray(cst2.book.head)[0, 0]) == 1
            )
            # the swim kernel lowers differently (dense column scatters
            # inside pallas) — probe it too, against the shared XLA form
            if ok:
                from corrosion_tpu.sim.scale import swim_tables_update

                # both channel forms: aligned rows (pig 0) and packed
                # entries (bounded piggyback)
                for consts in ((4, 4, 8, 6, 0), (4, 4, 8, 6, 2)):
                    args = _swim_probe_args(32, 4, jr.key(0),
                                            pig_k=consts[4])
                    want = swim_tables_update(consts, *args)
                    got = swim_tables_fused(consts, *args,
                                            interpret=False)
                    ok = ok and all(
                        bool(jnp.array_equal(a, b))
                        for a, b in zip(want, got)
                    )
            return ok

        try:
            # probes run from inside jit traces (the scale step chooses
            # its path while being traced) — _eager escapes the trace
            ok = _eager(_run_probe)
            _pallas_ok_cache[backend] = ok
            if not ok and backend != "cpu":
                _warn_degrade(
                    "differential",
                    "The fused kernels MISMATCHED the XLA path at tiny "
                    "shapes — a semantic divergence; investigate "
                    "ops/megakernel.py before trusting TPU numbers.",
                )
        except Exception:  # noqa: BLE001 — any lowering failure means "no"
            _pallas_ok_cache[backend] = False
            if backend != "cpu":
                import traceback

                _warn_degrade("differential", "Traceback follows.")
                traceback.print_exc()
    return _pallas_ok_cache[backend]


def _probe_n(blk: int) -> int:
    """A small n whose block size equals ``blk`` (so the probe exercises
    the caller's real block shape); 0 when no such multiple exists."""
    for mult in (3, 2, 5):
        if _block_size(mult * blk) == blk:
            return mult * blk
    return 0


def _width_ok_ingest(cfg, msgs: int, emit: bool = False) -> bool:
    """Lowering/VMEM probe for the ingest kernel at the caller's block
    and plane widths — a kernel that lowers at tiny widths can still
    fail Mosaic/VMEM at the real block shape, and this probe costs one
    small compile instead of a full-N bench attempt. ``emit`` probes the
    payload-emitting variant (extra outputs + selection loops) so the
    probed kernel matches the kernel actually run."""
    backend = _backend()
    blk = _block_size(cfg.n_nodes)
    seen_w = max(1, -(-cfg.buf_slots // 32))
    # narrow_dtypes changes the probed kernel's lowering (int16 q
    # planes), so it must key the cache like the swim probe's `narrow`
    key = (backend, "ingest", blk, cfg.n_origins, cfg.n_cells,
           cfg.bcast_queue, seen_w, msgs, emit,
           bool(getattr(cfg, "narrow_dtypes", False)),
           # the q-plane int8 tier changes the probed kernel's store
           # widths the same way (ISSUE 19); the probe below builds its
           # CrdtState from a replace(cfg, ...) so it carries the flag
           bool(getattr(cfg, "narrow_q_int8", False)))
    if key not in _width_ok_cache:
        nb = _probe_n(blk)
        if nb == 0 or nb >= cfg.n_nodes:
            # no cheaper representative exists — accept; a failure would
            # surface at the caller's own compile
            _width_ok_cache[key] = True
            return True
        def _run_width_probe() -> bool:
            import dataclasses

            from corrosion_tpu.sim.broadcast import CrdtState

            cfgb = dataclasses.replace(cfg, n_nodes=nb)
            cstb = CrdtState.create(cfgb)
            zb = jnp.zeros((nb, msgs), jnp.int32)
            liveb = jnp.zeros((nb, msgs), bool).at[0, 0].set(True)
            kw = {}
            if emit:
                kw = dict(
                    rand=jnp.zeros((nb, cfgb.bcast_queue), jnp.float32),
                    carried=jnp.ones(nb, jnp.int32),
                )
            out = ingest_changes_fused(
                cfgb, cstb, liveb, zb, zb + 1, zb, zb + 1, zb + 7, zb,
                zb, zb, interpret=False, **kw,
            )
            return int(out[1]["fresh"]) == 1

        try:
            # eager escape: see _pallas_works (probes run inside traces)
            _width_ok_cache[key] = _eager(_run_width_probe)
        except Exception:  # noqa: BLE001
            import traceback

            _width_ok_cache[key] = False
            _warn_degrade(
                f"ingest width (block {blk}, widths {key[3:]})",
                "Lowering/VMEM failure at the real block shape; "
                "traceback follows.",
            )
            traceback.print_exc()
    return _width_ok_cache[key]


def _width_ok_swim(n_nodes: int, m_slots: int, pig_k: int = 0,
                   narrow: bool = False, tx8: bool = False) -> bool:
    """Same as :func:`_width_ok_ingest` for the swim kernel (both the
    aligned-row and bounded-piggyback channel forms). ``narrow`` probes
    with int16 timer/budget planes so the probed kernel matches a
    ``narrow_dtypes`` caller's lowering; ``tx8`` keys the ``narrow_int8``
    (int8 mem_tx) dtype set separately for the same reason."""
    backend = _backend()
    blk = _block_size(n_nodes)
    key = (backend, "swim", blk, m_slots, pig_k, narrow, tx8)
    if key not in _width_ok_cache:
        nb = _probe_n(blk)
        if nb == 0 or nb >= n_nodes:
            _width_ok_cache[key] = True
            return True
        def _run_width_probe() -> bool:
            import jax.random as jr

            args = _swim_probe_args(nb, m_slots, jr.key(1), pig_k=pig_k,
                                    narrow=narrow, tx8=tx8)
            outs = swim_tables_fused(
                (m_slots, 6, 48, 10, pig_k), *args, interpret=False
            )
            # execution (not values) is what's probed; the tiny-shape
            # differential in _pallas_works pinned semantics
            return jax.block_until_ready(outs[0]).shape == (nb, m_slots)

        try:
            # eager escape: see _pallas_works (probes run inside traces)
            _width_ok_cache[key] = _eager(_run_width_probe)
        except Exception:  # noqa: BLE001
            import traceback

            _width_ok_cache[key] = False
            _warn_degrade(
                f"swim width (block {blk}, m_slots {m_slots}, "
                f"pig {pig_k})",
                "Lowering/VMEM failure at the real block shape; "
                "traceback follows.",
            )
            traceback.print_exc()
    return _width_ok_cache[key]


def use_fused(mode: str = "auto") -> bool:
    """Backend-level answer (tiny differential probes only)."""
    if mode != "auto":
        return mode in ("on", "interpret")
    return _backend() != "cpu" and _pallas_works()


def use_fused_ingest(cfg, msgs: int = 16, emit: bool = False) -> bool:
    """Shape-aware answer for the ingest kernel at ``cfg``'s widths."""
    if getattr(cfg, "bcast_wire_budget", False):
        # the wire-budget payload lane predates the kernel's ref layout
        # — flagged configs take the XLA path even when the knob pins
        # the fused path (round-6 kernel work)
        return False
    mode = fused_mode(cfg)
    if mode != "auto":
        return mode in ("on", "interpret")
    return use_fused() and _width_ok_ingest(cfg, msgs, emit)


def use_fused_swim(n_nodes: int, m_slots: int, pig_k: int = 0,
                   narrow: bool = False, mode: str = "auto",
                   tx8: bool = False) -> bool:
    """Shape-aware answer for the swim kernel at the caller's widths;
    ``mode`` is the caller's ``fused_mode(cfg)`` (the swim tables carry
    no config object of their own)."""
    if mode not in FUSED_MODES:
        raise ValueError(
            f"fused mode {mode!r} not one of {FUSED_MODES} (docs/fused.md)"
        )
    if mode != "auto":
        return mode in ("on", "interpret")
    return use_fused() and _width_ok_swim(n_nodes, m_slots, pig_k, narrow,
                                          tx8)


def prime_fused(cfg) -> dict:
    """Hoisted gate evaluation: run the eager pallas probes for every
    (kernel, width) the round step under ``cfg`` will consult, OUTSIDE
    any trace, and return the decisions.

    The gates below are consulted at TRACE time (the step chooses
    fused-vs-XLA while being traced) and, under ``auto`` on a real
    backend, would otherwise run their differential/width probes from
    inside a sharded dispatch via the ``_eager`` escape-hatch thread.
    Production dispatchers (``parallel/mesh.sharded_scale_run*``,
    ``resilience/segments.run_segmented``, ``Agent``, ``bench.py``) call
    this first so the probes run exactly once per (backend, shape) at
    Python level; the in-trace gate calls then hit the warm caches.
    Repeat calls are cheap cache lookups.

    Returns ``{"mode", "interpret", "ingest", "ingest_emit", "swim"}``
    — the knob, whether engaged kernels run interpreted (False when
    none engage), and the per-kernel decisions (``None`` for a kernel
    the config never dispatches)."""
    mode = fused_mode(cfg)
    out = {
        "mode": mode,
        "ingest": None,
        "ingest_emit": None,
        "swim": None,
    }
    single_cell = getattr(cfg, "tx_max_cells", 1) <= 1
    pig = int(getattr(cfg, "pig_changes", 0))
    if hasattr(cfg, "bcast_queue") and single_cell:
        # every ingest width the round step will consult, each probed
        # UNCONDITIONALLY (no short-circuit: a failing width must not
        # leave a later width's cache cold, or the trace-time gate
        # would run that probe from inside the dispatch — the exact
        # thing hoisting exists to prevent): the local-write width
        # (msgs=1, emitting the piggyback payload when the scale step
        # will), the piggyback receive batch (4 SWIM channels x pig
        # slots), and the full sim's apply-mailbox width
        gates = [use_fused_ingest(cfg, msgs=1)]
        if pig > 0:
            out["ingest_emit"] = use_fused_ingest(cfg, msgs=1, emit=True)
            gates.append(use_fused_ingest(cfg, msgs=4 * pig))
        recv = int(getattr(cfg, "recv_slots", 0))
        if recv > 0:
            gates.append(use_fused_ingest(cfg, msgs=recv))
        out["ingest"] = all(gates)
    if hasattr(cfg, "m_slots"):
        out["swim"] = use_fused_swim(
            cfg.n_nodes, cfg.m_slots,
            int(getattr(cfg, "pig_members", 0)),
            narrow=bool(getattr(cfg, "narrow_dtypes", False)),
            tx8=bool(getattr(cfg, "narrow_int8", False)),
            mode=mode,
        )
    # interpret is a statement about the kernels that RUN: False when
    # nothing engaged (an XLA-only record must never claim
    # interpret-mode execution)
    out["interpret"] = (
        (mode == "interpret" or _backend() == "cpu") and fused_engaged(out)
    )
    return out


def fused_engaged(decisions: dict) -> bool:
    """True when EVERY kernel the probed config dispatches engaged —
    the one definition of the ``pallas_fused`` provenance bit, shared
    by ``SoakResult.stats`` and the bench records so the two can never
    disagree about the same run."""
    vals = [decisions.get(k) for k in ("ingest", "ingest_emit", "swim")]
    vals = [v for v in vals if v is not None]
    return bool(vals) and all(vals)


def _cols(table, idx, fill=0):
    """``table[b, idx[b, m]]`` via a static column loop (VMEM registers)."""
    w = table.shape[1]
    out = jnp.full(idx.shape, fill, table.dtype)
    for c in range(w):
        out = jnp.where(idx == c, table[:, c : c + 1], out)
    return out


def _ingest_kernel(cfg_tuple, *refs):
    (n_origins, n_cells, q_slots, seen_words, hlc_round_bits,
     hlc_max_drift, no_q, pig_r, budget_bytes, wire_bytes,
     keep_rounds, enqueue_all) = cfg_tuple
    # ref layout: 31 base inputs (+2 with payload emission), then the
    # 22 base outputs (+3 with emission)
    n_in = 31 + (2 if pig_r else 0)
    (live_ref, origin_ref, dbv_ref, cell_ref, ver_ref, val_ref, site_ref,
     clp_ref, ts_ref, budget_ref,
     s_ver_ref, s_val_ref, s_site_ref, s_dbv_ref, s_clp_ref,
     head_ref, km_ref, seen_ref, org_id_ref, org_last_ref,
     q_origin_ref, q_dbv_ref, q_cell_ref, q_ver_ref, q_val_ref,
     q_site_ref, q_clp_ref, q_ts_ref, q_tx_ref,
     hlc_ref, now_ref) = refs[:31]
    if pig_r:
        rand_ref, carried_ref = refs[31:33]
    (o_s_ver, o_s_val, o_s_site, o_s_dbv, o_s_clp,
     o_head, o_km, o_seen, o_org_id, o_org_last,
     o_q_origin, o_q_dbv, o_q_cell, o_q_ver, o_q_val, o_q_site, o_q_clp,
     o_q_ts, o_q_tx,
     o_hlc, o_fresh, o_drift) = refs[n_in:n_in + 22]
    if pig_r:
        o_payload, o_sel, o_selok = refs[n_in + 22:]

    imin = jnp.int32(-2147483648)
    imax = jnp.int32(2147483647)
    ones32 = jnp.uint32(0xFFFFFFFF)

    live = live_ref[:] != 0
    origin = origin_ref[:]
    dbv = dbv_ref[:]
    cell = cell_ref[:]
    ver = ver_ref[:]
    val = val_ref[:]
    site = site_ref[:]
    clp = clp_ref[:]
    ts = ts_ref[:]
    b, m = origin.shape
    now = now_ref[0]

    # --- HLC fold with max-drift rejection (handlers.rs:689-701) --------
    hlc = hlc_ref[:][:, 0]
    phys = ts >> hlc_round_bits
    ts_ok = live & (phys <= now + hlc_max_drift)
    folded = jnp.max(jnp.where(ts_ok, ts, 0), axis=1)
    o_hlc[:] = jnp.maximum(hlc, folded)[:, None]
    o_drift[:] = jnp.sum(live & ~ts_ok, axis=1, keepdims=True).astype(
        jnp.int32
    )
    live = ts_ok

    # --- seen-check + in-batch dedupe (versions.record_versions) --------
    # round 4: bookkeeping lives at the origin's hash SLOT (origin % O)
    # and counts only while the slot tracks that exact actor
    # (versions.Book org table; unbounded writer set)
    head = head_ref[:]
    km = km_ref[:]
    flat_seen = seen_ref[:]  # [B, O*W]
    org_id = org_id_ref[:]
    org_last = org_last_ref[:]
    slot = jnp.where(origin >= 0, origin % n_origins, 0)
    owner_at = _cols(org_id, slot, fill=-1)
    owned_pre = (origin >= 0) & (owner_at == origin)
    h_at = _cols(head, slot)
    off = dbv - h_at - 1
    in_win = (off >= 0) & (off < 32 * seen_words)
    word_idx = slot * seen_words + jnp.where(off >= 0, off >> 5, 0)
    bit = (jnp.clip(off, 0, None) & 31).astype(jnp.uint32)
    bitval = jnp.uint32(1) << bit
    word_val = _cols(flat_seen, word_idx)
    hit = ((word_val >> bit) & 1) == 1
    seen_b = live & owned_pre & ((dbv <= h_at) | (in_win & hit))

    same = (
        (origin[:, :, None] == origin[:, None, :])
        & (dbv[:, :, None] == dbv[:, None, :])
        & live[:, None, :]
    )
    # iota compare, not tril-of-ones: a dense bool constant lowers to an
    # i8 constant + trunci-to-i1, which Mosaic rejects ("Unsupported
    # target bitwidth for truncation")
    earlier = (
        jax.lax.broadcasted_iota(jnp.int32, (m, m), 1)
        < jax.lax.broadcasted_iota(jnp.int32, (m, m), 0)
    )
    dup = jnp.any(same & earlier[None, :, :], axis=2)
    fresh = live & ~seen_b & ~dup
    o_fresh[:] = fresh.astype(jnp.int32)

    # --- slot claim/evict: literally the shared XLA function ------------
    from corrosion_tpu.ops.versions import claim_slots_arrays

    head, km, flat_seen, org_id, org_last = claim_slots_arrays(
        head, km, flat_seen, org_id, org_last, origin, fresh, now,
        keep_rounds, seen_words,
    )
    o_org_id[:] = org_id
    o_org_last[:] = org_last

    # --- record (post-claim ownership + rebased offsets) ----------------
    owned = (origin >= 0) & (_cols(org_id, slot, fill=-1) == origin)
    rec = fresh & owned
    h_at = _cols(head, slot)
    off = dbv - h_at - 1
    in_win = (off >= 0) & (off < 32 * seen_words)
    word_idx = slot * seen_words + jnp.where(off >= 0, off >> 5, 0)
    bit = (jnp.clip(off, 0, None) & 31).astype(jnp.uint32)
    bitval = jnp.uint32(1) << bit

    # --- seen-bit OR + known_max scatter-max + head advance -------------
    set_mask = rec & in_win
    new_cols = []
    for c in range(n_origins * seen_words):
        sel = set_mask & (word_idx == c)
        acc = flat_seen[:, c]
        for j in range(m):
            acc = acc | jnp.where(sel[:, j], bitval[:, j], jnp.uint32(0))
        new_cols.append(acc)
    seen3 = jnp.stack(new_cols, axis=1).reshape(b, n_origins, seen_words)

    km_cols = []
    for c in range(n_origins):
        sel = live & owned & (slot == c)
        km_cols.append(
            jnp.maximum(
                km[:, c], jnp.max(jnp.where(sel, dbv, imin), axis=1)
            )
        )
    km = jnp.stack(km_cols, axis=1)

    # head advance: count trailing ones per window, then shift it down
    x1 = seen3 + jnp.uint32(1)
    t_w = jnp.where(
        seen3 == ones32,
        jnp.int32(32),
        jax.lax.population_count(seen3 ^ x1).astype(jnp.int32) - 1,
    )
    total = t_w[:, :, 0]
    carry = t_w[:, :, 0] == 32
    for j in range(1, seen_words):
        total = total + jnp.where(carry, t_w[:, :, j], 0)
        carry = carry & (t_w[:, :, j] == 32)
    head = head + total
    s_words = total >> 5
    s_bits = (total & 31).astype(jnp.uint32)[:, :, None]
    hi_sh = jnp.where(s_bits > 0, jnp.uint32(32) - s_bits, 0)
    zeros_w = jnp.zeros((b, n_origins, 1), jnp.uint32)

    def word_from(s):
        if s >= seen_words:
            return jnp.zeros_like(seen3)
        return jnp.concatenate([seen3[:, :, s:]] + [zeros_w] * s, axis=2)

    shifted = jnp.zeros_like(seen3)
    for s in range(seen_words + 1):
        part = (word_from(s) >> s_bits) | jnp.where(
            s_bits > 0, word_from(s + 1) << hi_sh, 0
        )
        shifted = jnp.where((s_words == s)[:, :, None], part, shifted)
    o_head[:] = head
    o_km[:] = jnp.maximum(km, head)
    o_seen[:] = shifted.reshape(b, n_origins * seen_words)

    # --- LWW apply of fresh cells (lww.apply_changes_cols) --------------
    s_ver = s_ver_ref[:]
    s_val = s_val_ref[:]
    s_site = s_site_ref[:]
    s_dbv = s_dbv_ref[:]
    s_clp = s_clp_ref[:]
    keys_in = (clp, ver, val, site)
    out_cols = ([], [], [], [], [])
    for c in range(n_cells):
        alive = fresh & (cell == c)
        nonempty = jnp.any(alive, axis=1)
        mx = []
        for k in keys_in:
            kk = jnp.where(alive, k, imin)
            mk = jnp.max(kk, axis=1)
            alive = alive & (kk == mk[:, None])
            mx.append(mk)
        b_dbv = jnp.max(jnp.where(alive, dbv, imin), axis=1)
        a_keys = (s_clp[:, c], s_ver[:, c], s_val[:, c], s_site[:, c])
        wins = a_keys[-1] >= mx[-1]
        for ak, bk in zip(reversed(a_keys[:-1]), reversed(mx[:-1])):
            wins = (ak > bk) | ((ak == bk) & wins)
        take = nonempty & ~wins
        for dst, cur, new in zip(
            out_cols,
            (s_ver[:, c], s_val[:, c], s_site[:, c], s_dbv[:, c],
             s_clp[:, c]),
            (mx[1], mx[2], mx[3], b_dbv, mx[0]),
        ):
            dst.append(jnp.where(take, new, cur))
    o_s_ver[:] = jnp.stack(out_cols[0], axis=1)
    o_s_val[:] = jnp.stack(out_cols[1], axis=1)
    o_s_site[:] = jnp.stack(out_cols[2], axis=1)
    o_s_dbv[:] = jnp.stack(out_cols[3], axis=1)
    o_s_clp[:] = jnp.stack(out_cols[4], axis=1)

    # --- re-broadcast enqueue with evict-most-sent ----------------------
    # only RECORDED changes re-enqueue (see versions.record_versions:
    # unrecorded fresh messages would circulate forever) — except the
    # local-write path (enqueue_all), where the writer is the source of
    # truth and must disseminate even when its own slot is contended.
    # sequential argmin over the batch == the batch rank assignment of
    # slots.alloc_slots_evict (the r-th fresh item takes the r-th
    # smallest evict key; ties resolve to the lowest slot on both forms;
    # items beyond the slot count drop on both forms)
    enq = fresh if enqueue_all else rec
    q_origin = q_origin_ref[:]
    q_tx_now = q_tx_ref[:]
    evict_key = jnp.where(q_origin == no_q, imin, q_tx_now)
    rebudget = budget_ref[:]
    planes = [
        [q_origin, origin],
        [q_dbv_ref[:], dbv],
        [q_cell_ref[:], cell],
        [q_ver_ref[:], ver],
        [q_val_ref[:], val],
        [q_site_ref[:], site],
        [q_clp_ref[:], clp],
        [q_ts_ref[:], ts],
        [q_tx_now, rebudget],
    ]
    col_iota = jax.lax.broadcasted_iota(jnp.int32, evict_key.shape, 1)
    # arg-reductions over int operands don't lower on Mosaic (only f32);
    # min/argmin == min-reduce + lowest matching column, two passes
    for j in range(m):
        kmin = jnp.min(evict_key, axis=1)
        slot = jnp.min(
            jnp.where(evict_key == kmin[:, None], col_iota, q_slots), axis=1
        )
        write = (enq[:, j] & (kmin < imax))[:, None] & (
            col_iota == slot[:, None]
        )
        for pair in planes:
            pair[0] = jnp.where(write, pair[1][:, j : j + 1], pair[0])
        evict_key = jnp.where(write, imax, evict_key)
    for ref, pair in zip(
        (o_q_origin, o_q_dbv, o_q_cell, o_q_ver, o_q_val, o_q_site,
         o_q_clp, o_q_ts, o_q_tx),
        planes,
    ):
        # narrowed planes promote to int32 mid-kernel; store re-narrows
        ref[:] = pair[0].astype(ref.dtype)

    # --- piggyback payload selection (emitted for THIS round's packets) --
    # identical semantics to the XLA selection in piggyback_bcast_step:
    # budget_mask keeps the `allowed` highest-q_tx live slots (stable by
    # column), then the pig_r largest pre-drawn uniforms win; the q
    # planes are already in VMEM, so this costs no extra HBM traffic.
    if pig_r:
        q_origin_new = planes[0][0]
        q_tx_new = planes[8][0]
        rand = rand_ref[:]  # [B, Q] float32
        carried = carried_ref[:][:, 0]
        allowed = jnp.maximum(
            budget_bytes // (wire_bytes * jnp.maximum(carried, 1)), 1
        ).astype(jnp.int32)
        live_slot = (q_origin_new != no_q) & (q_tx_new > 0)
        # budget mask: iteratively take the max-q_tx live slot
        # (first-column ties, like the stable argsort rank form)
        bkey = jnp.where(live_slot, q_tx_new, imin)
        keep = col_iota < 0  # all-False without a bool constant (Mosaic)
        cnt = jnp.zeros((b,), jnp.int32)
        for _ in range(q_slots):
            kmax = jnp.max(bkey, axis=1)
            # int argmax doesn't lower on Mosaic: lowest matching column
            slot = jnp.min(
                jnp.where(bkey == kmax[:, None], col_iota, q_slots), axis=1
            )
            sel = (kmax > imin) & (cnt < allowed)
            wcol = col_iota == slot[:, None]
            keep = keep | (wcol & sel[:, None])
            cnt = cnt + sel.astype(jnp.int32)
            # the selected column retires unconditionally (sel or not)
            bkey = jnp.where(wcol, imin, bkey)
        # sample pig_r slots by the pre-drawn uniforms (top_k analog)
        rkey = jnp.where(keep, rand, jnp.float32(-1.0))
        sel_cols, sel_oks = [], []
        for _ in range(pig_r):
            rmax = jnp.max(rkey, axis=1)
            slot = jnp.argmax(rkey, axis=1).astype(jnp.int32)
            sel_cols.append(slot)
            sel_oks.append(rmax >= 0)
            rkey = jnp.where(col_iota == slot[:, None],
                             jnp.float32(-2.0), rkey)
        sel_slots = jnp.stack(sel_cols, axis=1)  # [B, R]
        sel_ok = jnp.stack(sel_oks, axis=1)
        fields = [planes[i][0] for i in (0, 1, 2, 3, 4, 5, 6)]
        # q_seq/q_nseq stay at their single-cell constants (0 / 1) on
        # this path — synthesize them so the payload layout matches the
        # unfused 11-group form exactly
        zeros_r = jnp.zeros((b, pig_r), jnp.int32)
        payload_groups = (
            [_cols(f, sel_slots) for f in fields[:7]]
            + [zeros_r, zeros_r + 1]
            + [_cols(planes[7][0], sel_slots)]  # q_ts
            + [sel_ok.astype(jnp.int32)]
        )
        o_payload[:] = jnp.concatenate(payload_groups, axis=1)
        o_sel[:] = sel_slots
        o_selok[:] = sel_ok.astype(jnp.int32)


def _block_size(n: int) -> int:
    for b in (1024, 800, 640, 512, 400, 256, 200, 128, 100, 64, 50, 32):
        if n % b == 0:
            return b
    return n


def ingest_changes_fused(cfg, cst, live, m_origin, m_dbv, m_cell, m_ver,
                         m_val, m_site, m_clp, m_ts, *, m_budget=None,
                         drift_rounds: Optional[int] = None,
                         rand=None, carried=None,
                         enqueue_all: bool = False,
                         interpret: Optional[bool] = None):
    """Drop-in fused form of the single-cell ``ingest_changes`` path.

    Same contract as ``sim.broadcast.ingest_changes`` minus the seq/nseq
    chunking fields — callers use this path only when
    ``cfg.tx_max_cells == 1``, where every version is single-cell (the
    queue's seq/nseq planes stay at their constant 0/1 values).

    When ``rand`` ([N, Q] uniforms) and ``carried`` ([N] delivery
    multiplicities) are given, the kernel ALSO emits this round's
    piggyback payload selection from the post-update queue planes it
    already holds in VMEM (returning ``(cst, info, (payload, sel_slots,
    sel_ok))``) — the XLA selection phase then disappears.

    Donated-carry contract (the mesh donation comment block,
    ``parallel/mesh.py`` "Changing donate_argnums here REQUIRES..."):
    inside a donating dispatch the ``cst`` planes ARE the donated carry
    buffers. Every input ref is fully consumed by the single
    ``pallas_call`` below — nothing captures a ref past the dispatch —
    so XLA may alias kernel outputs onto the donated inputs; the
    narrowed planes (``analysis/dtypes.py::NARROW_REFS``) keep their
    int16 dtype at the out-ref store (``.astype(ref.dtype)``), which is
    what keeps the donated carry's aval stable across fused and XLA
    rounds (a widened store would both break aliasing and retrace every
    consumer).
    """
    from corrosion_tpu.sim.broadcast import (
        CHANGE_WIRE_BYTES as _CHANGE_WIRE_BYTES,
        HLC_MAX_DRIFT_ROUNDS,
        HLC_ROUND_BITS,
        NO_Q,
    )

    if interpret is None:
        # the config knob may pin interpret mode on any backend
        # (docs/fused.md); otherwise CPU interprets, real backends lower
        interpret = fused_mode(cfg) == "interpret" or _backend() == "cpu"

    n = live.shape[0]
    o_cnt = cst.book.head.shape[1]
    w = cst.book.seen.shape[2]
    q = cst.q_origin.shape[1]
    c_cnt = cst.store[0].shape[1]
    blk = _block_size(n)

    emit = rand is not None and carried is not None
    pig_r = int(getattr(cfg, "pig_changes", 0)) if emit else 0
    cfg_tuple = (
        o_cnt, c_cnt, q, w,
        HLC_ROUND_BITS,
        HLC_MAX_DRIFT_ROUNDS if drift_rounds is None else drift_rounds,
        int(NO_Q),
        pig_r,
        int(getattr(cfg, "bcast_budget_bytes", 0)),
        _CHANGE_WIRE_BYTES,
        int(getattr(cfg, "org_keep_rounds", 16)),
        bool(enqueue_all),
    )

    def spec(width):
        return pl.BlockSpec((blk, width), lambda i: (i, 0))

    s_ver, s_val, s_site, s_dbv, s_clp = cst.store
    seen_flat = cst.book.seen.reshape(n, o_cnt * w)

    if m_budget is None:
        m_budget = jnp.full(
            m_origin.shape, max(1, int(cfg.bcast_max_transmissions) - 1),
            jnp.int32,
        )
    in_arrays = [
        live.astype(jnp.int32), m_origin, m_dbv, m_cell, m_ver, m_val,
        m_site, m_clp, m_ts, m_budget,
        s_ver, s_val, s_site, s_dbv, s_clp,
        cst.book.head, cst.book.known_max, seen_flat,
        cst.book.org_id, cst.book.org_last,
        cst.q_origin, cst.q_dbv, cst.q_cell, cst.q_ver, cst.q_val,
        cst.q_site, cst.q_clp, cst.q_ts, cst.q_tx,
        cst.hlc[:, None],
    ]
    in_specs = [spec(a.shape[1]) for a in in_arrays]
    now_arr = jnp.asarray(cst.now, jnp.int32)[None]
    in_arrays.append(now_arr)
    in_specs.append(pl.BlockSpec((1,), lambda i: (0,)))
    if pig_r:
        in_arrays.append(rand.astype(jnp.float32))
        in_specs.append(spec(q))
        in_arrays.append(jnp.asarray(carried, jnp.int32)[:, None])
        in_specs.append(spec(1))

    m = m_origin.shape[1]
    out_shapes = (
        [jax.ShapeDtypeStruct((n, c_cnt), jnp.int32)] * 5
        + [
            jax.ShapeDtypeStruct((n, o_cnt), jnp.int32),
            jax.ShapeDtypeStruct((n, o_cnt), jnp.int32),
            jax.ShapeDtypeStruct((n, o_cnt * w), jnp.uint32),
            jax.ShapeDtypeStruct((n, o_cnt), jnp.int32),  # org_id
            jax.ShapeDtypeStruct((n, o_cnt), jnp.int32),  # org_last
        ]
        + [jax.ShapeDtypeStruct((n, q), p.dtype) for p in (
            cst.q_origin, cst.q_dbv, cst.q_cell, cst.q_ver, cst.q_val,
            cst.q_site, cst.q_clp, cst.q_ts, cst.q_tx,
        )]
        + [
            jax.ShapeDtypeStruct((n, 1), jnp.int32),  # hlc
            jax.ShapeDtypeStruct((n, m), jnp.int32),  # fresh
            jax.ShapeDtypeStruct((n, 1), jnp.int32),  # drift rejects
        ]
    )
    if pig_r:
        out_shapes = list(out_shapes) + [
            jax.ShapeDtypeStruct((n, 11 * pig_r), jnp.int32),  # payload
            jax.ShapeDtypeStruct((n, pig_r), jnp.int32),  # sel slots
            jax.ShapeDtypeStruct((n, pig_r), jnp.int32),  # sel ok
        ]
    out_specs = [spec(s.shape[1]) for s in out_shapes]

    outs = pl.pallas_call(
        functools.partial(_ingest_kernel, cfg_tuple),
        grid=(n // blk,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(*in_arrays)

    (s_ver, s_val, s_site, s_dbv, s_clp, head, km, seen_flat,
     org_id, org_last,
     q_origin, q_dbv, q_cell, q_ver, q_val, q_site, q_clp, q_ts, q_tx,
     hlc, fresh, drift) = outs[:22]
    emitted = None
    if pig_r:
        emitted = (outs[22], outs[23], outs[24] != 0)

    book = cst.book._replace(
        head=head, known_max=km, seen=seen_flat.reshape(n, o_cnt, w),
        org_id=org_id, org_last=org_last,
    )
    cst = cst._replace(
        store=(s_ver, s_val, s_site, s_dbv, s_clp),
        book=book,
        q_origin=q_origin, q_dbv=q_dbv, q_cell=q_cell, q_ver=q_ver,
        q_val=q_val, q_site=q_site, q_clp=q_clp, q_ts=q_ts, q_tx=q_tx,
        hlc=hlc[:, 0],
    )
    fresh = fresh != 0
    info = {
        # delivered counts post-drift-rejection, like the unfused path
        "delivered": jnp.sum(live) - jnp.sum(drift),
        "fresh": jnp.sum(fresh),
        "tx_completed": jnp.int32(0),
        "clock_drift_rejects": jnp.sum(drift),
        "queued": jnp.sum(q_origin != NO_Q),
    }
    if emitted is not None:
        return cst, info, emitted
    return cst, info


def local_write_fused(cfg, cst, write_mask, cell, val, clp=None, *,
                      rand=None, carried=None,
                      interpret: Optional[bool] = None):
    """Fused form of ``sim.broadcast.local_write`` — a local commit is one
    self-addressed message (origin = site = self, dbv = next_dbv,
    ver = cell's current clock + 1, full transmission budget) pushed
    through the ingest kernel: identical apply/record/enqueue semantics
    (``POST /v1/transactions`` commit, reference ``public/mod.rs:177-256``),
    one kernel launch."""
    from corrosion_tpu.ops.dense import lookup_cols
    from corrosion_tpu.sim.broadcast import hlc_tick

    n = cfg.n_nodes
    iarr = jnp.arange(n, dtype=jnp.int32)
    if getattr(cfg, "any_writer", False):
        w = write_mask
    else:
        w = write_mask & (iarr < cfg.n_origins)
    if clp is None:
        clp = jnp.zeros(n, jnp.int32)

    dbv = cst.next_dbv
    cur_ver = lookup_cols(cst.store[0], cell[:, None])[:, 0]
    ts, _ = hlc_tick(cst.hlc, cst.now, w)
    # the kernel's HLC fold lands the same stamp: max(hlc, ts) == ts for
    # writers (hlc_tick is strictly ahead), untouched for others
    out = ingest_changes_fused(
        cfg, cst,
        w[:, None],
        iarr[:, None],
        dbv[:, None],
        cell[:, None],
        (cur_ver + 1)[:, None],
        val[:, None],
        iarr[:, None],
        clp[:, None],
        ts[:, None],
        m_budget=jnp.full((n, 1), int(cfg.bcast_max_transmissions),
                          jnp.int32),
        # a node never drift-rejects its own stamp (the unfused
        # local_write commits unconditionally) — disable rejection here
        drift_rounds=1 << 20,
        rand=rand,
        carried=carried,
        # the writer is the source of truth: its commit disseminates
        # even when its own bookkeeping slot is contended
        enqueue_all=True,
        interpret=interpret,
    )
    # emission only happens when pig_changes > 0 too — match the callee's
    # condition by unpacking on the actual return arity
    emitted = None
    if len(out) == 3:
        cst2, _, emitted = out
    else:
        cst2, _ = out
    cst2 = cst2._replace(next_dbv=jnp.where(w, dbv + 1, cst.next_dbv))
    if emitted is not None:
        return cst2, emitted
    return cst2


def _swim_kernel(consts, *refs):
    """Loads one node block's planes and defers to the shared row-local
    transform ``sim.scale.swim_tables_update`` — the pallas and XLA paths
    execute literally the same function, so they cannot drift."""
    from corrosion_tpu.sim.scale import swim_tables_update

    (mem_id_ref, mem_view_ref, old_id_ref, old_view_ref, timer_ref,
     tx_ref, alive_ref, inc_ref, node_id_ref, self_slot_ref, sus_ref,
     sends_ref, probe_slot_ref, suspect_key_ref, failed_ref) = refs[:15]
    ch_refs = refs[15:15 + 4 * 6]
    (o_id, o_view, o_timer, o_tx, o_inc, o_refute) = refs[15 + 4 * 6:]

    vec = lambda r: r[:][:, 0]  # noqa: E731 — [B,1] operand to [B]
    ch_in_id = [ch_refs[i][:] for i in range(4)]
    ch_in_view = [ch_refs[4 + i][:] for i in range(4)]
    ch_in_send = [ch_refs[8 + i][:] != 0 for i in range(4)]
    ch_valid = [vec(ch_refs[12 + i]) != 0 for i in range(4)]
    ch_snd = [vec(ch_refs[16 + i]) for i in range(4)]
    ch_snd_inc = [vec(ch_refs[20 + i]) for i in range(4)]

    mem_id, mem_view, timer, tx, inc, refute = swim_tables_update(
        consts,
        mem_id_ref[:], mem_view_ref[:], old_id_ref[:], old_view_ref[:],
        timer_ref[:], tx_ref[:],
        vec(alive_ref) != 0, vec(inc_ref), vec(node_id_ref),
        vec(self_slot_ref), vec(sus_ref), vec(sends_ref),
        vec(probe_slot_ref), vec(suspect_key_ref), vec(failed_ref) != 0,
        ch_in_id, ch_in_view, ch_in_send, ch_valid, ch_snd, ch_snd_inc,
    )
    o_id[:] = mem_id
    o_view[:] = mem_view
    # narrowed configs store timer/budget planes int16: mid-kernel
    # promotion is free, the store casts back to the plane dtype —
    # corrolint's dtype-widen rule (analysis/dtypes.py NARROW_REFS)
    # enforces exactly this cast-at-the-store shape
    o_timer[:] = timer.astype(o_timer.dtype)
    o_tx[:] = tx.astype(o_tx.dtype)
    o_inc[:] = inc[:, None]
    o_refute[:] = refute.astype(jnp.int32)[:, None]


def swim_tables_fused(
    consts,
    mem_id, mem_view, old_id, old_view, mem_timer, mem_tx,
    alive, inc, node_id, self_slot, sus_heard, sends,
    probe_slot, suspect_key, probe_failed,
    ch_in_id, ch_in_view, ch_in_send, ch_valid, ch_snd, ch_snd_inc,
    *, interpret: Optional[bool] = None,
):
    """Pallas-fused form of ``sim.scale.swim_tables_update`` (same
    argument order; channel groups as length-4 lists). No config object
    reaches this layer: callers resolve the knob and pass
    ``interpret=fused_interpret(cfg)`` (None = backend default). The
    donated-carry/narrow-dtype contract is the same as
    :func:`ingest_changes_fused` — the timer/budget out-ref stores cast
    back to the plane dtype (see ``_swim_kernel``)."""
    if interpret is None:
        interpret = _backend() == "cpu"
    n, m = mem_id.shape
    blk = _block_size(n)

    def col(v):
        return v.astype(jnp.int32)[:, None]

    in_arrays = (
        [mem_id, mem_view, old_id, old_view, mem_timer, mem_tx,
         col(alive), col(inc), col(node_id), col(self_slot),
         col(sus_heard), col(sends),
         col(probe_slot), col(suspect_key), col(probe_failed)]
        + list(ch_in_id)
        + list(ch_in_view)
        + [p.astype(jnp.int32) for p in ch_in_send]
        + [col(v) for v in ch_valid]
        + [col(v) for v in ch_snd]
        + [col(v) for v in ch_snd_inc]
    )

    def spec(width):
        return pl.BlockSpec((blk, width), lambda i: (i, 0))

    in_specs = [spec(a.shape[1]) for a in in_arrays]
    out_shapes = (
        [jax.ShapeDtypeStruct((n, m), jnp.int32)] * 2
        + [jax.ShapeDtypeStruct((n, m), mem_timer.dtype),
           jax.ShapeDtypeStruct((n, m), mem_tx.dtype)]
        + [jax.ShapeDtypeStruct((n, 1), jnp.int32)] * 2
    )
    out_specs = [spec(s.shape[1]) for s in out_shapes]

    outs = pl.pallas_call(
        functools.partial(_swim_kernel, consts),
        grid=(n // blk,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shapes,
        interpret=interpret,
    )(*in_arrays)
    mem_id, mem_view, timer, tx, inc_o, refute = outs
    return mem_id, mem_view, timer, tx, inc_o[:, 0], refute[:, 0] != 0
