"""Partial-changeset buffering — multi-cell transactions under jit.

The reference chunks a transaction's changeset over the wire
(``ChunkedChanges``, ``crates/corro-types/src/change.rs:66-178``): one
``db_version`` carries cells stamped ``seq`` 0..last_seq, possibly split
across packets. Receivers buffer partial seq ranges per version in
``__corro_buffered_changes`` + ``__corro_seq_bookkeeping`` and only
apply/expose the version once the whole range is present
(``process_incomplete_version`` -> ``process_fully_buffered_changes``,
``crates/corro-agent/src/agent/util.rs:1061-1194,546-696``) — that is
what makes a multi-statement transaction atomic in remote readers' eyes.

Array re-design: per node, a fixed pool of P partial slots keyed by
``(origin, db_version)``. Each slot holds a received-``seq`` bitmask
(int32, so ``seq < 31``) plus K payload lanes, one per seq. Arriving
cells match-or-allocate a slot, set their seq bit, and park their
payload; a slot whose mask covers ``0..nseq-1`` is *complete* — its
cells apply to the LWW store in one batch, the version records into the
``Book``, the slot frees. Slot-pool overflow drops the cell (the
reference's queue-cap policy); anti-entropy repairs, because sync
transfers whole versions from the peer's *store*, which by construction
only ever contains completed versions.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from corrosion_tpu.ops.slots import alloc_slots, scatter_rows

NO_SLOT = np.int32(-1)  # np scalar: safe to close over in pallas kernels


class Partials(NamedTuple):
    """Per-node partial-version buffer: [N, P] keys + [N, P, K] payloads."""

    origin: jax.Array  # int32 [N, P], -1 = free
    dbv: jax.Array  # int32 [N, P]
    mask: jax.Array  # int32 [N, P] — bitmask of received seqs
    nseq: jax.Array  # int32 [N, P] — total seqs in the version
    cell: jax.Array  # int32 [N, P, K]
    ver: jax.Array  # int32 [N, P, K]
    val: jax.Array  # int32 [N, P, K]
    site: jax.Array  # int32 [N, P, K]
    clp: jax.Array  # int32 [N, P, K]

    @staticmethod
    def create(n_nodes: int, p_slots: int, k_seqs: int) -> "Partials":
        if not 1 <= k_seqs <= 30:
            raise ValueError(
                f"k_seqs {k_seqs} not in 1..30 (seq bitmask lives in "
                f"an int32)"
            )
        z2 = lambda: jnp.zeros((n_nodes, p_slots), jnp.int32)  # noqa: E731
        z3 = lambda: jnp.zeros((n_nodes, p_slots, k_seqs), jnp.int32)  # noqa: E731
        return Partials(
            origin=jnp.full((n_nodes, p_slots), NO_SLOT, jnp.int32),
            dbv=z2(), mask=z2(), nseq=z2(),
            cell=z3(), ver=z3(), val=z3(), site=z3(), clp=z3(),
        )


def ingest_partials(par: Partials, live, m_origin, m_dbv, m_seq, m_nseq,
                    m_cell, m_ver, m_val, m_site, m_clp):
    """Buffer a per-node batch of partial-changeset cells.

    All message fields int32 [N, M]; ``live`` bool [N, M] marks candidate
    cells (caller has already dropped stale/seen versions). Returns
    ``(par, fresh)`` — ``fresh`` [N, M] marks cells newly buffered (the
    per-seq dedupe; fresh cells re-broadcast, duplicates drop — the seq
    overlap check of ``process_incomplete_version``, ``util.rs:1090``).
    """
    n, p = par.origin.shape
    k = par.cell.shape[2]
    m = m_origin.shape[1]

    # --- match existing slots -------------------------------------------
    slot_live = par.origin != NO_SLOT  # [N, P]
    eq = (
        live[:, :, None]
        & slot_live[:, None, :]
        & (par.origin[:, None, :] == m_origin[:, :, None])
        & (par.dbv[:, None, :] == m_dbv[:, :, None])
    )  # [N, M, P]
    has_match = jnp.any(eq, axis=2)
    match_slot = jnp.argmax(eq, axis=2).astype(jnp.int32)

    # --- group the batch by (origin, dbv); allocate one slot per leader --
    same_key = (
        live[:, :, None]
        & live[:, None, :]
        & (m_origin[:, :, None] == m_origin[:, None, :])
        & (m_dbv[:, :, None] == m_dbv[:, None, :])
    )  # [N, M, M'] — does message i share a key with message j
    leader_idx = jnp.argmax(same_key, axis=2).astype(jnp.int32)  # first j
    is_leader = live & (leader_idx == jnp.arange(m, dtype=jnp.int32)[None, :])
    seq_ok = (m_seq >= 0) & (m_seq < k) & (m_nseq >= 1) & (m_nseq <= k)
    alloc_want = is_leader & ~has_match & seq_ok
    free = ~slot_live
    slot_alloc, placed = alloc_slots(free, alloc_want)
    l_placed = jnp.take_along_axis(placed, leader_idx, axis=1)
    l_slot = jnp.take_along_axis(slot_alloc, leader_idx, axis=1)
    slot = jnp.where(has_match, match_slot, l_slot)
    found = has_match | (live & ~has_match & l_placed)

    # --- per-seq dedupe --------------------------------------------------
    seqc = jnp.clip(m_seq, 0, k - 1)
    bit = (jnp.int32(1) << seqc).astype(jnp.int32)
    pre_mask = jnp.where(
        has_match,
        jnp.take_along_axis(par.mask, jnp.clip(slot, 0, p - 1), axis=1),
        0,
    )
    already = (pre_mask >> seqc) & 1 == 1
    earlier = jnp.tril(jnp.ones((m, m), bool), k=-1)
    dup = jnp.any(
        same_key & (m_seq[:, :, None] == m_seq[:, None, :]) & earlier[None],
        axis=2,
    )
    fresh = live & found & seq_ok & ~already & ~dup

    # --- scatter: slot keys, nseq, mask bits, payload lanes --------------
    origin2 = scatter_rows(par.origin, slot_alloc, placed, m_origin)
    dbv2 = scatter_rows(par.dbv, slot_alloc, placed, m_dbv)
    rows = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[:, None], (n, m))
    flat_slot = jnp.where(fresh, rows * p + slot, n * p)
    nseq2 = (
        par.nseq.reshape(-1)
        .at[flat_slot.reshape(-1)]
        .max(m_nseq.reshape(-1), mode="drop")
        .reshape(n, p)
    )
    # each fresh cell adds a bit not yet set (dedupe above), so add == or
    mask2 = (
        par.mask.reshape(-1)
        .at[flat_slot.reshape(-1)]
        .add(jnp.where(fresh, bit, 0).reshape(-1), mode="drop")
        .reshape(n, p)
    )
    flat_lane = jnp.where(fresh, (rows * p + slot) * k + seqc, n * p * k)

    def put(dest, v):
        return (
            dest.reshape(-1)
            .at[flat_lane.reshape(-1)]
            .set(v.reshape(-1), mode="drop")
            .reshape(n, p, k)
        )

    par = Partials(
        origin=origin2, dbv=dbv2, mask=mask2, nseq=nseq2,
        cell=put(par.cell, m_cell), ver=put(par.ver, m_ver),
        val=put(par.val, m_val), site=put(par.site, m_site),
        clp=put(par.clp, m_clp),
    )
    return par, fresh


def complete_mask(par: Partials):
    """Which slots hold every seq of their version (``0..nseq-1`` all
    present) — ready for the atomic apply (the gap-closed trigger of
    ``process_fully_buffered_changes``, ``util.rs:546-696``)."""
    full_bits = (jnp.int32(1) << par.nseq) - 1
    return (par.origin != NO_SLOT) & (par.nseq > 0) & (par.mask == full_bits)


def free_slots(par: Partials, drop):
    """Release slots marked by ``drop`` bool [N, P]."""
    return par._replace(
        origin=jnp.where(drop, NO_SLOT, par.origin),
        dbv=jnp.where(drop, 0, par.dbv),
        mask=jnp.where(drop, 0, par.mask),
        nseq=jnp.where(drop, 0, par.nseq),
    )


def drop_stale_partials(par: Partials, book):
    """Free slots whose version is already at/below the node's head for
    that origin — the version arrived whole via sync (store merge + head
    jump), so the buffered fragments are garbage (the reference's
    buffered-meta GC, ``clear_buffered_meta_loop``, ``util.rs:430-490``).
    The origin's head lives at its hash slot and counts only while the
    slot tracks that actor (round 4, ``versions.Book``)."""
    from corrosion_tpu.ops.versions import org_slot

    live = par.origin != NO_SLOT
    slot, owned = org_slot(book, par.origin)
    h = jnp.take_along_axis(
        book.head, jnp.clip(slot, 0, book.head.shape[1] - 1), axis=1
    )
    return free_slots(par, live & owned & (par.dbv <= h))
