"""Random-candidate selection primitives.

The reference picks gossip fanout sets, sync peers, and probe subjects
by sampling from its member list (``choose_broadcast_members``,
``crates/corro-agent/src/broadcast/mod.rs:653-713``; sync peer sampling
``agent/handlers.rs:808-863``). Vectorized: score every candidate with a
uniform draw, mask out non-candidates, take ``top_k`` / ``argmax`` —
a uniform random sample without replacement per row.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import jax.random as jr


def sample_k(mask: jax.Array, k: int, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-row uniform sample of ``k`` distinct columns where ``mask``.

    ``mask`` bool [N, C]. Returns ``(cols, ok)``: int32 [N, k] column
    indices and bool [N, k] validity (rows with fewer than ``k``
    candidates return fewer valid picks).
    """
    scores = jnp.where(mask, jr.uniform(key, mask.shape), -1.0)
    val, cols = jax.lax.top_k(scores, k)
    return cols.astype(jnp.int32), val >= 0


def sample_k_biased(mask: jax.Array, bonus: jax.Array, k: int,
                    key: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Like :func:`sample_k` but with a per-candidate score ``bonus``
    added to the uniform draw. A bonus >= 1 gives *strict* priority over
    un-bonused candidates (uniform draws live in [0, 1)); fractional
    bonuses give a soft preference. This is how the reference's ordered
    choices vectorize: ring0-first broadcast fanout
    (``broadcast/mod.rs:653-713``) and ring-sorted sync peers
    (``handlers.rs:808-863``)."""
    scores = jnp.where(mask, jr.uniform(key, mask.shape) + bonus, -1.0)
    val, cols = jax.lax.top_k(scores, k)
    return cols.astype(jnp.int32), val >= 0


def sample_one(mask: jax.Array, key: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-row uniform sample of one column where ``mask``; (col, ok)."""
    scores = jnp.where(mask, jr.uniform(key, mask.shape), -1.0)
    col = jnp.argmax(scores, axis=1).astype(jnp.int32)
    return col, jnp.any(mask, axis=1)
