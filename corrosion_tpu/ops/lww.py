"""Last-write-wins merge kernels — the TPU-native equivalent of CR-SQLite.

The reference ships the CRDT engine as a prebuilt native SQLite extension
(``crates/corro-types/crsqlite-linux-x86_64.so``, loaded at
``crates/corro-types/src/sqlite.rs:121-139``). Its per-column LWW merge rule
(reference ``doc/crdts.md:14-16`` and ``doc/crdts.md:237``) is:

1. biggest ``col_version`` wins;
2. tie -> biggest ``value`` wins (SQLite ``max()`` ordering);
3. tie -> biggest ``site_id`` wins.

Here that rule is an elementwise lexicographic max over three int32 key
planes ``(col_version, value, site_id)``; each cell also carries the
origin's ``db_version`` as a payload plane (cr-sqlite clock rows keep
``db_version`` alongside, which is what anti-entropy sync ranges over).
A whole-store merge of two replicas is one fused elementwise op; merging a
batch of in-flight changes addressed at arbitrary cells is a lexicographic
segment-argmax followed by one scatter. Everything is int32 to stay on the
TPU's native integer path (no x64 emulation).

SWIM membership views use the same trick with a *packed* single-word key:
``incarnation * 4 + state_precedence`` so that "higher incarnation wins;
same incarnation: Down > Suspect > Alive" (foca's invariants; the reference
uses ``foca = 0.16``, ``Cargo.toml:28``) becomes plain ``maximum`` /
``segment_max`` / ``.at[].max`` on one int32 plane.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

# a numpy scalar, NOT a jnp array: module-level device arrays referenced
# inside a pallas kernel body become captured jaxpr constants, which the
# TPU lowering rejects ("captures constants [i32[]]"); np scalars inline
# as literals on every path
INT32_MIN = np.int32(-2147483648)

# SWIM member states, ordered by same-incarnation precedence
# (Down > Suspect > Alive), matching foca's update semantics.
STATE_ALIVE = 0
STATE_SUSPECT = 1
STATE_DOWN = 2


def lex_wins(a: Sequence[jax.Array], b: Sequence[jax.Array]) -> jax.Array:
    """Elementwise: does tuple ``a`` win (>=) against ``b`` lexicographically?

    With keys ``(col_version, value, site_id)`` this is exactly the LWW rule
    of ``doc/crdts.md:237``. Full ties keep ``a`` (the incumbent) — a full
    tie means an identical change, so it is immaterial.
    """
    if len(a) != len(b) or len(a) < 1:
        raise ValueError(
            f"key tuples must have equal nonzero length, got "
            f"{len(a)}/{len(b)}"
        )
    # Build from the last key up: wins_k = a_k > b_k | (a_k == b_k & wins_{k+1})
    wins = a[-1] >= b[-1]
    for ak, bk in zip(reversed(a[:-1]), reversed(b[:-1])):
        wins = (ak > bk) | ((ak == bk) & wins)
    return wins


def lex_max(
    a: Sequence[jax.Array], b: Sequence[jax.Array], *payloads
) -> Tuple[jax.Array, ...]:
    """Elementwise lexicographic max over key tuples, carrying payloads.

    ``payloads`` are ``(pa, pb)`` pairs selected by the same winner mask.
    Returns ``(*keys, *selected_payloads)``.
    """
    wins = lex_wins(a, b)
    keys = tuple(jnp.where(wins, ak, bk) for ak, bk in zip(a, b))
    extra = tuple(jnp.where(wins, pa, pb) for pa, pb in payloads)
    return keys + extra


def lex_segment_argmax(
    keys: Sequence[jax.Array], segment_ids: jax.Array, num_segments: int
) -> Tuple[jax.Array, jax.Array]:
    """Index of the lexicographically-largest key tuple per segment.

    One ``segment_max`` pass per key, masking losers with ``INT32_MIN``
    between passes — no int64 packing needed. Returns ``(argmax, nonempty)``
    where ``argmax`` is a global index into the batch (arbitrary member of
    the winner class for exact ties) and ``nonempty`` marks segments that
    received at least one live entry. Entries the caller wants ignored must
    be routed to a scratch segment beforehand.
    """
    alive = None
    for k in keys:
        kk = k if alive is None else jnp.where(alive, k, INT32_MIN)
        m = jax.ops.segment_max(kk, segment_ids, num_segments=num_segments)
        this = kk == m[segment_ids]
        alive = this if alive is None else (alive & this)
    idxs = jnp.arange(segment_ids.shape[0], dtype=jnp.int32)
    winner = jax.ops.segment_max(
        jnp.where(alive, idxs, jnp.int32(-1)), segment_ids, num_segments=num_segments
    )
    return jnp.maximum(winner, 0), winner >= 0


def merge_store(store, incoming):
    """Merge two whole LWW stores.

    A store is ``(ver, val, site, dbv, clp)`` planes, all int32 of
    identical shape: three LWW clock planes, the origin-db_version
    payload plane, and the **causal-length lifetime** plane ``clp`` — the
    row causal length (cr-sqlite ``cl``, ``doc/crdts.md:24-40``) current
    when the cell was written. The merge key is ``(clp, ver, val, site)``:
    a write from a later row lifetime beats any write from an earlier one
    regardless of col_version (cr-sqlite's "greater causal length wins"),
    and within a lifetime the plain LWW rule applies. This is the array
    analog of replaying every row of a remote ``crsql_changes`` into the
    local db (``INSERT INTO crsql_changes``, reference
    ``crates/corro-agent/src/agent/util.rs:1233``): each cell resolves
    independently.
    """
    a, b = store, incoming
    m_clp, m_ver, m_val, m_site, m_dbv = lex_max(
        (a[4], a[0], a[1], a[2]), (b[4], b[0], b[1], b[2]), (a[3], b[3])
    )
    return (m_ver, m_val, m_site, m_dbv, m_clp)


def apply_changes_to_store(store, flat_idx, ver, val, site, dbv, clp, valid):
    """Apply a batch of addressed changes to a flattened LWW store.

    ``store``: ``(ver, val, site, dbv, clp)`` planes flattened to 1-D
    size S. ``flat_idx`` int32 [M] target cell per change; ``valid`` bool
    [M] (invalid changes route to scratch segment S and vanish). Merge
    key per cell: ``(clp, ver, val, site)`` — see :func:`merge_store`.

    Matches applying a batch of remote changes in one SQLite tx
    (``process_multiple_changes``, reference
    ``crates/corro-agent/src/agent/util.rs:699``): order within the batch is
    irrelevant because the LWW join is commutative and associative — that is
    what makes it a CRDT and what lets the simulator apply a whole gossip
    round's message soup in one fused op.
    """
    s_ver, s_val, s_site, s_dbv, s_clp = store
    size = s_ver.shape[0]
    seg = jnp.where(valid, flat_idx, size).astype(jnp.int32)
    win, nonempty = lex_segment_argmax(
        (clp, ver, val, site), seg, num_segments=size + 1
    )
    win, nonempty = win[:size], nonempty[:size]
    b = (clp[win], ver[win], val[win], site[win], dbv[win])
    m_clp, m_ver, m_val, m_site, m_dbv = lex_max(
        (s_clp, s_ver, s_val, s_site), b[:4], (s_dbv, b[4])
    )
    return (
        jnp.where(nonempty, m_ver, s_ver),
        jnp.where(nonempty, m_val, s_val),
        jnp.where(nonempty, m_site, s_site),
        jnp.where(nonempty, m_dbv, s_dbv),
        jnp.where(nonempty, m_clp, s_clp),
    )


def apply_changes_cols(store, cell, ver, val, site, dbv, clp, valid):
    """Apply per-node message batches to [N, C] store planes — the
    column-loop (TPU) form of :func:`apply_changes_to_store`.

    ``store``: ``(ver, val, site, dbv, clp)`` planes [N, C]; messages are
    [N, M] with ``cell`` the target column per message. Per column: mask
    the messages addressing it, reduce the lexicographic max along the
    message axis (successive masking passes, one per key — same scheme as
    :func:`lex_segment_argmax` without the scatters), then merge with the
    incumbent. All reductions are over the small static M axis — no
    per-element scatter/gather (see ``ops/dense.py`` for why).
    """
    s_ver, s_val, s_site, s_dbv, s_clp = store
    n, c_cnt = s_ver.shape
    keys_in = (clp, ver, val, site)
    out = ([], [], [], [], [])
    for c in range(c_cnt):
        alive = valid & (cell == c)
        nonempty = jnp.any(alive, axis=1)
        mx = []
        for k in keys_in:
            kk = jnp.where(alive, k, INT32_MIN)
            m = jnp.max(kk, axis=1)
            alive = alive & (kk == m[:, None])
            mx.append(m)
        # ties carry identical keys (a (site, ver) pair names one change),
        # so any tied payload is the change's payload
        b_dbv = jnp.max(jnp.where(alive, dbv, INT32_MIN), axis=1)
        a = (s_clp[:, c], s_ver[:, c], s_val[:, c], s_site[:, c])
        m_clp, m_ver, m_val, m_site, m_dbv = lex_max(
            a, tuple(mx), (s_dbv[:, c], b_dbv)
        )
        for dst, merged, cur in zip(
            out, (m_ver, m_val, m_site, m_dbv, m_clp),
            (s_ver[:, c], s_val[:, c], s_site[:, c], s_dbv[:, c], s_clp[:, c]),
        ):
            dst.append(jnp.where(nonempty, merged, cur))
    return tuple(jnp.stack(cols, axis=1) for cols in out)


def pack_inc_state(incarnation, state):
    """Pack (incarnation, member-state) into one comparable int32.

    ``incarnation * 4 + state`` — so ordinary ``max`` implements foca's
    update precedence: higher incarnation always wins; within an
    incarnation Down(2) > Suspect(1) > Alive(0). Incarnations stay well
    below 2**29 (they bump only on refute/rejoin, reference
    ``crates/corro-types/src/actor.rs:199-210``).
    """
    return incarnation * 4 + state


def unpack_inc_state(packed):
    return packed >> 2, packed & 3
