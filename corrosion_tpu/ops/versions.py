"""Per-origin version bookkeeping — the array analog of ``BookedVersions``.

The reference tracks, per (node, origin-actor): applied versions, partially
applied versions, and *gaps* (needed ranges) in a rangemap mirrored into
``__corro_bookkeeping_gaps`` (``crates/corro-types/src/agent.rs:1270-1604``,
gap algebra ``compute_gaps_change`` at ``agent.rs:1179-1244``). Gaps drive
anti-entropy sync need computation (``crates/corro-types/src/sync.rs:127``),
and the seen-check dedupes re-broadcasts
(``crates/corro-agent/src/agent/handlers.rs:548-786``).

Array re-design (no dynamic rangemaps): because the LWW join is commutative
and associative, a change can be *applied* to the store the moment it
arrives, in any order; bookkeeping only needs to know WHICH origin-versions
have been seen. Per (node, origin) we keep

- ``head``      int32 [N, O]: all origin-versions ``1..head`` seen
  (contiguous prefix — the complement of the reference's gap set),
- ``known_max`` int32 [N, O]: highest origin-version heard of (gossiped
  alongside changes; bounds need computation),

plus a bounded per-node out-of-order buffer of seen versions beyond the
head — ``buf_origin``/``buf_ver`` int32 [N, K], free slots marked -1 —
the analog of the reference's partials/gap bookkeeping with the queue-cap
drop policy of ``handle_changes`` (overflow drops; sync repairs later).

Head advance ("gaps closing") is a sort + segmented boolean scan, fully
jittable and batched over all nodes at once.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from corrosion_tpu.ops.dense import (
    lookup_cols,
    scatter_cols_add,
    scatter_cols_max,
)
from corrosion_tpu.ops.slots import alloc_slots, scatter_rows

NO_ORIGIN = jnp.int32(-1)  # free buffer slot marker


class Book(NamedTuple):
    """Version bookkeeping for all N simulated nodes over O origins."""

    head: jax.Array  # int32 [N, O]
    known_max: jax.Array  # int32 [N, O]
    buf_origin: jax.Array  # int32 [N, K], -1 = free
    buf_ver: jax.Array  # int32 [N, K]

    @staticmethod
    def create(n_nodes: int, n_origins: int, buf_slots: int) -> "Book":
        return Book(
            head=jnp.zeros((n_nodes, n_origins), jnp.int32),
            known_max=jnp.zeros((n_nodes, n_origins), jnp.int32),
            buf_origin=jnp.full((n_nodes, buf_slots), NO_ORIGIN, jnp.int32),
            buf_ver=jnp.zeros((n_nodes, buf_slots), jnp.int32),
        )


def record_versions(book: Book, origin, ver, valid):
    """Record a per-node batch of incoming (origin, version) pairs.

    ``origin``/``ver``: int32 [N, M] — up to M messages per node this round;
    ``valid``: bool [N, M]. Returns ``(book, fresh)`` where ``fresh`` [N, M]
    marks messages not seen before by that node (the seen-cache check of
    ``handle_changes``, reference ``handlers.rs:548-786`` — fresh changes
    get applied and re-broadcast, stale ones dropped).

    Fresh messages are placed into free buffer slots (overflow → dropped,
    like the bounded processing queue, ``config.rs:15-27``; sync repairs),
    then heads advance over any newly-closed gaps.
    """
    # --- seen-checks -----------------------------------------------------
    seen = seen_versions(book, origin, ver, valid)
    # dedupe within the batch: keep only the first of identical (o, v) pairs
    same = (
        (origin[:, :, None] == origin[:, None, :])
        & (ver[:, :, None] == ver[:, None, :])
        & valid[:, None, :]
    )
    m = origin.shape[1]
    earlier = jnp.tril(jnp.ones((m, m), bool), k=-1)
    dup_in_batch = jnp.any(same & earlier[None, :, :], axis=2)

    fresh = valid & ~seen & ~dup_in_batch

    # --- slot allocation (per node, vectorized) --------------------------
    free = book.buf_origin == NO_ORIGIN
    slot, placed = alloc_slots(free, fresh)
    buf_origin = scatter_rows(book.buf_origin, slot, placed, origin)
    buf_ver = scatter_rows(book.buf_ver, slot, placed, ver)

    known_max = _scatter_max(book.known_max, origin, ver, valid)
    book = Book(book.head, known_max, buf_origin, buf_ver)
    return advance_heads(book), fresh


def _scatter_max(dest, origin, ver, valid):
    """``dest[i, origin[i,j]] = max(dest, ver[i,j])`` where valid."""
    return scatter_cols_max(dest, origin, ver, valid)


def bump_known_max(book: Book, origin, ver, valid) -> Book:
    """Raise ``known_max`` for heard-of (origin, version) pairs without
    recording them as seen — hearing a *fragment* of a chunked version
    still teaches a node the version exists (drives need computation and
    sync peer choice) even though the version is not applied until its
    seq range completes (``partial_need`` in ``SyncStateV1``, reference
    ``crates/corro-types/src/sync.rs:80``)."""
    return book._replace(
        known_max=_scatter_max(book.known_max, origin, ver, valid)
    )


def seen_versions(book: Book, origin, ver, valid):
    """Has this node already *fully* seen each (origin, version)? bool
    [N, M] — true when the version is at/below the contiguous head or
    parked in the out-of-order buffer (the seen-cache + bookie check of
    ``handle_changes``, ``handlers.rs:548-786``)."""
    behind_head = ver <= lookup_cols(book.head, origin)
    in_buffer = jnp.any(
        (book.buf_origin[:, None, :] == origin[:, :, None])
        & (book.buf_ver[:, None, :] == ver[:, :, None]),
        axis=2,
    )
    return valid & (behind_head | in_buffer)


def advance_heads(book: Book) -> Book:
    """Advance per-(node, origin) heads over buffered contiguous runs.

    The jittable replacement for the reference's gap-merge
    (``compute_gaps_change``, ``agent.rs:1179-1244``): sort each node's
    buffer by (origin, version), then a segmented boolean affine scan marks
    every entry reachable from its origin's head by a contiguous chain;
    reachable entries advance the head and free their slots. One pass
    suffices because the sort groups each origin's chain contiguously.
    """
    n_nodes, n_slots = book.buf_origin.shape
    n_origins = book.head.shape[1]

    free = book.buf_origin == NO_ORIGIN
    o_key = jnp.where(free, jnp.int32(n_origins), book.buf_origin)

    # lexsort by (origin, version), batched over nodes: two stable
    # argsort passes (a vmapped jnp.lexsort lowers to per-row sorts on
    # TPU; the batched form is one [N, K] sort kernel per pass); the
    # permutation applications go through lookup_cols — per-element
    # gathers are the op class the dense kernels exist to avoid
    order1 = jnp.argsort(book.buf_ver, axis=1, stable=True).astype(jnp.int32)
    o1 = lookup_cols(o_key, order1)
    order2 = jnp.argsort(o1, axis=1, stable=True).astype(jnp.int32)
    order = lookup_cols(order1, order2)
    o_s = lookup_cols(o_key, order)
    v_s = lookup_cols(book.buf_ver, order)

    head_at = lookup_cols(book.head, o_s)
    live = o_s < n_origins
    start = live & (v_s == head_at + 1)
    chain = (
        live
        & (o_s == jnp.roll(o_s, 1, axis=1))
        & (v_s == jnp.roll(v_s, 1, axis=1) + 1)
    )
    chain = chain.at[:, 0].set(False)

    # consumable[i] = start[i] | (chain[i] & consumable[i-1]) — an affine
    # boolean recurrence; solve with an associative scan over map
    # composition (c, s) ∘ (c', s') = (c & c', s | (c & s')).
    def compose(g1, g2):
        c1, s1 = g1
        c2, s2 = g2
        return c1 & c2, s2 | (c2 & s1)

    _, consumable = jax.lax.associative_scan(compose, (chain, start), axis=1)

    head = scatter_cols_max(book.head, o_s, v_s, consumable)

    # free consumed slots and any slot at/below the (possibly jumped) head
    head_after = lookup_cols(head, o_s)
    drop = consumable | (live & (v_s <= head_after))
    o_out = jnp.where(drop, NO_ORIGIN, jnp.where(live, o_s, NO_ORIGIN))
    v_out = jnp.where(drop | ~live, 0, v_s)
    return Book(head, jnp.maximum(book.known_max, head), o_out, v_out)


def needs_count(book: Book) -> jax.Array:
    """Outstanding need per (node, origin): versions heard of but not seen.

    ``known_max - head - |buffered in (head, known_max]|`` — the scalar
    magnitude of the reference's gap set, used both for sync peer choice
    ("most needed versions first", ``handlers.rs:808-863``) and as the
    convergence predicate (no needs + equal heads — the same check as the
    reference's ``check_bookkeeping.py`` Antithesis driver).
    """
    live = book.buf_origin != NO_ORIGIN
    o = book.buf_origin
    above_head = book.buf_ver > lookup_cols(book.head, o)
    counted = live & above_head
    buffered = scatter_cols_add(
        jnp.zeros(book.head.shape, jnp.int32), o,
        jnp.ones(o.shape, jnp.int32), counted,
    )
    return jnp.maximum(book.known_max - book.head, 0) - buffered
