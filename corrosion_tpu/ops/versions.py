"""Per-origin version bookkeeping — the array analog of ``BookedVersions``.

The reference tracks, per (node, origin-actor): applied versions, partially
applied versions, and *gaps* (needed ranges) in a rangemap mirrored into
``__corro_bookkeeping_gaps`` (``crates/corro-types/src/agent.rs:1270-1604``,
gap algebra ``compute_gaps_change`` at ``agent.rs:1179-1244``). Gaps drive
anti-entropy sync need computation (``crates/corro-types/src/sync.rs:127``),
and the seen-check dedupes re-broadcasts
(``crates/corro-agent/src/agent/handlers.rs:548-786``).

Array re-design (no dynamic rangemaps): because the LWW join is commutative
and associative, a change can be *applied* to the store the moment it
arrives, in any order; bookkeeping only needs to know WHICH origin-versions
have been seen. Per (node, origin) we keep

- ``head``      int32 [N, O]: all origin-versions ``1..head`` seen
  (contiguous prefix — the complement of the reference's gap set),
- ``known_max`` int32 [N, O]: highest origin-version heard of (gossiped
  alongside changes; bounds need computation),
- ``seen``      uint32 [N, O, W]: a head-relative *bit window* — bit ``b``
  of word ``w`` set means origin-version ``head + 1 + 32*w + b`` has been
  seen out of order. The window is the bounded out-of-order buffer analog
  of the reference's partials/gap bookkeeping with the queue-cap drop
  policy of ``handle_changes`` (versions beyond ``head + 32*W`` drop;
  anti-entropy sync repairs them later).

Everything — seen-checks, recording, head advance ("gaps closing"), need
counts — is elementwise integer/bit arithmetic: no sorts, no scans, no
data-dependent gathers, exactly the op mix the TPU runs at full HBM
bandwidth (see ``ops/dense.py`` for why that matters on this backend).
Head advance is "count trailing ones, shift the window".
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from corrosion_tpu.ops.dense import (
    lookup_cols,
    scatter_cols_max,
    scatter_cols_or,
)

_ONES = np.uint32(0xFFFFFFFF)  # np scalar: safe to close over in pallas kernels


class Book(NamedTuple):
    """Version bookkeeping for all N simulated nodes over O origin SLOTS.

    Round 4 (unbounded writer set): the reference books versions for
    every *observed* actor (``agent.rs:1270-1604`` keeps a
    ``BookedVersions`` per actor id in a map) — ANY node may write. The
    array analog is a bounded hash-slotted origin table, the same trick
    as the SWIM member table: origin ``x`` hashes to slot ``x % O``;
    ``org_id`` records which actor a slot currently tracks. A write
    from an untracked actor claims a free slot, or evicts an *idle*
    occupant (no fresh activity for ``org_keep_rounds``) — the evicted
    actor's dedupe/gap state is lost and anti-entropy sync rebuilds it,
    exactly the bounded-resource degradation the member table accepts.
    Initialization identity-claims slot ``s`` for actor ``s``, which
    reproduces the legacy fixed-pool semantics bit-for-bit while every
    writer id stays below O (no collisions ⇒ no evictions).
    """

    head: jax.Array  # int32 [N, O]
    known_max: jax.Array  # int32 [N, O]
    seen: jax.Array  # uint32 [N, O, W] — head-relative seen-bit window
    org_id: jax.Array  # int32 [N, O] — actor tracked per slot (-1 free)
    org_last: jax.Array  # int32 [N, O] — round of last fresh activity

    @staticmethod
    def create(n_nodes: int, n_origins: int, buf_slots: int) -> "Book":
        """``buf_slots`` sizes the out-of-order window, rounded up to
        whole 32-bit words (so the window never under-provides the
        requested capacity)."""
        words = max(1, -(-buf_slots // 32))
        return Book(
            head=jnp.zeros((n_nodes, n_origins), jnp.int32),
            known_max=jnp.zeros((n_nodes, n_origins), jnp.int32),
            seen=jnp.zeros((n_nodes, n_origins, words), jnp.uint32),
            org_id=jnp.broadcast_to(
                jnp.arange(n_origins, dtype=jnp.int32)[None, :],
                (n_nodes, n_origins),
            ),
            org_last=jnp.zeros((n_nodes, n_origins), jnp.int32),
        )

    @property
    def window_bits(self) -> int:
        return 32 * self.seen.shape[2]


def org_slot(book: Book, origin):
    """Hash-slot coordinates for message origins: ``(slot, owned)`` —
    ``slot`` int32 [N, M] is each origin's hash class (``origin % O``),
    ``owned`` marks slots currently tracking that exact actor."""
    o = book.head.shape[1]
    slot = jnp.where(origin >= 0, origin % o, 0)
    owned = (origin >= 0) & (lookup_cols(book.org_id, slot) == origin)
    return slot, owned


def _window_offsets(book: Book, slot, ver):
    """Per-message window coordinates: (head-at-slot, bit offset,
    flat word index into ``seen.reshape(N, O*W)``, in-window mask)."""
    w = book.seen.shape[2]
    h = lookup_cols(book.head, slot)
    off = ver - h - 1
    in_win = (off >= 0) & (off < 32 * w)
    word_idx = slot * w + jnp.where(off >= 0, off >> 5, 0)
    return h, off, word_idx, in_win


def seen_versions(book: Book, origin, ver, valid):
    """Has this node already seen each (origin, version)? bool [N, M] —
    true when the origin's slot tracks it AND the version is at/below
    the contiguous head or recorded in the out-of-order window (the
    seen-cache + bookie check of ``handle_changes``,
    ``handlers.rs:548-786``). Untracked origins are never seen — their
    changes apply (LWW is idempotent) and a slot claim may follow."""
    n, o, w = book.seen.shape
    slot, owned = org_slot(book, origin)
    h, off, word_idx, in_win = _window_offsets(book, slot, ver)
    word = lookup_cols(book.seen.reshape(n, o * w), word_idx, fill=0)
    bit = (jnp.clip(off, 0, None) & 31).astype(jnp.uint32)
    hit = ((word >> bit) & 1) == 1
    return valid & owned & ((ver <= h) | (in_win & hit))


def claim_slots_arrays(head, km, seen_flat, org_id, org_last, origin,
                       fresh, now, keep_rounds: int, seen_words: int):
    """Claim/evict origin slots for fresh foreign-actor messages —
    the SHARED form, plain [B, O] / [B, O*W] arrays and column-loop ops
    only, executed verbatim by both the XLA path (:func:`claim_slots`)
    and the pallas ingest kernel so the two cannot drift (the
    ``swim_tables_update`` convention).

    Per slot column: if any fresh message's origin hashes there with a
    LARGER id than the slot's occupant, the largest such origin takes
    the slot — but only when the slot is free or its occupant has been
    idle for ``keep_rounds`` (an active tracked actor is never evicted,
    so the legacy fixed-pool regime — all writers < O, identity claims —
    never churns). Claims are MONOTONE in the actor id (round 5, same
    lattice rule as the sync-side claim): recency-ordered claims let a
    quiescent cluster churn forever — circulating changesets for the
    colliding smaller actor evict the idle occupant, the eviction wipes
    the slot's seen window, the wiped window makes the occupant's old
    versions look fresh again, and freshly-recorded versions re-enter
    the broadcast queues with full budgets (measured: 50-140 org
    flips + saw-tooth known_max per round through 512 quiet rounds,
    PERF.md round 5). Under the monotone rule assignments converge and
    the storm decays by budget exhaustion; a smaller-id actor colliding
    with a larger one keeps apply-everywhere semantics but leans on the
    writer's own fanout + the sync sweep for dissemination (the
    documented collision trade; budget-following re-broadcast is the
    round-6 fairness fix). Eviction resets the slot's
    head/known_max/window; sync rebuilds them (the bounded-table analog
    of the reference's per-observed-actor map, ``agent.rs:1270-1604``).

    Returns ``(head, km, seen_flat, org_id, org_last)``."""
    b, o = head.shape
    slot = jnp.where(origin >= 0, origin % o, 0)
    id_cols, last_cols, reset_cols = [], [], []
    for c in range(o):
        owner = org_id[:, c]
        cand = fresh & (slot == c) & (origin >= 0)
        foreign = cand & (origin > owner[:, None])
        any_f = jnp.any(foreign, axis=1)
        new_owner = jnp.max(jnp.where(foreign, origin, -1), axis=1)
        evictable = (owner < 0) | (org_last[:, c] + keep_rounds < now)
        take = any_f & evictable
        id_cols.append(jnp.where(take, new_owner, owner))
        # activity: the (possibly new) owner had a fresh message now
        active = jnp.any(cand & (origin == id_cols[-1][:, None]), axis=1)
        last_cols.append(jnp.where(take | active, now, org_last[:, c]))
        reset_cols.append(take)
    reset = jnp.stack(reset_cols, axis=1)  # [B, O]
    reset_w = jnp.broadcast_to(
        reset[:, :, None], (b, o, seen_words)
    ).reshape(b, o * seen_words)
    return (
        jnp.where(reset, 0, head),
        jnp.where(reset, 0, km),
        jnp.where(reset_w, jnp.uint32(0), seen_flat),
        jnp.stack(id_cols, axis=1),
        jnp.stack(last_cols, axis=1),
    )


def claim_slots(book: Book, origin, fresh, now, keep_rounds: int) -> Book:
    """Book-level wrapper of :func:`claim_slots_arrays`."""
    n, o, w = book.seen.shape
    head, km, seen_flat, org_id, org_last = claim_slots_arrays(
        book.head, book.known_max, book.seen.reshape(n, o * w),
        book.org_id, book.org_last, origin, fresh, now, keep_rounds, w,
    )
    return Book(head, km, seen_flat.reshape(n, o, w), org_id, org_last)


def record_versions(book: Book, origin, ver, valid, now=None,
                    keep_rounds: int = 16):
    """Record a per-node batch of incoming (origin, version) pairs.

    ``origin``/``ver``: int32 [N, M] — up to M messages per node this round;
    ``valid``: bool [N, M]. Returns ``(book, fresh, rec)`` where
    ``fresh`` [N, M] marks messages not seen before by that node (the seen-cache check of
    ``handle_changes``, reference ``handlers.rs:548-786`` — fresh changes
    get applied and re-broadcast, stale ones dropped).

    Fresh messages from untracked actors first claim/evict their hash
    slot (:func:`claim_slots`; ``now`` = the round counter — omitted
    means "no claims", the pre-round-4 fixed-pool behavior). Only the
    slot owner's messages are then RECORDED; foreign messages that lost
    the claim still report fresh (they apply — LWW is idempotent) but
    leave no bookkeeping. Returns ``(book, fresh, rec)``; callers must
    re-broadcast only ``rec`` (recorded) messages — an unrecorded
    message reported fresh on EVERY arrival, so re-enqueueing it (with
    a fresh budget each time) would circulate forever between nodes
    with mismatched slot ownership (the reference likewise re-sends
    only changes its bookie accepted, ``handlers.rs:768-779``). Fresh
    in-window versions set their seen bit (beyond-window → dropped,
    like the bounded processing queue, ``config.rs:15-27``; sync
    repairs), then heads advance over any newly-closed gaps.
    """
    n, o, w = book.seen.shape
    seen = seen_versions(book, origin, ver, valid)

    # dedupe within the batch: keep only the first of identical (o, v)
    # pairs (also the precondition that lets the element-form bit scatter
    # below use add — each (word, bit) has at most one writer)
    m = origin.shape[1]
    same = (
        (origin[:, :, None] == origin[:, None, :])
        & (ver[:, :, None] == ver[:, None, :])
        & valid[:, None, :]
    )
    earlier = jnp.tril(jnp.ones((m, m), bool), k=-1)
    dup_in_batch = jnp.any(same & earlier[None, :, :], axis=2)

    fresh = valid & ~seen & ~dup_in_batch

    if now is not None:
        book = claim_slots(book, origin, fresh, now, keep_rounds)
    slot, owned = org_slot(book, origin)
    rec = fresh & owned

    _, off, word_idx, in_win = _window_offsets(book, slot, ver)
    bitval = jnp.uint32(1) << (jnp.clip(off, 0, None) & 31).astype(jnp.uint32)
    flat = scatter_cols_or(
        book.seen.reshape(n, o * w), word_idx, bitval, rec & in_win
    )
    known_max = scatter_cols_max(
        book.known_max, slot, ver, valid & owned
    )
    book = book._replace(known_max=known_max, seen=flat.reshape(n, o, w))
    return advance_heads(book), fresh, rec


def bump_known_max(book: Book, origin, ver, valid) -> Book:
    """Raise ``known_max`` for heard-of (origin, version) pairs without
    recording them as seen — hearing a *fragment* of a chunked version
    still teaches a node the version exists (drives need computation and
    sync peer choice) even though the version is not applied until its
    seq range completes (``partial_need`` in ``SyncStateV1``, reference
    ``crates/corro-types/src/sync.rs:80``). Only tracked actors book."""
    slot, owned = org_slot(book, origin)
    return book._replace(
        known_max=scatter_cols_max(book.known_max, slot, ver,
                                   valid & owned)
    )


def _trailing_ones(seen):
    """Trailing-one count of each (n, o) W-word little-endian bitfield:
    how many versions directly above the head are already seen."""
    w = seen.shape[2]
    x1 = seen + jnp.uint32(1)  # wraps all-ones to 0
    t_w = jnp.where(
        seen == _ONES,
        jnp.int32(32),
        lax.population_count(seen ^ x1).astype(jnp.int32) - 1,
    )
    total = t_w[:, :, 0]
    carry = t_w[:, :, 0] == 32
    for j in range(1, w):
        total = total + jnp.where(carry, t_w[:, :, j], 0)
        carry = carry & (t_w[:, :, j] == 32)
    return total


def _shift_right(seen, t):
    """Logical right shift of each (n, o) W-word bitfield by ``t`` bits
    (``t`` int32 [N, O] >= 0, arbitrary — over-shifts clear the field).
    The word-offset part of the shift unrolls over the static word axis;
    everything stays elementwise."""
    n, o, w = seen.shape
    t = jnp.minimum(t, 32 * w)
    s_words = t >> 5  # [N, O]
    s_bits = (t & 31).astype(jnp.uint32)[:, :, None]  # [N, O, 1]
    hi_sh = jnp.where(s_bits > 0, jnp.uint32(32) - s_bits, 0)
    has_bits = s_bits > 0

    zeros = jnp.zeros((n, o, 1), jnp.uint32)

    def word_from(s):  # seen shifted left (towards index 0) by s words
        if s >= w:
            return jnp.zeros_like(seen)
        return jnp.concatenate(
            [seen[:, :, s:]] + [zeros] * s, axis=2
        )

    out = jnp.zeros_like(seen)
    for s in range(w + 1):
        lo = word_from(s)
        hi = word_from(s + 1)
        part = (lo >> s_bits) | jnp.where(has_bits, hi << hi_sh, 0)
        out = jnp.where((s_words == s)[:, :, None], part, out)
    return out


def advance_heads(book: Book) -> Book:
    """Advance per-(node, origin) heads over contiguous seen runs.

    The jittable replacement for the reference's gap-merge
    (``compute_gaps_change``, ``agent.rs:1179-1244``): count the window's
    trailing ones, bump the head by that many, shift the window down —
    three elementwise ops over [N, O, W], no sort, no scan."""
    t = _trailing_ones(book.seen)
    head = book.head + t
    seen = _shift_right(book.seen, t)
    return book._replace(
        head=head, known_max=jnp.maximum(book.known_max, head), seen=seen
    )


def raise_heads(book: Book, new_head) -> Book:
    """Jump heads to ``new_head`` (int32 [N, O], e.g. the top of a synced
    range) and REBASE the seen windows to the new heads — the window is
    head-relative, so a head jump without the shift would corrupt it.
    Follow with :func:`advance_heads` to absorb bits now adjacent."""
    new_head = jnp.maximum(book.head, new_head)
    seen = _shift_right(book.seen, new_head - book.head)
    return book._replace(
        head=new_head,
        known_max=jnp.maximum(book.known_max, new_head),
        seen=seen,
    )


def needs_count(book: Book) -> jax.Array:
    """Outstanding need per (node, origin): versions heard of but not seen.

    ``known_max - head - popcount(window)`` — every set window bit is a
    seen version in ``(head, known_max]`` (seeing a version raises
    ``known_max`` to at least it). The scalar magnitude of the reference's
    gap set, used both for sync peer choice ("most needed versions first",
    ``handlers.rs:808-863``) and as the convergence predicate (no needs +
    equal heads — the same check as the reference's ``check_bookkeeping.py``
    Antithesis driver).
    """
    buffered = jnp.sum(
        lax.population_count(book.seen).astype(jnp.int32), axis=2
    )
    return jnp.maximum(book.known_max - book.head, 0) - buffered
