"""Per-origin version bookkeeping — the array analog of ``BookedVersions``.

The reference tracks, per (node, origin-actor): applied versions, partially
applied versions, and *gaps* (needed ranges) in a rangemap mirrored into
``__corro_bookkeeping_gaps`` (``crates/corro-types/src/agent.rs:1270-1604``,
gap algebra ``compute_gaps_change`` at ``agent.rs:1179-1244``). Gaps drive
anti-entropy sync need computation (``crates/corro-types/src/sync.rs:127``),
and the seen-check dedupes re-broadcasts
(``crates/corro-agent/src/agent/handlers.rs:548-786``).

Array re-design (no dynamic rangemaps): because the LWW join is commutative
and associative, a change can be *applied* to the store the moment it
arrives, in any order; bookkeeping only needs to know WHICH origin-versions
have been seen. Per (node, origin) we keep

- ``head``      int32 [N, O]: all origin-versions ``1..head`` seen
  (contiguous prefix — the complement of the reference's gap set),
- ``known_max`` int32 [N, O]: highest origin-version heard of (gossiped
  alongside changes; bounds need computation),
- ``seen``      uint32 [N, O, W]: a head-relative *bit window* — bit ``b``
  of word ``w`` set means origin-version ``head + 1 + 32*w + b`` has been
  seen out of order. The window is the bounded out-of-order buffer analog
  of the reference's partials/gap bookkeeping with the queue-cap drop
  policy of ``handle_changes`` (versions beyond ``head + 32*W`` drop;
  anti-entropy sync repairs them later).

Everything — seen-checks, recording, head advance ("gaps closing"), need
counts — is elementwise integer/bit arithmetic: no sorts, no scans, no
data-dependent gathers, exactly the op mix the TPU runs at full HBM
bandwidth (see ``ops/dense.py`` for why that matters on this backend).
Head advance is "count trailing ones, shift the window".
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from corrosion_tpu.ops.dense import (
    lookup_cols,
    scatter_cols_max,
    scatter_cols_or,
)

_ONES = np.uint32(0xFFFFFFFF)  # np scalar: safe to close over in pallas kernels


class Book(NamedTuple):
    """Version bookkeeping for all N simulated nodes over O origins."""

    head: jax.Array  # int32 [N, O]
    known_max: jax.Array  # int32 [N, O]
    seen: jax.Array  # uint32 [N, O, W] — head-relative seen-bit window

    @staticmethod
    def create(n_nodes: int, n_origins: int, buf_slots: int) -> "Book":
        """``buf_slots`` sizes the out-of-order window, rounded up to
        whole 32-bit words (so the window never under-provides the
        requested capacity)."""
        words = max(1, -(-buf_slots // 32))
        return Book(
            head=jnp.zeros((n_nodes, n_origins), jnp.int32),
            known_max=jnp.zeros((n_nodes, n_origins), jnp.int32),
            seen=jnp.zeros((n_nodes, n_origins, words), jnp.uint32),
        )

    @property
    def window_bits(self) -> int:
        return 32 * self.seen.shape[2]


def _window_offsets(book: Book, origin, ver):
    """Per-message window coordinates: (head-at-origin, bit offset,
    flat word index into ``seen.reshape(N, O*W)``, in-window mask)."""
    w = book.seen.shape[2]
    h = lookup_cols(book.head, origin)
    off = ver - h - 1
    in_win = (off >= 0) & (off < 32 * w)
    word_idx = origin * w + jnp.where(off >= 0, off >> 5, 0)
    return h, off, word_idx, in_win


def seen_versions(book: Book, origin, ver, valid):
    """Has this node already seen each (origin, version)? bool [N, M] —
    true when the version is at/below the contiguous head or recorded in
    the out-of-order window (the seen-cache + bookie check of
    ``handle_changes``, ``handlers.rs:548-786``)."""
    n, o, w = book.seen.shape
    h, off, word_idx, in_win = _window_offsets(book, origin, ver)
    word = lookup_cols(book.seen.reshape(n, o * w), word_idx, fill=0)
    bit = (jnp.clip(off, 0, None) & 31).astype(jnp.uint32)
    hit = ((word >> bit) & 1) == 1
    return valid & ((ver <= h) | (in_win & hit))


def record_versions(book: Book, origin, ver, valid):
    """Record a per-node batch of incoming (origin, version) pairs.

    ``origin``/``ver``: int32 [N, M] — up to M messages per node this round;
    ``valid``: bool [N, M]. Returns ``(book, fresh)`` where ``fresh`` [N, M]
    marks messages not seen before by that node (the seen-cache check of
    ``handle_changes``, reference ``handlers.rs:548-786`` — fresh changes
    get applied and re-broadcast, stale ones dropped).

    Fresh in-window versions set their seen bit (beyond-window → dropped,
    like the bounded processing queue, ``config.rs:15-27``; sync repairs),
    then heads advance over any newly-closed gaps.
    """
    n, o, w = book.seen.shape
    seen = seen_versions(book, origin, ver, valid)

    # dedupe within the batch: keep only the first of identical (o, v)
    # pairs (also the precondition that lets the element-form bit scatter
    # below use add — each (word, bit) has at most one writer)
    m = origin.shape[1]
    same = (
        (origin[:, :, None] == origin[:, None, :])
        & (ver[:, :, None] == ver[:, None, :])
        & valid[:, None, :]
    )
    earlier = jnp.tril(jnp.ones((m, m), bool), k=-1)
    dup_in_batch = jnp.any(same & earlier[None, :, :], axis=2)

    fresh = valid & ~seen & ~dup_in_batch

    _, off, word_idx, in_win = _window_offsets(book, origin, ver)
    bitval = jnp.uint32(1) << (jnp.clip(off, 0, None) & 31).astype(jnp.uint32)
    flat = scatter_cols_or(
        book.seen.reshape(n, o * w), word_idx, bitval, fresh & in_win
    )
    known_max = scatter_cols_max(book.known_max, origin, ver, valid)
    book = Book(book.head, known_max, flat.reshape(n, o, w))
    return advance_heads(book), fresh


def bump_known_max(book: Book, origin, ver, valid) -> Book:
    """Raise ``known_max`` for heard-of (origin, version) pairs without
    recording them as seen — hearing a *fragment* of a chunked version
    still teaches a node the version exists (drives need computation and
    sync peer choice) even though the version is not applied until its
    seq range completes (``partial_need`` in ``SyncStateV1``, reference
    ``crates/corro-types/src/sync.rs:80``)."""
    return book._replace(
        known_max=scatter_cols_max(book.known_max, origin, ver, valid)
    )


def _trailing_ones(seen):
    """Trailing-one count of each (n, o) W-word little-endian bitfield:
    how many versions directly above the head are already seen."""
    w = seen.shape[2]
    x1 = seen + jnp.uint32(1)  # wraps all-ones to 0
    t_w = jnp.where(
        seen == _ONES,
        jnp.int32(32),
        lax.population_count(seen ^ x1).astype(jnp.int32) - 1,
    )
    total = t_w[:, :, 0]
    carry = t_w[:, :, 0] == 32
    for j in range(1, w):
        total = total + jnp.where(carry, t_w[:, :, j], 0)
        carry = carry & (t_w[:, :, j] == 32)
    return total


def _shift_right(seen, t):
    """Logical right shift of each (n, o) W-word bitfield by ``t`` bits
    (``t`` int32 [N, O] >= 0, arbitrary — over-shifts clear the field).
    The word-offset part of the shift unrolls over the static word axis;
    everything stays elementwise."""
    n, o, w = seen.shape
    t = jnp.minimum(t, 32 * w)
    s_words = t >> 5  # [N, O]
    s_bits = (t & 31).astype(jnp.uint32)[:, :, None]  # [N, O, 1]
    hi_sh = jnp.where(s_bits > 0, jnp.uint32(32) - s_bits, 0)
    has_bits = s_bits > 0

    zeros = jnp.zeros((n, o, 1), jnp.uint32)

    def word_from(s):  # seen shifted left (towards index 0) by s words
        if s >= w:
            return jnp.zeros_like(seen)
        return jnp.concatenate(
            [seen[:, :, s:]] + [zeros] * s, axis=2
        )

    out = jnp.zeros_like(seen)
    for s in range(w + 1):
        lo = word_from(s)
        hi = word_from(s + 1)
        part = (lo >> s_bits) | jnp.where(has_bits, hi << hi_sh, 0)
        out = jnp.where((s_words == s)[:, :, None], part, out)
    return out


def advance_heads(book: Book) -> Book:
    """Advance per-(node, origin) heads over contiguous seen runs.

    The jittable replacement for the reference's gap-merge
    (``compute_gaps_change``, ``agent.rs:1179-1244``): count the window's
    trailing ones, bump the head by that many, shift the window down —
    three elementwise ops over [N, O, W], no sort, no scan."""
    t = _trailing_ones(book.seen)
    head = book.head + t
    seen = _shift_right(book.seen, t)
    return Book(head, jnp.maximum(book.known_max, head), seen)


def raise_heads(book: Book, new_head) -> Book:
    """Jump heads to ``new_head`` (int32 [N, O], e.g. the top of a synced
    range) and REBASE the seen windows to the new heads — the window is
    head-relative, so a head jump without the shift would corrupt it.
    Follow with :func:`advance_heads` to absorb bits now adjacent."""
    new_head = jnp.maximum(book.head, new_head)
    seen = _shift_right(book.seen, new_head - book.head)
    return Book(new_head, jnp.maximum(book.known_max, new_head), seen)


def needs_count(book: Book) -> jax.Array:
    """Outstanding need per (node, origin): versions heard of but not seen.

    ``known_max - head - popcount(window)`` — every set window bit is a
    seen version in ``(head, known_max]`` (seeing a version raises
    ``known_max`` to at least it). The scalar magnitude of the reference's
    gap set, used both for sync peer choice ("most needed versions first",
    ``handlers.rs:808-863``) and as the convergence predicate (no needs +
    equal heads — the same check as the reference's ``check_bookkeeping.py``
    Antithesis driver).
    """
    buffered = jnp.sum(
        lax.population_count(book.seen).astype(jnp.int32), axis=2
    )
    return jnp.maximum(book.known_max - book.head, 0) - buffered
