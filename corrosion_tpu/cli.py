"""The ``corrosion-tpu`` command line.

Mirrors the reference binary's command surface (``Command`` enum,
``crates/corrosion/src/main.rs:649-737``):

- ``agent`` — boot the node runtime (round loop + HTTP API + admin UDS +
  optional Prometheus), apply schema files, run until SIGINT
  (``command/agent.rs:19``);
- ``exec`` / ``query`` — one-shot statements over the HTTP API
  (``main.rs`` Exec/Query);
- ``sync generate`` — sync-state dump via admin (the Antithesis
  convergence probe);
- ``cluster members`` / ``cluster rejoin`` — membership ops via admin;
- ``backup`` / ``restore`` — portable node backup & full checkpoint
  (``main.rs:160-330``);
- ``locks`` — lock-registry dump;
- ``mem-report`` — per-table HBM audit of the configured sim state
  (``obs/memory.py``, docs/observability.md);
- ``template`` — render templates that re-render on subscription change;
- ``consul sync`` — Consul bridge loop.

Run as ``python -m corrosion_tpu <command>``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from corrosion_tpu.config import Config, default_toml, load_config


def _client(args):
    from corrosion_tpu.client import CorrosionApiClient

    return CorrosionApiClient(args.api_addr, args.api_port)


def _admin(args):
    from corrosion_tpu.admin import AdminClient

    return AdminClient(args.admin_path)


def _params(raw):
    """CLI params: JSON literals when they parse, raw strings otherwise
    (so ``--param 10.0.0.2`` stays a string but ``--param 80`` is an int)."""
    out = []
    for p in raw:
        try:
            out.append(json.loads(p))
        except json.JSONDecodeError:
            out.append(p)
    return out


def cmd_agent(args, cfg=None, regions=None) -> int:
    from corrosion_tpu.admin import AdminServer
    from corrosion_tpu.agent import Agent
    from corrosion_tpu.api import ApiServer
    from corrosion_tpu.db import Database

    if cfg is None:
        cfg = load_config(args.config) if args.config else Config()
    # validate listener addresses BEFORE anything starts, so a config typo
    # cannot strand half-booted servers
    prom_hostport = None
    if cfg.telemetry.prometheus_addr:
        host, sep, port = cfg.telemetry.prometheus_addr.rpartition(":")
        if not sep or not port.isdigit():
            raise SystemExit(
                f"telemetry.prometheus_addr must be host:port "
                f"(got {cfg.telemetry.prometheus_addr!r})"
            )
        prom_hostport = (host or "127.0.0.1", int(port))
    agent = Agent(cfg).start(pace_seconds=args.pace)
    if regions is not None:
        agent.set_regions(regions)
    agent.tripwire.hook_signals()
    api = admin = pg = prom = None
    try:
        db = Database(agent)
        from corrosion_tpu.maintenance import MaintenanceLoop

        maint = None
        if cfg.db.checkpoint_rounds > 0:
            # boot-time resume from the newest restorable rotated side
            # (the reference replays buffered state at boot, run_root.rs);
            # runs BEFORE schema files so edited schemas apply on top of
            # the restored state instead of being reverted by it
            man = MaintenanceLoop.resume_latest(agent, cfg.db.path, db=db)
            if man:
                print(f"resumed from {man['path']} (round {man['round']})",
                      flush=True)
        for path in cfg.db.schema_paths:
            with open(path) as f:
                db.apply_schema_sql(f.read())
        # the maintenance loop always runs (heap compaction, member
        # persistence, gauges — handlers.rs:455-540's loop is
        # unconditional too); checkpointing itself stays gated on the
        # configured cadence
        maint = MaintenanceLoop(
            agent, db=db,
            checkpoint_path=(cfg.db.path
                             if cfg.db.checkpoint_rounds > 0 else None),
            checkpoint_rounds=max(1, cfg.db.checkpoint_rounds),
        ).start()
        api = ApiServer(db, addr=cfg.api.addr, port=cfg.api.port).start()
        admin = AdminServer(agent, cfg.admin.uds_path, db=db).start()
        if cfg.pg.enabled:
            from corrosion_tpu.pg import PgServer

            pg = PgServer(db, addr=cfg.pg.addr, port=cfg.pg.port).start()
        if prom_hostport:
            from corrosion_tpu.utils.metrics import start_prometheus_listener

            prom = start_prometheus_listener(agent.metrics, *prom_hostport)
        if cfg.telemetry.otlp_path:
            from corrosion_tpu.utils.tracing import configure_otlp_file

            configure_otlp_file(cfg.telemetry.otlp_path)
        extras = (f" pg {pg.addr}:{pg.port}" if pg else "") + (
            f" prometheus {cfg.telemetry.prometheus_addr}" if prom else "")
        print(f"agent up: api http://{api.addr}:{api.port} "
              f"admin {cfg.admin.uds_path}{extras} nodes={agent.n_nodes}",
              flush=True)
        while not agent.tripwire.tripped:
            agent.tripwire.wait(0.5)
    finally:
        if admin:
            admin.stop()
        if api:
            api.stop()
        if pg:
            pg.stop()
        if prom:
            prom.shutdown()
        agent.shutdown()
        from corrosion_tpu.utils.tracing import flush_otlp

        flush_otlp()
    return 0


def cmd_exec(args) -> int:
    with_params = [(args.sql, _params(args.param))] if args.param else [args.sql]
    results = _client(args).execute(with_params, node=args.node)
    for r in results:
        print(json.dumps(r))
    return 0


def cmd_query(args) -> int:
    client = _client(args)
    stmt = (args.sql, _params(args.param)) if args.param else (args.sql, None)
    if args.follow:
        stream = client.subscribe(stmt[0], stmt[1], node=args.node)
        try:
            for event in stream:
                print(json.dumps(event), flush=True)
        except KeyboardInterrupt:
            stream.close()
        return 0
    cols, rows = client.query(stmt[0], stmt[1], node=args.node)
    if args.columns:
        print("\t".join(cols))
    for row in rows:
        print("\t".join(_fmt_cell(v) for v in row))
    return 0


def _fmt_cell(v) -> str:
    if isinstance(v, str):
        return v
    if isinstance(v, bytes):
        return "x'" + v.hex() + "'"
    return json.dumps(v)


def cmd_sync(args) -> int:
    from corrosion_tpu.utils.tracing import configure_otlp_file, flush_otlp, span

    # export the client-side span too when a config with an OTLP path is
    # at hand — otherwise the agent's serving span would reference a
    # parent no export contains (a rootless trace)
    cfg_path = getattr(args, "config", None)
    if cfg_path:
        cfg = load_config(cfg_path)
        if cfg.telemetry.otlp_path:
            configure_otlp_file(cfg.telemetry.otlp_path, service_name="corrosion-cli")
    try:
        # a client-side span whose context rides the admin call into the
        # agent's serving span (cross-process trace propagation)
        with span("cli.sync_generate"), _admin(args) as admin:
            out = admin.call("sync", **({"node": args.node}
                                        if args.node is not None else {}))
    finally:
        flush_otlp()
    print(json.dumps(out, indent=2))
    return 0


def cmd_cluster(args) -> int:
    with _admin(args) as admin:
        if args.cluster_cmd == "members":
            print(json.dumps(admin.call("cluster_members"), indent=2))
        elif args.cluster_cmd == "rejoin":
            admin.call("cluster_rejoin", node=args.node)
            print("ok")
        elif args.cluster_cmd == "set-id":
            print(json.dumps(admin.call("cluster_set_id",
                                        cluster_id=args.cluster_id)))
    return 0


def cmd_locks(args) -> int:
    with _admin(args) as admin:
        print(json.dumps(admin.call("locks", top=args.top), indent=2))
    return 0


def cmd_compact(args) -> int:
    with _admin(args) as admin:
        print(json.dumps(
            admin.call("compact", grace_seconds=args.grace), indent=2))
    return 0


def cmd_backup(args) -> int:
    with _admin(args) as admin:
        path = admin.call("backup", path=args.path, node=args.node)
    print(path)
    return 0


def cmd_restore(args) -> int:
    with _admin(args) as admin:
        if args.full:
            out = admin.call("restore", path=args.path)
        else:
            out = admin.call(
                "restore_backup", path=args.path,
                **({"node": args.node} if args.node is not None else {}),
            )
    print(json.dumps(out))
    return 0


def cmd_checkpoint(args) -> int:
    with _admin(args) as admin:
        print(admin.call("checkpoint", path=args.path))
    return 0


def cmd_verify_checkpoint(args) -> int:
    """Offline integrity check of a checkpoint directory: manifest,
    format, SHA-256 state-file hashes (every per-shard slice file of a
    sharded v3 checkpoint is hashed independently — one damaged slice
    fails the whole verify), slice-coverage validation, and state
    deserialization against the saved config. Exits non-zero on any
    defect."""
    from corrosion_tpu.checkpoint import verify_checkpoint

    try:
        out = verify_checkpoint(args.path)
    except Exception as e:  # noqa: BLE001 — any defect is a failed verify
        print(json.dumps({"ok": False, "path": args.path,
                          "error": f"{type(e).__name__}: {e}"}))
        return 1
    print(json.dumps({"ok": True, **out}, indent=2))
    return 0


def cmd_soak(args) -> int:
    """Preemption-safe soak run: R rounds in K-round segments with a
    crash-consistent checkpoint after each. ``--resume`` continues from
    the newest valid checkpoint under ``--checkpoint-dir`` (losing at
    most one segment); the segmented run is bitwise identical to a
    straight ``lax.scan`` of the same seed.

    ``--resume`` must be given the same config / ``--rounds`` /
    ``--write-frac`` as the original run — the input stack is rebuilt
    from the seed, and a different workload would not continue the same
    scan (sim-config drift is detected and refused; workload flags are
    the caller's contract)."""
    import jax.random as jr
    import numpy as np

    from corrosion_tpu.resilience import (
        Supervisor,
        resume_segmented,
        run_segmented,
    )
    from corrosion_tpu.resilience.segments import make_soak_inputs
    from corrosion_tpu.sim.transport import NetModel

    cfg_file = load_config(args.config) if args.config else Config()
    # the pipeline spans (segment dispatch / shard drain / serialize,
    # docs/observability.md) need the OTLP exporter installed to land
    # anywhere — the agent command wires this; a soak must too
    if cfg_file.telemetry.otlp_path:
        from corrosion_tpu.utils.tracing import configure_otlp_file

        configure_otlp_file(cfg_file.telemetry.otlp_path)
    cfg = cfg_file.sim_config()
    if getattr(args, "fused", None):
        # execution-path override on top of [perf] fused: same state,
        # same results (fused parity is pinned), different kernels —
        # checkpoint identity ignores it, so --resume composes freely
        import dataclasses

        cfg = dataclasses.replace(cfg, fused=args.fused).validate()
    if getattr(args, "quiet_mode", None):
        # same contract as --fused for the corroquiet active-set rounds:
        # quiet == dense bitwise, checkpoint identity ignores the key
        import dataclasses

        cfg = dataclasses.replace(cfg, quiet=args.quiet_mode).validate()
    net = NetModel.create(
        cfg.n_nodes,
        drop_prob=cfg_file.gossip.drop_prob,
        n_regions=cfg_file.gossip.n_regions,
    )
    inputs = make_soak_inputs(
        cfg, jr.key(cfg_file.sim.seed + 1), args.rounds,
        write_frac=args.write_frac,
    )
    mesh = None
    if args.shard:
        # shard the soak over a device mesh: checkpoints drain one
        # slice per device, and --resume re-places a checkpoint written
        # on ANY topology against this one (elastic restore,
        # docs/checkpoints.md)
        import jax

        from corrosion_tpu.parallel.mesh import (
            make_mesh,
            make_multihost_mesh,
            shard_state,
        )

        devices = jax.devices()
        if args.shard > len(devices):
            raise SystemExit(
                f"--shard {args.shard} exceeds the {len(devices)} "
                f"available devices"
            )
        devices = devices[:args.shard]
        mesh = (make_multihost_mesh(args.mesh_hosts, devices)
                if args.mesh_hosts else make_mesh(devices))
        net = shard_state(mesh, cfg.n_nodes, net)
        inputs = shard_state(mesh, cfg.n_nodes, inputs)
    supervisor = Supervisor(deadline_seconds=args.deadline or None)
    # observability plane (ISSUE 11, docs/observability.md): CLI flags
    # override the [obs] config section, then one observer covers the
    # whole run — NDJSON flight record, live /metrics listener, spans
    from corrosion_tpu.obs import make_observer

    if getattr(args, "flight", None):
        cfg_file.obs.flight_path = args.flight
    if getattr(args, "prom_port", None) is not None:
        cfg_file.obs.prometheus_port = args.prom_port
    if getattr(args, "jax_profile", False):
        cfg_file.obs.jax_profile = True
    obs = make_observer(cfg_file.obs)
    if obs is not None and obs.listener is not None:
        print(json.dumps({"prometheus_port": obs.listener.bound_port}),
              flush=True)
    common = dict(
        checkpoint_root=args.checkpoint_dir, keep_last=args.keep_last,
        supervisor=supervisor, donate=not args.no_donate,
        async_checkpoint=not args.sync_checkpoint, obs=obs,
    )
    try:
        if args.resume:
            result = resume_segmented(cfg, net, inputs, args.segment,
                                      mesh=mesh, **common)
        else:
            if cfg_file.sim.mode == "scale":
                from corrosion_tpu.sim.scale_step import (
                    ScaleSimState as StCls,
                )
            else:
                from corrosion_tpu.sim.step import SimState as StCls
            st = StCls.create(cfg)
            if mesh is not None:
                from corrosion_tpu.parallel.mesh import shard_state

                st = shard_state(mesh, cfg.n_nodes, st)
            result = run_segmented(
                cfg, st, net, jr.key(cfg_file.sim.seed), inputs,
                args.segment, **common,
            )
    finally:
        if obs is not None:
            obs.close()
        from corrosion_tpu.utils.tracing import flush_otlp

        flush_otlp()
    summary = {
        "completed_rounds": result.completed_rounds,
        "aborted": result.aborted,
        "checkpoint": result.checkpoint,
        # which pipeline ran: donation/async-checkpoint engagement plus
        # the stall-vs-overlapped-IO split (segments.run_segmented docs)
        "stats": result.stats,
        "metrics": {
            k: float(np.asarray(v).sum()) for k, v in result.infos.items()
        },
    }
    if cfg_file.obs.flight_path:
        summary["flight"] = cfg_file.obs.flight_path
    print(json.dumps(summary, indent=2))
    return 1 if result.aborted else 0


def cmd_template(args) -> int:
    from corrosion_tpu.tpl import render_template_cli

    return render_template_cli(args)


def cmd_consul(args) -> int:
    from corrosion_tpu.consul import consul_sync_cli

    return consul_sync_cli(args)


def _project_point(text: str) -> str:
    """argparse type for ``--project N[,M]``: validate here so a typo
    is a usage error, not a traceback; the string passes through to
    ``mem_report_cli``, which owns the one N/M parse."""
    try:
        parts = [int(p) for p in text.split(",")]
    except ValueError:
        parts = []
    if len(parts) not in (1, 2) or any(p <= 0 for p in parts):
        raise argparse.ArgumentTypeError(
            f"expected N or N,M (positive integers), got {text!r}")
    return text


def cmd_mem_report(args) -> int:
    """Per-table nbytes audit of the configured simulator state — the
    CLI face of ``obs/memory.py`` (which table is O(N·M) vs O(N), and
    what the HBM budget at [sim] n_nodes actually is). With
    ``--project N[,M]`` the audit is corrobudget's static projection
    instead (no state built — prices N=1M from the constructor ASTs)."""
    from corrosion_tpu.obs.memory import mem_report_cli

    return mem_report_cli(args)


def cmd_default_config(args) -> int:
    print(default_toml())
    return 0


def parse_topology(text: str):
    """``A -> B`` edge-list topology (corro-devcluster's format,
    ``corro-devcluster/src/topology/mod.rs``): returns (names in
    first-appearance order, edges as index pairs, group id per node from
    connected components)."""
    names: list = []
    index: dict = {}
    edges = []

    def nid(name: str) -> int:
        if name not in index:
            index[name] = len(names)
            names.append(name)
        return index[name]

    for line in text.splitlines():
        line = line.split("#", 1)[0].strip()
        if not line:
            continue
        if "->" in line:
            a, b = (s.strip() for s in line.split("->", 1))
            edges.append((nid(a), nid(b)))
        else:
            nid(line)
    # connected components -> region groups
    parent = list(range(len(names)))

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for a, b in edges:
        parent[find(a)] = find(b)
    roots = {}
    groups = []
    for i in range(len(names)):
        r = find(i)
        groups.append(roots.setdefault(r, len(roots)))
    return names, edges, groups


def cmd_devcluster(args) -> int:
    """Boot an N-node cluster from a topology file (corro-devcluster
    analog): node names map to simulator indices, topology components map
    to regions, and the agent serves the whole cluster."""
    with open(args.topology) as f:
        names, edges, groups = parse_topology(f.read())
    if not names:
        raise SystemExit(f"no nodes in topology file {args.topology}")
    cfg = load_config(args.config) if args.config else Config()
    cfg.sim.n_nodes = len(names)
    cfg.sim.n_origins = min(cfg.sim.n_origins, len(names))
    cfg.gossip.n_regions = max(groups) + 1 if groups else 1
    print(json.dumps({
        "nodes": {name: i for i, name in enumerate(names)},
        "edges": [[names[a], names[b]] for a, b in edges],
        "regions": {name: g for name, g in zip(names, groups)},
    }, indent=2), flush=True)
    # thread the per-node component assignment into the RTT-ring model
    # (region count alone would re-shuffle nodes round-robin)
    return cmd_agent(args, cfg=cfg, regions=groups)


def cmd_reload(args) -> int:
    with _admin(args) as admin:
        out = admin.call("reload", config=args.config)
    print(json.dumps(out))
    return 0


def cmd_assertions(args) -> int:
    with _admin(args) as admin:
        print(json.dumps(admin.call("assertions"), indent=2))
    return 0


def cmd_lint(args) -> int:
    """corrolint over the given paths (same engine as
    ``python -m corrosion_tpu.analysis`` and the tier-1 gate)."""
    from corrosion_tpu.analysis.__main__ import main as lint_main

    argv = list(args.paths or [])
    if args.format != "text":
        argv = ["--format", args.format] + argv
    if args.changed is not None:
        argv = ["--changed", args.changed] + argv
    if args.output_json is not None:
        argv = ["--output-json", args.output_json] + argv
    return lint_main(argv)


def cmd_chaos(args) -> int:
    """corrochaos: run seeded fault scenarios through the segmented
    soak pipeline and oracle-check them (docs/chaos.md). Any scenario
    is reproducible from ``(name, seed)`` alone — the verdict carries
    the trace digest that pins it. Under ``CORROSAN=1`` the whole run
    rides inside a sanitized window (races/leaks in the pipeline's
    threads fail the command)."""
    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    from corrosion_tpu.resilience.chaos import (
        SCENARIOS,
        TIER1_SCENARIOS,
        _host_scenarios,
        run_sweep,
    )

    if args.list:
        for name, script in sorted(SCENARIOS.items()):
            tier = " [tier1]" if name in TIER1_SCENARIOS else ""
            print(f"{name}{tier}: {len(script.phases)} phases, "
                  f"{script.total_rounds} rounds, "
                  f"{len(script.injections)} injection(s)")
        for name in sorted(_host_scenarios()):
            print(f"{name} [host-plane]: serving-plane scenario, "
                  f"run by name (not part of the default sweep)")
        return 0
    if args.script:
        return _chaos_replay_scripts(args)
    if args.scenario:
        names = list(args.scenario)
    elif args.tier1:
        names = list(TIER1_SCENARIOS)
    else:
        names = sorted(SCENARIOS)
    seed_range = None
    if args.seed_range:
        try:
            lo, _, hi = args.seed_range.partition(":")
            seed_range = (int(lo), int(hi))
        except ValueError:
            print(f"error: --seed-range wants A:B, got "
                  f"{args.seed_range!r}", file=sys.stderr)
            return 2
    corrosan = os.environ.get("CORROSAN") == "1"
    if corrosan:
        from corrosion_tpu.analysis.sanitizer import sanitized

        with sanitized() as san:
            out = run_sweep(names, seed=args.seed, seed_range=seed_range)
        findings = san.gate()
        if findings:
            out["ok"] = False
            out.setdefault("problems", []).extend(
                f"corrosan: {f.kind} {f.subject}" for f in findings
            )
    else:
        out = run_sweep(names, seed=args.seed, seed_range=seed_range)
    out["corrosan"] = corrosan
    if args.output_json:
        os.makedirs(os.path.dirname(os.path.abspath(args.output_json)),
                    exist_ok=True)
        with open(args.output_json, "w") as f:
            json.dump(out, f, indent=2)
    if args.convergence_json:
        # the rounds-to-convergence lineage artifact (supersedes the
        # seed-era single-scenario CONVERGENCE records): one entry per
        # scripted scenario, through the chaos engine's oracle-1 path
        conv = [
            {
                "scenario": r["name"],
                "seed": r["seed"],
                "n": r["n_nodes"],
                "faults": True,
                "rounds_to_convergence": r.get("rounds_to_convergence", -1),
                "converged": bool(r.get("converged")),
                "platform": out["platform"],
            }
            for r in out["scenarios"]
            if not r.get("skipped") and not r.get("host_plane")
        ]
        os.makedirs(
            os.path.dirname(os.path.abspath(args.convergence_json)),
            exist_ok=True)
        with open(args.convergence_json, "w") as f:
            json.dump(conv, f, indent=1)
    print(json.dumps(out, indent=2))
    return 0 if out["ok"] else 1


def _chaos_replay_scripts(args) -> int:
    """``corrosion-tpu chaos --script FILE [...]``: replay serialized
    scenario scripts — corpus reproducers (the envelope written by
    ``fuzz.save_reproducer``, which pins its own replay seed) or bare
    ``script_to_json`` documents (replayed at ``--seed``). The
    script↔JSON round-trip is a first-class contract: a replay
    re-derives the same trace digest the original run recorded."""
    import jax

    from corrosion_tpu.resilience.chaos import run_scenario, script_from_json
    from corrosion_tpu.resilience.fuzz import load_reproducer

    def replay() -> list:
        records = []
        for path in args.script:
            with open(path) as f:
                payload = json.load(f)
            if isinstance(payload, dict) and "script" in payload:
                script, seed, _meta = load_reproducer(path)
            else:
                script, seed = script_from_json(payload), args.seed
            records.append(run_scenario(script, seed=seed))
        return records

    corrosan = os.environ.get("CORROSAN") == "1"
    if corrosan:
        from corrosion_tpu.analysis.sanitizer import sanitized

        with sanitized() as san:
            records = replay()
        findings = san.gate()
    else:
        records, findings = replay(), []
    out = {
        "metric": "chaos_sweep",
        "seed": int(args.seed),
        "platform": jax.devices()[0].platform,
        "scripts": list(args.script),
        "scenarios": records,
        "corrosan": corrosan,
        "ok": all(r["ok"] for r in records) and not findings,
    }
    if findings:
        out.setdefault("problems", []).extend(
            f"corrosan: {f.kind} {f.subject}" for f in findings
        )
    if args.output_json:
        os.makedirs(os.path.dirname(os.path.abspath(args.output_json)),
                    exist_ok=True)
        with open(args.output_json, "w") as f:
            json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))
    return 0 if out["ok"] else 1


def cmd_fuzz(args) -> int:
    """corrofuzz: sweep a fixed-seed budget of GENERATED chaos
    scenarios (docs/chaos.md, "Generative fuzzing") and emit the
    ``fuzz_r18``-shaped record: per-seed verdict + rounds-to-
    convergence/quiescence. Deterministic end to end — same seeds,
    same scripts, same verdicts. ``--shrink-failures`` delta-debugs
    every failing seed to a 1-minimal reproducer and writes it to the
    corpus directory for ``chaos --script`` replay. Under
    ``CORROSAN=1`` the sweep rides a sanitized window like the chaos
    sweep."""
    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    from corrosion_tpu.resilience import fuzz

    try:
        lo, _, hi = args.seeds.partition(":")
        seeds = list(range(int(lo), int(hi) + 1))
    except ValueError:
        print(f"error: --seeds wants A:B, got {args.seeds!r}",
              file=sys.stderr)
        return 2
    if args.list:
        for seed in seeds:
            script = fuzz.gen_script(seed, profile=args.profile)
            print(f"{script.name}: N={script.n_nodes}, "
                  f"{len(script.phases)} phases, "
                  f"{script.total_rounds} rounds, injections="
                  f"{[i.kind for i in script.injections] or '[]'}")
        return 0
    corrosan = os.environ.get("CORROSAN") == "1"
    if corrosan:
        from corrosion_tpu.analysis.sanitizer import sanitized

        with sanitized() as san:
            out = fuzz.run_fuzz(seeds, profile=args.profile,
                                keep_failures=True)
        findings = san.gate()
        if findings:
            out["ok"] = False
            out.setdefault("problems", []).extend(
                f"corrosan: {f.kind} {f.subject}" for f in findings
            )
    else:
        out = fuzz.run_fuzz(seeds, profile=args.profile,
                            keep_failures=True)
    out["corrosan"] = corrosan
    if args.shrink_failures is not None:
        shrunk = []
        for case in out["cases"]:
            if case["ok"] or case.get("skipped"):
                continue
            script = fuzz.gen_script(case["seed"], profile=args.profile)
            minimal, runs = fuzz.shrink(script, case["seed"])
            path = fuzz.save_reproducer(
                minimal, case["seed"],
                note=f"shrunk from {script.name} in {runs} oracle runs; "
                     f"problems: {case.get('problems')}",
                path=os.path.join(args.shrink_failures,
                                  f"{minimal.name}.json"),
            )
            shrunk.append(path)
        out["reproducers"] = shrunk
    if args.output_json:
        os.makedirs(os.path.dirname(os.path.abspath(args.output_json)),
                    exist_ok=True)
        with open(args.output_json, "w") as f:
            json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))
    return 0 if out["ok"] else 1


def cmd_load(args) -> int:
    """corroload: the seeded concurrent-client load harness
    (docs/observability.md, "Serving plane"). Drives an in-process
    devcluster's HTTP API, NDJSON subscriptions and PG-wire server with
    N writers + M subscribers + K readers whose op streams are a pure
    function of ``--seed``, and emits the ``BENCH_SERVE`` record —
    client-side p50/p95/p99 per op class, delivery lag, and the
    server-vs-client request-count agreement gate. Under ``CORROSAN=1``
    the whole run rides inside a sanitized window."""
    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    from corrosion_tpu.obs.load import run_load, run_overload_bench

    if args.overload:
        # overload mode: corroguard's degradation-contract bench —
        # guarded arm (admission + bounded queues) and unguarded arm,
        # gated on "guard holds AND no-guard demonstrably violates".
        # The harness's own defaults (writers/subscribers/keys tuned to
        # saturate the guard) govern everything but the flags below.
        runner = run_overload_bench
        kwargs = dict(
            stages=tuple(int(x) for x in args.stages.split(",")),
            slow_subs=args.slow_subs, slow_ms=args.slow_ms,
            lag_bound_s=args.lag_bound, seed=args.seed,
        )
    else:
        runner = run_load
        kwargs = dict(
            writers=args.writers, subscribers=args.subscribers,
            pg_readers=args.pg_readers, write_ops=args.write_ops,
            pg_ops=args.pg_ops, keys=args.keys, seed=args.seed,
        )
    corrosan = os.environ.get("CORROSAN") == "1"
    if corrosan:
        from corrosion_tpu.analysis.sanitizer import sanitized

        with sanitized() as san:
            out = runner(**kwargs)
        findings = san.gate()
        if findings:
            out["ok"] = False
            out.setdefault("problems", []).extend(
                f"corrosan: {f.kind} {f.subject}" for f in findings
            )
    else:
        out = runner(**kwargs)
    out["corrosan"] = corrosan
    if args.output_json:
        os.makedirs(os.path.dirname(os.path.abspath(args.output_json)),
                    exist_ok=True)
        with open(args.output_json, "w") as f:
            json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))
    return 0 if out["ok"] else 1


def cmd_san(args) -> int:
    """corrosan fixture replay (same engine as
    ``python -m corrosion_tpu.analysis.sanitizer``): seeded
    race/leak/inversion scenarios the runtime sanitizer must detect,
    with verdicts published to the shared report artifact."""
    from corrosion_tpu.analysis.sanitizer.__main__ import main as san_main

    argv = list(args.fixtures or [])
    if args.list_fixtures:
        argv = ["--list-fixtures"] + argv
    if args.format != "text":
        argv = ["--format", args.format] + argv
    if args.output_json is not None:
        argv = ["--output-json", args.output_json] + argv
    return san_main(argv)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="corrosion-tpu",
        description="TPU-native gossip/CRDT cluster simulator",
    )
    p.add_argument("--api-addr", default="127.0.0.1")
    p.add_argument("--api-port", type=int, default=8787)
    p.add_argument("--admin-path", default="./admin.sock")
    sub = p.add_subparsers(dest="cmd", required=True)

    a = sub.add_parser("agent", help="run the agent")
    a.add_argument("-c", "--config", default=None)
    a.add_argument("--pace", type=float, default=0.05,
                   help="seconds per round (0 = flat out)")
    a.set_defaults(fn=cmd_agent)

    e = sub.add_parser("exec", help="execute write statements")
    e.add_argument("sql")
    e.add_argument("--param", action="append", default=[])
    e.add_argument("--node", type=int, default=0)
    e.set_defaults(fn=cmd_exec)

    q = sub.add_parser("query", help="run a read-only query")
    q.add_argument("sql")
    q.add_argument("--param", action="append", default=[])
    q.add_argument("--node", type=int, default=0)
    q.add_argument("--columns", action="store_true")
    q.add_argument("--follow", action="store_true",
                   help="subscribe and stream changes")
    q.set_defaults(fn=cmd_query)

    s = sub.add_parser("sync", help="sync state introspection")
    ssub = s.add_subparsers(dest="sync_cmd", required=True)
    sg = ssub.add_parser("generate")
    sg.add_argument("--node", type=int, default=None)
    sg.set_defaults(fn=cmd_sync)

    c = sub.add_parser("cluster", help="cluster membership ops")
    csub = c.add_subparsers(dest="cluster_cmd", required=True)
    csub.add_parser("members").set_defaults(fn=cmd_cluster)
    cr = csub.add_parser("rejoin")
    cr.add_argument("--node", type=int, required=True)
    cr.set_defaults(fn=cmd_cluster)
    ci = csub.add_parser("set-id")
    ci.add_argument("cluster_id", type=int)
    ci.set_defaults(fn=cmd_cluster)

    lk = sub.add_parser("locks", help="lock registry dump")
    lk.add_argument("--top", type=int, default=10)
    lk.set_defaults(fn=cmd_locks)

    cp = sub.add_parser("compact",
                        help="compact the value heap (vacuum analog)")
    cp.add_argument("--grace", type=float, default=300.0,
                    help="seconds of touch-recency that pin an id")
    cp.set_defaults(fn=cmd_compact)

    b = sub.add_parser("backup", help="portable single-node backup")
    b.add_argument("path")
    b.add_argument("--node", type=int, default=0)
    b.set_defaults(fn=cmd_backup)

    r = sub.add_parser("restore", help="restore a backup or checkpoint")
    r.add_argument("path")
    r.add_argument("--node", type=int, default=None)
    r.add_argument("--full", action="store_true",
                   help="path is a full checkpoint directory")
    r.set_defaults(fn=cmd_restore)

    ck = sub.add_parser("checkpoint", help="write a full cluster checkpoint")
    ck.add_argument("path")
    ck.set_defaults(fn=cmd_checkpoint)

    vc = sub.add_parser("verify-checkpoint",
                        help="verify a checkpoint directory's integrity")
    vc.add_argument("path")
    vc.set_defaults(fn=cmd_verify_checkpoint)

    sk = sub.add_parser("soak",
                        help="segmented soak run with per-segment "
                             "checkpoints (preemption-safe)")
    sk.add_argument("-c", "--config", default=None)
    sk.add_argument("--rounds", type=int, default=1024)
    sk.add_argument("--segment", type=int, default=128,
                    help="rounds per segment (checkpoint cadence)")
    sk.add_argument("--checkpoint-dir", default="./soak_checkpoints")
    sk.add_argument("--keep-last", type=int, default=3)
    sk.add_argument("--write-frac", type=float, default=0.25,
                    help="fraction of nodes writing per round")
    sk.add_argument("--deadline", type=float, default=0.0,
                    help="per-segment dispatch deadline in seconds "
                         "(0 = none)")
    sk.add_argument("--resume", action="store_true",
                    help="continue from the newest valid checkpoint")
    sk.add_argument("--no-donate", action="store_true",
                    help="disable carry buffer donation across segment "
                         "boundaries (debug: doubles state HBM)")
    sk.add_argument("--sync-checkpoint", action="store_true",
                    help="write checkpoints synchronously on the hot "
                         "loop instead of the overlapped background "
                         "writer")
    sk.add_argument("--shard", type=int, default=0,
                    help="shard the soak over an N-device mesh: per-"
                         "shard checkpoint drains, and --resume "
                         "reshards a checkpoint from ANY topology onto "
                         "this one (0 = single device)")
    sk.add_argument("--mesh-hosts", type=int, default=0,
                    help="with --shard: fold the devices into a 2-D "
                         "(dcn, node) mesh over this many hosts")
    from corrosion_tpu.sim.config import FUSED_MODES, QUIET_MODES

    sk.add_argument("--fused", choices=list(FUSED_MODES),
                    default=None,
                    help="fused megakernel path override (default: the "
                         "[perf] fused config key; docs/fused.md). "
                         "'interpret' runs the pallas kernels "
                         "interpreted on any backend — the parity/"
                         "debug mode")
    sk.add_argument("--quiet-mode", choices=list(QUIET_MODES),
                    dest="quiet_mode", default=None,
                    help="quiescence-aware active-set rounds override "
                         "(default: the [perf] quiet config key; "
                         "docs/fused.md). 'on' pins the quiet scan "
                         "body, 'auto' lets the segment pipeline pick "
                         "it for all-quiet segments — results are "
                         "bitwise identical either way")
    sk.add_argument("--flight", default=None, metavar="PATH",
                    help="flight-recorder NDJSON path (overrides [obs] "
                         "flight_path): crash-safe per-segment records "
                         "a dead soak leaves behind "
                         "(docs/observability.md)")
    sk.add_argument("--prom-port", type=int, default=None,
                    help="serve live /metrics for this soak on this "
                         "port (0 = ephemeral; overrides [obs] "
                         "prometheus_port)")
    sk.add_argument("--jax-profile", action="store_true",
                    help="annotate pipeline spans for jax.profiler "
                         "device traces (overrides [obs] jax_profile)")
    sk.set_defaults(fn=cmd_soak)

    t = sub.add_parser("template", help="render templates (re-render on change)")
    t.add_argument("spec", nargs="+", help="template.py:output pairs")
    t.add_argument("--once", action="store_true")
    t.add_argument("--node", type=int, default=0)
    t.set_defaults(fn=cmd_template)

    co = sub.add_parser("consul", help="consul bridge")
    cosub = co.add_subparsers(dest="consul_cmd", required=True)
    cs = cosub.add_parser("sync")
    cs.add_argument("--consul-addr", default="127.0.0.1:8500")
    cs.add_argument("--once", action="store_true")
    cs.add_argument("--node", type=int, default=0)
    cs.set_defaults(fn=cmd_consul)

    dc = sub.add_parser("devcluster",
                        help="boot a cluster from an `A -> B` topology file")
    dc.add_argument("topology")
    dc.add_argument("-c", "--config", default=None)
    dc.add_argument("--pace", type=float, default=0.05)
    dc.set_defaults(fn=cmd_devcluster)

    rl = sub.add_parser("reload", help="re-apply config (schema, log level)")
    rl.add_argument("config")
    rl.set_defaults(fn=cmd_reload)

    asr = sub.add_parser("assertions",
                         help="always/sometimes assertion report")
    asr.set_defaults(fn=cmd_assertions)

    lint = sub.add_parser(
        "lint", help="corrolint static analysis (v1 lexical checkers, "
                     "the v2 interprocedural sharding-contract, "
                     "dtype-flow, lock-order, donation-flow passes, "
                     "and the v3 corrobudget mem-budget/densify "
                     "symbolic-shape gate)")
    lint.add_argument("paths", nargs="*", default=None,
                      help="files/dirs (default: corrosion_tpu)")
    lint.add_argument("--format", choices=("text", "json"), default="text")
    lint.add_argument("--changed", metavar="GIT_REF", default=None,
                      help="lint only .py files changed vs the git ref "
                           "(fast pre-commit mode)")
    lint.add_argument("--output-json", metavar="PATH", default=None,
                      help="write a machine-readable findings report")
    lint.set_defaults(fn=cmd_lint)

    san = sub.add_parser(
        "san", help="corrosan runtime sanitizer: replay seeded "
                    "race/leak fixtures (detector true-positive guard); "
                    "the sanitized pytest run itself is CORROSAN=1 / "
                    "--corrosan on the test command")
    san.add_argument("fixtures", nargs="*", default=None,
                     help="fixture names (default: all)")
    san.add_argument("--list-fixtures", action="store_true")
    san.add_argument("--format", choices=("text", "json"), default="text")
    san.add_argument("--output-json", metavar="PATH", default=None,
                     help="write the fixtures section of the corrosan "
                          "report artifact")
    san.set_defaults(fn=cmd_san)

    ch = sub.add_parser(
        "chaos",
        help="corrochaos: run deterministic seeded fault scenarios "
             "through the segmented soak pipeline, double-oracle-"
             "checked (docs/chaos.md)")
    ch.add_argument("scenario", nargs="*", default=None,
                    help="scenario name(s) to run (default: the full "
                         "sweep; see --list)")
    ch.add_argument("--seed", type=int, default=0,
                    help="scenario seed — (name, seed) fully determines "
                         "the trace and the verdict")
    ch.add_argument("--seed-range", metavar="A:B", default=None,
                    help="sweep every scenario across seeds A..B "
                         "(inclusive); the record gains a per_seed map "
                         "of seed -> rounds-to-convergence")
    ch.add_argument("--tier1", action="store_true",
                    help="run only the tier-1 smoke subset")
    ch.add_argument("--list", action="store_true",
                    help="list the shipped scenarios and exit")
    ch.add_argument("--output-json", metavar="PATH", default=None,
                    help="write the sweep record (per-scenario verdicts, "
                         "rounds-to-convergence, checkpoints validated, "
                         "faults injected)")
    ch.add_argument("--convergence-json", metavar="PATH", default=None,
                    help="also write the CONVERGENCE_* lineage artifact "
                         "derived from the sweep")
    ch.add_argument("--script", metavar="FILE", action="append",
                    default=None,
                    help="replay serialized scenario script(s) instead "
                         "of registry names: corpus reproducer files "
                         "(tests/chaos_corpus/*.json, which pin their "
                         "own seed) or bare script JSON (replayed at "
                         "--seed); repeatable")
    ch.set_defaults(fn=cmd_chaos)

    fz = sub.add_parser(
        "fuzz",
        help="corrofuzz: sweep a fixed-seed budget of generated chaos "
             "scenarios (seeded grammar draws, three oracles, "
             "deterministic verdicts) and optionally shrink failures "
             "to corpus reproducers (docs/chaos.md)")
    fz.add_argument("--seeds", metavar="A:B", default="0:24",
                    help="inclusive fuzz-seed range; each seed "
                         "deterministically generates + judges one "
                         "scenario (default 0:24)")
    fz.add_argument("--profile", choices=("fast", "scale"),
                    default="fast",
                    help="N-ladder profile: fast = corrobudget-priced "
                         "fast rungs only; scale = the full 64..4k "
                         "ladder (slow)")
    fz.add_argument("--list", action="store_true",
                    help="print the generated scripts without running "
                         "them")
    fz.add_argument("--shrink-failures", metavar="DIR", default=None,
                    help="delta-debug every failing seed to a 1-minimal "
                         "reproducer JSON in DIR (replayable via "
                         "'chaos --script')")
    fz.add_argument("--output-json", metavar="PATH", default=None,
                    help="write the fuzz record (per-seed verdict + "
                         "rounds-to-convergence: artifacts/fuzz_r18.json)")
    fz.set_defaults(fn=cmd_fuzz)

    ld = sub.add_parser(
        "load",
        help="corroload: seeded concurrent-client load harness over "
             "the serving plane (HTTP + subscriptions + PG-wire) — "
             "emits the BENCH_SERVE record with client p50/p95/p99 "
             "and the server-vs-client agreement gate")
    ld.add_argument("--writers", type=int, default=4,
                    help="open-loop HTTP transaction writers")
    ld.add_argument("--subscribers", type=int, default=2,
                    help="NDJSON subscription streams measuring "
                         "write-commit -> delivery lag")
    ld.add_argument("--pg-readers", type=int, default=2,
                    help="PG-wire simple-query readers")
    ld.add_argument("--write-ops", type=int, default=32,
                    help="transactions per writer")
    ld.add_argument("--pg-ops", type=int, default=32,
                    help="queries per reader")
    ld.add_argument("--keys", type=int, default=12,
                    help="keyspace size (rows in load_kv)")
    ld.add_argument("--seed", type=int, default=0,
                    help="op-plan seed — the record carries the plan "
                         "digest it determines")
    ld.add_argument("--overload", action="store_true",
                    help="run corroguard's overload bench instead: a "
                         "guarded arm (admission control + bounded "
                         "queues) and an unguarded arm, gated on the "
                         "degradation contract (docs/overload.md)")
    ld.add_argument("--stages", default="2,4,8",
                    help="[overload] comma-separated open-loop writer "
                         "counts per ramp stage")
    ld.add_argument("--slow-subs", type=int, default=2,
                    help="[overload] deliberately slow subscribers")
    ld.add_argument("--slow-ms", type=float, default=25.0,
                    help="[overload] per-event stall of a slow "
                         "subscriber, milliseconds")
    ld.add_argument("--lag-bound", type=float, default=2.5,
                    help="[overload] p99 delivery-lag bound (seconds) "
                         "the guarded arm must hold")
    ld.add_argument("--output-json", metavar="PATH", default=None,
                    help="write the BENCH_SERVE record")
    ld.set_defaults(fn=cmd_load)

    mr = sub.add_parser(
        "mem-report",
        help="per-table HBM audit of the configured sim state "
             "(O(N·M) vs O(N) classification — the 1M memory-budget "
             "probe, docs/observability.md)")
    mr.add_argument("-c", "--config", default=None)
    mr.add_argument("--n-nodes", type=int, default=0,
                    help="override [sim] n_nodes for the audit")
    mr.add_argument("--project", metavar="N[,M]", default=None,
                    type=_project_point,
                    help="print corrobudget's STATIC projection at "
                         "(N[, M]) instead of building a state — "
                         "symbolic inventory, zero arrays, any N "
                         "(docs/memory-budget.md)")
    mr.set_defaults(fn=cmd_mem_report)

    d = sub.add_parser("default-config", help="print an example config file")
    d.set_defaults(fn=cmd_default_config)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        return args.fn(args)
    except BrokenPipeError:
        # downstream pager/head closed the pipe — normal unix behavior
        try:
            sys.stdout.close()
        except Exception:  # noqa: BLE001
            pass
        return 0


if __name__ == "__main__":
    sys.exit(main())
