"""Subscriptions (pubsub) + table update feeds.

Mirrors the reference's subscription engine (``crates/corro-types/src/
pubsub.rs:527-1100``) and its lighter sibling, the table updates feed
(``crates/corro-types/src/updates.rs``):

- a **Matcher** owns one SQL query against one observer node's replica,
  keeps the last materialized result keyed by pk (the reference keeps it
  in a dedicated per-subscription SQLite db), and on every round diffs
  the fresh result against it, emitting ``QueryEvent::Change`` rows with
  a **monotonic ChangeId** per matcher;
- subscribers attach live via per-subscriber queues (the tokio broadcast
  channel analog) and can **catch up from a ChangeId** through the
  matcher's retained change log (``pubsub.rs:842-878``);
- the **UpdatesManager** streams row-level ``NotifyEvent``s per table
  without a query (``/v1/updates/:table``).

Matchers re-poll on the agent's round listener — the seam where the
reference calls ``match_changes`` on every applied changeset
(``util.rs:1034-1037``, ``broadcast.rs:539-540``).
"""

from __future__ import annotations

import contextlib
import json
import os
import queue
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

from corrosion_tpu.utils.tracing import logger

# change kinds (reference ChangeType)
UPSERT = "update"
INSERT = "insert"
DELETE = "delete"


def _enc(v: Any) -> Any:
    """JSON-safe encoding for pk/row values (bytes get a tag)."""
    if isinstance(v, bytes):
        return {"__bytes__": v.hex()}
    return v


def _dec(v: Any) -> Any:
    if isinstance(v, dict) and "__bytes__" in v:
        return bytes.fromhex(v["__bytes__"])
    return v


def _enc_key(k: Any) -> Any:
    """Row keys are scalar pks (single-table) or composite pk tuples
    (JOIN subscriptions) — tuples get a tag so restore re-hashes them."""
    if isinstance(k, tuple):
        return {"__key__": [_enc(x) for x in k]}
    return _enc(k)


def _enc_wire(v: Any) -> Any:
    """HTTP wire encoding for change-event values (the ``{"blob"}``
    convention of ``api/http.py``, applied recursively to composite pk
    tuples)."""
    if isinstance(v, bytes):
        return {"blob": v.hex()}
    if isinstance(v, tuple):
        return [_enc_wire(x) for x in v]
    return v


def encode_change_frame(rec) -> bytes:
    """The NDJSON wire line for one change record, exactly as the HTTP
    layer frames it (``{"change": [kind, key, row, id]}`` + newline).
    Batched fanout (corroguard, docs/overload.md): the matcher encodes
    each per-round delta record ONCE through this and caches the bytes
    by change id — every subscriber stream multicasts the same frame
    instead of re-encoding per subscriber."""
    cid, kind, key, row = rec
    return json.dumps({"change": [
        kind,
        _enc_wire(key),
        None if row is None else [_enc_wire(v) for v in row],
        cid,
    ]}).encode() + b"\n"


def _dec_key(k: Any) -> Any:
    if isinstance(k, dict) and "__key__" in k:
        return tuple(_dec(x) for x in k["__key__"])
    return _dec(k)


def _serve_policy(serve):
    """Subscription queue policy triple ``(sub_queue, shed_policy,
    sub_shed_threshold)`` from a duck-typed ``[serve]`` section.

    ``serve=None`` means the DEFAULT policy (``config.ServeConfig``'s
    measured caps, docs/overload.md "Default caps") — NOT unlimited;
    ``sub_queue=0`` is the explicit unbounded opt-out
    (``ServeConfig.unlimited()``), which maps straight onto
    ``queue.Queue``'s ``maxsize<=0`` = infinite semantics. The lazy
    import keeps pubsub free of a module-level config dependency (any
    object with these attrs still works)."""
    if serve is None:
        from corrosion_tpu.config import ServeConfig

        serve = ServeConfig()
    return (
        int(getattr(serve, "sub_queue", 1024)),
        str(getattr(serve, "shed_policy", "shed-oldest")),
        int(getattr(serve, "sub_shed_threshold", 256)),
    )


class SubQueue(queue.Queue):
    """Per-subscriber event queue with bounded backpressure
    (corroguard, docs/overload.md). The producer (the round thread)
    never blocks. Two shed policies:

    - ``shed-oldest`` (default): on overflow the OLDEST queued frame is
      dropped to admit the new one — the consumer keeps receiving fresh
      events with a bounded lag and learns about the gap through the
      stream's resync marker (:meth:`take_resync`). Crossing
      ``shed_threshold`` cumulative drops marks the consumer lagged:
      the slow-consumer disconnect policy.
    - ``drop-newest`` (legacy): overflow refuses the new frame and
      marks the consumer lagged immediately — the tokio broadcast
      ``RecvError::Lagged`` behavior the reference relies on.
    """

    def __init__(self, maxsize: int = 65536,
                 shed_policy: str = "shed-oldest",
                 shed_threshold: int = 256):
        super().__init__(maxsize=maxsize)
        self.shed_policy = shed_policy
        self.shed_threshold = max(1, int(shed_threshold))
        self.lagged = False
        self._shed_mu = threading.Lock()
        self._shed = 0  # lifetime frames dropped (shed-oldest)
        self._resync = 0  # drops since the consumer last took a marker
        self._reported = 0  # drops already drained into shed_total

    def offer(self, item) -> bool:
        """Producer-side non-blocking enqueue. False = refused: the
        consumer is lagged and the fanout will disconnect it."""
        if self.lagged:
            return False
        while True:
            try:
                self.put_nowait(item)
                return True
            except queue.Full:
                if self.shed_policy != "shed-oldest":
                    self.lagged = True
                    return False
                try:
                    self.get_nowait()  # drop the oldest frame
                except queue.Empty:
                    continue  # the consumer drained it first; retry
                with self._shed_mu:
                    self._shed += 1
                    self._resync += 1
                    if self._shed >= self.shed_threshold:
                        self.lagged = True

    def preload(self, item) -> None:
        """Attach-time enqueue that bypasses ``maxsize``: the initial
        snapshot / catch-up backlog must arrive whole even when it is
        larger than the live bound (the consumer has not even started
        reading yet, so it cannot be 'slow'). Live ``offer`` traffic
        sheds against the bound as usual once the stream is running."""
        with self.mutex:
            self.queue.append(item)
            self.unfinished_tasks += 1
            self.not_empty.notify()

    def drain_shed(self) -> int:
        """Producer side: drops not yet folded into
        ``corro.subs.shed_total`` (the fanout drains after each round)."""
        with self._shed_mu:
            n = self._shed - self._reported
            self._reported = self._shed
            return n

    def take_resync(self) -> int:
        """Consumer side: drops since the last call — non-zero means
        the stream has a gap and the HTTP loop owes the client a resync
        marker before the next event (docs/overload.md)."""
        with self._shed_mu:
            n, self._resync = self._resync, 0
            return n


class DeltaTracker:
    """Applied-change detection per observed node, from store-plane
    diffs — the device-side analog of the reference feeding each applied
    changeset to ``match_changes`` (``util.rs:1036-1037``).

    Each round, the observer node's ``(ver, val, clp)`` planes are
    compared against the previous round's copy; changed cells map to
    grid rows, and rows map to ``(table, pk)`` through the
    :class:`RowMap` reverse lookup. The result is a candidate dict
    ``{table: {pk, ...}}`` — ``None`` means "no baseline yet"
    (callers fall back to a full re-query)."""

    def __init__(self, db):
        self.db = db
        self._planes: Dict[int, tuple] = {}
        # (round, delta) per node: consumers arriving within the same
        # round share one computation instead of each advancing the
        # baseline (which would hand the second caller an empty delta)
        self._cache: Dict[int, tuple] = {}
        self._mu = threading.Lock()

    def changed(self, node: int) -> Optional[Dict[str, set]]:
        import numpy as np

        snap = self.db.agent.snapshot()
        rnd = snap.get("round", -1)
        with self._mu:
            cached = self._cache.get(node)
            if cached is not None and cached[0] == rnd:
                return cached[1]
            store = snap["store"]  # (ver, val, site, dbv, clp) planes
            ver = np.asarray(store[0][node])
            val = np.asarray(store[1][node])
            clp = np.asarray(store[4][node])
            prev = self._planes.get(node)
            self._planes[node] = (ver.copy(), val.copy(), clp.copy())
            if prev is None:
                out = None
            else:
                ch = (prev[0] != ver) | (prev[1] != val) | (prev[2] != clp)
                if not ch.any():
                    out = {}
                else:
                    out = {}
                    n_cols = self.db.n_cols
                    for row in {int(c) // n_cols
                                for c in np.nonzero(ch)[0]}:
                        tp = self.db.rows.table_pk_of(row)
                        if tp is not None:
                            out.setdefault(tp[0], set()).add(tp[1])
            self._cache[node] = (rnd, out)
            return out


class Matcher:
    """One subscription query: materialized result + change log."""

    def __init__(self, db, node: int, sql: str, params: Any = None,
                 sub_id: Optional[str] = None, max_log: int = 4096,
                 restore: Optional[dict] = None, serve=None):
        self.id = sub_id or uuid.uuid4().hex
        self.db = db
        self.node = node
        self.sql = sql
        self.params = params
        self.max_log = max_log
        # validate the query + capture column names up front
        cols, _ = db.query(node, sql, params)
        self.columns: List[str] = list(cols)
        # the reference rewrites the SELECT to expose the pks of EVERY
        # table involved in the query (``pubsub.rs:527+``) so a change to
        # either side of a JOIN re-evaluates the match. Mirror that: run
        # a variant with every alias-qualified pk prepended and key the
        # materialized result by the composite pk tuple, stripping the
        # key columns on emit.
        import re

        from corrosion_tpu.db.database import SqlError, _Params

        ast = db._parse_select(sql, _Params(None), check_params=False)
        if ast["group"] or any(k == "agg" for k, _, _ in ast["cols"]):
            raise SqlError(
                "subscriptions require plain row queries "
                "(no aggregates / GROUP BY)"
            )
        from corrosion_tpu.db.database import _CteTable

        if any(isinstance(t, _CteTable) for t in ast["aliases"].values()):
            # CTE results have no pk to track matches by; the reference
            # likewise restricts subscription queries to its supported
            # matcher surface (pubsub.rs:527+)
            raise SqlError("subscriptions do not support WITH (CTEs)")
        pk_refs = [f"{a}.{t.pk.name}" for a, t in ast["aliases"].items()]
        self._n_keys = len(pk_refs)
        self._key_sql = re.sub(
            r"^\s*SELECT\s+", f"SELECT {', '.join(pk_refs)}, ", sql,
            count=1, flags=re.IGNORECASE,
        )
        # incremental matching (VERDICT r4 #6): per-alias candidate
        # restriction needs the alias->(table, pk record key) map in
        # pk_refs order, plus the set of tables reached only through
        # subqueries (a change there invalidates candidate filtering —
        # fall back to a full re-query)
        self._aliases = [
            (a, t.name, f"{a}.{t.pk.name}")
            for a, t in ast["aliases"].items()
        ]
        self._subq_tables: set = self._collect_subq_tables(
            list(ast["conds"]) + list(ast.get("having", [])), set()
        )
        # ORDER BY / LIMIT / OFFSET change which rows are IN the result
        # for reasons outside the changed pks; LEFT JOINs null-extend —
        # a right-side insert/delete flips (pk, None) keys the candidate
        # filter cannot reach. Diff the full result in both cases.
        self._can_increment = not (
            ast.get("order") or ast.get("limit") or ast.get("offset")
            or any(j[0] == "left" for j in ast.get("joins", ()))
        )
        self.n_queries = 0  # full + filtered executions (tests/metrics)
        # serving-plane telemetry (ISSUE 16): fanout depth/shed series
        # land on the owning agent's registry; bound once at build time
        # (read-only after publication)
        self._registry = db.agent.metrics
        # a restored matcher's state predates any delta baseline (the
        # persisted manifest may be a whole downtime old): its first
        # poll MUST be a full re-diff or down-window changes are lost
        self._force_full = restore is not None
        # corroguard queue policy for the subscriber queues this matcher
        # hands out (duck-typed [serve] section — pubsub stays free of a
        # config import; any object with these attrs works)
        self.sub_queue, self.shed_policy, self.shed_threshold = (
            _serve_policy(serve))
        self._state: Dict[Any, Tuple] = {}
        self._log: List[Tuple[int, str, Any, Optional[List[Any]]]] = []
        self._log_base = 1  # change id of _log[0]
        self.last_change_id = 0
        self._subs: List[SubQueue] = []
        # batched fanout: pre-encoded NDJSON frame per retained change id
        # (trimmed alongside _log); n_encodes counts encode operations so
        # tests can pin encode-once-per-event (not per-subscriber)
        self._wire: Dict[int, bytes] = {}
        self.n_encodes = 0
        self._mu = threading.Lock()
        if restore is not None:
            # resume the change-id sequence where the persisted manifest
            # left off (the reference resumes from its per-sub SQLite db,
            # pubsub.rs:842-878), PLUS a max_log alias gap: the manifest
            # may be stale by up to a persist interval, so ids in
            # (persisted, crash] were handed to clients but are not
            # recorded — restarting right after the persisted id would
            # re-assign those ids to *different* events. Skipping max_log
            # ids guarantees no client-held id aliases (a client further
            # behind than max_log gets the full re-dump path anyway).
            self.last_change_id = int(restore.get("last_change_id", 0))
            if self.last_change_id:
                self.last_change_id += self.max_log
            self._log_base = self.last_change_id + 1
            if "state" in restore:
                # pre-shutdown materialized rows: the first poll() diffs
                # them against the live replica, so changes that happened
                # while the agent was down surface as ordinary events
                self._state = {
                    _dec_key(k): tuple(_dec(v) for v in row)
                    for k, row in restore["state"]
                }
            else:
                self._prime()
        else:
            self._prime()

    @classmethod
    def _collect_subq_tables(cls, conds, acc: set) -> set:
        """Table names reachable only through subquery right sides."""
        for cond in conds:
            op, lhs, rhs = cond
            if op == "or":
                for branch in lhs:
                    cls._collect_subq_tables(branch, acc)
            elif op == "not":
                cls._collect_subq_tables(lhs, acc)
            elif isinstance(rhs, tuple) and rhs and rhs[0] in (
                "subq", "subq_list"
            ):
                sub = rhs[1]
                for t in sub["aliases"].values():
                    name = getattr(t, "name", None)
                    if name:
                        acc.add(name)
                cls._collect_subq_tables(sub.get("conds", []), acc)
        return acc

    def _current(self) -> Dict[Any, Tuple]:
        self.n_queries += 1
        _, rows = self.db.query(self.node, self._key_sql, self.params)
        k = self._n_keys
        if k == 1:
            # single-table: scalar pk key (the wire shape clients expect)
            return {row[0]: tuple(row[1:]) for row in rows}
        return {tuple(row[:k]): tuple(row[k:]) for row in rows}

    def _prime(self) -> None:
        fresh = self._current()
        with self._mu:
            self._state = fresh

    # --- diffing ---------------------------------------------------------
    def poll(self, candidates: Optional[Dict[str, set]] = None) -> int:
        """Diff the node's replica against the materialized state; emit
        change events. Returns the number of events emitted.

        ``candidates`` is the round's applied-delta dict
        ``{table: {pk, ...}}`` from :class:`DeltaTracker`. When given
        (and the query is incrementally evaluable), only candidate pks
        are re-queried — matcher cost per round is proportional to the
        changed rows, not the result set (the reference's candidate-PK
        diffing, ``pubsub.rs:527-1100``). ``None`` = unknown delta:
        full re-query."""
        if candidates is not None and (
            self._force_full
            or not self._can_increment
            or any(t in candidates for t in self._subq_tables)
        ):
            candidates = None
        if candidates is None:
            fresh = self._current()
            with self._mu:
                self._force_full = False
                events = self._diff_upserts(fresh)
                for key in self._state:
                    if key not in fresh:
                        events.append((DELETE, key, None))
                self._state = fresh
                out, subs = self._log_events_locked(events)
            return self._fanout(out, subs)
        # incremental: ONE re-query restricted to the candidate pks — a
        # disjunction of per-alias IN conds, so a delta touching both
        # sides of a JOIN still costs a single scan
        k = self._n_keys
        pk_sets: Dict[int, set] = {}
        for i, (alias, tname, pk_key) in enumerate(self._aliases):
            pks = candidates.get(tname)
            if pks:
                pk_sets[i] = set(pks)
        if not pk_sets:
            return 0  # nothing this matcher watches changed
        in_conds = [
            ("in", self._aliases[i][2], sorted(s, key=repr))
            for i, s in pk_sets.items()
        ]
        extra = (in_conds if len(in_conds) == 1
                 else [("or", [[c] for c in in_conds], None)])
        self.n_queries += 1
        rows = self.db.query_filtered(
            self.node, self._key_sql, self.params, extra)
        if k == 1:
            fresh_part = {row[0]: tuple(row[1:]) for row in rows}
        else:
            fresh_part = {tuple(row[:k]): tuple(row[k:]) for row in rows}
        with self._mu:
            events = self._diff_upserts(fresh_part)
            for key in list(self._state):
                if key in fresh_part:
                    continue
                # affected = some component pk was a candidate
                if k == 1:
                    hit = any(key in s for s in pk_sets.values())
                else:
                    hit = any(key[i] in s for i, s in pk_sets.items())
                if hit:
                    events.append((DELETE, key, None))
            for kind, key, row in events:
                if kind == DELETE:
                    self._state.pop(key, None)
                else:
                    self._state[key] = tuple(row)
            out, subs = self._log_events_locked(events)
        return self._fanout(out, subs)

    def _diff_upserts(self, fresh: Dict[Any, Tuple]) -> list:
        """INSERT/UPSERT events for ``fresh`` vs the materialized state
        (``self._mu`` held). Deletes differ per path — callers append."""
        events = []
        for key, row in fresh.items():
            old = self._state.get(key)
            if old is None:
                events.append((INSERT, key, list(row)))
            elif old != row:
                events.append((UPSERT, key, list(row)))
        return events

    def _log_events_locked(self, events):
        """Assign change ids + append to the log. Named per the
        ``*_locked`` convention: ``self._mu`` must be held (state
        already updated). Returns (records, subscribers)."""
        out = []
        for kind, key, row in events:
            self.last_change_id += 1
            rec = (self.last_change_id, kind, key, row)
            self._log.append(rec)
            out.append(rec)
        if len(self._log) > self.max_log:
            drop = len(self._log) - self.max_log
            self._log = self._log[drop:]
            self._log_base += drop
        return out, list(self._subs)

    def _fanout(self, out, subs) -> int:
        """Deliver records to subscriber queues OUTSIDE the lock
        (detach of a lagged subscriber re-acquires it).

        Batched fanout (corroguard): the per-round delta is walked once
        — each record's NDJSON wire line is encoded a single time and
        cached by change id, so every subscriber's HTTP loop multicasts
        the same bytes instead of re-encoding per subscriber. Shed
        accounting: shed-oldest drops drain into
        ``corro.subs.shed_total`` here (frame-accurate — the series
        agrees with the gaps clients observe), and consumers past their
        shed threshold are disconnected."""
        if out and subs:
            frames = {rec[0]: encode_change_frame(rec) for rec in out}
            self.n_encodes += len(frames)
            with self._mu:
                self._wire.update(frames)
                if len(self._wire) > self.max_log:
                    for cid in [c for c in self._wire
                                if c < self._log_base]:
                        del self._wire[cid]
        lagged = []
        for q in subs:
            refused = False
            for rec in out:
                if not q.offer(("change", rec)):
                    refused = True
                    break
            shed = q.drain_shed()
            if shed:
                self._registry.counter("corro.subs.shed_total",
                                       float(shed), {"sub": self.id})
            if refused or q.lagged:
                lagged.append(q)
        if out and subs:
            # deepest subscriber queue after this fanout: the early-
            # warning signal admission control acts on — a depth
            # climbing toward SubQueue maxsize means a consumer is
            # about to shed
            self._registry.gauge(
                "corro.subs.queue.depth",
                max(q.qsize() for q in subs), {"sub": self.id})
        for q in lagged:
            if q.shed_policy != "shed-oldest":
                # legacy drop-newest: the disconnect IS the shed event
                self._registry.counter("corro.subs.shed_total", 1.0,
                                       {"sub": self.id})
            logger.warning("matcher %s: disconnecting lagged subscriber",
                           self.id)
            self.detach(q)
        return len(out)

    def wire_frame(self, change_id: int) -> Optional[bytes]:
        """The cached pre-encoded NDJSON line for a retained change id
        (None once trimmed past ``max_log`` — streaming loops fall back
        to encoding the record themselves)."""
        with self._mu:
            return self._wire.get(change_id)

    # --- subscriber attach/detach ---------------------------------------
    def attach(self, from_change_id: Optional[int] = None) -> "SubQueue":
        """A live event queue, optionally preloaded with the catch-up
        backlog from ``from_change_id`` (exclusive). If the backlog has
        been GC'd past that id, the subscriber gets a full re-dump
        (columns + rows), like the reference's query restart."""
        q = SubQueue(maxsize=self.sub_queue, shed_policy=self.shed_policy,
                     shed_threshold=self.shed_threshold)
        with self._mu:
            # preload (not offer): the catch-up dump bypasses the live
            # bound — a subscriber must never be shed before it has had
            # a chance to read its first frame
            q.preload(("columns", self.columns))
            if from_change_id is None:
                for key, row in self._state.items():
                    q.preload(("row", (key, list(row))))
                q.preload(("eoq", self.last_change_id))
            elif (from_change_id + 1 >= self._log_base
                  and from_change_id <= self.last_change_id):
                for rec in self._log[from_change_id + 1 - self._log_base:]:
                    q.preload(("change", rec))
            else:
                # backlog GC'd: full resync
                for key, row in self._state.items():
                    q.preload(("row", (key, list(row))))
                q.preload(("eoq", self.last_change_id))
            self._subs.append(q)
        return q

    def detach(self, q: queue.Queue) -> None:
        with self._mu:
            if q in self._subs:
                self._subs.remove(q)

    @property
    def n_subscribers(self) -> int:
        return len(self._subs)

    @property
    def delivery_tables(self) -> List[str]:
        """Table name per pk-key component, in key order — the HTTP
        streaming loop resolves commit stamps (delivery latency) through
        this without reaching into the parse internals."""
        return [tname for _alias, tname, _pk in self._aliases]

    # --- persistence (pubsub.rs stores matcher SQL + state on disk) ------
    def manifest(self) -> dict:
        with self._mu:
            # cheap pointer copy under the lock; the O(result-set) encode
            # happens outside so poll()/attach() are not blocked by it
            state_items = list(self._state.items())
            last = self.last_change_id
        state = [[_enc_key(k), [_enc(v) for v in row]]
                 for k, row in state_items]
        return {"id": self.id, "node": self.node, "sql": self.sql,
                "params": self.params, "last_change_id": last,
                "state": state}


class SubsManager:
    """All matchers of one agent; re-polls them after every round."""

    def __init__(self, db, persist_dir: Optional[str] = None, serve=None):
        self.db = db
        self.persist_dir = persist_dir
        self.serve = serve  # corroguard [serve] queue policy (or None)
        self._tracker = db.delta_tracker()  # shared, per-round cached
        self._matchers: Dict[str, Matcher] = {}
        self._by_query: Dict[Tuple, str] = {}
        self._dirty: set = set()
        self._mu = threading.Lock()
        self._persist_q: "queue.Queue[Optional[str]]" = queue.Queue()
        self._persist_thread: Optional[threading.Thread] = None
        db.agent.add_round_listener(self._on_round)
        if persist_dir:
            import sys

            from corrosion_tpu.utils.lifecycle import spawn_counted

            os.makedirs(persist_dir, exist_ok=True)
            # a corrosan session (if one is active) witnesses manifest
            # write/delete ordering under this root — the PR-5
            # unsubscribe-vs-persist resurrection is detected here.
            # Resolved via sys.modules so the production path never
            # imports the sanitizer: any live session necessarily
            # already imported the hooks module.
            san_hooks = sys.modules.get(
                "corrosion_tpu.analysis.sanitizer.hooks")
            if san_hooks is not None:
                san_hooks.watch_dir(persist_dir)
            # manifests are written off-thread: a large materialized state
            # must not stall the agent round loop. Counted + corro- named:
            # close() joins it, and leak reports name the owner.
            self._persist_thread = spawn_counted(
                self._persist_worker, name="corro-subs-persist"
            )

    PERSIST_EVERY = 16  # rounds between manifest re-writes per dirty matcher

    def _on_round(self, round_no: int) -> None:
        # snapshot under _mu: subscribe() publishes freshly-built
        # matchers through this dict, and an unlocked read would hand
        # the round thread a matcher with no happens-before edge to its
        # construction (corrosan attr-race on the init attrs)
        with self._mu:
            matchers = list(self._matchers.values())
        # one delta computation per observed node, shared by all its
        # matchers (None on the node's first round = full re-query)
        cands: Dict[int, Optional[Dict[str, set]]] = {}
        for node in {m.node for m in matchers}:
            try:
                cands[node] = self._tracker.changed(node)
            except Exception:  # noqa: BLE001 — degrade to full polls
                logger.exception("delta tracking failed for node %s", node)
                cands[node] = None
        for m in matchers:
            try:
                if m.poll(cands.get(m.node)):
                    with self._mu:
                        self._dirty.add(m.id)
            except Exception:  # noqa: BLE001 — a bad matcher must not stall rounds
                logger.exception("matcher %s poll failed", m.id)
        # re-persist dirty matchers periodically (not every round — the
        # manifest carries the full materialized state) so a restart
        # resumes the change-id sequence close to where it stopped; a
        # stale manifest is safe: restore re-diffs from the persisted
        # state, skips a max_log id alias gap, and attach() treats
        # from>last_change_id as backlog-lost. The dirty check runs
        # under _mu (corrosan attr-race: the old unlocked `if
        # self._dirty` fast path raced close()'s swap) — the cadence
        # check alone keeps the common round lock-free.
        if round_no % self.PERSIST_EVERY == 0:
            with self._mu:
                dirty, self._dirty = self._dirty, set()
            for mid in dirty:
                if mid in self._matchers:
                    self._persist_q.put(mid)

    def _persist_worker(self) -> None:
        while True:
            mid = self._persist_q.get()
            if mid is None:
                return
            m = self._matchers.get(mid)
            if m is not None:
                try:
                    self._persist(m)
                except Exception:  # noqa: BLE001
                    logger.exception("failed to persist subscription %s", mid)
                # an unsubscribe() racing the write above has already
                # unlinked the manifest — a write that lands after it
                # would resurrect the dead subscription on restart.
                # Re-check liveness and remove the file we just wrote.
                with self._mu:
                    alive = mid in self._matchers
                if not alive and self.persist_dir:
                    path = os.path.join(self.persist_dir, f"{mid}.json")
                    with contextlib.suppress(FileNotFoundError):
                        os.unlink(path)

    def subscribe(self, node: int, sql: str, params: Any = None
                  ) -> Tuple[Matcher, bool]:
        """Get-or-create a matcher (the reference dedupes identical query
        subs onto one matcher). Returns (matcher, created)."""
        key = (node, sql, json.dumps(params, sort_keys=True, default=str))
        with self._mu:
            mid = self._by_query.get(key)
            if mid is not None:
                return self._matchers[mid], False
            m = Matcher(self.db, node, sql, params, serve=self.serve)
            self._matchers[m.id] = m
            self._by_query[key] = m.id
            self._persist(m)
            return m, True

    def get(self, sub_id: str) -> Optional[Matcher]:
        return self._matchers.get(sub_id)

    def unsubscribe(self, sub_id: str) -> bool:
        with self._mu:
            m = self._matchers.pop(sub_id, None)
            if m is None:
                return False
            self._by_query = {k: v for k, v in self._by_query.items()
                              if v != sub_id}
        # filesystem work OUTSIDE the lock (corrolint blocking-under-lock)
        if self.persist_dir:
            path = os.path.join(self.persist_dir, f"{sub_id}.json")
            if os.path.exists(path):
                os.unlink(path)
        return True

    def ids(self) -> List[str]:
        return list(self._matchers)

    def close(self) -> None:
        """Detach from the agent's round loop and flush pending manifests
        (matchers stop polling; their state stays restorable)."""
        self.db.agent.remove_round_listener(self._on_round)
        thread = self._persist_thread
        if thread is not None:
            self._persist_q.put(None)
            thread.join(timeout=30.0)
            with self._mu:
                self._persist_thread = None
        with self._mu:
            dirty, self._dirty = self._dirty, set()
        for mid in dirty:
            m = self._matchers.get(mid)
            if m is not None:
                self._persist(m)

    def _persist(self, m: Matcher) -> None:
        if not self.persist_dir:
            return
        with open(os.path.join(self.persist_dir, f"{m.id}.json"), "w") as f:
            json.dump(m.manifest(), f)

    def restore(self) -> int:
        """Recreate persisted matchers (boot hook, ``setup.rs:291-344``)."""
        if not self.persist_dir or not os.path.isdir(self.persist_dir):
            return 0
        n = 0
        for name in sorted(os.listdir(self.persist_dir)):
            if not name.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.persist_dir, name)) as f:
                    man = json.load(f)
                m = Matcher(self.db, man["node"], man["sql"], man["params"],
                            sub_id=man["id"], restore=man, serve=self.serve)
                with self._mu:
                    self._matchers[m.id] = m
                    key = (m.node, m.sql,
                           json.dumps(m.params, sort_keys=True, default=str))
                    self._by_query[key] = m.id
                n += 1
            except Exception:  # noqa: BLE001
                logger.exception("failed to restore subscription %s", name)
        return n


class UpdatesManager:
    """Row-level per-table feeds (``updates.rs:61-250``): each table feed
    diffs pk liveness + row content every round and emits
    ``NotifyEvent {kind, pk}``."""

    def __init__(self, db, node: int = 0, serve=None):
        self.db = db
        self.node = node
        self.serve = serve  # corroguard [serve] queue policy (or None)
        self._tracker = db.delta_tracker()  # shared, per-round cached
        self._feeds: Dict[str, List[queue.Queue]] = {}
        self._state: Dict[str, Dict[Any, Tuple]] = {}
        # tables whose last incremental re-read failed: their deltas are
        # consumed (the tracker baseline advanced), so the next round
        # must run a full self-healing snapshot
        self._force_full: set = set()
        self._mu = threading.Lock()
        db.agent.add_round_listener(self._on_round)

    def attach(self, table: str) -> SubQueue:
        self.db.schema.table(table)  # raises on unknown table
        maxsize, shed_policy, shed_threshold = _serve_policy(self.serve)
        q = SubQueue(maxsize=maxsize, shed_policy=shed_policy,
                     shed_threshold=shed_threshold)
        with self._mu:
            if table not in self._feeds:
                self._state[table] = self._snapshot_table(table)
            self._feeds.setdefault(table, []).append(q)
        return q

    def detach(self, table: str, q: queue.Queue) -> None:
        with self._mu:
            if table in self._feeds and q in self._feeds[table]:
                self._feeds[table].remove(q)
                if not self._feeds[table]:
                    del self._feeds[table]
                    del self._state[table]
                    self._force_full.discard(table)

    def _snapshot_table(self, table: str) -> Dict[Any, Tuple]:
        t = self.db.schema.table(table)
        cols = [c.name for c in t.columns]
        sql = f"SELECT {', '.join(cols)} FROM {table}"
        _, rows = self.db.query(self.node, sql)
        pk_idx = cols.index(t.pk.name)
        return {row[pk_idx]: tuple(row) for row in rows}

    def _on_round(self, round_no: int) -> None:
        with self._mu:
            tables = list(self._feeds)
        if not tables:
            return
        try:
            cands = self._tracker.changed(self.node)
        except Exception:  # noqa: BLE001 — degrade to full snapshots
            logger.exception("delta tracking failed for node %s", self.node)
            cands = None
        for table in tables:
            force = table in self._force_full
            if cands is not None and table not in cands and not force:
                continue  # no applied change touched this table
            try:
                if cands is None or force:
                    # unknown delta (or recovering from a failed
                    # incremental read whose candidates are already
                    # consumed): full table snapshot + full diff
                    fresh = self._snapshot_table(table)
                    partial = None
                    # detach() also mutates _force_full from API
                    # threads — not single-writer, so take the lock
                    with self._mu:
                        self._force_full.discard(table)
                else:
                    # incremental: re-read only the candidate rows
                    # (read_row returns None for dead/absent rows)
                    t = self.db.schema.table(table)
                    cols = [c.name for c in t.columns]
                    partial = {}
                    for pk in cands[table]:
                        row = self.db.read_row(self.node, table, pk)
                        partial[pk] = (
                            tuple(row.get(c) for c in cols)
                            if row is not None else None
                        )
                    fresh = None
            except Exception:  # noqa: BLE001
                logger.exception("updates feed poll failed for %s", table)
                # the round's candidates are consumed (tracker baseline
                # advanced): self-heal with a full snapshot next round
                with self._mu:
                    self._force_full.add(table)
                continue
            with self._mu:
                old = self._state.get(table)
                if old is None:
                    continue
                events = []
                if partial is not None:
                    for pk, row in partial.items():
                        if row is None:
                            if pk in old:
                                events.append((DELETE, pk))
                                old.pop(pk, None)
                        elif pk not in old:
                            events.append((INSERT, pk))
                            old[pk] = row
                        elif old[pk] != row:
                            events.append((UPSERT, pk))
                            old[pk] = row
                else:
                    for pk, row in fresh.items():
                        if pk not in old:
                            events.append((INSERT, pk))
                        elif old[pk] != row:
                            events.append((UPSERT, pk))
                    for pk in old:
                        if pk not in fresh:
                            events.append((DELETE, pk))
                    self._state[table] = fresh
                subs = list(self._feeds.get(table, ()))
            lagged = []
            label = {"sub": f"updates:{table}"}
            for q in subs:
                refused = False
                for ev in events:
                    if not q.offer(("notify", ev)):
                        refused = True
                        break
                shed = q.drain_shed()
                if shed:
                    # shed-oldest drops (frame-accurate, like the
                    # matcher fanout)
                    self.db.agent.metrics.counter(
                        "corro.subs.shed_total", float(shed), label)
                if refused or q.lagged:
                    lagged.append(q)
            if events and subs:
                self.db.agent.metrics.gauge(
                    "corro.subs.queue.depth",
                    max(q.qsize() for q in subs), label)
            for q in lagged:
                if q.shed_policy != "shed-oldest":
                    # legacy drop-newest: the disconnect IS the shed
                    self.db.agent.metrics.counter(
                        "corro.subs.shed_total", 1.0, label)
                logger.warning("updates feed %s: disconnecting lagged "
                               "subscriber", table)
                self.detach(table, q)
