"""corrosion_tpu — a TPU-native framework with the capabilities of Corrosion.

Corrosion (the reference, somtochiama/corrosion) is a gossip-based, eventually
consistent distributed SQLite for service discovery: SWIM membership (foca),
CRDT changeset broadcast over QUIC, periodic anti-entropy sync, LWW register
merge via the CR-SQLite extension.

This package rebuilds those capabilities TPU-first. Actual layout:

- ``sim``: the TPU cluster simulator. Nodes are rows of struct-of-arrays
  state; SWIM probe/ack/suspect/disseminate (``sim/swim.py``, bounded-table
  ``sim/scale.py``), changeset fanout (``sim/broadcast.py``), and
  anti-entropy sync (``sim/sync.py``) are fused, jittable message-passing
  steps (``sim/step.py``, ``sim/scale_step.py``); ``sim/parity.py`` holds
  the host oracle + parity harness. State shards across a
  ``jax.sharding.Mesh`` (``parallel/mesh.py``) so 10k-100k node clusters
  simulate on a TPU pod slice.
- ``ops``: the jittable kernels — LWW merge as lexicographic max over
  ``(col_version, value, site_id)`` clocks (``ops/lww.py``), per-origin
  version/gap bookkeeping (``ops/versions.py``), slot allocation and
  sampling primitives (``ops/slots.py``, ``ops/select.py``).
- ``agent`` + ``db`` + ``api``: the operator surface around the simulator —
  the agent round loop (``agent/core.py``), SQL over the LWW store
  (``db/``), HTTP ``/v1/*`` routes (``api/http.py``).
- Top-level subsystems mirroring the reference's crates: ``pg`` (PG wire),
  ``pubsub`` (subscriptions + update feeds), ``admin`` (UDS admin socket),
  ``cli``, ``client``, ``config``, ``checkpoint``, ``maintenance``,
  ``consul``, ``tpl`` (templates), ``testing`` (devcluster fixtures).
- ``utils``: tripwire/backoff/spawn/metrics/locks/assertions/hlc/tracing —
  the reference's lifecycle crates, reimagined for threads + JAX.
- ``native``: ctypes bindings to the C++ host engine
  (``native/corro_host.cpp``) used for parity checking.
"""

__version__ = "0.2.0"
