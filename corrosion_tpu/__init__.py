"""corrosion_tpu — a TPU-native framework with the capabilities of Corrosion.

Corrosion (the reference, superfly/corrosion) is a gossip-based, eventually
consistent distributed SQLite for service discovery: SWIM membership (foca),
CRDT changeset broadcast over QUIC, periodic anti-entropy sync, LWW register
merge via the CR-SQLite extension.

This package rebuilds those capabilities TPU-first, in two halves:

- ``corrosion_tpu.sim``: the TPU cluster simulator. Nodes are rows of
  struct-of-arrays state; SWIM probe/ack/suspect/disseminate, changeset
  fanout, and anti-entropy sync are fused, jittable message-passing steps;
  CR-SQLite's LWW merge is an elementwise lexicographic max over
  ``(col_version, value, site_id)`` clocks. State shards across a
  ``jax.sharding.Mesh`` so 10k-100k node clusters simulate on a TPU pod
  slice (neighbor exchange rides ICI collectives).

- ``corrosion_tpu.runtime``: the host-side agent runtime — a real,
  networked eventually-consistent SQLite node (asyncio + stdlib sqlite3)
  with the same protocol semantics, used both standalone (the product
  surface: HTTP API, schema management, subscriptions, CLI, admin) and as
  the small-cluster oracle the simulator is parity-checked against.

Shared pieces live in ``ops`` (jittable kernels), ``parallel`` (mesh and
sharding helpers), and ``utils`` (tripwire/backoff/spawn/metrics — the
reference's lifecycle crates, reimagined for asyncio).
"""

__version__ = "0.1.0"
