#!/usr/bin/env python
"""corrocost gate probe -> artifacts/cost_r20.json (ISSUE 20).

The CI face of the jaxpr/HLO cost & collective audit (docs/corrolint.md
"corrocost", PERF.md "Static roofline"):

- **cost fits**: every priced hot entry point traced abstractly at the
  fit points, interpolated exactly (Fraction arithmetic), holdouts
  verified, degrees gated against ``COST_DEGREES`` AND against the
  corrobudget symbolic inventory's own degrees — compute must grow
  exactly as fast as the state it touches, no faster;
- **1M roofline**: per-round flops / HBM-model bytes projected to the
  declared 1M point, cross-checked against a DIRECT abstract trace at
  N=1M (bit-equal for exact entries; recorded relative error for the
  piecewise fused path);
- **XLA cross-check**: the model vs ``compiled.cost_analysis()`` ratio
  must stay inside the declared band where the backend reports it;
- **collective audit**: both registered sharded entries lowered on the
  8-way virtual mesh across the FULL 16-combo knob matrix
  (quiet x fused x narrow_int8 x narrow_q_int8); manifests must match
  the committed ``COLLECTIVE_PINS`` bit for bit, the 2-D (dcn, node)
  mesh must compile the identical manifest, and the per-round traffic
  fit must hold at its holdout N;
- **mutation gate**: the smuggled-gather fixture MUST fail the pin
  gate — a gate that cannot fire is decoration;
- **lint face**: the ``collective-budget`` / ``cost-drift`` rules must
  be clean over the repo walk (rule counts recorded).

Exit 0 with ``"ok": true`` when every claim holds; exit 1 otherwise
(the artifact is written either way). First cold run compiles the full
matrix (~10 min); the persistent compile cache makes reruns cheap.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# must be set before jax initializes; conftest does the same for tests
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()


def main() -> int:
    problems = []

    import jax

    jax.config.update("jax_platforms", "cpu")

    from corrosion_tpu.analysis import collectives, cost, shapes
    from corrosion_tpu.analysis.runner import lint_report

    # --- cost fits + degree gates ----------------------------------------
    fits_rec = {}
    for name, entry in cost.PRICED_ENTRY_POINTS.items():
        fits = cost.fit_entry(name)
        rec = {}
        for metric, fit in fits.items():
            rec[metric] = {
                "poly": fit.render(),
                "exact": fit.exact,
                "degrees": {s: fit.degree(s) for s in fit.extents},
            }
            if entry.exact_fit and not fit.exact:
                problems.append(
                    f"{name}/{metric}: fit failed its holdouts — cost "
                    "is no longer polynomial in the extents")
        declared = cost.COST_DEGREES[entry.root]
        for sym in entry.extents:
            got = fits["flops"].degree(sym)
            want = declared.get(sym, 0)
            if got > want:
                problems.append(
                    f"{name}: flop degree {got} in {sym} exceeds the "
                    f"{entry.root} inventory degree {want} — compute "
                    "outgrew the state it touches")
        fits_rec[name] = rec

    # the inventory's OWN degrees must equal the declaration the lint
    # rule gates on (three-way: fits <= declared == inventory)
    inv_degrees = {}
    for root, declared in cost.COST_DEGREES.items():
        mode = "scale" if root == "ScaleSimState" else "full"
        # symbolic default (cfg=None) — the lint rule's own view; a
        # concrete config collapses bounded dims to constants
        inv = shapes.static_inventory(None, mode=mode)
        degs = cost.inventory_degrees(inv)
        inv_degrees[root] = degs
        for sym, want in declared.items():
            if degs.get(sym, 0) != want:
                problems.append(
                    f"{root}: inventory degree {degs.get(sym, 0)} in "
                    f"{sym} != declared COST_DEGREES {want}")

    # --- 1M roofline ------------------------------------------------------
    roof = cost.roofline()
    for name, rec in roof["entries"].items():
        for metric in ("flops", "hbm_bytes"):
            if rec["exact_fit_expected"]:
                if not rec[f"{metric}_direct_1m_matches"]:
                    problems.append(
                        f"{name}/{metric}: 1M extrapolation does not "
                        "reproduce the direct 1M trace")
            elif rec[f"{metric}_fit_rel_err"] > 1e-3:
                problems.append(
                    f"{name}/{metric}: fused fit drifted "
                    f"{rec[f'{metric}_fit_rel_err']:.2e} from the "
                    "direct 1M trace")

    # --- XLA cost_analysis cross-check -----------------------------------
    xla = cost.xla_agreement()
    if xla["reported"] and not xla["agrees"]:
        problems.append(
            f"model/XLA ratio left the band {xla['band']}: "
            f"flops {xla['flops_ratio']:.3f}, "
            f"bytes {xla['bytes_ratio']:.3f}")

    # --- collective audit: full knob matrix, both entries, both meshes ---
    audits = {}
    for entry in collectives.COLLECTIVE_BUDGET:
        rec = collectives.audit_entry(entry)
        problems.extend(rec.pop("problems"))
        audits[entry] = rec

    # --- per-round traffic fit + 1M projection ---------------------------
    traffic = collectives.collective_fit()
    for kind, rec in traffic["kinds"].items():
        if not rec["exact"]:
            problems.append(
                f"collective {kind} bytes are not affine in N "
                f"(holdout N={collectives.FIT_HOLDOUT_N} missed) — "
                "projection downgraded to unverified quadratic")

    # --- mutation gate: the smuggled gather MUST fire ---------------------
    mutated = collectives.collective_manifest(
        "sharded_scale_run", "dense",
        fn=collectives.smuggled_gather_entry)
    mut_problems = collectives.check_manifest(
        "sharded_scale_run", "dense", mutated)
    if not mut_problems:
        problems.append(
            "mutation fixture (smuggled all-gather) passed the pin "
            "gate — the gate cannot fire")

    # --- rule counts over the repo walk ----------------------------------
    root_dir = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings, n_files = lint_report(
        [os.path.join(root_dir, "corrosion_tpu"),
         os.path.join(root_dir, "bench.py")],
        checkers=["collective-budget", "cost-drift"])
    rule_counts = {"collective-budget": 0, "cost-drift": 0}
    for f in findings:
        rule_counts[f.rule] = rule_counts.get(f.rule, 0) + 1
        problems.append(f.render())

    record = {
        "probe": "cost_r20",
        "ok": not problems,
        "roofline": roof,
        "fits": fits_rec,
        "cost_degrees": cost.COST_DEGREES,
        "inventory_degrees": inv_degrees,
        "xla_agreement": xla,
        "collective_audit": audits,
        "collective_fit": traffic,
        "mutation_gate_fired": bool(mut_problems),
        "mutation_problems": mut_problems,
        "rule_counts": rule_counts,
        "files_checked": n_files,
    }
    if problems:
        record["problems"] = problems
    out = sys.argv[sys.argv.index("--output") + 1] if (
        "--output" in sys.argv) else "artifacts/cost_r20.json"
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    with open(out, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    print(json.dumps({
        "probe": "cost_r20", "ok": record["ok"],
        "mutation_gate_fired": record["mutation_gate_fired"],
        "flops_per_round_1m": roof["entries"].get(
            "sharded_scale_run", {}).get("flops_per_round"),
        "collective_bytes_per_round_1m": traffic["projected_1m_bytes"],
        "rule_counts": rule_counts,
    }))
    return 0 if not problems else 1


if __name__ == "__main__":
    sys.exit(main())
