#!/usr/bin/env python
"""corroquiet parity gate -> artifacts/quiet_r19.json (ISSUE 19).

The CI face of the quiescence-aware round variant's ONE contract —
``scale_sim_step_quiet`` is bitwise-indistinguishable from the dense
round on any trace — swept where it is hardest to hold:

- **masked == dense over the chaos registry**: every shipped scenario
  runs twice, once under ``quiet="on"`` and once under
  ``quiet="off"``. Both legs must pass all three oracles, and their
  fixpoint ``state_digest`` (a content hash of every reference leaf)
  must be IDENTICAL — the round variant is execution-only all the way
  through kills, skew, corruption, remesh, and mid-lineage flips;
- **quiescent-speedup smoke**: the trace the variant exists for — a
  settled cluster — must actually be cheap: active-set rounds at
  least 3x faster than dense at the bench smoke extents, bitwise
  equal, with the cheap-path round count recorded.

Run under ``CORROSAN=1`` from ``scripts/check.sh`` (the record notes
whether the sanitizer was live). Exit 0 with ``"ok": true`` when every
claim holds; exit 1 otherwise (the artifact is written either way).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# must be set before jax initializes; conftest does the same for tests
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()


def _scenario_sweep(problems):
    """Every registry scenario under both round variants: both green,
    identical fixpoint digest, identical oracle-arrival rounds."""
    import dataclasses

    from corrosion_tpu.resilience.chaos import SCENARIOS, run_scenario

    rows = []
    for name in sorted(SCENARIOS):
        script = SCENARIOS[name]
        legs = {}
        for mode in ("on", "off"):
            legs[mode] = run_scenario(
                dataclasses.replace(script, quiet=mode), seed=0)
        on, off = legs["on"], legs["off"]
        row = {
            "scenario": name,
            "ok_quiet": on["ok"],
            "ok_dense": off["ok"],
            "skipped": bool(on.get("skipped") or off.get("skipped")),
        }
        if not row["skipped"]:
            row["digest_match"] = (
                on["state_digest"] == off["state_digest"])
            row["rounds_to_convergence"] = on["rounds_to_convergence"]
            row["rounds_to_quiescence"] = on["rounds_to_quiescence"]
            if not on["ok"]:
                problems.append(
                    f"{name}: quiet leg failed: {on.get('problems')}")
            if not off["ok"]:
                problems.append(
                    f"{name}: dense leg failed: {off.get('problems')}")
            if not row["digest_match"]:
                problems.append(
                    f"{name}: masked != dense (fixpoint digest differs)")
            for k in ("rounds_to_convergence", "rounds_to_quiescence"):
                if on[k] != off[k]:
                    problems.append(
                        f"{name}: {k} differs across round variants: "
                        f"{on[k]} vs {off[k]}")
        rows.append(row)
    return rows


def _speedup_smoke(problems):
    """The steady-state claim at the bench smoke extents: quiet vs
    dense on a fully settled trace, bitwise gate + >= 3x."""
    import dataclasses
    import functools

    import jax
    import jax.numpy as jnp
    import jax.random as jr
    import numpy as np

    from corrosion_tpu.sim.scale_step import (
        ScaleSimState,
        make_write_inputs,
        scale_run_rounds,
        scale_sim_config,
    )
    from corrosion_tpu.sim.transport import NetModel

    n = int(os.environ.get("QUIET_PROBE_NODES", "512"))
    rounds = int(os.environ.get("QUIET_PROBE_ROUNDS", "48"))
    cfg = scale_sim_config(n)
    net = NetModel.create(n)
    inputs = make_write_inputs(cfg, jr.key(5), rounds,
                               jnp.zeros((rounds, n), bool))
    rps, final = {}, {}
    cheap = 0
    for label, mode in (("quiet", "on"), ("dense", "off")):
        c = dataclasses.replace(cfg, quiet=mode).validate()
        run = jax.jit(functools.partial(scale_run_rounds, c),
                      donate_argnums=(0,))
        s = jax.block_until_ready(
            run(ScaleSimState.create(c), net, jr.key(6), inputs))[0]
        t0 = time.perf_counter()
        s, infos = run(s, net, jr.key(7), inputs)
        jax.block_until_ready(s)
        rps[label] = rounds / (time.perf_counter() - t0)
        final[label] = s
        if label == "quiet":
            cheap = int(np.asarray(infos["quiet_round"]).sum())
    parity = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(final["quiet"]),
                        jax.tree.leaves(final["dense"]))
    )
    speedup = rps["quiet"] / max(rps["dense"], 1e-9)
    if not parity:
        problems.append("speedup smoke: quiet != dense bitwise")
    if speedup < 3.0:
        problems.append(
            f"speedup smoke: {speedup:.2f}x < 3x "
            f"({cheap}/{rounds} rounds cheap-pathed)")
    return {
        "n_nodes": n,
        "rounds": rounds,
        "cheap_rounds": cheap,
        "rps_quiet": round(rps["quiet"], 2),
        "rps_dense": round(rps["dense"], 2),
        "speedup": round(speedup, 2),
        "parity": parity,
    }


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    problems = []
    t0 = time.perf_counter()
    scenarios = _scenario_sweep(problems)
    smoke = _speedup_smoke(problems)

    record = {
        "probe": "quiet_r19",
        "ok": not problems,
        "corrosan": os.environ.get("CORROSAN", "") == "1",
        "scenarios": scenarios,
        "speedup_smoke": smoke,
        "elapsed_s": round(time.perf_counter() - t0, 2),
    }
    if problems:
        record["problems"] = problems
    out = sys.argv[sys.argv.index("--output") + 1] if (
        "--output" in sys.argv) else "artifacts/quiet_r19.json"
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    with open(out, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    print(json.dumps({
        "probe": record["probe"], "ok": record["ok"],
        "scenarios": len(scenarios),
        "digest_matches": sum(
            1 for r in scenarios if r.get("digest_match")),
        "speedup": smoke["speedup"],
    }))
    return 0 if not problems else 1


if __name__ == "__main__":
    sys.exit(main())
