"""Sub-phase profile of the piggyback broadcast path at scale: times the
selection (queue sampling + field gathers), the receiver ingest (dedupe +
apply + re-enqueue), and the enqueue machinery separately, printing each
number as soon as it's measured.

Usage: python scripts/profile_bcast.py [n_nodes] [scan_rounds]
"""

import os
import sys
import time

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from corrosion_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()


import jax.numpy as jnp  # noqa: E402
import jax.random as jr  # noqa: E402

from corrosion_tpu.ops.select import sample_k  # noqa: E402
from corrosion_tpu.ops.slots import budget_mask  # noqa: E402
from corrosion_tpu.sim.broadcast import (  # noqa: E402
    CHANGE_WIRE_BYTES,
    NO_Q,
    _enqueue,
    ingest_changes,
)
from corrosion_tpu.sim.scale_step import (  # noqa: E402
    ScaleSimState,
    scale_sim_config,
)
from corrosion_tpu.ops.dense import select_cols  # noqa: E402


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    cfg = scale_sim_config(n, n_origins=min(16, n))
    st = ScaleSimState.create(cfg)
    cst0 = st.crdt
    key = jr.key(0)
    q, r = cfg.bcast_queue, cfg.pig_changes
    n_chan = 4
    m = n_chan * r
    iarr = jnp.arange(n, dtype=jnp.int32)
    print(
        f"n={n} q={q} r={r} m={m} platform={jax.devices()[0].platform}",
        flush=True,
    )

    def timed(name, step, carry):
        def run(c, key):
            def body(cr, _):
                c, k = cr
                k, sub = jr.split(k)
                return (step(c, sub), k), ()

            (c, _), _ = jax.lax.scan(body, (c, key), None, length=rounds)
            return c

        f = jax.jit(run)
        t0 = time.perf_counter()
        jax.block_until_ready(f(carry, key))
        compile_s = time.perf_counter() - t0
        reps = 3
        t0 = time.perf_counter()
        for _ in range(reps):
            jax.block_until_ready(f(carry, key))
        dt = (time.perf_counter() - t0) / reps / rounds
        print(
            f"{name:18s} {dt * 1000:9.2f} ms/round  (compile {compile_s:.0f}s)",
            flush=True,
        )

    # synthetic channels: ring senders
    channels = [((iarr + 1 + j) % n, jnp.ones(n, bool)) for j in range(n_chan)]

    # (a) selection: budget + sample + field gathers for all channels
    def selection(cst, k):
        live_slot = (cst.q_origin != NO_Q) & (cst.q_tx > 0)
        live_slot = budget_mask(
            live_slot, cst.q_tx,
            max(1, cfg.bcast_budget_bytes // (CHANGE_WIRE_BYTES * n_chan)),
        )
        sel_slots, sel_ok = sample_k(live_slot, r, k)
        acc = cst.q_val
        for src, valid in channels:
            s_slots = jax.lax.optimization_barrier(sel_slots[src])
            for a in (cst.q_origin, cst.q_dbv, cst.q_cell, cst.q_ver,
                      cst.q_val, cst.q_site, cst.q_clp, cst.q_seq,
                      cst.q_nseq, cst.q_ts):
                rows = jax.lax.optimization_barrier(a[src])
                got = select_cols(rows, s_slots)  # [N, R]
                acc = acc.at[:, :r].add(got)
        return cst._replace(q_val=acc)

    timed("selection", selection, cst0)

    # (b) ingest with synthetic messages
    def ingest(cst, k):
        k1, k2 = jr.split(k)
        origin = jr.randint(k1, (n, m), 0, cfg.n_origins, dtype=jnp.int32)
        dbv = jr.randint(k2, (n, m), 1, 64, dtype=jnp.int32)
        cell = (origin * 4 + dbv) % cfg.n_cells
        live = jnp.ones((n, m), bool)
        cst, _ = ingest_changes(
            cfg, cst, live, origin, dbv, cell, dbv, dbv, origin,
            jnp.zeros((n, m), jnp.int32),
        )
        return cst

    timed("ingest", ingest, cst0)

    # (c) enqueue alone
    def enq(cst, k):
        k1, k2 = jr.split(k)
        origin = jr.randint(k1, (n, m), 0, cfg.n_origins, dtype=jnp.int32)
        dbv = jr.randint(k2, (n, m), 1, 1 << 20, dtype=jnp.int32)
        z = jnp.zeros((n, m), jnp.int32)
        return _enqueue(
            cst, jnp.ones((n, m), bool), origin, dbv, z, dbv, dbv, origin, z,
            z, jnp.ones((n, m), jnp.int32), z,
            jnp.full((n, m), 3, jnp.int32),
        )

    timed("enqueue", enq, cst0)


if __name__ == "__main__":
    main()
