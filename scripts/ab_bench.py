"""Interleaved A/B bench for the scale-round traffic cuts.

One-shot sequential A/B runs are invalid on the axon tunnel: the first
(cold) run of round 4 measured 25 rounds/s and the fourth 407 at the
SAME config — the warmup drift dwarfs any cut's effect. This bench
compiles every arm in ONE process, warms them all, then interleaves
timed reps round-robin so drift hits every arm equally; per-arm medians
of per-rep throughput are robust to one-off stalls.

Usage: python scripts/ab_bench.py [n_nodes] [reps]
Arms: default (narrow int16 planes since round 4), pig16 (bounded
piggyback), pull10 (pull = score pool, i.e. the pre-cut sync width),
and wide (int32 planes — the pre-narrowing baseline). Writes one JSON line per arm plus a summary line to
stdout and ``artifacts/AB_BENCH_r04.jsonl``.
"""

from __future__ import annotations

import functools
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    import dataclasses

    import jax.numpy as jnp
    import jax.random as jr

    from corrosion_tpu.sim.scale_step import (
        ScaleRoundInput,
        ScaleSimState,
        scale_run_rounds,
        scale_sim_config,
    )
    from corrosion_tpu.sim.transport import NetModel

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    rounds = 8
    platform = jax.devices()[0].platform

    base = scale_sim_config(n, n_origins=min(16, n))
    arm_cfgs = {"default": base}
    arm_cfgs["pig16"] = dataclasses.replace(base, pig_members=16)
    arm_cfgs["pull10"] = dataclasses.replace(
        base, sync_pull_peers=base.sync_peers
    )
    if any(f.name == "narrow_dtypes"
           for f in dataclasses.fields(type(base))):
        # narrow is the default since round 4 — the experiment arm is
        # the WIDE int32 baseline
        arm_cfgs["wide"] = dataclasses.replace(base, narrow_dtypes=False)

    out_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", "AB_BENCH_r04.jsonl",
    )
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    sink = open(out_path, "a")

    def emit(rec):
        line = json.dumps(rec)
        print(line, flush=True)
        sink.write(line + "\n")
        sink.flush()

    key = jr.key(0)
    k1, k2, k3 = jr.split(jr.key(1), 3)

    arms = {}
    for name, cfg in arm_cfgs.items():
        st = ScaleSimState.create(cfg)
        net = NetModel.create(n, drop_prob=0.01)
        quiet = ScaleRoundInput.quiet(cfg)
        inputs = jax.tree.map(
            lambda a: jnp.broadcast_to(a, (rounds,) + a.shape), quiet
        )
        w = (jr.uniform(k1, (rounds, n)) < 0.25) & (
            jnp.arange(n)[None, :] < cfg.n_origins
        )
        inputs = inputs._replace(
            write_mask=w,
            write_cell=jr.randint(k2, (rounds, n), 0, cfg.n_cells,
                                  dtype=jnp.int32),
            write_val=jr.randint(k3, (rounds, n), 0, 1 << 20,
                                 dtype=jnp.int32),
        )
        t0 = time.perf_counter()
        run = jax.jit(functools.partial(scale_run_rounds, cfg))
        st2 = jax.block_until_ready(run(st, net, key, inputs))[0]
        emit({"arm": name, "event": "compiled",
              "compile_s": round(time.perf_counter() - t0, 1)})
        arms[name] = dict(run=run, st=st2, net=net, inputs=inputs,
                          times=[])

    # extra warm lap for every arm before any timing
    for a in arms.values():
        a["st"] = jax.block_until_ready(
            a["run"](a["st"], a["net"], key, a["inputs"])
        )[0]

    from corrosion_tpu.ops import megakernel

    for i in range(reps):
        for name, a in arms.items():
            t0 = time.perf_counter()
            a["st"], _ = a["run"](a["st"], a["net"], jr.fold_in(key, i),
                                  a["inputs"])
            jax.block_until_ready(a["st"])
            a["times"].append(time.perf_counter() - t0)

    for name, a in arms.items():
        rps = [rounds / t for t in a["times"]]
        cfg = arm_cfgs[name]
        emit({
            "metric": f"ab_rounds_per_sec_n{n}_{platform}",
            "arm": name,
            "value": round(statistics.median(rps), 2),
            "best": round(max(rps), 2),
            "worst": round(min(rps), 2),
            "unit": "rounds/s",
            "reps": reps,
            "pig_members": cfg.pig_members,
            "sync_pull_peers": cfg.sync_pull_peers,
            "pallas_fused": bool(
                megakernel.use_fused_ingest(cfg, 4 * cfg.pig_changes)
                and megakernel.use_fused_swim(
                    cfg.n_nodes, cfg.m_slots, cfg.pig_members,
                    narrow=cfg.narrow_dtypes)
            ),
        })


if __name__ == "__main__":
    main()
