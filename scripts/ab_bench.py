"""Interleaved A/B bench for the scale-round traffic cuts.

One-shot sequential A/B runs are invalid on the axon tunnel: the first
(cold) run of round 4 measured 25 rounds/s and the fourth 407 at the
SAME config — the warmup drift dwarfs any cut's effect. This bench
compiles every arm in ONE process, warms them all, then interleaves
timed reps round-robin so drift hits every arm equally; per-arm medians
of per-rep throughput are robust to one-off stalls.

Round-5 harness fixes (VERDICT r4 weak #3): the ``pull10`` arm pins
``sync_pull_peers`` to a LITERAL 10 (round 4 set it to ``sync_peers``,
which equals the default's pull width at small N — a no-op arm that
"measured" a 46% delta of pure noise); a ``control`` arm duplicates the
default config so every run prints its own noise floor; the summary
marks an arm's delta significant only when it exceeds that floor.

Arms: default (narrow int16 planes since round 4), control (=default),
wide (int32 planes), pig16 (bounded piggyback), pull10 (literal pull
width 10), tx4 (4-cell chunked transactions through the partial-buffer
path — VERDICT r4 next #5).

Usage: python scripts/ab_bench.py [n_nodes] [reps]
Writes one JSON line per arm plus a summary to stdout and
``artifacts/AB_BENCH_r05.jsonl``.
"""

from __future__ import annotations

import functools
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    import dataclasses

    import jax.numpy as jnp
    import jax.random as jr

    from corrosion_tpu.sim.scale_step import (
        ScaleSimState,
        make_write_inputs,
        scale_run_rounds,
        scale_sim_config,
    )
    from corrosion_tpu.sim.transport import NetModel

    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 20
    rounds = 8
    platform = jax.devices()[0].platform

    base = scale_sim_config(n, n_origins=min(16, n))
    arm_cfgs = {"default": base, "control": base}
    arm_cfgs["pig16"] = dataclasses.replace(base, pig_members=16)
    # literal 10 (the reference's max sync fanout, handlers.rs:838) —
    # NOT base.sync_peers, which made round 4's arm config-identical to
    # default at small N
    arm_cfgs["pull10"] = dataclasses.replace(base, sync_pull_peers=10)
    if any(f.name == "narrow_dtypes"
           for f in dataclasses.fields(type(base))):
        # narrow is the default since round 4 — the experiment arm is
        # the WIDE int32 baseline
        arm_cfgs["wide"] = dataclasses.replace(base, narrow_dtypes=False)
    arm_cfgs["tx4"] = scale_sim_config(n, n_origins=min(16, n),
                                       tx_max_cells=4)
    if any(f.name == "bcast_wire_budget"
           for f in dataclasses.fields(type(base))):
        # the round-5 fairness flag: measures the wire lane's cost for
        # the round-6 default-on decision (forces the XLA ingest path)
        arm_cfgs["wirebudget"] = dataclasses.replace(
            base, bcast_wire_budget=True)

    out_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "artifacts", "AB_BENCH_r05.jsonl",
    )
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    sink = open(out_path, "a")

    def emit(rec):
        line = json.dumps(rec)
        print(line, flush=True)
        sink.write(line + "\n")
        sink.flush()

    key = jr.key(0)
    k1, k2 = jr.split(jr.key(1), 2)

    def build_inputs(cfg):
        w = (jr.uniform(k1, (rounds, n)) < 0.25) & (
            jnp.arange(n)[None, :] < cfg.n_origins
        )
        return make_write_inputs(cfg, k2, rounds, w)

    arms = {}
    for name, cfg in arm_cfgs.items():
        st = ScaleSimState.create(cfg)
        net = NetModel.create(n, drop_prob=0.01)
        inputs = build_inputs(cfg)
        t0 = time.perf_counter()
        run = jax.jit(functools.partial(scale_run_rounds, cfg))
        st2 = jax.block_until_ready(run(st, net, key, inputs))[0]
        emit({"arm": name, "event": "compiled",
              "compile_s": round(time.perf_counter() - t0, 1)})
        arms[name] = dict(run=run, st=st2, net=net, inputs=inputs,
                          times=[])

    # extra warm lap for every arm before any timing
    for a in arms.values():
        a["st"] = jax.block_until_ready(
            a["run"](a["st"], a["net"], key, a["inputs"])
        )[0]

    from corrosion_tpu.ops import megakernel

    for i in range(reps):
        for name, a in arms.items():
            t0 = time.perf_counter()
            a["st"], _ = a["run"](a["st"], a["net"], jr.fold_in(key, i),
                                  a["inputs"])
            jax.block_until_ready(a["st"])
            a["times"].append(time.perf_counter() - t0)

    medians = {}
    for name, a in arms.items():
        rps = [rounds / t for t in a["times"]]
        cfg = arm_cfgs[name]
        medians[name] = statistics.median(rps)
        emit({
            "metric": f"ab_rounds_per_sec_n{n}_{platform}",
            "arm": name,
            "value": round(medians[name], 2),
            "best": round(max(rps), 2),
            "worst": round(min(rps), 2),
            "unit": "rounds/s",
            "reps": reps,
            "pig_members": cfg.pig_members,
            "sync_pull_peers": cfg.sync_pull_peers,
            "tx_max_cells": cfg.tx_max_cells,
            "pallas_fused": bool(
                megakernel.use_fused_ingest(cfg, 4 * cfg.pig_changes)
                and megakernel.use_fused_swim(
                    cfg.n_nodes, cfg.m_slots, cfg.pig_members,
                    narrow=cfg.narrow_dtypes,
                    mode=megakernel.fused_mode(cfg))
            ),
            "fused_mode": megakernel.fused_mode(cfg),
        })

    # the control arm runs an IDENTICAL config to default: their median
    # gap is one noise estimate, but it can land near zero by chance —
    # combine it with the within-arm rep spread (IQR) of both identical
    # arms so the floor never collapses below the run's real jitter
    def iqr(name):
        rps = sorted(rounds / t for t in arms[name]["times"])
        if len(rps) < 4:
            return max(rps) - min(rps)
        q = statistics.quantiles(rps, n=4)
        return q[2] - q[0]

    noise = max(abs(medians["control"] - medians["default"]),
                iqr("default"), iqr("control"))
    summary = {
        "metric": f"ab_summary_n{n}_{platform}",
        "reps": reps,
        "noise_floor_rps": round(noise, 2),
        "noise_floor_pct": round(
            100.0 * noise / max(medians["default"], 1e-9), 2),
        "deltas_vs_default": {
            name: {
                "delta_rps": round(m - medians["default"], 2),
                "significant": abs(m - medians["default"]) > noise,
            }
            for name, m in medians.items()
            if name not in ("default", "control")
        },
    }
    emit(summary)


if __name__ == "__main__":
    main()
