"""Per-phase profiling of the scale round on the current backend.

Times each protocol phase in isolation under lax.scan to find the slow
one. Usage: python scripts/profile_phases.py [n_nodes rounds]
"""

import functools
import os
import sys
import time

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from corrosion_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()

import jax.numpy as jnp
import jax.random as jr


from corrosion_tpu.ops.lww import STATE_ALIVE
from corrosion_tpu.ops.select import sample_k
from corrosion_tpu.sim.broadcast import local_write
from corrosion_tpu.sim.scale import scale_swim_step
from corrosion_tpu.sim.scale_step import (
    ScaleRoundInput,
    ScaleSimState,
    piggyback_bcast_step,
    scale_sim_config,
    scale_sim_step,
)
from corrosion_tpu.sim.sync import sync_step
from corrosion_tpu.sim.transport import NetModel


def timed(name, fn, *args):
    fn = jax.jit(fn)
    out = jax.block_until_ready(fn(*args))  # compile
    t0 = time.perf_counter()
    reps = 3
    for _ in range(reps):
        out = jax.block_until_ready(fn(*args))
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:30s} {dt*1000:10.2f} ms")
    return out


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 16384
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 10
    cfg = scale_sim_config(n, n_origins=min(16, n))
    net = NetModel.create(n, drop_prob=0.01)
    st = ScaleSimState.create(cfg)
    key = jr.key(0)
    inp = ScaleRoundInput.quiet(cfg)
    print(f"n={n} m={cfg.m_slots} rounds={rounds} platform={jax.devices()[0].platform}")

    def scan_over(step):
        def run(st, key):
            def body(carry, _):
                st, key = carry
                key, sub = jr.split(key)
                st = step(st, sub)
                return (st, key), ()
            (st, _), _ = jax.lax.scan(body, (st, key), None, length=rounds)
            return st

        return run

    # full round
    timed("full round", scan_over(lambda s, k: scale_sim_step(cfg, s, net, k, inp)[0]), st, key)

    # swim only
    def swim_only(s, k):
        swim, _, _, _ = scale_swim_step(cfg, s.swim, net, k)
        return s._replace(swim=swim)
    timed("swim only", scan_over(swim_only), st, key)

    # bcast only (fixed channels)
    iarr = jnp.arange(n, dtype=jnp.int32)
    channels = [((iarr + 1) % n, jnp.ones(n, bool))]
    def bcast_only(s, k):
        cst = local_write(cfg, s.crdt, inp.write_mask, inp.write_cell, inp.write_val)
        cst, _ = piggyback_bcast_step(cfg, cst, channels, k)
        return s._replace(crdt=cst)
    timed("bcast only", scan_over(bcast_only), st, key)

    # sync only (fixed peers, one per configured fanout slot)
    p_cnt = cfg.sync_peers
    peers = jnp.stack([(iarr + 1 + j) % n for j in range(p_cnt)], axis=1)
    p_ok = jnp.ones((n, p_cnt), bool)
    def sync_only(s, k):
        cst, _, _ = sync_step(cfg, s.crdt, peers, p_ok, s.swim.alive, net, k)
        return s._replace(crdt=cst)
    timed("sync only", scan_over(sync_only), st, key)

    # swim sub-phases: probe+merge without record/apply
    def swim_sample(s, k):
        bel = (s.swim.mem_id >= 0) & ((s.swim.mem_view & 3) == STATE_ALIVE)
        cols, ok = sample_k(bel, 3, k)
        return s._replace(swim=s.swim._replace(inc=s.swim.inc + cols[:, 0] * 0 + ok[:, 0]))
    timed("sample_k only", scan_over(swim_sample), st, key)


if __name__ == "__main__":
    main()
