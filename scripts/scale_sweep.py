#!/usr/bin/env python
"""BENCH_SCALE_N sweep -> artifacts/scale_sweep_r19.json (ISSUE 19).

The ROADMAP's carried scale-ladder item, stood up as a probe next to
the ckpt/membudget artifacts: climb N through CPU-scaled rungs (the
flagship extents shrunk to ``m_slots=8, tx_max_cells=1`` so a laptop
can hold them) and record, per rung:

- **rounds/s** for the dense round AND the quiet round variant on a
  settled trace (the corroquiet steady-state claim, measured at scale
  rather than at the bench smoke's N=512);
- **measured vs projected HBM**: ``obs/memory.state_bytes`` of the
  real state must equal corrobudget's static
  ``obs/memory.projected_bytes`` at the same N — the same agreement
  the bench records as ``hbm_bytes`` / ``hbm_bytes_projected_1m``,
  here pinned EXACTLY at every rung actually built;
- **checkpoint drain bytes per shard** from one segmented leg over the
  8 virtual devices (the ISSUE 9 sharded drain, priced at rung scale).

Rungs come from ``BENCH_SCALE_N`` (comma list, default
``100000,300000``). The 1M rung is deliberately NOT in the default
list: it is slow on CPU and belongs to a TPU tunnel session — set
``BENCH_SCALE_1M=1`` (and optionally put ``1000000`` in
``BENCH_SCALE_N``) to run it; otherwise the artifact records it as
skipped with the reason.

Exit 0 with ``"ok": true`` when every agreement holds; exit 1
otherwise (the artifact is written either way).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# must be set before jax initializes; conftest does the same for tests
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

SLOW_RUNG = 1_000_000


def _rung_cfg(n):
    """The CPU-scaled flagship config: small M so the O(N*M) tables fit
    a host at 300k, chunking off (tx_max_cells=1) so the rung prices
    the steady-state round, not the ingest tail."""
    from corrosion_tpu.sim.scale_step import scale_sim_config

    return scale_sim_config(
        n, m_slots=8, n_origins=4, n_rows=4, n_cols=2, tx_max_cells=1,
    )


def _run_rung(n, rounds, warm_runs, problems):
    import dataclasses
    import functools
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp
    import jax.random as jr
    import numpy as np

    from corrosion_tpu.obs.memory import projected_bytes, state_bytes
    from corrosion_tpu.parallel.mesh import make_mesh, shard_state
    from corrosion_tpu.resilience.segments import run_segmented
    from corrosion_tpu.sim.scale_step import (
        ScaleSimState,
        make_write_inputs,
        scale_run_rounds,
    )
    from corrosion_tpu.sim.transport import NetModel

    cfg = _rung_cfg(n)
    st = ScaleSimState.create(cfg)

    # --- measured vs projected HBM: must agree EXACTLY ------------------
    measured = state_bytes(st)
    projected = projected_bytes(cfg, n)
    if measured != projected:
        problems.append(
            f"N={n}: measured HBM {measured} != projected {projected}"
        )

    # --- rounds/s, dense vs quiet round variant -------------------------
    net = NetModel.create(n)
    inputs = make_write_inputs(cfg, jr.key(11), rounds,
                               jnp.zeros((rounds, n), bool))
    rps = {}
    quiet_cheap = 0
    for label, mode in (("dense", "off"), ("quiet", "on")):
        c = dataclasses.replace(cfg, quiet=mode).validate()
        run = jax.jit(functools.partial(scale_run_rounds, c),
                      donate_argnums=(0,))
        s = ScaleSimState.create(c)
        # warm runs settle the cold-start carry (SWIM membership churn)
        # so the timed leg prices the steady state the variant targets
        for i in range(warm_runs):
            s, infos = run(s, net, jr.key(12 + i), inputs)
        jax.block_until_ready(s)
        t0 = time.perf_counter()
        s, infos = run(s, net, jr.key(99), inputs)
        jax.block_until_ready(s)
        rps[label] = rounds / (time.perf_counter() - t0)
        if label == "quiet":
            quiet_cheap = int(np.asarray(infos["quiet_round"]).sum())

    # --- checkpoint drain bytes per shard (segmented, 8-way) ------------
    ckpt = {}
    n_dev = len(jax.devices())
    if n % n_dev == 0:
        mesh = make_mesh(jax.devices())
        seg_rounds = min(8, rounds)
        seg_in = jax.tree.map(lambda a: a[:seg_rounds], inputs)
        tmp = tempfile.mkdtemp(prefix="scale_sweep_")
        try:
            res = run_segmented(
                cfg, shard_state(mesh, n, ScaleSimState.create(cfg)),
                shard_state(mesh, n, net), jr.key(13),
                shard_state(mesh, n, seg_in),
                segment_rounds=max(seg_rounds // 2, 1), mode="scale",
                checkpoint_root=tmp,
            )
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
        s = res.stats
        if s["ckpt_shards"] != n_dev:
            problems.append(
                f"N={n}: drained {s['ckpt_shards']} shards, "
                f"expected {n_dev}"
            )
        ckpt = {
            "shards": s["ckpt_shards"],
            "drain_bytes": s["ckpt_drain_bytes"],
            "bytes_per_shard": s["ckpt_drain_bytes"]
            // max(s["ckpt_shards"], 1),
            "shard_bytes_max": s["ckpt_shard_bytes_max"],
            "quiet_mode": s.get("quiet_mode", "off"),
            "quiet_segments": s.get("quiet_segments", 0),
        }
    else:
        ckpt = {"skipped": f"N={n} not divisible by {n_dev} devices"}

    return {
        "n": n,
        "rounds": rounds,
        "hbm_bytes_measured": measured,
        "hbm_bytes_projected": projected,
        "hbm_agree": measured == projected,
        "rounds_per_s": {k: round(v, 3) for k, v in rps.items()},
        "quiet_speedup": round(rps["quiet"] / max(rps["dense"], 1e-9), 3),
        "quiet_cheap_rounds": quiet_cheap,
        "ckpt": ckpt,
    }


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    rungs = [
        int(x) for x in os.environ.get(
            "BENCH_SCALE_N", "100000,300000").split(",") if x.strip()
    ]
    rounds = int(os.environ.get("BENCH_SCALE_ROUNDS", "16"))
    warm_runs = int(os.environ.get("BENCH_SCALE_WARM_RUNS", "2"))
    run_1m = os.environ.get("BENCH_SCALE_1M", "") == "1"

    problems = []
    records = []
    for n in rungs:
        if n >= SLOW_RUNG and not run_1m:
            records.append({
                "n": n,
                "skipped": "slow rung: set BENCH_SCALE_1M=1 "
                           "(TPU tunnel session; hours on CPU)",
            })
            continue
        t0 = time.perf_counter()
        rec = _run_rung(n, rounds, warm_runs, problems)
        rec["elapsed_s"] = round(time.perf_counter() - t0, 2)
        records.append(rec)
    if not any(r["n"] >= SLOW_RUNG for r in records):
        records.append({
            "n": SLOW_RUNG,
            "skipped": "slow rung: set BENCH_SCALE_1M=1 and add it to "
                       "BENCH_SCALE_N (TPU tunnel session)",
        })

    record = {
        "metric": "scale_sweep_r19",
        "ok": not problems,
        "devices": len(jax.devices()),
        "rungs": records,
    }
    if problems:
        record["problems"] = problems
    out = sys.argv[sys.argv.index("--output") + 1] if (
        "--output" in sys.argv) else "artifacts/scale_sweep_r19.json"
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    with open(out, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    print(json.dumps({
        "metric": record["metric"], "ok": record["ok"],
        "rungs": [
            {k: r[k] for k in ("n", "rounds_per_s", "quiet_speedup",
                               "hbm_agree") if k in r}
            | ({"skipped": r["skipped"]} if "skipped" in r else {})
            for r in records
        ],
    }))
    return 0 if not problems else 1


if __name__ == "__main__":
    sys.exit(main())
