#!/bin/bash
# One tunnel-window TPU session: bank the round's artifacts in value
# order, tolerating a tunnel death at any point (every step writes its
# artifact independently; later steps reuse the persistent compile
# cache, utils/compile_cache.py).
#
#   1. pallas kernel probe on the real backend  -> PALLAS_PROBE_r05.json
#   2. fresh flagship bench at HEAD (100k)      -> artifacts/bench_last.json
#      (the driver's capture re-prints this cache AND reuses the warm
#      compile cache for its own fresh attempt)
#   3. interleaved A/B with control arm         -> AB_BENCH_r05.jsonl
#   4. 100k convergence under the fault mix     -> CONVERGENCE_r05_tpu.json
#   5. 100k chunked-tx (tx4) convergence        -> CONVERGENCE_r05_tpu_tx4.json
#   6. chunked-tx flagship bench cost           -> stdout (tx4 record)
#
# Usage: scripts/tpu_session.sh [logfile]
set -u
cd "$(dirname "$0")/.."
LOG="${1:-artifacts/tpu_session_r05.log}"
mkdir -p artifacts
exec >>"$LOG" 2>&1

step() {
  echo "=== [$(date -u +%H:%M:%S)] $1 (timeout ${2}s)"
  shift 2
  timeout "$TO" "$@"
  echo "=== rc=$?"
}

echo "=== session start $(date -u) commit $(git rev-parse --short HEAD)"

TO=1800 step "pallas probe" 1800 python scripts/pallas_probe.py 100000

TO=1800 step "fresh flagship bench" 1800 \
  env BENCH_WORKER=1 python bench.py

TO=2400 step "A/B with control arm" 2400 \
  python scripts/ab_bench.py 100000 20

TO=2400 step "convergence 100k" 2400 \
  python scripts/convergence_bench.py 100000 \
  --out=artifacts/CONVERGENCE_r05_tpu.json

TO=2400 step "convergence 100k tx4" 2400 \
  python scripts/convergence_bench.py 100000 --tx=4 \
  --out=artifacts/CONVERGENCE_r05_tpu_tx4.json

TO=1800 step "chunked-tx bench" 1800 \
  env BENCH_WORKER=1 BENCH_TX_CELLS=4 python bench.py

TO=1800 step "many-writer bench (collision regime)" 1800 \
  env BENCH_WORKER=1 BENCH_WRITERS=1024 python bench.py

echo "=== session end $(date -u)"
