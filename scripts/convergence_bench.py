"""Rounds-to-convergence + wall-clock/round across cluster sizes — the
tracked metric of BASELINE.md ("gossip rounds-to-convergence +
wall-clock/round, 256-100k nodes").

For each N: run a write burst (conflict-heavy, every origin hot), then
quiet gossip rounds in scan chunks until the convergence predicate holds
("no needs, equal heads, equal stores" over alive nodes — the same check
as the reference's Antithesis ``check_bookkeeping.py`` driver), with
kill/partition faults optionally injected during the burst.

Prints one JSON line per cluster size:
  {"n": N, "rounds_to_convergence": R, "ms_per_round": T, "platform": P}

Usage: python scripts/convergence_bench.py [N ...]  (default 256 1024 4096)
"""

import json
import os
import sys
import time

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from corrosion_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()


import jax.numpy as jnp  # noqa: E402
import jax.random as jr  # noqa: E402

from corrosion_tpu.sim.scale_step import (  # noqa: E402
    ScaleRoundInput,
    ScaleSimState,
    make_write_inputs,
    scale_crdt_metrics,
    scale_run_rounds,
    scale_sim_config,
)
from corrosion_tpu.sim.transport import NetModel  # noqa: E402

CHUNK = 8
MAX_ROUNDS = 1024
BURST_ROUNDS = 6


def run_one(n: int, faults: bool = True, n_origins: int | None = None,
            tx_cells: int = 1) -> dict:
    """Write burst (+ optional kills/partition) -> heal -> quiet rounds
    until the convergence predicate holds. ``tx_cells > 1`` routes the
    burst through K-cell chunked transactions (the partial-buffer path,
    ``change.rs:66-178`` + ``util.rs:1061-1194`` — VERDICT r4 next #5)."""
    n_origins = n_origins or int(os.environ.get("CONV_ORIGINS", "16"))
    cfg = scale_sim_config(n, n_origins=min(n_origins, n),
                           tx_max_cells=tx_cells)
    net = NetModel.create(n, drop_prob=0.02)
    st = ScaleSimState.create(cfg)
    key = jr.key(0)
    quiet = ScaleRoundInput.quiet(cfg)

    k1, k2, k4 = jr.split(jr.key(1), 3)
    w = (jr.uniform(k1, (BURST_ROUNDS, n)) < 0.5) & (
        jnp.arange(n)[None, :] < cfg.n_origins
    )
    burst = make_write_inputs(cfg, k2, BURST_ROUNDS, w)
    net_burst = net
    if faults:
        # fault mix during the burst (BASELINE full-mix shape): 1% of
        # non-origin nodes die and the cluster splits into two halves;
        # the quiet phase heals + revives, and convergence is measured
        # from the heal
        killed = (jr.uniform(k4, (n,)) < 0.01) & (
            jnp.arange(n) >= cfg.n_origins
        )
        kill = jnp.zeros((BURST_ROUNDS, n), bool).at[1].set(killed)
        burst = burst._replace(kill=kill)
        net_burst = net._replace(
            partition=(jnp.arange(n, dtype=jnp.int32) % 2)
        )

    quiet_chunk = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (CHUNK,) + a.shape), quiet
    )
    if faults:
        revive = jnp.zeros((CHUNK, n), bool).at[0].set(killed)
        first_chunk = quiet_chunk._replace(revive=revive)
    else:
        first_chunk = quiet_chunk

    st, _ = scale_run_rounds(cfg, st, net_burst, key, burst)
    rounds = BURST_ROUNDS
    t0 = time.perf_counter()
    timed_rounds = 0
    chunk_inp = first_chunk
    while rounds < MAX_ROUNDS:
        st, _ = scale_run_rounds(
            cfg, st, net, jr.fold_in(key, rounds), chunk_inp
        )
        chunk_inp = quiet_chunk
        jax.block_until_ready(st)
        rounds += CHUNK
        timed_rounds += CHUNK
        m = scale_crdt_metrics(cfg, st)
        if bool(m["converged"]):
            break
    dt = time.perf_counter() - t0
    m = scale_crdt_metrics(cfg, st)
    return {
        "n": n,
        "n_origins": cfg.n_origins,
        "faults": bool(faults),
        "tx_max_cells": cfg.tx_max_cells,
        "rounds_to_convergence": rounds,
        "converged": bool(m["converged"]),
        "org_aligned_frac": round(float(m["org_aligned_frac"]), 4),
        "ms_per_round": round(dt * 1000 / max(1, timed_rounds), 3),
        "platform": jax.devices()[0].platform,
    }


def main():
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    out_path, tx_cells = None, 1
    for a in sys.argv[1:]:
        if a.startswith("--out="):
            out_path = a.split("=", 1)[1]
        if a.startswith("--tx="):
            tx_cells = int(a.split("=", 1)[1])
    sizes = [int(a) for a in args] or [256, 1024, 4096]
    records = []
    for n in sizes:
        rec = run_one(n, tx_cells=tx_cells)
        # one process compiles several whole-cluster programs; without
        # dropping the in-memory executables between sizes the next
        # LLVM compile can die with "Cannot allocate memory" (observed
        # at the 4096 compile after 256+1024)
        jax.clear_caches()
        records.append(rec)
        print(json.dumps(rec), flush=True)
        if out_path:  # flush after every size — tunnel runs die mid-way
            with open(out_path, "w") as f:
                json.dump(records, f, indent=1)


if __name__ == "__main__":
    main()
