"""Quantify the origin-slot collision regime (VERDICT r4 next #9).

With the unbounded writer set, per-actor bookkeeping rides a
hash-slotted ``[N, n_origins]`` table. When ACTIVE writers outnumber
slots, different nodes may track different actor subsets — head
comparison is skipped on misaligned slots (``scale_crdt_metrics``), the
full-store sweep (``sync_sweep_every``) still converges the data, and
quiescence realigns the books. This probe measures, for writers ≫
slots:

- ``org_aligned_frac`` over time under sustained churn (how misaligned
  the books run in steady state),
- rounds until STORE convergence after the churn stops (the
  user-visible guarantee), and
- rounds until ``org_aligned_frac`` returns to 1.0 (bookkeeping
  realignment), against the sweep cadence.

Usage: python scripts/collision_probe.py [n] [writers] [churn_rounds]
       (defaults 4096 64 64; slots = 16, i.e. writers = 4x slots)
Writes one JSON line per phase + a summary to stdout and, with
``--out=PATH``, the record list to PATH.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from corrosion_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()

import jax.numpy as jnp  # noqa: E402
import jax.random as jr  # noqa: E402

from corrosion_tpu.sim.scale_step import (  # noqa: E402
    ScaleRoundInput,
    ScaleSimState,
    make_write_inputs,
    scale_crdt_metrics,
    scale_run_rounds,
    scale_sim_config,
)
from corrosion_tpu.sim.transport import NetModel  # noqa: E402

CHUNK = 8
# long enough to capture the store-convergence epidemic tail (measured
# at 1024/64w: divergence pinned until ~round 340, zero by ~472)
MAX_QUIET = int(os.environ.get("COLL_MAX_QUIET", "1536"))


def main() -> None:
    args = [a for a in sys.argv[1:] if not a.startswith("--")]
    out_path = None
    for a in sys.argv[1:]:
        if a.startswith("--out="):
            out_path = a.split("=", 1)[1]
    n = int(args[0]) if len(args) > 0 else 4096
    writers = int(args[1]) if len(args) > 1 else 64
    churn_rounds = int(args[2]) if len(args) > 2 else 64
    slots = int(os.environ.get("COLL_SLOTS", "16"))

    overrides = {}
    if os.environ.get("COLL_SWEEP"):
        # sweep-cadence arm: the full-store sweep is the store-epidemic
        # engine; its cadence bounds store convergence latency
        overrides["sync_sweep_every"] = int(os.environ["COLL_SWEEP"])
    cfg = scale_sim_config(n, n_origins=slots, **overrides)
    if not cfg.any_writer:
        raise ValueError(
            "collision probe needs the unbounded writer set "
            "(cfg.any_writer)"
        )
    net = NetModel.create(n, drop_prob=0.01)
    st = ScaleSimState.create(cfg)
    key = jr.key(0)
    records = []
    # ONE jitted runner reused by both phases (identical input shapes):
    # a second whole-cluster compile OOMs the 1-core host's LLVM
    import functools

    run = jax.jit(functools.partial(scale_run_rounds, cfg))

    def emit(rec):
        records.append(rec)
        print(json.dumps(rec), flush=True)
        if out_path:  # flush after every phase — a later-phase death
            with open(out_path, "w") as f:  # must not lose the artifact
                json.dump(records, f, indent=1)

    # writers spread across the WHOLE id space, 4x the slot table
    k_w, k_m, k_in = jr.split(jr.key(1), 3)
    writer_ids = jr.choice(k_w, n, (min(writers, n),), replace=False)
    is_writer = jnp.zeros(n, bool).at[writer_ids].set(True)

    # --- phase 1: sustained churn, writers >> slots ----------------------
    aligned_trace = []
    rounds = 0
    t0 = time.perf_counter()
    while rounds < churn_rounds:
        w = (jr.uniform(jr.fold_in(k_m, rounds), (CHUNK, n)) < 0.25) \
            & is_writer[None, :]
        inputs = make_write_inputs(cfg, jr.fold_in(k_in, rounds), CHUNK, w)
        st, _ = run(st, net, jr.fold_in(key, rounds), inputs)
        jax.block_until_ready(st)
        rounds += CHUNK
        m = scale_crdt_metrics(cfg, st)
        aligned_trace.append(round(float(m["org_aligned_frac"]), 4))
    emit({
        "phase": "churn",
        "n": n, "slots": slots, "writers": writers,
        "rounds": rounds,
        "org_aligned_frac_trace": aligned_trace,
        "steady_aligned_frac": aligned_trace[-1],
        "ms_per_round": round(
            (time.perf_counter() - t0) * 1000 / rounds, 3),
        "platform": jax.devices()[0].platform,
    })

    # --- phase 2: quiescence — store convergence, then book realignment --
    # (same jitted runner, same input shapes: no second compile)
    quiet = ScaleRoundInput.quiet(cfg)
    quiet_chunk = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (CHUNK,) + a.shape), quiet
    )
    store_conv_at = None
    full_conv_at = None
    needs_trace = []
    store_div_trace = []
    q = 0
    while q < MAX_QUIET:
        st, _ = run(st, net, jr.fold_in(key, 10_000 + q), quiet_chunk)
        jax.block_until_ready(st)
        q += CHUNK
        m = scale_crdt_metrics(cfg, st)
        needs_trace.append(int(m["total_needs"]))
        store_div_trace.append(int(m["n_store_diverged"]))
        if store_conv_at is None and bool(m["store_converged"]):
            store_conv_at = q
        if full_conv_at is None and bool(m["converged"]):
            full_conv_at = q
        if store_conv_at is not None and full_conv_at is not None:
            break
    sweep_period = max(1, cfg.sync_interval) * max(1, cfg.sync_sweep_every)
    m = scale_crdt_metrics(cfg, st)
    emit({
        "phase": "quiescence",
        # the user-visible guarantee: identical replicas everywhere
        "rounds_to_store_convergence": store_conv_at,
        # full bookkeeping quiescence (heads + needs): with writers >>
        # slots this NEVER happens — slot re-claims reset heads, needs
        # re-open, and the churn is self-sustaining (needs_trace shows
        # the oscillation); operators must size n_origins >= active
        # writers if they need bookkeeping to quiesce
        "rounds_to_full_convergence": full_conv_at,
        "final_org_aligned_frac": round(float(m["org_aligned_frac"]), 4),
        "final_total_needs": int(m["total_needs"]),
        "needs_trace_per_chunk": needs_trace[::8],
        # the store epidemic: diverged-replica count per 8th chunk
        "store_div_trace_per_chunk": store_div_trace[::8],
        "sweep_period_rounds": sweep_period,
        "store_converged": store_conv_at is not None,
    })

if __name__ == "__main__":
    main()
