#!/usr/bin/env bash
# The local CI gate: corrolint static analysis + tier-1 tests.
#
#   scripts/check.sh            # lint + tier-1
#   scripts/check.sh --lint     # lint only (fast, no jax compile)
#
# Lint scope since corrolint v2: the package PLUS bench.py and
# scripts/ — everything that drives the hot entry points. Findings are
# also published machine-readably (rule counts + per-finding records)
# to artifacts/lint_r06.json for trend tracking across PRs.
#
# The same analyzer also rides tier-1 itself
# (tests/test_analysis.py::test_repo_is_clean), so running the pytest
# command alone still enforces the lint gate; this script just fails
# faster and prints findings directly.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== corrolint =="
python -m corrosion_tpu.analysis corrosion_tpu bench.py scripts \
    --output-json artifacts/lint_r06.json
echo "corrolint: clean (report: artifacts/lint_r06.json)"

if [[ "${1:-}" == "--lint" ]]; then
    exit 0
fi

echo "== tier-1 tests =="
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly
