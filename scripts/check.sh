#!/usr/bin/env bash
# The local CI gate: corrolint static analysis + corrosan runtime
# sanitizer + tier-1 tests.
#
#   scripts/check.sh            # lint + corrosan + tier-1
#   scripts/check.sh --lint     # lint only (fast, no jax compile)
#   scripts/check.sh --san      # lint + corrosan (skip plain tier-1)
#
# Lint scope since corrolint v2: the package PLUS bench.py and
# scripts/ — everything that drives the hot entry points. Findings are
# published machine-readably to artifacts/lint_r06.json.
#
# The sharded-checkpoint probe (ISSUE 9) publishes
# artifacts/ckpt_r09.json: per-shard drain stall vs overlapped IO vs
# shard count, plus the 8->4 resharded-restore bitwise check.
#
# corrosan (ISSUE 8) publishes artifacts/san_r08.json with two
# sections: "fixtures" (seeded-race replay verdicts via
# `corrosion-tpu san`) and "pytest" (the threaded test modules re-run
# under CORROSAN=1: witnessed lock edges diffed against corrolint's
# static graph, race/leak findings — the run FAILS on any unsuppressed
# finding).
#
# The same analyzers also ride tier-1 itself
# (tests/test_analysis.py::test_repo_is_clean, tests/test_corrosan.py),
# so running the pytest command alone still enforces both gates; this
# script fails faster, prints findings directly, and exercises the
# full sanitized module sweep.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== corrolint =="
python -m corrosion_tpu.analysis corrosion_tpu bench.py scripts \
    --output-json artifacts/lint_r06.json
echo "corrolint: clean (report: artifacts/lint_r06.json)"

if [[ "${1:-}" == "--lint" ]]; then
    exit 0
fi

echo "== corrosan: seeded-fixture replay =="
env JAX_PLATFORMS=cpu python -m corrosion_tpu.analysis.sanitizer \
    --output-json artifacts/san_r08.json

echo "== corrosan: sanitized threaded-module sweep =="
env CORROSAN=1 CORROSAN_REPORT=artifacts/san_r08.json JAX_PLATFORMS=cpu \
    python -m pytest \
    tests/test_pubsub_incremental.py tests/test_resilience.py \
    tests/test_agent.py tests/test_http_api.py tests/test_pg.py \
    tests/test_maintenance.py \
    -q -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
echo "corrosan: clean (report: artifacts/san_r08.json)"

if [[ "${1:-}" == "--san" ]]; then
    exit 0
fi

echo "== sharded checkpoint probe =="
# per-shard drain + elastic 8->4 resharded restore, published next to
# the lint/san artifacts (stall vs overlapped IO vs shard count)
python scripts/ckpt_probe.py --output artifacts/ckpt_r09.json
echo "ckpt probe: ok (report: artifacts/ckpt_r09.json)"

echo "== tier-1 tests =="
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly
