#!/usr/bin/env bash
# The local CI gate: corrolint static analysis + corrosan runtime
# sanitizer + tier-1 tests.
#
#   scripts/check.sh            # lint + corrosan + tier-1
#   scripts/check.sh --lint     # lint only (fast, no jax compile)
#   scripts/check.sh --san      # lint + corrosan (skip plain tier-1)
#
# Lint scope since corrolint v2: the package PLUS bench.py and
# scripts/ — everything that drives the hot entry points. Findings are
# published machine-readably to artifacts/lint_r06.json.
#
# The sharded-checkpoint probe (ISSUE 9) publishes
# artifacts/ckpt_r09.json: per-shard drain stall vs overlapped IO vs
# shard count, plus the 8->4 resharded-restore bitwise check.
#
# The observability smoke (ISSUE 11) publishes artifacts/obs_r11.json:
# flight-record replay consistency, live mid-soak /metrics advance,
# the quiet-trace activity oracle, and the memory-audit closure —
# under CORROSAN=1.
#
# The corroserve load harness (ISSUE 16) publishes
# artifacts/serve_r16.json: seeded concurrent HTTP/subscription/PG-wire
# clients vs the server's own request accounting (the agreement gate),
# under CORROSAN=1.
#
# The corroguard overload bench (ISSUE 17) publishes
# artifacts/serve_r17.json: the two-arm degradation-contract record —
# the guarded plane must hold the lag bound under the ramp AND the
# unguarded plane must demonstrably violate it — under CORROSAN=1.
#
# corrosan (ISSUE 8) publishes artifacts/san_r08.json with two
# sections: "fixtures" (seeded-race replay verdicts via
# `corrosion-tpu san`) and "pytest" (the threaded test modules re-run
# under CORROSAN=1: witnessed lock edges diffed against corrolint's
# static graph, race/leak findings — the run FAILS on any unsuppressed
# finding).
#
# The same analyzers also ride tier-1 itself
# (tests/test_analysis.py::test_repo_is_clean, tests/test_corrosan.py),
# so running the pytest command alone still enforces both gates; this
# script fails faster, prints findings directly, and exercises the
# full sanitized module sweep.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== corrolint =="
python -m corrosion_tpu.analysis corrosion_tpu bench.py scripts \
    --output-json artifacts/lint_r06.json
# the fused path's files must be IN lint scope (ISSUE 10), and since
# ISSUE 13 so must the corrochaos engine + fault compiler: lint them
# explicitly (missing paths exit 2) and require the focused report to
# have actually walked all of them — an accidental walk/scope
# regression would otherwise silently stop checking the kernel
# boundaries the dtype-flow/donation rules exist for (or the chaos
# engine's lock/assert discipline). The walk must also close over the
# state-constructor files (scale/broadcast/versions/partials): the
# PR-11 mem-budget checker prices the WALKED tree, and a scoped walk
# that cannot see the constructors reports the budget dark (this gate
# was silently red between PR 11 and ISSUE 13 for exactly that reason)
python -m corrosion_tpu.analysis \
    corrosion_tpu/ops/megakernel.py corrosion_tpu/sim/scale_step.py \
    corrosion_tpu/parallel/mesh.py corrosion_tpu/resilience/segments.py \
    corrosion_tpu/resilience/chaos.py corrosion_tpu/sim/scenario.py \
    corrosion_tpu/sim/scale.py corrosion_tpu/sim/broadcast.py \
    corrosion_tpu/ops/versions.py corrosion_tpu/ops/partials.py \
    corrosion_tpu/resilience/fuzz.py \
    corrosion_tpu/analysis/collectives.py corrosion_tpu/analysis/cost.py \
    --output-json /tmp/lint_fused_scope.json
python - <<'PY'
import json
scoped = json.load(open("/tmp/lint_fused_scope.json"))
if scoped["files_checked"] != 13 or not scoped["clean"]:
    raise SystemExit(f"fused/chaos-path lint scope regressed: {scoped}")
full = json.load(open("artifacts/lint_r06.json"))
assert "rule_counts" in full, "lint report lost rule_counts"
if full["files_checked"] < scoped["files_checked"]:
    raise SystemExit("repo lint walk smaller than the fused/chaos file set")
print(f"corrolint scope: fused + chaos files covered "
      f"({full['files_checked']} files in the repo walk)")
PY
echo "corrolint: clean (report: artifacts/lint_r06.json)"

echo "== corrobudget: 1M HBM budget gate =="
# the ISSUE 12 memory-budget audit (docs/memory-budget.md): static
# inventory + projections at N in {100k, 300k, 1M}, the static==runtime
# cross-check at a real small-N point, the declared per-class budget at
# the 1M point, and the mem-budget/densify rule counts — published as
# artifacts/membudget_r12.json (written even on failure)
env JAX_PLATFORMS=cpu python scripts/membudget_probe.py \
    --output artifacts/membudget_r12.json
python - <<'PY'
import json
rec = json.load(open("artifacts/membudget_r12.json"))
if not rec.get("ok"):
    raise SystemExit(f"membudget gate failed: {rec.get('problems')}")
if not rec.get("budget_ok") or not rec.get("cross_check_ok"):
    raise SystemExit(f"membudget gate inconsistent: {rec}")
proj = rec["projections"]["1000000"]
print("membudget: 1M projection",
      f"{proj['total_bytes'] / 1e9:.3f} GB",
      f"({len(rec['inventory'])} leaves,",
      f"int8 arm saves {rec['projection_1m_narrow_int8']['saved_bytes_vs_default'] / 1e6:.0f} MB)")
PY
echo "corrobudget: under budget (report: artifacts/membudget_r12.json)"

if [[ "${1:-}" == "--lint" ]]; then
    exit 0
fi

echo "== corrocost: cost & collective audit =="
# the ISSUE 20 jaxpr/HLO pricing gate (docs/corrolint.md "corrocost",
# PERF.md "Static roofline"): exact per-round cost fits for every hot
# entry point (degrees gated against the corrobudget inventory), the 1M
# roofline cross-checked against a direct 1M abstract trace, the XLA
# cost_analysis band, and the GSPMD collective manifests of BOTH
# registered sharded entries across the full 16-combo knob matrix on
# flat and 2-D meshes — pinned bit for bit, with the smuggled-gather
# mutation fixture required to FAIL the gate. Published as
# artifacts/cost_r20.json (written even on failure). Compiles the
# matrix: cold ~10 min, compile-cache-warm reruns are cheap.
env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/cost_probe.py --output artifacts/cost_r20.json
python - <<'PY'
import json
rec = json.load(open("artifacts/cost_r20.json"))
if not rec.get("ok"):
    raise SystemExit(f"corrocost gate failed: {rec.get('problems')}")
if not rec.get("mutation_gate_fired"):
    raise SystemExit("smuggled-gather mutation fixture did not fire")
roof = rec["roofline"]["entries"]["sharded_scale_run"]
for metric in ("flops", "hbm_bytes"):
    if not roof[f"{metric}_fit_exact"] or not roof[f"{metric}_direct_1m_matches"]:
        raise SystemExit(f"1M {metric} roofline not exact: {roof}")
audited = set(rec["collective_audit"])
if audited != {"sharded_scale_run", "sharded_scale_run_carry"}:
    raise SystemExit(f"collective audit lost an entry: {audited}")
for entry, arec in rec["collective_audit"].items():
    if len(arec["labels"]) != 16:
        raise SystemExit(f"{entry}: knob matrix incomplete: "
                         f"{sorted(arec['labels'])}")
print(f"corrocost: {roof['flops_per_round'] / 1e9:.1f} Gflop/round and "
      f"{rec['collective_fit']['projected_1m_bytes'] / 1e6:.1f} MB "
      f"cross-shard/round at 1M; 32 manifests pinned, mutation fired")
PY
echo "corrocost: ok (report: artifacts/cost_r20.json)"

echo "== corrosan: seeded-fixture replay =="
env JAX_PLATFORMS=cpu python -m corrosion_tpu.analysis.sanitizer \
    --output-json artifacts/san_r08.json

echo "== corrosan: sanitized threaded-module sweep =="
env CORROSAN=1 CORROSAN_REPORT=artifacts/san_r08.json JAX_PLATFORMS=cpu \
    python -m pytest \
    tests/test_pubsub_incremental.py tests/test_resilience.py \
    tests/test_agent.py tests/test_http_api.py tests/test_pg.py \
    tests/test_maintenance.py \
    -q -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly
echo "corrosan: clean (report: artifacts/san_r08.json)"

if [[ "${1:-}" == "--san" ]]; then
    exit 0
fi

echo "== fused-interpret pipeline smoke =="
# the fused megakernel path through the WHOLE pipeline on CPU
# (ISSUE 10, docs/fused.md): BENCH_SMOKE with the pallas kernels in
# interpret mode — gated on fused==unfused parity, donated segments,
# and the per-shard checkpoint-drain telemetry, published as
# artifacts/fused_r10.json
# 8 virtual devices so the soak leg shards and the record proves the
# per-shard drain under the fused path (matches the tier-1 harness)
env BENCH_SMOKE=1 BENCH_FUSED=interpret JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python bench.py | tail -n 1 > artifacts/fused_r10.json
python - <<'PY'
import json
rec = json.load(open("artifacts/fused_r10.json"))
problems = rec.get("problems", [])
if not rec.get("ok"):
    raise SystemExit(f"fused smoke not ok: {problems}")
if rec.get("fused_mode") != "interpret" or not rec.get("pallas_fused"):
    raise SystemExit("fused smoke did not ride the fused path: "
                     f"{rec.get('fused_mode')}/{rec.get('pallas_fused')}")
if rec.get("fused_parity") is not True:
    raise SystemExit("fused==unfused parity not verified on the smoke")
soak = rec["soak"]
if soak.get("donated_segments", 0) < 1 or not soak.get("pallas_fused"):
    raise SystemExit(f"fused soak leg lost donation or the kernels: {soak}")
if soak.get("ckpt_shards", 0) < 1 or soak.get("ckpt_drain_bytes", 0) <= 0:
    raise SystemExit(f"fused soak leg lost shard-drain telemetry: {soak}")
print("fused smoke:", rec["metric"], rec["value"], rec["unit"],
      f"(parity ok, {soak['ckpt_shards']} ckpt shard(s))")
PY
echo "fused smoke: ok (report: artifacts/fused_r10.json)"

echo "== observability smoke =="
# the flight-recorder plane (ISSUE 11): small segmented soak with the
# recorder + live /metrics listener on, mid-soak scrape asserted
# advancing, flight replay matched against the run's stats, the
# quiet-trace activity oracle, and the memory-audit closure — all
# inside a corrosan sanitized window (the obs threads must come and go
# without a race/leak finding). Published as artifacts/obs_r11.json.
env CORROSAN=1 JAX_PLATFORMS=cpu \
    python scripts/obs_probe.py --output artifacts/obs_r11.json > /dev/null
python - <<'PY'
import json
rec = json.load(open("artifacts/obs_r11.json"))
if not rec.get("ok"):
    raise SystemExit(f"obs smoke not ok: {rec.get('problems')}")
if not rec.get("corrosan"):
    raise SystemExit("obs smoke did not run under the sanitizer")
if len(rec["scrape"]["distinct_mid_run"]) < 2:
    raise SystemExit(f"mid-soak scrape not advancing: {rec['scrape']}")
print("obs smoke:", rec["flight"]["segments"], "segment(s) replayed,",
      len(rec["scrape"]["distinct_mid_run"]), "distinct mid-run scrapes,",
      rec["hbm_bytes"], "hbm bytes")
PY
echo "obs smoke: ok (report: artifacts/obs_r11.json)"

echo "== corroserve load harness =="
# the ISSUE 16 serving-plane gate (docs/observability.md, "Serving
# plane"): seeded concurrent clients — HTTP writers + NDJSON
# subscribers + PG-wire readers — against an in-process devcluster,
# under CORROSAN=1. The record's agreement section is the oracle:
# server-side request histograms must count EXACTLY the requests the
# clients tallied. Published as artifacts/serve_r16.json
# (BENCH_SERVE_r16.json at the repo root is the committed lineage
# record from the same harness).
env CORROSAN=1 JAX_PLATFORMS=cpu \
    python -m corrosion_tpu load \
    --writers 3 --subscribers 2 --pg-readers 2 \
    --write-ops 8 --pg-ops 8 --keys 8 --seed 16 \
    --output-json artifacts/serve_r16.json > /dev/null
python - <<'PY'
import json
rec = json.load(open("artifacts/serve_r16.json"))
if not rec.get("ok"):
    raise SystemExit(f"serve harness not ok: {rec.get('problems')}")
if not rec.get("corrosan"):
    raise SystemExit("serve harness did not run under the sanitizer")
agr = rec["agreement"]
if not (agr["ok"] and agr["transactions"]["ok"] and agr["pg_select"]["ok"]):
    raise SystemExit(f"server/client request counts disagree: {agr}")
for op in ("write", "pg_query", "subscribe_delivery"):
    stats = rec["ops"][op]
    if stats["count"] <= 0 or not (0.0 <= stats["p50"] <= stats["p99"]):
        raise SystemExit(f"serve harness op {op} malformed: {stats}")
print(f"serve harness: {agr['transactions']['server']} tx, "
      f"{agr['pg_select']['server']} pg selects, "
      f"{rec['server']['deliveries']} deliveries agree "
      f"(write p99 {rec['ops']['write']['p99'] * 1e3:.1f} ms, "
      f"delivery p99 {rec['ops']['subscribe_delivery']['p99'] * 1e3:.1f} ms)")
PY
echo "serve harness: ok (report: artifacts/serve_r16.json)"

echo "== corroguard overload bench =="
# the ISSUE 17 degradation-contract gate (docs/overload.md): the same
# serving plane driven past its breaking point, twice — guarded
# (admission control + bounded shed-oldest fanout) and unguarded —
# under CORROSAN=1. The two-arm record is the oracle: the guard must
# HOLD the contract (bounded p99 delivery lag, monotone shed counters,
# Retry-After-honoring closed-loop client fully absorbed, zero leaked
# serving threads) while the identical ramp without the guard must
# VIOLATE the lag bound — a bound loose enough for the naked plane
# would gate nothing. Published as artifacts/serve_r17.json;
# BENCH_SERVE_r17.json at the repo root is the committed lineage record
# from the same bench.
env CORROSAN=1 JAX_PLATFORMS=cpu \
    python -m corrosion_tpu load --overload --seed 17 \
    --output-json artifacts/serve_r17.json > /dev/null
python - <<'PY'
import json
rec = json.load(open("artifacts/serve_r17.json"))
if not rec.get("ok"):
    raise SystemExit(f"overload bench not ok: {rec}")
if not rec.get("corrosan"):
    raise SystemExit("overload bench did not run under the sanitizer")
if not rec["contract_holds_guarded"]:
    raise SystemExit("guard failed its own degradation contract: "
                     f"{rec['guarded']['contract']}")
if not rec["contract_violated_unguarded"]:
    raise SystemExit("unguarded arm met the lag bound — the bench "
                     f"gates nothing: {rec['unguarded']['contract']}")
g = rec["guarded"]
for arm in (g, rec["unguarded"]):
    if arm["leaked_threads"]:
        raise SystemExit(f"serving threads leaked: {arm['leaked_threads']}")
if not g["agreement"]["ok"]:
    raise SystemExit(f"server/client counts disagree under overload: "
                     f"{g['agreement']}")
print(f"overload bench: guard held (p99 lag "
      f"{g['contract']['delivery_p99_s'] * 1e3:.0f} ms <= "
      f"{g['contract']['lag_bound_s'] * 1e3:.0f} ms, pressure "
      f"{g['contract']['pressure_final']:.0f}, closed-loop "
      f"{g['closed_loop']['done']}/{g['closed_loop']['ops']} absorbed); "
      f"unguarded violated (p99 "
      f"{rec['unguarded']['contract']['delivery_p99_s'] * 1e3:.0f} ms)")
PY
echo "overload bench: ok (report: artifacts/serve_r17.json)"

echo "== corrochaos fault-scenario sweep =="
# the ISSUE 13 robustness gate (docs/chaos.md): every shipped seeded
# fault scenario — partition-heal, clock-skew past the HLC drift gate,
# rejoin refutation, mid-segment preemption (both crash windows),
# checkpoint corruption, elastic 8->4 remesh, fused<->unfused flip,
# plus the r18 composed scenarios (corrupt-remesh, skew-partition,
# preempt-storm) — through the REAL segmented pipeline under
# CORROSAN=1, triple-oracle-checked (convergence + no checkpoint
# restores diverged state + the healed cluster quiesces).
# Publishes per-scenario verdicts to artifacts/chaos_r13.json and the
# rounds-to-convergence lineage record to CONVERGENCE_r13_cpu.json
# (superseding the seed-era one-scenario artifact).
env CORROSAN=1 JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m corrosion_tpu chaos \
    --output-json artifacts/chaos_r13.json \
    --convergence-json artifacts/CONVERGENCE_r13_cpu.json > /dev/null
python - <<'PY'
import json
rec = json.load(open("artifacts/chaos_r13.json"))
if not rec.get("ok"):
    bad = [r for r in rec["scenarios"] if not r.get("ok")]
    raise SystemExit(f"chaos sweep failed: {bad or rec.get('problems')}")
if not rec.get("corrosan"):
    raise SystemExit("chaos sweep did not run under the sanitizer")
scen = rec["scenarios"]
if len(scen) < 6 or any(r.get("skipped") for r in scen):
    raise SystemExit(f"chaos sweep incomplete: {scen}")
names = {r["name"] for r in scen}
composed = {"corrupt-remesh", "skew-partition", "preempt-storm"}
if not composed <= names:
    raise SystemExit(f"composed scenarios missing: {composed - names}")
if not all(r.get("quiesced") for r in scen):
    bad = [r["name"] for r in scen if not r.get("quiesced")]
    raise SystemExit(f"third oracle (quiescence) failed: {bad}")
validated = sum(r["checkpoints_validated"] for r in scen)
faults = sum(r["faults_injected"] for r in scen)
print(f"chaos sweep: {len(scen)} scenarios ok (all quiesced), "
      f"{validated} checkpoints validated, {faults} host-plane faults "
      f"injected")
PY
echo "chaos sweep: ok (report: artifacts/chaos_r13.json)"

echo "== corrofuzz generative sweep =="
# the ISSUE 18 robustness gate (docs/chaos.md "Generative fuzzing"):
# a fixed-seed budget of generated multi-fault scenarios — seeded
# random-but-valid scripts over the whole fault grammar, N drawn from
# the corrobudget-priced fast ladder — each judged by all three
# oracles under the same CORROSAN window. A failing seed is a real
# finding: shrink it (corrosion-tpu fuzz --shrink-failures) and commit
# the reproducer to tests/chaos_corpus/.
env CORROSAN=1 JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m corrosion_tpu fuzz --seeds 0:24 \
    --output-json artifacts/fuzz_r18.json > /dev/null
python - <<'PY'
import json
rec = json.load(open("artifacts/fuzz_r18.json"))
if not rec.get("ok"):
    bad = [c for c in rec["cases"] if not c.get("ok")]
    raise SystemExit(f"corrofuzz sweep failed: {bad}")
if not rec.get("corrosan"):
    raise SystemExit("corrofuzz sweep did not run under the sanitizer")
if len(rec["cases"]) < 25 or any(c.get("skipped") for c in rec["cases"]):
    raise SystemExit(f"corrofuzz budget incomplete: {rec['cases']}")
kinds = {k for c in rec["cases"] for k in c["injections"]}
slow = [r for r in rec["ladder"] if r["slow"]]
print(f"corrofuzz: {len(rec['cases'])} generated scenarios ok "
      f"({sorted(kinds)} exercised; ladder to "
      f"{rec['ladder'][-1]['n_nodes']} nodes, {len(slow)} slow rungs)")
PY
echo "corrofuzz: ok (report: artifacts/fuzz_r18.json)"

echo "== sharded checkpoint probe =="
# per-shard drain + elastic 8->4 resharded restore, published next to
# the lint/san artifacts (stall vs overlapped IO vs shard count)
python scripts/ckpt_probe.py --output artifacts/ckpt_r09.json
echo "ckpt probe: ok (report: artifacts/ckpt_r09.json)"

echo "== corroquiet parity gate =="
# the ISSUE 19 quiescence gate (PERF.md "Quiescence"): every registry
# chaos scenario run under BOTH round variants — quiet="on" and
# quiet="off" — must pass all three oracles AND land on the identical
# fixpoint state digest (masked == dense through kills, skew,
# corruption, remesh, and mid-lineage quiet flips), plus the
# steady-state speedup smoke (active-set rounds >= 3x dense on a
# settled trace, bitwise equal). Under CORROSAN=1; published as
# artifacts/quiet_r19.json.
env CORROSAN=1 JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python scripts/quiet_probe.py --output artifacts/quiet_r19.json
python - <<'PY'
import json
rec = json.load(open("artifacts/quiet_r19.json"))
if not rec.get("ok"):
    raise SystemExit(f"quiet parity gate failed: {rec.get('problems')}")
if not rec.get("corrosan"):
    raise SystemExit("quiet parity gate did not run under the sanitizer")
scen = [r for r in rec["scenarios"] if not r.get("skipped")]
if len(scen) < 6 or not all(r.get("digest_match") for r in scen):
    raise SystemExit(f"quiet parity sweep incomplete: {rec['scenarios']}")
smoke = rec["speedup_smoke"]
print(f"quiet parity: {len(scen)} scenarios masked==dense, "
      f"speedup {smoke['speedup']}x "
      f"({smoke['cheap_rounds']}/{smoke['rounds']} rounds cheap)")
PY
echo "quiet parity: ok (report: artifacts/quiet_r19.json)"

echo "== tier-1 tests =="
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly
