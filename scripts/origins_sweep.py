"""Writer-set cost curve (VERDICT r2 #5): run the flagship round at a
sweep of origin-pool sizes and print one JSON line per configuration —
the measured cost of unbounding the writer set from 16 toward
"any node may write" (the reference books versions per observed actor,
``crates/corro-types/src/agent.rs:1270-1604``).

Round 4: with the unbounded writer set the sweep spreads the ACTIVE
writers across the whole id space (``BENCH_WRITERS``) while the
bookkeeping slot table stays at its flagship size — the regime the
hash-slotted origin table exists for.

Usage: python scripts/origins_sweep.py [n_nodes] [writers ...]
       (defaults: 100000, sweep 16 64 256)
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    args = sys.argv[1:]
    n = int(args[0]) if args else 100_000
    sweep = [int(a) for a in args[1:]] or [16, 64, 256]
    for o in sweep:
        env = dict(os.environ)
        env.update(
            BENCH_WORKER="1",
            BENCH_NODES=str(n),
            # slot table FIXED at the flagship default (16) across the
            # whole sweep so the measured curve isolates the active-
            # writer axis; o writers drawn from the whole id space
            BENCH_WRITERS=str(o),
        )
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            capture_output=True, text=True, timeout=2400, env=env,
        )
        line = next(
            (ln for ln in reversed(proc.stdout.strip().splitlines())
             if ln.startswith("{")),
            json.dumps({"error": proc.stderr.strip()[-300:], "origins": o}),
        )
        print(line, flush=True)


if __name__ == "__main__":
    main()
