#!/usr/bin/env bash
# Pre-warm the persistent XLA compile cache (.jax_cache) before a timed
# tier-1 run or bench capture (ISSUE 4 CI/tooling satellite).
#
# The smoke bench compiles the exact flagship shapes the throughput
# pipeline dispatches — the donated scale scan and the segmented soak's
# (segment length, donation) program pair — so one run here makes every
# subsequent timed run dispatch-only. tests/conftest.py exports the same
# JAX_COMPILATION_CACHE_DIR to its subprocesses, so the suite and this
# script share one cache.
set -euo pipefail
cd "$(dirname "$0")/.."

export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/.jax_cache}"

BENCH_SMOKE=1 python bench.py > /dev/null
# WARM_FLAGSHIP=1 additionally makes the pytest session pre-compile the
# flagship round at the shared test shape (tests/conftest.py fixture)
echo "warm: $JAX_COMPILATION_CACHE_DIR"
