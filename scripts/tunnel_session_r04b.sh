#!/bin/bash
# Round-4b TPU session: pallas-proof with the FIXED kernels first, then
# interleaved A/B, then phase profiles, convergence, origins sweep.
cd /root/repo
mkdir -p artifacts
T=artifacts/tunnel_$(date +%m%d_%H%M)
echo "== pallas probe (fixed kernels: np scalars, iota masks, no int argmin)"
timeout 2400 python scripts/pallas_probe.py 2>&1 | tee $T.pallas2.log
echo "== mosaic op-pattern probe"
timeout 1200 python scripts/mosaic_op_probe.py 2>&1 | tee $T.opprobe.log
echo "== interleaved A/B bench (default / pig16 / pull10 / narrow?)"
timeout 3600 python scripts/ab_bench.py 100000 10 2>&1 | tee $T.ab.log
echo "== bench (headline; seeds bench_last.json write-first record)"
BENCH_WORKER=1 timeout 2400 python bench.py 2>&1 | tee $T.bench2.log
echo "== scale (phase profile)"
timeout 2400 python scripts/profile_scale.py 100000 8 2>&1 | tee $T.scale2.log
echo "== bcast (sub-phase profile)"
timeout 2400 python scripts/profile_bcast.py 100000 8 2>&1 | tee $T.bcast2.log
echo "== convergence (tracked metric at 100k, kill+partition mix)"
timeout 4000 python scripts/convergence_bench.py 100000 --out=artifacts/CONVERGENCE_r04_tpu.json 2>&1 | tee $T.conv2.log
echo "== origins sweep"
timeout 5000 python scripts/origins_sweep.py 100000 64 256 2>&1 | tee $T.origins2.log
echo "== session r04b done"
