#!/bin/bash
# Round-4 tunnel watcher: probe the axon tunnel every ~8 min in the
# background; on FIRST success fire the full measurement session
# (scripts/tunnel_session.sh), then exit. The tunnel has been observed
# down for entire 12 h rounds (round 3) and hanging >9 min in backend
# init, so probes run with generous timeouts and never block the
# foreground build.
cd /root/repo
LOG=/root/repo/artifacts/tpu_watch_r04.log
echo "== watcher start $(date +%F_%T)" >> "$LOG"
while true; do
  echo "-- probe $(date +%T)" >> "$LOG"
  OUT=$(BENCH_PROBE=1 timeout 480 python bench.py 2>>"$LOG")
  echo "$OUT" >> "$LOG"
  # exit 0 alone is not "tunnel alive": jax can silently fall back to
  # its CPU backend — require a real non-cpu platform in the probe line
  if echo "$OUT" | grep -q '"platform":' && \
     ! echo "$OUT" | grep -q '"platform": *"cpu"'; then
    echo "== TUNNEL ALIVE $(date +%T) — firing session" >> "$LOG"
    bash scripts/tunnel_session.sh >> "$LOG" 2>&1
    echo "== session done $(date +%T)" >> "$LOG"
    exit 0
  fi
  sleep 480
done
