#!/bin/bash
# One-shot TPU measurement session: run the measurement sequence the
# moment the tunnel is alive, highest-value first (the tunnel has been
# observed to flap — if it dies mid-session, the early artifacts must
# be the ones that matter). Logs to artifacts/.
#
# Round-4 order (VERDICT r3): pallas-lowering proof first (weak #6),
# then the headline bench (its success seeds artifacts/bench_last.json,
# which bench.py now prints write-first so the driver's end-of-round
# capture lands a TPU number even if the tunnel has died again), then
# the A/B arms of the three landed traffic cuts, then profiles,
# convergence, and the origins sweep.
cd /root/repo
mkdir -p artifacts
T=artifacts/tunnel_$(date +%m%d_%H%M)
echo "== pallas probe (does pallas lower on the real backend?)"
timeout 1800 python scripts/pallas_probe.py 2>&1 | tee $T.pallas.log
echo "== micro (op-class pricing)"
timeout 1200 python scripts/profile_micro.py "${1:-100000}" 2>&1 | tee $T.micro.log
echo "== bench (headline number + pallas_fused; seeds bench_last.json)"
BENCH_WORKER=1 timeout 2400 python bench.py 2>&1 | tee $T.bench.log
echo "== bench A/B: bounded piggyback"
BENCH_WORKER=1 BENCH_PIG_MEMBERS=16 timeout 2400 python bench.py 2>&1 | tee $T.bench_pig.log
echo "== bench A/B: sync pulls (10 = score-pool width, off) vs default 3"
BENCH_WORKER=1 BENCH_SYNC_PULL=10 timeout 2400 python bench.py 2>&1 | tee $T.bench_pull.log
echo "== bench A/B: narrow dtypes off (wide int32 planes)"
BENCH_WORKER=1 BENCH_NARROW=0 timeout 2400 python bench.py 2>&1 | tee $T.bench_wide.log
echo "== scale (phase profile)"
timeout 2400 python scripts/profile_scale.py "${1:-100000}" 8 2>&1 | tee $T.scale.log
echo "== bcast (sub-phase profile)"
timeout 2400 python scripts/profile_bcast.py "${1:-100000}" 8 2>&1 | tee $T.bcast.log
echo "== convergence (tracked metric at 100k, kill+partition mix)"
timeout 4000 python scripts/convergence_bench.py 100000 --out=artifacts/CONVERGENCE_r04_tpu.json 2>&1 | tee $T.conv.log
echo "== origins sweep"
timeout 5000 python scripts/origins_sweep.py 100000 64 256 2>&1 | tee $T.origins.log
