#!/bin/bash
# One-shot TPU measurement session: run the measurement sequence the
# moment the tunnel is alive, highest-value first (the tunnel has been
# observed to flap — if it dies mid-session, the early artifacts must
# be the ones that matter). Logs to artifacts/.
cd /root/repo
mkdir -p artifacts
T=artifacts/tunnel_$(date +%m%d_%H%M)
echo "== micro (op-class pricing)"
timeout 1200 python scripts/profile_micro.py "${1:-100000}" 2>&1 | tee $T.micro.log
echo "== bench (headline number + pallas_fused)"
BENCH_WORKER=1 timeout 2400 python bench.py 2>&1 | tee $T.bench.log
echo "== bench A/B: bounded piggyback"
BENCH_WORKER=1 BENCH_PIG_MEMBERS=16 timeout 2400 python bench.py 2>&1 | tee $T.bench_pig.log
echo "== scale (phase profile)"
timeout 2400 python scripts/profile_scale.py "${1:-100000}" 8 2>&1 | tee $T.scale.log
echo "== bcast (sub-phase profile)"
timeout 2400 python scripts/profile_bcast.py "${1:-100000}" 8 2>&1 | tee $T.bcast.log
echo "== origins sweep"
timeout 5000 python scripts/origins_sweep.py 100000 64 256 2>&1 | tee $T.origins.log
echo "== convergence"
timeout 4000 python scripts/convergence_bench.py 100000 --out=artifacts/CONVERGENCE_r03_tpu.json 2>&1 | tee $T.conv.log
