#!/bin/bash
# One-shot TPU measurement session: run the full profiling + bench
# sequence the moment the tunnel is alive, logging to artifacts/.
cd /root/repo
mkdir -p artifacts
T=artifacts/tunnel_$(date +%m%d_%H%M)
echo "== micro" ; timeout 1200 python scripts/profile_micro.py "${1:-100000}" 2>&1 | tee $T.micro.log
echo "== scale" ; timeout 2400 python scripts/profile_scale.py "${1:-100000}" 8 2>&1 | tee $T.scale.log
echo "== bcast" ; timeout 2400 python scripts/profile_bcast.py "${1:-100000}" 8 2>&1 | tee $T.bcast.log
echo "== bench" ; BENCH_WORKER=1 timeout 2400 python bench.py 2>&1 | tee $T.bench.log
echo "== origins sweep" ; timeout 5000 python scripts/origins_sweep.py 100000 64 256 2>&1 | tee $T.origins.log
echo "== convergence" ; timeout 4000 python scripts/convergence_bench.py 100000 --out=artifacts/CONVERGENCE_r03_tpu.json 2>&1 | tee $T.conv.log
