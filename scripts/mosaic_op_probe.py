"""Bisect which op patterns inside the megakernels fail Mosaic TPU
lowering ("Unsupported target bitwidth for truncation", int arg-reduce,
...). Each pattern is a tiny standalone pallas kernel compiled on the
real backend; one JSON line per pattern. Patterns mirror the exact op
mix of ``ops/megakernel.py``'s ingest + swim kernels so a pass here
means the big kernels' op classes all lower.
"""

from __future__ import annotations

import functools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    import jax.numpy as jnp
    import numpy as np
    from jax.experimental import pallas as pl

    B, W = 32, 8

    def run(name, kernel, n_in=1, out_dtype=jnp.int32):
        x = jnp.arange(B * W, dtype=jnp.int32).reshape(B, W) % 7
        args = [x] * n_in
        try:
            out = pl.pallas_call(
                kernel,
                grid=(1,),
                in_specs=[pl.BlockSpec((B, W), lambda i: (0, 0))] * n_in,
                out_specs=pl.BlockSpec((B, W), lambda i: (0, 0)),
                out_shape=jax.ShapeDtypeStruct((B, W), out_dtype),
                interpret=False,
            )(*args)
            jax.block_until_ready(out)
            print(json.dumps({"pattern": name, "ok": True}), flush=True)
            return True
        except Exception as e:  # noqa: BLE001
            msg = str(e).splitlines()
            key = next((l for l in msg if "Mosaic" in l or "Unsupported" in l
                        or "NotImplemented" in l), msg[0] if msg else "?")
            print(json.dumps({"pattern": name, "ok": False,
                              "err": key[:160]}), flush=True)
            return False

    iota = jax.lax.broadcasted_iota(jnp.int32, (B, W), 1)

    def k_bool_store(x_ref, o_ref):
        b = x_ref[:] != 0
        o_ref[:] = b.astype(jnp.int32)

    def k_bool_and_reduce3(x_ref, o_ref):
        x = x_ref[:]
        same = (x[:, :, None] == x[:, None, :])
        tri = jnp.tril(jnp.ones((W, W), bool), k=-1)
        dup = jnp.any(same & tri[None, :, :], axis=2)
        o_ref[:] = dup.astype(jnp.int32)

    def k_shift_vec(x_ref, o_ref):
        x = x_ref[:].astype(jnp.uint32)
        bit = (x & 31).astype(jnp.uint32)
        o_ref[:] = ((jnp.uint32(1) << bit) | (x >> bit)).astype(jnp.int32)

    def k_popcount(x_ref, o_ref):
        x = x_ref[:].astype(jnp.uint32)
        o_ref[:] = jax.lax.population_count(x).astype(jnp.int32)

    def k_np_scalar_where(x_ref, o_ref):
        x = x_ref[:]
        o_ref[:] = jnp.where(x > 3, np.int32(-2147483648), x)

    def k_min_iota_select(x_ref, o_ref):
        x = x_ref[:]
        kmin = jnp.min(x, axis=1)
        slot = jnp.min(jnp.where(x == kmin[:, None], iota, W), axis=1)
        o_ref[:] = jnp.broadcast_to(slot[:, None], (B, W))

    def k_argmax_f32(x_ref, o_ref):
        x = x_ref[:].astype(jnp.float32)
        o_ref[:] = jnp.broadcast_to(
            jnp.argmax(x, axis=1).astype(jnp.int32)[:, None], (B, W)
        )

    def k_cols_select(x_ref, o_ref):
        x = x_ref[:]
        out = jnp.zeros_like(x)
        for c in range(W):
            out = jnp.where(x == c, x[:, c:c + 1], out)
        o_ref[:] = out

    def k_mod(x_ref, o_ref):
        o_ref[:] = (x_ref[:] % W) * 4 + 1

    def k_div_pyint(x_ref, o_ref):
        o_ref[:] = (10 * 1024 * 1024 // (183 * jnp.maximum(x_ref[:], 1)))

    def k_bool_or_acc(x_ref, o_ref):
        x = x_ref[:]
        keep = jnp.zeros((B, W), bool)
        sel = x > 3
        keep = keep | (sel & (iota == 2))
        o_ref[:] = keep.astype(jnp.int32)

    def k_row_bcast(x_ref, o_ref):
        x = x_ref[:]
        o_ref[:] = jnp.broadcast_to(jnp.max(x, axis=1)[:, None], (B, W))

    def k_scalar_ref(x_ref, o_ref):
        # [B,1]-style scalar lanes: x[:, 0] broadcast ops
        v = x_ref[:][:, 0]
        o_ref[:] = jnp.broadcast_to(v[:, None], (B, W)) + 1

    results = {}
    for name, k in [
        ("bool_store", k_bool_store),
        ("bool_and_reduce3", k_bool_and_reduce3),
        ("shift_vec", k_shift_vec),
        ("popcount", k_popcount),
        ("np_scalar_where", k_np_scalar_where),
        ("min_iota_select", k_min_iota_select),
        ("argmax_f32", k_argmax_f32),
        ("cols_select", k_cols_select),
        ("mod", k_mod),
        ("div_pyint", k_div_pyint),
        ("bool_or_acc", k_bool_or_acc),
        ("row_bcast", k_row_bcast),
        ("scalar_ref", k_scalar_ref),
    ]:
        results[name] = run(name, k)
    print(json.dumps({"metric": "mosaic_op_probe",
                      "backend": jax.default_backend(),
                      "failed": [k for k, v in results.items() if not v]}),
          flush=True)


if __name__ == "__main__":
    main()
