#!/usr/bin/env python
"""Observability smoke probe (ISSUE 11) -> artifacts/obs_r11.json.

A small segmented soak under the full flight-recorder plane, gated on
the acceptance criteria the plane exists for:

1. **live scrape advancing** — a scraper thread polls the standalone
   Prometheus listener WHILE the soak runs and the sampled
   ``corro_soak_rounds_total`` values must be non-decreasing with at
   least two distinct mid-run values (a soak visible only after the
   fact is the bug this PR removes);
2. **flight replay consistency** — the NDJSON record replays to the
   same segment count / completed rounds / checkpoint facts the run's
   own ``SoakResult.stats`` reports;
3. **quiet-trace activity oracle** — a zero-traffic trace reports zero
   per-shard activity on every ``active_*`` channel, a seeded traffic
   trace reports non-zero (the masks the future active-set round
   variant will gate on);
4. **memory audit closure** — the per-table audit sums to the measured
   state size, and ``O(N*M)`` tables dominate at scale sim shapes.

Under ``CORROSAN=1`` the whole probe runs inside a sanitized window
(race/lock-order/fs/leak detectors armed): the obs plane's flush and
listener threads must come and go without a finding.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _probe(rec: dict) -> list:
    import jax

    jax.config.update("jax_platforms", "cpu")
    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    import tempfile

    import jax.numpy as jnp
    import jax.random as jr
    import numpy as np

    from corrosion_tpu.obs import (
        FlightRecorder,
        SoakObserver,
        memory_report,
        replay_flight_record,
        state_bytes,
    )
    from corrosion_tpu.resilience.segments import (
        make_soak_inputs,
        run_segmented,
    )
    from corrosion_tpu.sim.scale_step import (
        ScaleSimState,
        make_write_inputs,
        scale_run_rounds_carry,
        scale_sim_config,
    )
    from corrosion_tpu.sim.transport import NetModel
    from corrosion_tpu.utils.metrics import (
        Registry,
        start_prometheus_listener,
    )

    problems: list = []
    n_nodes = int(os.environ.get("OBS_PROBE_NODES", "256"))
    rounds = int(os.environ.get("OBS_PROBE_ROUNDS", "10"))
    cfg = scale_sim_config(n_nodes)
    net = NetModel.create(n_nodes, drop_prob=0.01)
    st = ScaleSimState.create(cfg)

    # --- (4) memory audit closure ---------------------------------------
    report = memory_report(st, n_nodes)
    table_sum = sum(t["nbytes"] for t in report["tables"].values())
    measured = state_bytes(st)
    rec["hbm_bytes"] = measured
    rec["mem_by_class"] = report["by_class"]
    if not (table_sum == report["total_bytes"] == measured > 0):
        problems.append(
            f"memory audit does not sum to the measured state size: "
            f"{table_sum} vs {report['total_bytes']} vs {measured}"
        )
    if report["by_class"].get("O(N*M)", 0) <= report["by_class"].get(
            "O(N)", 0):
        problems.append("O(N*M) tables do not dominate the scale state")

    # --- (1)+(2) soak under the plane, scraped live ---------------------
    registry = Registry()
    listener = start_prometheus_listener(registry, port=0)
    samples: list = []
    stop = threading.Event()

    def scrape_loop():
        url = f"http://127.0.0.1:{listener.bound_port}/metrics"
        while not stop.is_set():
            try:
                text = urllib.request.urlopen(url, timeout=2).read().decode()
            except OSError:
                continue
            for line in text.splitlines():
                if line.startswith("corro_soak_rounds_total "):
                    samples.append(float(line.split()[1]))
            stop.wait(0.02)

    from corrosion_tpu.utils.lifecycle import spawn_counted

    scraper = spawn_counted(scrape_loop, name="corro-obs-probe-scraper")
    inputs = make_soak_inputs(cfg, jr.key(1), rounds, write_frac=0.25)
    with tempfile.TemporaryDirectory() as tmp:
        flight_path = os.path.join(tmp, "flight.ndjson")
        obs = SoakObserver(flight=FlightRecorder(flight_path),
                           registry=registry, listener=listener)
        try:
            res = run_segmented(
                cfg, st, net, jr.key(0), inputs,
                segment_rounds=max(1, rounds // 5),
                checkpoint_root=os.path.join(tmp, "ck"), obs=obs,
            )
        finally:
            stop.set()
            scraper.join(timeout=10)
            obs.close()  # joins corro-obs-flight, shuts the listener down
        # replay only AFTER close(): the flush thread owns the file until
        # the drain+join — reading earlier races the tail records
        replay = replay_flight_record(flight_path)

    mid = [s for s in samples if 0 < s < res.completed_rounds]
    if any(b < a for a, b in zip(samples, samples[1:])):
        problems.append("scraped corro_soak_rounds_total decreased")
    if len(set(mid)) < 2:
        problems.append(
            f"mid-soak scrape saw {sorted(set(mid))} — the series did "
            f"not visibly advance while the soak ran"
        )
    rec["scrape"] = {
        "samples": len(samples),
        "distinct_mid_run": sorted(set(mid)),
        "final": samples[-1] if samples else None,
    }
    rec["flight"] = {
        "segments": replay["segments"],
        "completed_rounds": replay["completed_rounds"],
        "rounds_per_s": replay["rounds_per_s"],
        "ended": replay["ended"],
        "skipped_lines": replay["skipped_lines"],
    }
    if replay["segments"] != res.stats["segments"]:
        problems.append(
            f"flight replay segments {replay['segments']} != run "
            f"stats {res.stats['segments']}"
        )
    if replay["completed_rounds"] != res.completed_rounds:
        problems.append("flight replay completed_rounds != run")
    for k in ("ckpt_written", "donated_segments", "ckpt_drain_bytes"):
        if replay["stats"].get(k) != res.stats.get(k):
            problems.append(
                f"flight replay stats[{k!r}] {replay['stats'].get(k)} "
                f"!= run {res.stats.get(k)}"
            )

    # --- (3) quiescence oracle ------------------------------------------
    quiet_rounds = 6
    quiet = make_soak_inputs(cfg, jr.key(2), quiet_rounds, write_frac=0.0)
    run = jax.jit(
        lambda s, k, i: scale_run_rounds_carry(cfg, s, net, k, i))
    (_, _), q_infos = run(ScaleSimState.create(cfg), jr.key(3), quiet)
    q_act = {k: float(np.asarray(v).sum()) for k, v in q_infos.items()
             if k.startswith("active_")}
    w = jnp.zeros((quiet_rounds, n_nodes), bool).at[:, :32].set(True)
    seeded = make_write_inputs(cfg, jr.key(4), quiet_rounds, w)
    (_, _), s_infos = run(ScaleSimState.create(cfg), jr.key(3), seeded)
    s_act = {k: float(np.asarray(v).sum()) for k, v in s_infos.items()
             if k.startswith("active_")}
    rec["activity"] = {"quiet": q_act, "seeded": s_act}
    if not q_act or any(v != 0.0 for v in q_act.values()):
        problems.append(
            f"quiet trace reported non-zero activity: {q_act}"
        )
    if sum(s_act.values()) <= 0:
        problems.append(
            f"seeded trace reported zero activity: {s_act}"
        )
    return problems


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--output", default="artifacts/obs_r11.json")
    args = ap.parse_args()
    rec: dict = {"metric": "obs_smoke", "corrosan": False}
    t0 = time.perf_counter()
    if os.environ.get("CORROSAN") == "1":
        # the probe's own window: flush/listener/scraper threads and the
        # obs locks run under the race + leak detectors
        from corrosion_tpu.analysis.sanitizer import sanitized

        rec["corrosan"] = True
        with sanitized() as san:
            problems = _probe(rec)
        findings = san.gate()
        if findings:
            problems += [f"corrosan: {f.kind} {f.subject}"
                         for f in findings]
    else:
        problems = _probe(rec)
    rec["elapsed_s"] = round(time.perf_counter() - t0, 2)
    rec["ok"] = not problems
    if problems:
        rec["problems"] = problems
    os.makedirs(os.path.dirname(os.path.abspath(args.output)),
                exist_ok=True)
    with open(args.output, "w") as f:
        json.dump(rec, f, indent=2)
    print(json.dumps(rec, indent=2))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
