"""Differential phase profile of the 100k scale round on the current
backend: times 4 programs (full round, swim only, swim+bcast, sync) and
prints each as soon as it's measured (no buffering — tunnel runs die
mid-way often enough that partial output matters).

Usage: python scripts/profile_scale.py [n_nodes] [scan_rounds]
"""

import os
import sys
import time

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from corrosion_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()


import jax.numpy as jnp  # noqa: E402
import jax.random as jr  # noqa: E402

from corrosion_tpu.sim.broadcast import local_write  # noqa: E402
from corrosion_tpu.sim.scale import scale_swim_step  # noqa: E402
from corrosion_tpu.sim.scale_step import (  # noqa: E402
    ScaleRoundInput,
    ScaleSimState,
    piggyback_bcast_step,
    scale_sim_config,
    scale_sim_step,
)
from corrosion_tpu.sim.sync import sync_step  # noqa: E402
from corrosion_tpu.sim.transport import NetModel  # noqa: E402


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    cfg = scale_sim_config(n, n_origins=min(16, n))
    net = NetModel.create(n, drop_prob=0.01)
    st = ScaleSimState.create(cfg)
    inp = ScaleRoundInput.quiet(cfg)
    key = jr.key(0)
    print(
        f"n={n} m={cfg.m_slots} rounds={rounds} "
        f"platform={jax.devices()[0].platform}",
        flush=True,
    )

    def timed(name, step):
        def run(st, key):
            def body(carry, _):
                s, k = carry
                k, sub = jr.split(k)
                return (step(s, sub), k), ()

            (s, _), _ = jax.lax.scan(body, (st, key), None, length=rounds)
            return s

        f = jax.jit(run)
        t0 = time.perf_counter()
        jax.block_until_ready(f(st, key))
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        reps = 3
        for _ in range(reps):
            jax.block_until_ready(f(st, key))
        dt = (time.perf_counter() - t0) / reps / rounds
        print(
            f"{name:16s} {dt * 1000:9.2f} ms/round  (compile {compile_s:.0f}s)",
            flush=True,
        )

    timed("full", lambda s, k: scale_sim_step(cfg, s, net, k, inp)[0])

    def swim_only(s, k):
        swim, _, _, _ = scale_swim_step(cfg, s.swim, net, k)
        return s._replace(swim=swim)

    timed("swim", swim_only)

    def swim_bcast(s, k):
        k1, k2 = jr.split(k)
        swim, _, channels, carried = scale_swim_step(cfg, s.swim, net, k1)
        cst = local_write(
            cfg, s.crdt._replace(now=s.crdt.now + 1), inp.write_mask,
            inp.write_cell, inp.write_val, inp.write_clp,
        )
        cst, _ = piggyback_bcast_step(cfg, cst, channels, k2, carried)
        return ScaleSimState(swim, cst)

    timed("swim+bcast", swim_bcast)

    iarr = jnp.arange(n, dtype=jnp.int32)
    p = cfg.sync_peers
    peers = jnp.stack([(iarr + 1 + j) % n for j in range(p)], axis=1)

    def sync_only(s, k):
        cst, _, _ = sync_step(
            cfg, s.crdt, peers, jnp.ones((n, p), bool), s.swim.alive, net, k,
            go_all=True,
        )
        return s._replace(crdt=cst)

    timed("sync(go_all)", sync_only)


if __name__ == "__main__":
    main()
