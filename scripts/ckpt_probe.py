#!/usr/bin/env python
"""Sharded-checkpoint pipeline probe -> artifacts/ckpt_r09.json.

A CPU-budget end-to-end check of the ISSUE 9 story, published as a
machine-readable artifact next to the lint/san reports:

- **stall vs overlapped IO vs shard count**: one segmented soak runs
  synchronously un-sharded (the baseline that pays serialize+hash+IO on
  the hot loop) and one runs sharded over the 8 virtual devices with
  the async writer — the sharded arm must drain one slice per device
  (``ckpt_shards == 8``, largest shard a fraction of the total) with
  the hot-loop stall under the overlapped IO time;
- **elastic restore**: the sharded run's checkpoint resumes on a
  4-device mesh and must finish bitwise identical to an uninterrupted
  straight scan (the resharded-restore acceptance bar).

Exit 0 with ``"ok": true`` when every claim holds; exit 1 otherwise
(the artifact is written either way).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# must be set before jax initializes; conftest does the same for tests
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()


def main() -> int:
    import jax

    # sitecustomize may register a TPU-tunnel plugin; force CPU like
    # the test harness does
    jax.config.update("jax_platforms", "cpu")
    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    import jax.random as jr
    import numpy as np

    from corrosion_tpu.parallel.mesh import make_mesh, shard_state
    from corrosion_tpu.resilience.segments import (
        make_soak_inputs,
        resume_segmented,
        run_segmented,
    )
    from corrosion_tpu.sim.scale_step import (
        ScaleSimState,
        scale_run_rounds,
        scale_sim_config,
    )
    from corrosion_tpu.sim.transport import NetModel

    import tempfile

    # tests/test_resilience.py's scale rig shapes — persistent-cache hits
    cfg = scale_sim_config(
        24, m_slots=8, n_origins=4, n_rows=4, n_cols=2, sync_interval=4
    )
    net = NetModel.create(cfg.n_nodes, drop_prob=0.02)
    st0 = ScaleSimState.create(cfg)
    key0 = jr.key(3)
    inputs = make_soak_inputs(cfg, jr.key(5), 16, write_frac=0.25,
                              mode="scale")
    st_ref, _ = jax.jit(
        lambda s, k, i: scale_run_rounds(cfg, s, net, k, i)
    )(st0, key0, inputs)
    jax.block_until_ready(st_ref)

    problems = []

    # --- arm 1: synchronous, un-sharded (hot-loop baseline) --------------
    with tempfile.TemporaryDirectory() as tmp:
        r_sync = run_segmented(
            cfg, st0, net, key0, inputs, segment_rounds=8, mode="scale",
            checkpoint_root=tmp, donate=False, async_checkpoint=False,
        )
    if r_sync.stats["ckpt_shards"] != 1:
        problems.append("un-sharded arm drained more than one shard")

    # --- arm 2: sharded + overlapped writer ------------------------------
    import shutil

    mesh8 = make_mesh(jax.devices()[:8])
    st_s = shard_state(mesh8, cfg.n_nodes, st0)
    net_s = shard_state(mesh8, cfg.n_nodes, net)
    in_s = shard_state(mesh8, cfg.n_nodes, inputs)
    tmp_root = tempfile.mkdtemp(prefix="ckpt_probe_")
    try:
        r_shard = run_segmented(
            cfg, st_s, net_s, key0,
            jax.tree.map(lambda a: a[:8], in_s), segment_rounds=8,
            mode="scale", checkpoint_root=tmp_root,
        )
        s = r_shard.stats
        if s["ckpt_shards"] != 8:
            problems.append(
                f"sharded arm drained {s['ckpt_shards']} shards")
        if s["ckpt_shard_bytes_max"] * 2 > s["ckpt_drain_bytes"]:
            problems.append("largest shard holds over half the drain bytes")
        # stall vs io is recorded but not gated here: at probe size (24
        # nodes, ~40 KB of carry) per-shard Python overhead dominates
        # both numbers; BENCH_SMOKE=1 enforces stall < io at bench scale

        # --- elastic restore: resume the 8-way checkpoint on 4 devices ---
        mesh4 = make_mesh(jax.devices()[:4])
        res = resume_segmented(
            cfg, shard_state(mesh4, cfg.n_nodes, net),
            shard_state(mesh4, cfg.n_nodes, inputs), segment_rounds=8,
            mode="scale", checkpoint_root=tmp_root, mesh=mesh4,
        )
    finally:
        shutil.rmtree(tmp_root, ignore_errors=True)
    resharded_ok = res.completed_rounds == 16 and not res.aborted and all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(st_ref), jax.tree.leaves(res.state))
    )
    if not resharded_ok:
        problems.append("8->4 resharded resume is not bitwise identical")

    record = {
        "metric": "ckpt_probe_cpu",
        "ok": not problems,
        "devices": len(jax.devices()),
        "resharded_restore_ok": resharded_ok,
        "sync_unsharded": {
            "ckpt_stall_s": round(r_sync.stats["ckpt_stall_s"], 4),
            "ckpt_shards": r_sync.stats["ckpt_shards"],
            "ckpt_drain_bytes": r_sync.stats["ckpt_drain_bytes"],
        },
        "async_sharded": {
            "ckpt_stall_s": round(s["ckpt_stall_s"], 4),
            "ckpt_io_s": round(s["ckpt_io_s"], 4),
            "ckpt_serialize_s": round(s["ckpt_serialize_s"], 4),
            "ckpt_shards": s["ckpt_shards"],
            "ckpt_drain_bytes": s["ckpt_drain_bytes"],
            "ckpt_shard_bytes_max": s["ckpt_shard_bytes_max"],
        },
        "resume_4dev": {
            "ckpt_shards": res.stats["ckpt_shards"],
            "completed_rounds": res.completed_rounds,
        },
    }
    if problems:
        record["problems"] = problems
    out = sys.argv[sys.argv.index("--output") + 1] if (
        "--output" in sys.argv) else "artifacts/ckpt_r09.json"
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    with open(out, "w") as f:
        json.dump(record, f, indent=2)
    print(json.dumps(record))
    return 0 if not problems else 1


if __name__ == "__main__":
    sys.exit(main())
