"""Prove (or disprove) that the pallas megakernels lower and run on the
real backend, at the flagship block shapes — VERDICT r3 weak #6: every
fused==unfused differential has only ever run in interpret mode on CPU;
``_pallas_works()`` has never returned on a real axon/TPU backend.

Writes ONE json line to stdout and to ``artifacts/PALLAS_PROBE_r05.json``
recording, per kernel, whether the tiny differential and the real-block-
shape width probes passed, so the round has a committed artifact either
way (a lowering failure is a result, not a missing measurement).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    from corrosion_tpu.ops import megakernel
    from corrosion_tpu.sim.scale_step import scale_sim_config

    backend = jax.default_backend()
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    cfg = scale_sim_config(n)
    rec: dict = {
        "metric": "pallas_probe",
        "backend": backend,
        "n_nodes": n,
        "block": megakernel._block_size(n),
        "complete": False,
    }

    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "artifacts", "PALLAS_PROBE_r05.json")

    def checkpoint() -> None:
        """Write after every probe step: backend init / a probe hang +
        the session timeout's SIGKILL must still leave the partial
        results on disk (the round-3 tunnel hung >9 min routinely)."""
        if backend == "cpu":
            # a CPU sanity run must not masquerade as the round's answer
            # to "does pallas lower on the target backend"
            return
        os.makedirs(os.path.dirname(out), exist_ok=True)
        tmp = out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, out)

    checkpoint()
    t0 = time.time()
    rec["differential_ok"] = bool(megakernel._pallas_works())
    rec["differential_s"] = round(time.time() - t0, 1)
    checkpoint()

    # msgs must match the live round's ingest width (4 channels x
    # pig_changes messages) — a narrower probe can pass where the real
    # kernel fails Mosaic/VMEM
    msgs = 4 * cfg.pig_changes
    for name, fn in (
        ("ingest", lambda: megakernel._width_ok_ingest(cfg, msgs=msgs)),
        ("ingest_emit",
         lambda: megakernel._width_ok_ingest(cfg, msgs=1, emit=True)),
        ("swim", lambda: megakernel._width_ok_swim(cfg.n_nodes,
                                                   cfg.m_slots, 0)),
        ("swim_pig16", lambda: megakernel._width_ok_swim(cfg.n_nodes,
                                                         cfg.m_slots, 16)),
    ):
        t0 = time.time()
        try:
            rec[f"{name}_ok"] = bool(fn())
        except Exception as exc:  # noqa: BLE001 — a crash is a result too
            rec[f"{name}_ok"] = False
            rec[f"{name}_error"] = repr(exc)[:300]
        rec[f"{name}_s"] = round(time.time() - t0, 1)
        checkpoint()

    rec["value"] = 1.0 if all(
        rec.get(k) for k in
        ("differential_ok", "ingest_ok", "ingest_emit_ok", "swim_ok",
         "swim_pig16_ok")
    ) else 0.0
    rec["complete"] = True
    checkpoint()
    print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
