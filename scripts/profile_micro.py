"""Microbenchmark the op classes the 100k round is built from, on the
current backend — extends PERF.md's characterization table. Run this
FIRST when the tunnel comes back: it prices each remaining op class
(flat [N] scatters for election/notify/carried, card row gathers, 1-D
gathers for comparison, [N*P] sync scatters, uniform draws, pallas
probe) so the next fusion target is chosen from data, not guesses.

Usage: python scripts/profile_micro.py [n_nodes]
"""

import os
import sys
import time

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from corrosion_tpu.utils.compile_cache import enable_compile_cache

enable_compile_cache()

import jax.numpy as jnp  # noqa: E402
import jax.random as jr  # noqa: E402


def timed(name, fn, *args, reps=20):
    f = jax.jit(fn)
    try:
        t0 = time.perf_counter()
        jax.block_until_ready(f(*args))
        compile_s = time.perf_counter() - t0
        t0 = time.perf_counter()
        for _ in range(reps):
            out = f(*args)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / reps
        print(f"{name:34s} {dt * 1e3:9.3f} ms  (compile {compile_s:.1f}s)",
              flush=True)
    except Exception as e:  # noqa: BLE001 — keep pricing the rest
        print(f"{name:34s} FAILED: {type(e).__name__}: {e}", flush=True)


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    key = jr.key(0)
    idx = jr.randint(key, (n,), 0, n, dtype=jnp.int32)
    vals = jr.randint(jr.fold_in(key, 1), (n,), 0, 1 << 20, dtype=jnp.int32)
    card = jr.randint(jr.fold_in(key, 2), (n, 8), 0, 1 << 20, dtype=jnp.int32)
    wide = jr.randint(jr.fold_in(key, 3), (n, 64), 0, 1 << 20, dtype=jnp.int32)
    idx_np = jr.randint(jr.fold_in(key, 4), (n, 10), 0, n, dtype=jnp.int32)
    print(f"n={n} platform={jax.devices()[0].platform}", flush=True)

    timed("elementwise max+mul [N,64]", lambda a: jnp.maximum(a, 3) * 2, wide)
    timed("1-D gather x[idx] [N]", lambda v, i: v[i] + 1, vals, idx)
    timed("card row gather [N,8]",
          lambda c, i: jax.lax.optimization_barrier(c[i]).sum(axis=1),
          card, idx)
    timed("wide row gather [N,64] barriered",
          lambda w, i: jax.lax.optimization_barrier(w[i])[:, 0],
          wide, idx)
    timed("flat scatter-add [N]",
          lambda i: jnp.zeros(n, jnp.int32).at[i].add(1, mode="drop"), idx)
    timed("flat scatter-max [N]",
          lambda i, v: jnp.full(n, -1, jnp.int32).at[i].max(v, mode="drop"),
          idx, vals)
    timed("4x flat scatter-add [N] (carried)",
          lambda i: sum(
              jnp.zeros(n, jnp.int32).at[jnp.clip(i + k, 0, n - 1)]
              .add(1, mode="drop")
              for k in range(4)
          ), idx)
    timed("scatter-add [N,10] flat (sync load)",
          lambda ip: jnp.zeros(n + 1, jnp.int32)
          .at[ip.reshape(-1)].add(1, mode="drop")[:n], idx_np)
    timed("uniform draw [N]", lambda k: jr.uniform(k, (n,)), key)
    timed("uniform draw [N,3]", lambda k: jr.uniform(k, (n, 3)), key)
    timed("top_k 4 of [N,32]",
          lambda w: jax.lax.top_k(w[:, :32].astype(jnp.float32), 4)[1], wide)
    timed("argsort [N,32]",
          lambda w: jnp.argsort(w[:, :32], axis=1), wide)
    timed("argmax [N,64]", lambda w: jnp.argmax(w, axis=1), wide)

    # pallas availability + ingest/swim kernel probe
    from corrosion_tpu.ops import megakernel

    t0 = time.perf_counter()
    ok = megakernel._pallas_works()
    print(f"pallas_works: {ok}  ({time.perf_counter() - t0:.1f}s)",
          flush=True)


if __name__ == "__main__":
    main()
