#!/usr/bin/env python
"""corrobudget gate probe -> artifacts/membudget_r12.json (ISSUE 12).

The CI face of the 1M memory-budget audit (docs/memory-budget.md):

- **static inventory**: every ``ScaleSimState`` leaf with its symbolic
  shape, dtype, and complexity class, from the constructor ASTs
  (``analysis/shapes.py`` — no arrays built);
- **projections** at N ∈ {100k, 300k, 1M} under the flagship extents,
  plus the int8 (``narrow_int8``) arm at 1M;
- **cross-check**: the static inventory must match the LIVE
  ``obs/memory.py`` audit leaf-for-leaf (names, shapes, dtypes,
  nbytes) at a small real (N, M) point — the same both-directions
  pin tier-1 runs in ``tests/test_membudget.py``;
- **budget gate**: the declared per-class budget (``HBM_BUDGET``) must
  hold at the 1M point, and the ``mem-budget``/``densify`` rules must
  be clean over the repo walk (rule counts recorded).

Exit 0 with ``"ok": true`` when every claim holds; exit 1 otherwise
(the artifact is written either way).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# must be set before jax initializes (the runtime cross-check builds a
# real small-N state); conftest does the same for tests
os.environ["JAX_PLATFORMS"] = "cpu"


def main() -> int:
    problems = []

    from corrosion_tpu.analysis import shapes
    from corrosion_tpu.analysis.runner import lint_report
    from corrosion_tpu.sim.scale_step import scale_sim_config

    # --- static inventory + projections ---------------------------------
    template = scale_sim_config(100_000)
    inv = shapes.static_inventory(template, mode="scale")
    projections = {}
    for n in (100_000, 300_000, 1_000_000):
        rep = inv.report({"N": n})
        if rep["unresolved"]:
            problems.append(f"unresolved leaves at N={n}: "
                            f"{rep['unresolved']}")
        projections[str(n)] = {
            "total_bytes": rep["total_bytes"],
            "by_class": rep["by_class"],
        }
    report_1m = inv.report(dict(shapes.HBM_BUDGET["point"]))

    # the int8 arm (the applied ISSUE-12 shrink) at the same point
    import dataclasses

    i8_cfg = dataclasses.replace(template, narrow_int8=True).validate()
    i8_rep = shapes.static_inventory(i8_cfg, mode="scale").report(
        dict(shapes.HBM_BUDGET["point"]))
    saved = report_1m["total_bytes"] - i8_rep["total_bytes"]
    if saved <= 0:
        problems.append(
            f"narrow_int8 projection saved nothing ({saved} bytes)")

    # --- budget gate ----------------------------------------------------
    budget_ok = True
    for cls, budget in shapes.HBM_BUDGET["per_class_bytes"].items():
        used = report_1m["by_class"].get(cls, 0)
        if used > budget:
            budget_ok = False
            problems.append(
                f"{cls} over budget at 1M: {used} > {budget}")

    # --- static == runtime cross-check at a real point ------------------
    import jax

    jax.config.update("jax_platforms", "cpu")
    from corrosion_tpu.obs.memory import memory_report
    from corrosion_tpu.sim.scale_step import ScaleSimState

    small = scale_sim_config(4096, m_slots=32)
    st = ScaleSimState.create(small)
    live = memory_report(st, small.n_nodes)
    static = shapes.static_inventory(small, mode="scale").report()
    cross_ok = set(live["tables"]) == set(static["tables"])
    for name in live["tables"]:
        a = live["tables"][name]
        b = static["tables"].get(name)
        if b is None or any(a[k] != b[k] for k in
                            ("shape", "dtype", "nbytes", "class")):
            cross_ok = False
            problems.append(f"static/runtime drift at {name}: {a} vs {b}")
            break
    if live["total_bytes"] != static["total_bytes"]:
        cross_ok = False
        problems.append("static/runtime total_bytes drift")

    # --- rule counts over the repo walk ---------------------------------
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    paths = [os.path.join(root, "corrosion_tpu"),
             os.path.join(root, "bench.py"),
             os.path.join(root, "scripts")]
    findings, n_files = lint_report(
        paths, checkers=["mem-budget", "densify"])
    rule_counts = {"mem-budget": 0, "densify": 0}
    for f in findings:
        rule_counts[f.rule] = rule_counts.get(f.rule, 0) + 1
        problems.append(f.render())

    # --- ranked offenders (the audit deliverable) -----------------------
    offenders = sorted(
        ((name, e) for name, e in report_1m["tables"].items()
         if e["class"] != "O(1)"),
        key=lambda kv: -kv[1]["nbytes"])

    record = {
        "probe": "membudget_r12",
        "ok": not problems,
        "budget_ok": budget_ok,
        "cross_check_ok": cross_ok,
        "budget": shapes.HBM_BUDGET,
        "extents": dict(inv.bindings),
        "flags": dict(inv.flags),
        "inventory": {
            name: {
                "symbolic": leaf.shape_str(),
                "dtype": leaf.dtype,
            }
            for name, leaf in inv.leaves.items()
        },
        "projections": projections,
        "projection_1m_narrow_int8": {
            "total_bytes": i8_rep["total_bytes"],
            "by_class": i8_rep["by_class"],
            "saved_bytes_vs_default": saved,
        },
        "worst_offenders_1m": [
            {"table": name, "nbytes": e["nbytes"], "class": e["class"],
             "symbolic": e["symbolic"], "dtype": e["dtype"]}
            for name, e in offenders[:10]
        ],
        "rule_counts": rule_counts,
        "files_checked": n_files,
    }
    if problems:
        record["problems"] = problems
    out = sys.argv[sys.argv.index("--output") + 1] if (
        "--output" in sys.argv) else "artifacts/membudget_r12.json"
    os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
    with open(out, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    print(json.dumps({k: record[k] for k in
                      ("probe", "ok", "budget_ok", "cross_check_ok",
                       "rule_counts")}))
    return 0 if not problems else 1


if __name__ == "__main__":
    sys.exit(main())
