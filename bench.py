"""Benchmark: gossip-simulator round throughput on one chip.

Prints one JSON line ``{"metric", "value", "unit", "vs_baseline"}``.

North-star (BASELINE.md): >=10,000 simulated gossip rounds/sec at 100k
nodes on a v5e-8. This bench runs the fused whole-cluster round at the
north-star scale — the bounded member-table simulator (``sim/scale_step``:
SWIM + piggybacked changeset broadcast + anti-entropy sync, O(N*M) state)
— under ``lax.scan`` on whatever single chip is available and reports
steady-state rounds/sec; ``vs_baseline`` is the fraction of the 10k
rounds/sec target (which assumes all 8 chips of a v5e-8; a single chip
carries the whole cluster here).
"""

from __future__ import annotations

import functools
import json
import os
import time

import jax

# this environment's sitecustomize forces a platform via config.update,
# which outranks the JAX_PLATFORMS env var — re-honor the env var
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.numpy as jnp
import jax.random as jr


def main() -> None:
    from corrosion_tpu.sim.scale_step import (
        ScaleRoundInput,
        ScaleSimState,
        scale_run_rounds,
        scale_sim_config,
    )
    from corrosion_tpu.sim.transport import NetModel

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    n_nodes = int(os.environ.get("BENCH_NODES", 100_000 if on_tpu else 256))
    rounds = int(os.environ.get("BENCH_ROUNDS", 100 if on_tpu else 4))
    reps = int(os.environ.get("BENCH_REPS", 5 if on_tpu else 2))

    cfg = scale_sim_config(n_nodes, n_origins=min(16, n_nodes))
    key = jr.key(0)
    st = ScaleSimState.create(cfg)
    net = NetModel.create(n_nodes, drop_prob=0.01)

    # conflict-heavy inputs: origins write hot cells at random rounds
    k1, k2, k3 = jr.split(jr.key(1), 3)
    quiet = ScaleRoundInput.quiet(cfg)
    inputs = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (rounds,) + a.shape), quiet
    )
    w = (jr.uniform(k1, (rounds, n_nodes)) < 0.25) & (
        jnp.arange(n_nodes)[None, :] < cfg.n_origins
    )
    inputs = inputs._replace(
        write_mask=w,
        write_cell=jr.randint(k2, (rounds, n_nodes), 0, cfg.n_cells, dtype=jnp.int32),
        write_val=jr.randint(k3, (rounds, n_nodes), 0, 1 << 20, dtype=jnp.int32),
    )

    run = jax.jit(functools.partial(scale_run_rounds, cfg), donate_argnums=(0,))
    st = jax.block_until_ready(run(st, net, key, inputs))[0]  # compile + warm

    t0 = time.perf_counter()
    for i in range(reps):
        st, infos = run(st, net, jr.fold_in(key, i), inputs)
    jax.block_until_ready(st)
    dt = time.perf_counter() - t0

    rps = reps * rounds / dt
    target = 10_000.0
    print(
        json.dumps(
            {
                "metric": f"gossip_rounds_per_sec_n{n_nodes}_{platform}",
                "value": round(rps, 2),
                "unit": "rounds/s",
                "vs_baseline": round(rps / target, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
