"""Benchmark: gossip-simulator round throughput on one chip.

Prints one JSON line ``{"metric", "value", "unit", "vs_baseline"}``.

North-star (BASELINE.md): >=10,000 simulated gossip rounds/sec at 100k
nodes on a v5e-8. This bench runs the fused whole-cluster round
(SWIM + changeset broadcast + anti-entropy sync) under ``lax.scan`` on
whatever single chip is available and reports steady-state rounds/sec;
``vs_baseline`` is the fraction of the 10k rounds/sec target.
"""

from __future__ import annotations

import functools
import json
import os
import time

import jax

# this environment's sitecustomize forces a platform via config.update,
# which outranks the JAX_PLATFORMS env var — re-honor the env var
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

import jax.random as jr


def main() -> None:
    from corrosion_tpu.sim.config import wan_config
    from corrosion_tpu.sim.scenario import conflict_heavy
    from corrosion_tpu.sim.step import SimState, run_rounds
    from corrosion_tpu.sim.transport import NetModel

    platform = jax.devices()[0].platform
    n_nodes = int(os.environ.get("BENCH_NODES", 4096 if platform == "tpu" else 64))
    rounds = int(os.environ.get("BENCH_ROUNDS", 64 if platform == "tpu" else 4))
    reps = int(os.environ.get("BENCH_REPS", 5 if platform == "tpu" else 2))

    cfg = wan_config(n_nodes)
    key = jr.key(0)
    st = SimState.create(cfg)
    net = NetModel.create(n_nodes, drop_prob=0.01)
    inputs = conflict_heavy(cfg, rounds, jr.key(1), write_prob=0.25)

    run = jax.jit(functools.partial(run_rounds, cfg), donate_argnums=(0,))
    st, _ = jax.block_until_ready(run(st, net, key, inputs))  # compile + warm

    t0 = time.perf_counter()
    for i in range(reps):
        st, infos = run(st, net, jr.fold_in(key, i), inputs)
    jax.block_until_ready(st)
    dt = time.perf_counter() - t0

    rps = reps * rounds / dt
    target = 10_000.0
    print(
        json.dumps(
            {
                "metric": f"sim_rounds_per_sec_n{n_nodes}_{platform}",
                "value": round(rps, 2),
                "unit": "rounds/s",
                "vs_baseline": round(rps / target, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
