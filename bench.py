"""Benchmark: gossip-simulator round throughput.

Prints JSON lines ``{"metric", "value", "unit", "vs_baseline", ...}`` —
the LAST line is the round's measurement. (The supervisor is
write-first: the first line is the best-known cached/reserve record so a
driver kill at any point leaves a parsed result; a completed run prints
its fresh measurement last. Consumers must parse the final JSON line.)

North-star (BASELINE.md): >=10,000 simulated gossip rounds/sec at 100k
nodes on a v5e-8. The bench runs the fused whole-cluster round at the
north-star scale — the bounded member-table simulator (``sim/scale_step``:
SWIM + piggybacked changeset broadcast + anti-entropy sync, O(N*M) state)
— under ``lax.scan`` and reports steady-state rounds/sec; ``vs_baseline``
is the fraction of the 10k rounds/sec target.

Robustness (round-1 post-mortem: the TPU backend failed to initialize
once and the whole round shipped with rc=1 and no number): the module is
a supervisor/worker pair. The supervisor (default entry) runs the actual
measurement in a *subprocess* (``BENCH_WORKER=1``) so a backend-init
crash never takes out the parent; it retries TPU attempts with backoff,
degrades the node count, and finally falls back to CPU at reduced N. It
ALWAYS leaves at least one parseable JSON line on stdout — on total
failure an explicit diagnostic record with ``value=0.0`` — and exits 0
unless even the diagnostic cannot be produced. Diagnostics go to stderr.

Pipeline provenance (ISSUE 4): every record carries ``donated`` (the
scan carry dispatched through ``donate_argnums`` and the input buffers
were consumed) and ``sharded`` (device count of the node-axis mesh the
state was placed on; 1 = single device). ``BENCH_SMOKE=1`` runs the
CPU-budget pipeline check instead of a measurement (see ``_smoke``).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

TARGET_RPS = 10_000.0

# Every successful measurement is persisted here; the supervisor prints
# the cached record as its FIRST stdout line on the next run, so a
# driver kill at ANY point still leaves a parsed record (round-3
# post-mortem: the driver killed the supervisor during probe#0 and the
# round shipped rc=124 with parsed=null).
CACHE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "artifacts", "bench_last.json"
)


def _git_head() -> str:
    """Short HEAD hash for provenance; "" when unavailable."""
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        ).stdout.strip()
    except Exception:  # noqa: BLE001 — provenance is best-effort
        return ""


def _load_cache() -> dict | None:
    try:
        with open(CACHE_PATH) as f:
            rec = json.load(f)
        if "metric" in rec and "value" in rec:
            return rec
    except (OSError, json.JSONDecodeError):
        pass
    return None


def _rank(rec: dict) -> tuple:
    """Cache precedence: any TPU record beats any CPU record; larger N
    beats smaller at the same platform; freshness wins ties (caller
    overwrites on >=)."""
    is_tpu = rec.get("platform") not in (None, "cpu")
    import re

    m = re.search(r"_n(\d+)_", str(rec.get("metric", "")))
    return (1 if is_tpu else 0, int(m.group(1)) if m else 0)


def _save_cache(rec: dict) -> None:
    """Atomic write so a kill mid-save never corrupts the cache; never
    downgrades (a small-N CPU reserve must not evict a real TPU record).
    Records carry when/what-code they measured, so a cached number
    re-reported rounds later is visibly stale rather than silently
    current."""
    old = _load_cache()
    if old is not None and _rank(rec) < _rank(old):
        return
    rec = dict(rec)
    rec.setdefault(
        "measured_at", time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime())
    )
    head = _git_head()
    if head:
        rec.setdefault("measured_commit", head)
    try:
        os.makedirs(os.path.dirname(CACHE_PATH), exist_ok=True)
        tmp = CACHE_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, CACHE_PATH)
    except OSError as exc:  # cache is best-effort; never fail the bench
        print(f"bench cache write failed: {exc}", file=sys.stderr)


def _emit(rec: dict) -> None:
    print(json.dumps(rec), flush=True)


# --------------------------------------------------------------------------
# worker: the actual measurement (runs in a subprocess)
# --------------------------------------------------------------------------


def _probe() -> None:
    """Tiny worker: init the backend + run one op. Proves the TPU tunnel
    is alive without paying the full bench compile."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    import jax.numpy as jnp

    x = jnp.ones((256, 256))
    (x @ x).block_until_ready()
    print(json.dumps({"metric": "probe", "value": 1.0,
                      "platform": jax.devices()[0].platform}))


def _worker() -> None:
    import functools

    import jax

    # this environment's sitecustomize forces a platform via config.update,
    # which outranks the JAX_PLATFORMS env var — re-honor the env var
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    import jax.numpy as jnp
    import jax.random as jr

    from corrosion_tpu.sim.scale_step import (
        ScaleSimState,
        make_write_inputs,
        scale_run_rounds,
        scale_sim_config,
    )
    from corrosion_tpu.sim.transport import NetModel

    platform = jax.devices()[0].platform
    on_tpu = platform != "cpu"  # the axon tunnel reports its own name
    # scan length 8: the tunnel's remote-compile service drops the
    # connection on the 100-round scanned program (observed: "response
    # body closed before all bytes were read"); 8 compiles reliably and
    # reps amortize dispatch overhead instead
    n_nodes = int(os.environ.get("BENCH_NODES", 100_000 if on_tpu else 256))
    rounds = int(os.environ.get("BENCH_ROUNDS", 8 if on_tpu else 4))
    reps = int(os.environ.get("BENCH_REPS", 12 if on_tpu else 2))

    # workload shape knobs (VERDICT r2: the flagship's CRDT working set
    # was 16 origins x 64 cells — unrepresentatively tiny): the writer
    # pool and store shape are env-tunable so the capture can also run
    # heavier mixes (e.g. BENCH_ORIGINS=256 BENCH_ROWS=64)
    n_origins = min(int(os.environ.get("BENCH_ORIGINS", "16")), n_nodes)
    overrides = dict(
        n_origins=n_origins,
        n_rows=int(os.environ.get("BENCH_ROWS", "16")),
        n_cols=int(os.environ.get("BENCH_COLS", "4")),
        # bounded piggyback A/B (BENCH_PIG_MEMBERS=16): ~4x less channel
        # HBM traffic, entry merges move into the pallas kernel's VMEM
        pig_members=int(os.environ.get("BENCH_PIG_MEMBERS", "0")),
    )
    # A/B knobs for the landed traffic cuts; only forwarded when the
    # config actually defines the field (so an arm run against an older
    # library errors loudly in the record, not with a TypeError crash)
    import dataclasses as _dc

    from corrosion_tpu.sim.scale_step import ScaleSimConfig as _Cfg

    fields = {f.name for f in _dc.fields(_Cfg)}
    if os.environ.get("BENCH_SYNC_PULL"):
        # =10 widens the pull set back to the whole scoring pool (the
        # pre-cut behavior)
        overrides["sync_pull_peers"] = int(os.environ["BENCH_SYNC_PULL"])
    if os.environ.get("BENCH_NARROW"):
        # =0 keeps wide int32 planes
        overrides["narrow_dtypes"] = os.environ["BENCH_NARROW"] != "0"
    if os.environ.get("BENCH_NARROW8"):
        # =1 stores mem_tx as int8 (ISSUE 12, the corrobudget shrink;
        # requires the narrow arm — docs/memory-budget.md)
        overrides["narrow_int8"] = os.environ["BENCH_NARROW8"] == "1"
    if os.environ.get("BENCH_TX_CELLS"):
        # >1 routes writes through K-cell chunked transactions (the
        # partial-buffer path, change.rs:66-178 + util.rs:1061-1194)
        overrides["tx_max_cells"] = int(os.environ["BENCH_TX_CELLS"])
    if os.environ.get("BENCH_FUSED"):
        # fused-path arm (ISSUE 10): auto/on/off/interpret — the
        # execution knob the sim config threads to ops/megakernel.py;
        # "interpret" is the CPU-parity arm, "off" the XLA A/B arm
        overrides["fused"] = os.environ["BENCH_FUSED"]
    if os.environ.get("BENCH_QUIET"):
        # quiescence arm (ISSUE 19): auto/on/off — "on" swaps the scan
        # body to the lax.cond active-set round; "auto" (the default)
        # is host-resolved per segment and runs dense inside one scan
        overrides["quiet"] = os.environ["BENCH_QUIET"]
    unknown = [k for k in overrides if k not in fields]
    for k in unknown:
        del overrides[k]
    cfg = scale_sim_config(n_nodes, **overrides)
    key = jr.key(0)
    st = ScaleSimState.create(cfg)
    net = NetModel.create(n_nodes, drop_prob=0.01)
    # HBM footprint of the scan carry (ISSUE 11): array metadata only —
    # the first number of the 1M memory-budget audit, carried on every
    # bench record so N sweeps chart bytes next to rounds/s. The
    # _projected_1m twin (ISSUE 12) is corrobudget's STATIC projection
    # of the SAME config's table set at N=1M (docs/memory-budget.md),
    # so every record also prices the run against the flagship point
    from corrosion_tpu.obs.memory import projected_bytes, state_bytes

    hbm_bytes = state_bytes(st)
    # ISSUE 19 scale-sweep wiring: also project at the RUN's own N —
    # measured and projected price the same point, so they must agree
    # exactly (the scale_sweep.py rung gate, carried on every record)
    hbm_bytes_projected = projected_bytes(cfg, n_nodes)
    hbm_bytes_projected_1m = projected_bytes(cfg, 1_000_000)

    # node-axis sharding over every visible device (the flagship
    # multi-chip path): state/net/inputs get P("node") placements and
    # the SAME jitted scan below runs unchanged across the mesh.
    # BENCH_SHARD=0 forces single-device; BENCH_MESH_HOSTS=H selects the
    # 2-D (dcn, node) multi-host mesh shape.
    n_devices = len(jax.devices())
    mesh = None
    sharded = 1
    if (os.environ.get("BENCH_SHARD", "1") != "0"
            and n_devices > 1 and n_nodes % n_devices == 0):
        from corrosion_tpu.parallel.mesh import (
            make_mesh,
            make_multihost_mesh,
            shard_state,
        )

        mesh_hosts = int(os.environ.get("BENCH_MESH_HOSTS", "0"))
        mesh = (make_multihost_mesh(mesh_hosts) if mesh_hosts > 1
                else make_mesh())
        sharded = n_devices

    # conflict-heavy inputs: writers hit hot cells at random rounds.
    # BENCH_WRITERS (round 4, unbounded writer set): how many ACTIVE
    # writers, spread across the whole id space — distinct from
    # n_origins, which now sizes the per-node bookkeeping slot table.
    # Default: the legacy shape (first n_origins nodes write).
    k1, k2, k4 = jr.split(jr.key(1), 3)
    n_writers = int(os.environ.get("BENCH_WRITERS", "0"))
    if n_writers > 0 and getattr(cfg, "any_writer", False):
        writer_ids = jr.choice(
            k4, n_nodes, (min(n_writers, n_nodes),), replace=False
        )
        is_writer = jnp.zeros(n_nodes, bool).at[writer_ids].set(True)
    else:
        is_writer = jnp.arange(n_nodes) < cfg.n_origins
    w = (jr.uniform(k1, (rounds, n_nodes)) < 0.25) & is_writer[None, :]
    # shared construction (routes through K-cell chunked txs when
    # BENCH_TX_CELLS>1 — the partial-buffer path, VERDICT r4 next #5)
    inputs = make_write_inputs(cfg, k2, rounds, w)

    if mesh is not None:
        st = shard_state(mesh, n_nodes, st)
        net = shard_state(mesh, n_nodes, net)
        inputs = shard_state(mesh, n_nodes, inputs)

    from corrosion_tpu.parallel.mesh import buffers_donated
    from corrosion_tpu.ops import megakernel

    # hoist the fused-path probes out of the warm call's trace: under
    # "auto" on TPU this runs the tiny differential + width probes once,
    # eagerly, BEFORE the sharded dispatch compiles (docs/fused.md)
    fused_dec = megakernel.prime_fused(cfg)

    run = jax.jit(functools.partial(scale_run_rounds, cfg), donate_argnums=(0,))
    probe = st  # donation probe: the warm call must consume these buffers
    st = jax.block_until_ready(run(st, net, key, inputs))[0]  # compile + warm
    donated = buffers_donated(probe)
    del probe

    t0 = time.perf_counter()
    for i in range(reps):
        st, infos = run(st, net, jr.fold_in(key, i), inputs)
    jax.block_until_ready(st)
    dt = time.perf_counter() - t0

    # corrocost static provenance (ISSUE 20, after the timed loop so the
    # abstract traces never pollute the measurement): the per-round flop
    # price of this SAME config family — fitted over the extents,
    # checked against the direct jaxpr count at the run's own N, and
    # projected to the flagship 1M point — plus the compiled sharded
    # program's cross-shard bytes. BENCH_COST=0 skips all of it (tight
    # TPU capture windows); failures degrade to None, never kill a
    # finished measurement.
    flops_per_round = flops_projected_1m = None
    flops_projection_agrees = None
    collective_bytes_per_round = None
    if os.environ.get("BENCH_COST", "1") != "0":
        from corrosion_tpu.analysis import collectives as _coll
        from corrosion_tpu.analysis import cost as _cost

        try:
            env = {"N": n_nodes, "M": cfg.m_slots}
            fit = _cost.fit_for_config(cfg)["flops"]
            direct = _cost.price_per_round(
                "sharded_scale_run", env, template=cfg)
            flops_per_round = direct.flops
            flops_projected_1m = fit.at(
                {"N": 1_000_000, "M": cfg.m_slots})
            pred = fit.at(env)
            if cfg.fused in ("on", "interpret"):
                # pallas grids are ceil-divisions: piecewise fit, so
                # the agreement gate is a tolerance, not bit-equality
                flops_projection_agrees = (
                    abs(pred - direct.flops) <= direct.flops // 1000)
            else:
                flops_projection_agrees = pred == direct.flops
        except Exception:  # noqa: BLE001 — provenance, not the payload
            pass
        if mesh is not None:
            collective_bytes_per_round = _coll.projected_collective_bytes(
                cfg, mesh)

    rps = reps * rounds / dt
    rec = {
                "metric": (
                    f"gossip_rounds_per_sec_n{n_nodes}_"
                    f"{'tpu' if on_tpu else 'cpu'}"
                ),
                "value": round(rps, 2),
                "unit": "rounds/s",
                "vs_baseline": round(rps / TARGET_RPS, 4),
                "platform": platform,
                "n_origins": cfg.n_origins,
                "n_writers": int(jnp.sum(is_writer)),
                "n_rows": cfg.n_rows,
                "n_cols": cfg.n_cols,
                "pig_members": cfg.pig_members,
                "tx_max_cells": cfg.tx_max_cells,
                # which pipeline produced this number (ISSUE 4): a record
                # measured without donation (duplicate carry in HBM) or
                # on one chip is not comparable to the sharded flagship
                "donated": donated,
                "sharded": sharded,
                # the scan carry's HBM bytes (per-table audit:
                # `corrosion-tpu mem-report`; obs/memory.py) + the
                # static 1M projection of the same table set
                "hbm_bytes": hbm_bytes,
                "hbm_bytes_projected": hbm_bytes_projected,
                "hbm_projection_agrees": hbm_bytes == hbm_bytes_projected,
                "hbm_bytes_projected_1m": hbm_bytes_projected_1m,
                # corrocost provenance (ISSUE 20, docs/corrolint.md):
                # static per-round flop price at this run's own (N, M)
                # (must agree with the fitted polynomial — the smoke
                # gate), its flagship 1M projection, and the compiled
                # sharded program's cross-shard bytes for one round
                # (None off-mesh or under BENCH_COST=0)
                "flops_per_round": flops_per_round,
                "flops_projected_1m": flops_projected_1m,
                "flops_projection_agrees": flops_projection_agrees,
                "collective_bytes_per_round": collective_bytes_per_round,
                # loud fused-path visibility (VERDICT r2 weak #2): a TPU
                # record measured on the XLA fallback is flagged, not
                # silently reported as if it were the pallas path —
                # shape-aware (prime_fused probed the real widths), and
                # carried truthfully through the sharded runner: these
                # are the SAME gate decisions the traced step consulted
                "pallas_fused": megakernel.fused_engaged(fused_dec),
                # the knob + whether the kernels ran interpreted — an
                # interpret-mode record must never read as a real
                # pallas-lowered number
                "fused_mode": cfg.fused,
                "fused_interpret": fused_dec["interpret"],
                # quiescence-path provenance (ISSUE 19): which round
                # variant the scan body compiled with — a quiet="on"
                # number on a busy trace pays the cond overhead and is
                # not comparable to the dense headline
                "quiet_mode": cfg.quiet,
    }
    if unknown:
        rec["dropped_overrides"] = unknown
    # direct worker runs (tunnel sessions use BENCH_WORKER=1) must seed
    # the supervisor's write-first cache too — but only default-config
    # measurements, so an A/B arm's record never becomes the headline
    try:
        is_default = cfg == scale_sim_config(
            n_nodes, n_origins=min(16, n_nodes)
        )
    except Exception:  # noqa: BLE001 — never lose a finished measurement
        is_default = False
    if is_default:
        _save_cache(rec)
    print(json.dumps(rec))


# --------------------------------------------------------------------------
# smoke: CPU-budget pipeline regression check (BENCH_SMOKE=1)
# --------------------------------------------------------------------------


def _smoke() -> None:
    """In-process CPU smoke bench with a hard deadline, always rc=0.

    Not a throughput number — a *pipeline* check cheap enough for tier-1:
    it proves (a) the scale bench path dispatches with buffer donation
    active (no duplicate carry allocation — a lost ``donate_argnums``
    shows up as ``donated: false``), and (b) the segmented soak's
    per-segment checkpoint stall is the host drain only, with
    serialization/hash/IO overlapped onto the background writer
    (``ckpt_stall_s`` ≪ ``ckpt_io_s``). Accidental host syncs or a lost
    donation regress these fields long before a TPU capture would."""
    t_start = time.perf_counter()
    deadline_s = float(os.environ.get("BENCH_SMOKE_DEADLINE_S", "240"))

    import tempfile

    import jax

    jax.config.update("jax_platforms", "cpu")
    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    import functools

    import jax.random as jr

    from corrosion_tpu.resilience.segments import (
        make_soak_inputs,
        run_segmented,
    )
    from corrosion_tpu.sim.scale_step import (
        ScaleSimState,
        make_write_inputs,
        scale_run_rounds,
        scale_sim_config,
    )
    from corrosion_tpu.sim.transport import NetModel

    n_nodes = int(os.environ.get("BENCH_NODES", "768"))
    rounds = int(os.environ.get("BENCH_ROUNDS", "4"))
    overrides = {}
    if os.environ.get("BENCH_FUSED"):
        # fused-path smoke arm (ISSUE 10): BENCH_FUSED=interpret runs
        # the pallas megakernels interpreted through the WHOLE pipeline
        # below (donated scan, sharded segmented soak, per-shard
        # checkpoint drain) and additionally gates fused==unfused
        # parity on this run's workload
        overrides["fused"] = os.environ["BENCH_FUSED"]
    if os.environ.get("BENCH_QUIET"):
        # quiescence knob for the busy legs below (ISSUE 19); the
        # dedicated quiet-trace arm (a'') always runs its own on/off
        # A/B regardless
        overrides["quiet"] = os.environ["BENCH_QUIET"]
    cfg = scale_sim_config(n_nodes, **overrides)
    net = NetModel.create(n_nodes, drop_prob=0.01)

    from corrosion_tpu.ops import megakernel

    fused_dec = megakernel.prime_fused(cfg)  # probes hoisted pre-trace
    pallas_fused = megakernel.fused_engaged(fused_dec)

    # --- (a) the bench hot path, donation probed -------------------------
    k1, k2 = jr.split(jr.key(1))
    import jax.numpy as jnp

    w = (jr.uniform(k1, (rounds, n_nodes)) < 0.25) \
        & (jnp.arange(n_nodes) < cfg.n_origins)[None, :]
    inputs = make_write_inputs(cfg, k2, rounds, w)
    from corrosion_tpu.parallel.mesh import buffers_donated

    run = jax.jit(functools.partial(scale_run_rounds, cfg),
                  donate_argnums=(0,))
    st = ScaleSimState.create(cfg)
    probe = st
    st = jax.block_until_ready(run(st, net, jr.key(0), inputs))[0]
    donated = buffers_donated(probe)
    del probe
    t0 = time.perf_counter()
    st, _ = run(st, net, jr.key(2), inputs)
    jax.block_until_ready(st)
    rps = rounds / (time.perf_counter() - t0)

    # --- (a') fused == unfused parity on this very workload --------------
    # only when a fused kernel actually engaged: replay the same
    # warm+timed sequence on the pinned XLA path and require bitwise
    # identity — the interpret-mode smoke (BENCH_FUSED=interpret) gates
    # the whole record on it
    fused_parity = None
    if pallas_fused:
        import dataclasses

        import numpy as np

        cfg_off = dataclasses.replace(cfg, fused="off").validate()
        run_off = jax.jit(functools.partial(scale_run_rounds, cfg_off),
                          donate_argnums=(0,))
        st_off = run_off(ScaleSimState.create(cfg_off), net, jr.key(0),
                         inputs)[0]
        st_off, _ = run_off(st_off, net, jr.key(2), inputs)
        jax.block_until_ready(st_off)
        fused_parity = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(jax.tree.leaves(st), jax.tree.leaves(st_off))
        )

    # --- (a'') quiescence arm (ISSUE 19): active-set vs dense ------------
    # the same fully-quiet trace through both round variants — only the
    # `quiet` knob differs. Gates (1) bitwise parity of the final
    # carries (the masked==dense oracle on the bench's own workload)
    # and (2) the >=3x per-round speedup the cheap fixpoint path exists
    # for. The net is clean here: a dropped probe marks the round
    # disturbed and honestly runs it dense, which is correct but leaves
    # nothing for a speedup smoke to measure.
    import dataclasses

    import numpy as np

    quiet_rounds = int(os.environ.get("BENCH_QUIET_ROUNDS", "48"))
    q_net = NetModel.create(n_nodes)
    q_inputs = make_write_inputs(
        cfg, jr.key(5), quiet_rounds,
        jnp.zeros((quiet_rounds, n_nodes), bool))
    q_rps = {}
    q_final = {}
    quiet_cheap = 0
    for label, mode in (("quiet", "on"), ("dense", "off")):
        c = dataclasses.replace(cfg, quiet=mode).validate()
        r = jax.jit(functools.partial(scale_run_rounds, c),
                    donate_argnums=(0,))
        s = jax.block_until_ready(
            r(ScaleSimState.create(c), q_net, jr.key(6), q_inputs))[0]
        t1 = time.perf_counter()
        s, q_infos = r(s, q_net, jr.key(7), q_inputs)
        jax.block_until_ready(s)
        q_rps[label] = quiet_rounds / (time.perf_counter() - t1)
        q_final[label] = s
        if label == "quiet":
            quiet_cheap = int(np.asarray(q_infos["quiet_round"]).sum())
    quiet_parity = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(q_final["quiet"]),
                        jax.tree.leaves(q_final["dense"])))
    del q_final
    quiet_speedup = q_rps["quiet"] / max(q_rps["dense"], 1e-9)

    # --- (b) segmented soak, overlapped checkpointing --------------------
    # sharded over every available device when the process has more
    # than one, so the record shows the per-shard checkpoint drain:
    # ckpt_shard_bytes_max must be a per-device slice of the carry, not
    # the whole state funneled through one host (ISSUE 9)
    soak_rounds = int(os.environ.get("BENCH_SMOKE_SOAK_ROUNDS", "12"))
    soak_inputs = make_soak_inputs(cfg, jr.key(3), soak_rounds,
                                   write_frac=0.25)
    soak_st = ScaleSimState.create(cfg)
    from corrosion_tpu.obs.memory import projected_bytes, state_bytes

    hbm_bytes = state_bytes(soak_st)
    hbm_bytes_projected = projected_bytes(cfg, n_nodes)
    hbm_bytes_projected_1m = projected_bytes(cfg, 1_000_000)
    soak_net = net
    n_devices = len(jax.devices())
    if n_devices > 1:
        from corrosion_tpu.parallel.mesh import make_mesh, shard_state

        mesh = make_mesh()
        soak_st = shard_state(mesh, n_nodes, soak_st)
        soak_net = shard_state(mesh, n_nodes, soak_net)
        soak_inputs = shard_state(mesh, n_nodes, soak_inputs)
    # the soak leg runs under the flight-recorder observability plane
    # (ISSUE 11): the smoke gates on the NDJSON replay agreeing with
    # the run's own stats and on the live corro.soak.* series advancing
    from corrosion_tpu.obs import (
        FlightRecorder,
        SoakObserver,
        replay_flight_record,
    )
    from corrosion_tpu.utils.metrics import Registry

    obs_registry = Registry()
    with tempfile.TemporaryDirectory() as tmp:
        obs = SoakObserver(
            flight=FlightRecorder(os.path.join(tmp, "flight.ndjson")),
            registry=obs_registry,
        )
        try:
            res = run_segmented(
                cfg, soak_st, soak_net, jr.key(4), soak_inputs,
                segment_rounds=max(1, soak_rounds // 4),
                checkpoint_root=tmp, obs=obs,
            )
        finally:
            obs.close()
        flight = replay_flight_record(obs.flight.path)
    stats = res.stats

    # --- (c) corrocost provenance + agreement gate (ISSUE 20) ------------
    # the static fit of THIS config family must reproduce the direct
    # jaxpr count at the smoke's own shape exactly — the cheapest
    # end-to-end proof that the committed 1M projections price the
    # program the smoke just ran. The sharded collective manifest is
    # skipped when the deadline is already crowded (compile-cache-cold
    # first runs); BENCH_COST=0 skips the whole leg.
    flops_per_round = flops_projected_1m = None
    flops_projection_agrees = None
    collective_bytes_per_round = None
    if os.environ.get("BENCH_COST", "1") != "0":
        from corrosion_tpu.analysis import collectives as _coll
        from corrosion_tpu.analysis import cost as _cost

        try:
            env = {"N": n_nodes, "M": cfg.m_slots}
            fit = _cost.fit_for_config(cfg)["flops"]
            direct = _cost.price_per_round(
                "sharded_scale_run", env, template=cfg)
            flops_per_round = direct.flops
            flops_projected_1m = fit.at(
                {"N": 1_000_000, "M": cfg.m_slots})
            pred = fit.at(env)
            if cfg.fused in ("on", "interpret"):
                flops_projection_agrees = (
                    abs(pred - direct.flops) <= direct.flops // 1000)
            else:
                flops_projection_agrees = pred == direct.flops
        except Exception:  # noqa: BLE001 — provenance, not the payload
            pass
        if (n_devices > 1
                and time.perf_counter() - t_start < 0.7 * deadline_s):
            collective_bytes_per_round = _coll.projected_collective_bytes(
                cfg, mesh)

    elapsed = time.perf_counter() - t_start
    problems = []
    if not donated:
        problems.append("scale bench dispatch lost buffer donation")
    if stats.get("donated_segments", 0) < 1:
        problems.append("soak segments ran un-donated")
    if not stats.get("async_checkpoint"):
        problems.append("async checkpoint writer did not engage")
    if stats.get("ckpt_stall_s", 0.0) >= stats.get("ckpt_io_s", 0.0):
        # the check the smoke exists for: serialization/hash/IO crept
        # back onto the hot loop (stall should be the memcpy drain only)
        problems.append("checkpoint stall not overlapped (stall >= io)")
    if n_devices > 1:
        if stats.get("ckpt_shards", 0) != n_devices:
            problems.append(
                f"checkpoint drained {stats.get('ckpt_shards', 0)} "
                f"shard(s) on a {n_devices}-device mesh"
            )
        else:
            # the whole point of the per-shard drain: no single shard
            # holds a whole checkpoint's state. drain_bytes accumulates
            # over ALL checkpoints while shard_bytes_max is per-segment,
            # so normalize to one checkpoint's drain before comparing
            per_ckpt = stats.get("ckpt_drain_bytes", 0) / max(
                1, stats.get("ckpt_written", 1))
            if stats.get("ckpt_shard_bytes_max", 0) >= per_ckpt > 0:
                problems.append("checkpoint drain did not split per shard")
    if fused_parity is False:
        # the gate the fused smoke exists for: the pallas kernels
        # diverged from the XLA path on this workload
        problems.append("fused != unfused on the smoke workload")
    if not quiet_parity:
        # the hard oracle of ISSUE 19: the active-set round must be
        # bitwise-indistinguishable from dense on any trace
        problems.append("quiet != dense on the quiet smoke trace")
    if quiet_speedup < 3.0:
        problems.append(
            f"quiet-trace speedup {quiet_speedup:.2f}x < 3x "
            f"({quiet_cheap}/{quiet_rounds} rounds cheap-pathed)")
    # observability-plane gates (ISSUE 11): the flight record must
    # replay to the same pipeline facts the live run reported, and the
    # bridge must have advanced the live soak series
    if flight["segments"] != stats.get("segments", 0):
        problems.append(
            f"flight record replayed {flight['segments']} segment(s), "
            f"run reported {stats.get('segments', 0)}"
        )
    if not flight["ended"] or flight["completed_rounds"] != res.completed_rounds:
        problems.append("flight record end state disagrees with the run")
    if obs_registry.get_counter("corro.soak.rounds_total") != float(
            res.completed_rounds):
        problems.append("live corro.soak.rounds_total did not advance")
    if pallas_fused != bool(stats.get("pallas_fused")):
        problems.append(
            "segmented soak and bench path disagree about the fused "
            f"gate ({stats.get('pallas_fused')} vs {pallas_fused})"
        )
    if hbm_bytes != hbm_bytes_projected:
        problems.append(
            f"measured HBM {hbm_bytes} != static projection "
            f"{hbm_bytes_projected} at N={n_nodes} (scale-sweep gate)"
        )
    if flops_projection_agrees is False:
        # the corrocost smoke gate (ISSUE 20): the committed fit must
        # price the program the smoke actually dispatched
        problems.append(
            f"static flop projection disagrees with the jaxpr count "
            f"at N={n_nodes} (corrocost gate)"
        )
    if elapsed > deadline_s:
        problems.append(f"deadline exceeded: {elapsed:.0f}s > {deadline_s:.0f}s")
    rec = {
        "metric": f"bench_smoke_n{n_nodes}_cpu",
        "value": round(rps, 2),
        "unit": "rounds/s",
        "ok": not problems,
        "donated": donated,
        # the device count the SOAK leg ran on (the bench leg is
        # single-device by construction): with >1 devices the soak
        # shards over the whole mesh and the per-shard drain telemetry
        # below must show it
        "sharded": n_devices,
        # fused-path provenance (ISSUE 10): knob, engagement, interpret
        # mode, and the parity verdict (null = no fused kernel engaged)
        "pallas_fused": pallas_fused,
        "fused_mode": cfg.fused,
        "fused_interpret": fused_dec["interpret"],
        "fused_parity": fused_parity,
        # quiescence-path provenance + the quiet-trace A/B (ISSUE 19):
        # the busy legs above ran under `quiet_mode`; the `quiet` block
        # is the dedicated on/off A/B on a fully quiet trace
        "quiet_mode": cfg.quiet,
        "quiet": {
            "rounds": quiet_rounds,
            "cheap_rounds": quiet_cheap,
            "rps_quiet": round(q_rps["quiet"], 2),
            "rps_dense": round(q_rps["dense"], 2),
            "speedup": round(quiet_speedup, 2),
            "parity": quiet_parity,
        },
        "hbm_bytes": hbm_bytes,
        "hbm_bytes_projected": hbm_bytes_projected,
        "hbm_projection_agrees": hbm_bytes == hbm_bytes_projected,
        "hbm_bytes_projected_1m": hbm_bytes_projected_1m,
        # corrocost provenance (ISSUE 20): static per-round flop price
        # at the smoke shape (gated == the direct jaxpr count above),
        # its flagship 1M projection, and the sharded program's
        # cross-shard bytes per round (None single-device / skipped)
        "flops_per_round": flops_per_round,
        "flops_projected_1m": flops_projected_1m,
        "flops_projection_agrees": flops_projection_agrees,
        "collective_bytes_per_round": collective_bytes_per_round,
        # flight-record replay facts (ISSUE 11): proves the soak leg
        # left a parseable NDJSON whose summary matches the live stats
        "flight": {
            "segments": flight["segments"],
            "completed_rounds": flight["completed_rounds"],
            "rounds_per_s": flight["rounds_per_s"],
            "ended": flight["ended"],
            "skipped_lines": flight["skipped_lines"],
        },
        "elapsed_s": round(elapsed, 2),
        "deadline_s": deadline_s,
        "soak": {
            "segments": stats.get("segments", 0),
            "donated_segments": stats.get("donated_segments", 0),
            "async_checkpoint": bool(stats.get("async_checkpoint")),
            # the segment dispatch's own fused-gate record: the soak leg
            # must ride the same path the bench leg reported
            "fused_mode": stats.get("fused_mode", "auto"),
            "pallas_fused": bool(stats.get("pallas_fused")),
            "ckpt_stall_s": round(stats.get("ckpt_stall_s", 0.0), 4),
            "ckpt_io_s": round(stats.get("ckpt_io_s", 0.0), 4),
            "ckpt_written": stats.get("ckpt_written", 0),
            "ckpt_overlapped_segments": stats.get(
                "ckpt_overlapped_segments", 0),
            # per-shard drain telemetry (ISSUE 9): the largest single
            # shard's drained bytes vs the total — a per-device slice
            # of the carry, not the whole state through one host
            "ckpt_shards": stats.get("ckpt_shards", 0),
            "ckpt_drain_bytes": stats.get("ckpt_drain_bytes", 0),
            "ckpt_shard_bytes_max": stats.get("ckpt_shard_bytes_max", 0),
            "ckpt_serialize_s": round(
                stats.get("ckpt_serialize_s", 0.0), 4),
        },
    }
    if problems:
        rec["problems"] = problems
    _emit(rec)


# --------------------------------------------------------------------------
# supervisor: retry ladder, CPU fallback, never-empty output
# --------------------------------------------------------------------------


def _attempt(env_extra: dict, timeout_s: float,
             probe: bool = False) -> tuple[dict | None, str]:
    """Run the worker in a subprocess; return (parsed JSON or None, err)."""
    env = dict(os.environ)
    env.update(env_extra)
    env["BENCH_PROBE" if probe else "BENCH_WORKER"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout_s:.0f}s"
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-12:]
        return None, f"rc={proc.returncode}: " + " | ".join(tail)
    for line in reversed((proc.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
                if "metric" in rec and "value" in rec:
                    return rec, ""
            except json.JSONDecodeError:
                continue
    return None, "worker produced no JSON line"


def main() -> None:
    """Write-first supervisor (round-3 post-mortem: the TPU-or-bust
    ladder's 5400 s internal deadline exceeded the driver's kill budget,
    the driver killed it during probe#0, and the round shipped rc=124
    with NO record at all).

    Strategy: before pursuing anything, put a best-known record on
    stdout — the cached last success (``artifacts/bench_last.json``,
    updated by every successful run, including in-session tunnel runs)
    or, lacking one, a fast small-N CPU reserve. Only then pursue TPU
    within the deadline budget (``BENCH_DEADLINE_S``, also capped by
    ``BENCH_DRIVER_BUDGET_S`` if the driver exports one); any success is
    printed as a NEWER (last) JSON line and cached. A driver kill at any
    point leaves the first line parseable; a completed run's last line
    is the best measurement available."""
    want_platform = os.environ.get("JAX_PLATFORMS", "")

    def _env_f(var: str) -> float | None:
        # a malformed driver-supplied value must never crash before the
        # write-first record is out
        try:
            return float(os.environ[var])
        except (KeyError, ValueError):
            return None

    # Default budget is sized to the DRIVER's observed kill window
    # (round-4 post-mortem: rc=124 for the second round running — the
    # old 5400 s default guaranteed a kill whenever the tunnel was slow,
    # and the parsed record was forever the stale cache). ~270 s fits
    # inside a ~300 s capture with margin; in-session tunnel runs that
    # want the patient retry ladder export BENCH_DEADLINE_S explicitly.
    budget_s = _env_f("BENCH_DEADLINE_S") or 270.0
    for var in ("BENCH_DRIVER_BUDGET_S", "DRIVER_BUDGET_S"):
        v = _env_f(var)
        if v is not None:
            budget_s = min(budget_s, v - 60.0)
    budget_s = max(budget_s, 120.0)
    patient = budget_s > 900.0
    deadline = time.time() + budget_s
    cpu_reserve = 900.0 if patient else 0.0

    def remaining() -> float:
        return deadline - time.time()

    errors: list[str] = []
    emitted: list[dict] = []

    def finish(rec: dict) -> None:
        if errors:
            rec = dict(rec)
            rec["attempts_failed"] = errors
        _emit(rec)

    # ---- write-first: a parsed record exists before any TPU pursuit ----
    cached = _load_cache()
    if (
        cached is not None
        and want_platform == "cpu"
        and cached.get("platform") != "cpu"
    ):
        # an explicitly-CPU run must not report a stale TPU record (nor
        # let it suppress the CPU fallback below)
        cached = None
    if cached is not None:
        first = dict(cached)
        first["cached"] = True
        _emit(first)
        emitted.append(first)
    elif want_platform == "cpu":
        # no insurance reserve needed: cpu#0 below cannot hang on the
        # tunnel, and the reserve would be the identical measurement
        pass
    else:
        # no cache: buy insurance with a fast small-N CPU run before the
        # (possibly hung) tunnel gets a chance to eat the whole budget
        rec, err = _attempt(
            {
                "JAX_PLATFORMS": "cpu",
                "BENCH_NODES": "256",
                "BENCH_ROUNDS": "8",
                "BENCH_REPS": "2",
            },
            # non-patient: cap the insurance at a third of the window so
            # probe + full still fit after a slow reserve
            min(700.0, max(120.0, remaining() - 120.0)) if patient
            else max(90.0, remaining() / 3.0),
        )
        if rec is not None:
            rec["reserve"] = True
            _save_cache(rec)
            _emit(rec)
            emitted.append(rec)
        else:
            errors.append(f"cpu-quick-reserve: {err[:300]}")
            # even total reserve failure must leave a parsed line
            _emit(
                {
                    "metric": "gossip_rounds_per_sec_unavailable",
                    "value": 0.0,
                    "unit": "rounds/s",
                    "vs_baseline": 0.0,
                    "error": "quick reserve failed; pursuing TPU",
                }
            )

    def try_one(label: str, env_extra: dict, timeout_s: float,
                probe: bool = False, is_reserve: bool = False):
        # TPU rungs leave the CPU reserve untouched; the fallback itself
        # spends the reserve
        budget = remaining() if is_reserve else remaining() - cpu_reserve
        timeout_s = min(timeout_s, max(60.0, budget))
        t0 = time.time()
        rec, err = _attempt(env_extra, timeout_s, probe=probe)
        if rec is None:
            msg = f"attempt {label} failed after {time.time() - t0:.0f}s: {err}"
            print(msg, file=sys.stderr)
            errors.append(f"{label}: {err[:300]}")
        return rec

    if want_platform == "cpu":
        rec = try_one("cpu#0", {}, 1500.0)
        if rec is not None:
            _save_cache(rec)
            return finish(rec)
    else:
        # TPU pursuit: (probe?, label, env, timeout, sleep_after_failure)
        if patient:
            plan = [
                (True, "probe#0", {}, 300.0, 30.0),
                (False, "full#0", {}, 1600.0, 60.0),
                (True, "probe#1", {}, 300.0, 60.0),
                (False, "degraded-50k", {"BENCH_NODES": "50000"}, 1200.0,
                 120.0),
                (True, "probe#2", {}, 450.0, 120.0),
                (False, "full#1", {}, 1600.0, 120.0),
                (True, "probe#3", {}, 600.0, 60.0),
                (False, "degraded-25k",
                 {"BENCH_NODES": "25000", "BENCH_REPS": "8"}, 1200.0, 60.0),
                (False, "full#2", {}, 1600.0, 0.0),
            ]
        else:
            # driver-window plan (VERDICT r4 next #1): one short probe,
            # then straight to the measurement — the persistent compile
            # cache (warmed by in-session tunnel runs at the same
            # commit's shapes) makes the full attempt dispatch-only, so
            # probe(~40 s init) + full(~60-90 s) fits ~270 s. A dead
            # tunnel costs only the 90 s probe; the cached record is
            # already on stdout and the process exits 0 well inside the
            # driver's kill window instead of eating SIGKILL at rc=124.
            # COLD-cache ordering: when the cached record was measured
            # at a different commit, the 100k executable is almost
            # certainly uncached and its ~195 s compile cannot fit —
            # bank a fresh small-N TPU number FIRST (fast compile),
            # then attempt 100k with whatever window remains.
            head = _git_head()
            # fresh AND full-N: a banked fresh-25k record at this commit
            # must not imply the 100k executable is cached
            cache_fresh = (
                cached is not None
                and cached.get("platform") not in (None, "cpu")
                and head
                and cached.get("measured_commit") == head
                and f"_n{os.environ.get('BENCH_NODES', '100000')}_"
                in str(cached.get("metric", ""))
            )
            if cache_fresh:
                plan = [
                    (True, "probe#0", {}, 90.0, 0.0),
                    (False, "full#0", {},
                     max(120.0, remaining() - 120.0), 0.0),
                    (False, "degraded-25k",
                     {"BENCH_NODES": "25000", "BENCH_REPS": "8"},
                     max(90.0, remaining() - 210.0), 0.0),
                ]
            else:
                plan = [
                    (True, "probe#0", {}, 90.0, 0.0),
                    (False, "fresh-25k",
                     {"BENCH_NODES": "25000", "BENCH_REPS": "8"},
                     max(100.0, remaining() - 150.0), 0.0),
                    (False, "full#0", {},
                     max(90.0, remaining() - 260.0), 0.0),
                ]
        def probe_says_tpu(label, env_extra, timeout_s) -> bool:
            rec = try_one(label, env_extra, timeout_s, probe=True)
            if rec is None:
                return False
            plat = rec.get("platform")
            if plat in (None, "cpu") and not want_platform:
                # jax silently fell back to its CPU backend: a full
                # "auto" run would measure an incomparable small-N CPU
                # number and mask the TPU outage
                errors.append(
                    f"{label}: initialized platform {plat!r}, not TPU"
                )
                return False
            return True

        def full_attempt(label, env_extra, timeout_s):
            rec = try_one(label, env_extra, timeout_s)
            if rec is not None and (
                rec.get("platform") == "cpu" and not want_platform
            ):
                errors.append(f"{label}: worker ran on cpu backend, not TPU")
                return None
            return rec

        probe_ok = True
        for is_probe, label, env_extra, timeout_s, sleep_s in plan:
            if remaining() <= cpu_reserve + (120.0 if patient else 75.0):
                errors.append(f"{label}: skipped, deadline budget exhausted")
                break
            if not patient and not is_probe and not probe_ok:
                # non-patient fast exit (code review r5): a dead tunnel
                # costs only the probe — the cached record is already on
                # stdout and hanging a full attempt would spend the
                # driver's kill window for nothing
                errors.append(f"{label}: skipped, probe saw no TPU")
                break
            if is_probe:
                ok = probe_says_tpu(label, env_extra, timeout_s)
                probe_ok = ok
            else:
                # degraded rungs run whenever reached — a full-N attempt
                # already failed by then, and the failure may be
                # N-dependent (timeout/OOM) even on a healthy tunnel
                rec = full_attempt(label, env_extra, timeout_s)
                if rec is not None:
                    _save_cache(rec)
                    if label == "fresh-25k":
                        # emit it and still try 100k in the remaining
                        # window (code review r5: returning here would
                        # leave 100k forever unmeasured at new commits);
                        # if 100k fails, the tail re-emits emitted[-1]
                        # — this record — as the final line
                        _emit(rec)
                        emitted.append(rec)
                        continue
                    return finish(rec)
                ok = False
            # sleep after ANY failed rung: the tunnel has been observed
            # to hang >9 min and then recover — give it time
            if not ok and sleep_s and remaining() > cpu_reserve + sleep_s:
                time.sleep(sleep_s)

        # recovery loop: the plan burned ~30 min at most; spend whatever
        # deadline budget remains alternating probe -> full attempt so a
        # tunnel that comes back late in the window still yields a TPU
        # record (compilation is cached, so retries are cheap)
        r = 0
        while remaining() > cpu_reserve + 720.0:
            r += 1
            if probe_says_tpu(f"probe#r{r}", {}, 300.0):
                rec = full_attempt(f"full#r{r}", {}, 1600.0)
                if rec is not None:
                    _save_cache(rec)
                    return finish(rec)
            if remaining() > cpu_reserve + 720.0:
                time.sleep(240.0)

    # TPU pursuit failed. The first stdout line already carries the
    # best-known record; only print MORE if it genuinely improves on what
    # is out there (the driver parses the LAST json line of a completed
    # run, so a worse trailing record would mask a better cached one).
    have_tpu = any(
        r.get("platform") not in (None, "cpu") and r.get("value", 0) > 0
        for r in emitted
    )
    have_full_cpu = any(
        r.get("platform") == "cpu"
        and r.get("value", 0) > 0
        and "n256_" not in str(r.get("metric", ""))
        for r in emitted
    )
    if not have_tpu and not have_full_cpu and remaining() > 180.0:
        rec = try_one(
            "cpu-fallback",
            {
                "JAX_PLATFORMS": "cpu",
                "BENCH_NODES": os.environ.get("BENCH_CPU_NODES", "4096"),
                "BENCH_ROUNDS": "8",
                "BENCH_REPS": "2",
            },
            1200.0,
            is_reserve=True,
        )
        if rec is not None:
            _save_cache(rec)
            return finish(rec)

    if not emitted:
        # total failure: explicit diagnostic record, never an empty round
        finish(
            {
                "metric": "gossip_rounds_per_sec_unavailable",
                "value": 0.0,
                "unit": "rounds/s",
                "vs_baseline": 0.0,
                "error": "all bench attempts failed",
            }
        )
    elif errors:
        # pursuit failed but a cached/reserve record stands: re-emit it
        # WITH the attempt log so the outage is visible in the parsed
        # record, not just on stderr (same record, so last-line parsing
        # loses nothing)
        finish(dict(emitted[-1]))


if __name__ == "__main__":
    if os.environ.get("BENCH_PROBE"):
        _probe()
    elif os.environ.get("BENCH_SMOKE"):
        _smoke()
    elif os.environ.get("BENCH_WORKER"):
        _worker()
    else:
        main()
