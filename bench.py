"""Benchmark: gossip-simulator round throughput.

Prints ONE JSON line ``{"metric", "value", "unit", "vs_baseline", ...}``.

North-star (BASELINE.md): >=10,000 simulated gossip rounds/sec at 100k
nodes on a v5e-8. The bench runs the fused whole-cluster round at the
north-star scale — the bounded member-table simulator (``sim/scale_step``:
SWIM + piggybacked changeset broadcast + anti-entropy sync, O(N*M) state)
— under ``lax.scan`` and reports steady-state rounds/sec; ``vs_baseline``
is the fraction of the 10k rounds/sec target.

Robustness (round-1 post-mortem: the TPU backend failed to initialize
once and the whole round shipped with rc=1 and no number): the module is
a supervisor/worker pair. The supervisor (default entry) runs the actual
measurement in a *subprocess* (``BENCH_WORKER=1``) so a backend-init
crash never takes out the parent; it retries TPU attempts with backoff,
degrades the node count, and finally falls back to CPU at reduced N. It
ALWAYS prints exactly one JSON line on stdout — on total failure the line
is an explicit diagnostic record with ``value=0.0`` — and exits 0 unless
even the diagnostic cannot be produced. Diagnostics go to stderr.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

TARGET_RPS = 10_000.0


# --------------------------------------------------------------------------
# worker: the actual measurement (runs in a subprocess)
# --------------------------------------------------------------------------


def _probe() -> None:
    """Tiny worker: init the backend + run one op. Proves the TPU tunnel
    is alive without paying the full bench compile."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    import jax.numpy as jnp

    x = jnp.ones((256, 256))
    (x @ x).block_until_ready()
    print(json.dumps({"metric": "probe", "value": 1.0,
                      "platform": jax.devices()[0].platform}))


def _worker() -> None:
    import functools

    import jax

    # this environment's sitecustomize forces a platform via config.update,
    # which outranks the JAX_PLATFORMS env var — re-honor the env var
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from corrosion_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    import jax.numpy as jnp
    import jax.random as jr

    from corrosion_tpu.sim.scale_step import (
        ScaleRoundInput,
        ScaleSimState,
        scale_run_rounds,
        scale_sim_config,
    )
    from corrosion_tpu.sim.transport import NetModel

    platform = jax.devices()[0].platform
    on_tpu = platform != "cpu"  # the axon tunnel reports its own name
    # scan length 8: the tunnel's remote-compile service drops the
    # connection on the 100-round scanned program (observed: "response
    # body closed before all bytes were read"); 8 compiles reliably and
    # reps amortize dispatch overhead instead
    n_nodes = int(os.environ.get("BENCH_NODES", 100_000 if on_tpu else 256))
    rounds = int(os.environ.get("BENCH_ROUNDS", 8 if on_tpu else 4))
    reps = int(os.environ.get("BENCH_REPS", 12 if on_tpu else 2))

    # workload shape knobs (VERDICT r2: the flagship's CRDT working set
    # was 16 origins x 64 cells — unrepresentatively tiny): the writer
    # pool and store shape are env-tunable so the capture can also run
    # heavier mixes (e.g. BENCH_ORIGINS=256 BENCH_ROWS=64)
    n_origins = min(int(os.environ.get("BENCH_ORIGINS", "16")), n_nodes)
    cfg = scale_sim_config(
        n_nodes,
        n_origins=n_origins,
        n_rows=int(os.environ.get("BENCH_ROWS", "16")),
        n_cols=int(os.environ.get("BENCH_COLS", "4")),
        # bounded piggyback A/B (BENCH_PIG_MEMBERS=16): ~4x less channel
        # HBM traffic, entry merges move into the pallas kernel's VMEM
        pig_members=int(os.environ.get("BENCH_PIG_MEMBERS", "0")),
    )
    key = jr.key(0)
    st = ScaleSimState.create(cfg)
    net = NetModel.create(n_nodes, drop_prob=0.01)

    # conflict-heavy inputs: origins write hot cells at random rounds
    k1, k2, k3 = jr.split(jr.key(1), 3)
    quiet = ScaleRoundInput.quiet(cfg)
    inputs = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (rounds,) + a.shape), quiet
    )
    w = (jr.uniform(k1, (rounds, n_nodes)) < 0.25) & (
        jnp.arange(n_nodes)[None, :] < cfg.n_origins
    )
    inputs = inputs._replace(
        write_mask=w,
        write_cell=jr.randint(k2, (rounds, n_nodes), 0, cfg.n_cells, dtype=jnp.int32),
        write_val=jr.randint(k3, (rounds, n_nodes), 0, 1 << 20, dtype=jnp.int32),
    )

    run = jax.jit(functools.partial(scale_run_rounds, cfg), donate_argnums=(0,))
    st = jax.block_until_ready(run(st, net, key, inputs))[0]  # compile + warm

    t0 = time.perf_counter()
    for i in range(reps):
        st, infos = run(st, net, jr.fold_in(key, i), inputs)
    jax.block_until_ready(st)
    dt = time.perf_counter() - t0

    rps = reps * rounds / dt
    from corrosion_tpu.ops import megakernel

    print(
        json.dumps(
            {
                "metric": (
                    f"gossip_rounds_per_sec_n{n_nodes}_"
                    f"{'tpu' if on_tpu else 'cpu'}"
                ),
                "value": round(rps, 2),
                "unit": "rounds/s",
                "vs_baseline": round(rps / TARGET_RPS, 4),
                "platform": platform,
                "n_origins": cfg.n_origins,
                "n_rows": cfg.n_rows,
                "n_cols": cfg.n_cols,
                "pig_members": cfg.pig_members,
                # loud fused-path visibility (VERDICT r2 weak #2): a TPU
                # record measured on the XLA fallback is flagged, not
                # silently reported as if it were the pallas path —
                # shape-aware, so a width-lowering failure shows here too
                "pallas_fused": bool(
                    megakernel.use_fused_ingest(cfg, 4 * cfg.pig_changes)
                    and megakernel.use_fused_swim(
                        cfg.n_nodes, cfg.m_slots, cfg.pig_members
                    )
                ),
            }
        )
    )


# --------------------------------------------------------------------------
# supervisor: retry ladder, CPU fallback, never-empty output
# --------------------------------------------------------------------------


def _attempt(env_extra: dict, timeout_s: float,
             probe: bool = False) -> tuple[dict | None, str]:
    """Run the worker in a subprocess; return (parsed JSON or None, err)."""
    env = dict(os.environ)
    env.update(env_extra)
    env["BENCH_PROBE" if probe else "BENCH_WORKER"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout_s:.0f}s"
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-12:]
        return None, f"rc={proc.returncode}: " + " | ".join(tail)
    for line in reversed((proc.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
                if "metric" in rec and "value" in rec:
                    return rec, ""
            except json.JSONDecodeError:
                continue
    return None, "worker produced no JSON line"


def main() -> None:
    """TPU-or-bust supervisor (round-2 post-mortem: two 300 s probes
    failed and the ladder never made a single full TPU attempt — the
    round shipped a CPU record while the builder's own later runs showed
    the tunnel recovering >10 min in).

    Strategy: within a deadline budget (``BENCH_DEADLINE_S``, default
    5400 s), alternate cheap init probes with FULL TPU attempts — a probe
    failure *degrades* the next attempt (smaller N compiles faster) but
    never skips TPU. The persistent compilation cache
    (``corrosion_tpu/utils/compile_cache.py``) makes every retry after
    the first compile-free. A 900 s reserve always leaves room for the
    CPU fallback so the round is never benchless."""
    want_platform = os.environ.get("JAX_PLATFORMS", "")
    deadline = time.time() + float(os.environ.get("BENCH_DEADLINE_S", "5400"))
    cpu_reserve = 900.0

    def remaining() -> float:
        return deadline - time.time()

    errors: list[str] = []

    def finish(rec: dict) -> None:
        if errors:
            rec["attempts_failed"] = errors
        print(json.dumps(rec))

    def try_one(label: str, env_extra: dict, timeout_s: float,
                probe: bool = False, is_reserve: bool = False):
        # TPU rungs leave the CPU reserve untouched; the fallback itself
        # spends the reserve
        budget = remaining() if is_reserve else remaining() - cpu_reserve
        timeout_s = min(timeout_s, max(60.0, budget))
        t0 = time.time()
        rec, err = _attempt(env_extra, timeout_s, probe=probe)
        if rec is None:
            msg = f"attempt {label} failed after {time.time() - t0:.0f}s: {err}"
            print(msg, file=sys.stderr)
            errors.append(f"{label}: {err[:300]}")
        return rec

    if want_platform == "cpu":
        rec = try_one("cpu#0", {}, 1500.0)
        if rec is not None:
            return finish(rec)
    else:
        # TPU pursuit: (probe?, label, env, timeout, sleep_after_failure)
        plan = [
            (True, "probe#0", {}, 300.0, 30.0),
            (False, "full#0", {}, 1600.0, 60.0),
            (True, "probe#1", {}, 300.0, 60.0),
            (False, "degraded-50k", {"BENCH_NODES": "50000"}, 1200.0, 120.0),
            (True, "probe#2", {}, 450.0, 120.0),
            (False, "full#1", {}, 1600.0, 120.0),
            (True, "probe#3", {}, 600.0, 60.0),
            (False, "degraded-25k",
             {"BENCH_NODES": "25000", "BENCH_REPS": "8"}, 1200.0, 60.0),
            (False, "full#2", {}, 1600.0, 0.0),
        ]
        def probe_says_tpu(label, env_extra, timeout_s) -> bool:
            rec = try_one(label, env_extra, timeout_s, probe=True)
            if rec is None:
                return False
            plat = rec.get("platform")
            if plat in (None, "cpu") and not want_platform:
                # jax silently fell back to its CPU backend: a full
                # "auto" run would measure an incomparable small-N CPU
                # number and mask the TPU outage
                errors.append(
                    f"{label}: initialized platform {plat!r}, not TPU"
                )
                return False
            return True

        def full_attempt(label, env_extra, timeout_s):
            rec = try_one(label, env_extra, timeout_s)
            if rec is not None and (
                rec.get("platform") == "cpu" and not want_platform
            ):
                errors.append(f"{label}: worker ran on cpu backend, not TPU")
                return None
            return rec

        for is_probe, label, env_extra, timeout_s, sleep_s in plan:
            if remaining() <= cpu_reserve + 120.0:
                errors.append(f"{label}: skipped, deadline budget exhausted")
                break
            if is_probe:
                ok = probe_says_tpu(label, env_extra, timeout_s)
            else:
                # degraded rungs run whenever reached — a full-N attempt
                # already failed by then, and the failure may be
                # N-dependent (timeout/OOM) even on a healthy tunnel
                rec = full_attempt(label, env_extra, timeout_s)
                if rec is not None:
                    return finish(rec)
                ok = False
            # sleep after ANY failed rung: the tunnel has been observed
            # to hang >9 min and then recover — give it time
            if not ok and sleep_s and remaining() > cpu_reserve + sleep_s:
                time.sleep(sleep_s)

        # recovery loop: the plan burned ~30 min at most; spend whatever
        # deadline budget remains alternating probe -> full attempt so a
        # tunnel that comes back late in the window still yields a TPU
        # record (compilation is cached, so retries are cheap)
        r = 0
        while remaining() > cpu_reserve + 720.0:
            r += 1
            if probe_says_tpu(f"probe#r{r}", {}, 300.0):
                rec = full_attempt(f"full#r{r}", {}, 1600.0)
                if rec is not None:
                    return finish(rec)
            if remaining() > cpu_reserve + 720.0:
                time.sleep(240.0)

    # final fallback: CPU at reduced N so the record is never empty
    rec = try_one(
        "cpu-fallback",
        {
            "JAX_PLATFORMS": "cpu",
            "BENCH_NODES": os.environ.get("BENCH_CPU_NODES", "4096"),
            "BENCH_ROUNDS": "8",
            "BENCH_REPS": "2",
        },
        1200.0,
        is_reserve=True,
    )
    if rec is not None:
        return finish(rec)

    # total failure: emit an explicit diagnostic record, never an empty round
    finish(
        {
            "metric": "gossip_rounds_per_sec_unavailable",
            "value": 0.0,
            "unit": "rounds/s",
            "vs_baseline": 0.0,
            "error": "all bench attempts failed",
        }
    )


if __name__ == "__main__":
    if os.environ.get("BENCH_PROBE"):
        _probe()
    elif os.environ.get("BENCH_WORKER"):
        _worker()
    else:
        main()
