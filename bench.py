"""Benchmark: gossip-simulator round throughput.

Prints ONE JSON line ``{"metric", "value", "unit", "vs_baseline", ...}``.

North-star (BASELINE.md): >=10,000 simulated gossip rounds/sec at 100k
nodes on a v5e-8. The bench runs the fused whole-cluster round at the
north-star scale — the bounded member-table simulator (``sim/scale_step``:
SWIM + piggybacked changeset broadcast + anti-entropy sync, O(N*M) state)
— under ``lax.scan`` and reports steady-state rounds/sec; ``vs_baseline``
is the fraction of the 10k rounds/sec target.

Robustness (round-1 post-mortem: the TPU backend failed to initialize
once and the whole round shipped with rc=1 and no number): the module is
a supervisor/worker pair. The supervisor (default entry) runs the actual
measurement in a *subprocess* (``BENCH_WORKER=1``) so a backend-init
crash never takes out the parent; it retries TPU attempts with backoff,
degrades the node count, and finally falls back to CPU at reduced N. It
ALWAYS prints exactly one JSON line on stdout — on total failure the line
is an explicit diagnostic record with ``value=0.0`` — and exits 0 unless
even the diagnostic cannot be produced. Diagnostics go to stderr.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

TARGET_RPS = 10_000.0


# --------------------------------------------------------------------------
# worker: the actual measurement (runs in a subprocess)
# --------------------------------------------------------------------------


def _probe() -> None:
    """Tiny worker: init the backend + run one op. Proves the TPU tunnel
    is alive without paying the full bench compile."""
    import jax

    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
    import jax.numpy as jnp

    x = jnp.ones((256, 256))
    (x @ x).block_until_ready()
    print(json.dumps({"metric": "probe", "value": 1.0,
                      "platform": jax.devices()[0].platform}))


def _worker() -> None:
    import functools

    import jax

    # this environment's sitecustomize forces a platform via config.update,
    # which outranks the JAX_PLATFORMS env var — re-honor the env var
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    import jax.numpy as jnp
    import jax.random as jr

    from corrosion_tpu.sim.scale_step import (
        ScaleRoundInput,
        ScaleSimState,
        scale_run_rounds,
        scale_sim_config,
    )
    from corrosion_tpu.sim.transport import NetModel

    platform = jax.devices()[0].platform
    on_tpu = platform != "cpu"  # the axon tunnel reports its own name
    # scan length 8: the tunnel's remote-compile service drops the
    # connection on the 100-round scanned program (observed: "response
    # body closed before all bytes were read"); 8 compiles reliably and
    # reps amortize dispatch overhead instead
    n_nodes = int(os.environ.get("BENCH_NODES", 100_000 if on_tpu else 256))
    rounds = int(os.environ.get("BENCH_ROUNDS", 8 if on_tpu else 4))
    reps = int(os.environ.get("BENCH_REPS", 12 if on_tpu else 2))

    cfg = scale_sim_config(n_nodes, n_origins=min(16, n_nodes))
    key = jr.key(0)
    st = ScaleSimState.create(cfg)
    net = NetModel.create(n_nodes, drop_prob=0.01)

    # conflict-heavy inputs: origins write hot cells at random rounds
    k1, k2, k3 = jr.split(jr.key(1), 3)
    quiet = ScaleRoundInput.quiet(cfg)
    inputs = jax.tree.map(
        lambda a: jnp.broadcast_to(a, (rounds,) + a.shape), quiet
    )
    w = (jr.uniform(k1, (rounds, n_nodes)) < 0.25) & (
        jnp.arange(n_nodes)[None, :] < cfg.n_origins
    )
    inputs = inputs._replace(
        write_mask=w,
        write_cell=jr.randint(k2, (rounds, n_nodes), 0, cfg.n_cells, dtype=jnp.int32),
        write_val=jr.randint(k3, (rounds, n_nodes), 0, 1 << 20, dtype=jnp.int32),
    )

    run = jax.jit(functools.partial(scale_run_rounds, cfg), donate_argnums=(0,))
    st = jax.block_until_ready(run(st, net, key, inputs))[0]  # compile + warm

    t0 = time.perf_counter()
    for i in range(reps):
        st, infos = run(st, net, jr.fold_in(key, i), inputs)
    jax.block_until_ready(st)
    dt = time.perf_counter() - t0

    rps = reps * rounds / dt
    print(
        json.dumps(
            {
                "metric": (
                    f"gossip_rounds_per_sec_n{n_nodes}_"
                    f"{'tpu' if on_tpu else 'cpu'}"
                ),
                "value": round(rps, 2),
                "unit": "rounds/s",
                "vs_baseline": round(rps / TARGET_RPS, 4),
            }
        )
    )


# --------------------------------------------------------------------------
# supervisor: retry ladder, CPU fallback, never-empty output
# --------------------------------------------------------------------------


def _attempt(env_extra: dict, timeout_s: float,
             probe: bool = False) -> tuple[dict | None, str]:
    """Run the worker in a subprocess; return (parsed JSON or None, err)."""
    env = dict(os.environ)
    env.update(env_extra)
    env["BENCH_PROBE" if probe else "BENCH_WORKER"] = "1"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        return None, f"timeout after {timeout_s:.0f}s"
    if proc.returncode != 0:
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-12:]
        return None, f"rc={proc.returncode}: " + " | ".join(tail)
    for line in reversed((proc.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                rec = json.loads(line)
                if "metric" in rec and "value" in rec:
                    return rec, ""
            except json.JSONDecodeError:
                continue
    return None, "worker produced no JSON line"


def main() -> None:
    want_platform = os.environ.get("JAX_PLATFORMS", "")
    # cheap init probe first: TPU backend init has been observed to hang
    # for >9 min when the tunnel is down — don't burn full-bench timeouts
    # discovering that. Two probe tries with backoff, then CPU fallback.
    backend_ok = want_platform == "cpu"
    if not backend_ok:
        for i in range(2):
            rec, err = _attempt({}, 300.0, probe=True)
            if rec is not None:
                plat = rec.get("platform")
                if want_platform or plat not in (None, "cpu"):
                    backend_ok = True
                else:
                    # jax silently fell back to its CPU backend: an "auto"
                    # run would measure an incomparable small-N CPU number
                    # and mask the TPU outage — route to the explicit
                    # cpu-fallback record instead
                    err = f"probe initialized platform {plat!r}, not TPU"
                if backend_ok:
                    break
            print(f"backend probe #{i} failed: {err}", file=sys.stderr)
            time.sleep(15.0)

    # attempt ladder: (label, env overrides, timeout seconds)
    ladder: list[tuple[str, dict, float]] = []
    if backend_ok and want_platform and want_platform != "cpu":
        # explicit platform request: honor it, with retries
        for i in range(3):
            ladder.append((f"{want_platform}#{i}", {}, 1500.0))
    elif backend_ok and want_platform == "cpu":
        ladder.append(("cpu#0", {}, 1500.0))
    elif backend_ok:
        # default: whatever backend jax picks (TPU when the tunnel is up),
        # retried with backoff; then a degraded-N attempt
        ladder.append(("auto#0", {}, 1500.0))
        ladder.append(("auto#1", {}, 1200.0))
        ladder.append(
            ("auto-degraded", {"BENCH_NODES": "50000", "BENCH_ROUNDS": "50"}, 1200.0)
        )
    # final fallback: CPU at reduced N so the record is never empty
    ladder.append(
        (
            "cpu-fallback",
            {
                "JAX_PLATFORMS": "cpu",
                "BENCH_NODES": os.environ.get("BENCH_CPU_NODES", "4096"),
                "BENCH_ROUNDS": "8",
                "BENCH_REPS": "2",
            },
            1200.0,
        )
    )

    errors: list[str] = []
    backoff = 10.0
    for idx, (label, env_extra, timeout_s) in enumerate(ladder):
        t0 = time.time()
        rec, err = _attempt(env_extra, timeout_s)
        if rec is not None:
            if errors:
                rec["attempts_failed"] = errors
            print(json.dumps(rec))
            return
        msg = f"attempt {label} failed after {time.time() - t0:.0f}s: {err}"
        print(msg, file=sys.stderr)
        errors.append(f"{label}: {err[:300]}")
        if idx + 1 < len(ladder):
            time.sleep(backoff)
            backoff = min(backoff * 2, 60.0)

    # total failure: emit an explicit diagnostic record, never an empty round
    print(
        json.dumps(
            {
                "metric": "gossip_rounds_per_sec_unavailable",
                "value": 0.0,
                "unit": "rounds/s",
                "vs_baseline": 0.0,
                "error": "all bench attempts failed",
                "attempts_failed": errors,
            }
        )
    )


if __name__ == "__main__":
    if os.environ.get("BENCH_PROBE"):
        _probe()
    elif os.environ.get("BENCH_WORKER"):
        _worker()
    else:
        main()
