"""SWIM membership step: join / failure-detection / rejoin / partition.

These mirror the reference's in-process cluster tests (real agents on
loopback asserting convergence, ``crates/corro-agent/src/agent/tests.rs``)
— here the "cluster" is the vectorized state and the assertion is
``swim_metrics``'s ground-truth accuracy (BASELINE config 2)."""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from corrosion_tpu.sim.config import SimConfig, wan_config
from corrosion_tpu.sim.swim import SwimState, swim_metrics, swim_step
from corrosion_tpu.sim.transport import NetModel

N = 32


@pytest.fixture(scope="module")
def stepper():
    cfg = wan_config(N, announce_interval=8)
    step = jax.jit(
        lambda st, net, key, kill, revive: swim_step(
            cfg, st, net, key, kill=kill, revive=revive
        )[0]
    )
    return cfg, step


def run(step, st, net, key, rounds, kill=None, revive=None):
    none = jnp.zeros(N, bool)
    for r in range(rounds):
        key, sub = jr.split(key)
        k = kill if (kill is not None and r == 0) else none
        v = revive if (revive is not None and r == 0) else none
        st = step(st, net, sub, k, v)
    return st, key


def test_join_converges_from_seeds(stepper):
    cfg, step = stepper
    st = SwimState.create(cfg, n_seeds=3)
    net = NetModel.create(N)
    st, _ = run(step, st, net, jr.key(0), 40)
    m = swim_metrics(st)
    assert bool(m["converged"]), float(m["accuracy"])


def test_failure_detected_then_rejoin(stepper):
    cfg, step = stepper
    st = SwimState.create(cfg, n_seeds=3)
    net = NetModel.create(N)
    st, key = run(step, st, net, jr.key(1), 40)

    kill = jnp.zeros(N, bool).at[7].set(True)
    st, key = run(step, st, net, key, 60, kill=kill)
    m = swim_metrics(st)
    assert int(m["n_alive"]) == N - 1
    assert bool(m["converged"]), float(m["accuracy"])
    # every alive node sees 7 as Down (it was known before the kill)
    states = np.asarray(st.view) & 3
    known = np.asarray(st.view) >= 0
    viewers = np.asarray(st.alive)
    assert all(known[i, 7] and states[i, 7] == 2 for i in range(N) if viewers[i])

    # rejoin: identity renew bumps incarnation and spreads
    revive = jnp.zeros(N, bool).at[7].set(True)
    st, key = run(step, st, net, key, 80, revive=revive)
    m = swim_metrics(st)
    assert int(m["n_alive"]) == N
    assert bool(m["converged"]), float(m["accuracy"])
    assert int(st.incarnation[7]) >= 1


def test_converges_under_heavy_loss(stepper):
    cfg, step = stepper
    st = SwimState.create(cfg, n_seeds=3)
    net = NetModel.create(N, drop_prob=0.15)
    st, _ = run(step, st, net, jr.key(2), 120)
    m = swim_metrics(st)
    assert float(m["accuracy"]) > 0.95, float(m["accuracy"])


def test_partition_then_heal(stepper):
    cfg, step = stepper
    st = SwimState.create(cfg, n_seeds=3)
    net = NetModel.create(N)
    st, key = run(step, st, net, jr.key(3), 40)

    # split 2:1; each side should declare the other Down
    part = NetModel.create(N)._replace(
        partition=(jnp.arange(N) % 3 == 0).astype(jnp.int32),
    )
    st, key = run(step, st, part, key, 60)
    states = np.asarray(st.view) & 3
    pa = np.asarray(part.partition)
    cross = pa[:, None] != pa[None, :]
    assert (states[cross] == 2).mean() > 0.95  # almost all cross-views Down

    # heal: announces + down-notices + incarnation renewal re-knit the mesh
    st, key = run(step, st, net, key, 200)
    m = swim_metrics(st)
    assert bool(m["converged"]), float(m["accuracy"])


def test_bootstrap_members_full_view():
    """Persisted-members replay into the full-view sim: every node starts
    believing the listed members alive (initialise_foca ApplyMany)."""
    import numpy as np

    from corrosion_tpu.ops.lww import STATE_ALIVE
    from corrosion_tpu.sim.config import SimConfig
    from corrosion_tpu.sim.swim import SwimState, bootstrap_members

    cfg = SimConfig(n_nodes=12).validate()
    st = SwimState.create(cfg, n_seeds=2)
    st = bootstrap_members(st, [5, 9, 11], incarnations=[0, 3, 1])
    view = np.asarray(st.view)
    for nid, inc in ((5, 0), (9, 3), (11, 1)):
        col = view[:, nid]
        assert ((col & 3) == STATE_ALIVE).all()
        assert (col >> 2 >= inc).all()  # incarnation carried over
    # unlisted non-seed members stay unknown
    assert (view[:, 4][np.arange(12) != 4] == -1).all()
