"""corrocost (ISSUE 20): jaxpr/HLO cost & collective auditor gates.

Four test tiers:

- **rule fixtures**: ``collective-budget`` fires on undeclared explicit
  collectives (and honors declared sites + reasoned suppressions);
  ``cost-drift`` fires when a constructor's symbolic degree leaves the
  declared fit degrees;
- **coverage + registry sync**: every ``HOT_ENTRY_POINTS`` name is
  priced, every registered sharded entry is audited, the declared
  degrees equal the corrobudget inventory's own degrees, and the
  roofline point matches corrobudget's;
- **fit regressions**: exact interpolation with verified holdouts,
  degrees, and the 1M-projection == direct-1M-trace identity;
- **dtype-flow runtime cross-check**: the NARROW_LEAVES registry against
  the REAL traced entry outputs under the narrow knobs — no leaf
  widens through the jaxpr, and every registry name exists in the
  state (both directions);
- **collective manifests** (8 virtual devices): lowered manifests match
  the committed pins bit for bit, the 2-D mesh compiles the identical
  program, and the smuggled-gather mutation fixture FAILS the gate.
"""

import dataclasses

import jax
import pytest

from corrosion_tpu.analysis import collectives, cost, dtypes, shapes
from corrosion_tpu.analysis.runner import check_source

# --- rule fixtures --------------------------------------------------------

SMUGGLED = '''
import jax
import jax.numpy as jnp
from jax import lax


def drain_views(st, mesh):
    gathered = lax.all_gather(st.store, "node")
    return jnp.sum(gathered)
'''


def _collective(src, path="corrosion_tpu/sim/fixture_coll.py"):
    return check_source(
        src, path, {"collective-budget": collectives.check_project})


def test_collective_budget_fires_on_undeclared_site():
    findings = _collective(SMUGGLED)
    assert len(findings) == 1
    f = findings[0]
    assert f.rule == "collective-budget"
    assert "all_gather" in f.message and "drain_views" in f.message
    assert f.line == 8


def test_collective_budget_fires_on_sharding_constraint():
    src = SMUGGLED.replace(
        'lax.all_gather(st.store, "node")',
        "jax.lax.with_sharding_constraint(st.store, spec)")
    findings = _collective(src)
    assert len(findings) == 1
    assert "with_sharding_constraint" in findings[0].message


def test_collective_budget_declared_site_is_clean(monkeypatch):
    monkeypatch.setitem(
        collectives.DECLARED_COLLECTIVE_SITES,
        "corrosion_tpu.sim.fixture_coll.drain_views",
        "test fixture: deliberate gather")
    assert _collective(SMUGGLED) == []


def test_collective_budget_reasoned_suppression():
    src = SMUGGLED.replace(
        'lax.all_gather(st.store, "node")',
        'lax.all_gather(st.store, "node")  '
        "# corrolint: disable=collective-budget -- fixture gather")
    assert _collective(src) == []


def test_collective_budget_out_of_scope_is_clean():
    # must be a path that EXISTS — nonexistent paths are deliberately
    # in scope so fixture blobs can probe the rule
    assert _collective(
        SMUGGLED, path="corrosion_tpu/analysis/runner.py") == []


def test_collective_budget_module_level_fires():
    src = "from jax import lax\nTOTAL = lax.psum(1, 'node')\n"
    findings = _collective(src)
    assert len(findings) == 1
    assert "module-level" in findings[0].message


def test_collective_registry_empty_by_design():
    # the whole point of the static rule today: the runtime surface has
    # NO hand-written collectives — GSPMD owns cross-shard traffic and
    # the pinned manifests audit it. Adding one means declaring it.
    assert collectives.DECLARED_COLLECTIVE_SITES == {}


WRONG_DEGREE = '''
from typing import NamedTuple
import jax
import jax.numpy as jnp


class ScaleSimState(NamedTuple):
    pair: jax.Array

    @staticmethod
    def create(cfg):
        n = cfg.n_nodes
        return ScaleSimState(pair=jnp.zeros((n, n), jnp.int8))
'''


def test_cost_drift_fires_on_degree_change():
    findings = check_source(
        WRONG_DEGREE, "fixture_cost.py",
        {"cost-drift": cost.check_project})
    assert any(f.rule == "cost-drift" and "degree 2" in f.message
               for f in findings)


def test_cost_drift_silent_without_state_root():
    assert check_source(
        "def f():\n    return 1\n", "fixture_cost.py",
        {"cost-drift": cost.check_project}) == []


# --- coverage + registry sync ---------------------------------------------


def test_every_hot_entry_point_is_priced():
    from corrosion_tpu.analysis.tracecount import HOT_ENTRY_POINTS

    missing = set(HOT_ENTRY_POINTS) - set(cost.PRICED_ENTRY_POINTS)
    assert not missing, (
        f"hot entry points registered but not priced by corrocost: "
        f"{sorted(missing)} — add a PricedEntry in analysis/cost.py")


def test_every_sharded_entry_is_audited():
    from corrosion_tpu.parallel.mesh import SHARDED_ENTRY_POINTS

    assert set(SHARDED_ENTRY_POINTS) == set(collectives.COLLECTIVE_BUDGET)
    for entry, budget in collectives.COLLECTIVE_BUDGET.items():
        assert budget["pins"], f"{entry} has no committed pins"
        assert set(budget["pins"]) == {
            lb for lb, _ in collectives.knob_matrix()}, (
            f"{entry} pins do not cover the full knob matrix")


def test_declared_degrees_match_inventory():
    # three-way sync: COST_DEGREES == the corrobudget inventory's own
    # max degrees, for both state roots (the lint rule gates the same
    # equality over the walked tree)
    for root, declared in cost.COST_DEGREES.items():
        mode = "scale" if root == "ScaleSimState" else "full"
        # symbolic default config (cfg=None): a concrete config would
        # collapse bounded dims to constants and erase their degree —
        # exactly the lint rule's ConfigVal.default() view
        inv = shapes.static_inventory(None, mode=mode)
        assert cost.inventory_degrees(inv) == declared, root


def test_roofline_point_matches_corrobudget():
    assert cost.ROOFLINE_POINT == shapes.HBM_BUDGET["point"]


def test_repo_walk_is_clean_for_v4_rules():
    from corrosion_tpu.analysis.runner import lint_report

    findings, n_files = lint_report(
        ["corrosion_tpu", "bench.py"],
        checkers=["collective-budget", "cost-drift"])
    assert findings == []
    assert n_files > 20


# --- fit regressions ------------------------------------------------------


def test_scale_step_fit_exact_and_bilinear():
    fits = cost.fit_entry("scale_sim_step")
    for metric, fit in fits.items():
        assert fit.exact, (metric, fit.render())
        assert fit.degree("N") == 1 and fit.degree("M") == 1, fit.render()


def test_full_step_fit_exact_and_quadratic():
    fits = cost.fit_entry("full_sim_step")
    assert fits["flops"].exact
    assert fits["flops"].degree("N") == 2, fits["flops"].render()


def test_fit_degrees_never_exceed_inventory():
    for name, entry in cost.PRICED_ENTRY_POINTS.items():
        if name not in ("scale_sim_step", "full_sim_step"):
            continue  # one scan entry is covered by the 1M test below
        fits = cost.fit_entry(name)
        declared = cost.COST_DEGREES[entry.root]
        for sym in entry.extents:
            assert fits["flops"].degree(sym) <= declared.get(sym, 0), (
                f"{name}: compute outgrew the {entry.root} inventory "
                f"in {sym}")


def test_1m_projection_reproduces_direct_trace():
    # the extrapolation license: the fitted per-round polynomial at
    # N=1M must equal a DIRECT abstract trace of the 1M-node program,
    # bit for bit, for flops AND model bytes
    fits = cost.fit_entry("sharded_scale_run")
    direct = cost.price_per_round("sharded_scale_run",
                                  dict(cost.ROOFLINE_POINT))
    for metric, fit in fits.items():
        assert fit.exact, fit.render()
        assert fit.at(cost.ROOFLINE_POINT) == getattr(direct, metric)


def test_fused_entry_declared_piecewise():
    # the pallas grid's ceil-division makes the fused cost only
    # piecewise polynomial — the registry must say so (roofline then
    # uses the direct 1M trace as truth, not the extrapolation)
    assert not cost.PRICED_ENTRY_POINTS["fused_scale_run"].exact_fit
    assert cost.PRICED_ENTRY_POINTS["sharded_scale_run"].exact_fit


def test_xla_cost_analysis_agreement():
    rec = cost.xla_agreement()
    if not rec["reported"]:
        pytest.skip("backend reports no cost_analysis")
    assert rec["agrees"], rec


# --- dtype-flow runtime cross-check (satellite 1) -------------------------


def _narrow_cfg(**knobs):
    from corrosion_tpu.sim.scale_step import scale_sim_config

    return scale_sim_config(
        24, m_slots=8, n_origins=4, n_rows=4, n_cols=2, sync_interval=4,
        **knobs)


def _leaf_widths(cfg):
    """name -> set of observed bit widths over the REAL traced output
    state of the scan entry (path leaf name == registry key)."""
    import functools

    from corrosion_tpu.sim.scale_step import scale_run_rounds

    entry = cost.PRICED_ENTRY_POINTS["sharded_scale_run"]
    st_out = jax.eval_shape(
        functools.partial(scale_run_rounds, cfg),
        *cost._scale_specs(cfg, 2))[0]
    widths = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(st_out)[0]:
        name = None
        for part in reversed(path):
            if hasattr(part, "name"):
                name = part.name
                break
        if name is None:
            continue
        widths.setdefault(name, set()).add(leaf.dtype.itemsize * 8)
    del entry
    return widths


@pytest.mark.parametrize("knobs", [
    {"narrow_int8": True, "narrow_q_int8": True},
    {"narrow_int8": True, "narrow_q_int8": False},
    {"narrow_int8": False, "narrow_q_int8": True},
])
def test_narrow_leaves_never_widen_through_the_jaxpr(knobs):
    # the registry widths are the fully-narrow contract; a knob left
    # off legitimately keeps its own planes wider, so the gate is
    # "never wider than the knob's contract": with the knob on, the
    # traced output must sit at the declared width exactly
    widths = _leaf_widths(_narrow_cfg(**knobs))
    i8_planes = {"mem_tx"}
    q8_planes = {"q_seq", "q_nseq", "q_tx"}
    for name, declared in dtypes.NARROW_LEAVES.items():
        assert name in widths, (
            f"registry leaf {name} not found in the traced state — "
            "NARROW_LEAVES out of sync with the real carry")
        got = widths[name]
        assert len(got) == 1, (name, got)
        (bits,) = got
        if name in i8_planes and not knobs["narrow_int8"]:
            assert bits >= declared, (name, bits)
        elif name in q8_planes and not knobs["narrow_q_int8"]:
            assert bits >= declared, (name, bits)
        else:
            assert bits == declared, (
                f"{name}: traced width {bits} != declared {declared} — "
                "a leaf widened (or over-narrowed) through the jaxpr")


def test_narrow_registry_names_all_exist_in_state():
    # registry-sync, the other direction: every NARROW_LEAVES key must
    # name a real leaf of the default-config carry too
    widths = _leaf_widths(_narrow_cfg())
    assert set(dtypes.NARROW_LEAVES) <= set(widths)


# --- collective manifests (mesh tier) -------------------------------------

needs_mesh = pytest.mark.skipif(
    len(jax.devices()) < collectives.MESH_DEVICES,
    reason=f"needs {collectives.MESH_DEVICES} devices")


@needs_mesh
@pytest.mark.parametrize("label", collectives.TIER1_LABELS)
def test_manifest_matches_pins(label):
    man = collectives.collective_manifest("sharded_scale_run", label)
    assert collectives.check_manifest(
        "sharded_scale_run", label, man) == []


@needs_mesh
def test_carry_entry_manifest_and_2d_mesh_identical():
    flat = collectives.collective_manifest(
        "sharded_scale_run_carry", "dense")
    assert collectives.check_manifest(
        "sharded_scale_run_carry", "dense", flat) == []
    dcn = collectives.collective_manifest(
        "sharded_scale_run_carry", "dense", mesh_kind="dcn,node")
    assert {k: list(v) for k, v in dcn.items()} == \
        {k: list(v) for k, v in flat.items()}, (
        "2-D (dcn,node) mesh compiled a different collective manifest")


@needs_mesh
def test_smuggled_gather_fails_the_gate():
    mutated = collectives.collective_manifest(
        "sharded_scale_run", "dense",
        fn=collectives.smuggled_gather_entry)
    problems = collectives.check_manifest(
        "sharded_scale_run", "dense", mutated)
    assert problems, (
        "the smuggled all-gather passed the pin gate — the gate "
        "cannot fire")
    assert any("drifted" in p for p in problems)
    # the smuggle specifically inflates the gather traffic
    pins = collectives.COLLECTIVE_BUDGET["sharded_scale_run"]["pins"]
    assert mutated["all-gather"][1] > pins["dense"]["all-gather"][1]


@needs_mesh
def test_manifest_parser_on_live_hlo():
    # the regex tier never goes stale silently: the parser must find
    # at least one collective in the real compiled sharded program,
    # and every kind it finds must be a known HLO kind
    man = collectives.collective_manifest("sharded_scale_run", "dense")
    assert man, "no collectives parsed from a sharded program"
    assert set(man) <= set(collectives.COLLECTIVE_HLO_KINDS)
    for kind, (defs, nbytes) in man.items():
        assert defs > 0 and nbytes > 0, (kind, defs, nbytes)
