"""The CPU-budget smoke bench (``BENCH_SMOKE=1``) as a tier test.

One subprocess run of the pipeline regression check: it must exit 0
inside its hard deadline and report the two facts the throughput
trajectory depends on — the scale bench path dispatches with buffer
donation active (no duplicate carry allocation), and the segmented
soak's per-segment checkpoint stall is the host drain only, with
serialization/hash/IO overlapped onto the background writer. A lost
``donate_argnums`` or an accidental synchronous host transfer in the hot
loop fails here without needing a TPU.
"""

import json
import os
import subprocess
import sys

import pytest

BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


@pytest.mark.slow
def test_bench_smoke_pipeline_facts():
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        BENCH_SMOKE="1",
        BENCH_NODES="512",
        BENCH_ROUNDS="3",
        BENCH_SMOKE_SOAK_ROUNDS="8",
        BENCH_SMOKE_DEADLINE_S="200",
    )
    # the smoke subprocess shares the suite's persistent compile cache
    # (conftest exports JAX_COMPILATION_CACHE_DIR), so repeat runs are
    # dispatch-only
    proc = subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True,
        timeout=220, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    lines = [ln for ln in proc.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert lines, f"no JSON line on stdout: {proc.stdout!r}"
    rec = json.loads(lines[-1])

    assert rec["ok"], rec.get("problems")
    assert rec["donated"] is True
    assert rec["value"] > 0
    soak = rec["soak"]
    assert soak["async_checkpoint"] is True
    assert soak["donated_segments"] >= 1
    assert soak["ckpt_written"] == soak["segments"]
    # the overlapped drain: hot-loop stall well under the writer's IO
    assert soak["ckpt_stall_s"] < soak["ckpt_io_s"]
    # quiescence arm (ISSUE 19): provenance recorded, the active-set
    # round is bitwise dense-identical on the quiet trace, and the
    # cheap fixpoint path actually pays for itself
    assert rec["quiet_mode"] in ("auto", "on", "off")
    assert rec["quiet"]["parity"] is True
    assert rec["quiet"]["speedup"] >= 3.0
    assert rec["quiet"]["cheap_rounds"] > 0
    # scale-sweep wiring (ISSUE 19): the static projection priced at
    # the run's own N must equal the measured carry bytes exactly
    assert rec["hbm_projection_agrees"] is True
    assert rec["hbm_bytes"] == rec["hbm_bytes_projected"] > 0
    assert rec["elapsed_s"] <= rec["deadline_s"]
