"""Mesh-sharded simulator == single-device simulator, bit for bit.

The sharding layer must be a pure placement change: same PRNG keys, same
inputs => identical states whether the node axis lives on one device or
is split across the 8 virtual CPU devices (conftest forces
``--xla_force_host_platform_device_count=8``).
"""

import jax
import jax.numpy as jnp
import jax.random as jr
import pytest

from corrosion_tpu.parallel.mesh import make_mesh, shard_state, sharded_run
from corrosion_tpu.sim.config import wan_config
from corrosion_tpu.sim.scenario import conflict_heavy
from corrosion_tpu.sim.step import SimState, run_rounds
from corrosion_tpu.sim.transport import NetModel


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
@pytest.mark.parametrize("mesh_factory", ["flat", "multihost"])
def test_sharded_matches_single_device(mesh_factory):
    """Any mesh layout must be a pure placement change: same PRNG keys and
    inputs produce bitwise-identical state whether the node axis lives on
    one device, a flat 8-device mesh, or a 2-D (dcn, node) multi-host
    mesh (2 virtual hosts x 4 chips; DCN outer, ICI inner)."""
    from corrosion_tpu.parallel.mesh import make_multihost_mesh

    cfg = wan_config(32, n_rows=4, n_cols=2, buf_slots=8, bcast_queue=8, recv_slots=16)
    st = SimState.create(cfg)
    net = NetModel.create(cfg.n_nodes, drop_prob=0.05)
    key = jr.key(7)
    inputs = conflict_heavy(cfg, 6, jr.key(8), write_prob=0.5)

    ref, ref_infos = run_rounds(cfg, st, net, key, inputs)
    jax.block_until_ready(ref)

    if mesh_factory == "flat":
        mesh = make_mesh(jax.devices()[:8])
    else:
        mesh = make_multihost_mesh(2, jax.devices()[:8])
        assert mesh.axis_names == ("dcn", "node")
    st_s = shard_state(mesh, cfg.n_nodes, st)
    net_s = shard_state(mesh, cfg.n_nodes, net)
    in_s = shard_state(mesh, cfg.n_nodes, inputs)
    out, infos = sharded_run(cfg, mesh, st_s, net_s, key, in_s)
    jax.block_until_ready(out)

    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
        assert jnp.array_equal(a, b)
    # per-round info reductions cross shards — they must agree too
    for k in ref_infos:
        assert jnp.array_equal(ref_infos[k], infos[k]), k
    # the store plane is really split 8 ways across the mesh
    assert len(out.crdt.store[0].sharding.device_set) == 8


def test_state_is_actually_sharded():
    cfg = wan_config(32, n_rows=4, n_cols=2)
    mesh = make_mesh(jax.devices()[:8])
    st = shard_state(mesh, cfg.n_nodes, SimState.create(cfg))
    # the [N, N] view plane must be split over the node axis
    assert len(st.swim.view.sharding.device_set) == 8
    assert st.swim.view.sharding.spec[0] == "node"


