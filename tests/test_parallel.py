"""Mesh-sharded simulator == single-device simulator, bit for bit.

The sharding layer must be a pure placement change: same PRNG keys, same
inputs => identical states whether the node axis lives on one device or
is split across the 8 virtual CPU devices (conftest forces
``--xla_force_host_platform_device_count=8``).
"""

import jax
import jax.numpy as jnp
import jax.random as jr
import pytest

from corrosion_tpu.parallel.mesh import make_mesh, shard_state, sharded_run
from corrosion_tpu.sim.config import wan_config
from corrosion_tpu.sim.scenario import conflict_heavy
from corrosion_tpu.sim.step import SimState, run_rounds
from corrosion_tpu.sim.transport import NetModel


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
@pytest.mark.parametrize("mesh_factory", ["flat", "multihost"])
def test_sharded_matches_single_device(mesh_factory):
    """Any mesh layout must be a pure placement change: same PRNG keys and
    inputs produce bitwise-identical state whether the node axis lives on
    one device, a flat 8-device mesh, or a 2-D (dcn, node) multi-host
    mesh (2 virtual hosts x 4 chips; DCN outer, ICI inner)."""
    from corrosion_tpu.parallel.mesh import make_multihost_mesh

    cfg = wan_config(32, n_rows=4, n_cols=2, buf_slots=8, bcast_queue=8, recv_slots=16)
    st = SimState.create(cfg)
    net = NetModel.create(cfg.n_nodes, drop_prob=0.05)
    key = jr.key(7)
    inputs = conflict_heavy(cfg, 6, jr.key(8), write_prob=0.5)

    ref, ref_infos = run_rounds(cfg, st, net, key, inputs)
    jax.block_until_ready(ref)

    if mesh_factory == "flat":
        mesh = make_mesh(jax.devices()[:8])
    else:
        mesh = make_multihost_mesh(2, jax.devices()[:8])
        assert mesh.axis_names == ("dcn", "node")
    st_s = shard_state(mesh, cfg.n_nodes, st)
    net_s = shard_state(mesh, cfg.n_nodes, net)
    in_s = shard_state(mesh, cfg.n_nodes, inputs)
    out, infos = sharded_run(cfg, mesh, st_s, net_s, key, in_s)
    jax.block_until_ready(out)

    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
        assert jnp.array_equal(a, b)
    # per-round info reductions cross shards — they must agree too
    for k in ref_infos:
        assert jnp.array_equal(ref_infos[k], infos[k]), k
    # the store plane is really split 8 ways across the mesh
    assert len(out.crdt.store[0].sharding.device_set) == 8


def test_state_is_actually_sharded():
    cfg = wan_config(32, n_rows=4, n_cols=2)
    mesh = make_mesh(jax.devices()[:8])
    st = shard_state(mesh, cfg.n_nodes, SimState.create(cfg))
    # the [N, N] view plane must be split over the node axis
    assert len(st.swim.view.sharding.device_set) == 8
    assert st.swim.view.sharding.spec[0] == "node"


def test_multihost_mesh_rejects_bad_host_split():
    """A device count that does not split over the host count must raise
    a real ValueError — a bare assert is stripped under ``python -O``
    and the mis-shaped mesh would crash far away in device_put."""
    from corrosion_tpu.parallel.mesh import make_multihost_mesh

    devs = jax.devices()[:8]
    with pytest.raises(ValueError, match="do not split"):
        make_multihost_mesh(3, devs)
    with pytest.raises(ValueError, match="do not split"):
        make_multihost_mesh(0, devs)
    with pytest.raises(ValueError, match="do not split"):
        make_multihost_mesh(-2, devs)


# --- flagship (scale) path -------------------------------------------------


def scale_rig(rounds=6):
    from corrosion_tpu.sim.scale_step import (
        ScaleSimState,
        make_write_inputs,
        scale_sim_config,
    )

    cfg = scale_sim_config(
        32, m_slots=8, n_origins=4, n_rows=4, n_cols=2, sync_interval=4
    )
    st = ScaleSimState.create(cfg)
    net = NetModel.create(cfg.n_nodes, drop_prob=0.05)
    mask = jr.uniform(jr.key(9), (rounds, cfg.n_nodes)) < 0.4
    inputs = make_write_inputs(cfg, jr.key(8), rounds, mask)
    return cfg, st, net, inputs


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
@pytest.mark.parametrize("mesh_factory", ["flat", "multihost"])
def test_sharded_scale_flagship_matches_single_device(mesh_factory):
    """The 100k-capable flagship scan (``scale_run_rounds``) under the
    mesh with DONATED carry must stay a pure placement change: bitwise
    identical state and per-round metrics vs the single-device scan, on
    both the flat 1-D mesh and the 2-D (dcn, node) multi-host mesh."""
    from corrosion_tpu.parallel.mesh import (
        make_multihost_mesh,
        sharded_scale_run,
    )
    from corrosion_tpu.sim.scale_step import scale_run_rounds

    cfg, st, net, inputs = scale_rig()
    key = jr.key(7)
    ref, ref_infos = jax.jit(
        lambda s, k, i: scale_run_rounds(cfg, s, net, k, i)
    )(st, key, inputs)
    jax.block_until_ready(ref)

    mesh = (make_mesh(jax.devices()[:8]) if mesh_factory == "flat"
            else make_multihost_mesh(2, jax.devices()[:8]))
    st_s = shard_state(mesh, cfg.n_nodes, st)
    net_s = shard_state(mesh, cfg.n_nodes, net)
    in_s = shard_state(mesh, cfg.n_nodes, inputs)
    probe = st_s
    out, infos = sharded_scale_run(cfg, mesh, st_s, net_s, key, in_s)
    jax.block_until_ready(out)

    # donation: the sharded carry-in was consumed, not copied
    assert any(leaf.is_deleted() for leaf in jax.tree.leaves(probe))
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
        assert jnp.array_equal(a, b)
    for k in ref_infos:
        assert jnp.array_equal(ref_infos[k], infos[k]), k
    # carry-out keeps the node-axis placement for the next dispatch
    assert len(out.crdt.store[0].sharding.device_set) == 8


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_sharded_scale_carry_chain_matches_straight():
    """Two donated sharded segments chained through the FULL scan carry
    (state + PRNG key) == one straight scan — the soak runner's
    multi-chip contract (``sharded_scale_run_carry``)."""
    from corrosion_tpu.parallel.mesh import sharded_scale_run_carry
    from corrosion_tpu.sim.scale_step import scale_run_rounds_carry

    cfg, st, net, inputs = scale_rig(rounds=8)
    key = jr.key(21)
    (ref_st, ref_key), _ = jax.jit(
        lambda s, k, i: scale_run_rounds_carry(cfg, s, net, k, i)
    )(st, key, inputs)
    jax.block_until_ready(ref_st)

    mesh = make_mesh(jax.devices()[:8])
    net_s = shard_state(mesh, cfg.n_nodes, net)
    st_s = shard_state(mesh, cfg.n_nodes, st)
    k_s = key
    for lo, hi in ((0, 4), (4, 8)):
        seg = shard_state(
            mesh, cfg.n_nodes, jax.tree.map(lambda a: a[lo:hi], inputs)
        )
        (st_s, k_s), _ = sharded_scale_run_carry(
            cfg, mesh, st_s, net_s, k_s, seg
        )
    jax.block_until_ready(st_s)
    for a, b in zip(jax.tree.leaves(ref_st), jax.tree.leaves(st_s)):
        assert jnp.array_equal(a, b)
    assert jnp.array_equal(jr.key_data(ref_key), jr.key_data(k_s))


