"""Admin UDS socket + CLI surface (``corro-admin`` + the ``corrosion``
binary's command enum)."""

import json

import pytest

from corrosion_tpu import cli
from corrosion_tpu.admin import AdminClient, AdminServer
from corrosion_tpu.agent import Agent
from corrosion_tpu.api import ApiServer
from corrosion_tpu.config import Config
from corrosion_tpu.db import Database

SCHEMA = "CREATE TABLE kv (k TEXT PRIMARY KEY, v INTEGER);"


def rig_config():
    cfg = Config()
    cfg.sim.mode = "scale"
    cfg.sim.n_nodes = 16
    cfg.sim.m_slots = 8
    cfg.sim.n_origins = 4
    cfg.sim.n_rows = 8
    cfg.sim.n_cols = 4
    cfg.perf.sync_interval = 4
    cfg.gossip.drop_prob = 0.0
    return cfg


@pytest.fixture(scope="module")
def rig(tmp_path_factory):
    uds = str(tmp_path_factory.mktemp("adm") / "admin.sock")
    with Agent(rig_config()) as agent:
        agent.wait_rounds(10, timeout=120)
        db = Database(agent)
        db.apply_schema_sql(SCHEMA)
        db.execute(0, [("INSERT INTO kv (k, v) VALUES ('a', 1)",)])
        with ApiServer(db, port=0) as api, AdminServer(agent, uds, db=db):
            yield agent, db, api, uds


def test_admin_ping_and_members(rig):
    _, _, _, uds = rig
    with AdminClient(uds) as admin:
        assert admin.call("ping") == "pong"
        members = admin.call("cluster_members")
        assert len(members) == 16
        assert admin.call("cluster_set_id", cluster_id=3) == 3


def test_admin_sync_and_actor_version(rig):
    agent, _, _, uds = rig
    with AdminClient(uds) as admin:
        state = admin.call("sync", node=0)
        assert state["actor_id"] == 0
        ver = admin.call("actor_version", node=0, origin=0)
        assert ver["head"] >= 1  # we wrote at node 0
        all_states = admin.call("sync")
        assert len(all_states) == agent.n_nodes


def test_admin_locks_and_log(rig):
    _, _, _, uds = rig
    with AdminClient(uds) as admin:
        locks = admin.call("locks", top=5)
        assert isinstance(locks, list)
        assert admin.call("log", level="debug") == "debug"
        admin.call("log", level="info")


def test_admin_fault_injection(rig):
    agent, _, _, uds = rig
    victim = agent.n_nodes - 1
    with AdminClient(uds) as admin:
        admin.call("kill", node=victim)
        agent.wait_rounds(2, timeout=60)
        assert not bool(agent.snapshot()["alive"][victim])
        admin.call("cluster_rejoin", node=victim)
        agent.wait_rounds(2, timeout=60)
        assert bool(agent.snapshot()["alive"][victim])
        admin.call("partition", groups=[i % 2 for i in range(agent.n_nodes)])
        admin.call("heal")
    with AdminClient(uds) as admin:
        with pytest.raises(RuntimeError):
            admin.call("no_such_command")


def test_admin_checkpoint_backup(tmp_path, rig):
    _, _, _, uds = rig
    with AdminClient(uds) as admin:
        ck = admin.call("checkpoint", path=str(tmp_path / "ck"))
        assert ck.endswith("ck")
        b = admin.call("backup", path=str(tmp_path / "b.npz"), node=0)
        out = admin.call("restore_backup", path=b, node=2)
        assert out["node"] == 2
        restored = admin.call("restore", path=ck)
        assert "round" in restored


def test_cli_exec_query_sync(rig, capsys):
    _, _, api, uds = rig
    base = ["--api-addr", api.addr, "--api-port", str(api.port),
            "--admin-path", uds]
    assert cli.main(base + ["exec", "INSERT INTO kv (k, v) VALUES ('c', 3)"]) == 0
    out = capsys.readouterr().out
    assert json.loads(out.splitlines()[-1])["rows_affected"] == 1

    assert cli.main(base + ["query", "SELECT k, v FROM kv", "--columns"]) == 0
    out = capsys.readouterr().out
    assert out.splitlines()[0] == "k\tv"
    assert any("c\t3" in line for line in out.splitlines())

    assert cli.main(base + ["sync", "generate", "--node", "0"]) == 0
    assert json.loads(capsys.readouterr().out)["actor_id"] == 0

    assert cli.main(base + ["cluster", "members"]) == 0
    assert len(json.loads(capsys.readouterr().out)) == 16

    assert cli.main(base + ["locks", "--top", "3"]) == 0
    capsys.readouterr()

    assert cli.main(base + ["default-config"]) == 0
    assert "[gossip]" in capsys.readouterr().out


def test_cli_backup_restore(tmp_path, rig, capsys):
    _, _, api, uds = rig
    base = ["--api-addr", api.addr, "--api-port", str(api.port),
            "--admin-path", uds]
    assert cli.main(base + ["backup", str(tmp_path / "cli_b.npz")]) == 0
    path = capsys.readouterr().out.strip()
    assert cli.main(base + ["restore", path, "--node", "1"]) == 0
    assert json.loads(capsys.readouterr().out)["node"] == 1
    assert cli.main(base + ["checkpoint", str(tmp_path / "cli_ck")]) == 0
    ck = capsys.readouterr().out.strip()
    assert cli.main(base + ["restore", ck, "--full"]) == 0


def test_admin_compact(rig):
    """Operator-triggered heap compaction (round 5, vacuum_db analog):
    an unreferenced value frees; the live value stays resolvable."""
    agent, db, _, uds = rig
    # a value UNIQUE to this test, then overwrite it everywhere
    db.execute(0, [("UPDATE kv SET v = 987654 WHERE k = 'a'",)])
    agent.wait_rounds(20, timeout=120)
    vid_old = db.heap.intern(987654)
    db.execute(0, [("UPDATE kv SET v = 987655 WHERE k = 'a'",)])
    agent.wait_rounds(24, timeout=120)  # drain queues everywhere
    import time
    time.sleep(0.1)
    # admin wiring (the live floor keeps recently-touched ids safe)
    with AdminClient(uds) as admin:
        out = admin.call("compact", grace_seconds=0.0)
    assert set(out) == {"freed", "live", "len"} and out["live"] <= out["len"]
    # the freeing semantics themselves, with an immediate grace
    freed = db.compact_heap(grace_seconds=0.0)
    assert freed + out["freed"] >= 1
    with pytest.raises(LookupError):
        db.heap.lookup(vid_old)
    _, rows = db.query(0, "SELECT v FROM kv WHERE k = 'a'")
    assert list(rows) == [[987655]]
