"""The ``BENCH_SCALE_N`` sweep (``scripts/scale_sweep.py``) as a tier
test (ISSUE 19).

One subprocess run at a tiny rung pins the three facts the committed
``artifacts/scale_sweep_r19.json`` claims at 100k/300k: measured HBM
equals corrobudget's static projection EXACTLY, the segmented leg
drains one checkpoint slice per device, and both round variants report
a rounds/s figure. The 1M rung stays out of tier-1: slow-marked and
gated on ``BENCH_SCALE_1M=1`` (a TPU tunnel session — hours on CPU).
"""

import json
import os
import subprocess
import sys

import pytest

SWEEP = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "scripts", "scale_sweep.py")


@pytest.mark.slow
def test_scale_sweep_tiny_rung(tmp_path):
    out = tmp_path / "sweep.json"
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        BENCH_SCALE_N="2048",
        BENCH_SCALE_ROUNDS="6",
        BENCH_SCALE_WARM_RUNS="1",
    )
    proc = subprocess.run(
        [sys.executable, SWEEP, "--output", str(out)],
        capture_output=True, text=True, timeout=400, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())

    assert rec["ok"], rec.get("problems")
    rung = rec["rungs"][0]
    assert rung["n"] == 2048
    assert rung["hbm_agree"] is True
    assert rung["hbm_bytes_measured"] == rung["hbm_bytes_projected"] > 0
    assert rung["rounds_per_s"]["dense"] > 0
    assert rung["rounds_per_s"]["quiet"] > 0
    assert rung["ckpt"]["shards"] == rec["devices"]
    assert rung["ckpt"]["bytes_per_shard"] > 0
    # the 1M rung is always present in the artifact — run or skipped
    # with the tunnel-session pointer, never silently absent
    slow = [r for r in rec["rungs"] if r["n"] >= 1_000_000]
    assert slow and "skipped" in slow[0]


@pytest.mark.slow
def test_scale_sweep_1m_rung(tmp_path):
    """The flagship rung — tunnel-gated on top of the slow mark: it
    prices a 1M-node state and belongs to a TPU session."""
    if os.environ.get("BENCH_SCALE_1M") != "1":
        pytest.skip("1M rung needs BENCH_SCALE_1M=1 (TPU tunnel session)")
    out = tmp_path / "sweep_1m.json"
    env = dict(os.environ)
    env.update(BENCH_SCALE_N="1000000", BENCH_SCALE_ROUNDS="4",
               BENCH_SCALE_WARM_RUNS="1")
    proc = subprocess.run(
        [sys.executable, SWEEP, "--output", str(out)],
        capture_output=True, text=True, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(out.read_text())
    assert rec["ok"], rec.get("problems")
    assert rec["rungs"][0]["hbm_agree"] is True
