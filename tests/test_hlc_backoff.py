"""HLC clock + backoff iterator (reference ``uhlc`` usage and
``crates/backoff``)."""

import random

import pytest

from corrosion_tpu.utils.backoff import Backoff
from corrosion_tpu.utils.hlc import ClockDriftError, HLClock, Timestamp


def test_hlc_monotonic():
    clk = HLClock(actor=7)
    stamps = [clk.new_timestamp() for _ in range(100)]
    assert all(a < b for a, b in zip(stamps, stamps[1:]))
    assert stamps[0].actor == 7


def test_hlc_update_from_remote():
    t = [1_000_000]
    clk = HLClock(actor=1, now_us=lambda: t[0])
    remote = Timestamp(((t[0] + 1000) << 16) | 5, 2)
    clk.update_with_timestamp(remote)
    local = clk.new_timestamp()
    assert local.ntp > remote.ntp  # stays ahead of everything observed


def test_hlc_drift_rejection():
    t = [1_000_000]
    clk = HLClock(actor=1, max_delta_ms=300, now_us=lambda: t[0])
    too_far = Timestamp((t[0] + 400_000) << 16, 2)  # 400 ms ahead
    with pytest.raises(ClockDriftError):
        clk.update_with_timestamp(too_far)


def test_backoff_growth_and_caps():
    b = Backoff(min_wait=1, max_wait=8, factor=2, jitter=0.0,
                rng=random.Random(0))
    it = iter(b)
    vals = [next(it) for _ in range(6)]
    assert vals == [1, 2, 4, 8, 8, 8]


def test_backoff_jitter_bounds():
    b = Backoff(min_wait=1, max_wait=15, jitter=0.5, rng=random.Random(1))
    for i, d in zip(range(50), b):
        assert 1 <= d <= 15


def test_backoff_max_retries():
    b = Backoff(min_wait=1, max_wait=4, jitter=0.0, max_retries=3)
    assert len(list(b)) == 3
