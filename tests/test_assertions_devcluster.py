"""Always/sometimes assertions (Antithesis SDK analog), admin reload, and
the devcluster topology parser."""

import pytest

from corrosion_tpu.cli import parse_topology
from corrosion_tpu.utils.assertions import AssertionRegistry


def test_assert_always_counts_and_strict(monkeypatch):
    reg = AssertionRegistry()
    assert reg.always(True, "inv") is True
    assert reg.always(False, "inv", "details") is False
    assert reg.violations() == {"inv": 1}
    snap = reg.snapshot()
    assert snap["always"]["inv"] == {"passes": 1, "failures": 1}
    monkeypatch.setenv("CORRO_TPU_STRICT_ASSERTS", "1")
    with pytest.raises(AssertionError):
        reg.always(False, "inv")


def test_assert_sometimes_liveness():
    reg = AssertionRegistry()
    reg.sometimes(False, "syncs")
    reg.sometimes(False, "syncs")
    reg.sometimes(True, "delivers")
    rep = reg.liveness_report()
    assert rep["syncs"]["never_hit"] and rep["syncs"]["checks"] == 2
    assert not rep["delivers"]["never_hit"]


def test_unreachable():
    reg = AssertionRegistry()
    reg.unreachable("impossible state")
    assert reg.violations() == {"unreachable: impossible state": 1}


def test_strict_mode_tracks_env_live(monkeypatch):
    """``strict`` reads the env per call: flipping
    CORRO_TPU_STRICT_ASSERTS mid-run arms/disarms raising without
    rebuilding the registry (the admin-reload story)."""
    reg = AssertionRegistry()
    monkeypatch.delenv("CORRO_TPU_STRICT_ASSERTS", raising=False)
    assert not reg.strict
    assert reg.always(False, "soft") is False  # logs + counts, no raise
    monkeypatch.setenv("CORRO_TPU_STRICT_ASSERTS", "1")
    assert reg.strict
    with pytest.raises(AssertionError, match="soft"):
        reg.always(False, "soft", "ctx")
    with pytest.raises(AssertionError, match="unreachable: dead"):
        reg.unreachable("dead")
    # failures kept counting through both modes
    assert reg.violations()["soft"] == 2


def test_strict_mode_never_raises_on_sometimes(monkeypatch):
    """Liveness probes are observations, not invariants: a probe that
    has not fired YET must not kill a strict run."""
    monkeypatch.setenv("CORRO_TPU_STRICT_ASSERTS", "1")
    reg = AssertionRegistry()
    assert reg.sometimes(False, "syncs") is False
    assert reg.liveness_report()["syncs"]["never_hit"]


def test_liveness_report_transitions_and_counts():
    """A probe leaves ``never_hit`` the first time it observes True and
    stays hit; checks/hits count every evaluation."""
    reg = AssertionRegistry()
    reg.sometimes(False, "delivers")
    assert reg.liveness_report()["delivers"] == {
        "checks": 1, "hits": 0, "never_hit": True,
    }
    reg.sometimes(True, "delivers")
    reg.sometimes(False, "delivers")
    rep = reg.liveness_report()["delivers"]
    assert rep == {"checks": 3, "hits": 1, "never_hit": False}
    # independent probes do not share counters
    reg.sometimes(True, "other")
    assert reg.liveness_report()["delivers"]["checks"] == 3


def test_module_helpers_hit_global_registry():
    from corrosion_tpu.utils.assertions import (
        REGISTRY,
        assert_always,
        assert_sometimes,
        assert_unreachable,
    )

    assert_sometimes(True, "test-probe-global")
    rep = REGISTRY.liveness_report()["test-probe-global"]
    assert rep["hits"] >= 1 and not rep["never_hit"]
    assert_always(True, "test-inv-global")
    assert "test-inv-global" not in REGISTRY.violations()
    assert_unreachable("test-unreachable-global")
    assert REGISTRY.violations()["unreachable: test-unreachable-global"] >= 1


def test_parse_topology():
    names, edges, groups = parse_topology("""
        # two components
        a -> b
        b -> c
        d -> e
        loner
    """)
    assert names == ["a", "b", "c", "d", "e", "loner"]
    assert (0, 1) in edges and (3, 4) in edges
    # a,b,c share a group; d,e share another; loner is its own
    assert groups[0] == groups[1] == groups[2]
    assert groups[3] == groups[4] != groups[0]
    assert len({groups[0], groups[3], groups[5]}) == 3


def test_agent_round_assertions_fire():
    """A running agent's round loop populates the global registry."""
    from corrosion_tpu.agent import Agent
    from corrosion_tpu.config import Config
    from corrosion_tpu.utils.assertions import REGISTRY

    cfg = Config()
    cfg.sim.n_nodes = 16
    cfg.sim.m_slots = 8
    cfg.sim.n_origins = 4
    cfg.sim.n_rows = 4
    cfg.sim.n_cols = 2
    cfg.perf.sync_interval = 2
    cfg.gossip.drop_prob = 0.0
    with Agent(cfg) as agent:
        assert agent.wait_rounds(20, timeout=120)
        agent.write(0, 1, 99)
        assert agent.wait_rounds(10, timeout=60)
    snap = REGISTRY.snapshot()
    assert "round counters non-negative" in snap["always"]
    assert snap["always"]["round counters non-negative"]["failures"] == 0
    live = REGISTRY.liveness_report()
    assert not live["SWIM probes are acked"]["never_hit"]
    assert not live["broadcasts deliver changes"]["never_hit"]
