"""Sync server-side load adaptation (VERDICT r2 #8).

The reference caps concurrent sync serves at 3 (``corro-types/src/
agent.rs:143``), rejects clients past the permits
(``corro-agent/src/api/peer/mod.rs:1462-1479``), and adapts its stream
chunk 8 KiB -> 1 KiB for slow peers (``peer/mod.rs:364-368``). The dense
analogs (client shedding at ~4x permits + proportional grant shrink,
``sim/sync.py``) must (a) bound an overloaded server's granted work near
``serve_cap * sync_chunk``, (b) leave unloaded servers at full chunk,
and (c) degrade in a way later sync rounds repair.
"""

import jax.numpy as jnp
import jax.random as jr
import pytest

from corrosion_tpu.sim.scale_step import ScaleSimState, scale_sim_config
from corrosion_tpu.sim.sync import sync_step
from corrosion_tpu.sim.transport import NetModel

N = 64
SERVER_HEAD = 1 << 14  # far more than one chunk


@pytest.fixture()
def rig():
    cfg = scale_sim_config(
        N, n_origins=4, sync_chunk=32, sync_min_chunk=4, serve_cap=3
    )
    st = ScaleSimState.create(cfg)
    # node 0 is far ahead on every origin; everyone else is at zero
    book = st.crdt.book
    head = book.head.at[0, :].set(SERVER_HEAD)
    book = book._replace(head=head, known_max=jnp.maximum(book.known_max, head))
    cst = st.crdt._replace(book=book)
    net = NetModel.create(N, drop_prob=0.0)
    return cfg, cst, net


def overload_peers(cfg):
    """Every node syncs to node 0 only (one lane; others invalid)."""
    peers = jnp.zeros((N, cfg.sync_peers), jnp.int32)
    p_ok = jnp.zeros((N, cfg.sync_peers), bool).at[:, 0].set(True)
    p_ok = p_ok.at[0, :].set(False)  # the server itself doesn't self-sync
    return peers, p_ok


def test_overload_bounds_granted_work(rig):
    cfg, cst, net = rig
    peers, p_ok = overload_peers(cfg)
    alive = jnp.ones(N, bool)
    cst2, ok, info = sync_step(
        cfg, cst, peers, p_ok, alive, net, jr.key(0), go_all=True
    )
    granted = int(info["versions_granted"])
    # 63 clients of one server: without shedding + chunk shrink this
    # would be 63 * 32 * n_origins = 8064 granted versions; the analog
    # bounds expected work near serve_cap * sync_chunk * n_origins = 384
    # (slack 4x for the probabilistic shed)
    assert granted > 0
    assert granted <= 4 * cfg.serve_cap * cfg.sync_chunk * cfg.n_origins
    assert int(info["serve_rejects"]) > 0
    # admitted clients progressed, shed clients did not lose anything
    heads = cst2.book.head[1:, 0]
    assert int(jnp.max(heads)) > 0
    assert int(jnp.min(cst2.book.head)) >= 0


def test_unloaded_server_grants_full_chunk(rig):
    cfg, cst, net = rig
    # a single client (node 1) syncs to node 0: no load, full chunk
    peers = jnp.zeros((N, cfg.sync_peers), jnp.int32)
    p_ok = jnp.zeros((N, cfg.sync_peers), bool).at[1, 0].set(True)
    alive = jnp.ones(N, bool)
    cst2, ok, info = sync_step(
        cfg, cst, peers, p_ok, alive, net, jr.key(1), go_all=True
    )
    assert bool(ok[1, 0])
    assert int(info["serve_rejects"]) == 0
    assert int(cst2.book.head[1, 0]) == cfg.sync_chunk  # ungated grant


def test_overload_is_repaired_by_later_rounds(rig):
    """Shed clients retry on later cohort rounds: total client progress
    keeps growing — degradation is budget-shaped, not starvation."""
    cfg, cst, net = rig
    peers, p_ok = overload_peers(cfg)
    alive = jnp.ones(N, bool)
    key = jr.key(2)
    min_head_prev = 0
    for r in range(40):
        key, sub = jr.split(key)
        cst, ok, info = sync_step(
            cfg, cst, peers, p_ok, alive, net, sub, go_all=True
        )
    min_head = int(jnp.min(cst.book.head[1:, 0]))
    # 40 overloaded rounds at >= sync_min_chunk each for admitted turns:
    # every client must have been admitted at least a few times
    assert min_head > 0
    assert min_head >= cfg.sync_min_chunk


def test_defer_cap_force_admits_starved_clients(rig):
    """A client at the defer cap is admitted unconditionally whatever
    the shed coin flips say (the deterministic anti-starvation bound),
    and its counter resets on the served round."""
    cfg, cst, net = rig
    peers, p_ok = overload_peers(cfg)
    alive = jnp.ones(N, bool)
    cst = cst._replace(
        sync_defer=jnp.full(N, cfg.sync_defer_cap, jnp.int32)
    )
    cst2, ok, info = sync_step(
        cfg, cst, peers, p_ok, alive, net, jr.key(3), go_all=True
    )
    assert int(info["serve_rejects"]) == 0
    assert bool(jnp.all(ok[1:, 0]))
    # every served CLIENT resets; node 0 never requests, so its counter
    # is (correctly) untouched
    assert int(jnp.max(cst2.sync_defer[1:])) == 0
    assert int(jnp.min(cst2.book.head[1:, 0])) > 0
