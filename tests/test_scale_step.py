"""Scale round (piggyback dissemination + sync): convergence tests.

The assertion mirrors the reference's stress tests and Antithesis
``check_bookkeeping.py``: after writes stop, every alive node reaches the
same LWW store, equal heads, and no outstanding needs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import jax.random as jr
import pytest

from corrosion_tpu.sim.scale_step import (
    ScaleRoundInput,
    ScaleSimState,
    scale_crdt_metrics,
    scale_run_rounds,
    scale_sim_config,
)
from corrosion_tpu.sim.transport import NetModel


def quiet_inputs(cfg, rounds):
    z = ScaleRoundInput.quiet(cfg)
    return jax.tree.map(lambda a: jnp.broadcast_to(a, (rounds,) + a.shape), z)


@pytest.fixture(scope="module")
def cfg():
    return scale_sim_config(
        48, m_slots=16, n_origins=4, n_rows=4, n_cols=2, sync_interval=4
    )


def run(cfg, st, net, key, inputs):
    return jax.jit(lambda s, i: scale_run_rounds(cfg, s, net, key, i))(st, inputs)


def test_single_writer_converges(cfg):
    net = NetModel.create(cfg.n_nodes, drop_prob=0.02)
    st = ScaleSimState.create(cfg)
    # warm membership so the piggyback carrier has live channels
    st, _ = run(cfg, st, net, jr.key(0), quiet_inputs(cfg, 40))

    rounds = 30
    inp = quiet_inputs(cfg, rounds)
    n = cfg.n_nodes
    w = jnp.zeros((rounds, n), bool).at[:8, 0].set(True)
    cell = jnp.zeros((rounds, n), jnp.int32).at[:8, 0].set(
        jnp.arange(8, dtype=jnp.int32) % cfg.n_cells
    )
    val = jnp.zeros((rounds, n), jnp.int32).at[:8, 0].set(100 + jnp.arange(8))
    inp = inp._replace(write_mask=w, write_cell=cell, write_val=val)
    st, _ = run(cfg, st, net, jr.key(1), inp)
    # drain: no new writes, let broadcast + sync finish
    st, _ = run(cfg, st, net, jr.key(2), quiet_inputs(cfg, 150))

    m = scale_crdt_metrics(cfg, st)
    assert bool(m["converged"]), f"diverged: {int(m['n_diverged'])} nodes"
    # full convergence implies the store-only milestone (round 5: the
    # collision probe separates them — converged => store_converged)
    assert bool(m["store_converged"])
    # writer's values actually landed everywhere
    assert int(st.crdt.store[1][-1, 0]) >= 100


def test_conflict_heavy_converges(cfg):
    net = NetModel.create(cfg.n_nodes, drop_prob=0.02)
    st = ScaleSimState.create(cfg)
    st, _ = run(cfg, st, net, jr.key(3), quiet_inputs(cfg, 40))

    rounds = 24
    n = cfg.n_nodes
    k1, k2, k3 = jr.split(jr.key(4), 3)
    inp = quiet_inputs(cfg, rounds)
    w = (jr.uniform(k1, (rounds, n)) < 0.5) & (
        jnp.arange(n)[None, :] < cfg.n_origins
    )
    cell = jr.randint(k2, (rounds, n), 0, 2).astype(jnp.int32)
    val = jr.randint(k3, (rounds, n), 0, 1 << 20).astype(jnp.int32)
    inp = inp._replace(write_mask=w, write_cell=cell, write_val=val)
    st, _ = run(cfg, st, net, jr.key(5), inp)
    st, _ = run(cfg, st, net, jr.key(6), quiet_inputs(cfg, 200))

    m = scale_crdt_metrics(cfg, st)
    assert bool(m["converged"]), f"diverged: {int(m['n_diverged'])} nodes"
    assert int(m["total_needs"]) == 0


def test_partition_and_cluster_gating_at_scale():
    """The node-card link predicate must gate exactly like the
    per-element form it replaced: no payload crosses a partition or a
    ClusterId boundary (uni.rs:75-77, peer/mod.rs:1425-1436), and
    healing the partition lets the cluster converge."""
    import functools

    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from corrosion_tpu.sim.scale_step import (
        ScaleRoundInput,
        ScaleSimState,
        scale_crdt_metrics,
        scale_sim_config,
        scale_sim_step,
    )
    from corrosion_tpu.sim.transport import NetModel

    n = 64
    cfg = scale_sim_config(n, n_origins=4, sync_interval=4)
    st = ScaleSimState.create(cfg)
    net = NetModel.create(n, drop_prob=0.0)
    # split: evens vs odds (origins 0..3 land in both groups)
    part = (jnp.arange(n, dtype=jnp.int32) % 2)
    net_split = net._replace(partition=part)
    inp = ScaleRoundInput.quiet(cfg)
    w = inp._replace(
        write_mask=jnp.arange(n) < 4,
        write_cell=jnp.arange(n) % cfg.n_cells,
        write_val=jnp.full(n, 9, jnp.int32),
    )
    step = jax.jit(functools.partial(scale_sim_step, cfg))
    key = jr.key(3)
    st, _ = step(st, net_split, key, w)
    for i in range(30):
        key, sub = jr.split(key)
        st, _ = step(st, net_split, sub, inp)
    km = st.crdt.book.known_max
    # origin 0 (even) is invisible to every odd node; origin 1 (odd)
    # invisible to every even node
    odd = jnp.arange(n) % 2 == 1
    assert int(jnp.max(jnp.where(odd, km[:, 0], 0))) == 0
    assert int(jnp.max(jnp.where(~odd, km[:, 1], 0))) == 0
    # heal -> converge
    for i in range(120):
        key, sub = jr.split(key)
        st, _ = step(st, net, sub, inp)
    m = scale_crdt_metrics(cfg, st)
    assert bool(m["converged"]), int(m["n_diverged"])

    # a foreign ClusterId gates everything, even without partitions
    st2 = ScaleSimState.create(cfg)
    net_cid = net._replace(
        cluster_id=jnp.where(jnp.arange(n) < 32, 0, 1).astype(jnp.int32)
    )
    key2 = jr.key(4)
    st2, _ = step(st2, net_cid, key2, w)
    for i in range(20):
        key2, sub = jr.split(key2)
        st2, _ = step(st2, net_cid, sub, inp)
    km2 = st2.crdt.book.known_max
    back = jnp.arange(n) >= 32
    assert int(jnp.max(jnp.where(back, jnp.max(km2, axis=1), 0))) == 0


def test_bounded_piggyback_detects_churn_and_converges():
    """pig_members > 0 bounds member updates per packet (foca's packet
    bound). Detection, down-conversion, and CRDT convergence must still
    work — fresh rumors have refilled budgets, so they win the bounded
    slots first."""
    import functools

    import jax
    import jax.numpy as jnp
    import jax.random as jr

    from corrosion_tpu.sim.scale import scale_swim_metrics
    from corrosion_tpu.sim.scale_step import (
        ScaleRoundInput,
        ScaleSimState,
        scale_crdt_metrics,
        scale_sim_config,
        scale_sim_step,
    )
    from corrosion_tpu.sim.transport import NetModel

    n = 96
    cfg = scale_sim_config(n, n_origins=8, sync_interval=4, pig_members=8)
    st = ScaleSimState.create(cfg)
    net = NetModel.create(n, drop_prob=0.01)
    inp = ScaleRoundInput.quiet(cfg)
    step = jax.jit(functools.partial(scale_sim_step, cfg))
    key = jr.key(5)
    # writes + a kill burst
    w = inp._replace(
        write_mask=jnp.arange(n) < 8,
        write_cell=jnp.arange(n) % cfg.n_cells,
        write_val=jnp.full(n, 3, jnp.int32),
        kill=(jnp.arange(n) >= n - 4),
    )
    st, _ = step(st, net, key, w)
    for i in range(140):
        key, sub = jr.split(key)
        st, _ = step(st, net, sub, inp)
    sm = scale_swim_metrics(st.swim)
    # dead nodes detected (accuracy counts them only as Down/purged)
    assert float(sm["accuracy"]) > 0.95
    m = scale_crdt_metrics(cfg, st)
    assert bool(m["converged"]), int(m["n_diverged"])


def test_narrow_dtypes_matches_wide_exactly():
    """PERF.md cut #4: int16 HBM planes must be a pure layout change —
    every round's full state (widened for comparison) and every info
    stream must equal the wide-config run bit-for-bit."""
    import dataclasses

    base = scale_sim_config(
        48, m_slots=16, n_origins=4, n_rows=4, n_cols=2, sync_interval=4,
        pig_members=4, narrow_dtypes=False,  # pin the wide arm
    )
    narrow = dataclasses.replace(base, narrow_dtypes=True).validate()
    assert narrow.timer_dtype == jnp.int16

    net = NetModel.create(base.n_nodes, drop_prob=0.02)
    rounds = 48
    key = jr.key(3)
    inp = quiet_inputs(base, rounds)
    n = base.n_nodes
    k1, k2, k3, k4 = jr.split(jr.key(4), 4)
    w = (jr.uniform(k1, (rounds, n)) < 0.3) & (
        jnp.arange(n)[None, :] < base.n_origins
    )
    kills = jnp.zeros((rounds, n), bool).at[10, 5].set(True)
    revs = jnp.zeros((rounds, n), bool).at[30, 5].set(True)
    inp = inp._replace(
        write_mask=w,
        write_cell=jr.randint(k2, (rounds, n), 0, base.n_cells,
                              dtype=jnp.int32),
        write_val=jr.randint(k3, (rounds, n), 1, 1 << 15, dtype=jnp.int32),
        kill=kills, revive=revs,
    )

    st_w, info_w = run(base, ScaleSimState.create(base), net, key, inp)
    st_n, info_n = run(narrow, ScaleSimState.create(narrow), net, key, inp)

    # state planes equal after widening; dtypes actually narrowed
    assert st_n.swim.mem_tx.dtype == jnp.int16
    assert st_n.crdt.q_tx.dtype == jnp.int16
    assert st_n.crdt.last_sync.dtype == jnp.int16
    for a, b in zip(jax.tree.leaves(st_w), jax.tree.leaves(st_n)):
        assert jnp.array_equal(
            jnp.asarray(a, jnp.int32) if a.dtype != bool else a,
            jnp.asarray(b, jnp.int32) if b.dtype != bool else b,
        ), "narrow state diverged from wide"
    for k in info_w:
        assert jnp.array_equal(info_w[k], info_n[k]), f"info {k} diverged"

    # same convergence behavior under churn
    st_n, _ = run(narrow, st_n, net, jr.key(5), quiet_inputs(narrow, 150))
    m = scale_crdt_metrics(narrow, st_n)
    assert bool(m["converged"])


def test_narrow_dtypes_fused_matches_unfused():
    """The pallas kernels must honor the narrow planes (widen on load,
    re-narrow on store) with identical results."""
    import dataclasses

    base = scale_sim_config(
        32, m_slots=8, n_origins=4, n_rows=4, n_cols=2, sync_interval=4,
        pig_members=4, narrow_dtypes=False,  # pin the wide arm
    )
    narrow = dataclasses.replace(base, narrow_dtypes=True).validate()
    net = NetModel.create(base.n_nodes, drop_prob=0.02)
    rounds = 24
    inp = quiet_inputs(narrow, rounds)
    n = base.n_nodes
    k1, k2, k3 = jr.split(jr.key(6), 3)
    w = (jr.uniform(k1, (rounds, n)) < 0.3) & (
        jnp.arange(n)[None, :] < base.n_origins
    )
    inp = inp._replace(
        write_mask=w,
        write_cell=jr.randint(k2, (rounds, n), 0, base.n_cells,
                              dtype=jnp.int32),
        write_val=jr.randint(k3, (rounds, n), 1, 1 << 15, dtype=jnp.int32),
    )
    fused = dataclasses.replace(narrow, fused="interpret").validate()
    unfused = dataclasses.replace(narrow, fused="off").validate()
    st_f, info_f = run(fused, ScaleSimState.create(fused), net,
                       jr.key(7), inp)
    st_u, info_u = run(unfused, ScaleSimState.create(unfused), net,
                       jr.key(7), inp)
    for a, b in zip(jax.tree.leaves(st_f), jax.tree.leaves(st_u)):
        assert jnp.array_equal(a, b), "fused narrow state diverged"
    for k in info_f:
        assert jnp.array_equal(info_f[k], info_u[k]), f"info {k} diverged"


# --- round-4: unbounded writer set (hash-slotted origin table) -----------

def test_any_writer_beyond_origin_pool_converges():
    """Writers with ids >= n_origins (impossible pre-round-4) claim
    hash slots and their writes reach every node; VERDICT r3 #5, the
    reference's per-observed-actor bookkeeping (agent.rs:1270-1604)."""
    cfg = scale_sim_config(
        48, m_slots=16, n_origins=8, n_rows=4, n_cols=2, sync_interval=4,
    )
    assert cfg.any_writer
    net = NetModel.create(cfg.n_nodes, drop_prob=0.02)
    st = ScaleSimState.create(cfg)
    st, _ = run(cfg, st, net, jr.key(0), quiet_inputs(cfg, 40))

    # two high-id writers in DISTINCT hash classes (no eviction churn):
    # 17 % 8 = 1, 30 % 8 = 6
    rounds = 20
    inp = quiet_inputs(cfg, rounds)
    n = cfg.n_nodes
    w = (jnp.zeros((rounds, n), bool)
         .at[:6, 17].set(True).at[:6, 30].set(True))
    cell = jnp.zeros((rounds, n), jnp.int32).at[:6, 30].set(3)
    val = (jnp.zeros((rounds, n), jnp.int32)
           .at[:6, 17].set(500 + jnp.arange(6))
           .at[:6, 30].set(900 + jnp.arange(6)))
    inp = inp._replace(write_mask=w, write_cell=cell, write_val=val)
    st, _ = run(cfg, st, net, jr.key(1), inp)
    st, _ = run(cfg, st, net, jr.key(2), quiet_inputs(cfg, 200))

    m = scale_crdt_metrics(cfg, st)
    assert bool(m["converged"]), f"diverged: {int(m['n_diverged'])}"
    # node 17's write landed on an arbitrary other node, in cell 0
    assert int(st.crdt.store[1][5, 0]) == 505
    assert int(st.crdt.store[1][5, 3]) == 905
    # bookkeeping tracks the foreign actors at their hash slots
    assert int(st.crdt.book.org_id[5, 17 % 8]) == 17
    assert int(st.crdt.book.org_id[5, 30 % 8]) == 30


def test_smaller_id_collider_still_converges_storewise():
    """The monotone claim rule's documented trade (round 5): a writer
    whose id is SMALLER than its slot's tracked actor never takes the
    slot's bookkeeping — but its data must still reach every replica
    (own fanout + the full-store sweep). Store convergence is the
    user-visible guarantee; the slot stays with the larger actor."""
    cfg = scale_sim_config(
        48, m_slots=16, n_origins=8, n_rows=4, n_cols=2, sync_interval=4,
        org_keep_rounds=8,
    )
    net = NetModel.create(cfg.n_nodes, drop_prob=0.0)
    st = ScaleSimState.create(cfg)
    st, _ = run(cfg, st, net, jr.key(0), quiet_inputs(cfg, 40))

    n = cfg.n_nodes
    # writer 10 (slot 10 % 8 = 2) writes FIRST and goes idle; writer 2
    # (same slot, smaller id) writes later — under the monotone rule it
    # must NOT take the slot, yet its cells must converge everywhere
    rounds = 40
    inp = quiet_inputs(cfg, rounds)
    w = (jnp.zeros((rounds, n), bool)
         .at[0:3, 10].set(True).at[25:28, 2].set(True))
    val = (jnp.zeros((rounds, n), jnp.int32)
           .at[0:3, 10].set(200).at[25:28, 2].set(100))
    cell = (jnp.zeros((rounds, n), jnp.int32)
            .at[0:3, 10].set(2).at[25:28, 2].set(1))
    inp = inp._replace(write_mask=w, write_cell=cell, write_val=val)
    st, _ = run(cfg, st, net, jr.key(1), inp)
    st, _ = run(cfg, st, net, jr.key(2), quiet_inputs(cfg, 300))

    m = scale_crdt_metrics(cfg, st)
    assert bool(m["store_converged"]), int(m["n_store_diverged"])
    # both writers' data landed everywhere
    assert int(st.crdt.store[1][7, 2]) == 200
    assert int(st.crdt.store[1][7, 1]) == 100
    # the slot still tracks the LARGER actor (monotone: no downgrade)
    assert int(st.crdt.book.org_id[7, 2]) == 10


def test_wire_budget_restores_displaced_actor_epidemic():
    """Budget-following re-broadcast (round 5, bcast_wire_budget): with
    sync effectively disabled, a displaced smaller-id actor's write
    reaches every node ONLY when receivers re-forward it at the wire
    budget minus one — without the flag, circulation stops at the
    writer's own fanout (receivers hold no bookkeeping for the actor,
    so the classic rec-gate never re-enqueues). Circulation then
    terminates by budget depth: queues drain to empty."""
    import dataclasses

    n = 48
    base = scale_sim_config(
        n, m_slots=16, n_origins=8, n_rows=4, n_cols=2,
        sync_interval=10_000, org_keep_rounds=10_000,
        bcast_max_transmissions=8,
    )
    rounds = 48

    def coverage(cfg):
        net = NetModel.create(n, drop_prob=0.0)
        st = ScaleSimState.create(cfg)
        st, _ = run(cfg, st, net, jr.key(0), quiet_inputs(cfg, 30))
        # org slots initialize to IDENTITY (slot c tracks actor c) and
        # the huge keep_rounds means nothing ever evicts: actor 10
        # (slot 10 % 8 = 2, owned by actor 2 everywhere) is permanently
        # bookkeeping-less — the displaced regime, with no setup phase
        assert int((np.asarray(st.crdt.book.org_id)[:, 2] == 2).sum()) == n
        inp = quiet_inputs(cfg, rounds)
        w = jnp.zeros((rounds, n), bool).at[0:2, 10].set(True)
        inp = inp._replace(
            write_mask=w,
            write_cell=jnp.ones((rounds, n), jnp.int32),
            write_val=jnp.zeros((rounds, n), jnp.int32)
            .at[0:2, 10].set(900),
        )
        st, infos = run(cfg, st, net, jr.key(2), inp)
        got = np.asarray(st.crdt.store[1])[:, 1] == 900
        return int(got.sum()), int(np.asarray(infos["queued"])[-1]), st

    cov_off, _, _ = coverage(base)
    cov_on, _, st_on = coverage(
        dataclasses.replace(base, bcast_wire_budget=True))
    # near-total epidemic coverage (budget depth 4 over random fanout
    # can stochastically miss a node or two with sync disabled — the
    # sweep backstop is what guarantees the tail in real configs)
    assert cov_on >= n - 2, f"epidemic incomplete: {cov_on}/{n}"
    assert cov_off < n // 2 and cov_on > 3 * cov_off, (
        f"arms no longer discriminate: on={cov_on} off={cov_off}"
    )
    # bounded circulation: the budget depth exhausts and queues drain
    cfg_on = dataclasses.replace(base, bcast_wire_budget=True)
    net = NetModel.create(n, drop_prob=0.0)
    st_on, infos = run(cfg_on, st_on, net, jr.key(3),
                       quiet_inputs(cfg_on, 40))
    assert int(np.asarray(infos["queued"])[-1]) == 0


def test_slot_eviction_idle_owner_loses():
    """A colliding writer evicts an idle slot occupant after
    org_keep_rounds; the cluster still converges (sync rebuilds)."""
    cfg = scale_sim_config(
        48, m_slots=16, n_origins=8, n_rows=4, n_cols=2, sync_interval=4,
        org_keep_rounds=8,
    )
    net = NetModel.create(cfg.n_nodes, drop_prob=0.0)
    st = ScaleSimState.create(cfg)
    st, _ = run(cfg, st, net, jr.key(0), quiet_inputs(cfg, 40))

    n = cfg.n_nodes
    # writer 2 (slot 2) writes, then goes idle; writer 10 (10 % 8 = 2,
    # same slot) writes later and must take the slot
    rounds = 40
    inp = quiet_inputs(cfg, rounds)
    w = (jnp.zeros((rounds, n), bool)
         .at[0:3, 2].set(True).at[25:28, 10].set(True))
    val = (jnp.zeros((rounds, n), jnp.int32)
           .at[0:3, 2].set(100).at[25:28, 10].set(200))
    cell = (jnp.zeros((rounds, n), jnp.int32)
            .at[0:3, 2].set(1).at[25:28, 10].set(2))
    inp = inp._replace(write_mask=w, write_cell=cell, write_val=val)
    st, _ = run(cfg, st, net, jr.key(1), inp)
    st, _ = run(cfg, st, net, jr.key(2), quiet_inputs(cfg, 200))

    m = scale_crdt_metrics(cfg, st)
    assert bool(m["converged"])
    # both writers' cells landed everywhere despite the shared slot
    assert int(st.crdt.store[1][7, 1]) == 100
    assert int(st.crdt.store[1][7, 2]) == 200
    # the slot now tracks the later writer
    assert int(st.crdt.book.org_id[7, 2]) == 10


def test_any_writer_fused_matches_unfused():
    """The ingest kernel's claim/evict path must equal the XLA form."""
    import dataclasses

    cfg = scale_sim_config(
        32, m_slots=8, n_origins=4, n_rows=4, n_cols=2, sync_interval=4,
        org_keep_rounds=4,
    )
    net = NetModel.create(cfg.n_nodes, drop_prob=0.02)
    rounds = 24
    inp = quiet_inputs(cfg, rounds)
    n = cfg.n_nodes
    k1, k2, k3 = jr.split(jr.key(8), 3)
    # writers all over the id space, colliding classes included
    w = jr.uniform(k1, (rounds, n)) < 0.15
    inp = inp._replace(
        write_mask=w,
        write_cell=jr.randint(k2, (rounds, n), 0, cfg.n_cells,
                              dtype=jnp.int32),
        write_val=jr.randint(k3, (rounds, n), 1, 1 << 15, dtype=jnp.int32),
    )
    fused = dataclasses.replace(cfg, fused="interpret").validate()
    unfused = dataclasses.replace(cfg, fused="off").validate()
    st_f, info_f = run(fused, ScaleSimState.create(fused), net,
                       jr.key(9), inp)
    st_u, info_u = run(unfused, ScaleSimState.create(unfused), net,
                       jr.key(9), inp)
    for a, b in zip(jax.tree.leaves(st_f), jax.tree.leaves(st_u)):
        assert jnp.array_equal(a, b), "fused any-writer state diverged"
    for k in info_f:
        assert jnp.array_equal(info_f[k], info_u[k]), f"info {k} diverged"


def test_colliding_active_writers_store_converges_via_sweep():
    """Two actors in the SAME hash class, both continuously active:
    bounded bookkeeping cannot range-track both, but the periodic
    full-store sweep lane must still converge the STORE (review r4:
    without it a gossip-dropped change could diverge permanently)."""
    cfg = scale_sim_config(
        48, m_slots=16, n_origins=8, n_rows=4, n_cols=2, sync_interval=4,
        org_keep_rounds=1 << 14,  # occupants effectively never idle
        sync_sweep_every=2,
    )
    net = NetModel.create(cfg.n_nodes, drop_prob=0.10)  # heavy loss
    st = ScaleSimState.create(cfg)
    st, _ = run(cfg, st, net, jr.key(0), quiet_inputs(cfg, 40))

    n = cfg.n_nodes
    rounds = 40
    inp = quiet_inputs(cfg, rounds)
    # actors 3 and 11 share slot 3 (11 % 8 == 3); both write many rounds
    w = (jnp.zeros((rounds, n), bool)
         .at[:30, 3].set(True).at[:30, 11].set(True))
    cell = (jnp.zeros((rounds, n), jnp.int32)
            .at[:30, 3].set(1).at[:30, 11].set(2))
    val = (jnp.zeros((rounds, n), jnp.int32)
           .at[:30, 3].set(1000 + jnp.arange(30))
           .at[:30, 11].set(2000 + jnp.arange(30)))
    inp = inp._replace(write_mask=w, write_cell=cell, write_val=val)
    st, _ = run(cfg, st, net, jr.key(1), inp)
    st, _ = run(cfg, st, net, jr.key(2), quiet_inputs(cfg, 300))

    # stores equal everywhere (the predicate's store clause); head
    # alignment is per-tracked-actor and needs settle via the sweep
    m = scale_crdt_metrics(cfg, st)
    assert bool(m["converged"]), f"diverged: {int(m['n_diverged'])}"
    assert int(st.crdt.store[1][20, 1]) == 1029
    assert int(st.crdt.store[1][20, 2]) == 2029


def test_flagship_combination_narrow_pig_anywriter_fused():
    """The full bench configuration in one: narrow dtypes + bounded
    piggyback + unbounded writers, fused == unfused, and converges."""
    import dataclasses

    cfg = scale_sim_config(
        32, m_slots=8, n_origins=4, n_rows=4, n_cols=2, sync_interval=4,
        pig_members=4, narrow_dtypes=True, org_keep_rounds=4,
    )
    assert cfg.any_writer and cfg.narrow_dtypes and cfg.pig_members
    net = NetModel.create(cfg.n_nodes, drop_prob=0.02)
    rounds = 24
    inp = quiet_inputs(cfg, rounds)
    n = cfg.n_nodes
    k1, k2, k3 = jr.split(jr.key(10), 3)
    w = jr.uniform(k1, (rounds, n)) < 0.2  # writers across the id space
    inp = inp._replace(
        write_mask=w,
        write_cell=jr.randint(k2, (rounds, n), 0, cfg.n_cells,
                              dtype=jnp.int32),
        write_val=jr.randint(k3, (rounds, n), 1, 1 << 15, dtype=jnp.int32),
    )
    fused = dataclasses.replace(cfg, fused="interpret").validate()
    unfused = dataclasses.replace(cfg, fused="off").validate()
    st_f, _ = run(fused, ScaleSimState.create(fused), net, jr.key(11), inp)
    st_u, _ = run(unfused, ScaleSimState.create(unfused), net,
                  jr.key(11), inp)
    for a, b in zip(jax.tree.leaves(st_f), jax.tree.leaves(st_u)):
        assert jnp.array_equal(a, b), "flagship-combination fused diverged"
    # drain and converge (on the unfused state; they are equal anyway).
    # 300 rounds: the over-capacity regime converges its books through
    # sweep-lane lattice joins, whose uniform pairing mixes in O(N)
    # sweeps — slower than range-grant sync but unconditional
    st_u, _ = run(cfg, st_u, net, jr.key(12), quiet_inputs(cfg, 300))
    m = scale_crdt_metrics(cfg, st_u)
    assert bool(m["converged"]), f"diverged: {int(m['n_diverged'])}"


# --- ISSUE 12: the corrobudget-identified int8 shrink (mem_tx) ----------

def _int8_write_rig(n_nodes=48, rounds=40):
    import dataclasses

    base = scale_sim_config(
        n_nodes, m_slots=16, n_origins=4, n_rows=4, n_cols=2,
        sync_interval=4, pig_members=4, narrow_dtypes=True,
    )
    i8 = dataclasses.replace(base, narrow_int8=True).validate()
    net = NetModel.create(base.n_nodes, drop_prob=0.02)
    inp = quiet_inputs(base, rounds)
    n = base.n_nodes
    k1, k2, k3 = jr.split(jr.key(40), 3)
    w = jr.uniform(k1, (rounds, n)) < 0.3
    inp = inp._replace(
        write_mask=w,
        write_cell=jr.randint(k2, (rounds, n), 0, base.n_cells,
                              dtype=jnp.int32),
        write_val=jr.randint(k3, (rounds, n), 1, 1 << 15, dtype=jnp.int32),
        kill=jnp.zeros((rounds, n), bool).at[8, 3].set(True),
        revive=jnp.zeros((rounds, n), bool).at[25, 3].set(True),
    )
    return base, i8, net, inp


def test_narrow_int8_matches_int16_exactly():
    """The ISSUE-12 shrink must be a pure layout change: the int8
    ``mem_tx`` arm equals the int16 arm bit-for-bit (state widened for
    comparison) on a churny written trace, and the dtype actually
    narrowed — corrobudget's projection maths is only honest if the
    narrowed plane is semantics-free."""
    base, i8, net, inp = _int8_write_rig()
    assert i8.tx_dtype == jnp.int8 and base.tx_dtype == jnp.int16

    st16, info16 = run(base, ScaleSimState.create(base), net, jr.key(41),
                       inp)
    st8, info8 = run(i8, ScaleSimState.create(i8), net, jr.key(41), inp)
    assert st8.swim.mem_tx.dtype == jnp.int8
    assert st8.swim.mem_timer.dtype == jnp.int16  # timer stays 16
    for a, b in zip(jax.tree.leaves(st16), jax.tree.leaves(st8)):
        wa = a if a.dtype == bool else jnp.asarray(a, jnp.int32)
        wb = b if b.dtype == bool else jnp.asarray(b, jnp.int32)
        assert jnp.array_equal(wa, wb), "int8 state diverged from int16"
    for k in info16:
        assert jnp.array_equal(info16[k], info8[k]), f"info {k} diverged"


def test_narrow_int8_validation():
    import dataclasses

    base = scale_sim_config(32, m_slots=8)
    with pytest.raises(ValueError, match="tier of narrow_dtypes"):
        dataclasses.replace(base, narrow_dtypes=False,
                            narrow_int8=True).validate()
    with pytest.raises(ValueError, match="int8 range"):
        dataclasses.replace(base, narrow_dtypes=True, narrow_int8=True,
                            max_transmissions=200).validate()
    # the dtype-flow registry guards the shrunk leaf at 8 bits
    from corrosion_tpu.analysis.dtypes import NARROW_LEAVES, NARROW_REFS

    assert NARROW_LEAVES["mem_tx"] == 8 and NARROW_REFS["o_tx"] == 8


def test_narrow_int8_fused_matches_unfused():
    """The pallas swim kernel under the int8 budget plane (widen on
    load, cast back at the out-ref store) — the probe cache keys the
    int8 dtype set separately (``tx8``), so the probed kernel is the
    dispatched kernel."""
    import dataclasses

    _, i8, net, inp = _int8_write_rig(n_nodes=32, rounds=24)
    fused = dataclasses.replace(i8, fused="interpret").validate()
    unfused = dataclasses.replace(i8, fused="off").validate()
    st_f, info_f = run(fused, ScaleSimState.create(fused), net,
                       jr.key(42), inp)
    st_u, info_u = run(unfused, ScaleSimState.create(unfused), net,
                       jr.key(42), inp)
    assert st_f.swim.mem_tx.dtype == jnp.int8
    for a, b in zip(jax.tree.leaves(st_f), jax.tree.leaves(st_u)):
        assert jnp.array_equal(a, b), "fused int8 state diverged"
    for k in info_f:
        assert jnp.array_equal(info_f[k], info_u[k]), f"info {k} diverged"


@pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 virtual devices")
def test_narrow_int8_sharded_matches_single_device():
    """The int8 plane through the REAL donated mesh entry point
    (``sharded_scale_run``): bitwise == single device, carry donated,
    mem_tx still int8 on the way out."""
    from corrosion_tpu.parallel.mesh import make_mesh, shard_state, sharded_scale_run

    _, i8, net, inp = _int8_write_rig(n_nodes=48, rounds=16)
    st = ScaleSimState.create(i8)
    key = jr.key(43)
    ref, ref_infos = jax.jit(
        lambda s, k, i: scale_run_rounds(i8, s, net, k, i)
    )(st, key, inp)
    jax.block_until_ready(ref)

    mesh = make_mesh(jax.devices()[:8])
    st_s = shard_state(mesh, i8.n_nodes, st)
    net_s = shard_state(mesh, i8.n_nodes, net)
    in_s = shard_state(mesh, i8.n_nodes, inp)
    probe = st_s
    out, infos = sharded_scale_run(i8, mesh, st_s, net_s, key, in_s)
    jax.block_until_ready(out)

    assert any(leaf.is_deleted() for leaf in jax.tree.leaves(probe))
    assert out.swim.mem_tx.dtype == jnp.int8
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(out)):
        assert jnp.array_equal(a, b), "sharded int8 state diverged"
    for k in ref_infos:
        assert jnp.array_equal(ref_infos[k], infos[k]), k


# --- ISSUE 19: the int8 queue-counter tier (q_tx/q_seq/q_nseq) ----------

def _q_int8_rig(n_nodes=48, rounds=40, tx_cells=3):
    """Churny written trace with chunked transactions, so q_seq/q_nseq
    actually count past their initializers."""
    import dataclasses

    base = scale_sim_config(
        n_nodes, m_slots=16, n_origins=4, n_rows=4, n_cols=2,
        sync_interval=4, pig_members=4, narrow_dtypes=True,
        tx_max_cells=tx_cells,
    )
    q8 = dataclasses.replace(base, narrow_q_int8=True).validate()
    net = NetModel.create(base.n_nodes, drop_prob=0.02)
    inp = quiet_inputs(base, rounds)
    n = base.n_nodes
    k1, k2, k3, k4 = jr.split(jr.key(50), 4)
    w = jr.uniform(k1, (rounds, n)) < 0.25
    t = (jr.uniform(k4, (rounds, n)) < 0.15) & ~w
    start = jr.randint(k2, (rounds, n), 0, base.n_cells, dtype=jnp.int32)
    tx_cell = (start[..., None] + jnp.arange(tx_cells)) % base.n_cells
    inp = inp._replace(
        write_mask=w,
        write_cell=start,
        write_val=jr.randint(k3, (rounds, n), 1, 1 << 15, dtype=jnp.int32),
        tx_mask=t,
        tx_len=jnp.full((rounds, n), tx_cells, jnp.int32),
        tx_cell=tx_cell,
        tx_val=jr.randint(k3, (rounds, n, tx_cells), 1, 1 << 15,
                          dtype=jnp.int32),
        kill=jnp.zeros((rounds, n), bool).at[8, 3].set(True),
        revive=jnp.zeros((rounds, n), bool).at[25, 3].set(True),
    )
    return base, q8, net, inp


def test_narrow_q_int8_matches_int16_exactly():
    """The ISSUE-19 queue shrink must be a pure layout change: the int8
    q_tx/q_seq/q_nseq arm equals the int16 arm bit-for-bit (widened for
    comparison) on a churny chunked-transaction trace, and only the
    counter planes narrowed."""
    base, q8, net, inp = _q_int8_rig()
    assert q8.q_dtype == jnp.int8 and base.q_dtype == jnp.int16

    st16, info16 = run(base, ScaleSimState.create(base), net, jr.key(51),
                       inp)
    st8, info8 = run(q8, ScaleSimState.create(q8), net, jr.key(51), inp)
    for plane in ("q_tx", "q_seq", "q_nseq"):
        assert getattr(st8.crdt, plane).dtype == jnp.int8, plane
    assert st8.crdt.q_cell.dtype == jnp.int16  # grid ids stay 16
    assert st8.crdt.last_sync.dtype == jnp.int16  # 4095 cap stays 16
    # the chunked txs must have actually exercised the counters
    assert int(jnp.max(st16.crdt.q_nseq)) > 1
    for a, b in zip(jax.tree.leaves(st16), jax.tree.leaves(st8)):
        wa = a if a.dtype == bool else jnp.asarray(a, jnp.int32)
        wb = b if b.dtype == bool else jnp.asarray(b, jnp.int32)
        assert jnp.array_equal(wa, wb), "int8 q state diverged from int16"
    for k in info16:
        assert jnp.array_equal(info16[k], info8[k]), f"info {k} diverged"


def test_narrow_q_int8_fused_matches_unfused():
    """The fused ingest kernel under the int8 queue planes — the probe
    cache keys the q dtype set separately, so the probed kernel is the
    dispatched kernel."""
    import dataclasses

    _, q8, net, inp = _q_int8_rig(n_nodes=32, rounds=24)
    fused = dataclasses.replace(q8, fused="interpret").validate()
    unfused = dataclasses.replace(q8, fused="off").validate()
    st_f, info_f = run(fused, ScaleSimState.create(fused), net,
                       jr.key(52), inp)
    st_u, info_u = run(unfused, ScaleSimState.create(unfused), net,
                       jr.key(52), inp)
    assert st_f.crdt.q_tx.dtype == jnp.int8
    for a, b in zip(jax.tree.leaves(st_f), jax.tree.leaves(st_u)):
        assert jnp.array_equal(a, b), "fused int8 q state diverged"
    for k in info_f:
        assert jnp.array_equal(info_f[k], info_u[k]), f"info {k} diverged"


def test_narrow_q_int8_quiet_composes():
    """int8 queue planes under the quiet round variant: both perf tiers
    stacked still equal the plain dense int16 arm bit-for-bit."""
    import dataclasses

    base, q8, net, inp = _q_int8_rig(n_nodes=32, rounds=24)
    quiet8 = dataclasses.replace(q8, quiet="on").validate()
    st_ref, _ = run(base, ScaleSimState.create(base), net, jr.key(53), inp)
    st_q8, _ = run(quiet8, ScaleSimState.create(quiet8), net, jr.key(53),
                   inp)
    assert st_q8.crdt.q_tx.dtype == jnp.int8
    for a, b in zip(jax.tree.leaves(st_ref), jax.tree.leaves(st_q8)):
        wa = a if a.dtype == bool else jnp.asarray(a, jnp.int32)
        wb = b if b.dtype == bool else jnp.asarray(b, jnp.int32)
        assert jnp.array_equal(wa, wb), "quiet int8 q state diverged"


def test_narrow_q_int8_validation():
    import dataclasses

    base = scale_sim_config(32, m_slots=8)
    with pytest.raises(ValueError, match="tier of narrow_dtypes"):
        dataclasses.replace(base, narrow_dtypes=False,
                            narrow_q_int8=True).validate()
    with pytest.raises(ValueError, match="int8 range"):
        dataclasses.replace(base, narrow_dtypes=True, narrow_q_int8=True,
                            bcast_max_transmissions=200).validate()
    # the dtype-flow registry guards the shrunk leaves at 8 bits, and a
    # pre-ISSUE-19 checkpoint restores as the default-off tier
    from corrosion_tpu.analysis.dtypes import NARROW_LEAVES, NARROW_REFS
    from corrosion_tpu.checkpoint import COMPAT_DEFAULT_CONFIG_KEYS

    assert all(NARROW_LEAVES[p] == 8 for p in ("q_tx", "q_seq", "q_nseq"))
    assert NARROW_REFS["o_q_tx"] == 8
    assert COMPAT_DEFAULT_CONFIG_KEYS["narrow_q_int8"] is False
