"""corroload harness (ISSUE 16): seeded op plans, client-side
percentiles, one end-to-end load run, and the BENCH_SERVE schema
docs-sync gate."""

import os

from corrosion_tpu.obs.load import percentiles, plan_ops


def test_plan_ops_deterministic():
    """(seed, shape) fully determines the op streams and the digest the
    BENCH_SERVE record carries — reruns are byte-identical plans."""
    a = plan_ops(7, writers=3, write_ops=16, pg_readers=2, pg_ops=8,
                 keys=10)
    b = plan_ops(7, writers=3, write_ops=16, pg_readers=2, pg_ops=8,
                 keys=10)
    assert a == b
    assert len(a["writers"]) == 3 and len(a["writers"][0]) == 16
    assert len(a["pg"]) == 2 and len(a["pg"][0]) == 8
    assert all(0 <= k < 10 for ops in a["writers"] + a["pg"] for k in ops)
    # per-leg streams are independent (not one stream copied around)
    assert a["writers"][0] != a["writers"][1]
    c = plan_ops(8, writers=3, write_ops=16, pg_readers=2, pg_ops=8,
                 keys=10)
    assert c["digest"] != a["digest"]


def test_percentiles_exact():
    """Client-side percentiles are exact order statistics with linear
    interpolation — checked against a known distribution."""
    samples = [i / 100.0 for i in range(1, 101)]  # 0.01 .. 1.00
    p = percentiles(samples)
    assert abs(p["p50"] - 0.505) < 1e-9
    assert abs(p["p95"] - 0.9505) < 1e-9
    assert abs(p["p99"] - 0.9901) < 1e-9
    assert percentiles([]) == {"p50": 0.0, "p95": 0.0, "p99": 0.0}
    assert percentiles([0.25])["p99"] == 0.25


def test_run_load_end_to_end():
    """One small load run against a real in-process rig: the record is
    well-formed, every op class saw traffic, and the server-vs-client
    agreement gates hold."""
    from corrosion_tpu.obs.load import run_load

    rec = run_load(writers=2, subscribers=1, pg_readers=1, write_ops=3,
                   pg_ops=3, keys=4, seed=3, warm_rounds=6)
    assert rec["ok"], rec["problems"]
    assert rec["kind"] == "bench_serve" and rec["schema"] == 1
    assert rec["plan_digest"] == plan_ops(
        3, writers=2, write_ops=3, pg_readers=1, pg_ops=3, keys=4
    )["digest"]
    assert rec["ops"]["write"]["count"] == 6
    assert rec["ops"]["pg_query"]["count"] == 3
    assert rec["ops"]["subscribe_delivery"]["count"] > 0
    assert rec["ops"]["write"]["p99"] >= rec["ops"]["write"]["p50"] > 0
    assert rec["qps"] > 0 and rec["duration_s"] > 0
    assert rec["agreement"]["ok"]
    assert rec["agreement"]["transactions"]["server"] == \
        rec["agreement"]["transactions"]["client"]
    assert rec["server"]["deliveries"] >= rec[
        "ops"]["subscribe_delivery"]["count"]
    assert rec["server"]["delivery_quantiles_s"]["p50"] >= 0.0


def test_bench_serve_schema_documented():
    """Every field the harness writes into the BENCH_SERVE record
    appears in the schema section of docs/observability.md (the flight-
    record doc-gate pattern)."""
    doc = open(os.path.join(os.path.dirname(__file__), "..", "docs",
                            "observability.md")).read()
    for field in ("plan_digest", "duration_s", "qps", "write",
                  "pg_query", "subscribe_delivery", "http_503",
                  "tx_requests", "pg_selects", "deliveries",
                  "delivery_quantiles_s", "unready_total", "shed_total",
                  "agreement", "corrosan"):
        assert f"`{field}`" in doc, f"BENCH_SERVE field {field} undocumented"
