"""corroload harness (ISSUE 16): seeded op plans, client-side
percentiles, one end-to-end load run, and the BENCH_SERVE schema
docs-sync gate."""

import os

import pytest

from corrosion_tpu.obs.load import percentiles, plan_ops


def test_plan_ops_deterministic():
    """(seed, shape) fully determines the op streams and the digest the
    BENCH_SERVE record carries — reruns are byte-identical plans."""
    a = plan_ops(7, writers=3, write_ops=16, pg_readers=2, pg_ops=8,
                 keys=10)
    b = plan_ops(7, writers=3, write_ops=16, pg_readers=2, pg_ops=8,
                 keys=10)
    assert a == b
    assert len(a["writers"]) == 3 and len(a["writers"][0]) == 16
    assert len(a["pg"]) == 2 and len(a["pg"][0]) == 8
    assert all(0 <= k < 10 for ops in a["writers"] + a["pg"] for k in ops)
    # per-leg streams are independent (not one stream copied around)
    assert a["writers"][0] != a["writers"][1]
    c = plan_ops(8, writers=3, write_ops=16, pg_readers=2, pg_ops=8,
                 keys=10)
    assert c["digest"] != a["digest"]


def test_percentiles_exact():
    """Client-side percentiles are exact order statistics with linear
    interpolation — checked against a known distribution."""
    samples = [i / 100.0 for i in range(1, 101)]  # 0.01 .. 1.00
    p = percentiles(samples)
    assert abs(p["p50"] - 0.505) < 1e-9
    assert abs(p["p95"] - 0.9505) < 1e-9
    assert abs(p["p99"] - 0.9901) < 1e-9
    assert percentiles([]) == {"p50": 0.0, "p95": 0.0, "p99": 0.0}
    assert percentiles([0.25])["p99"] == 0.25


def test_run_load_end_to_end():
    """One small load run against a real in-process rig: the record is
    well-formed, every op class saw traffic, and the server-vs-client
    agreement gates hold."""
    from corrosion_tpu.obs.load import run_load

    rec = run_load(writers=2, subscribers=1, pg_readers=1, write_ops=3,
                   pg_ops=3, keys=4, seed=3, warm_rounds=6)
    assert rec["ok"], rec["problems"]
    assert rec["kind"] == "bench_serve" and rec["schema"] == 1
    assert rec["plan_digest"] == plan_ops(
        3, writers=2, write_ops=3, pg_readers=1, pg_ops=3, keys=4
    )["digest"]
    assert rec["ops"]["write"]["count"] == 6
    assert rec["ops"]["pg_query"]["count"] == 3
    assert rec["ops"]["subscribe_delivery"]["count"] > 0
    assert rec["ops"]["write"]["p99"] >= rec["ops"]["write"]["p50"] > 0
    assert rec["qps"] > 0 and rec["duration_s"] > 0
    assert rec["agreement"]["ok"]
    assert rec["agreement"]["transactions"]["server"] == \
        rec["agreement"]["transactions"]["client"]
    assert rec["server"]["deliveries"] >= rec[
        "ops"]["subscribe_delivery"]["count"]
    assert rec["server"]["delivery_quantiles_s"]["p50"] >= 0.0


def test_bench_serve_schema_documented():
    """Every field the harness writes into the BENCH_SERVE record
    appears in the schema section of docs/observability.md (the flight-
    record doc-gate pattern)."""
    doc = open(os.path.join(os.path.dirname(__file__), "..", "docs",
                            "observability.md")).read()
    for field in ("plan_digest", "duration_s", "qps", "write",
                  "pg_query", "subscribe_delivery", "http_503",
                  "tx_requests", "pg_selects", "deliveries",
                  "delivery_quantiles_s", "unready_total", "shed_total",
                  "agreement", "corrosan"):
        assert f"`{field}`" in doc, f"BENCH_SERVE field {field} undocumented"


# --- the corroguard overload harness (PR 17, docs/overload.md) ------------


def test_plan_overload_deterministic():
    """(seed, shape) fully determines the overload op plan — ramp-stage
    writer streams and the closed-loop stream — and its digest."""
    from corrosion_tpu.obs.load import plan_overload

    a = plan_overload(9, stages=(2, 4), write_ops=6, keys=8,
                      closed_loop_ops=5)
    b = plan_overload(9, stages=(2, 4), write_ops=6, keys=8,
                      closed_loop_ops=5)
    assert a == b
    assert len(a["stages"]) == 2
    assert [len(w) for w in a["stages"][1]] == [6] * 4
    assert len(a["closed_loop"]) == 5
    c = plan_overload(10, stages=(2, 4), write_ops=6, keys=8,
                      closed_loop_ops=5)
    assert c["digest"] != a["digest"]


def test_bench_serve_overload_schema_documented():
    """Every field of the bench_serve_overload record (and its per-arm
    serve_overload records) is documented in docs/observability.md."""
    doc = open(os.path.join(os.path.dirname(__file__), "..", "docs",
                            "observability.md")).read()
    for field in ("stage_stats", "delivery_lag_s", "slow_delivery_lag_s",
                  "resyncs", "frames_dropped", "closed_loop",
                  "attempts_503", "retry_delays", "pg_probe",
                  "leaked_threads", "contract", "lag_bound_s",
                  "delivery_p99_s", "lag_bounded", "shed_monotone",
                  "pressure_final", "absorbed", "guarded", "unguarded",
                  "contract_holds_guarded", "contract_violated_unguarded",
                  "admission_rejected_total", "subs_shed_total",
                  "unready_overloaded_total"):
        assert f"`{field}`" in doc, f"overload field {field} undocumented"


def test_run_overload_guarded_small_end_to_end():
    """A small guarded overload run against a deliberately tiny guard:
    the ramp sheds, the record is well-formed, the server-vs-client
    agreement holds (503s included), and nothing leaks."""
    from corrosion_tpu.config import ServeConfig
    from corrosion_tpu.obs.load import plan_overload, run_overload

    serve = ServeConfig(max_inflight=1, max_queue=0, queue_wait=0.02,
                        max_streams=8, retry_after_cap=5.0,
                        sub_queue=2, sub_shed_threshold=1 << 30,
                        stream_sndbuf=4608)
    rec = run_overload(stages=(2, 4), write_ops=12, subscribers=2,
                       slow_subs=1, slow_ms=25.0, keys=16,
                       closed_loop_ops=6, pg_probes=3, seed=11,
                       warm_rounds=6, serve=serve)
    assert rec["kind"] == "serve_overload" and rec["guard"]
    assert rec["plan_digest"] == plan_overload(
        11, stages=(2, 4), write_ops=12, keys=16,
        closed_loop_ops=6)["digest"]
    assert len(rec["stage_stats"]) == 2
    # the tiny guard actually shed under the ramp
    assert rec["contract"]["pressure_final"] > 0
    assert rec["contract"]["shed_monotone"]
    # the polite closed-loop client was absorbed whole
    assert rec["closed_loop"]["done"] == 6
    assert rec["closed_loop"]["failed"] == 0
    # agreement: every client attempt (503s included) server-accounted
    assert rec["agreement"]["ok"], rec["agreement"]
    assert rec["leaked_threads"] == []
    assert rec["ok"], rec["problems"]


@pytest.mark.slow
def test_run_overload_bench_degradation_contract():
    """The full two-arm bench: the guard holds the degradation contract
    under the default ramp AND the unguarded plane demonstrably
    violates the same lag bound — the check.sh overload-stage gate."""
    from corrosion_tpu.obs.load import run_overload_bench

    rec = run_overload_bench(seed=0, n_nodes=8)
    assert rec["kind"] == "bench_serve_overload"
    assert rec["contract_holds_guarded"], rec["guarded"]["contract"]
    assert rec["contract_violated_unguarded"], rec["unguarded"]["contract"]
    assert rec["ok"]
    g, u = rec["guarded"], rec["unguarded"]
    assert g["contract"]["delivery_p99_s"] <= g["contract"]["lag_bound_s"]
    assert u["contract"]["delivery_p99_s"] > u["contract"]["lag_bound_s"]
    # Retry-After honored at least once by the closed-loop client in
    # the guarded arm means the hint plumbing ran end to end
    assert g["closed_loop"]["failed"] == 0
