"""corroquiet (ISSUE 19): the quiescence-gated active-set round.

The one contract: ``scale_sim_step_quiet`` is bitwise-indistinguishable
from the dense round on ANY trace — quiet, seeded-write, kill/revive
churn, every registry chaos scenario — while cheap-pathing provably
settled rounds. Plus the execution-only checkpoint surface: a lineage
written under one round variant resumes under the other, bit for bit
(``checkpoint.EXECUTION_ONLY_CONFIG_KEYS``), and the segmented runner's
host fast path (``segments.run_segmented`` under ``quiet="auto"``)
short-circuits fully-quiet segments without perturbing a single leaf.
"""

import dataclasses
import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import jax.random as jr

from corrosion_tpu.sim.scale_step import (
    ScaleSimState,
    make_write_inputs,
    scale_run_rounds,
    scale_sim_config,
    scale_sim_step,
    scale_sim_step_quiet,
)
from corrosion_tpu.sim.transport import NetModel

N = 48
ROUNDS = 48


def _cfg(**overrides):
    return scale_sim_config(
        N, m_slots=8, n_origins=4, n_rows=4, n_cols=2, sync_interval=4,
        **overrides,
    )


def _trace(cfg, kind, rounds=ROUNDS, seed=7):
    """A stacked round-input trace: all-quiet, seeded writes, or writes
    plus kill/revive churn."""
    n = cfg.n_nodes
    key = jr.key(seed)
    w = jnp.zeros((rounds, n), bool)
    if kind != "quiet":
        w = ((jr.uniform(key, (rounds, n)) < 0.3)
             & (jnp.arange(n) < cfg.n_origins)[None, :]
             & (jnp.arange(rounds) < 10)[:, None])
    inputs = make_write_inputs(cfg, jr.fold_in(key, 1), rounds, w)
    if kind == "churn":
        kill = jnp.zeros((rounds, n), bool).at[2, n - 1].set(True)
        revive = jnp.zeros((rounds, n), bool).at[rounds // 2, n - 1].set(True)
        inputs = inputs._replace(kill=kill, revive=revive)
    return inputs


def _run(cfg, inputs, seed=0):
    run = jax.jit(functools.partial(scale_run_rounds, cfg))
    st, infos = run(ScaleSimState.create(cfg), NetModel.create(cfg.n_nodes),
                    jr.key(seed), inputs)
    jax.block_until_ready(st)
    return st, infos


def _assert_bitwise(st_a, st_b, label):
    for i, (a, b) in enumerate(zip(jax.tree.leaves(st_a),
                                   jax.tree.leaves(st_b))):
        assert np.array_equal(np.asarray(a), np.asarray(b)), (
            f"{label}: state leaf {i} diverged")


# --- the device-plane oracle: masked == dense, bitwise --------------------


@pytest.mark.parametrize("kind", ["quiet", "seeded", "churn"])
def test_quiet_matches_dense_bitwise(kind):
    """Every leaf of the carry AND every shared info key is identical
    under quiet="on" and quiet="off" — on a settled trace, a seeded
    write trace, and a kill/revive churn trace."""
    cfg_q = _cfg(quiet="on")
    cfg_d = _cfg(quiet="off")
    inputs = _trace(cfg_q, kind)
    st_q, infos_q = _run(cfg_q, inputs)
    st_d, infos_d = _run(cfg_d, inputs)
    _assert_bitwise(st_q, st_d, kind)
    # dense info keys are a subset of the quiet step's (which adds the
    # corro.quiet.* sources); the shared ones must match bitwise
    for k in infos_d:
        assert np.array_equal(np.asarray(infos_q[k]),
                              np.asarray(infos_d[k])), (
            f"{kind}: info {k!r} diverged")
    if kind == "quiet":
        # the trace this variant exists for: the cold-start carry takes
        # ~18 rounds to settle (SWIM membership churn from an all-fresh
        # state), after which every round off the sync/backstop schedule
        # is cheap — assert on the steady-state half
        qr = np.asarray(infos_q["quiet_round"]).astype(int)
        assert int(qr[ROUNDS // 2:].sum()) > ROUNDS // 4


def test_quiet_series_and_backstop_accounting():
    """The quiet step emits the corro.quiet.* sources — cheap rounds,
    skipped shards, backstop fires — and the dense step emits none."""
    cfg_q = _cfg(quiet="on")
    inputs = _trace(cfg_q, "quiet")
    _, infos_q = _run(cfg_q, inputs)
    cheap = int(np.asarray(infos_q["quiet_round"]).sum())
    backstop = int(np.asarray(infos_q["quiet_backstop"]).sum())
    assert cheap > 0
    # sync_interval=4 forces a dense round every 4th tick on a settled
    # trace: each one is a backstop fire by definition
    assert backstop > 0
    assert cheap + backstop <= ROUNDS
    skipped = int(np.asarray(infos_q["quiet_shards_skipped"]).sum())
    assert skipped == cheap * cfg_q.quiet_shards
    _, infos_d = _run(_cfg(quiet="off"), inputs)
    assert "quiet_round" not in infos_d


def test_quiet_backstop_interval_overrides_sync():
    """quiet_backstop_interval decouples the backstop from the sync
    cadence — a tighter backstop forces more dense rounds, bitwise
    equal to dense all the same."""
    cfg_q = _cfg(quiet="on", quiet_backstop_interval=2)
    inputs = _trace(cfg_q, "quiet")
    st_q, infos_q = _run(cfg_q, inputs)
    st_d, _ = _run(_cfg(quiet="off"), inputs)
    _assert_bitwise(st_q, st_d, "backstop=2")
    # every other round is blocked by the backstop, on top of the sync
    # schedule: cheap rounds can be at most half the trace
    assert 0 < int(np.asarray(infos_q["quiet_round"]).sum()) <= ROUNDS // 2


def test_quiet_auto_is_dense_at_device_level():
    """quiet="auto" resolves at the HOST (segments.run_segmented); the
    device-level scan under "auto" is the dense program."""
    cfg = _cfg()  # quiet defaults to "auto"
    assert cfg.quiet == "auto"
    _, infos = _run(cfg, _trace(cfg, "quiet", rounds=8))
    assert "quiet_round" not in infos


def test_quiet_step_signature_parity():
    """Both step variants share the registry signature (cfg, st, net,
    key, inp) and one round of each matches bitwise on a busy input."""
    import inspect

    for fn in (scale_sim_step, scale_sim_step_quiet):
        assert list(inspect.signature(fn).parameters)[:4] == [
            "cfg", "st", "net", "key"]
    cfg_q = _cfg(quiet="on")
    inputs = _trace(cfg_q, "seeded", rounds=1)
    one = jax.tree.map(lambda a: a[0], inputs)
    st0 = ScaleSimState.create(cfg_q)
    net = NetModel.create(cfg_q.n_nodes)
    st_q, _ = scale_sim_step_quiet(cfg_q, st0, net, jr.key(3), one)
    st_d, _ = scale_sim_step(_cfg(quiet="off"), st0, net, jr.key(3), one)
    _assert_bitwise(st_q, st_d, "single step")


# --- config + checkpoint surface ------------------------------------------


def test_quiet_config_validation():
    with pytest.raises(ValueError, match="quiet"):
        _cfg(quiet="sometimes")
    with pytest.raises(ValueError, match="sync_cohort"):
        _cfg(quiet="on", sync_cohort=False)
    with pytest.raises(ValueError, match="backstop"):
        _cfg(quiet_backstop_interval=-1)
    with pytest.raises(ValueError, match="quiet_shards"):
        _cfg(quiet_shards=7)  # does not divide 48
    _cfg(quiet="on", quiet_shards=4)  # divides: fine


def test_quiet_is_execution_only_identity():
    """The quiet knobs never change checkpoint identity — a lineage
    written under one variant restores under any other."""
    from corrosion_tpu.checkpoint import (
        EXECUTION_ONLY_CONFIG_KEYS,
        config_identity,
    )

    assert {"quiet", "quiet_backstop_interval",
            "quiet_shards"} <= set(EXECUTION_ONLY_CONFIG_KEYS)
    base = _cfg()
    flipped = dataclasses.replace(
        base, quiet="on", quiet_backstop_interval=2, quiet_shards=4
    ).validate()
    assert config_identity(base) == config_identity(flipped)


@pytest.mark.parametrize("first,second", [("on", "off"), ("off", "on")])
def test_quiet_checkpoint_resume_cross_mode(first, second, tmp_path):
    """A segmented soak checkpointed under one round variant resumes
    under the other mid-lineage and lands bitwise on the dense straight
    run's final state."""
    from corrosion_tpu.resilience.segments import (
        resume_segmented,
        run_segmented,
    )

    cfg_a = _cfg(quiet=first)
    cfg_b = _cfg(quiet=second)
    rounds = 16
    inputs = _trace(cfg_a, "seeded", rounds=rounds)
    net = NetModel.create(cfg_a.n_nodes)
    ref, _ = _run(_cfg(quiet="off"), inputs, seed=0)

    half = jax.tree.map(lambda a: a[: rounds // 2], inputs)
    run_segmented(cfg_a, ScaleSimState.create(cfg_a), net, jr.key(0),
                  half, segment_rounds=4, checkpoint_root=str(tmp_path))
    res = resume_segmented(cfg_b, net, inputs, segment_rounds=4,
                           checkpoint_root=str(tmp_path))
    assert res.completed_rounds == rounds
    _assert_bitwise(res.state, ref, f"{first}->{second} resume")


# --- the segmented host fast path -----------------------------------------


def test_segments_quiet_auto_fast_path(tmp_path):
    """Under quiet="auto" the segmented runner short-circuits segments
    whose inputs AND carry are provably quiet — dispatching the quiet
    program for them and the EXACT historical dense program for the
    rest — with every leaf and every shared info row bitwise equal to
    the dense straight scan."""
    from corrosion_tpu.resilience.segments import run_segmented

    cfg = _cfg()  # quiet="auto"
    rounds = ROUNDS
    inputs = _trace(cfg, "seeded", rounds=rounds)
    net = NetModel.create(cfg.n_nodes)
    ref, infos_ref = _run(_cfg(quiet="off"), inputs, seed=0)

    res = run_segmented(cfg, ScaleSimState.create(cfg), net, jr.key(0),
                        inputs, segment_rounds=8,
                        checkpoint_root=str(tmp_path))
    assert res.completed_rounds == rounds
    _assert_bitwise(res.state, ref, "quiet-auto soak")
    assert res.stats["quiet_mode"] == "auto"
    # writes stop at round 10: the later segments are input-quiet and,
    # once the carry settles, host-skipped onto the quiet program
    assert res.stats["quiet_segments"] >= 1
    for k in infos_ref:
        assert np.array_equal(np.asarray(res.infos[k]),
                              np.asarray(infos_ref[k])), (
            f"soak info {k!r} diverged")
    # mixed segments: dense parts zero-fill the quiet-only keys
    assert int(np.asarray(res.infos["quiet_round"]).sum()) > 0


def test_segments_quiet_off_never_fast_paths(tmp_path):
    from corrosion_tpu.resilience.segments import run_segmented

    cfg = _cfg(quiet="off")
    inputs = _trace(cfg, "quiet", rounds=16)
    res = run_segmented(cfg, ScaleSimState.create(cfg),
                        NetModel.create(cfg.n_nodes), jr.key(0), inputs,
                        segment_rounds=4, checkpoint_root=str(tmp_path))
    assert res.stats["quiet_mode"] == "off"
    assert res.stats["quiet_segments"] == 0
    assert "quiet_round" not in res.infos


# --- the parity harness + chaos registry ----------------------------------


def test_quiet_parity_harness_workload():
    """sim/parity.py battery rung: the same workload script under both
    round variants — identical planes, alive set, rounds-to-converge."""
    from corrosion_tpu.sim.parity import WorkloadScript, run_sim_script

    script = WorkloadScript.random_full_mix(
        n_nodes=24, n_origins=4, n_cells=8, rounds=16, seed=5)
    on = run_sim_script(script, seed=2, settle_rounds=256, quiet="on")
    off = run_sim_script(script, seed=2, settle_rounds=256, quiet="off")
    for p_on, p_off in zip(on[0], off[0]):
        assert np.array_equal(p_on, p_off)
    assert np.array_equal(on[1], off[1])
    assert on[2] == off[2]  # identical rounds-to-convergence


def test_quiet_flip_scenario_registered():
    from corrosion_tpu.resilience.chaos import INJECTION_KINDS, SCENARIOS

    assert "quiet_flip" in INJECTION_KINDS
    script = SCENARIOS["quiet-flip"]
    assert script.quiet == "on"
    flips = [i.quiet for i in script.injections if i.kind == "quiet_flip"]
    assert flips == ["off", "on"]  # both directions in one lineage


def test_quiet_chaos_scenario_tier1():
    """One registry scenario under quiet="on": both oracles plus the
    quiescence drain stay green and the chaos leg stays bitwise."""
    from corrosion_tpu.resilience.chaos import SCENARIOS, run_scenario

    script = dataclasses.replace(SCENARIOS["preempt-mid-segment"],
                                 quiet="on")
    rec = run_scenario(script, seed=0)
    assert rec["ok"], rec.get("problems")
    assert rec["bitwise_match"] and rec["converged"] and rec["quiesced"]


def _scenario_names():
    from corrosion_tpu.resilience.chaos import SCENARIOS

    return sorted(SCENARIOS)


@pytest.mark.slow
@pytest.mark.parametrize("name", _scenario_names())
def test_quiet_chaos_registry_full(name):
    """The whole registry under quiet="on" (the check.sh quiet-parity
    stage runs this sweep as artifacts/quiet_r19.json)."""
    from corrosion_tpu.resilience.chaos import SCENARIOS, run_scenario

    rec = run_scenario(dataclasses.replace(SCENARIOS[name], quiet="on"),
                       seed=0)
    assert rec["ok"], rec.get("problems")
    if not rec.get("skipped"):
        assert rec["bitwise_match"] and rec["converged"] and rec["quiesced"]
