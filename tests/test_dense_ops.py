"""Backend-adaptive dense column ops: the TPU loop form and the CPU
element-indexed form must agree exactly (ops/dense.py)."""

import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from corrosion_tpu.ops import dense


@pytest.fixture
def rng_arrays():
    key = jr.key(3)
    k1, k2, k3, k4 = jr.split(key, 4)
    n, w, m = 64, 16, 24
    table = jr.randint(k1, (n, w), 0, 100, dtype=jnp.int32)
    idx = jr.randint(k2, (n, m), -2, w + 2, dtype=jnp.int32)  # incl. oob
    vals = jr.randint(k3, (n, m), 1, 1000, dtype=jnp.int32)
    valid = jr.uniform(k4, (n, m)) < 0.7
    valid = valid & (idx >= 0) & (idx < w)
    return table, idx, vals, valid


def _both(fn, *args):
    try:
        dense.FORCE_DENSE = True
        a = np.asarray(fn(*args))
        dense.FORCE_DENSE = False
        b = np.asarray(fn(*args))
    finally:
        dense.FORCE_DENSE = None
    return a, b


def test_lookup_cols_forms_agree(rng_arrays):
    table, idx, _, _ = rng_arrays
    a, b = _both(dense.lookup_cols, table, idx, 0)
    assert np.array_equal(a, b)
    # matches the take_along semantics for in-range indices
    w = table.shape[1]
    ref = np.take_along_axis(
        np.asarray(table), np.clip(np.asarray(idx), 0, w - 1), axis=1
    )
    in_range = (np.asarray(idx) >= 0) & (np.asarray(idx) < w)
    assert np.array_equal(a[in_range], ref[in_range])
    assert (a[~in_range] == 0).all()


def test_scatter_cols_max_forms_agree(rng_arrays):
    table, idx, vals, valid = rng_arrays
    a, b = _both(dense.scatter_cols_max, table, idx, vals, valid)
    assert np.array_equal(a, b)


def test_scatter_cols_add_forms_agree(rng_arrays):
    table, idx, vals, valid = rng_arrays
    a, b = _both(dense.scatter_cols_add, table, idx, vals, valid)
    assert np.array_equal(a, b)


def test_scatter_cols_set_forms_agree_unique_writers():
    # set semantics require one writer per (row, column): use a
    # permutation-based index so both forms must agree exactly
    key = jr.key(9)
    n, w = 32, 8
    dest = jr.randint(key, (n, w), 0, 50, dtype=jnp.int32)
    idx = jnp.argsort(jr.uniform(jr.fold_in(key, 1), (n, w)), axis=1).astype(
        jnp.int32
    )
    vals = jr.randint(jr.fold_in(key, 2), (n, w), 100, 200, dtype=jnp.int32)
    valid = jr.uniform(jr.fold_in(key, 3), (n, w)) < 0.6
    a, b = _both(dense.scatter_cols_set, dest, idx, vals, valid)
    assert np.array_equal(a, b)
    # unwritten cells keep dest
    an = np.asarray(a)
    dn, vn = np.asarray(dest), np.asarray(valid)
    for r in range(n):
        written = set(np.asarray(idx)[r][vn[r]].tolist())
        for c in range(w):
            if c not in written:
                assert an[r, c] == dn[r, c]
