"""Backend-adaptive dense column ops: the TPU loop form and the CPU
element-indexed form must agree exactly (ops/dense.py)."""

import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from corrosion_tpu.ops import dense


@pytest.fixture
def rng_arrays():
    key = jr.key(3)
    k1, k2, k3, k4 = jr.split(key, 4)
    n, w, m = 64, 16, 24
    table = jr.randint(k1, (n, w), 0, 100, dtype=jnp.int32)
    idx = jr.randint(k2, (n, m), -2, w + 2, dtype=jnp.int32)  # incl. oob
    vals = jr.randint(k3, (n, m), 1, 1000, dtype=jnp.int32)
    valid = jr.uniform(k4, (n, m)) < 0.7
    valid = valid & (idx >= 0) & (idx < w)
    return table, idx, vals, valid


def _both(fn, *args):
    try:
        dense.FORCE_DENSE = True
        a = np.asarray(fn(*args))
        dense.FORCE_DENSE = False
        b = np.asarray(fn(*args))
    finally:
        dense.FORCE_DENSE = None
    return a, b


def test_lookup_cols_forms_agree(rng_arrays):
    table, idx, _, _ = rng_arrays
    a, b = _both(dense.lookup_cols, table, idx, 0)
    assert np.array_equal(a, b)
    # matches the take_along semantics for in-range indices
    w = table.shape[1]
    ref = np.take_along_axis(
        np.asarray(table), np.clip(np.asarray(idx), 0, w - 1), axis=1
    )
    in_range = (np.asarray(idx) >= 0) & (np.asarray(idx) < w)
    assert np.array_equal(a[in_range], ref[in_range])
    assert (a[~in_range] == 0).all()


def test_scatter_cols_max_forms_agree(rng_arrays):
    table, idx, vals, valid = rng_arrays
    a, b = _both(dense.scatter_cols_max, table, idx, vals, valid)
    assert np.array_equal(a, b)


def test_scatter_cols_add_forms_agree(rng_arrays):
    table, idx, vals, valid = rng_arrays
    a, b = _both(dense.scatter_cols_add, table, idx, vals, valid)
    assert np.array_equal(a, b)


def test_scatter_cols_set_forms_agree_unique_writers():
    # set semantics require one writer per (row, column): use a
    # permutation-based index so both forms must agree exactly
    key = jr.key(9)
    n, w = 32, 8
    dest = jr.randint(key, (n, w), 0, 50, dtype=jnp.int32)
    idx = jnp.argsort(jr.uniform(jr.fold_in(key, 1), (n, w)), axis=1).astype(
        jnp.int32
    )
    vals = jr.randint(jr.fold_in(key, 2), (n, w), 100, 200, dtype=jnp.int32)
    valid = jr.uniform(jr.fold_in(key, 3), (n, w)) < 0.6
    a, b = _both(dense.scatter_cols_set, dest, idx, vals, valid)
    assert np.array_equal(a, b)
    # unwritten cells keep dest
    an = np.asarray(a)
    dn, vn = np.asarray(dest), np.asarray(valid)
    for r in range(n):
        written = set(np.asarray(idx)[r][vn[r]].tolist())
        for c in range(w):
            if c not in written:
                assert an[r, c] == dn[r, c]


def test_scatter_cols_or_forms_agree_and_match_numpy():
    # the record_versions bit scatter: unique (idx, bit) per valid writer
    # within a call (the documented precondition), but bits may already be
    # set in dest — both forms must compute the true OR
    key = jr.key(21)
    n, w, m = 48, 6, 12
    dest = jr.randint(key, (n, w), 0, 1 << 16, dtype=jnp.uint32)
    idx = jr.randint(jr.fold_in(key, 1), (n, m), -1, w + 1, dtype=jnp.int32)
    # give each message column its own bit -> no two writers share a bit
    bit = jnp.broadcast_to(jnp.arange(m, dtype=jnp.uint32)[None, :], (n, m))
    vals = jnp.uint32(1) << bit
    valid = jr.uniform(jr.fold_in(key, 2), (n, m)) < 0.8
    valid = valid & (idx >= 0) & (idx < w)
    a, b = _both(dense.scatter_cols_or, dest, idx, vals, valid)
    assert np.array_equal(a, b)
    ref = np.asarray(dest).copy()
    iN, vN, valN = np.asarray(idx), np.asarray(vals), np.asarray(valid)
    for r in range(n):
        for j in range(m):
            if valN[r, j]:
                ref[r, iN[r, j]] |= vN[r, j]
    assert np.array_equal(a, ref)


def test_versions_oracle_holds_on_dense_form():
    # CI runs on CPU (element form); pin the dense/TPU form and re-run the
    # Book-vs-oracle property check so the hot-path form is covered too
    from tests.test_versions import run_rounds as book_rounds

    try:
        dense.FORCE_DENSE = True
        rng = np.random.default_rng(11)
        book, oracles, fresh_ok = book_rounds(
            rng, n_nodes=4, n_origins=3, slots=64, batch=6, rounds=8,
            max_ver=15,
        )
    finally:
        dense.FORCE_DENSE = None
    assert fresh_ok
    heads = np.asarray(book.head)
    for n_, o in np.ndindex(heads.shape):
        assert heads[n_, o] == oracles[n_].head(o), (n_, o)


def test_apply_changes_forms_agree():
    # the LWW batch apply: TPU column-loop vs CPU segment-reduce form
    key = jr.key(33)
    n, c, m = 24, 8, 10
    store = tuple(
        jr.randint(jr.fold_in(key, i), (n, c), 0, 6, dtype=jnp.int32)
        for i in range(5)
    )
    # include out-of-range cells: invalid on BOTH forms, never applied
    cell = jr.randint(jr.fold_in(key, 10), (n, m), -2, c + 2, dtype=jnp.int32)
    # wide key range: a full-key tie with differing payloads is broken
    # arbitrarily (and differently) by the two forms — real traffic can't
    # produce one ((site, ver) names a unique change), so keep the test
    # tie-free the same way
    fields = tuple(
        jr.randint(jr.fold_in(key, 20 + i), (n, m), 0, 100_000, dtype=jnp.int32)
        for i in range(5)
    )
    valid = jr.uniform(jr.fold_in(key, 30), (n, m)) < 0.7
    a, b = _both(dense.apply_changes, store, cell, *fields, valid)
    for pa, pb in zip(a, b):
        assert np.array_equal(np.asarray(pa), np.asarray(pb))
