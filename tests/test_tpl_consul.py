"""Template engine + Consul bridge (``corro-tpl`` / ``corrosion consul
sync``)."""

import http.server
import json
import threading

import pytest

from corrosion_tpu.agent import Agent
from corrosion_tpu.api import ApiServer
from corrosion_tpu.client import CorrosionApiClient
from corrosion_tpu.config import Config
from corrosion_tpu.consul import CONSUL_SCHEMA, ConsulClient, ConsulSync
from corrosion_tpu.db import Database
from corrosion_tpu.tpl import TemplateRunner, render_template

SCHEMA = "CREATE TABLE svc (name TEXT PRIMARY KEY, addr TEXT, port INTEGER);"


def rig_config():
    cfg = Config()
    cfg.sim.mode = "scale"
    cfg.sim.n_nodes = 16
    cfg.sim.m_slots = 8
    cfg.sim.n_origins = 4
    cfg.sim.n_rows = 8
    cfg.sim.n_cols = 4
    cfg.perf.sync_interval = 4
    cfg.gossip.drop_prob = 0.0
    return cfg


@pytest.fixture(scope="module")
def rig():
    with Agent(rig_config()) as agent:
        agent.wait_rounds(10, timeout=120)
        db = Database(agent)
        db.apply_schema_sql(SCHEMA)
        db.execute(0, [
            ("INSERT INTO svc (name, addr, port) VALUES ('web', '10.0.0.1', 80)",),
        ])
        with ApiServer(db, port=0) as api:
            client = CorrosionApiClient(api.addr, api.port)
            yield agent, db, client


TEMPLATE = """
rows = sql("SELECT name, addr, port FROM svc")
for r in sorted(rows, key=lambda r: r["name"]):
    write(f"upstream {r['name']} {{ server {r['addr']}:{r['port']}; }}\\n")
write("# host: " + hostname() + "\\n")
"""


def test_render_template(rig):
    _, db, _ = rig
    out, queries = render_template(
        TEMPLATE, lambda q, p: db.query(0, q, p)
    )
    assert "upstream web { server 10.0.0.1:80; }" in out
    assert len(queries) == 1


def test_template_runner_rerender(tmp_path, rig):
    agent, db, client = rig
    src = tmp_path / "t.py"
    dst = tmp_path / "out.conf"
    src.write_text(TEMPLATE)
    runner = TemplateRunner(client, [f"{src}:{dst}"])
    runner.render_all()
    first = dst.read_text()
    assert "web" in first
    # change the data; a re-render pass must pick it up
    client.execute([
        ("INSERT INTO svc (name, addr, port) VALUES ('api', '10.0.0.9', 443)",)
    ])
    agent.wait_rounds(3, timeout=60)
    runner.render_all()
    assert "api" in dst.read_text()


def test_template_bad_spec(rig):
    _, _, client = rig
    with pytest.raises(ValueError):
        TemplateRunner(client, ["no-colon-spec"])


# --- consul bridge --------------------------------------------------------

class FakeConsul(http.server.BaseHTTPRequestHandler):
    services = {"web-1": {"Service": "web", "Port": 80}}
    checks = {"web-1-check": {"Status": "passing"}}

    def do_GET(self):
        if self.path == "/v1/agent/services":
            body = json.dumps(self.services).encode()
        elif self.path == "/v1/agent/checks":
            body = json.dumps(self.checks).encode()
        else:
            self.send_response(404)
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):
        pass


@pytest.fixture()
def fake_consul():
    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), FakeConsul)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield f"127.0.0.1:{httpd.server_address[1]}"
    httpd.shutdown()
    httpd.server_close()


def test_consul_sync(rig, fake_consul):
    agent, db, client = rig
    client.schema([CONSUL_SCHEMA])
    sync = ConsulSync(
        ConsulClient(fake_consul),
        execute=lambda stmts, node: client.execute(stmts, node=node),
    )
    n_svc, n_chk = sync.sync_once()
    assert (n_svc, n_chk) == (1, 1)
    row = db.read_row(0, "consul_services", "web-1")
    assert row is not None and json.loads(row["data"])["Port"] == 80
    # unchanged poll -> no writes
    assert sync.sync_once() == (0, 0)
    # removal -> delete
    FakeConsul.services = {}
    n_svc, _ = sync.sync_once()
    assert n_svc == 1
    agent.wait_rounds(2, timeout=60)
    assert db.read_row(0, "consul_services", "web-1") is None


def test_render_template_order_by_and_aggregate(rig):
    """Templates lean on the grown SQL surface (VERDICT #8): ORDER BY
    drives deterministic config output, aggregates drive summary lines —
    the shapes the reference's Rhai templates run against full SQLite."""
    _, db, _ = rig
    db.execute(0, [
        ("INSERT INTO svc (name, addr, port) VALUES ('api', '10.0.0.2', 81)",),
        ("INSERT INTO svc (name, addr, port) VALUES ('cache', '10.0.0.3', 82)",),
    ])
    tpl = """
for r in sql("SELECT name, port FROM svc ORDER BY port DESC LIMIT 2"):
    write(f"{r['name']}:{r['port']}\\n")
n = sql("SELECT COUNT(*) AS n FROM svc")[0]["n"]
write(f"# {n} services\\n")
"""
    out, queries = render_template(tpl, lambda q, p: db.query(0, q, p))
    assert out.splitlines() == ["cache:82", "api:81", "# 3 services"]
    assert len(queries) == 2
