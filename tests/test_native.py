"""Native (C++) host engine: parity against the Python oracle and the
JAX kernels under identical random traffic."""

import numpy as np
import pytest

import jax.numpy as jnp

from corrosion_tpu.sim.oracle import OracleNode

native = pytest.importorskip("corrosion_tpu.native")
if not native.available():
    pytest.skip("no C++ toolchain", allow_module_level=True)


def random_changes(rng, n, n_cells, n_origins, max_ver=6):
    cell = rng.integers(0, n_cells, n)
    ver = rng.integers(1, max_ver, n)
    val = rng.integers(0, 1000, n)
    site = rng.integers(0, n_origins, n)
    origin = rng.integers(0, n_origins, n)
    dbv = rng.integers(1, 40, n)
    clp = rng.integers(0, 3, n)  # causal-length lifetime collisions
    return np.stack(
        [cell, ver, val, site, origin, dbv, clp], axis=1
    ).astype(np.int32)


def test_native_matches_python_oracle():
    rng = np.random.default_rng(0)
    n_cells, n_origins = 8, 3
    nat = native.NativeNode(n_cells, n_origins)
    orc = OracleNode(n_origins)
    for _ in range(50):
        batch = random_changes(rng, 20, n_cells, n_origins)
        fresh_nat = nat.apply(batch)
        fresh_orc = np.array([orc.apply(tuple(row)) for row in batch])
        np.testing.assert_array_equal(fresh_nat, fresh_orc)
    for o in range(n_origins):
        assert nat.head(o) == orc.head(o)
        assert nat.needs(o) == orc.needs(o)
        assert nat.known_max(o) == orc.known_max.get(o, 0)
    ver, val, site, dbv, clp = nat.store()
    for c in range(n_cells):
        got = (int(ver[c]), int(val[c]), int(site[c]), int(dbv[c]),
               int(clp[c]))
        want = orc.store.get(c, (0, 0, 0, 0, 0))
        assert got == want, f"cell {c}: {got} != {want}"


def test_native_matches_jax_book():
    from corrosion_tpu.ops.versions import Book, needs_count, record_versions

    rng = np.random.default_rng(1)
    n_origins = 4
    # buffer big enough that nothing is dropped (native book is unbounded;
    # the JAX buffer's drop-on-overflow is by design and tested elsewhere)
    nat = native.NativeNode(1, n_origins)
    book = Book.create(1, n_origins, buf_slots=256)
    for _ in range(30):
        origin = rng.integers(0, n_origins, 8).astype(np.int32)
        ver = rng.integers(1, 30, 8).astype(np.int32)
        for o, v in zip(origin, ver):
            nat.record(int(o), int(v))
        book, _, _ = record_versions(
            book, jnp.asarray(origin)[None, :], jnp.asarray(ver)[None, :],
            jnp.ones((1, 8), bool),
        )
    needs = needs_count(book)
    for o in range(n_origins):
        assert int(book.head[0, o]) == nat.head(o)
        assert int(book.known_max[0, o]) == nat.known_max(o)
        assert int(needs[0, o]) == nat.needs(o)


def test_gap_interval_algebra():
    """Directed gap-merge cases from the reference's gap algebra tests
    (``agent.rs:1606-1841`` shape): extend-up, extend-down, bridge."""
    nat = native.NativeNode(1, 1)
    assert nat.record(0, 2) and nat.record(0, 4)
    assert nat.head(0) == 0 and nat.n_gaps(0) == 2  # [1] and [3]
    assert nat.record(0, 3)  # bridge 2-4
    assert nat.n_gaps(0) == 1
    assert nat.record(0, 1)  # close the head gap
    assert nat.head(0) == 4 and nat.needs(0) == 0 and nat.n_gaps(0) == 0
    assert not nat.record(0, 3)  # duplicate is stale
    nat2 = native.NativeNode(1, 1)
    assert nat2.record(0, 10)
    assert nat2.needs(0) == 9 and nat2.n_gaps(0) == 1
