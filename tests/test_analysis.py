"""corrolint: each checker fires on seeded bad code, honors
suppressions, and the shipped tree is clean; the trace-stability
harness holds the one-compile-per-entry-point contract."""

import textwrap

import pytest

from corrosion_tpu.analysis import check_source, run_paths
from corrosion_tpu.analysis.__main__ import main as lint_main


def rules_of(findings):
    return [f.rule for f in findings]


def lint(src, checkers=None):
    from corrosion_tpu.analysis import ALL_CHECKERS

    selected = ({k: ALL_CHECKERS[k] for k in checkers}
                if checkers else None)
    return check_source(textwrap.dedent(src), "fixture.py", selected)


# --- donation-safety ------------------------------------------------------

BAD_DONATION_LOCAL = """
    import jax

    step = jax.jit(lambda s: s + 1, donate_argnums=(0,))

    def run(st):
        out = step(st)
        total = st.sum()  # use-after-donate
        return out, total
"""


def test_donation_reuse_fires_on_local_jit():
    findings = lint(BAD_DONATION_LOCAL, ["donation-safety"])
    assert rules_of(findings) == ["donation-reuse"]
    assert findings[0].line == 8
    assert "`st` read after being donated" in findings[0].message


def test_donation_reuse_fires_on_registered_entry_point():
    src = """
        def drive(cfg, mesh, st, net, key, inputs):
            out, infos = sharded_scale_run(cfg, mesh, st, net, key, inputs)
            return st.swim, infos  # st was donated away
    """
    findings = lint(src, ["donation-safety"])
    assert rules_of(findings) == ["donation-reuse"]
    assert "sharded_scale_run" in findings[0].message


def test_donation_rebind_is_clean():
    src = """
        import jax

        step = jax.jit(lambda s: s + 1, donate_argnums=(0,))

        def run(st):
            st = step(st)  # canonical donation idiom: re-bind
            return st.sum()
    """
    assert lint(src, ["donation-safety"]) == []


def test_donation_decorated_def_and_carry_chain():
    src = """
        import functools, jax

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def seg(st, key, inputs):
            return (st, key), 0

        def soak(st, key, inputs):
            (st, key), infos = seg(st, key, inputs)  # chained: clean
            bad = seg(st, key, inputs)
            return key.sum(), bad  # key donated by the second call
    """
    findings = lint(src, ["donation-safety"])
    assert rules_of(findings) == ["donation-reuse"]
    assert "`key` read after being donated to seg()" in findings[0].message


def test_donation_exclusive_branches_do_not_leak():
    """A donation on one if-branch must not flag a read on the
    mutually exclusive else-branch — but a read AFTER the if/else
    still flags (either path may have consumed the buffer)."""
    src = """
        import jax

        step = jax.jit(lambda s: s + 1, donate_argnums=(0,))

        def run(st, fast):
            if fast:
                out = step(st)
            else:
                out = st * 2  # st alive on this path: clean
            return out

        def run_then_read(st, fast):
            if fast:
                out = step(st)
            else:
                out = st * 2
            return out, st.sum()  # st MAY be donated here: flag
    """
    findings = lint(src, ["donation-safety"])
    assert rules_of(findings) == ["donation-reuse"]
    assert "`st` read after being donated" in findings[0].message
    assert findings[0].line == 18  # the read AFTER the merged branches


# --- lock-discipline ------------------------------------------------------

BAD_LOCK_MUTATION = """
    import threading

    class Writer:
        def __init__(self):
            self._mu = threading.Lock()
            self._state = []

        def push(self, item):
            self._state.append(item)  # unlocked mutation

        def set(self, item):
            self._error = item  # unlocked mutation

        def ok(self, item):
            with self._mu:
                self._state.append(item)
"""


def test_unlocked_mutation_fires():
    findings = lint(BAD_LOCK_MUTATION, ["lock-discipline"])
    assert rules_of(findings) == ["unlocked-mutation"] * 2
    assert "Writer.push" in findings[0].message
    assert "Writer.set" in findings[1].message


def test_blocking_under_lock_fires():
    src = """
        import threading

        class Writer:
            def __init__(self):
                self._mu = threading.Lock()

            def flush(self, batch):
                with self._mu:
                    with open("/tmp/x", "w") as f:
                        f.write(batch)

            def wait(self, fut):
                with self._mu:
                    return fut.result()
    """
    findings = lint(src, ["lock-discipline"])
    assert rules_of(findings) == ["blocking-under-lock"] * 2


def test_locked_suffix_convention():
    src = """
        import threading

        class Writer:
            def __init__(self):
                self._mu = threading.Lock()
                self._buf = []

            def _push_locked(self, item):
                self._buf.append(item)  # caller holds the lock: clean

            def _flush_locked(self):
                import json
                with open("/tmp/x", "w") as f:  # IO with lock held
                    f.write(json.dumps(self._buf))
    """
    findings = lint(src, ["lock-discipline"])
    assert rules_of(findings) == ["blocking-under-lock"]
    assert "_flush_locked" in findings[0].message


def test_multi_lock_class_is_skipped():
    src = """
        import threading

        class TwoLocks:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._x = 0

            def bump(self):
                self._x += 1  # ownership not inferable: out of scope
    """
    assert lint(src, ["lock-discipline"]) == []


def test_closure_under_with_is_not_held():
    src = """
        import threading

        class Spawner:
            def __init__(self):
                self._mu = threading.Lock()
                self._n = 0

            def start(self):
                with self._mu:
                    def worker():
                        self._n += 1  # runs later, lock released
                    return worker
    """
    findings = lint(src, ["lock-discipline"])
    assert rules_of(findings) == ["unlocked-mutation"]


def test_nested_class_lock_does_not_shield_outer():
    """A nested class owning its own lock must not flip the outer
    class into the multi-lock skip."""
    src = """
        import threading

        class Outer:
            def __init__(self):
                self._mu = threading.Lock()
                self._buf = []

            class Inner:
                def __init__(self):
                    self._lk = threading.Lock()

            def push(self, v):
                self._buf.append(v)  # unlocked: must still flag
    """
    findings = lint(src, ["lock-discipline"])
    assert rules_of(findings) == ["unlocked-mutation"]
    assert "Outer.push" in findings[0].message


def test_condition_wrapping_the_lock_is_an_alias():
    """``threading.Condition(self._mu)`` shares the mutex it wraps, so
    ``with self._cv:`` counts as holding the lock (the admission-
    controller idiom)."""
    src = """
        import threading

        class Gate:
            def __init__(self):
                self._mu = threading.Lock()
                self._cv = threading.Condition(self._mu)
                self._n = 0

            def take(self):
                with self._cv:
                    self._n += 1  # the cv IS the lock: clean
                    self._cv.notify()

            def leak(self):
                self._n -= 1  # genuinely unlocked: must still flag
    """
    findings = lint(src, ["lock-discipline"])
    assert rules_of(findings) == ["unlocked-mutation"]
    assert "Gate.leak" in findings[0].message


def test_condition_wrapping_another_lock_is_not_an_alias():
    """A Condition built over anything but the class's own single lock
    (its own hidden mutex, some other object's lock) must NOT count as
    holding the lock."""
    src = """
        import threading

        class Gate:
            def __init__(self):
                self._mu = threading.Lock()
                self._cv = threading.Condition()
                self._n = 0

            def take(self):
                with self._cv:
                    self._n += 1  # a DIFFERENT mutex: still unlocked
    """
    findings = lint(src, ["lock-discipline"])
    assert rules_of(findings) == ["unlocked-mutation"]
    assert "Gate.take" in findings[0].message


# --- strippable-assert ----------------------------------------------------


def test_bare_assert_fires():
    findings = lint("""
        def f(x):
            assert x > 0, "must be positive"
            return x
    """, ["strippable-assert"])
    assert rules_of(findings) == ["bare-assert"]
    assert "python -O" in findings[0].message


# --- trace-hygiene --------------------------------------------------------


def test_tracer_branch_fires():
    src = """
        import functools, jax

        @functools.partial(jax.jit, static_argnums=(0,))
        def step(cfg, x):
            if x > cfg.limit:  # tracer bool conversion
                return x
            while x.sum() > 0:  # tracer loop
                x = x - 1
            return x
    """
    findings = lint(src, ["trace-hygiene"])
    assert rules_of(findings) == ["tracer-branch"] * 2
    assert all("`x`" in f.message for f in findings)


def test_static_facts_are_allowed():
    src = """
        import jax

        @jax.jit
        def step(x, y=None):
            if y is None:  # identity on None: static
                y = x
            if x.shape[0] > 4:  # shapes are static
                return x + y
            if len(x) == 2 or isinstance(x, tuple):
                return x
            return y
    """
    assert lint(src, ["trace-hygiene"]) == []


def test_static_arg_branch_is_allowed():
    src = """
        import functools, jax

        @functools.partial(jax.jit, static_argnames=("mode",))
        def step(x, mode):
            if mode == "fast":  # static arg: concrete at trace time
                return x * 2
            return x
    """
    assert lint(src, ["trace-hygiene"]) == []


def test_import_time_jnp_fires():
    src = """
        import jax.numpy as jnp

        LIMIT = jnp.array(3)  # device work at import

        def f(x, table=jnp.zeros(4)):  # defaults evaluate at import
            return x + table + LIMIT
    """
    findings = lint(src, ["trace-hygiene"])
    assert rules_of(findings) == ["import-time-jnp"] * 2


def test_unhashable_static_default_fires():
    src = """
        import functools, jax

        @functools.partial(jax.jit, static_argnums=(1,))
        def f(x, axes=[0, 1]):
            return x.sum(axes[0])
    """
    findings = lint(src, ["trace-hygiene"])
    assert rules_of(findings) == ["unhashable-static-default"]


def test_tracer_branch_covers_keyword_only_args():
    src = """
        import jax

        @jax.jit
        def step(x, *, y):
            if y > 0:  # kw-only args are traced too
                return x
            return -x
    """
    findings = lint(src, ["trace-hygiene"])
    assert rules_of(findings) == ["tracer-branch"]
    assert "`y`" in findings[0].message


def test_static_argnames_covers_keyword_only():
    src = """
        import functools, jax

        @functools.partial(jax.jit, static_argnames=("mode",))
        def step(x, *, mode, table={}):
            if mode:  # static kw-only: clean
                return x
            return -x
    """
    # `mode` is static (clean branch); `table` is a traced kw-only arg
    # whose dict default is NOT a static-default finding (it is not
    # static), but branching is not done on it either
    assert lint(src, ["trace-hygiene"]) == []


def test_unhashable_static_default_keyword_only():
    src = """
        import functools, jax

        @functools.partial(jax.jit, static_argnames=("axes",))
        def f(x, *, axes=[0, 1]):
            return x.sum()
    """
    findings = lint(src, ["trace-hygiene"])
    assert rules_of(findings) == ["unhashable-static-default"]


# --- suppressions ---------------------------------------------------------


def test_suppression_with_reason_is_honored():
    findings = lint("""
        def f(x):
            assert x > 0  # corrolint: disable=bare-assert -- perf-critical inner loop, validated at boot
            return x
    """, ["strippable-assert"])
    assert findings == []


def test_suppression_without_reason_is_a_finding():
    findings = lint("""
        def f(x):
            assert x > 0  # corrolint: disable=bare-assert
            return x
    """, ["strippable-assert"])
    assert sorted(rules_of(findings)) == [
        "bare-assert", "suppression-missing-reason",
    ]


def test_suppression_on_own_line_guards_next_line():
    findings = lint("""
        def f(x):
            # corrolint: disable=bare-assert -- documented invariant
            assert x > 0
            return x
    """, ["strippable-assert"])
    assert findings == []


def test_suppression_for_other_rule_does_not_mask():
    findings = lint("""
        def f(x):
            assert x > 0  # corrolint: disable=tracer-branch -- wrong rule
            return x
    """, ["strippable-assert"])
    assert rules_of(findings) == ["bare-assert"]


def test_suppression_inside_string_literal_is_inert():
    """The directive only counts in REAL comments — inside a string it
    neither suppresses nor misfires as a reasonless suppression."""
    findings = lint('''
        def f(x):
            msg = "use # corrolint: disable=bare-assert to waive"
            assert x > 0  # the string above must not mask this
            return msg
    ''', ["strippable-assert"])
    assert rules_of(findings) == ["bare-assert"]


def test_missing_path_is_an_error_not_clean(tmp_path):
    """A lint gate must never read 'walked nothing' as 'clean'."""
    with pytest.raises(FileNotFoundError):
        run_paths([str(tmp_path / "nope")])
    (tmp_path / "empty").mkdir()
    with pytest.raises(FileNotFoundError):
        run_paths([str(tmp_path / "empty")])
    assert lint_main([str(tmp_path / "nope")]) == 2


# --- the repo gate --------------------------------------------------------


def _package_dir():
    import os

    import corrosion_tpu

    return os.path.dirname(corrosion_tpu.__file__)


def test_repo_is_clean():
    """The shipped tree passes its own analyzer — the tier-1 lint gate.

    Scope since v2: the package plus ``bench.py`` and ``scripts/``
    (everything driving the hot entry points). Every finding must be
    fixed or suppressed-with-reason; this is the same engine the CLI
    runs, so CI and `python -m corrosion_tpu.analysis` can never
    disagree."""
    import os

    repo = os.path.dirname(_package_dir())
    paths = [_package_dir()]
    for extra in ("bench.py", "scripts"):
        candidate = os.path.join(repo, extra)
        if os.path.exists(candidate):  # absent in installed-package runs
            paths.append(candidate)
    findings = run_paths(paths)
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


def test_cli_clean_file_exits_zero(capsys):
    # one clean file, not the whole package — test_repo_is_clean
    # already walks the tree; this only covers the CLI's exit-0 path
    import os

    assert lint_main([os.path.join(_package_dir(), "analysis", "base.py")]) == 0


def test_cli_reports_findings(tmp_path, capsys):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(x):\n    assert x\n    return x\n")
    assert lint_main([str(bad)]) == 1
    out = capsys.readouterr().out
    assert "bare-assert" in out and "bad.py:2" in out

    assert lint_main(["--format", "json", str(bad)]) == 1
    out = capsys.readouterr().out
    import json

    payload = json.loads(out)
    assert payload[0]["rule"] == "bare-assert"
    assert payload[0]["line"] == 2


def test_cli_default_works_from_any_cwd(tmp_path, monkeypatch, capsys):
    """With no paths the CLI lints the installed package, not a
    cwd-relative directory name."""
    monkeypatch.chdir(tmp_path)
    assert lint_main([]) == 0


def test_cli_rejects_unknown_checker(capsys):
    assert lint_main(["--checkers", "nope", "corrosion_tpu"]) == 2


# --- trace stability ------------------------------------------------------


def test_hot_entry_points_compile_once():
    """One compilation per registered hot entry point across
    representative re-invocations (fresh keys, rebuilt inputs, host
    round-trips, donated-carry chaining) — the PERF.md no-retrace story
    as an enforced contract."""
    from corrosion_tpu.analysis.tracecount import assert_trace_stable

    counts = assert_trace_stable(repeats=3)
    assert set(counts) == {
        "full_sim_step", "scale_sim_step", "segment_dispatch",
        "sharded_scale_run", "segmented_soak", "fused_scale_run",
        "quiet_scale_run",
    }


def test_counting_jit_counts_retraces():
    """The counter itself must detect instability (meta-test: a probe
    that DOES retrace reports > 1)."""
    import jax.numpy as jnp

    from corrosion_tpu.analysis.tracecount import counting_jit

    fn, traces = counting_jit(lambda x: x * 2)
    fn(jnp.zeros(3))
    fn(jnp.zeros(3))  # cache hit
    assert traces() == 1
    fn(jnp.zeros(4))  # new shape: retrace
    assert traces() == 2
