"""corroguard admission control (PR 17, docs/overload.md): the
AdmissionController policy surface unit-tested against a private
registry, route classification, the derived Retry-After hint, the
client's hint-honoring retry engine, and the HTTP 503 / PG-wire 53300
shed paths end-to-end on a real rig."""

import socket
import struct
import threading
import time
import urllib.request

import pytest

from corrosion_tpu.agent import Agent
from corrosion_tpu.api.admission import (
    ROUTE_CLASSES,
    AdmissionController,
    route_class,
)
from corrosion_tpu.api.http import ApiServer
from corrosion_tpu.client import ApiUnavailable, CorrosionApiClient
from corrosion_tpu.config import Config, ServeConfig
from corrosion_tpu.db import Database
from corrosion_tpu.pg import PgServer
from corrosion_tpu.utils.backoff import Backoff, retry_call
from corrosion_tpu.utils.metrics import Registry


def ctl(reg=None, **kw) -> AdmissionController:
    return AdmissionController(ServeConfig(**kw),
                               registry=reg or Registry())


# --- policy units ---------------------------------------------------------

def test_disabled_guard_admits_everything():
    """max_inflight <= 0 is the unguarded plane: every admit is free
    and the admission series are never minted."""
    reg = Registry()
    # defaults are non-zero (measured, docs/overload.md) since r18 —
    # the naked plane is now an explicit opt-out
    c = AdmissionController(ServeConfig.unlimited(), registry=reg)
    assert not c.enabled
    for cls in ROUTE_CLASSES:
        for _ in range(64):
            assert c.admit(cls)
    assert reg.get_counter("corro.admission.admitted_total",
                           {"class": "write"}) == 0.0


def test_cap_reject_and_release_cycle():
    """At capacity with an empty waiting room the next admit sheds
    immediately; release hands the slot back."""
    reg = Registry()
    c = ctl(reg, max_inflight=2, max_queue=0, queue_wait=0.01)
    assert c.admit("write") and c.admit("write")
    t0 = time.monotonic()
    assert not c.admit("write")
    assert time.monotonic() - t0 < 0.5  # no waiting room -> no wait
    assert reg.get_counter("corro.admission.admitted_total",
                           {"class": "write"}) == 2.0
    assert reg.get_counter("corro.admission.rejected_total",
                           {"class": "write"}) == 1.0
    assert reg.get_gauge("corro.admission.inflight",
                         {"class": "write"}) == 2.0
    c.release("write")
    assert c.admit("write")
    assert reg.get_gauge("corro.admission.inflight",
                         {"class": "write"}) == 2.0


def test_classes_have_independent_budgets():
    c = ctl(max_inflight=1, max_queue=0, queue_wait=0.01)
    assert c.admit("write")
    assert c.admit("read")  # a full write class gates nothing else
    assert not c.admit("write")


def test_queued_caller_gets_freed_slot():
    """A caller parked in the waiting room is admitted when a slot
    frees within queue_wait (no shed, queued_total counts the park)."""
    reg = Registry()
    c = ctl(reg, max_inflight=1, max_queue=1, queue_wait=5.0)
    assert c.admit("write")
    out = {}

    def waiter():
        out["admitted"] = c.admit("write")

    t = threading.Thread(target=waiter)
    t.start()
    # wait until the waiter is actually parked before releasing
    deadline = time.monotonic() + 5.0
    while (reg.get_counter("corro.admission.queued_total",
                           {"class": "write"}) < 1.0
           and time.monotonic() < deadline):
        time.sleep(0.005)
    c.release("write")
    t.join(timeout=5.0)
    assert out["admitted"] is True
    assert reg.get_counter("corro.admission.queued_total",
                           {"class": "write"}) == 1.0
    assert reg.get_counter("corro.admission.rejected_total",
                           {"class": "write"}) == 0.0
    assert reg.get_gauge("corro.admission.queue.depth",
                         {"class": "write"}) == 0.0


def test_queue_wait_timeout_sheds():
    reg = Registry()
    c = ctl(reg, max_inflight=1, max_queue=1, queue_wait=0.05)
    assert c.admit("write")
    t0 = time.monotonic()
    assert not c.admit("write")  # parks, times out, sheds
    assert 0.04 <= time.monotonic() - t0 < 2.0
    assert reg.get_counter("corro.admission.queued_total",
                           {"class": "write"}) == 1.0
    assert reg.get_counter("corro.admission.rejected_total",
                           {"class": "write"}) == 1.0
    assert reg.get_gauge("corro.admission.queue.depth",
                         {"class": "write"}) == 0.0


def test_full_waiting_room_sheds_without_waiting():
    c = ctl(max_inflight=1, max_queue=1, queue_wait=10.0)
    assert c.admit("write")
    parked = threading.Thread(target=c.admit, args=("write",))
    parked.start()
    deadline = time.monotonic() + 5.0
    while c._waiting["write"] < 1 and time.monotonic() < deadline:
        time.sleep(0.005)
    t0 = time.monotonic()
    assert not c.admit("write")  # room already holds max_queue waiters
    assert time.monotonic() - t0 < 1.0  # shed NOW, not after queue_wait
    c.release("write")
    parked.join(timeout=5.0)
    c.release("write")


def test_stream_capacity_separate_from_oneshot():
    """stream/pg draw from max_streams (held-ticket classes must not
    starve one-shot requests); <=0 falls back to max_inflight."""
    c = ctl(max_inflight=2, max_queue=0, queue_wait=0.01, max_streams=5)
    assert c.capacity("write") == 2 and c.capacity("read") == 2
    assert c.capacity("stream") == 5 and c.capacity("pg") == 5
    for _ in range(5):
        assert c.admit("stream")
    assert not c.admit("stream")
    # max_streams=0 is the explicit fallback-to-max_inflight knob (the
    # default is now a measured non-zero cap, see docs/overload.md)
    assert ctl(max_inflight=3, max_streams=0).capacity("stream") == 3


def test_route_class_mapping():
    # the control plane is NEVER gated
    for route in ("/v1/health", "/v1/ready", "/metrics"):
        assert route_class(route, "GET") is None
        assert route_class(route, "POST") is None
    assert route_class("/v1/transactions", "POST") == "write"
    assert route_class("/v1/migrations", "POST") == "write"
    assert route_class("/v1/subscriptions", "POST") == "stream"
    assert route_class("/v1/subscriptions/{id}", "GET") == "stream"
    assert route_class("/v1/updates/{table}", "GET") == "stream"
    assert route_class("/v1/queries", "POST") == "read"
    assert route_class("unmatched", "GET") == "read"


# --- Retry-After derivation -----------------------------------------------

def test_retry_after_cold_plane_quotes_floor():
    assert ctl(max_inflight=1).retry_after("write") == 1


def test_retry_after_scales_and_clamps_to_cap():
    """p95 x (requests ahead) — a deep slow backlog quotes the cap, and
    the hint is memoized so rejects stay cheap under overload."""
    reg = Registry()
    c = ctl(reg, max_inflight=8, max_queue=0, queue_wait=0.01,
            retry_after_cap=7.0)
    for _ in range(50):
        reg.histogram("corro.http.request.seconds", 4.0,
                      {"route": "/v1/transactions", "method": "POST",
                       "code": "200"})
    for _ in range(5):
        assert c.admit("write")
    ra = c.retry_after("write")
    assert ra == 7  # ~4s p95 * 5 ahead, clamped to the cap
    # memo: new observations within the 0.25 s window do not re-derive
    for _ in range(50):
        reg.histogram("corro.http.request.seconds", 0.001,
                      {"route": "/v1/transactions", "method": "POST",
                       "code": "200"})
    assert c.retry_after("write") == ra


def test_retry_after_always_at_least_one_second():
    reg = Registry()
    c = ctl(reg, max_inflight=8, retry_after_cap=30.0)
    reg.histogram("corro.http.request.seconds", 0.0005,
                  {"route": "/v1/queries", "method": "POST",
                   "code": "200"})
    assert c.retry_after("read") >= 1


# --- the client retry engine honors the hint ------------------------------

class _Hinted(ConnectionError):
    def __init__(self, hint):
        super().__init__("503")
        self.retry_after = hint


def test_retry_call_honors_retry_after_hint():
    """A retryable exception carrying retry_after overrides the
    jittered schedule for that attempt."""
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise _Hinted(0.125)
        return "done"

    out = retry_call(flaky,
                     backoff=Backoff(min_wait=30.0, max_wait=60.0,
                                     jitter=0.0, max_retries=5),
                     sleep=sleeps.append)
    assert out == "done"
    assert sleeps == [0.125, 0.125]  # the hint, not the 30 s schedule


def test_retry_call_caps_hint_at_max_wait():
    """A hostile/confused hint cannot park the client past the
    policy's max_wait."""
    sleeps = []
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise _Hinted(3600.0)
        return "ok"

    assert retry_call(flaky,
                      backoff=Backoff(min_wait=0.01, max_wait=0.25,
                                      jitter=0.0, max_retries=3),
                      sleep=sleeps.append) == "ok"
    assert sleeps == [0.25]


# --- end to end on a real rig ---------------------------------------------

SCHEMA = """
CREATE TABLE adm (
    k TEXT PRIMARY KEY,
    v INTEGER
);
"""


def adm_config():
    cfg = Config()
    cfg.sim.mode = "scale"
    cfg.sim.n_nodes = 16
    cfg.sim.m_slots = 8
    cfg.sim.n_origins = 4
    cfg.sim.n_rows = 16
    cfg.sim.n_cols = 4
    cfg.perf.sync_interval = 4
    cfg.gossip.drop_prob = 0.0
    return cfg


@pytest.fixture(scope="module")
def rig():
    serve = ServeConfig(max_inflight=1, max_queue=0, queue_wait=0.05,
                        max_streams=1, retry_after_cap=7.0)
    with Agent(adm_config()) as agent:
        agent.wait_rounds(10, timeout=120)
        db = Database(agent)
        admission = AdmissionController(serve, registry=agent.metrics)
        with ApiServer(db, port=0, serve=serve,
                       admission=admission) as api, \
                PgServer(db, port=0, admission=admission) as pgs:
            client = CorrosionApiClient(api.addr, api.port)
            client.schema([SCHEMA])
            yield agent, api, pgs, admission, client


def _quiesce(admission, timeout=10.0):
    """Wait for every slot to be released: a client sees its response a
    beat before the server handler's finally-release runs, so a test
    that grabs slots right after a request can race that gap."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with admission._mu:
            if all(v == 0 for v in admission._inflight.values()):
                return
        time.sleep(0.005)
    raise AssertionError(f"slots still held: {admission._inflight}")


def test_http_write_shed_503_with_derived_retry_after(rig):
    agent, api, _, admission, client = rig
    _quiesce(admission)
    before = agent.metrics.get_counter("corro.http.unready_total",
                                       {"status": "overloaded"})
    assert admission.admit("write")  # saturate the single write slot
    try:
        with pytest.raises(ApiUnavailable) as e:
            client.execute([("INSERT INTO adm (k, v) VALUES (?, ?)",
                             ["shed", 1])])
        assert e.value.status == 503
        assert e.value.retry_after is not None
        assert 1 <= e.value.retry_after <= 7  # clamped to the rig's cap
    finally:
        admission.release("write")
    assert agent.metrics.get_counter(
        "corro.http.unready_total", {"status": "overloaded"}) == before + 1
    assert agent.metrics.get_counter(
        "corro.admission.rejected_total", {"class": "write"}) >= 1.0


def test_control_plane_never_gated(rig):
    """/v1/health answers 200 even with every admission class
    saturated — you can always ask a drowning server how it feels."""
    _, api, _, admission, _ = rig
    _quiesce(admission)
    held = [c for c in ROUTE_CLASSES if admission.admit(c)]
    assert set(held) == set(ROUTE_CLASSES)
    try:
        with urllib.request.urlopen(
                f"http://{api.addr}:{api.port}/v1/health",
                timeout=30) as resp:
            assert resp.status == 200
    finally:
        for c in held:
            admission.release(c)


def test_client_with_retry_503_rides_out_the_shed(rig):
    """A retry_503 client sleeps the server's hint and succeeds once
    the slot frees — the closed-loop contract of the overload bench."""
    _, api, _, admission, _ = rig
    _quiesce(admission)
    polite = CorrosionApiClient(api.addr, api.port, retry_503=6,
                                retry_503_max_wait=0.1)
    assert admission.admit("write")
    freed = threading.Timer(0.3, admission.release, args=("write",))
    freed.start()
    try:
        res = polite.execute([("INSERT INTO adm (k, v) VALUES (?, ?)",
                               ["polite", 2])])
        assert res[0]["rows_affected"] == 1
    finally:
        freed.join()


def test_pg_accept_shed_53300(rig):
    """A shed PG connection gets the canonical 53300 ErrorResponse
    before the auth handshake."""
    _, _, pgs, admission, _ = rig
    _quiesce(admission)
    assert admission.admit("pg")  # saturate the single pg ticket
    try:
        with socket.create_connection((pgs.addr, pgs.port),
                                      timeout=30) as s:
            payload = struct.pack("!I", 196608)
            for k, v in (("user", "t"), ("database", "corrosion")):
                payload += k.encode() + b"\x00" + v.encode() + b"\x00"
            payload += b"\x00"
            s.sendall(struct.pack("!I", len(payload) + 4) + payload)
            kind = s.recv(1)
            assert kind == b"E"
            (length,) = struct.unpack("!I", _read_exact(s, 4))
            body = _read_exact(s, length - 4)
            assert b"53300" in body
            assert b"retry after" in body
    finally:
        admission.release("pg")


def _read_exact(s: socket.socket, n: int) -> bytes:
    data = b""
    while len(data) < n:
        chunk = s.recv(n - len(data))
        if not chunk:
            raise ConnectionResetError
        data += chunk
    return data
