"""Per-round bandwidth budget + queue overflow policy.

The reference meters broadcast at 10 MiB/s through a governor and, when
the queue overflows, drops the oldest most-sent changeset to admit new
ones (``crates/corro-agent/src/broadcast/mod.rs:410-812,460-463``). The
sim analogs: ``bcast_budget_bytes`` shapes how many queued changesets may
ride each round's packets (least-sent first), and ``alloc_slots_evict``
implements drop-oldest-most-sent."""

import jax.numpy as jnp
import jax.random as jr
import numpy as np

from corrosion_tpu.ops.slots import alloc_slots_evict, budget_mask
from corrosion_tpu.sim import scenario
from corrosion_tpu.sim.broadcast import CHANGE_WIRE_BYTES
from corrosion_tpu.sim.config import wan_config
from corrosion_tpu.sim.step import SimState, crdt_metrics, run_rounds
from corrosion_tpu.sim.transport import NetModel


def test_alloc_slots_evict_prefers_free_then_most_sent():
    # row 0: slot 1 free -> used first; then evict slot 2 (lowest key)
    free = jnp.array([[False, True, False, False]])
    evict_key = jnp.array([[5, 99, 1, 3]], jnp.int32)
    want = jnp.array([[True, True, True, False]])
    slot, placed = alloc_slots_evict(free, evict_key, want)
    assert placed.all(axis=1)[0] or bool(placed[0, :3].all())
    got = [int(slot[0, j]) for j in range(3)]
    assert got[0] == 1  # the free slot
    assert got[1] == 2  # most-sent (lowest remaining budget) evicted first
    assert got[2] == 3  # next lowest


def test_alloc_slots_evict_caps_at_capacity():
    free = jnp.zeros((1, 2), bool)
    evict_key = jnp.array([[1, 2]], jnp.int32)
    want = jnp.ones((1, 4), bool)
    slot, placed = alloc_slots_evict(free, evict_key, want)
    assert int(placed.sum()) == 2  # only K items can land


def test_budget_mask_keeps_highest_priority():
    live = jnp.array([[True, True, True, False]])
    pri = jnp.array([[3, 9, 5, 7]], jnp.int32)
    out = budget_mask(live, pri, allowed=2)
    assert out.tolist() == [[False, True, True, False]]
    # allowed >= K is a no-op
    assert budget_mask(live, pri, allowed=4) is live


def test_overload_budget_shapes_then_sync_repairs():
    """Under a send budget far below the offered write load, dissemination
    is shaped (per-round sends bounded by the budget), the queue evicts
    rather than wedges, and anti-entropy sync still repairs the cluster to
    convergence once the load stops."""
    n = 16
    budget_slots = 2  # changesets per node-round through the carrier
    fanout = wan_config(n).bcast_fanout
    cfg = wan_config(
        n,
        n_origins=4,
        n_rows=4,
        n_cols=2,
        sync_interval=2,
        bcast_queue=8,
        bcast_budget_bytes=budget_slots * CHANGE_WIRE_BYTES * fanout,
    )
    st = SimState.create(cfg)
    net = NetModel.create(n, drop_prob=0.0)
    # heavy load: every origin writes every round for 30 rounds
    inp = scenario.conflict_heavy(cfg, 30, jr.key(1), write_prob=1.0, hot_cells=4)
    st, infos = run_rounds(cfg, st, net, jr.key(2), inp)
    sent = np.asarray(infos["sent"])
    # budget-shaped: a node can flush at most budget_slots slots to at
    # most fanout targets each round
    assert (sent <= n * budget_slots * cfg.bcast_fanout).all(), sent.max()
    # repair: stop writing, let sync close the gaps
    st, _ = run_rounds(cfg, st, net, jr.key(3), scenario.quiet(cfg, 200))
    m = crdt_metrics(cfg, st)
    assert bool(m["converged"]), (int(m["n_diverged"]), int(m["total_needs"]))
