"""Maintenance loop (auto-checkpoint rotation, heap watch) + the
launch_test_agent fixture (``corro-tests`` analog)."""

import pytest

from corrosion_tpu.checkpoint import restore_checkpoint
from corrosion_tpu.maintenance import MaintenanceLoop
from corrosion_tpu.testing import TEST_SCHEMA, launch_test_agent, cluster_config


def test_cluster_config_overrides():
    cfg = cluster_config(n_nodes=8, drop_prob=0.5, sync_interval=2)
    assert cfg.sim.n_nodes == 8
    assert cfg.gossip.drop_prob == 0.5
    assert cfg.perf.sync_interval == 2
    with pytest.raises(AttributeError):
        cluster_config(nope=1)


@pytest.fixture(scope="module")
def rig():
    with launch_test_agent(http=True) as r:
        yield r


def test_launch_test_agent_full_stack(rig):
    # schema applied, HTTP up, cluster gossips
    rig.client.execute([
        ("INSERT INTO tests (id, text) VALUES (?, ?)", [1, "hello"])])
    cols, rows = rig.client.query("SELECT id, text FROM tests")
    assert rows == [[1, "hello"]]
    assert len(rig.client.members()) == rig.agent.n_nodes


def test_auto_checkpoint_rotation(tmp_path, rig):
    maint = MaintenanceLoop(
        rig.agent, db=rig.db, checkpoint_path=str(tmp_path),
        checkpoint_rounds=1,
    )
    rig.agent.wait_rounds(2, timeout=60)
    first = maint.tick()
    assert first and first.endswith("auto-a")
    rig.agent.wait_rounds(2, timeout=60)
    second = maint.tick()
    assert second and second.endswith("auto-b")
    # latest picks the most recent complete side
    latest = MaintenanceLoop.latest_auto_checkpoint(str(tmp_path))
    assert latest == second
    # and it restores cleanly
    man = restore_checkpoint(rig.agent, latest, db=rig.db)
    assert man["round"] >= 1


def test_checkpoint_cadence_respected(tmp_path, rig):
    maint = MaintenanceLoop(
        rig.agent, db=rig.db, checkpoint_path=str(tmp_path),
        checkpoint_rounds=10_000_000,
    )
    maint._last_ckpt_round = rig.agent.round_no
    assert maint.tick() is None  # cadence not reached -> no write


def test_resume_falls_back_past_corrupt_side(tmp_path, rig):
    maint = MaintenanceLoop(
        rig.agent, db=rig.db, checkpoint_path=str(tmp_path),
        checkpoint_rounds=1,
    )
    rig.agent.wait_rounds(2, timeout=60)
    a = maint.tick()
    rig.agent.wait_rounds(2, timeout=60)
    b = maint.tick()
    assert a and b and a != b
    # corrupt the newest side's state file; its manifest still exists
    import json
    import os
    newest = MaintenanceLoop.latest_auto_checkpoint(str(tmp_path))
    with open(os.path.join(newest, "manifest.json")) as f:
        name = sorted(json.load(f)["files"])[0]
    with open(os.path.join(newest, name), "wb") as f:
        f.write(b"garbage")
    man = MaintenanceLoop.resume_latest(rig.agent, str(tmp_path), db=rig.db)
    assert man is not None and man["path"] != newest  # fell back


def test_rotation_seeds_away_from_latest(tmp_path, rig):
    m1 = MaintenanceLoop(rig.agent, db=rig.db, checkpoint_path=str(tmp_path),
                         checkpoint_rounds=1)
    rig.agent.wait_rounds(2, timeout=60)
    first = m1.tick()
    assert first.endswith("auto-a")
    # a fresh loop (restart) must write the OTHER side first
    m2 = MaintenanceLoop(rig.agent, db=rig.db, checkpoint_path=str(tmp_path),
                         checkpoint_rounds=1)
    rig.agent.wait_rounds(2, timeout=60)
    second = m2.tick()
    assert second.endswith("auto-b")


def test_incomplete_side_is_invisible(tmp_path, rig):
    import os

    maint = MaintenanceLoop(rig.agent, db=rig.db, checkpoint_path=str(tmp_path),
                            checkpoint_rounds=1)
    rig.agent.wait_rounds(2, timeout=60)
    maint.tick()
    good = MaintenanceLoop.latest_auto_checkpoint(str(tmp_path))
    # simulate a crash mid-write on the other side: state.npz without manifest
    other = os.path.join(str(tmp_path), "auto-b")
    os.makedirs(other, exist_ok=True)
    with open(os.path.join(other, "state.npz"), "wb") as f:
        f.write(b"partial")
    assert MaintenanceLoop.latest_auto_checkpoint(str(tmp_path)) == good


def test_heap_watch_warns_once(tmp_path, rig, caplog):
    maint = MaintenanceLoop(rig.agent, db=rig.db, heap_soft_limit=1)
    import logging

    with caplog.at_level(logging.WARNING, logger="corrosion_tpu"):
        maint.tick()
        maint.tick()
    warnings = [r for r in caplog.records if "value heap" in r.message]
    assert len(warnings) == 1  # warned exactly once
    assert rig.agent.metrics.get_gauge("corro.db.value_heap.len") >= 1


def test_members_persist_and_bootstrap(tmp_path, rig):
    """Membership -> DB persistence round-trip (the __corro_members
    analog, broadcast/mod.rs:814-949 + util.rs:69-130): the maintenance
    loop dumps the member list; a FRESH agent bootstraps its SWIM views
    from the dump and starts out believing in the persisted members, not
    just the static seed set."""
    import json

    import numpy as np

    from corrosion_tpu.agent import Agent
    from corrosion_tpu.ops.lww import STATE_ALIVE

    agent = rig.agent
    path = str(tmp_path / "members.json")
    old_members_path = agent.config.db.members_path
    agent.config.db.members_path = path
    try:
        loop = MaintenanceLoop(agent, db=rig.db, interval_seconds=0.1)
        agent.wait_rounds(2, timeout=60)
        loop.tick()
    finally:
        agent.config.db.members_path = old_members_path
    dump = json.load(open(path))
    assert len(dump["members"]) == agent.n_nodes  # everyone alive

    # a FRESH agent (no shared state) bootstrapping from the dump knows
    # every persisted member at round zero
    cfg = cluster_config()
    cfg.db.members_path = path
    fresh = Agent(cfg)  # not started — inspect the initial state
    swim = fresh._state.swim
    believed = (
        (swim.mem_id >= 0)
        & (swim.mem_view >= 0)
        & ((swim.mem_view & 3) == STATE_ALIVE)
    )
    known_per_node = np.asarray(believed.sum(axis=1))
    # bounded table: every node knows (at least) most of the 16 members
    # immediately — far more than the 4-seed cold boot
    assert known_per_node.min() >= 8, known_per_node.tolist()

    # a cold-boot agent without the file only knows seeds + itself
    cold = Agent(cluster_config())
    cold_swim = cold._state.swim
    cold_believed = (
        (cold_swim.mem_id >= 0)
        & (cold_swim.mem_view >= 0)
        & ((cold_swim.mem_view & 3) == STATE_ALIVE)
    )
    assert np.asarray(cold_believed.sum(axis=1)).max() <= 6


# --- round-5: heap compaction (vacuum_db analog, handlers.rs:398-452) ----

def test_heap_compaction_frees_unreferenced_ids(rig):
    db, agent = rig.db, rig.agent
    vid_old = db.heap.intern("compact-me-old")
    db.execute(0, [("INSERT INTO tests (id, text) VALUES (40, "
                    "'compact-me-old')",)])
    agent.wait_rounds(6, timeout=60)  # disseminate: replicas + queues
    # overwrite everywhere: the old value must drain from every replica
    db.execute(0, [("UPDATE tests SET text = 'compact-me-new' "
                    "WHERE id = 40",)])
    agent.wait_rounds(20, timeout=120)  # converge + queue slots freed
    refs = db.referenced_value_ids()
    assert vid_old not in refs, "old value still referenced somewhere"
    live_before = db.heap.live_count
    freed = db.compact_heap(grace_seconds=0.0)
    assert freed >= 1
    assert db.heap.live_count == live_before - freed
    # the old id is gone; the new value still resolves
    with pytest.raises(LookupError):
        db.heap.lookup(vid_old)
    _, rows = db.query(0, "SELECT text FROM tests WHERE id = 40")
    assert list(rows) == [["compact-me-new"]]
    # freed ids are REUSED by later interns (stable-id free list)
    vid_new = db.heap.intern("compact-reuse")
    assert vid_new <= live_before  # came from the free list, not append


def test_heap_state_dict_preserves_holes(rig):
    from corrosion_tpu.db.values import ValueHeap

    h = ValueHeap()
    a, b, c = h.intern("keep-a"), h.intern("drop-b"), h.intern("keep-c")
    h.compact({a, c}, grace_seconds=0.0)
    h2 = ValueHeap.from_state_dict(h.state_dict())
    # positions survive the roundtrip, including the hole
    assert h2.lookup(a) == "keep-a" and h2.lookup(c) == "keep-c"
    with pytest.raises(LookupError):
        h2.lookup(b)
    # and the hole is reusable
    assert h2.intern("refill") == b


def test_maintenance_compacts_on_cadence(rig, caplog):
    maint = MaintenanceLoop(rig.agent, db=rig.db, heap_compact_rounds=0,
                            heap_grace_seconds=1e9)
    # grace window keeps everything: cadence pass frees nothing, no warn
    maint.tick()
    assert rig.agent.metrics.get_gauge("corro.db.value_heap.live") >= 1
