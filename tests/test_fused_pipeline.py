"""ISSUE 10: the fused megakernel path as a first-class citizen of the
sharded / donated / segmented pipeline.

The ``fused`` config knob (``config.perf.fused`` -> ``cfg.fused``,
docs/fused.md) replaced the old module-global test pin; these tests
cover the knob's gate matrix, interpret-mode fused == unfused bitwise
parity through every production dispatcher (single step, 1-D and 2-D
sharded mesh runs, a crash-injected segmented soak resume), and the
pipeline telemetry the segments runner / bench record.
"""

import dataclasses

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from corrosion_tpu.ops import megakernel
from corrosion_tpu.resilience.segments import (
    make_soak_inputs,
    resume_segmented,
    run_segmented,
)
from corrosion_tpu.sim.scale_step import (
    ScaleSimState,
    scale_run_rounds,
    scale_sim_config,
    scale_sim_step,
)
from corrosion_tpu.sim.transport import NetModel


def _cfg(**overrides):
    return scale_sim_config(
        32, m_slots=8, n_origins=4, n_rows=4, n_cols=2, sync_interval=4,
        **overrides,
    )


def _trees_equal(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


# --- the knob itself ------------------------------------------------------


def test_fused_knob_validates():
    with pytest.raises(ValueError, match="fused"):
        _cfg(fused="pallas-please")
    for mode in megakernel.FUSED_MODES:
        assert _cfg(fused=mode).fused == mode
    # ONE canonical mode tuple: the configs, the gates, and the CLI all
    # share sim.config.FUSED_MODES (megakernel re-exports it)
    from corrosion_tpu.sim.config import FUSED_MODES, SimConfig

    assert megakernel.FUSED_MODES is FUSED_MODES
    with pytest.raises(ValueError, match="fused"):
        SimConfig(n_nodes=8, fused="bogus").validate()
    from corrosion_tpu.sim.scale import scale_config

    with pytest.raises(ValueError, match="fused"):
        scale_config(8, fused="bogus")


def test_perf_config_threads_fused():
    """config.perf.fused reaches the sim config — file key and env
    overlay both (the production plumbing the CLI/Agent ride)."""
    from corrosion_tpu.config import Config, load_config

    cfg_file = Config()
    cfg_file.perf.fused = "interpret"
    assert cfg_file.to_scale_config().fused == "interpret"
    assert cfg_file.to_full_config().fused == "interpret"
    overlaid = load_config(
        None, environ={"CORRO_TPU__PERF__FUSED": "off"}
    )
    assert overlaid.sim_config().fused == "off"


def test_prime_fused_decisions_cpu():
    """The hoisted probe entry: pinned modes decide without probing;
    auto on CPU stays on the XLA path."""
    assert megakernel.prime_fused(_cfg(fused="interpret")) == {
        "mode": "interpret", "interpret": True,
        "ingest": True, "ingest_emit": True, "swim": True,
    }
    off = megakernel.prime_fused(_cfg(fused="off"))
    assert off["mode"] == "off"
    assert not (off["ingest"] or off["ingest_emit"] or off["swim"])
    # an XLA-only run must never claim interpret-mode execution
    assert off["interpret"] is False
    assert megakernel.fused_engaged(off) is False
    auto = megakernel.prime_fused(_cfg(fused="auto"))
    assert auto["ingest"] is False and auto["swim"] is False
    assert auto["interpret"] is False


# --- gate matrix: shape-keyed caching, no re-probe inside a trace ---------


@pytest.fixture
def mock_tpu(monkeypatch):
    """A TPU-shaped backend for the gates only (``megakernel._backend``
    is a seam precisely so the jit machinery keeps its real backend)."""
    monkeypatch.setattr(megakernel, "_backend", lambda: "mock-tpu")
    saved_ok = dict(megakernel._pallas_ok_cache)
    saved_width = dict(megakernel._width_ok_cache)
    yield
    megakernel._pallas_ok_cache.clear()
    megakernel._pallas_ok_cache.update(saved_ok)
    megakernel._width_ok_cache.clear()
    megakernel._width_ok_cache.update(saved_width)


def test_width_probe_cache_is_shape_keyed_and_never_reprobes_in_trace(
        mock_tpu, monkeypatch):
    """Satellite (ISSUE 10): under ``auto`` the width probes run once
    per (backend, shape) via the ``_eager`` escape hatch; an identical
    shape consulted from INSIDE a jit trace must hit the cache, and a
    different shape must key a fresh probe."""
    calls = []

    def stub_eager(fn):
        calls.append(fn)
        return True

    monkeypatch.setattr(megakernel, "_eager", stub_eager)
    megakernel._pallas_ok_cache["mock-tpu"] = True
    # n chosen so a cheaper representative block exists (see _probe_n):
    # blk(4096) = 1024, probe n = 3072 < 4096 — the probe actually runs
    n, m = 4096, 64
    assert megakernel.use_fused_swim(n, m, 0, mode="auto")
    assert len(calls) == 1
    # same shape, from inside a trace: cache hit, no new probe
    def traced(x):
        assert megakernel.use_fused_swim(n, m, 0, mode="auto")
        return x + 1

    jax.jit(traced)(jnp.zeros(3))
    assert len(calls) == 1
    # a different width is a different cache key -> one fresh probe
    assert megakernel.use_fused_swim(n, 2 * m, 0, mode="auto")
    assert len(calls) == 2
    # narrow-dtype lowering keys separately too (int16 planes lower
    # differently)
    assert megakernel.use_fused_swim(n, m, 0, narrow=True, mode="auto")
    assert len(calls) == 3


def test_fused_off_pins_xla_under_tpu_backend(mock_tpu, monkeypatch):
    """Satellite (ISSUE 10): ``fused="off"`` provably takes the XLA
    path on a TPU-shaped backend — the gates answer False without ever
    spawning a probe."""

    def exploding_eager(fn):
        raise AssertionError("fused='off' must never probe")

    monkeypatch.setattr(megakernel, "_eager", exploding_eager)
    cfg = _cfg(fused="off")
    assert megakernel.use_fused_ingest(cfg, msgs=1) is False
    assert megakernel.use_fused_ingest(cfg, msgs=16, emit=True) is False
    assert megakernel.use_fused_swim(
        cfg.n_nodes, cfg.m_slots, 0, mode="off") is False
    dec = megakernel.prime_fused(cfg)
    assert not (dec["ingest"] or dec["swim"])
    # pinned-on modes skip the probes symmetrically (no eager calls)
    assert megakernel.use_fused_ingest(_cfg(fused="on"), msgs=1) is True


def test_eager_probe_thread_is_counted_and_corro_named(monkeypatch):
    """Satellite (ISSUE 10): the probe escape-hatch thread rides
    ``spawn_counted`` under a ``corro-`` name, so corrosan's leak gate
    and the conftest liveness check attribute it like every other
    spawn in this repo."""
    import threading

    monkeypatch.setattr(megakernel, "_trace_state_clean", False)
    info = megakernel._eager(
        lambda: (threading.current_thread().name,
                 threading.current_thread().daemon)
    )
    assert info == ("corro-pallas-probe", True)


# --- interpret-mode parity through the pipeline ---------------------------


def test_single_step_parity_interpret():
    """fused(interpret) == unfused bitwise for the jitted single step."""
    import functools

    net = NetModel.create(32, drop_prob=0.02)
    outs = {}
    for mode in ("interpret", "off"):
        cfg = _cfg(fused=mode)
        step = jax.jit(functools.partial(scale_sim_step, cfg))
        st = ScaleSimState.create(cfg)
        inp = make_soak_inputs(cfg, jr.key(1), 6, write_frac=0.3)
        for r in range(6):
            st, _ = step(st, net, jr.fold_in(jr.key(2), r),
                         jax.tree.map(lambda a: a[r], inp))
        outs[mode] = jax.block_until_ready(st)
    assert _trees_equal(outs["interpret"], outs["off"])


@pytest.mark.parametrize("mesh_kind", ["1d", "2d"])
def test_sharded_mesh_parity_interpret(mesh_kind):
    """fused(interpret) == unfused bitwise through the REAL donated
    sharded entry point (``parallel/mesh.sharded_scale_run``), on the
    1-D node mesh and the 2-D (dcn, node) fold."""
    from corrosion_tpu.parallel.mesh import (
        buffers_donated,
        make_mesh,
        make_multihost_mesh,
        shard_state,
        sharded_scale_run,
    )

    n, rounds = 64, 4
    net = NetModel.create(n, drop_prob=0.02)
    cfg_off = scale_sim_config(
        n, m_slots=8, n_origins=4, n_rows=4, n_cols=2, sync_interval=4,
        fused="off")
    # shapes are fused-independent: one input stack serves both arms
    inputs = make_soak_inputs(cfg_off, jr.key(7), rounds, write_frac=0.25)
    key = jr.key(9)

    # unfused single-device reference
    st_ref, _ = jax.jit(
        lambda s, k, i: scale_run_rounds(cfg_off, s, net, k, i)
    )(ScaleSimState.create(cfg_off), key, inputs)
    st_ref = jax.block_until_ready(st_ref)

    cfg_f = dataclasses.replace(cfg_off, fused="interpret").validate()
    mesh = make_multihost_mesh(2) if mesh_kind == "2d" else make_mesh()
    st = shard_state(mesh, n, ScaleSimState.create(cfg_f))
    probe = st
    st_f, _ = sharded_scale_run(
        cfg_f, mesh, st, shard_state(mesh, n, net), key,
        shard_state(mesh, n, inputs))
    st_f = jax.block_until_ready(st_f)
    # the fused path rode the donated dispatch for real
    assert buffers_donated(probe)
    assert _trees_equal(st_ref, st_f)


# slow (ISSUE 12 tier-1 rebalance): ~33s; crash-injected resume stays
# tier-1 unfused (test_resilience) and cross-mode fused resume stays
# via test_fused_checkpoint_resumes_across_modes — check.sh's fused
# interpret smoke still replays the full segmented fused pipeline
@pytest.mark.slow
def test_fused_segmented_soak_crash_injected_resume(tmp_path, monkeypatch):
    """The acceptance scenario in one: a fused(interpret) segmented
    soak with per-segment checkpoints, a crash injected mid-save, a
    resume from the surviving checkpoint — final state bitwise equal to
    the straight UNFUSED scan, with the stats recording the fused
    pipeline (donation + pallas engagement)."""
    import corrosion_tpu.checkpoint as ckpt_mod
    from corrosion_tpu.resilience.retention import latest_valid_checkpoint

    rounds = 16
    cfg_off = _cfg(fused="off")
    cfg_f = _cfg(fused="interpret")
    net = NetModel.create(cfg_off.n_nodes, drop_prob=0.02)
    st0 = ScaleSimState.create(cfg_off)
    key0 = jr.key(3)
    inputs = make_soak_inputs(cfg_off, jr.key(5), rounds, write_frac=0.25)
    st_ref, _ = jax.jit(
        lambda s, k, i: scale_run_rounds(cfg_off, s, net, k, i)
    )(st0, key0, inputs)
    st_ref = jax.block_until_ready(st_ref)

    root = str(tmp_path / "soak")
    # fused run of the first half: 2 donated-pipeline segments,
    # checkpoints at rounds 4 and 8
    r1 = run_segmented(cfg_f, ScaleSimState.create(cfg_f), net, key0,
                       jax.tree.map(lambda a: a[:8], inputs),
                       segment_rounds=4, checkpoint_root=root)
    assert not r1.aborted and r1.completed_rounds == 8
    assert r1.stats["pallas_fused"] and r1.stats["fused_mode"] == "interpret"
    assert r1.stats["donated_segments"] >= 1
    good = latest_valid_checkpoint(root)

    # crash mid-save of the NEXT checkpoint: the half-written side must
    # not poison recovery (sync writer so the failure fires at the save)
    def exploding_write(path, data):
        with open(path, "wb") as f:
            f.write(b"PK\x03\x04 partial garbage")
        raise OSError("simulated preemption mid-checkpoint")

    monkeypatch.setattr(ckpt_mod, "_write_bytes", exploding_write)
    with pytest.raises(OSError):
        resume_segmented(cfg_f, net,
                         jax.tree.map(lambda a: a[:12], inputs),
                         segment_rounds=4, checkpoint_root=root,
                         async_checkpoint=False)
    monkeypatch.undo()
    assert latest_valid_checkpoint(root) == good  # seg-8 survived

    # resume the FULL run from the surviving checkpoint — still fused
    r2 = resume_segmented(cfg_f, net, inputs, segment_rounds=4,
                          checkpoint_root=root)
    assert not r2.aborted and r2.completed_rounds == rounds
    assert r2.stats["pallas_fused"]
    assert _trees_equal(st_ref, r2.state)


def test_fused_checkpoint_resumes_across_modes(tmp_path):
    """``fused`` is execution-only: a checkpoint written by a fused
    soak resumes under ``fused="off"`` (and vice versa) bit for bit —
    ``checkpoint.config_identity`` excludes the knob, while genuine
    sim-config drift still refuses."""
    rounds = 12
    cfg_f = _cfg(fused="interpret")
    cfg_off = _cfg(fused="off")
    net = NetModel.create(cfg_f.n_nodes, drop_prob=0.02)
    key0 = jr.key(21)
    inputs = make_soak_inputs(cfg_f, jr.key(23), rounds, write_frac=0.25)
    st_ref, _ = jax.jit(
        lambda s, k, i: scale_run_rounds(cfg_off, s, net, k, i)
    )(ScaleSimState.create(cfg_off), key0, inputs)
    st_ref = jax.block_until_ready(st_ref)

    root = str(tmp_path / "soak")
    run_segmented(cfg_f, ScaleSimState.create(cfg_f), net, key0,
                  jax.tree.map(lambda a: a[:6], inputs),
                  segment_rounds=6, checkpoint_root=root)
    res = resume_segmented(cfg_off, net, inputs, segment_rounds=6,
                           checkpoint_root=root)
    assert res.completed_rounds == rounds
    assert _trees_equal(st_ref, res.state)
    # semantic drift is still refused
    drifted = dataclasses.replace(
        _cfg(fused="off"), sync_interval=8).validate()
    with pytest.raises(ValueError, match="differs"):
        resume_segmented(drifted, net, inputs, segment_rounds=6,
                         checkpoint_root=root)


# --- telemetry ------------------------------------------------------------


def test_soak_stats_record_fused_pipeline():
    """SoakResult.stats carries the fused-gate record next to the
    donation/checkpoint facts (what bench smoke and the TPU capture
    surface as one JSON record)."""
    cfg = _cfg(fused="interpret")
    net = NetModel.create(cfg.n_nodes, drop_prob=0.0)
    inputs = make_soak_inputs(cfg, jr.key(31), 8, write_frac=0.2)
    res = run_segmented(cfg, ScaleSimState.create(cfg), net, jr.key(33),
                        inputs, segment_rounds=4)
    assert res.stats["fused_mode"] == "interpret"
    assert res.stats["pallas_fused"] is True
    assert res.stats["fused_interpret"] is True
    off = run_segmented(_cfg(fused="off"),
                        ScaleSimState.create(cfg), net, jr.key(33),
                        inputs, segment_rounds=4)
    assert off.stats["pallas_fused"] is False
    assert off.stats["fused_mode"] == "off"


def test_known_donating_covers_fused_trace():
    """Registry meta-test (ISSUE 10): tracing the donated mesh entry
    point with the fused kernels in the scanned body donates exactly
    the registered leaf set — the megakernel introduces no new
    un-donatable inputs and drops none."""
    from corrosion_tpu.analysis.donation import KNOWN_DONATING
    from corrosion_tpu.parallel import mesh as pmesh

    cfg = _cfg(fused="interpret")
    megakernel.prime_fused(cfg)
    values = dict(
        st=ScaleSimState.create(cfg),
        net=NetModel.create(cfg.n_nodes),
        key=jr.key(0),
        inputs=make_soak_inputs(cfg, jr.key(0), 2, write_frac=0.25),
    )
    traced = pmesh._scale_run.trace(
        cfg, values["st"], values["net"], values["key"], values["inputs"])
    n_st = len(jax.tree.leaves(values["st"]))
    assert KNOWN_DONATING["sharded_scale_run"] == (2,)
    assert set(traced.donate_argnums) == set(range(n_st))
