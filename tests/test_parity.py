"""State parity: host oracle cluster vs TPU sim under identical workload
scripts (SURVEY §7 step 7 — the corro-devcluster comparison with the
``check_bookkeeping`` predicate)."""

import numpy as np
import pytest

from corrosion_tpu.sim.parity import (
    OracleCluster,
    WorkloadScript,
    check_agreement_validity,
    check_bitwise_parity,
    run_sim_script,
)

N_NODES, N_ORIGINS, N_CELLS, ROUNDS = 24, 4, 8, 12


def _run_oracle(script, seed=1):
    oc = OracleCluster(N_NODES, N_ORIGINS, N_CELLS, seed=seed)
    taken = oc.run(script)
    assert taken > 0, "oracle cluster failed to converge"
    return oc


def test_oracle_cluster_converges_alone():
    script = WorkloadScript.random_single_writer(
        N_NODES, N_ORIGINS, N_CELLS, ROUNDS, seed=7)
    oc = _run_oracle(script)
    # spot-check: the last write per cell won
    planes = oc.store_planes()
    last = {}
    for batch in script.writes:
        for node, cell, val in batch:
            last[cell] = val
    for cell, val in last.items():
        assert planes[1][cell] == val


def test_bitwise_parity_single_writer():
    script = WorkloadScript.random_single_writer(
        N_NODES, N_ORIGINS, N_CELLS, ROUNDS, seed=3)
    oc = _run_oracle(script)
    planes, alive, taken = run_sim_script(script, seed=3)
    assert taken > 0, "sim failed to converge"
    problems = check_bitwise_parity(oc, planes, alive)
    assert not problems, "\n".join(problems)


def test_bitwise_parity_with_loss():
    """Parity must survive a lossy network (sync repairs the gaps)."""
    script = WorkloadScript.random_single_writer(
        N_NODES, N_ORIGINS, N_CELLS, ROUNDS, seed=11)
    oc = _run_oracle(script)
    planes, alive, taken = run_sim_script(script, seed=11, drop_prob=0.05)
    assert taken > 0, "sim failed to converge under loss"
    problems = check_bitwise_parity(oc, planes, alive)
    assert not problems, "\n".join(problems)


def test_delete_resurrect_parity_all_engines():
    """Causal-length regime (``doc/crdts.md`` ``cl``): inserts, updates,
    deletes, and resurrects race through the network; every engine —
    Python oracle, TPU sim, and the native C++ cluster — must converge,
    agree across nodes, and settle every row's CL register on the
    script's final causal length (deletes beat concurrent updates,
    resurrects beat stale lifetimes)."""
    n_rows, n_cols = 4, 2
    script = WorkloadScript.random_delete_resurrect(
        N_NODES, N_ORIGINS, n_rows, n_cols, rounds=16, seed=9)
    # final causal length per row per the script
    final_cl = {}
    for batch in script.writes:
        for w in batch:
            node, cell, val = w[0], w[1], w[2]
            if cell % n_cols == 0:
                final_cl[cell] = max(final_cl.get(cell, 0), val)

    oc = OracleCluster(N_NODES, N_ORIGINS, n_rows * n_cols, seed=1)
    assert oc.run(script) > 0, "oracle failed to converge"
    o_planes = oc.store_planes()

    planes, alive, taken_sim = run_sim_script(script, seed=9)
    assert taken_sim > 0, "sim failed to converge"
    problems = check_agreement_validity(script, planes, alive)
    assert not problems, "\n".join(problems)

    ref = int(np.argmax(alive))
    for cell, cl in final_cl.items():
        assert int(o_planes[1][cell]) == cl, f"oracle row cl at {cell}"
        assert int(planes[1][ref][cell]) == cl, f"sim row cl at {cell}"
        # the CL register's lifetime stamp equals its value by construction
        assert int(planes[4][ref][cell]) == cl

    try:
        from corrosion_tpu import native
    except ImportError:
        native = None
    if native is not None and native.available():
        nat = native.NativeCluster(N_NODES, N_ORIGINS, n_rows * n_cols, seed=1)
        assert nat.run(script) > 0, "native cluster failed to converge"
        n_planes = nat.store_planes()
        for cell, cl in final_cl.items():
            assert int(n_planes[1][cell]) == cl, f"native row cl at {cell}"


def test_conflict_parity_agreement_and_validity():
    script = WorkloadScript.random_conflicting(
        N_NODES, N_ORIGINS, N_CELLS, ROUNDS, seed=5, hot_cells=2)
    # oracle converges on its own trajectory
    oc = _run_oracle(script)
    # sim converges on its own trajectory; agreement + validity must hold
    planes, alive, taken = run_sim_script(script, seed=5)
    assert taken > 0
    problems = check_agreement_validity(script, planes, alive)
    assert not problems, "\n".join(problems)
    # both systems settled on SOME valid winner for the hot cells
    o_planes = oc.store_planes()
    written = script.written_values()
    for cell in written:
        assert int(o_planes[1][cell]) in written[cell]
