"""State parity: host oracle cluster vs TPU sim under identical workload
scripts (SURVEY §7 step 7 — the corro-devcluster comparison with the
``check_bookkeeping`` predicate)."""

import numpy as np
import pytest

from corrosion_tpu.sim.parity import (
    OracleCluster,
    WorkloadScript,
    check_agreement_validity,
    check_bitwise_parity,
    run_sim_script,
)

N_NODES, N_ORIGINS, N_CELLS, ROUNDS = 24, 4, 8, 12


def _run_oracle(script, seed=1):
    oc = OracleCluster(N_NODES, N_ORIGINS, N_CELLS, seed=seed)
    taken = oc.run(script)
    assert taken > 0, "oracle cluster failed to converge"
    return oc


def test_oracle_cluster_converges_alone():
    script = WorkloadScript.random_single_writer(
        N_NODES, N_ORIGINS, N_CELLS, ROUNDS, seed=7)
    oc = _run_oracle(script)
    # spot-check: the last write per cell won
    planes = oc.store_planes()
    last = {}
    for batch in script.writes:
        for node, cell, val in batch:
            last[cell] = val
    for cell, val in last.items():
        assert planes[1][cell] == val


def test_bitwise_parity_single_writer():
    script = WorkloadScript.random_single_writer(
        N_NODES, N_ORIGINS, N_CELLS, ROUNDS, seed=3)
    oc = _run_oracle(script)
    planes, alive, taken = run_sim_script(script, seed=3)
    assert taken > 0, "sim failed to converge"
    problems = check_bitwise_parity(oc, planes, alive)
    assert not problems, "\n".join(problems)


def test_bitwise_parity_with_loss():
    """Parity must survive a lossy network (sync repairs the gaps)."""
    script = WorkloadScript.random_single_writer(
        N_NODES, N_ORIGINS, N_CELLS, ROUNDS, seed=11)
    oc = _run_oracle(script)
    planes, alive, taken = run_sim_script(script, seed=11, drop_prob=0.05)
    assert taken > 0, "sim failed to converge under loss"
    problems = check_bitwise_parity(oc, planes, alive)
    assert not problems, "\n".join(problems)


def test_conflict_parity_agreement_and_validity():
    script = WorkloadScript.random_conflicting(
        N_NODES, N_ORIGINS, N_CELLS, ROUNDS, seed=5, hot_cells=2)
    # oracle converges on its own trajectory
    oc = _run_oracle(script)
    # sim converges on its own trajectory; agreement + validity must hold
    planes, alive, taken = run_sim_script(script, seed=5)
    assert taken > 0
    problems = check_agreement_validity(script, planes, alive)
    assert not problems, "\n".join(problems)
    # both systems settled on SOME valid winner for the hot cells
    o_planes = oc.store_planes()
    written = script.written_values()
    for cell in written:
        assert int(o_planes[1][cell]) in written[cell]
