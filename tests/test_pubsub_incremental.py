"""Incremental subscription matching (VERDICT r4 #6): matchers are fed
the round's applied (table, pk) deltas instead of re-running their full
query every round — the analog of the reference's candidate-PK diffing
per applied changeset (``pubsub.rs:527-1100``, hooked at
``util.rs:1036-1037``). The pinned property: matcher query executions
stay FLAT while the replica is quiet, and scale with the delta (not the
result set) when it isn't."""

import pytest

from corrosion_tpu.agent import Agent
from corrosion_tpu.config import Config
from corrosion_tpu.db import Database
from corrosion_tpu.pubsub import DELETE, INSERT, UPSERT, SubsManager

SCHEMA = """
CREATE TABLE items (
    pk INTEGER PRIMARY KEY,
    v INTEGER,
    grp INTEGER
);
CREATE TABLE grps (
    gid INTEGER PRIMARY KEY,
    label TEXT
);
"""

N_ROWS = 64


def inc_config():
    cfg = Config()
    cfg.sim.mode = "scale"
    cfg.sim.n_nodes = 16
    cfg.sim.m_slots = 8
    cfg.sim.n_origins = 4
    cfg.sim.n_rows = N_ROWS
    cfg.sim.n_cols = 4
    cfg.perf.sync_interval = 4
    cfg.gossip.drop_prob = 0.0
    return cfg


@pytest.fixture(scope="module")
def rig():
    with Agent(inc_config()) as agent:
        agent.wait_rounds(5, timeout=120)
        db = Database(agent)
        db.apply_schema_sql(SCHEMA)
        # a "large" table relative to the delta sizes below
        stmts = [
            (f"INSERT INTO items (pk, v, grp) VALUES ({i}, {i * 10}, "
             f"{i % 3})",)
            for i in range(40)
        ]
        stmts += [
            (f"INSERT INTO grps (gid, label) VALUES ({g}, 'g{g}')",)
            for g in range(3)
        ]
        db.execute(0, stmts)
        agent.wait_rounds(2, timeout=60)
        yield agent, db


def _settle(agent, mgr, m):
    """Let the matcher see one post-subscribe round (its first poll is a
    full re-query: the delta tracker has no baseline yet)."""
    agent.wait_rounds(2, timeout=60)


def test_quiet_rounds_run_no_queries(rig):
    agent, db = rig
    mgr = SubsManager(db)
    try:
        m, created = mgr.subscribe(0, "SELECT pk, v FROM items")
        assert created and len(m._state) == 40
        _settle(agent, mgr, m)
        q0 = m.n_queries
        agent.wait_rounds(6, timeout=60)
        # no applied deltas -> zero query executions, full or filtered
        assert m.n_queries == q0
    finally:
        mgr.close()


def test_small_delta_runs_filtered_queries_only(rig):
    agent, db = rig
    mgr = SubsManager(db)
    try:
        m, _ = mgr.subscribe(0, "SELECT pk, v FROM items")
        _settle(agent, mgr, m)
        q0 = m.n_queries
        db.execute(0, [("UPDATE items SET v = 999 WHERE pk = 7",)])
        agent.wait_rounds(3, timeout=60)
        # the write lands in one round: exactly one filtered re-query
        # (plus nothing on the quiet rounds after) — NOT one per round
        assert 1 <= m.n_queries - q0 <= 2
        assert m._state[7] == (7, 999)
        kinds = [rec[1] for rec in m._log]
        assert UPSERT in kinds
    finally:
        mgr.close()


def test_insert_and_delete_via_delta(rig):
    agent, db = rig
    mgr = SubsManager(db)
    try:
        m, _ = mgr.subscribe(0, "SELECT pk, v FROM items WHERE v < 100000")
        _settle(agent, mgr, m)
        db.execute(0, [("INSERT INTO items (pk, v, grp) "
                        "VALUES (51, 510, 0)",)])
        agent.wait_rounds(3, timeout=60)
        assert m._state.get(51) == (51, 510)
        assert (m._log[-1][1], m._log[-1][2]) == (INSERT, 51)
        db.execute(0, [("DELETE FROM items WHERE pk = 51",)])
        agent.wait_rounds(3, timeout=60)
        assert 51 not in m._state
        assert (m._log[-1][1], m._log[-1][2]) == (DELETE, 51)
    finally:
        mgr.close()


def test_join_matcher_incremental_both_sides(rig):
    agent, db = rig
    mgr = SubsManager(db)
    try:
        m, _ = mgr.subscribe(
            0, "SELECT i.pk, i.v, g.label FROM items i "
               "JOIN grps g ON i.grp = g.gid")
        _settle(agent, mgr, m)
        q0 = m.n_queries
        # change the RIGHT side: one grps row -> events for its items
        db.execute(0, [("UPDATE grps SET label = 'zzz' WHERE gid = 1",)])
        agent.wait_rounds(3, timeout=60)
        assert m.n_queries - q0 <= 2  # one filtered query, not full
        changed = [rec for rec in m._log if rec[1] == UPSERT]
        assert changed and all(row[2] == "zzz" for _, _, _, row in changed)
    finally:
        mgr.close()


def test_left_join_matcher_full_polls_and_stays_correct(rig):
    # code review r5: LEFT JOIN null-extension flips (pk, None) keys the
    # candidate filter cannot reach -> incremental must be disabled
    agent, db = rig
    mgr = SubsManager(db)
    try:
        db.execute(0, [("INSERT INTO items (pk, v, grp) "
                        "VALUES (60, 600, 9)",)])  # grp 9 has no grps row
        agent.wait_rounds(2, timeout=60)
        m, _ = mgr.subscribe(
            0, "SELECT i.pk, g.label FROM items i "
               "LEFT JOIN grps g ON i.grp = g.gid")
        assert not m._can_increment
        assert (60, None) in m._state
        db.execute(0, [("INSERT INTO grps (gid, label) "
                        "VALUES (9, 'nine')",)])
        agent.wait_rounds(3, timeout=60)
        # the null-extended key was replaced, not duplicated
        assert (60, 9) in m._state and (60, None) not in m._state
    finally:
        mgr.close()


def test_subquery_table_change_falls_back_to_full(rig):
    agent, db = rig
    mgr = SubsManager(db)
    try:
        m, _ = mgr.subscribe(
            0, "SELECT pk FROM items WHERE grp IN "
               "(SELECT gid FROM grps WHERE label != 'nope')")
        assert "grps" in m._subq_tables
        _settle(agent, mgr, m)
        q0 = m.n_queries
        # a change in the subquery table cannot be candidate-filtered:
        # the matcher must fall back to a full (correct) re-query
        db.execute(0, [("UPDATE grps SET label = 'xx' WHERE gid = 2",)])
        agent.wait_rounds(3, timeout=60)
        assert m.n_queries > q0
    finally:
        mgr.close()


def test_shared_tracker_serves_both_managers_once(rig):
    """SubsManager and UpdatesManager share one DeltaTracker through
    Database.delta_tracker(); the per-(node, round) cache means the
    second consumer reuses the first's computation AND both still see
    the same candidates (an earlier design advanced the baseline on
    first read, handing the second manager an empty delta)."""
    agent, db = rig
    from corrosion_tpu.pubsub import SubsManager, UpdatesManager

    mgr = SubsManager(db)
    upd = UpdatesManager(db, node=0)
    assert mgr._tracker is upd._tracker  # one tracker per Database
    try:
        m, _ = mgr.subscribe(0, "SELECT pk, v FROM items")
        q_upd = upd.attach("items")
        agent.wait_rounds(2, timeout=60)
        calls = {"n": 0}
        orig = type(mgr._tracker).changed

        def spy(self, node):
            calls["n"] += 1
            return orig(self, node)

        type(mgr._tracker).changed = spy
        try:
            db.execute(0, [("UPDATE items SET v = 777 WHERE pk = 3",)])
            agent.wait_rounds(3, timeout=60)
        finally:
            type(mgr._tracker).changed = orig
        # both consumers observed the change...
        assert m._state[3] == (3, 777)
        events = []
        while not q_upd.empty():
            events.append(q_upd.get_nowait())
        assert any(ev[0] == "notify" and ev[1][1] == 3 for ev in events)
        # ...and the tracker was consulted by both every round (cache
        # hit for the second) — 2 calls per round, all served
        assert calls["n"] >= 2
    finally:
        mgr.close()
        db.agent.remove_round_listener(upd._on_round)


def test_updates_feed_incremental_insert_update_delete(rig):
    """The updates feed re-reads only candidate rows (round 5): INSERT,
    UPSERT, and DELETE all surface through the partial path."""
    agent, db = rig
    from corrosion_tpu.pubsub import UpdatesManager

    upd = UpdatesManager(db, node=0)
    try:
        q = upd.attach("items")
        agent.wait_rounds(2, timeout=60)
        db.execute(0, [("INSERT INTO items (pk, v, grp) "
                        "VALUES (55, 1, 0)",)])
        agent.wait_rounds(3, timeout=60)
        db.execute(0, [("UPDATE items SET v = 2 WHERE pk = 55",)])
        agent.wait_rounds(3, timeout=60)
        db.execute(0, [("DELETE FROM items WHERE pk = 55",)])
        agent.wait_rounds(3, timeout=60)
        kinds = []
        while not q.empty():
            ev = q.get_nowait()
            if ev[0] == "notify" and ev[1][1] == 55:
                kinds.append(ev[1][0])
        assert kinds == ["insert", "update", "delete"]
    finally:
        db.agent.remove_round_listener(upd._on_round)
