"""Partial-changeset buffering: multi-cell transactions are atomic.

The reference buffers chunked changesets per (version, seq-range) and
only applies a version once its whole range is present
(``process_incomplete_version`` -> ``process_fully_buffered_changes``,
``crates/corro-agent/src/agent/util.rs:1061-1194,546-696``), which is
what keeps a multi-statement transaction from being observed torn on
remote nodes. These tests drive the array analogs directly and through
the full sim round."""

import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from corrosion_tpu.ops.partials import (
    Partials,
    complete_mask,
    drop_stale_partials,
    free_slots,
    ingest_partials,
)


def _msgs(rows):
    """rows: list (per node) of lists of (origin, dbv, seq, nseq, cell,
    ver, val, site, clp); pad to rectangular [N, M] arrays + live mask."""
    m = max(len(r) for r in rows)
    n = len(rows)
    fields = [np.zeros((n, m), np.int32) for _ in range(9)]
    live = np.zeros((n, m), bool)
    for i, r in enumerate(rows):
        for j, msg in enumerate(r):
            live[i, j] = True
            for f, v in zip(fields, msg):
                f[i, j] = v
    return jnp.asarray(live), tuple(jnp.asarray(f) for f in fields)


def test_buffer_until_complete_then_apply():
    par = Partials.create(2, 4, 4)
    # node 0 gets seqs 0,1 of a 3-cell version — incomplete
    live, f = _msgs([
        [(0, 1, 0, 3, 10, 1, 100, 0, 0), (0, 1, 1, 3, 11, 1, 101, 0, 0)],
        [],
    ])
    par, fresh = ingest_partials(par, live, *f)
    assert np.asarray(fresh).tolist() == [[True, True], [False, False]]
    assert not np.asarray(complete_mask(par)).any()
    # the final seq closes the range
    live, f = _msgs([[(0, 1, 2, 3, 12, 1, 102, 0, 0)], []])
    par, fresh = ingest_partials(par, live, *f)
    assert bool(np.asarray(fresh)[0, 0])
    full = np.asarray(complete_mask(par))
    assert full[0].sum() == 1 and full[1].sum() == 0
    slot = int(np.argmax(full[0]))
    assert int(par.nseq[0, slot]) == 3 and int(par.mask[0, slot]) == 0b111
    cells = sorted(np.asarray(par.cell)[0, slot, :3].tolist())
    assert cells == [10, 11, 12]
    par = free_slots(par, jnp.asarray(full))
    assert not np.asarray(complete_mask(par)).any()


def test_duplicate_seqs_not_fresh():
    par = Partials.create(1, 4, 4)
    live, f = _msgs([[(0, 1, 0, 2, 10, 1, 100, 0, 0),
                      (0, 1, 0, 2, 10, 1, 100, 0, 0)]])  # dup in one batch
    par, fresh = ingest_partials(par, live, *f)
    assert np.asarray(fresh).tolist() == [[True, False]]
    live, f = _msgs([[(0, 1, 0, 2, 10, 1, 100, 0, 0)]])  # dup across rounds
    par, fresh = ingest_partials(par, live, *f)
    assert not bool(np.asarray(fresh)[0, 0])
    assert int(par.mask[0, int(np.argmax(np.asarray(par.origin[0]) >= 0))]) == 0b1


def test_interleaved_versions_share_no_slot():
    par = Partials.create(1, 4, 4)
    live, f = _msgs([[
        (0, 1, 0, 2, 10, 1, 100, 0, 0),
        (1, 7, 0, 2, 20, 1, 200, 1, 0),
        (0, 1, 1, 2, 11, 1, 101, 0, 0),
        (1, 7, 1, 2, 21, 1, 201, 1, 0),
    ]])
    par, fresh = ingest_partials(par, live, *f)
    assert np.asarray(fresh).all()
    full = np.asarray(complete_mask(par))
    assert full.sum() == 2  # both versions complete, in distinct slots
    origins = sorted(np.asarray(par.origin)[0][full[0]].tolist())
    assert origins == [0, 1]


def test_slot_overflow_drops():
    par = Partials.create(1, 2, 4)  # only 2 slots
    live, f = _msgs([[
        (0, 1, 0, 2, 10, 1, 1, 0, 0),
        (0, 2, 0, 2, 11, 1, 1, 0, 0),
        (0, 3, 0, 2, 12, 1, 1, 0, 0),  # no slot left -> dropped
    ]])
    par, fresh = ingest_partials(par, live, *f)
    assert np.asarray(fresh).tolist() == [[True, True, False]]


def test_drop_stale_partials_frees_synced_versions():
    from corrosion_tpu.ops.versions import Book

    par = Partials.create(1, 4, 4)
    live, f = _msgs([[(0, 5, 0, 2, 10, 1, 1, 0, 0)]])
    par, _ = ingest_partials(par, live, *f)
    book = Book.create(1, 2, 32)
    book = book._replace(
        head=jnp.asarray([[5, 0]], jnp.int32)  # origin 0's head reached 5
    )
    par = drop_stale_partials(par, book)
    assert not (np.asarray(par.origin) >= 0).any()


def test_transaction_never_observed_torn_under_loss():
    """A 4-statement transaction must never be visible partially on any
    remote node, at ANY round, under 5% packet drop (VERDICT #3's done
    criterion; atomicity per ``process_fully_buffered_changes``)."""
    import jax

    from corrosion_tpu.sim.scale_step import (
        ScaleRoundInput,
        ScaleSimState,
        scale_crdt_metrics,
        scale_sim_config,
        scale_sim_step,
    )
    from corrosion_tpu.sim.transport import NetModel

    n, k = 24, 4
    cfg = scale_sim_config(n, n_origins=4, n_rows=4, n_cols=4,
                           tx_max_cells=k, sync_interval=4)
    st = ScaleSimState.create(cfg)
    net = NetModel.create(n, drop_prob=0.05)
    step = jax.jit(lambda s, key, i: scale_sim_step(cfg, s, net, key, i))
    key = jr.key(7)
    quiet = ScaleRoundInput.quiet(cfg)

    # node 0 commits a 4-cell transaction on cells 1,5,9,13 (written by
    # nothing else); fanout + loss scatter the chunks across rounds
    tx_cells = np.array([1, 5, 9, 13], np.int32)
    inp = quiet._replace(
        tx_mask=jnp.asarray(np.eye(1, n, 0, dtype=bool)[0]),
        tx_len=jnp.full(n, k, jnp.int32),
        tx_cell=jnp.broadcast_to(jnp.asarray(tx_cells), (n, k)),
        tx_val=jnp.broadcast_to(jnp.asarray([11, 22, 33, 44], jnp.int32), (n, k)),
    )
    key, sub = jr.split(key)
    st, _ = step(st, sub, inp)
    converged_at = None
    for r in range(200):
        vers = np.asarray(st.crdt.store[0])[:, tx_cells]  # [N, 4]
        present = (vers > 0).sum(axis=1)
        torn = np.nonzero((present > 0) & (present < k))[0]
        assert torn.size == 0, (
            f"round {r}: nodes {torn.tolist()} observe a torn transaction "
            f"(cells present: {present[torn].tolist()})"
        )
        m = scale_crdt_metrics(cfg, st)
        if bool(m["converged"]) and present.min() == k:
            converged_at = r
            break
        key, sub = jr.split(key)
        st, _ = step(st, sub, quiet)
    assert converged_at is not None, "transaction never converged"
    # every node holds the full transaction with one shared db_version
    dbvs = np.asarray(st.crdt.store[3])[:, tx_cells]
    assert (dbvs == dbvs[0, 0]).all()
    vals = np.asarray(st.crdt.store[1])[:, tx_cells]
    assert (vals == np.array([11, 22, 33, 44])).all()


def test_transaction_parity_oracle_vs_sim():
    """Chunked-changeset regime end-to-end: random multi-cell
    transactions, oracle and sim converge to bitwise-identical stores."""
    from corrosion_tpu.sim.parity import (
        OracleCluster,
        WorkloadScript,
        check_bitwise_parity,
        run_sim_script,
    )

    script = WorkloadScript.random_transactions(
        24, 4, 32, rounds=10, tx_cells=4, seed=3
    )
    oc = OracleCluster(24, 4, 32, seed=1)
    assert oc.run(script) > 0, "oracle failed to converge"
    planes, alive, taken = run_sim_script(script, seed=3)
    assert taken > 0, "sim failed to converge"
    problems = check_bitwise_parity(oc, planes, alive)
    assert not problems, "\n".join(problems)


def test_transaction_parity_native_engine():
    """The C++ cluster engine buffers chunked versions the same way:
    bitwise-identical converged stores on the transaction workload."""
    from corrosion_tpu import native
    from corrosion_tpu.sim.parity import OracleCluster, WorkloadScript

    if not native.available():
        pytest.skip("native library unavailable")
    script = WorkloadScript.random_transactions(
        24, 4, 32, rounds=10, tx_cells=4, seed=3
    )
    nat = native.NativeCluster(24, 4, 32, seed=1)
    assert nat.run(script) > 0
    oc = OracleCluster(24, 4, 32, seed=1)
    assert oc.run(script) > 0
    for name, op, npn in zip(("ver", "val", "site", "dbv", "clp"),
                             oc.store_planes(), nat.store_planes()):
        assert np.array_equal(op, npn), f"{name} plane diverged"


def test_transaction_parity_under_drop():
    """Same regime with 5% loss: convergence via re-broadcast + sync
    repair, still bitwise-identical to the loss-free oracle."""
    from corrosion_tpu.sim.parity import (
        OracleCluster,
        WorkloadScript,
        check_bitwise_parity,
        run_sim_script,
    )

    script = WorkloadScript.random_transactions(
        16, 4, 24, rounds=8, tx_cells=3, seed=11
    )
    oc = OracleCluster(16, 4, 24, seed=2)
    assert oc.run(script) > 0
    planes, alive, taken = run_sim_script(script, seed=11, drop_prob=0.05)
    assert taken > 0, "sim failed to converge under drop"
    problems = check_bitwise_parity(oc, planes, alive)
    assert not problems, "\n".join(problems)


def test_unowned_chunked_fragments_apply_but_do_not_rebroadcast():
    """Round-4 circulation gate for chunked versions: fragments from an
    actor whose hash slot is held by a DIFFERENT active actor still
    apply (after completion) but must not re-enqueue — the freed
    partial slot forgets them, so re-enqueueing with a fresh budget
    would circulate them forever (review r4)."""
    import jax.numpy as jnp

    from corrosion_tpu.sim.broadcast import (
        NO_Q,
        CrdtState,
        ingest_changes,
        local_write,
    )
    from corrosion_tpu.sim.config import SimConfig

    cfg = SimConfig(
        n_nodes=4, n_origins=2, any_writer=True, org_keep_rounds=1000,
        n_rows=4, n_cols=2, tx_max_cells=2, partial_slots=4,
        bcast_queue=8,
    ).validate()
    cst = CrdtState.create(cfg)
    # keep slot 0 of every node ACTIVE for actor 0 so actor 2 (2 % 2 ==
    # 0, same class) can never claim it: a write from node 0 this round
    w = jnp.asarray([True, False, False, False])
    cst = cst._replace(now=cst.now + 1)
    cst = local_write(cfg, cst, w, jnp.zeros(4, jnp.int32),
                      jnp.full(4, 7, jnp.int32))
    queued_before = int(jnp.sum(cst.q_origin != NO_Q))

    # two fragments of actor 2's chunked version (dbv 1, seq 0/1 of 2)
    # delivered to node 1 — origin 2 hashes to the (actively held) slot 0
    live = jnp.zeros((4, 2), bool).at[1, :].set(True)
    f = lambda a, b: jnp.zeros((4, 2), jnp.int32).at[1, 0].set(a).at[1, 1].set(b)  # noqa: E731
    cst2, info = ingest_changes(
        cfg, cst, live,
        m_origin=f(2, 2), m_dbv=f(1, 1), m_cell=f(2, 3), m_ver=f(1, 1),
        m_val=f(41, 42), m_site=f(2, 2), m_clp=f(1, 1),
        m_seq=f(0, 1), m_nseq=f(2, 2), m_ts=f(0, 0),
    )
    # the completed transaction applied to node 1's store...
    assert int(cst2.store[1][1, 2]) == 41
    assert int(cst2.store[1][1, 3]) == 42
    # ...but nothing new entered node 1's broadcast queue
    assert int(jnp.sum(cst2.q_origin[1] != NO_Q)) == 0
    # and the bookkeeping slot still tracks actor 0
    assert int(cst2.book.org_id[1, 0]) == 0

    # control: the same fragments from the OWNED actor 0 do re-enqueue
    cst3, _ = ingest_changes(
        cfg, cst, live,
        m_origin=f(0, 0), m_dbv=f(1, 1), m_cell=f(2, 3), m_ver=f(1, 1),
        m_val=f(41, 42), m_site=f(0, 0), m_clp=f(1, 1),
        m_seq=f(0, 1), m_nseq=f(2, 2), m_ts=f(0, 0),
    )
    assert int(jnp.sum(cst3.q_origin[1] != NO_Q)) == 2
