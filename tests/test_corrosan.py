"""corrosan: runtime sanitizer + leak gate (ISSUE 8).

Three layers of guarantees pinned here:

1. **fixture verdicts** — every seeded race/leak fixture is detected
   and every clean twin passes (no false negatives on fixtures, no
   false positives on the fixed shapes), including the PR-5 pubsub
   unsubscribe-vs-persist regression pair against the real
   ``SubsManager``;
2. **witnessed ⊆ static** — a sanitized battery driving the real
   threaded stack (agent round loop, subscriptions, updates feeds,
   HTTP API, persist worker) runs sanitizer-clean, actually witnesses
   the static graph's cross-class edge, and every named witnessed edge
   is inside corrolint's static lock-order graph ∪ the reasoned
   allowlist — the two models cannot silently drift;
3. **plumbing** — locks born at registered creation sites get their
   static names (otherwise the subset check would be vacuously green),
   spawned threads carry the ``corro-`` prefix, the report artifact
   has its schema, and the allowlist can never go stale against the
   static graph.
"""

import json
import os
import threading

import pytest

from corrosion_tpu.analysis.sanitizer import (
    KINDS,
    run_all_fixtures,
    run_fixture,
    sanitized,
    static_lock_graph,
)
from corrosion_tpu.analysis.sanitizer.allowlist import (
    ALLOWED_ATTR_RACES,
    ALLOWED_LOCK_EDGES,
    ALLOWED_LEAK_PREFIXES,
)
from corrosion_tpu.config import Config


def small_config():
    cfg = Config()
    cfg.sim.n_nodes = 16
    cfg.sim.m_slots = 8
    cfg.sim.n_origins = 4
    cfg.sim.n_rows = 8
    cfg.sim.n_cols = 2
    cfg.gossip.drop_prob = 0.0
    return cfg


# --- 1. fixture verdicts ---------------------------------------------------

def test_seeded_fixtures_detected():
    """Every non-jax seeded fixture: bugs flagged, clean twins pass."""
    results = run_all_fixtures([
        "race-unlocked", "race-locked", "lock-inversion",
        "lock-nested-clean", "thread-leak", "fd-leak", "executor-leak",
    ])
    bad = [r for r in results if not r.ok]
    assert not bad, "fixture verdict mismatches:\n" + "\n".join(
        f"{r.name}: expected {r.expect or ('clean',)}, got "
        f"{r.found or ('clean',)}\n  " + "\n  ".join(r.details)
        for r in bad
    )


def test_pubsub_unsub_vs_persist_regression():
    """The PR-5 race, re-provoked under corrosan with a forced
    interleaving: the reverted worker must be flagged (true-positive
    guard for the whole detector), the shipped worker must pass."""
    reverted = run_fixture("pubsub-resurrect-reverted")
    assert reverted.ok, (
        "sanitizer MISSED the reverted unsubscribe-vs-persist race "
        f"(found only: {reverted.found})"
    )
    assert "fs-resurrect" in reverted.found
    fixed = run_fixture("pubsub-resurrect-fixed")
    assert fixed.ok, (
        "sanitizer flagged the FIXED persist worker:\n"
        + "\n".join(fixed.details)
    )


# --- 2. witnessed ⊆ static -------------------------------------------------

def test_sanitized_battery_clean_and_witness_subset_of_static(tmp_path):
    """Drive the real threaded stack under one sanitized window: the
    run must be sanitizer-clean, must actually witness the static
    graph's SubsManager -> Matcher edge (proof the pairing observes
    something), and every named witnessed edge must be in the static
    graph ∪ ALLOWED_LOCK_EDGES."""
    import urllib.request

    with sanitized() as san:
        from corrosion_tpu.agent import Agent
        from corrosion_tpu.api import ApiServer
        from corrosion_tpu.db import Database
        from corrosion_tpu.pubsub import SubsManager, UpdatesManager
        from corrosion_tpu.resilience import Supervisor

        sup = Supervisor(deadline_seconds=300.0)
        agent = Agent(small_config()).start(supervisor=sup)
        try:
            db = Database(agent)
            db.apply_schema_sql(
                "CREATE TABLE t (pk INTEGER PRIMARY KEY, v INTEGER);"
            )
            mgr = SubsManager(db, persist_dir=str(tmp_path / "subs"))
            matcher, _ = mgr.subscribe(0, "SELECT pk, v FROM t")
            live_q = matcher.attach()
            upd = UpdatesManager(db)
            feed_q = upd.attach("t")
            api = ApiServer(db, subs=mgr, updates=upd).start()
            for i in range(4):
                db.execute(
                    0, [(f"INSERT INTO t (pk, v) VALUES ({i}, {i * 7})",)]
                )
            assert agent.wait_rounds(3, timeout=300)
            with urllib.request.urlopen(
                f"http://{api.addr}:{api.port}/v1/health", timeout=30
            ) as resp:
                assert json.load(resp)["round"] >= 0
            mgr.unsubscribe(matcher.id)
            assert agent.wait_rounds(2, timeout=300)
            upd.detach("t", feed_q)
            api.stop()
            mgr.close()
        finally:
            agent.shutdown()

    findings = san.gate()
    assert not findings, (
        "sanitized battery is not clean:\n"
        + "\n".join(f.render() for f in findings)
    )
    named = san.witness.named_edges()
    assert (
        "corrosion_tpu.pubsub.SubsManager._mu",
        "corrosion_tpu.pubsub.Matcher._mu",
    ) in named, f"the static cross-class edge was never witnessed: {named}"
    static_names = static_lock_graph().edge_names()
    extra = named - static_names - set(ALLOWED_LOCK_EDGES)
    assert not extra, (
        f"witnessed lock edges outside static graph + allowlist: {extra}"
    )
    # the battery exercised real spawns, and all of them wound down
    assert san.leaks.spawned_count() > 10


# --- 3. plumbing -----------------------------------------------------------

def test_runtime_locks_get_static_names():
    """Locks born at registered creation sites must resolve to their
    static nodes — if this breaks, the subset check silently degrades
    to comparing nothing."""
    with sanitized():
        from corrosion_tpu.resilience.supervisor import Supervisor
        from corrosion_tpu.utils.locks import LockRegistry

        sup = Supervisor()
        registry = LockRegistry()
        tracked = registry.lock("probe")
        anon = threading.Lock()
    sup_node = getattr(sup._mu, "san_node", None)
    assert sup_node is not None and sup_node.name == (
        "corrosion_tpu.resilience.supervisor.Supervisor._mu"
    )
    reg_node = getattr(registry._mu, "san_node", None)
    assert reg_node is not None and reg_node.name == (
        "corrosion_tpu.utils.locks.LockRegistry._mu"
    )
    inner = getattr(tracked._lock, "san_node", None)
    assert inner is not None and inner.name == (
        "corrosion_tpu.utils.locks.TrackedLock._lock"
    )
    assert getattr(anon, "san_node", None) is None


def test_allowlists_cannot_go_stale():
    """Every allow-listed lock node must still EXIST in the static
    graph (a renamed/moved lock must invalidate its entry), and every
    entry of every allowlist must carry a reason."""
    nodes = {n.name for n in static_lock_graph().creation_sites}
    for (frm, to), reason in ALLOWED_LOCK_EDGES.items():
        assert frm in nodes, f"allowlisted lock {frm} no longer exists"
        assert to in nodes, f"allowlisted lock {to} no longer exists"
        assert reason.strip()
    for table in (ALLOWED_ATTR_RACES, ALLOWED_LEAK_PREFIXES):
        for key, reason in table.items():
            assert str(reason).strip(), f"{key} has no reason"


def test_spawns_carry_corro_prefix():
    """ISSUE 8 satellite: the host plane's background threads are
    attributable by name in sanitizer and leak reports."""
    from corrosion_tpu.agent import Agent
    from corrosion_tpu.api import ApiServer
    from corrosion_tpu.db import Database

    agent = Agent(small_config()).start()
    try:
        db = Database(agent)
        api = ApiServer(db).start()
        try:
            names = {t.name for t in threading.enumerate()}
            assert "corro-agent-round-loop" in names
            assert "corro-api-http" in names
        finally:
            api.stop()
    finally:
        agent.shutdown()


def test_report_artifact_schema(tmp_path):
    """The CLI's fixture replay writes the shared report artifact with
    the documented shape (docs/corrosan.md JSON schema section)."""
    from corrosion_tpu.analysis.sanitizer.__main__ import main as san_main
    from corrosion_tpu.analysis.sanitizer.report import load_section

    out = str(tmp_path / "san.json")
    rc = san_main(["race-unlocked", "race-locked", "--output-json", out,
                   "--format", "json"])
    assert rc == 0
    with open(out) as f:
        doc = json.load(f)
    assert doc["tool"] == "corrosan"
    section = doc["sections"]["fixtures"]
    assert load_section(out, "fixtures") == section
    assert load_section(out, "pytest") is None
    assert section["ok"] is True
    names = {r["name"] for r in section["results"]}
    assert names == {"race-unlocked", "race-locked"}
    for r in section["results"]:
        assert set(r) >= {"name", "expect", "found", "ok", "details"}


def test_finding_kinds_documented():
    """Every corrosan finding kind appears in docs/corrosan.md — the
    human catalog cannot drift from the code (the corrolint doc
    meta-test pattern)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    doc_path = os.path.join(repo, "docs", "corrosan.md")
    if not os.path.exists(doc_path):
        pytest.skip("docs/ not shipped in this environment")
    with open(doc_path) as f:
        doc = f.read()
    missing = [kind for kind in KINDS if kind not in doc]
    assert not missing, f"kinds missing from docs/corrosan.md: {missing}"
    for fixture_name in ("pubsub-resurrect-reverted", "race-unlocked"):
        assert fixture_name in doc
