"""Host agent: write at A, poll B for convergence — the in-process analog
of the reference's ``insert_rows_and_gossip`` integration tests
(``crates/corro-agent/src/agent/tests.rs:52``)."""

import numpy as np
import pytest

from corrosion_tpu.agent import Agent
from corrosion_tpu.config import Config


def small_config(**sim_over):
    cfg = Config()
    cfg.sim.mode = sim_over.pop("mode", "scale")
    cfg.sim.n_nodes = 32
    cfg.sim.m_slots = 16
    cfg.sim.n_origins = 4
    cfg.sim.n_rows = 4
    cfg.sim.n_cols = 2
    cfg.perf.sync_interval = 4
    cfg.gossip.drop_prob = 0.01
    for k, v in sim_over.items():
        setattr(cfg.sim, k, v)
    return cfg


@pytest.fixture(scope="module")
def agent():
    with Agent(small_config()) as a:
        # warm membership before the tests write
        assert a.wait_rounds(30, timeout=120)
        yield a


def test_write_and_gossip(agent):
    agent.write(node=0, cell=3, value=777)
    deadline = 400
    reader = agent.n_nodes - 1
    while deadline:
        if agent.read_cell(reader, 3)["value"] == 777:
            break
        agent.wait_rounds(5, timeout=60)
        deadline -= 5
    assert agent.read_cell(reader, 3)["value"] == 777
    assert agent.read_cell(reader, 3)["site"] == 0


def test_members_and_sync_state(agent):
    ms = agent.members()
    assert len(ms) == agent.n_nodes
    assert all(m["state"] == "Alive" for m in ms)
    ss = agent.sync_state(1)
    assert "heads" in ss and ss["actor_id"] == 1


def test_kill_revive_and_convergence(agent):
    victim = agent.n_nodes - 2
    agent.kill_node(victim)
    assert agent.wait_rounds(2, timeout=60)
    assert not bool(agent.snapshot()["alive"][victim])
    agent.revive_node(victim)
    assert agent.wait_rounds(2, timeout=60)
    assert bool(agent.snapshot()["alive"][victim])
    # drain until converged (bounded)
    for _ in range(100):
        if agent.converged():
            break
        agent.wait_rounds(5, timeout=60)
    assert agent.converged()


def test_writer_validation(agent):
    with pytest.raises(ValueError):
        agent.write(node=agent.n_nodes - 1, cell=0, value=1)
    with pytest.raises(ValueError):
        agent.write(node=0, cell=10_000, value=1)
