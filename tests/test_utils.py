"""Host utilities: metrics registry, tracing, lock registry, lifecycle."""

import logging
import time

from corrosion_tpu.utils.lifecycle import (
    Tripwire,
    backoff,
    pending_count,
    spawn_counted,
    wait_for_all_pending,
)
from corrosion_tpu.utils.locks import LockRegistry
from corrosion_tpu.utils.metrics import Registry, RoundTimer, record_round_info
from corrosion_tpu.utils.tracing import SpanContext, inject_traceparent, span


def test_metrics_registry_and_prometheus():
    r = Registry()
    r.counter("corro.broadcast.sent", 3)
    r.counter("corro.broadcast.sent", 2)
    r.gauge("corro.members.count", 42, labels={"state": "alive"})
    r.histogram("corro.sync.seconds", 0.03)
    r.histogram("corro.sync.seconds", 4.2)
    assert r.get_counter("corro.broadcast.sent") == 5
    text = r.render()
    assert "corro_broadcast_sent 5" in text
    assert 'corro_members_count{state="alive"} 42' in text
    assert "corro_sync_seconds_count 2" in text
    assert 'le="+Inf"} 2' in text


def test_record_round_info():
    r = Registry()
    record_round_info({"acked": 7, "queued": 3, "unknown_key": 9}, registry=r)
    record_round_info({"acked": 1}, registry=r)
    assert r.get_counter("corro.gossip.probe.acked") == 8
    assert r.get_gauge("corro.broadcast.pending.count") == 3


def test_round_timer_slow_warn():
    r = Registry()
    with RoundTimer("round", warn_seconds=0.0, registry=r):
        time.sleep(0.01)
    assert r.get_counter("corro.round.slow") == 1


def test_span_propagation():
    with span("sync.client") as parent:
        tp = inject_traceparent()
        assert tp is not None and parent.trace_id in tp
    # server side extracts the context and continues the same trace
    with span("sync.server", traceparent=tp) as server_ctx:
        assert server_ctx.trace_id == parent.trace_id
    assert SpanContext.from_traceparent("garbage") is None


def test_lock_registry_watchdog():
    logs = []

    class L:
        def warning(self, msg, *a):
            logs.append(msg % a)

    reg = LockRegistry(warn_seconds=0.0, logger=L())
    lk = reg.lock("bookie.write")
    with lk:
        time.sleep(0.01)
        slow = reg.check()
        assert slow and slow[0]["label"] == "bookie.write"
    assert reg.check() == []  # released -> clean
    assert logs and "bookie.write" in logs[0]


def test_lifecycle_spawn_and_tripwire():
    tw = Tripwire()
    results = []

    def worker():
        tw.wait(5)
        results.append(1)

    spawn_counted(worker)
    spawn_counted(worker)
    assert pending_count() >= 2
    tw.trip()
    assert wait_for_all_pending(timeout=5)
    assert results == [1, 1] and tw.tripped


def test_backoff_grows_and_caps():
    delays = []
    for i, d in zip(range(8), backoff(base=0.1, factor=2, max_delay=1.0, jitter=0)):
        delays.append(d)
    assert delays[0] == 0.1 and delays[1] == 0.2
    assert max(delays) == 1.0 and delays[-1] == 1.0


def test_otlp_file_exporter(tmp_path):
    """Spans export in OTLP-JSON shape with parent/child links intact —
    the reference's OTLP pipeline (main.rs:57-150) pointed at a file."""
    import json

    from corrosion_tpu.utils import tracing

    path = str(tmp_path / "spans.otlp.jsonl")
    tracing.configure_otlp_file(path, service_name="test-svc")
    try:
        with span("outer") as outer_ctx:
            with span("inner", step="apply"):
                pass
        tracing.flush_otlp()
    finally:
        tracing.configure_otlp_file(None)

    batches = [json.loads(line) for line in open(path)]
    spans = [
        s
        for b in batches
        for rs in b["resourceSpans"]
        for ss in rs["scopeSpans"]
        for s in ss["spans"]
    ]
    by_name = {s["name"]: s for s in spans}
    assert set(by_name) == {"outer", "inner"}
    svc = batches[0]["resourceSpans"][0]["resource"]["attributes"][0]
    assert svc["value"]["stringValue"] == "test-svc"
    inner, outer = by_name["inner"], by_name["outer"]
    assert inner["traceId"] == outer["traceId"] == outer_ctx.trace_id
    assert inner["parentSpanId"] == outer["spanId"]
    assert "parentSpanId" not in outer  # trace root
    assert int(inner["endTimeUnixNano"]) >= int(inner["startTimeUnixNano"])
    assert inner["attributes"][0]["key"] == "step"


def test_admin_sync_trace_propagation(tmp_path):
    """CLI-side span context rides the admin socket into the agent's
    serving span — the SyncTraceContextV1 inject/extract seam
    (sync.rs:33-67)."""
    import json

    from corrosion_tpu.admin import AdminClient, AdminServer
    from corrosion_tpu.agent import Agent
    from corrosion_tpu.testing import cluster_config
    from corrosion_tpu.utils import tracing

    path = str(tmp_path / "spans.otlp.jsonl")
    sock = str(tmp_path / "admin.sock")
    tracing.configure_otlp_file(path)
    try:
        with Agent(cluster_config()) as agent:
            agent.wait_rounds(2, timeout=120)
            srv = AdminServer(agent, sock).start()
            try:
                with span("cli.sync_generate") as client_ctx:
                    client = AdminClient(sock)
                    out = client.call("sync", node=0)
                    client.close()
                assert "heads" in out
            finally:
                srv.stop()
        tracing.flush_otlp()
    finally:
        tracing.configure_otlp_file(None)

    spans = [
        s
        for line in open(path)
        for rs in json.loads(line)["resourceSpans"]
        for ss in rs["scopeSpans"]
        for s in ss["spans"]
    ]
    server_spans = [s for s in spans if s["name"] == "admin.sync_state"]
    assert server_spans, "serving span not exported"
    sp = server_spans[0]
    # same trace, parented under the client's span — cross-process link
    assert sp["traceId"] == client_ctx.trace_id
    assert sp["parentSpanId"] == client_ctx.span_id
