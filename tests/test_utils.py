"""Host utilities: metrics registry, tracing, lock registry, lifecycle."""

import logging
import time

from corrosion_tpu.utils.lifecycle import (
    Tripwire,
    backoff,
    pending_count,
    spawn_counted,
    wait_for_all_pending,
)
from corrosion_tpu.utils.locks import LockRegistry
from corrosion_tpu.utils.metrics import Registry, RoundTimer, record_round_info
from corrosion_tpu.utils.tracing import SpanContext, inject_traceparent, span


def test_metrics_registry_and_prometheus():
    r = Registry()
    r.counter("corro.broadcast.sent", 3)
    r.counter("corro.broadcast.sent", 2)
    r.gauge("corro.members.count", 42, labels={"state": "alive"})
    r.histogram("corro.sync.seconds", 0.03)
    r.histogram("corro.sync.seconds", 4.2)
    assert r.get_counter("corro.broadcast.sent") == 5
    text = r.render()
    assert "corro_broadcast_sent 5" in text
    assert 'corro_members_count{state="alive"} 42' in text
    assert "corro_sync_seconds_count 2" in text
    assert 'le="+Inf"} 2' in text


def test_record_round_info():
    r = Registry()
    record_round_info({"acked": 7, "queued": 3, "unknown_key": 9}, registry=r)
    record_round_info({"acked": 1}, registry=r)
    assert r.get_counter("corro.gossip.probe.acked") == 8
    assert r.get_gauge("corro.broadcast.pending.count") == 3


def test_prometheus_label_value_escaping():
    """Exposition-format spec: `"`, `\\` and newline in label values
    must be escaped — raw they corrupt the whole scrape."""
    r = Registry()
    r.gauge("corro.test.series", 1, labels={"q": 'say "hi"',
                                            "b": "a\\b",
                                            "n": "line1\nline2"})
    text = r.render()
    assert '\\"hi\\"' in text
    assert 'b="a\\\\b"' in text
    assert 'n="line1\\nline2"' in text
    assert "\nline2" not in text  # no raw newline inside a label value


def test_prometheus_one_type_line_per_metric_name():
    """Labeled samples of one metric share a single `# TYPE` line —
    strict expfmt parsers reject a scrape with a repeated TYPE line."""
    r = Registry()
    r.gauge("corro.mem.table.bytes", 1, labels={"table": "a"})
    r.gauge("corro.mem.table.bytes", 2, labels={"table": "b"})
    r.counter("corro.test.c", 1, labels={"x": "1"})
    r.counter("corro.test.c", 1, labels={"x": "2"})
    text = r.render()
    assert text.count("# TYPE corro_mem_table_bytes gauge") == 1
    assert text.count("# TYPE corro_test_c counter") == 1
    assert text.count("corro_mem_table_bytes{") == 2


def test_prometheus_le_formatting():
    """Bucket bounds render canonically (`le="1"`, never `le="1.0"`)."""
    r = Registry()
    r.histogram("corro.test.hist", 0.7, buckets=(0.5, 1.0, 2.5, 10.0))
    text = r.render()
    assert 'le="0.5"' in text and 'le="1"' in text
    assert 'le="2.5"' in text and 'le="10"' in text
    assert 'le="+Inf"' in text
    assert 'le="1.0"' not in text and 'le="10.0"' not in text
    # shortest round-trip, not %g: >6-significant-digit bounds must not
    # collide into one duplicate le label
    r2 = Registry()
    r2.histogram("corro.test.hp", 1.0, buckets=(1234567.0, 1234568.0))
    t2 = r2.render()
    assert 'le="1234567"' in t2 and 'le="1234568"' in t2


def test_latency_buckets_default_ladder():
    """The default histogram ladder must resolve serving-plane
    latencies: sub-ms (PG point reads) through 10s (slow-path sync),
    log-spaced so quantile interpolation error stays proportional."""
    from corrosion_tpu.utils.metrics import LATENCY_BUCKETS

    assert LATENCY_BUCKETS[0] <= 0.0005  # sub-ms resolution
    assert LATENCY_BUCKETS[-1] >= 10.0
    assert list(LATENCY_BUCKETS) == sorted(LATENCY_BUCKETS)
    # log-spaced: no adjacent pair more than ~4x apart (a decade gap
    # would make every quantile in it a wild guess)
    for lo, hi in zip(LATENCY_BUCKETS, LATENCY_BUCKETS[1:]):
        assert hi / lo <= 4.0 + 1e-9
    r = Registry()
    r.histogram("corro.test.lat", 0.003)
    h = r.snapshot()["histograms"][("corro.test.lat", ())]
    assert tuple(h["buckets"]) == tuple(LATENCY_BUCKETS)


def test_histogram_bucket_ladder_pinned_per_name():
    """First observation of a NAME fixes its bucket ladder for every
    label set: mixed `le` ladders within one family are malformed
    exposition (PromQL histogram_quantile silently mis-aggregates)."""
    r = Registry()
    r.histogram("corro.test.pin", 0.8, buckets=(0.5, 1.0, 2.0),
                labels={"a": "1"})
    # later caller asks for a different ladder — it must NOT fork the family
    r.histogram("corro.test.pin", 1.5, buckets=(0.1, 10.0),
                labels={"a": "2"})
    hists = r.snapshot()["histograms"]
    ladders = {tuple(h["buckets"]) for (n, _l), h in hists.items()
               if n == "corro.test.pin"}
    assert ladders == {(0.5, 1.0, 2.0)}
    text = r.render()
    assert 'le="10"' not in text


def test_histogram_quantiles_known_distribution():
    """The snapshot-side quantile estimator against distributions with
    known percentiles (linear interpolation within a bucket)."""
    from corrosion_tpu.utils.metrics import (
        histogram_quantile,
        quantiles_from_histogram,
    )

    r = Registry()
    # uniform on (0, 1): 1000 samples, fine ladder -> p50 ~ 0.5 etc.
    for i in range(1000):
        r.histogram("corro.test.uni", (i + 0.5) / 1000.0,
                    buckets=tuple(j / 20.0 for j in range(1, 21)))
    h = r.snapshot()["histograms"][("corro.test.uni", ())]
    qs = quantiles_from_histogram(h)
    assert abs(qs["p50"] - 0.5) < 0.06
    assert abs(qs["p95"] - 0.95) < 0.06
    assert abs(qs["p99"] - 0.99) < 0.06
    # two-point distribution: 90 fast + 10 slow -> p50 in the fast
    # bucket, p99 in the slow one
    r2 = Registry()
    for _ in range(90):
        r2.histogram("corro.test.bi", 0.004, buckets=(0.005, 0.05, 0.5))
    for _ in range(10):
        r2.histogram("corro.test.bi", 0.4, buckets=(0.005, 0.05, 0.5))
    h2 = r2.snapshot()["histograms"][("corro.test.bi", ())]
    assert histogram_quantile(h2, 0.5) <= 0.005
    assert 0.05 < histogram_quantile(h2, 0.99) <= 0.5
    # degenerate inputs stay finite
    assert histogram_quantile({"count": 0, "buckets": (), "counts": (),
                               "sum": 0.0}, 0.5) == 0.0


def test_exposition_render_parse_roundtrip():
    """`parse_exposition(render())` reconstructs the snapshot — the
    guarantee the load harness's server-vs-client agreement gate (and
    any external scraper) stands on. Covers escaped label values and
    histograms, where the exposition is cumulative but the snapshot
    is per-bucket."""
    from corrosion_tpu.utils.metrics import parse_exposition

    r = Registry()
    r.counter("corro.test.reqs", 7, labels={"route": "/v1/x", "m": "GET"})
    r.counter("corro.test.reqs", 3, labels={"route": "/v1/y", "m": "POST"})
    r.gauge("corro.test.depth", 42, labels={"q": 'say "hi"\n\\done'})
    for v in (0.003, 0.02, 0.02, 4.0):
        r.histogram("corro.test.lat", v, buckets=(0.01, 0.1, 1.0))
    parsed = parse_exposition(r.render())
    assert parsed["counters"][
        ("corro_test_reqs", (("m", "GET"), ("route", "/v1/x")))] == 7.0
    assert parsed["counters"][
        ("corro_test_reqs", (("m", "POST"), ("route", "/v1/y")))] == 3.0
    # escaped label value survives the round trip byte-for-byte
    assert parsed["gauges"][
        ("corro_test_depth", (("q", 'say "hi"\n\\done'),))] == 42.0
    h = parsed["histograms"][("corro_test_lat", ())]
    assert h["count"] == 4
    assert abs(h["sum"] - 4.043) < 1e-9
    # de-accumulated per-bucket counts, not the cumulative wire form
    assert h["counts"] == [1, 2, 0, 1]
    assert [float(b) for b in h["buckets"]] == [0.01, 0.1, 1.0]


def test_prometheus_listener_ephemeral_port_and_join():
    """port=0 binds an ephemeral port exposed as `bound_port`, and
    shutdown() joins the counted corro-prometheus thread (the leak gate
    must see it exit) and closes the socket."""
    import threading
    import urllib.request

    from corrosion_tpu.utils.metrics import start_prometheus_listener

    r = Registry()
    r.counter("corro.test.up", 1)
    srv = start_prometheus_listener(r, port=0)
    assert srv.bound_port > 0
    text = urllib.request.urlopen(
        f"http://127.0.0.1:{srv.bound_port}/metrics", timeout=5
    ).read().decode()
    assert "corro_test_up 1" in text
    srv.shutdown()
    assert not any(t.name == "corro-prometheus" and t.is_alive()
                   for t in threading.enumerate())
    # listening socket closed (fd released) — without racing another
    # process for the freed ephemeral port
    assert srv.socket.fileno() == -1


def test_round_timer_slow_warn():
    r = Registry()
    with RoundTimer("round", warn_seconds=0.0, registry=r):
        time.sleep(0.01)
    assert r.get_counter("corro.round.slow") == 1


def test_span_propagation():
    with span("sync.client") as parent:
        tp = inject_traceparent()
        assert tp is not None and parent.trace_id in tp
    # server side extracts the context and continues the same trace
    with span("sync.server", traceparent=tp) as server_ctx:
        assert server_ctx.trace_id == parent.trace_id
    assert SpanContext.from_traceparent("garbage") is None


def test_lock_registry_watchdog():
    logs = []

    class L:
        def warning(self, msg, *a):
            logs.append(msg % a)

    reg = LockRegistry(warn_seconds=0.0, logger=L())
    lk = reg.lock("bookie.write")
    with lk:
        time.sleep(0.01)
        slow = reg.check()
        assert slow and slow[0]["label"] == "bookie.write"
    assert reg.check() == []  # released -> clean
    assert logs and "bookie.write" in logs[0]


def test_lifecycle_spawn_and_tripwire():
    tw = Tripwire()
    results = []

    def worker():
        tw.wait(5)
        results.append(1)

    spawn_counted(worker)
    spawn_counted(worker)
    assert pending_count() >= 2
    tw.trip()
    assert wait_for_all_pending(timeout=5)
    assert results == [1, 1] and tw.tripped


def test_backoff_grows_and_caps():
    delays = []
    for i, d in zip(range(8), backoff(base=0.1, factor=2, max_delay=1.0, jitter=0)):
        delays.append(d)
    assert delays[0] == 0.1 and delays[1] == 0.2
    assert max(delays) == 1.0 and delays[-1] == 1.0


def test_otlp_file_exporter(tmp_path):
    """Spans export in OTLP-JSON shape with parent/child links intact —
    the reference's OTLP pipeline (main.rs:57-150) pointed at a file."""
    import json

    from corrosion_tpu.utils import tracing

    path = str(tmp_path / "spans.otlp.jsonl")
    tracing.configure_otlp_file(path, service_name="test-svc")
    try:
        with span("outer") as outer_ctx:
            with span("inner", step="apply"):
                pass
        tracing.flush_otlp()
    finally:
        tracing.configure_otlp_file(None)

    batches = [json.loads(line) for line in open(path)]
    spans = [
        s
        for b in batches
        for rs in b["resourceSpans"]
        for ss in rs["scopeSpans"]
        for s in ss["spans"]
    ]
    by_name = {s["name"]: s for s in spans}
    assert set(by_name) == {"outer", "inner"}
    svc = batches[0]["resourceSpans"][0]["resource"]["attributes"][0]
    assert svc["value"]["stringValue"] == "test-svc"
    inner, outer = by_name["inner"], by_name["outer"]
    assert inner["traceId"] == outer["traceId"] == outer_ctx.trace_id
    assert inner["parentSpanId"] == outer["spanId"]
    assert "parentSpanId" not in outer  # trace root
    assert int(inner["endTimeUnixNano"]) >= int(inner["startTimeUnixNano"])
    assert inner["attributes"][0]["key"] == "step"


def test_otlp_exporter_failed_flush_retains_batch(tmp_path):
    """A failed flush keeps the batch for the next attempt — spans are
    not lost to a transient IO error."""
    from corrosion_tpu.utils.tracing import OtlpFileExporter

    ex = OtlpFileExporter(str(tmp_path / "no_such_dir" / "s.jsonl"),
                         flush_every=1)
    ex.export({"spanId": "a" * 16, "name": "one"})  # flush fails, retained
    assert len(ex._buf) == 1
    ex.path = str(tmp_path / "s.jsonl")  # path heals
    ex.flush()
    assert ex._buf == []
    import json

    batch = json.loads(open(ex.path).readline())
    spans = batch["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert [s["name"] for s in spans] == ["one"]


def test_otlp_exporter_buffer_cap_under_broken_path(tmp_path, monkeypatch):
    """A permanently broken path cannot grow the retained buffer beyond
    MAX_BUFFERED — newest spans win, oldest are shed."""
    from corrosion_tpu.utils.tracing import OtlpFileExporter

    monkeypatch.setattr(OtlpFileExporter, "MAX_BUFFERED", 8)
    ex = OtlpFileExporter(str(tmp_path / "missing" / "s.jsonl"),
                         flush_every=1)
    for i in range(20):
        ex.export({"name": f"s{i}"})  # every flush fails
    assert len(ex._buf) == 8
    assert [s["name"] for s in ex._buf] == [f"s{i}" for i in range(12, 20)]


def test_from_traceparent_rejects_malformed():
    """Malformed inbound trace context must parse to None (a poisoned
    id would corrupt strict OTLP consumers downstream)."""
    from corrosion_tpu.utils.tracing import SpanContext

    good = "00-" + "a" * 32 + "-" + "b" * 16 + "-01"
    assert SpanContext.from_traceparent(good) is not None
    bad = [
        None,
        "",
        "garbage",
        "00-short-span-01",
        "00-" + "a" * 31 + "-" + "b" * 16 + "-01",  # trace id too short
        "00-" + "a" * 32 + "-" + "b" * 15 + "-01",  # span id too short
        "00-" + "g" * 32 + "-" + "b" * 16 + "-01",  # non-hex trace id
        "00-" + "a" * 32 + "-" + "z" * 16 + "-01",  # non-hex span id
        "00-" + "a" * 32 + "-" + "b" * 16,  # missing flags field
        good + "-extra",  # too many fields
    ]
    for tp in bad:
        assert SpanContext.from_traceparent(tp) is None, tp


def test_admin_sync_trace_propagation(tmp_path):
    """CLI-side span context rides the admin socket into the agent's
    serving span — the SyncTraceContextV1 inject/extract seam
    (sync.rs:33-67)."""
    import json

    from corrosion_tpu.admin import AdminClient, AdminServer
    from corrosion_tpu.agent import Agent
    from corrosion_tpu.testing import cluster_config
    from corrosion_tpu.utils import tracing

    path = str(tmp_path / "spans.otlp.jsonl")
    sock = str(tmp_path / "admin.sock")
    tracing.configure_otlp_file(path)
    try:
        with Agent(cluster_config()) as agent:
            agent.wait_rounds(2, timeout=120)
            srv = AdminServer(agent, sock).start()
            try:
                with span("cli.sync_generate") as client_ctx:
                    client = AdminClient(sock)
                    out = client.call("sync", node=0)
                    client.close()
                assert "heads" in out
            finally:
                srv.stop()
        tracing.flush_otlp()
    finally:
        tracing.configure_otlp_file(None)

    spans = [
        s
        for line in open(path)
        for rs in json.loads(line)["resourceSpans"]
        for ss in rs["scopeSpans"]
        for s in ss["spans"]
    ]
    server_spans = [s for s in spans if s["name"] == "admin.sync_state"]
    assert server_spans, "serving span not exported"
    sp = server_spans[0]
    # same trace, parented under the client's span — cross-process link
    assert sp["traceId"] == client_ctx.trace_id
    assert sp["parentSpanId"] == client_ctx.span_id
