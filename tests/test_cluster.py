"""End-to-end cluster convergence: broadcast + sync + SWIM together.

The sim analog of the reference's ``configurable_stress_test``
(``crates/corro-agent/src/agent/tests.rs:286-600``): fire interleaved
writes at the cluster, then poll until every node's store/heads/needs
agree — convergence IS the assertion."""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from corrosion_tpu.sim import scenario
from corrosion_tpu.sim.config import wan_config
from corrosion_tpu.sim.step import RoundInput, SimState, crdt_metrics, run_rounds
from corrosion_tpu.sim.transport import NetModel

N = 24


@pytest.fixture(scope="module")
def cfg():
    return wan_config(
        N, n_origins=4, n_rows=4, n_cols=2, sync_interval=4, announce_interval=8
    )


def settle(cfg, st, net, key, rounds):
    inp = scenario.quiet(cfg, rounds)
    return run_rounds(cfg, st, net, key, inp)


def test_single_writer_propagates_to_all(cfg):
    st = SimState.create(cfg)
    net = NetModel.create(N)
    key = jr.key(10)
    inp = scenario.single_writer(cfg, 20, jr.key(11), writes_per_round=1)
    st, _ = run_rounds(cfg, st, net, key, inp)
    st, _ = settle(cfg, st, net, jr.key(12), 60)
    m = crdt_metrics(cfg, st)
    assert bool(m["converged"]), (
        int(m["n_diverged"]),
        int(m["total_needs"]),
    )
    # writer's 20 versions reached everyone: heads[*, 0] == 20
    heads = np.asarray(st.crdt.book.head)
    assert (heads[:, 0] == 20).all(), heads[:, 0]
    # and the winning cells are identical everywhere
    assert len(np.unique(np.asarray(st.crdt.store[1]), axis=0)) == 1


def test_conflict_heavy_multi_writer_converges(cfg):
    st = SimState.create(cfg)
    net = NetModel.create(N, drop_prob=0.05)
    inp = scenario.conflict_heavy(cfg, 30, jr.key(21), write_prob=0.5, hot_cells=2)
    st, _ = run_rounds(cfg, st, net, jr.key(20), inp)
    st, _ = settle(cfg, st, NetModel.create(N), jr.key(22), 100)
    m = crdt_metrics(cfg, st)
    assert bool(m["converged"]), (int(m["n_diverged"]), int(m["total_needs"]))


def test_sync_repairs_partition(cfg):
    # writes happen while the cluster is partitioned; after healing,
    # anti-entropy must reconcile both sides
    st = SimState.create(cfg)
    part = scenario.partitioned_net(cfg, groups=2)
    inp = scenario.conflict_heavy(cfg, 20, jr.key(31), write_prob=0.4, hot_cells=2)
    st, _ = run_rounds(cfg, st, part, jr.key(30), inp)

    healed = NetModel.create(N)
    st, _ = settle(cfg, st, healed, jr.key(32), 150)
    m = crdt_metrics(cfg, st)
    assert bool(m["converged"]), (int(m["n_diverged"]), int(m["total_needs"]))


def test_churn_mix_converges_after_quiesce(cfg):
    st = SimState.create(cfg)
    net = NetModel.create(N, drop_prob=0.02)
    inp = scenario.full_mix(cfg, 40, jr.key(41), churn_rate=0.01, write_prob=0.3)
    st, _ = run_rounds(cfg, st, net, jr.key(40), inp)
    # revive everyone, stop writing, let it settle
    n = cfg.n_nodes
    wake = scenario.quiet(cfg, 1)._replace(
        revive=(~st.swim.alive)[None, :]
    )
    st, _ = run_rounds(cfg, st, net, jr.key(42), wake)
    st, _ = settle(cfg, st, NetModel.create(N), jr.key(43), 150)
    m = crdt_metrics(cfg, st)
    assert bool(m["converged"]), (int(m["n_diverged"]), int(m["total_needs"]))


def test_cluster_id_gates_payload_delivery(cfg):
    """ClusterId payload gating (uni.rs:75-77, peer/mod.rs:1425-1436):
    nodes stamped with a foreign cluster id receive nothing — no
    broadcast, no sync — until the id is set back, then they catch up."""
    st = SimState.create(cfg)
    net = NetModel.create(N)
    # last 4 nodes sit on cluster id 7
    foreign = np.zeros(N, np.int32)
    foreign[-4:] = 7
    net_split = net._replace(cluster_id=jnp.asarray(foreign))
    key = jr.key(40)
    inp = scenario.single_writer(cfg, 10, jr.key(41), writes_per_round=1)
    st, _ = run_rounds(cfg, st, net_split, key, inp)
    st, _ = settle(cfg, st, net_split, jr.key(42), 40)
    heads = np.asarray(st.crdt.book.head)
    assert (heads[:-4, 0] == 10).all(), "same-id nodes must converge"
    assert (heads[-4:, 0] == 0).all(), (
        "foreign-id nodes must receive no payloads"
    )
    # admin sets the id back -> sync repairs the gap
    st, _ = settle(cfg, st, net, jr.key(43), 80)
    m = crdt_metrics(cfg, st)
    assert bool(m["converged"])
    assert (np.asarray(st.crdt.book.head)[:, 0] == 10).all()
