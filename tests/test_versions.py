"""Property tests: Book (version bookkeeping) against the oracle seen-sets.

The array Book must match the oracle's contiguous head, freshness
decisions, and needs counts for any arrival order — the same contracts the
reference's gap-algebra unit tests pin down
(``crates/corro-types/src/agent.rs:1606-1841``)."""

import numpy as np
import jax.numpy as jnp

from corrosion_tpu.ops import Book, needs_count, record_versions
from corrosion_tpu.sim.oracle import OracleNode


def run_rounds(rng, n_nodes, n_origins, slots, batch, rounds, max_ver=20):
    book = Book.create(n_nodes, n_origins, slots)
    oracles = [OracleNode(n_origins) for _ in range(n_nodes)]
    fresh_match = True
    for _ in range(rounds):
        origin = rng.integers(0, n_origins, (n_nodes, batch))
        ver = rng.integers(1, max_ver, (n_nodes, batch))
        valid = rng.random((n_nodes, batch)) < 0.7
        book, fresh, _ = record_versions(
            book,
            jnp.asarray(origin, jnp.int32),
            jnp.asarray(ver, jnp.int32),
            jnp.asarray(valid),
        )
        fresh = np.asarray(fresh)
        for n in range(n_nodes):
            batch_seen = set()
            for j in range(batch):
                if not valid[n, j]:
                    continue
                o, v = int(origin[n, j]), int(ver[n, j])
                want = oracles[n].record(o, v) and (o, v) not in batch_seen
                batch_seen.add((o, v))
                if bool(fresh[n, j]) != want:
                    fresh_match = False
    return book, oracles, fresh_match


def test_heads_and_freshness_match_oracle_when_buffer_ample():
    rng = np.random.default_rng(3)
    # slots ample: every out-of-order version fits, so heads must be exact
    book, oracles, fresh_ok = run_rounds(
        rng, n_nodes=5, n_origins=3, slots=64, batch=8, rounds=12, max_ver=15
    )
    assert fresh_ok
    heads = np.asarray(book.head)
    needs = np.asarray(needs_count(book))
    for n, o in np.ndindex(heads.shape):
        assert heads[n, o] == oracles[n].head(o), (n, o)
        assert needs[n, o] == oracles[n].needs(o), (n, o)


def test_contiguous_delivery_keeps_buffer_empty():
    n_nodes, n_origins = 4, 2
    book = Book.create(n_nodes, n_origins, 8)
    for v in range(1, 6):
        origin = jnp.zeros((n_nodes, 2), jnp.int32)
        ver = jnp.full((n_nodes, 2), v, jnp.int32)
        valid = jnp.asarray([[True, True]] * n_nodes)  # duplicate in batch
        book, fresh, _ = record_versions(book, origin, ver, valid)
        assert np.asarray(fresh)[:, 0].all() and not np.asarray(fresh)[:, 1].any()
    assert (np.asarray(book.head)[:, 0] == 5).all()
    assert (np.asarray(book.seen) == 0).all()


def test_gap_then_close_advances_head_in_one_pass():
    book = Book.create(1, 1, 8)
    o = jnp.zeros((1, 4), jnp.int32)
    # versions 2,3,5 arrive first: head stays 0, needs = 3 (1,2,3 missing? no:
    # known_max=5, seen={2,3,5} → missing {1,4} → needs 2)
    book, _, _ = record_versions(
        book, o[:, :3], jnp.asarray([[2, 3, 5]], jnp.int32), jnp.ones((1, 3), bool)
    )
    assert int(book.head[0, 0]) == 0
    assert int(needs_count(book)[0, 0]) == 2
    # 1 and 4 arrive: whole chain 1..5 must collapse in one record call
    book, _, _ = record_versions(
        book, o[:, :2], jnp.asarray([[4, 1]], jnp.int32), jnp.ones((1, 2), bool)
    )
    assert int(book.head[0, 0]) == 5
    assert int(needs_count(book)[0, 0]) == 0
    assert (np.asarray(book.seen) == 0).all()


def test_buffer_overflow_drops_but_keeps_correct_heads():
    rng = np.random.default_rng(4)
    # window tiny (32 bits) vs a wide version range: beyond-window versions
    # drop; heads must still be a *lower bound* of the oracle's and never
    # exceed it (dropped = not seen)
    book, oracles, _ = run_rounds(
        rng, n_nodes=4, n_origins=2, slots=3, batch=6, rounds=10, max_ver=200
    )
    heads = np.asarray(book.head)
    for n, o in np.ndindex(heads.shape):
        assert heads[n, o] <= oracles[n].head(o), (n, o)
