"""HLC wired into the data path (VERDICT #4).

The reference stamps every local write (``crsql_set_ts``,
``public/mod.rs:88-100``), folds every received ts (``handlers.rs:689-701``)
and sync clock message (``peer/mod.rs:1439-1458``), and drops stamps too
far ahead (``setup.rs:96-101``, 300 ms). Here: writes stamp from the
per-node device clock (``CrdtState.hlc``), ingest and sync fold, drift
rejects surface as a round metric, and the API boundary stamps with the
host ``HLClock``."""

import jax
import jax.numpy as jnp
import jax.random as jr
import numpy as np

from corrosion_tpu.sim.broadcast import (
    HLC_MAX_DRIFT_ROUNDS,
    HLC_ROUND_BITS,
    CrdtState,
    ingest_changes,
    local_write,
)
from corrosion_tpu.sim.config import SimConfig
from corrosion_tpu.sim.step import RoundInput, SimState, sim_step
from corrosion_tpu.sim.transport import NetModel


def test_clock_never_regresses_and_remote_stamps_advance_it():
    """Causality: per-node clocks are monotone across rounds, and a
    reader that applied a writer's change holds a clock >= that change's
    stamp (folding)."""
    n = 16
    cfg = SimConfig(n_nodes=n, n_origins=4).validate()
    st = SimState.create(cfg)
    net = NetModel.create(n)
    step = jax.jit(lambda s, k, i: sim_step(cfg, s, net, k, i))
    key = jr.key(0)
    quiet = RoundInput.quiet(cfg)

    prev = np.zeros(n, np.int64)
    writer_stamp_max = 0
    for r in range(24):
        inp = quiet
        if r < 8:  # writer 0 writes every early round
            inp = quiet._replace(
                write_mask=jnp.asarray(np.eye(1, n, 0, dtype=bool)[0]),
                write_cell=jnp.zeros(n, jnp.int32),
                write_val=jnp.full(n, 100 + r, jnp.int32),
            )
        key, sub = jr.split(key)
        st, _ = step(st, sub, inp)
        hlc = np.asarray(st.crdt.hlc).astype(np.int64)
        assert (hlc >= prev).all(), f"clock regressed at round {r}"
        # physical part never runs ahead of round + drift bound
        assert (hlc >> HLC_ROUND_BITS).max() <= (r + 1) + HLC_MAX_DRIFT_ROUNDS
        prev = hlc
        writer_stamp_max = max(writer_stamp_max, int(hlc[0]))

    # convergence spreads the writer's stamps: any node holding the
    # writer's data folded a stamp >= the writer's first write stamp
    ver = np.asarray(st.crdt.store[0])
    holders = ver[:, 0] > 0
    assert holders.sum() > n // 2, "dissemination failed (test harness)"
    first_stamp = 1 << HLC_ROUND_BITS  # round 1's minimum stamp
    assert (prev[holders] >= first_stamp).all()


def test_drift_rejected_changes_dropped_and_counted():
    """A stamp more than HLC_MAX_DRIFT_ROUNDS ahead of local time gets
    its change dropped and counted (handlers.rs:696-701)."""
    n = 4
    cfg = SimConfig(n_nodes=n, n_origins=2).validate()
    cst = CrdtState.create(cfg)
    cst = cst._replace(now=jnp.int32(5))
    z = jnp.zeros((n, 1), jnp.int32)
    far_ahead = jnp.full(
        (n, 1), (5 + HLC_MAX_DRIFT_ROUNDS + 3) << HLC_ROUND_BITS, jnp.int32
    )
    live = jnp.ones((n, 1), bool)
    cst2, info = ingest_changes(
        cfg, cst, live,
        m_origin=z, m_dbv=z + 1, m_cell=z, m_ver=z + 1, m_val=z + 7,
        m_site=z, m_clp=z, m_seq=z, m_nseq=z + 1, m_ts=far_ahead,
    )
    assert int(info["clock_drift_rejects"]) == n
    assert int(info["fresh"]) == 0
    assert not np.asarray(cst2.store[0]).any(), "rejected change applied"
    # in-range stamps fold and apply
    okay_ts = jnp.full((n, 1), 6 << HLC_ROUND_BITS, jnp.int32)
    cst3, info = ingest_changes(
        cfg, cst, live,
        m_origin=z, m_dbv=z + 1, m_cell=z, m_ver=z + 1, m_val=z + 7,
        m_site=z, m_clp=z, m_seq=z, m_nseq=z + 1, m_ts=okay_ts,
    )
    assert int(info["clock_drift_rejects"]) == 0
    assert int(info["fresh"]) == n
    assert (np.asarray(cst3.hlc) >= 6 << HLC_ROUND_BITS).all()


def test_write_stamps_are_monotonic_per_node():
    """Writer stamps strictly increase even with several writes in close
    rounds (uhlc new_timestamp semantics on the device clock)."""
    n = 8
    cfg = SimConfig(n_nodes=n, n_origins=2).validate()
    cst = CrdtState.create(cfg)
    stamps = []
    for r in range(1, 5):
        cst = cst._replace(now=jnp.int32(r))
        w = jnp.asarray(np.eye(1, n, 0, dtype=bool)[0])
        cst = local_write(
            cfg, cst, w, jnp.zeros(n, jnp.int32), jnp.full(n, r, jnp.int32)
        )
        # same round, second write: logical counter must break the tie
        cst = local_write(
            cfg, cst, w, jnp.ones(n, jnp.int32), jnp.full(n, r, jnp.int32)
        )
        stamps.append(int(cst.hlc[0]))
    assert stamps == sorted(set(stamps)), "stamps not strictly monotonic"


def test_agent_api_boundary_stamps():
    """write_many stamps transactions with the host HLClock (and the
    stamps are strictly monotonic per node)."""
    from corrosion_tpu.agent.core import Agent
    from corrosion_tpu.config import Config

    cfg = Config()
    cfg.sim.n_nodes = 8
    cfg.sim.n_origins = 2
    with Agent(cfg) as a:
        # first round includes jit compile; generous timeouts keep the
        # test robust on a loaded CI machine
        a.wait_rounds(1, timeout=180.0)
        r1 = a.write(0, 0, 1, timeout=120.0)
        r2 = a.write_many(0, [(1, 2), (2, 3)], timeout=120.0)
        assert "ts" in r1 and "ts" in r2
        t1 = tuple(map(int, r1["ts"].split("@")[0].split(".")))
        t2 = tuple(map(int, r2["ts"].split("@")[0].split(".")))
        assert t2 > t1, "API stamps not monotonic"
