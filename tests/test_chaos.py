"""corrochaos: the deterministic seeded fault-scenario engine
(docs/chaos.md, ``resilience/chaos.py``).

Tier-1 replays the small tier-1 scripts end to end against all THREE
oracles (convergence within budget; every surviving manifest replays
to the uninterrupted fixpoint bitwise; the healed cluster quiesces —
activity drains to zero), pins verdict determinism in ``(name,
seed)``, and meta-tests the registry against the doc. The full sweep
— every shipped scenario, including the 8->4 remesh, the fused flip
and the ISSUE-18 composed scenarios — is slow-marked here and rides
``scripts/check.sh`` under ``CORROSAN=1`` (publishing
``artifacts/chaos_r13.json``).
"""

import dataclasses
import os

import pytest

from corrosion_tpu.checkpoint import CheckpointIntegrityError, load_checkpoint
from corrosion_tpu.resilience.chaos import (
    INJECTION_KINDS,
    SCENARIOS,
    TIER1_SCENARIOS,
    Injection,
    ScenarioScript,
    compile_scenario,
    corrupt_checkpoint,
    run_scenario,
    run_sweep,
    scenario_config,
)
from corrosion_tpu.sim.broadcast import HLC_MAX_DRIFT_ROUNDS
from corrosion_tpu.sim.scenario import FaultPhase

DOC = os.path.join(os.path.dirname(__file__), "..", "docs", "chaos.md")


# --- tier-1 smoke: the small scripts, both oracles ------------------------


@pytest.mark.parametrize("name", TIER1_SCENARIOS)
def test_tier1_scenario_passes_all_three_oracles(name, tmp_path):
    rec = run_scenario(SCENARIOS[name], seed=0, workdir=str(tmp_path))
    assert rec["ok"], rec.get("problems")
    # oracle 1: the chaos leg matches the uninterrupted run bitwise and
    # settles to the converged fixpoint within the script's budget
    assert rec["bitwise_match"] and rec["converged"]
    assert rec["rounds_to_convergence"] >= rec["rounds_scripted"]
    # oracle 2: the checkpoint lineage validated (no diverged restores)
    assert rec["checkpoints_validated"] >= 1
    # oracle 3: the healed cluster went quiet within the same budget
    assert rec["quiesced"]
    assert rec["rounds_to_quiescence"] >= rec["rounds_scripted"]
    # every scripted host-plane fault actually fired
    assert rec["faults_injected"] == len(SCENARIOS[name].injections)


def test_verdict_deterministic_in_name_and_seed(tmp_path):
    """Same (name, seed) -> the SAME verdict record, field for field
    (trace digest included); a different seed -> a different trace."""
    script = ScenarioScript(
        name="determinism-probe",
        phases=(FaultPhase(rounds=4, write_frac=0.3),
                FaultPhase(rounds=4)),
        injections=(Injection(kind="preempt", phase=0),),
        settle_budget=128,
    )
    a = run_scenario(script, seed=3, workdir=str(tmp_path / "a"))
    b = run_scenario(script, seed=3, workdir=str(tmp_path / "b"))
    assert a == b
    assert a["ok"], a.get("problems")
    _cfg, _traces, other = compile_scenario(script, seed=4)
    assert other != a["trace_digest"]


# --- the injected-fault primitives ---------------------------------------


def test_injected_crash_marker_gates_seam_attribution():
    """Only an exception chain carrying the seam's ``corrochaos:``
    marker counts as the scripted fault — a genuine pipeline failure
    during an armed phase must surface, not be silently recovered."""
    from corrosion_tpu.resilience.chaos import _injected_crash

    inner = OSError("corrochaos: killed writing a state slice of seg-x")
    wrapped = RuntimeError(
        "async checkpoint write failed; the previous segment has no "
        "committed recovery point"
    )
    wrapped.__cause__ = inner
    assert _injected_crash(wrapped)
    assert _injected_crash(inner)
    assert not _injected_crash(RuntimeError("disk full"))
    genuine = RuntimeError("async checkpoint write failed")
    genuine.__cause__ = OSError(28, "No space left on device")
    assert not _injected_crash(genuine)


def test_crash_before_first_commit_fails_the_verdict_not_the_sweep(tmp_path):
    """A script whose injected crash kills the FIRST ever save leaves
    nothing to resume from: the scenario must record a failed verdict
    (engine error in ``problems``) instead of raising out of the
    engine and killing the rest of a sweep."""
    script = ScenarioScript(
        name="first-save-crash",
        phases=(FaultPhase(rounds=4, write_frac=0.2),),
        injections=(Injection(kind="crash_slice", phase=0),),
        settle_budget=64,
    )
    rec = run_scenario(script, seed=0, workdir=str(tmp_path))
    assert not rec["ok"]
    assert any("engine error" in p for p in rec["problems"])


def test_corrupt_checkpoint_is_refused_on_load(tmp_path):
    from corrosion_tpu.resilience.async_ckpt import write_segment_checkpoint
    from corrosion_tpu.resilience.segments import _key_to_json
    from corrosion_tpu.sim.scale_step import ScaleSimState

    script = SCENARIOS["ckpt-corrupt"]
    cfg = scenario_config(script)
    import jax.random as jr

    path = write_segment_checkpoint(
        cfg, "scale", ScaleSimState.create(cfg),
        _key_to_json(jr.key(0)), 4, str(tmp_path), keep_last=8,
    )
    load_checkpoint(path, verify=True)  # clean before the flip
    corrupt_checkpoint(path)
    with pytest.raises(CheckpointIntegrityError):
        load_checkpoint(path, verify=True)


def test_script_validation_refuses_malformed_scenarios():
    with pytest.raises(ValueError):
        ScenarioScript(name="empty", phases=()).validate()
    with pytest.raises(ValueError):
        FaultPhase(rounds=0).validate()
    with pytest.raises(ValueError):
        FaultPhase(rounds=4, kill_frac=1.5).validate()
    with pytest.raises(ValueError):
        Injection(kind="meteor-strike", phase=0).validate()
    with pytest.raises(ValueError):
        Injection(kind="fused_flip", phase=0).validate()  # no target mode
    with pytest.raises(ValueError):
        ScenarioScript(
            name="oob",
            phases=(FaultPhase(rounds=4),),
            injections=(Injection(kind="preempt", phase=7),),
        ).validate()


# --- registry / doc meta-tests -------------------------------------------


def test_registry_covers_the_required_fault_axes():
    """The ISSUE-13 acceptance axes all have a shipped scenario."""
    assert len(SCENARIOS) >= 6
    phases = [ph for s in SCENARIOS.values() for ph in s.phases]
    kinds = {i.kind for s in SCENARIOS.values() for i in s.injections}
    assert any(ph.partition_groups > 1 for ph in phases)  # partition-heal
    assert any(
        ph.clock_skew_rounds > HLC_MAX_DRIFT_ROUNDS for ph in phases
    )  # skew past the drift gate
    assert any(ph.kill_frac > 0 for ph in phases)
    assert any(ph.revive_killed for ph in phases)  # rejoin-refutation
    assert {"crash_slice", "crash_manifest", "corrupt_checkpoint",
            "remesh", "fused_flip"} <= kinds
    # the ISSUE-18 composed scenarios are shipped and actually composed
    # (two+ fault axes in one script)
    assert {"corrupt-remesh", "skew-partition", "preempt-storm"} \
        <= set(SCENARIOS)
    cr = SCENARIOS["corrupt-remesh"]
    assert {i.kind for i in cr.injections} == {"corrupt_checkpoint",
                                               "remesh"}
    sp = SCENARIOS["skew-partition"].phases[0]
    assert sp.partition_groups > 1 and sp.clock_skew_rounds > \
        HLC_MAX_DRIFT_ROUNDS
    ps = SCENARIOS["preempt-storm"]
    assert {i.kind for i in ps.injections} == {"crash_slice", "preempt",
                                               "crash_manifest"}
    assert any(ph.kill_frac > 0 for ph in ps.phases)
    # tier-1 subset is real and shipped
    assert set(TIER1_SCENARIOS) <= set(SCENARIOS)
    assert 2 <= len(TIER1_SCENARIOS) <= 3


def test_every_shipped_scenario_is_documented():
    """docs/chaos.md names every scenario, every injection kind, and
    every FaultPhase field (the corrosan-KINDS meta-test pattern)."""
    with open(DOC) as f:
        doc = f.read()
    missing = [name for name in SCENARIOS if name not in doc]
    assert not missing, f"scenarios missing from docs/chaos.md: {missing}"
    missing = [k for k in INJECTION_KINDS if k not in doc]
    assert not missing, f"injection kinds missing from docs/chaos.md: {missing}"
    missing = [
        f.name for f in dataclasses.fields(FaultPhase) if f.name not in doc
    ]
    assert not missing, f"FaultPhase fields missing from docs/chaos.md: {missing}"


def test_artifact_lineage_superseded():
    """The scripted sweep's convergence artifact exists (satellite 6:
    CONVERGENCE_r13 supersedes the seed-era one-scenario record) and
    carries one converged entry per non-skipped shipped scenario."""
    import json

    path = os.path.join(os.path.dirname(__file__), "..", "artifacts",
                        "CONVERGENCE_r13_cpu.json")
    assert os.path.exists(path), "run scripts/check.sh to record it"
    with open(path) as f:
        conv = json.load(f)
    names = {r["scenario"] for r in conv}
    assert names <= set(SCENARIOS)
    assert len(names) >= 6
    assert all(r["converged"] and r["rounds_to_convergence"] > 0
               for r in conv)


# --- the full sweep (slow; also rides check.sh under CORROSAN=1) ---------


@pytest.mark.slow
def test_full_sweep_every_scenario_both_oracles():
    out = run_sweep(seed=0)
    bad = [r for r in out["scenarios"] if not r["ok"]]
    assert out["ok"], bad
    assert {r["name"] for r in out["scenarios"]} == set(SCENARIOS)
    # the 8-virtual-device conftest rig means nothing may skip here
    assert not any(r.get("skipped") for r in out["scenarios"])
    assert all(r["converged"] and r["bitwise_match"] and r["quiesced"]
               for r in out["scenarios"])


# --- seed-range sweeps + the host-plane scenario (PR 17) ------------------


def test_seed_range_sweep_structure(monkeypatch):
    """--seed-range A:B runs every selected scenario once per seed and
    folds rounds-to-convergence into the per_seed map."""
    import corrosion_tpu.resilience.chaos as chaos_mod

    calls = []

    def stub(script, seed=0, workdir=None):
        calls.append((script.name, seed))
        return {"name": script.name, "seed": seed, "ok": True,
                "rounds_to_convergence": 10 + seed}

    monkeypatch.setattr(chaos_mod, "run_scenario", stub)
    out = chaos_mod.run_sweep(["partition-heal", "clock-skew"],
                              seed_range=(2, 4))
    assert calls == [(n, s) for s in (2, 3, 4)
                     for n in ("partition-heal", "clock-skew")]
    assert out["ok"] and out["seed"] == 2
    assert out["seed_range"] == [2, 4]
    assert set(out["per_seed"]) == {"2", "3", "4"}
    for s in (2, 3, 4):
        assert out["per_seed"][str(s)] == {
            "partition-heal": 10 + s, "clock-skew": 10 + s}
    with pytest.raises(ValueError):
        chaos_mod.run_sweep(["partition-heal"], seed_range=(4, 2))


def test_host_plane_scenario_registered_outside_default_sweep():
    """serve-overload is reachable by name but NOT part of SCENARIOS —
    the sweep artifact schema stays pinned to the device-plane
    registry (docs/chaos.md, "Host-plane scenarios")."""
    from corrosion_tpu.resilience.chaos import _host_scenarios

    hosts = _host_scenarios()
    assert "serve-overload" in hosts
    assert "serve-overload" not in SCENARIOS
    with pytest.raises(ValueError):
        run_sweep(["no-such-scenario"])


def test_serve_overload_plan_deterministic():
    """(seed, shape) fully determines the serve-overload write plan:
    per-writer single-owner key streams, stamps, and the digest the
    verdict carries."""
    from corrosion_tpu.resilience.serve_overload import plan_serve_overload

    a = plan_serve_overload(5, writers=3, ops=8, keys=9)
    assert a == plan_serve_overload(5, writers=3, ops=8, keys=9)
    assert a["digest"] != plan_serve_overload(6, writers=3, ops=8,
                                              keys=9)["digest"]
    # single-owner partition: writer w owns exactly the keys = w (mod 3)
    for w, ops in enumerate(a["writers"]):
        assert ops, "every writer has work"
        assert all(k % 3 == w and 0 <= k < 9 for k in ops)


@pytest.mark.slow
def test_serve_overload_scenario_end_to_end(tmp_path):
    """The host-plane scenario through the sweep dispatcher: both
    serving-plane oracles hold, the ramp actually shed, and the ready
    flap (mid-run live restore) was applied."""
    from corrosion_tpu.resilience.serve_overload import plan_serve_overload

    out = run_sweep(["serve-overload"], seed=0)
    assert out["ok"], [r.get("problems") for r in out["scenarios"]]
    (rec,) = out["scenarios"]
    assert rec["host_plane"] and rec["name"] == "serve-overload"
    assert rec["plan_digest"] == plan_serve_overload(
        0, writers=4, ops=40, keys=12)["digest"]
    assert rec["acked_writes"] > 0
    assert rec["subs_shed_total"] > 0  # the scenario must overload
    assert rec["resyncs"] >= 1
    assert rec["ready_flap_applied"]
