"""RTT rings: region-distance bucketing, ring0-first fanout preference,
and convergence with a multi-region topology (``members.rs:38,130-178``,
``broadcast/mod.rs:653-713``)."""

import jax.numpy as jnp
import jax.random as jr
import numpy as np
import pytest

from corrosion_tpu.agent import Agent
from corrosion_tpu.config import Config
from corrosion_tpu.ops.select import sample_k_biased
from corrosion_tpu.sim.transport import N_RINGS, NetModel, ring_of, same_region


def test_ring_of_circular_distance():
    net = NetModel.create(12, n_regions=4)  # regions 0,1,2,3 interleaved
    src = jnp.zeros(12, jnp.int32)  # node 0 is region 0
    dst = jnp.arange(12, dtype=jnp.int32)
    rings = np.asarray(ring_of(net, src, dst))
    # node 1 -> region 1 -> ring 1; node 2 -> region 2 -> ring 2;
    # node 3 -> region 3 -> circular distance 1 -> ring 1
    assert rings[0] == 0 and rings[4] == 0  # same region
    assert rings[1] == 1 and rings[3] == 1
    assert rings[2] == 2
    assert rings.max() < N_RINGS


def test_single_region_all_ring0():
    net = NetModel.create(8)
    ij = jnp.arange(8, dtype=jnp.int32)
    rings = np.asarray(ring_of(net, jnp.zeros(8, jnp.int32), ij))
    assert (rings == 0).all()
    assert np.asarray(same_region(net)).all()


def test_sample_k_biased_strict_priority():
    # 16 candidates, 4 with bonus 1.0: a k=4 sample must pick exactly those
    mask = jnp.ones((1, 16), bool)
    bonus = jnp.zeros((1, 16)).at[0, [2, 5, 9, 13]].set(1.0)
    cols, ok = sample_k_biased(mask, bonus, 4, jr.key(0))
    assert ok.all()
    assert sorted(np.asarray(cols)[0].tolist()) == [2, 5, 9, 13]


def test_sample_k_biased_soft_preference():
    # soft bonus shifts the distribution but does not exclude others
    mask = jnp.ones((256, 8), bool)
    bonus = jnp.zeros((256, 8)).at[:, 0].set(0.5)
    cols, _ = sample_k_biased(mask, bonus, 1, jr.key(1))
    frac = float(np.mean(np.asarray(cols) == 0))
    assert frac > 0.3  # uniform would be 0.125


def test_multi_region_cluster_converges():
    cfg = Config()
    cfg.sim.mode = "scale"
    cfg.sim.n_nodes = 32
    cfg.sim.m_slots = 16
    cfg.sim.n_origins = 4
    cfg.sim.n_rows = 4
    cfg.sim.n_cols = 2
    cfg.perf.sync_interval = 4
    cfg.gossip.drop_prob = 0.01
    cfg.gossip.n_regions = 4
    with Agent(cfg) as agent:
        assert agent.wait_rounds(30, timeout=120)
        ms = agent.members()
        assert {m["region"] for m in ms} == {0, 1, 2, 3}
        assert any(m["ring"] > 0 for m in ms)
        agent.write(node=0, cell=1, value=4242)
        reader = agent.n_nodes - 1  # region 3, cross-region delivery
        for _ in range(100):
            if agent.read_cell(reader, 1)["value"] == 4242:
                break
            agent.wait_rounds(5, timeout=60)
        assert agent.read_cell(reader, 1)["value"] == 4242
