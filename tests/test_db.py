"""DB layer: schema parse/diff/apply constraints + SQL execution over the
cluster — the analog of the reference's schema tests (``schema.rs``) and
the HTTP write/read path tests (``api/public/mod.rs``)."""

import pytest

from corrosion_tpu.agent import Agent
from corrosion_tpu.config import Config
from corrosion_tpu.db import Database, SchemaError, parse_schema_sql
from corrosion_tpu.db.values import ValueHeap, corro_json_contains

SCHEMA = """
CREATE TABLE users (
    id INTEGER PRIMARY KEY,
    name TEXT NOT NULL DEFAULT 'anon',
    score INTEGER,
    bio TEXT
);
"""


def db_config():
    cfg = Config()
    cfg.sim.mode = "scale"
    cfg.sim.n_nodes = 16
    cfg.sim.m_slots = 8
    cfg.sim.n_origins = 4
    cfg.sim.n_rows = 8
    cfg.sim.n_cols = 4
    cfg.perf.sync_interval = 4
    cfg.gossip.drop_prob = 0.0
    return cfg


@pytest.fixture(scope="module")
def db():
    with Agent(db_config()) as agent:
        agent.wait_rounds(10, timeout=120)
        d = Database(agent)
        d.apply_schema_sql(SCHEMA)
        yield d


# --- schema parsing ------------------------------------------------------

def test_parse_schema():
    s = parse_schema_sql(SCHEMA)
    t = s.table("users")
    assert t.pk.name == "id"
    assert [c.name for c in t.value_columns] == ["name", "score", "bio"]
    assert t.column("name").default == "anon"
    assert t.col_index("name") == 1 and t.col_index("bio") == 3


def test_schema_constraints():
    with pytest.raises(SchemaError):  # no pk
        parse_schema_sql("CREATE TABLE t (a INTEGER, b TEXT);")
    with pytest.raises(SchemaError):  # unique forbidden
        parse_schema_sql("CREATE TABLE t (a INTEGER PRIMARY KEY, b TEXT UNIQUE);")
    with pytest.raises(SchemaError):  # unique index forbidden
        parse_schema_sql(
            "CREATE TABLE t (a INTEGER PRIMARY KEY);"
            "CREATE UNIQUE INDEX i ON t (a);"
        )
    # table-level pk works
    s = parse_schema_sql("CREATE TABLE t (a INTEGER, b TEXT, PRIMARY KEY (a));")
    assert s.table("t").pk.name == "a"


def test_schema_diff_rejects_destructive(db):
    with pytest.raises(SchemaError):  # dropping a column
        db.apply_schema_sql("CREATE TABLE users (id INTEGER PRIMARY KEY);")
    # adding a table and appending a column are fine
    changes = db.apply_schema_sql(
        "CREATE TABLE users (id INTEGER PRIMARY KEY, name TEXT NOT NULL "
        "DEFAULT 'anon', score INTEGER, bio TEXT);\n"
        "CREATE TABLE deploys (node TEXT PRIMARY KEY, version INTEGER);"
    )
    assert ("create_table", "deploys") in changes


# --- write / read path ---------------------------------------------------

def test_insert_select_roundtrip(db):
    db.execute(0, [
        ("INSERT INTO users (id, name, score) VALUES (?, ?, ?)", [1, "ada", 10]),
        ("INSERT INTO users (id, name, score) VALUES (?, ?, ?)", [2, "bob", 5]),
    ])
    cols, rows = db.query(0, "SELECT id, name, score FROM users")
    got = sorted(rows)
    assert cols == ["id", "name", "score"]
    assert got == [[1, "ada", 10], [2, "bob", 5]]
    # default applied
    assert db.read_row(0, "users", 1)["bio"] is None


def test_update_delete(db):
    db.execute(0, [("UPDATE users SET score = ? WHERE id = ?", [99, 1])])
    assert db.read_row(0, "users", 1)["score"] == 99
    db.execute(0, [("DELETE FROM users WHERE id = ?", [2])])
    assert db.read_row(0, "users", 2) is None
    # delete is idempotent; re-insert revives via causal length
    (res,) = db.execute(0, [("DELETE FROM users WHERE id = ?", [2])])
    assert res["rows_affected"] == 0
    db.execute(0, [("INSERT INTO users (id, name) VALUES (?, ?)", [2, "bob2"])])
    assert db.read_row(0, "users", 2)["name"] == "bob2"


def test_transaction_sees_earlier_statements(db):
    # later statements in ONE transaction must observe earlier ones, like
    # sequential statements inside a real SQLite tx (public/mod.rs:141-174)
    results = db.execute(0, [
        ("INSERT INTO users (id, name, score) VALUES (?, ?, ?)", [7, "zoe", 1]),
        ("UPDATE users SET score = ? WHERE id = ?", [2, 7]),
        ("DELETE FROM users WHERE id = ?", [7]),
        ("INSERT INTO users (id, name) VALUES (?, ?)", [7, "zoe2"]),
    ])
    assert [r["rows_affected"] for r in results] == [1, 1, 1, 1]
    for _ in range(100):
        row = db.read_row(0, "users", 7)
        if row is not None and row["name"] == "zoe2":
            break
        db.agent.wait_rounds(2, timeout=60)
    row = db.read_row(0, "users", 7)
    assert row["name"] == "zoe2"
    # the re-insert resets unspecified columns to their defaults (SQLite
    # semantics: a fresh row, not a resurrected one) — score was 2 before
    # the in-transaction DELETE and must not leak through
    assert row["score"] is None


def test_insert_stages_cl_flip_last(db):
    # insert atomicity: the causal-length flip that turns the row live must
    # be staged AFTER the value cells — within one chunk the commit is
    # atomic, but when an oversized transaction splits into several
    # versions this ordering keeps readers from seeing a live all-NULL row
    from corrosion_tpu.db.schema import CL_COL

    _, cells, _ = db._plan_write(
        0, "INSERT INTO users (id, name, score) VALUES (42, 'x', 1)", None, {}
    )
    cl_positions = [
        i for i, (cell, _v, _l) in enumerate(cells)
        if cell % db.n_cols == CL_COL
    ]
    assert cl_positions == [len(cells) - 1]


def test_concurrent_delete_beats_update(db):
    """cr-sqlite causal-length semantics: a delete racing a concurrent
    update on another node wins — the row ends deleted everywhere, the
    update's cell lands in a dead lifetime (doc/crdts.md cl)."""
    agent = db.agent
    db.execute(0, [("INSERT INTO users (id, name, score) VALUES (9, 'race', 1)",)])
    # replicate to node 1 so its update targets a live row
    for _ in range(100):
        if db.read_row(1, "users", 9) is not None:
            break
        agent.wait_rounds(2, timeout=60)
    assert db.read_row(1, "users", 9) is not None
    # fire both without waiting in between: they race through the rounds
    db.execute(0, [("DELETE FROM users WHERE id = ?", [9])], wait=False)
    db.execute(1, [("UPDATE users SET score = ? WHERE id = ?", [777, 9])],
               wait=False)
    # converge: the delete's higher causal length wins on every replica
    for _ in range(150):
        views = [db.read_row(n, "users", 9) for n in (0, 1, agent.n_nodes - 1)]
        if all(v is None for v in views):
            break
        agent.wait_rounds(2, timeout=60)
    assert all(
        db.read_row(n, "users", 9) is None for n in (0, 1, agent.n_nodes - 1)
    )
    # resurrect: a fresh lifetime, stale columns do not leak back
    db.execute(0, [("INSERT INTO users (id, name) VALUES (9, 'back')",)])
    for _ in range(100):
        row = db.read_row(0, "users", 9)
        if row is not None and row["name"] == "back":
            break
        agent.wait_rounds(2, timeout=60)
    row = db.read_row(0, "users", 9)
    assert row["name"] == "back" and row["score"] is None


def test_where_and_limit(db):
    _, rows = db.query(0, "SELECT id FROM users WHERE score >= ?", [50])
    assert [1] in list(rows)
    _, rows = db.query(0, "SELECT id FROM users LIMIT 1")
    assert len(list(rows)) == 1


def test_replication_to_reader_node(db):
    agent = db.agent
    db.execute(1, [("INSERT INTO users (id, name, score) VALUES (3, 'eve', 7)",)])
    reader = agent.n_nodes - 1
    # cells replicate independently (column-level LWW) — wait for the
    # whole row, not just the first cell that lands
    for _ in range(100):
        row = db.read_row(reader, "users", 3)
        if row is not None and row["name"] == "eve" and row["score"] == 7:
            break
        agent.wait_rounds(4, timeout=60)
    assert db.read_row(reader, "users", 3)["score"] == 7


def test_sql_errors(db):
    from corrosion_tpu.db.database import SqlError

    with pytest.raises(SqlError):
        db.execute(0, ["SELECT * FROM users"])  # read on write path
    with pytest.raises(SqlError):
        db.query(0, "DELETE FROM users WHERE id = 1")  # write on read path
    with pytest.raises(SqlError):
        db.execute(0, [("INSERT INTO users (name) VALUES ('x')",)])  # no pk
    with pytest.raises(SqlError):
        db.execute(0, [("UPDATE users SET name = NULL WHERE id = 1",)])


def test_table_stats(db):
    stats = db.table_stats(0)
    assert stats["users"]["live"] >= 1


def test_state_dict_roundtrip(db):
    state = db.state_dict()
    with Agent(db_config()) as a2:
        d2 = Database(a2)
        d2.load_state_dict(state)
        assert d2.schema.table("users").pk.name == "id"
        assert d2.rows.get("users", 1) == db.rows.get("users", 1)
        assert len(d2.heap) == len(db.heap)


# --- value heap ----------------------------------------------------------

def test_value_heap():
    h = ValueHeap()
    assert h.intern(None) == 0
    a = h.intern("x")
    assert h.intern("x") == a
    assert h.intern(1) != h.intern(1.0)  # SQL type identity
    assert h.lookup(h.intern(b"\x01")) == b"\x01"
    h2 = ValueHeap.from_state_dict(h.state_dict())
    assert h2.lookup(a) == "x" and len(h2) == len(h)


def test_json_contains():
    assert corro_json_contains('{"a": 1, "b": [1, 2]}', '{"b": [2]}')
    assert not corro_json_contains('{"a": 1}', '{"b": 1}')


# --- extended SELECT surface (VERDICT #8) --------------------------------

@pytest.fixture(scope="module")
def rich_db():
    """Two tables + a deterministic dataset for the relational surface."""
    cfg = db_config()
    cfg.sim.n_rows = 40  # squads + players + round-5 bulk-insert pks share the grid
    with Agent(cfg) as agent:
        agent.wait_rounds(5, timeout=120)
        d = Database(agent)
        d.apply_schema_sql("""
            CREATE TABLE players (pid INTEGER PRIMARY KEY, pname TEXT,
                                  score INTEGER, team INTEGER);
            CREATE TABLE squads (sid INTEGER PRIMARY KEY, title TEXT);
        """)
        d.execute(0, [("INSERT INTO squads (sid, title) VALUES (1, 'red')",),
                      ("INSERT INTO squads (sid, title) VALUES (2, 'blue')",),
                      ("INSERT INTO squads (sid, title) VALUES (3, 'gray')",)])
        data = [("a", 30, 1), ("b", 10, 2), ("c", 20, 1), ("d", 40, 2),
                ("e", 25, 1)]
        for i, (nm, sc, tm) in enumerate(data):
            d.execute(0, [(f"INSERT INTO players (pid, pname, score, team) "
                           f"VALUES ({i}, '{nm}', {sc}, {tm})",)])
        yield d


def test_order_by_limit_offset(rich_db):
    names, rows = rich_db.query(
        0, "SELECT pname, score FROM players ORDER BY score DESC "
           "LIMIT 2 OFFSET 1")
    assert names == ["pname", "score"]
    assert list(rows) == [["a", 30], ["e", 25]]


def test_aggregates_whole_table(rich_db):
    names, rows = rich_db.query(
        0, "SELECT COUNT(*), SUM(score), MIN(score), MAX(score), AVG(score) "
           "FROM players")
    assert list(rows) == [[5, 125, 10, 40, 25.0]]
    assert names[0] == "COUNT(*)"


def test_group_by_with_aliases(rich_db):
    names, rows = rich_db.query(
        0, "SELECT team, COUNT(*) AS n, SUM(score) AS total FROM players "
           "GROUP BY team ORDER BY team")
    assert names == ["team", "n", "total"]
    assert list(rows) == [[1, 3, 75], [2, 2, 50]]


def test_pk_equi_join(rich_db):
    names, rows = rich_db.query(
        0, "SELECT p.pname, s.title FROM players p "
           "JOIN squads s ON p.team = s.sid "
           "WHERE p.score >= 25 ORDER BY p.pname")
    assert names == ["pname", "title"]
    assert list(rows) == [["a", "red"], ["d", "blue"], ["e", "red"]]


def test_left_join_keeps_unmatched(rich_db):
    names, rows = rich_db.query(
        0, "SELECT s.title, COUNT(p.pid) AS members FROM squads s "
           "LEFT JOIN players p ON p.team = s.sid "
           "GROUP BY s.title ORDER BY s.title")
    assert list(rows) == [["blue", 2], ["gray", 0], ["red", 3]]


def test_limit_offset_params_and_describe(rich_db):
    names, rows = rich_db.query(
        0, "SELECT pname AS who FROM players ORDER BY score LIMIT ?", [2])
    assert names == ["who"] and list(rows) == [["b"], ["c"]]
    assert rich_db.query_columns(
        "SELECT team, COUNT(*) AS n FROM players GROUP BY team"
    ) == ["team", "n"]


def test_order_by_nulls_first(rich_db):
    rich_db.execute(0, [("INSERT INTO players (pid, pname, team) "
                         "VALUES (9, 'z', 3)",)])
    names, rows = rich_db.query(
        0, "SELECT pname, score FROM players ORDER BY score LIMIT 2")
    # SQLite sorts NULLs first ascending
    assert list(rows)[0] == ["z", None]
    rich_db.execute(0, [("DELETE FROM players WHERE pid = 9",)])


# --- round-3 dialect: LIKE/GLOB, HAVING, subqueries (VERDICT r2 #10) -----

def test_like_and_glob(rich_db):
    # LIKE is ASCII case-insensitive; % / _ wildcards
    _, rows = rich_db.query(
        0, "SELECT pname FROM players WHERE pname LIKE 'A%' ORDER BY pname")
    assert list(rows) == [["a"]]
    _, rows = rich_db.query(
        0, "SELECT title FROM squads WHERE title NOT LIKE '%r%' "
           "ORDER BY title")
    assert list(rows) == [["blue"]]
    # GLOB is case-sensitive with * / ? wildcards
    _, rows = rich_db.query(
        0, "SELECT title FROM squads WHERE title GLOB 'b*'")
    assert list(rows) == [["blue"]]
    _, rows = rich_db.query(
        0, "SELECT title FROM squads WHERE title GLOB 'B*'")
    assert list(rows) == []
    # parametrized pattern; _ matches exactly one char
    _, rows = rich_db.query(
        0, "SELECT pname FROM players WHERE pname LIKE ?", ["_"])
    assert len(list(rows)) == 5


def test_having(rich_db):
    _, rows = rich_db.query(
        0, "SELECT team, COUNT(*) AS n FROM players GROUP BY team "
           "HAVING COUNT(*) > 2 ORDER BY team")
    assert list(rows) == [[1, 3]]
    # HAVING on an output alias
    _, rows = rich_db.query(
        0, "SELECT team, SUM(score) AS total FROM players GROUP BY team "
           "HAVING total >= 75")
    assert list(rows) == [[1, 75]]


def test_scalar_subquery_in_where(rich_db):
    _, rows = rich_db.query(
        0, "SELECT pname FROM players WHERE score = "
           "(SELECT MAX(score) FROM players)")
    assert list(rows) == [["d"]]


def test_in_subquery_and_literal_list(rich_db):
    _, rows = rich_db.query(
        0, "SELECT pname FROM players WHERE team IN "
           "(SELECT sid FROM squads WHERE title LIKE 'r%') ORDER BY pname")
    assert list(rows) == [["a"], ["c"], ["e"]]
    _, rows = rich_db.query(
        0, "SELECT pname FROM players WHERE score IN (10, 40) "
           "ORDER BY pname")
    assert list(rows) == [["b"], ["d"]]
    _, rows = rich_db.query(
        0, "SELECT pname FROM players WHERE team NOT IN (1) AND score > 15")
    assert list(rows) == [["d"]]


def test_expression_projections(rich_db):
    # arithmetic with int truncation + aliases
    _, rows = rich_db.query(
        0, "SELECT pname, score * 2 AS dbl, score / 7 FROM players "
           "WHERE pid = 0")
    assert list(rows) == [["a", 60, 4]]
    # COALESCE / NULL propagation (pid 9 has NULL score while present)
    rich_db.execute(0, [("INSERT INTO players (pid, pname, team) "
                         "VALUES (8, 'y', 3)",)])
    _, rows = rich_db.query(
        0, "SELECT COALESCE(score, -1) AS s, score + 1 FROM players "
           "WHERE pid = 8")
    assert list(rows) == [[-1, None]]
    rich_db.execute(0, [("DELETE FROM players WHERE pid = 8",)])
    # string functions + concat
    _, rows = rich_db.query(
        0, "SELECT UPPER(pname) || '!' AS shout, LENGTH(pname) "
           "FROM players WHERE pid = 1")
    assert list(rows) == [["B!", 1]]
    # expressions inside GROUP BY output rows
    _, rows = rich_db.query(
        0, "SELECT team * 10 AS t10, COUNT(*) AS n FROM players "
           "GROUP BY team ORDER BY t10")
    assert list(rows) == [[10, 3], [20, 2]]


def test_expression_sqlite_semantics(rich_db):
    """Operator semantics differentially pinned against real SQLite:
    numeric coercion for arithmetic, C-style modulo, truncating integer
    division, half-away-from-zero ROUND, literal projections."""
    import sqlite3

    con = sqlite3.connect(":memory:")
    for expr in ["2 * 3 || 'x'", "-7 % 3", "7 % -3", "ROUND(2.5)",
                 "ROUND(-2.5)", "'3x' + 1", "5 / 2", "-5 / 2",
                 "2 + 2 * 3", "COALESCE(NULL, 4)"]:
        want = con.execute(f"SELECT {expr}").fetchone()[0]
        _, rows = rich_db.query(
            0, f"SELECT {expr} AS v FROM players WHERE pid = 0")
        assert list(rows) == [[want]], expr
    # bare literal projections
    _, rows = rich_db.query(
        0, "SELECT 5, NULL AS x FROM players WHERE pid = 0")
    assert list(rows) == [[5, None]]


def test_order_by_expression(rich_db):
    # sort by a computed key that matches no column or alias
    _, rows = rich_db.query(
        0, "SELECT pname FROM players WHERE score >= 10 "
           "ORDER BY 0 - score LIMIT 2")
    assert list(rows) == [["d"], ["a"]]
    _, rows = rich_db.query(
        0, "SELECT pname, score FROM players WHERE score >= 10 "
           "ORDER BY score % 3, pname")
    first = list(rows)[0]
    assert first[1] % 3 == min(s % 3 for s in (30, 10, 20, 40, 25))


def test_expression_where_lhs(rich_db):
    _, rows = rich_db.query(
        0, "SELECT pname FROM players WHERE score % 10 = 5")
    assert list(rows) == [["e"]]
    _, rows = rich_db.query(
        0, "SELECT pname FROM players WHERE LENGTH(pname) = 1 "
           "AND score + 10 > 35 ORDER BY pname")
    assert list(rows) == [["a"], ["d"]]


def test_order_by_ordinal(rich_db):
    # SQLite: ORDER BY 2 sorts by the second output column
    _, rows = rich_db.query(
        0, "SELECT pname, score FROM players WHERE score >= 10 ORDER BY 2")
    assert [r[1] for r in rows] == [10, 20, 25, 30, 40]
    _, rows = rich_db.query(
        0, "SELECT pname, score FROM players WHERE score >= 10 "
           "ORDER BY 2 DESC LIMIT 1")
    assert list(rows) == [["d", 40]]
    import pytest as _pytest

    from corrosion_tpu.db.database import SqlError
    with _pytest.raises(SqlError):
        rich_db.query(0, "SELECT pname FROM players ORDER BY 7")


def test_group_by_expression(rich_db):
    _, rows = rich_db.query(
        0, "SELECT COUNT(*) AS n FROM players WHERE score >= 10 "
           "GROUP BY score % 2 ORDER BY n")
    # scores 30,10,20,40,25 -> parity groups {even: 4, odd: 1}
    assert list(rows) == [[1], [4]]


def test_group_by_alias_and_order_by_group_expr(rich_db):
    # GROUP BY an output alias (SQLite allows it)
    _, rows = rich_db.query(
        0, "SELECT score % 2 AS par, COUNT(*) AS n FROM players "
           "WHERE score >= 10 GROUP BY par ORDER BY par")
    assert list(rows) == [[0, 4], [1, 1]]
    # ORDER BY the grouping expression itself
    _, rows = rich_db.query(
        0, "SELECT COUNT(*) AS n FROM players WHERE score >= 10 "
           "GROUP BY score % 2 ORDER BY score % 2 DESC")
    assert list(rows) == [[1], [4]]


# --- round-4 dialect: OR / NOT / parens / IS NULL (VERDICT r3 #7) --------
# expected rows pinned against real SQLite (sqlite3 stdlib) on the same
# dataset; `z` (score NULL, team 3) exercises three-valued logic

def test_where_or_and_parens(rich_db):
    rich_db.execute(0, [("INSERT INTO players (pid, pname, team) "
                         "VALUES (9, 'z', 3)",)])
    try:
        _, rows = rich_db.query(
            0, "SELECT pname FROM players WHERE score < 15 OR score > 35 "
               "ORDER BY pname")
        assert list(rows) == [["b"], ["d"]]
        _, rows = rich_db.query(
            0, "SELECT pname FROM players WHERE (team = 1 AND score > 20) "
               "OR (team = 2 AND score < 15) ORDER BY pname")
        assert list(rows) == [["a"], ["b"], ["e"]]
        # UNKNOWN (NULL score) propagates through OR: z matches only via
        # the pname arm
        _, rows = rich_db.query(
            0, "SELECT pname FROM players WHERE score > 35 OR pname = 'z' "
               "ORDER BY pname")
        assert list(rows) == [["d"], ["z"]]
    finally:
        rich_db.execute(0, [("DELETE FROM players WHERE pid = 9",)])


def test_where_not_three_valued(rich_db):
    rich_db.execute(0, [("INSERT INTO players (pid, pname, team) "
                         "VALUES (9, 'z', 3)",)])
    try:
        _, rows = rich_db.query(
            0, "SELECT pname FROM players WHERE NOT (team = 1 OR score > 35) "
               "ORDER BY pname")
        assert list(rows) == [["b"]]
        # SQLite: NOT (NULL > 5) is NULL, not true — z stays excluded
        _, rows = rich_db.query(
            0, "SELECT pname FROM players WHERE NOT (score > 5) "
               "ORDER BY pname")
        assert list(rows) == []
        # bare NOT on a single comparison
        _, rows = rich_db.query(
            0, "SELECT pname FROM players WHERE NOT score = 30 "
               "ORDER BY pname")
        assert list(rows) == [["b"], ["c"], ["d"], ["e"]]
        # NOT IN with a NULL member is never true (pinned: empty)
        _, rows = rich_db.query(
            0, "SELECT pname FROM players WHERE score NOT IN (10, NULL) "
               "ORDER BY pname")
        assert list(rows) == []
    finally:
        rich_db.execute(0, [("DELETE FROM players WHERE pid = 9",)])


def test_is_null_and_mixed_boolean(rich_db):
    rich_db.execute(0, [("INSERT INTO players (pid, pname, team) "
                         "VALUES (9, 'z', 3)",)])
    try:
        _, rows = rich_db.query(
            0, "SELECT pname FROM players WHERE score IS NULL")
        assert list(rows) == [["z"]]
        _, rows = rich_db.query(
            0, "SELECT COUNT(*) FROM players WHERE score IS NOT NULL")
        assert list(rows) == [[5]]
        _, rows = rich_db.query(
            0, "SELECT pname FROM players WHERE pname NOT LIKE '%a%' AND "
               "(team = 2 OR score IS NULL) ORDER BY pname")
        assert list(rows) == [["b"], ["d"], ["z"]]
    finally:
        rich_db.execute(0, [("DELETE FROM players WHERE pid = 9",)])


def test_having_or(rich_db):
    _, rows = rich_db.query(
        0, "SELECT team, COUNT(*) AS n FROM players "
           "WHERE score IS NOT NULL GROUP BY team "
           "HAVING COUNT(*) > 2 OR SUM(score) < 60 ORDER BY team")
    assert list(rows) == [[1, 3], [2, 2]]
    _, rows = rich_db.query(
        0, "SELECT team FROM players GROUP BY team "
           "HAVING NOT (COUNT(*) > 2) ORDER BY team")
    assert list(rows) == [[2]]


def test_from_less_select_and_random(rich_db):
    # round 5: FROM-less SELECTs evaluate once against a dual row
    _, rows = rich_db.query(0, "SELECT 1 + 2")
    assert list(rows) == [[3]]
    _, rows = rich_db.query(0, "SELECT random()")
    (v,), = list(rows)
    assert isinstance(v, int) and -(1 << 63) <= v < (1 << 63)


def test_recursive_cte_generator(rich_db):
    # the reference's stress-driver shape (agent/tests.rs:622): a
    # recursive CTE as a bounded row generator
    _, rows = rich_db.query(
        0, "WITH RECURSIVE cte(n) AS (SELECT 1 UNION ALL "
           "SELECT n + 1 FROM cte LIMIT 5) SELECT n FROM cte")
    assert list(rows) == [[1], [2], [3], [4], [5]]
    # random() generator: LIMIT bounds the total row count
    _, rows = rich_db.query(
        0, "WITH RECURSIVE cte(id) AS (SELECT random() UNION ALL "
           "SELECT random() FROM cte LIMIT 7) SELECT id FROM cte")
    got = list(rows)
    assert len(got) == 7 and all(isinstance(r[0], int) for r in got)


def test_insert_select_bulk(rich_db):
    # INSERT INTO t (cols) WITH RECURSIVE ... SELECT — the reference's
    # bulk-insert driver (parallel_driver_large_tx_sync.sh)
    res = rich_db.execute(0, [(
        "INSERT INTO players (pid, pname, team, score) "
        "WITH RECURSIVE g(n) AS (SELECT 100 UNION ALL "
        "SELECT n + 1 FROM g LIMIT 4) "
        "SELECT n, 'bulk', 1, n * 2 FROM g",)])
    try:
        assert res[0]["rows_affected"] == 4
        _, rows = rich_db.query(
            0, "SELECT pid, score FROM players WHERE pname = 'bulk' "
               "ORDER BY pid")
        assert list(rows) == [[100, 200], [101, 202], [102, 204],
                              [103, 206]]
    finally:
        rich_db.execute(0, [
            (f"DELETE FROM players WHERE pid = {i}",)
            for i in range(100, 104)
        ])


def test_insert_select_sees_earlier_tx_statements(rich_db):
    # code review r5: INSERT...SELECT must read the tx overlay — an
    # earlier statement's row is selectable (SQLite sequential-tx
    # semantics)
    try:
        res = rich_db.execute(0, [
            ("INSERT INTO players (pid, pname, team, score) "
             "VALUES (110, 'ov', 1, 7)",),
            ("INSERT INTO squads (sid, title) "
             "SELECT pid, pname FROM players WHERE pid = 110",),
        ])
        assert [r["rows_affected"] for r in res] == [1, 1]
        _, rows = rich_db.query(0, "SELECT title FROM squads "
                                   "WHERE sid = 110")
        assert list(rows) == [["ov"]]
    finally:
        rich_db.execute(0, [("DELETE FROM players WHERE pid = 110",),
                            ("DELETE FROM squads WHERE sid = 110",)])


def test_insert_with_cte_select_sees_tx_overlay(rich_db):
    # code review r5: the overlay must flow into CTE bodies too
    try:
        res = rich_db.execute(0, [
            ("INSERT INTO players (pid, pname, team, score) "
             "VALUES (115, 'cte', 1, 3)",),
            ("INSERT INTO squads (sid, title) "
             "WITH c AS (SELECT pid, pname FROM players WHERE pid = 115) "
             "SELECT pid, pname FROM c",),
        ])
        assert [r["rows_affected"] for r in res] == [1, 1]
    finally:
        rich_db.execute(0, [("DELETE FROM players WHERE pid = 115",),
                            ("DELETE FROM squads WHERE sid = 115",)])


def test_recursive_cte_offset_and_subquery_ref(rich_db):
    # compound LIMIT n OFFSET m skips m rows (SQLite semantics)
    _, rows = rich_db.query(
        0, "WITH RECURSIVE c(n) AS (SELECT 1 UNION ALL "
           "SELECT n + 1 FROM c LIMIT 3 OFFSET 2) SELECT n FROM c")
    assert list(rows) == [[3], [4], [5]]
    # a self-reference from a subquery fails loudly, not with TypeError
    from corrosion_tpu.db.database import SqlError

    with pytest.raises(SqlError):
        _, rows = rich_db.query(
            0, "WITH RECURSIVE c(n) AS (SELECT 1 UNION ALL SELECT 2 "
               "WHERE 2 IN (SELECT n FROM c)) SELECT n FROM c")
        list(rows)


def test_update_with_expression(rich_db):
    # round 5 dialect: SET col = <expr over the pre-update row>
    # (the reference gets this free from embedded SQLite)
    rich_db.execute(0, [("INSERT INTO players (pid, pname, team, score) "
                         "VALUES (8, 'x', 1, 10)",)])
    try:
        rich_db.execute(0, [("UPDATE players SET score = score + 5 "
                             "WHERE pid = 8",)])
        _, rows = rich_db.query(0, "SELECT score FROM players WHERE pid = 8")
        assert list(rows) == [[15]]
        # expressions see the PRE-update row, and functions work
        rich_db.execute(0, [("UPDATE players SET score = score * 2, "
                             "pname = UPPER(pname) WHERE pid = 8",)])
        _, rows = rich_db.query(
            0, "SELECT pname, score FROM players WHERE pid = 8")
        assert list(rows) == [["X", 30]]
        # within one tx, a later statement reads the earlier write
        rich_db.execute(0, [
            ("UPDATE players SET score = 100 WHERE pid = 8",),
            ("UPDATE players SET score = score + 1 WHERE pid = 8",),
        ])
        _, rows = rich_db.query(0, "SELECT score FROM players WHERE pid = 8")
        assert list(rows) == [[101]]
    finally:
        rich_db.execute(0, [("DELETE FROM players WHERE pid = 8",)])


def test_on_conflict_do_update(rich_db):
    # round 5 dialect: ON CONFLICT DO UPDATE SET with excluded.* refs
    rich_db.execute(0, [("INSERT INTO players (pid, pname, team, score) "
                         "VALUES (7, 'up', 1, 10)",)])
    try:
        # conflicting insert: SET from excluded + expression over both
        rich_db.execute(0, [(
            "INSERT INTO players (pid, pname, team, score) "
            "VALUES (7, 'new', 2, 5) "
            "ON CONFLICT DO UPDATE SET score = score + excluded.score, "
            "pname = excluded.pname",)])
        _, rows = rich_db.query(
            0, "SELECT pname, team, score FROM players WHERE pid = 7")
        # team untouched (not in SET), score = 10 + 5, pname replaced
        assert list(rows) == [["new", 1, 15]]
        # non-conflicting insert with the clause inserts normally
        rich_db.execute(0, [(
            "INSERT INTO players (pid, pname, team, score) "
            "VALUES (17, 'fresh', 3, 1) "
            "ON CONFLICT DO UPDATE SET score = excluded.score",)])
        _, rows = rich_db.query(
            0, "SELECT pname FROM players WHERE pid = 17")
        assert list(rows) == [["fresh"]]
    finally:
        rich_db.execute(0, [("DELETE FROM players WHERE pid = 7",),
                            ("DELETE FROM players WHERE pid = 17",)])


def test_quoted_identifier_with_keyword(rich_db):
    # ADVICE r4: a double-quoted identifier containing ' OR '/' AND '
    # must not mis-split the WHERE clause (sqlite3 resolves unknown
    # double-quoted identifiers as strings; we require the split to stay
    # whole — "pname" is a real column here, so this is pure splitting)
    _, rows = rich_db.query(
        0, 'SELECT pname FROM players WHERE "pname" = \'a\' OR '
           '"pname" = \'b\' ORDER BY pname')
    assert list(rows) == [["a"], ["b"]]


def test_quoted_identifier_in_projection_and_clauses(rich_db):
    # code review r5: comma/keyword inside a double-quoted identifier
    # must not split the projection or start a clause
    _, rows = rich_db.query(
        0, 'SELECT "pname" FROM players WHERE "pname" = \'a\'')
    assert list(rows) == [["a"]]
    from corrosion_tpu.db.database import _split_top_commas, _split_top_kw
    assert _split_top_commas('"a, b", c') == ['"a, b"', "c"]
    assert _split_top_kw('"a where b" = 1', "WHERE") == ['"a where b" = 1']


def test_having_expression_lhs_is_sql_error(rich_db):
    # ADVICE r4: an expression left side in HAVING raises SqlError, not
    # TypeError
    from corrosion_tpu.db.database import SqlError

    with pytest.raises(SqlError):
        _, rows = rich_db.query(
            0, "SELECT team FROM players GROUP BY team "
               "HAVING score + 1 > 5")
        list(rows)  # rows are lazy; evaluation raises on consumption


def test_or_in_join_and_subquery(rich_db):
    # consul/template-style service query through the relational surface
    _, rows = rich_db.query(
        0, "SELECT p.pname, s.title FROM players p "
           "JOIN squads s ON p.team = s.sid "
           "WHERE s.title = 'red' OR p.score > 35 ORDER BY p.pname")
    assert list(rows) == [["a", "red"], ["c", "red"], ["d", "blue"],
                          ["e", "red"]]
    _, rows = rich_db.query(
        0, "SELECT pname FROM players WHERE team IN "
           "(SELECT sid FROM squads WHERE title = 'gray') "
           "OR score = (SELECT MIN(score) FROM players) ORDER BY pname")
    assert list(rows) == [["b"]]


# --- round-4 dialect: non-recursive CTEs (WITH ... AS) -------------------
# pinned against stdlib sqlite3 on the same dataset

def test_cte_basic_and_chained(rich_db):
    _, rows = rich_db.query(
        0, "WITH hi AS (SELECT pname, score FROM players "
           "WHERE score >= 25) SELECT pname FROM hi ORDER BY pname")
    assert list(rows) == [["a"], ["d"], ["e"]]
    # a later CTE sees an earlier one
    _, rows = rich_db.query(
        0, "WITH hi AS (SELECT pname, score, team FROM players "
           "WHERE score > 15), "
           "reds AS (SELECT pname FROM hi WHERE team = 1) "
           "SELECT COUNT(*) FROM reds")
    assert list(rows) == [[3]]


def test_cte_join_and_aggregate(rich_db):
    _, rows = rich_db.query(
        0, "WITH t AS (SELECT team, SUM(score) AS total FROM players "
           "GROUP BY team) "
           "SELECT s.title, t.total FROM t JOIN squads s "
           "ON t.team = s.sid ORDER BY s.title")
    assert list(rows) == [["blue", 50], ["red", 75]]


def test_cte_in_subquery(rich_db):
    _, rows = rich_db.query(
        0, "WITH m AS (SELECT MAX(score) AS top FROM players) "
           "SELECT pname FROM players WHERE score = "
           "(SELECT top FROM m)")
    assert list(rows) == [["d"]]


def test_cte_errors(rich_db):
    import pytest as _pytest

    from corrosion_tpu.db.database import SqlError

    with _pytest.raises(SqlError):
        rich_db.query(0, "WITH x AS SELECT 1 SELECT * FROM x")
    with _pytest.raises(SqlError):
        rich_db.query(0, "WITH x AS (SELECT pname FROM players "
                         "SELECT pname FROM x")
